"""Primary-side WAL shipping: the log-structured replication feed.

``WalShipper`` serves three questions against a live primary store and
its attached WAL:

- ``fetch(follower, cursor, max_bytes)`` — the records past the
  follower's cursor, bounded by bytes, NEVER past the WAL's durable
  frontier (a follower must not apply what the primary could still
  lose to a crash). The cursor doubles as the ack: it advances the
  follower's retention pin (WriteAheadLog.register_cursor), so
  checkpoint truncation can never delete a segment the slowest
  registered follower still needs.

- ``anchor()`` — a bootstrap anchor for followers whose cursor
  precedes the log's first retained record: the primary's dictionary
  values, sketch-mirror arrays (≡ device aggregates, bitwise) and
  write clocks, captured under the store's read lock so they are
  exactly consistent with the applied WAL sequence. A device-free
  replica adopting it serves the whole sketch tier from genesis; its
  row/segment coverage starts at the anchor.

- ``status()`` — per-follower cursors and lag for /api/replication.

``ShipServer`` is the framed-TCP endpoint (the scribe server's
threading shape) speaking replicate/protocol.py.
"""

from __future__ import annotations

import socketserver
import threading
import time
from typing import Dict, Optional

from zipkin_tpu.replicate import protocol as P


class WalShipper:
    """See the module docstring. One instance per primary process."""

    def __init__(self, store, wal=None, registry=None, tracker=None):
        from zipkin_tpu import obs

        self.store = store
        self.hot = getattr(store, "hot", store)
        self.wal = wal if wal is not None else self.hot.wal
        if self.wal is None:
            raise ValueError(
                "WAL shipping needs a WriteAheadLog attached to the "
                "primary store (--wal-dir)")
        # Batch-lineage tracker (obs.fleet.LineageTracker): fetch()
        # reports each shipped sampled record ("ship" child span) and
        # stitches the follower's backhauled apply spans into the
        # primary's own trace store. None = tracing off.
        self.tracker = tracker
        # Follower bookkeeping only — WAL calls happen OUTSIDE the
        # hold (the cursor pin itself lives in the WAL, under its own
        # condition).
        self._lock = threading.Lock()  # lock-order: 79 ship-followers
        self._followers: Dict[str, dict] = {}  # guarded-by: _lock
        # Latest pushed registry snapshot per follower (the FETCH
        # "metrics" ride-along) — the federation's remote sources.
        self._follower_metrics: Dict[str, dict] = {}  # guarded-by: _lock
        reg = registry or obs.default_registry()
        self._registry = reg
        self.c_bytes = reg.register(obs.Counter(
            "zipkin_replication_shipped_bytes_total",
            "WAL record bytes shipped to followers"))
        self.c_records = reg.register(obs.Counter(
            "zipkin_replication_shipped_records_total",
            "WAL records shipped to followers"))
        self.c_anchors = reg.register(obs.Counter(
            "zipkin_replication_anchors_total",
            "Bootstrap anchors served to followers"))
        self.g_followers = reg.register(obs.Gauge(
            "zipkin_replication_followers",
            "Followers with a registered shipping cursor",
            fn=lambda: float(len(self._followers))))
        self.g_min_lag = reg.register(obs.Gauge(
            "zipkin_replication_max_follower_lag_records",
            "Durable records not yet fetched by the furthest-behind "
            "follower (0 = all followers current)",
            fn=self._max_lag))

    def _max_lag(self) -> float:
        durable = self.wal.durable_seq
        with self._lock:
            cursors = [f["cursor"] for f in self._followers.values()]
        if not cursors:
            return 0.0
        return float(max(0, durable - min(cursors)))

    # -- protocol bodies ------------------------------------------------

    def hello(self, follower: str, mode: str) -> dict:
        self.wal.register_cursor(follower)
        now = time.time()
        with self._lock:
            self._followers.setdefault(follower, {
                "cursor": 0, "mode": mode, "connected_at": now,
                "bytes": 0, "records": 0,
            })["mode"] = mode
        return {
            "proto": P.PROTO_VERSION,
            "config": P.config_to_dict(self.hot.config),
            "last_seq": self.wal.last_seq,
            "durable_seq": self.wal.durable_seq,
            "first_seq": self.wal.first_available_seq(),
        }

    def fetch(self, follower: str, cursor: int, max_bytes: int,
              ack: Optional[int] = None, spans=None, metrics=None):
        """(records, last_seq, durable_seq) past ``cursor`` — or None
        when the cursor precedes the retained log (anchor needed).
        ``ack`` is the follower's LOCALLY-DURABLE frontier and is what
        moves its retention pin (defaults to the cursor — right for a
        replica, which re-anchors after total loss; a warm standby
        acks its checkpointed frontier so a crash can always re-replay
        the gap from the log).

        ``spans``/``metrics`` are the FETCH frame's observability
        ride-alongs (replicate/protocol.py): backhauled follower apply
        spans get stitched into the primary's lineage trace, and the
        pushed registry snapshot becomes the follower's column of the
        federated ``/metrics?fleet=1`` view."""
        cursor = max(0, int(cursor))
        ack = cursor if ack is None else max(0, int(ack))
        self.wal.advance_cursor(follower, ack)
        trk = self.tracker
        if trk is not None and spans:
            trk.ingest_remote_spans(follower, spans)
        if metrics is not None:
            with self._lock:
                self._follower_metrics[follower] = metrics
        first = self.wal.first_available_seq()
        if cursor + 1 < first:
            return None
        durable = self.wal.durable_seq
        records = []
        nbytes = 0
        for seq, payload in self.wal.replay(cursor):
            if seq > durable:
                break
            records.append((seq, payload))
            nbytes += len(payload)
            if nbytes >= max_bytes:
                break
        if trk is not None:
            for seq, _payload in records:
                trk.note_shipped(seq, follower)
        self.c_records.inc(len(records))
        self.c_bytes.inc(nbytes)
        with self._lock:
            f = self._followers.get(follower)
            if f is not None:
                f["cursor"] = max(f["cursor"], cursor)
                f["ack"] = max(f.get("ack", 0), ack)
                f["bytes"] += nbytes
                f["records"] += len(records)
        return records, self.wal.last_seq, durable

    def anchor(self) -> bytes:
        """Serialize a bootstrap anchor (see module docstring). The
        mirror snapshot and the applied sequence are taken under ONE
        read-lock hold, so no commit can land between them."""
        from zipkin_tpu.wal.record import DICT_NAMES, dump_value

        hot = self.hot
        hot.ensure_sketch_mirror()  # warm it OUTSIDE the read hold
        with hot._rw.read():
            arrays = hot.sketch_mirror.arrays()
            # graftlint: disable=guarded-by — mirrored clocks advance
            # only inside _rw.write() holds; a read hold pins them
            # (the checkpoint save path documents the same contract).
            applied = int(hot._wal_applied)
            wp = int(hot._wp)
        dict_values = {
            name: [dump_value(v)
                   for v in getattr(hot.dicts, name).values()]
            for name in DICT_NAMES
        }
        self.c_anchors.inc()
        return P.encode_anchor(applied, wp,
                               P.config_to_dict(hot.config),
                               dict_values, list(arrays))

    def drop_follower(self, follower: str) -> None:
        """Release a decommissioned follower's retention pin (an
        operator action — a mere disconnect keeps the pin so the
        follower can reconnect without an anchor)."""
        self.wal.drop_cursor(follower)
        with self._lock:
            self._followers.pop(follower, None)
            self._follower_metrics.pop(follower, None)

    def fleet_sources(self):
        """The federation's remote half: one ((label, value), ...)
        + registry-snapshot pair per follower that has pushed metrics
        (obs.fleet.render_federated's ``sources`` shape, minus the
        primary's own row — FleetObs prepends that)."""
        with self._lock:
            snaps = sorted(self._follower_metrics.items())
        return [
            ((("role", "follower"), ("follower", name)), snap)
            for name, snap in snaps
        ]

    def status(self) -> dict:
        durable = self.wal.durable_seq
        with self._lock:
            followers = {
                name: {
                    "mode": f["mode"],
                    "cursor": f["cursor"],
                    "ackSeq": f.get("ack", f["cursor"]),
                    "lagRecords": max(0, durable - f["cursor"]),
                    "shippedBytes": f["bytes"],
                    "shippedRecords": f["records"],
                }
                for name, f in self._followers.items()
            }
        return {
            "role": "primary",
            "lastSeq": self.wal.last_seq,
            "durableSeq": durable,
            "firstSeq": self.wal.first_available_seq(),
            "followers": followers,
        }

    def close(self) -> None:
        for m in (self.c_bytes, self.c_records, self.c_anchors,
                  self.g_followers, self.g_min_lag):
            if self._registry.get(m.name) is m:
                self._registry.unregister(m.name)


class _ShipHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        import socket

        sock = self.request
        sock.settimeout(self.server.io_timeout_s)  # type: ignore[attr-defined]
        shipper: WalShipper = self.server.shipper  # type: ignore[attr-defined]
        follower = None
        try:
            while True:
                msg = P.read_msg(sock)
                if msg is None:
                    return
                msg_type, meta, _blob = msg
                if msg_type == P.HELLO:
                    follower = str(meta.get("follower", "anonymous"))
                    out = P.encode_msg(
                        P.HELLO_OK,
                        shipper.hello(follower,
                                      str(meta.get("mode", "replica"))))
                elif msg_type == P.FETCH:
                    if follower is None:
                        out = P.encode_msg(
                            P.ERR, {"error": "FETCH before HELLO"})
                    else:
                        ack = meta.get("ack")
                        got = shipper.fetch(
                            follower, int(meta.get("cursor", 0)),
                            int(meta.get("max_bytes", 8 << 20)),
                            ack=None if ack is None else int(ack),
                            spans=meta.get("spans"),
                            metrics=meta.get("metrics"))
                        if got is None:
                            out = P.encode_msg(P.NEED_ANCHOR, {
                                "first_seq":
                                    shipper.wal.first_available_seq(),
                            })
                        else:
                            records, last, durable = got
                            out = P.encode_records(records, last,
                                                   durable)
                elif msg_type == P.ANCHOR:
                    out = shipper.anchor()
                else:
                    out = P.encode_msg(
                        P.ERR, {"error": f"unknown msg {msg_type}"})
                # encode_msg frames include their own length word.
                sock.sendall(out)
        except (P.ShipProtocolError, socket.timeout, ConnectionError,
                OSError):
            return


class ShipServer(socketserver.ThreadingTCPServer):
    """Framed-TCP WAL-ship endpoint bound to (host, port)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, shipper: WalShipper, host: str = "0.0.0.0",
                 port: int = 9412, io_timeout_s: float = 60.0):
        super().__init__((host, port), _ShipHandler)
        self.shipper = shipper
        self.io_timeout_s = io_timeout_s

    def serve_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True,
                             name="zipkin-ship-server")
        t.start()
        return t
