"use strict";
// Watch finished requests; any response echoing X-B3-TraceId (the
// ZipkinWSGIMiddleware contract) gets a row linking into the UI's
// #trace= deep link. Reference role: zipkin-browser-extension's
// request listing; this rebuild uses only devtools.network, so it
// needs no host permissions.
const $ = (id) => document.getElementById(id);
const esc = (s) => String(s).replace(/[&<>"]/g,
  (c) => ({"&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;"}[c]));
let n = 0;

function headerValue(headers, name) {
  name = name.toLowerCase();
  for (const h of headers || [])
    if (h.name.toLowerCase() === name) return h.value;
  return null;
}

function addRow(method, url, status, traceId) {
  $("empty").style.display = "none";
  const base = $("base").value.replace(/\/+$/, "");
  const tr = document.createElement("tr");
  tr.innerHTML = `<td>${esc(method)}</td>
    <td class="url" title="${esc(url)}">${esc(url)}</td>
    <td>${esc(status)}</td>
    <td class="mono"><a href="${esc(base)}/#trace=${esc(traceId)}"
      target="_blank">${esc(traceId)}</a></td>`;
  $("rows").appendChild(tr);
  n += 1;
  $("count").textContent = n + " traced";
}

chrome.devtools.network.onRequestFinished.addListener((req) => {
  try {
    const hs = req.response && req.response.headers;
    const tid = headerValue(hs, "X-B3-TraceId");
    if (!tid || !/^[0-9a-fA-F]+$/.test(tid)) return;
    // Unsampled requests were never recorded — a link would 404.
    if (headerValue(hs, "X-B3-Sampled") === "0") return;
    addRow(req.request.method, req.request.url,
           req.response.status, tid);
  } catch (e) { /* never break the panel on a malformed entry */ }
});

$("clear").onclick = () => {
  for (const tr of [...$("rows").querySelectorAll("tr")].slice(1))
    tr.remove();
  n = 0;
  $("count").textContent = "";
  $("empty").style.display = "";
};
