"use strict";
// Register the "Zipkin" devtools panel (works in Chrome and Firefox;
// Firefox aliases chrome.* for devtools APIs).
chrome.devtools.panels.create("Zipkin", "", "panel.html", () => {});
