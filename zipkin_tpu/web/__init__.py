"""Static single-page UI (the zipkin-web role, minus the JVM).

Reference: zipkin-web's mustache + Flight.js SPA — trace list + search
(web/Main.scala:77-89, Handlers.scala:23-49), per-trace waterfall
(component_ui/trace.js), dagre-d3 dependency graph fed by
/api/dependencies (component_ui/dependencyGraph.js:1-40). Re-expressed
as one dependency-free HTML file rendered from the same JSON API this
framework already serves; no build system, no vendored JS.
"""

from __future__ import annotations

import os

_HERE = os.path.dirname(os.path.abspath(__file__))


def index_html() -> bytes:
    with open(os.path.join(_HERE, "index.html"), "rb") as f:
        return f.read()
