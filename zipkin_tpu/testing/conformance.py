"""Behavioral conformance suite for SpanStore implementations.

Parity target: ``SpanStoreValidator``
(zipkin-common/.../storage/util/SpanStoreValidator.scala:27,80,100) — the
reference's reusable suite that every backend (in-memory, redis, cassandra)
must pass. Here every backend means the in-memory reference store and the
TPU columnar store.

Usage (pytest):

    @pytest.mark.parametrize("name", conformance_test_names())
    def test_store(name):
        run_conformance_test(name, lambda: MyStore())
"""

from __future__ import annotations

from typing import Callable, Dict, List

from zipkin_tpu.models.span import Annotation, BinaryAnnotation, Endpoint, Span
from zipkin_tpu.models.trace import Trace
from zipkin_tpu.store.base import SpanStore, TraceIdDuration, TTL_TOP

EP = Endpoint(123, 123, "service")


def _bin(key: str, value: str) -> BinaryAnnotation:
    return BinaryAnnotation(key, value.encode(), host=EP)


SPAN_ID = 456
ANN1 = Annotation(1, "cs", EP)
ANN2 = Annotation(2, "sr", None)
ANN3 = Annotation(20, "custom", EP)
ANN4 = Annotation(20, "custom", EP)
ANN5 = Annotation(5, "custom", EP)
ANN6 = Annotation(6, "custom", EP)
ANN7 = Annotation(7, "custom", EP)
ANN8 = Annotation(8, "custom", EP)

SPAN1 = Span(123, "methodcall", SPAN_ID, None, (ANN1, ANN3), (_bin("BAH", "BEH"),))
SPAN2 = Span(456, "methodcall", SPAN_ID, None, (ANN2,), (_bin("BAH2", "BEH2"),))
SPAN3 = Span(789, "methodcall", SPAN_ID, None, (ANN2, ANN3, ANN4), (_bin("BAH2", "BEH2"),))
SPAN4 = Span(999, "methodcall", SPAN_ID, None, (ANN6, ANN7), ())
SPAN5 = Span(999, "methodcall", SPAN_ID, None, (ANN5, ANN8), (_bin("BAH2", "BEH2"),))
SPAN_EMPTY_SPAN_NAME = Span(123, "", SPAN_ID, None, (ANN1, ANN2), ())
SPAN_EMPTY_SERVICE_NAME = Span(123, "spanname", SPAN_ID, None, (), ())

StoreFactory = Callable[[], SpanStore]
_TESTS: Dict[str, Callable[[StoreFactory], None]] = {}


def _test(name: str):
    def deco(f):
        _TESTS[name] = f
        return f

    return deco


def _load(factory: StoreFactory, spans) -> SpanStore:
    store = factory()
    store.apply(list(spans))
    return store


@_test("get by trace id")
def _(factory):
    store = _load(factory, [SPAN1])
    spans = store.get_spans_by_trace_id(SPAN1.trace_id)
    assert len(spans) == 1
    assert spans[0] == SPAN1


@_test("get by trace ids")
def _(factory):
    span666 = Span(666, "methodcall2", SPAN_ID, None, (ANN2,), (_bin("BAH2", "BEH2"),))
    store = _load(factory, [SPAN1, span666])

    actual1 = store.get_spans_by_trace_ids([SPAN1.trace_id])
    assert actual1
    trace1 = Trace(actual1[0])
    assert trace1.spans and trace1.spans[0] == SPAN1

    actual2 = store.get_spans_by_trace_ids([SPAN1.trace_id, span666.trace_id])
    assert len(actual2) == 2
    assert Trace(actual2[0]).spans[0] == SPAN1
    assert Trace(actual2[1]).spans[0] == span666


@_test("get by trace ids returns an empty list if nothing is found")
def _(factory):
    store = _load(factory, [])
    assert store.get_spans_by_trace_ids([54321]) == []


@_test("alter TTL on a span")
def _(factory):
    store = _load(factory, [SPAN1])
    store.set_time_to_live(SPAN1.trace_id, 1234.0)
    assert store.get_time_to_live(SPAN1.trace_id) in (1234.0, TTL_TOP)


@_test("check for existing traces")
def _(factory):
    store = _load(factory, [SPAN1, SPAN4])
    result = store.traces_exist([SPAN1.trace_id, SPAN4.trace_id, 111111])
    assert result == {SPAN1.trace_id, SPAN4.trace_id}


@_test("get spans by name")
def _(factory):
    store = _load(factory, [SPAN1])
    assert store.get_span_names("service") == {SPAN1.name}


@_test("get service names")
def _(factory):
    store = _load(factory, [SPAN1])
    assert store.get_all_service_names() == set(SPAN1.service_names)


@_test("get trace ids by name")
def _(factory):
    store = _load(factory, [SPAN1])
    assert store.get_trace_ids_by_name("service", None, 100, 3)[0].trace_id == SPAN1.trace_id
    assert (
        store.get_trace_ids_by_name("service", "methodcall", 100, 3)[0].trace_id
        == SPAN1.trace_id
    )
    assert store.get_trace_ids_by_name("badservice", None, 100, 3) == []
    assert store.get_trace_ids_by_name("service", "badmethod", 100, 3) == []
    assert store.get_trace_ids_by_name("badservice", "badmethod", 100, 3) == []


@_test("get traces duration")
def _(factory):
    store = _load(factory, [SPAN1, SPAN2, SPAN3, SPAN4])
    expected = [
        TraceIdDuration(SPAN1.trace_id, 19, 1),
        TraceIdDuration(SPAN2.trace_id, 0, 2),
        TraceIdDuration(SPAN3.trace_id, 18, 2),
        TraceIdDuration(SPAN4.trace_id, 1, 6),
    ]
    result = store.get_traces_duration(
        [SPAN1.trace_id, SPAN2.trace_id, SPAN3.trace_id, SPAN4.trace_id]
    )
    assert sorted(result, key=lambda d: d.trace_id) == sorted(
        expected, key=lambda d: d.trace_id
    )

    store2 = _load(factory, [SPAN4])
    assert store2.get_traces_duration([999]) == [TraceIdDuration(999, 1, 6)]
    store2.apply([SPAN5])
    assert store2.get_traces_duration([999]) == [TraceIdDuration(999, 3, 5)]


@_test("get trace ids by annotation")
def _(factory):
    store = _load(factory, [SPAN1])
    res1 = store.get_trace_ids_by_annotation("service", "custom", None, 100, 3)
    assert res1[0].trace_id == SPAN1.trace_id
    # Core annotations are not indexed.
    assert store.get_trace_ids_by_annotation("service", "cs", None, 100, 3) == []
    res3 = store.get_trace_ids_by_annotation("service", "BAH", b"BEH", 100, 3)
    assert res3[0].trace_id == SPAN1.trace_id


@_test("limit on annotations")
def _(factory):
    store = _load(factory, [SPAN1, SPAN4, SPAN5])
    res = store.get_trace_ids_by_annotation("service", "custom", None, 100, 2)
    assert len(res) == 2
    assert res[0].trace_id == SPAN1.trace_id
    assert res[1].trace_id == SPAN5.trace_id


@_test("wont index empty service names")
def _(factory):
    store = _load(factory, [SPAN_EMPTY_SERVICE_NAME])
    assert store.get_all_service_names() == set()


@_test("wont index empty span names")
def _(factory):
    # SPAN_EMPTY_SPAN_NAME has service "service" but span name "": the
    # empty name must not appear in the span-name index. (The reference
    # validator queried get_span_names("") which is vacuous; this version
    # actually checks the indexing behavior.)
    store = _load(factory, [SPAN_EMPTY_SPAN_NAME])
    assert store.get_span_names("service") == set()


@_test("one trace with many matching spans fills one limit slot")
def _(factory):
    # Trace 123 has three spans carrying "custom"; trace 999 has one,
    # older. With limit 2 the hot trace must collapse to a single slot
    # (its most recent span's ts) so trace 999 still surfaces.
    hot1 = Span(123, "methodcall", 1, None, (Annotation(10, "custom", EP),), ())
    hot2 = Span(123, "methodcall", 2, None, (Annotation(11, "custom", EP),), ())
    hot3 = Span(123, "methodcall", 3, None, (Annotation(12, "custom", EP),), ())
    cold = Span(999, "methodcall", 4, None, (Annotation(5, "custom", EP),), ())
    store = _load(factory, [hot1, hot2, hot3, cold])
    res = store.get_trace_ids_by_annotation("service", "custom", None, 100, 2)
    assert [i.trace_id for i in res] == [123, 999]
    assert res[0].timestamp == 12
    by_name = store.get_trace_ids_by_name("service", None, 100, 2)
    assert [i.trace_id for i in by_name] == [123, 999]


@_test("end_ts filters results")
def _(factory):
    store = _load(factory, [SPAN1])  # last annotation at ts 20
    assert store.get_trace_ids_by_name("service", None, 19, 3) == []
    assert store.get_trace_ids_by_name("service", None, 20, 3) != []


def conformance_test_names() -> List[str]:
    return list(_TESTS)


def run_conformance_test(name: str, factory: StoreFactory) -> None:
    _TESTS[name](factory)


def run_all(factory: StoreFactory) -> None:
    for name, fn in _TESTS.items():
        fn(factory)
