"""Crash-injection harness: named kill points + a subprocess drive.

The durability contract (docs/DURABILITY.md) is only testable by
actually dying: a child process drives the normal ingest path with a
WAL attached and ``SIGKILL``s ITSELF at a named point mid-write; the
parent then recovers from what survived on disk and compares the
result bitwise against an uncrashed oracle drive of the same batches.
This is the FakeCassandra/minicluster move (SURVEY §4) applied to
durability: real process death, no mocked fsync.

Kill points (activated via ``ZIPKIN_CRASH_POINT=<name>[:N]`` — fire on
the Nth hit, default the 1st; SIGKILL, so no atexit/finally runs):

- ``before-append``   just before a launch group's WAL append — the
  batch must be absent in full after recovery.
- ``after-append``    between the durable append and the donating
  device commit — replay must re-apply the batch.
- ``after-commit``    after the device commit, before the ack returns —
  the batch is present though never acked (durability is one-way).
- ``mid-seal``        between an eviction-capture pull and the cold
  segment append — replay must re-capture and re-seal.
- ``mid-checkpoint``  between checkpoint.save's two renames — load
  must fall back to ``.old`` (or a fresh store) + WAL replay.
- ``mid-truncate``    between per-segment deletes of a checkpoint's
  WAL truncation — the surviving suffix must still recover.

``kill_point`` compiles to a dict-miss-fast no-op when the env var is
unset, so the production hooks cost one attribute load per call site.

Child usage (the parent helper ``run_crash_child`` builds this):

    ZIPKIN_CRASH_POINT=after-append \\
    python -m zipkin_tpu.testing.crash WORKDIR --batches 10 --ckpt-at 5

The child acks each batch only after ``wait_durable`` (fsync=batch by
default) and journals progress to ``WORKDIR/acked.log`` (fsync'd), so
the parent knows exactly which batches were durably acked. It asserts
one WAL record per batch (exit 3 otherwise) — the invariant that lets
the parent line the recovered record frontier up against a batch-
granular oracle drive.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
from typing import List, Optional, Sequence, Tuple

# -- the kill switch (read once, at import, in the CHILD process) -------

_spec = os.environ.get("ZIPKIN_CRASH_POINT")
if _spec:
    _name, _, _nth = _spec.partition(":")
    _POINT: Optional[str] = _name
    _NTH = int(_nth) if _nth else 1
else:
    _POINT, _NTH = None, 0
_hits = 0

KILL_POINTS = ("before-append", "after-append", "after-commit",
               "mid-seal", "mid-checkpoint", "mid-truncate")


def kill_point(name: str) -> None:
    """Die here (SIGKILL — no cleanup, no flush) when this is the
    activated point's Nth hit. No-op unless ZIPKIN_CRASH_POINT is set."""
    global _hits
    if _POINT is None or name != _POINT:
        return
    _hits += 1
    if _hits >= _NTH:
        os.kill(os.getpid(), signal.SIGKILL)


# -- shared drive fixtures (child AND parent oracle use these) ----------
#
# Geometry note: the serial config never evicts at the drive sizes the
# tests use (WAL mechanics only); the tiered config's 2^8 ring laps
# several times, so eviction capture and cold-tier sealing are on the
# replayed path. Batches are sized so each apply plans exactly ONE
# launch unit (<= CHAIN_SIZES[0] trace parts, well under the span/ann
# budgets) — the child asserts it, see module docstring.

_TRACES_PER_BATCH = 6


def crash_config(tiered: bool):
    from zipkin_tpu.store import device as dev

    if tiered:
        return dev.StoreConfig(
            capacity=1 << 8, ann_capacity=1 << 10, bann_capacity=1 << 9,
            max_services=32, max_span_names=64,
            max_annotation_values=256, max_binary_keys=64,
            cms_width=1 << 10, hll_p=6, quantile_buckets=256,
        )
    return dev.StoreConfig(
        capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
        max_services=32, max_span_names=128, max_annotation_values=256,
        max_binary_keys=64, cms_width=1 << 10, hll_p=8,
        quantile_buckets=512,
    )


def build_crash_store(tiered: bool):
    """A fresh store at the harness geometry — the recovery factory
    and the oracle builder (identical construction on both sides is
    what makes the bitwise comparison meaningful)."""
    from zipkin_tpu.store.tpu import TpuSpanStore

    hot = TpuSpanStore(crash_config(tiered))
    if not tiered:
        return hot
    from zipkin_tpu.store.archive import ArchiveParams, TieredSpanStore

    return TieredSpanStore(hot, params=ArchiveParams.for_config(
        hot.config, compact_fanin=2, small_span_limit=hot.config.capacity,
        bloom_bits=1 << 12, cms_width=1 << 10, hll_p=6,
    ))


def crash_batches(n_batches: int, tiered: bool = False) -> List[list]:
    """Deterministic batches (seeded rng): the child drives them, the
    parent re-derives them for the oracle."""
    import numpy as np

    from zipkin_tpu.tracegen.gen import generate_traces

    rng = np.random.default_rng(41 if tiered else 40)
    traces = generate_traces(
        n_traces=n_batches * _TRACES_PER_BATCH, max_depth=3,
        rng=rng, n_services=8,
    )
    return [
        [s for t in traces[i * _TRACES_PER_BATCH:
                           (i + 1) * _TRACES_PER_BATCH] for s in t]
        for i in range(n_batches)
    ]


def _paths(workdir: str) -> Tuple[str, str, str]:
    return (os.path.join(workdir, "wal"),
            os.path.join(workdir, "ckpt"),
            os.path.join(workdir, "acked.log"))


# -- child ---------------------------------------------------------------


def _child_main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="zipkin_tpu.testing.crash")
    ap.add_argument("workdir")
    ap.add_argument("--batches", type=int, default=10)
    ap.add_argument("--ckpt-at", default="",
                    help="comma-separated 1-based batch counts after "
                         "which to checkpoint")
    ap.add_argument("--tiered", action="store_true")
    ap.add_argument("--fsync", default="batch")
    ap.add_argument("--segment-bytes", type=int, default=64 << 20)
    args = ap.parse_args(argv)

    from zipkin_tpu import checkpoint
    from zipkin_tpu.wal import WriteAheadLog

    os.makedirs(args.workdir, exist_ok=True)
    wal_dir, ckpt_dir, acked_path = _paths(args.workdir)
    ckpt_at = {int(x) for x in args.ckpt_at.split(",") if x}

    store = build_crash_store(args.tiered)
    hot = getattr(store, "hot", store)
    wal = WriteAheadLog(wal_dir, fsync=args.fsync,
                        segment_bytes=args.segment_bytes)
    hot.attach_wal(wal)
    batches = crash_batches(args.batches, tiered=args.tiered)

    acked = open(acked_path, "a")
    for i, batch in enumerate(batches):
        store.apply(batch)
        if wal.last_seq != i + 1:
            print(f"batch {i} planned {wal.last_seq - i} launch units; "
                  f"the harness requires exactly one — shrink the "
                  f"batch geometry", file=sys.stderr)
            return 3
        wal.wait_durable(wal.last_seq)
        # The ack: a receiver would return OK here. Journaled with its
        # own fsync so the parent knows the durably-acked frontier.
        acked.write(f"{i} {wal.last_seq}\n")
        acked.flush()
        os.fsync(acked.fileno())
        if i + 1 in ckpt_at:
            checkpoint.save(store, ckpt_dir)
    # No kill fired (point unset, or set past the drive): exit clean.
    wal.sync()
    return 0


# -- parent helpers (tests/test_crash.py) --------------------------------


def run_crash_child(workdir: str, point: Optional[str] = None,
                    hit: int = 1, batches: int = 10,
                    ckpt_at: Sequence[int] = (), tiered: bool = False,
                    fsync: str = "batch",
                    segment_bytes: int = 64 << 20,
                    timeout: float = 600.0):
    """Spawn the child drive; returns the CompletedProcess. A fired
    kill point shows up as ``returncode == -signal.SIGKILL``."""
    env = dict(os.environ)
    env.pop("ZIPKIN_CRASH_POINT", None)
    if point is not None:
        if point not in KILL_POINTS:
            raise ValueError(f"unknown kill point {point!r}")
        env["ZIPKIN_CRASH_POINT"] = f"{point}:{hit}"
    cmd = [sys.executable, "-m", "zipkin_tpu.testing.crash", workdir,
           "--batches", str(batches), "--fsync", fsync,
           "--segment-bytes", str(segment_bytes)]
    if ckpt_at:
        cmd += ["--ckpt-at", ",".join(str(x) for x in ckpt_at)]
    if tiered:
        cmd.append("--tiered")
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)


def acked_batches(workdir: str) -> int:
    """Number of batches the child durably acked before dying."""
    path = _paths(workdir)[2]
    if not os.path.exists(path):
        return 0
    n = 0
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) == 2:
                n = int(parts[0]) + 1
    return n


def recover_crashed(workdir: str, tiered: bool = False):
    """Recover from whatever the dead child left on disk. Returns
    (store, replay stats, wal)."""
    from zipkin_tpu.wal import WriteAheadLog, recover

    wal_dir, ckpt_dir, _ = _paths(workdir)
    wal = WriteAheadLog(wal_dir, fsync="off")
    store, stats = recover(
        ckpt_dir, wal,
        fresh_store=lambda: build_crash_store(tiered))
    return store, stats, wal


def states_bitwise_equal(a, b) -> bool:
    import jax
    import numpy as np

    fa, _ = jax.tree_util.tree_flatten(jax.device_get(a))
    fb, _ = jax.tree_util.tree_flatten(jax.device_get(b))
    return len(fa) == len(fb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(fa, fb))


def verify_recovery(workdir: str, total_batches: int,
                    tiered: bool = False) -> dict:
    """The acceptance check, shared by every kill-point test:

    1. every durably-ACKED batch survived (applied >= acked);
    2. the recovered state is BITWISE identical to an uncrashed
       oracle that applied exactly the recovered batch prefix —
       hot rings/arena/counters, and for tiered drives the cold
       segment frontier and federated trace reads too;
    3. the first un-applied batch is PROVABLY ABSENT (its trace ids
       resolve to nothing), never partially applied.

    Raises AssertionError with context on any violation."""
    store, stats, wal = recover_crashed(workdir, tiered=tiered)
    acked = acked_batches(workdir)
    applied = stats["applied_seq"]
    assert applied >= acked, (
        f"durably-acked batch lost: acked {acked}, recovered only "
        f"{applied} ({stats})")
    assert applied <= total_batches

    batches = crash_batches(total_batches, tiered=tiered)
    oracle = build_crash_store(tiered)
    for b in batches[:applied]:
        oracle.apply(b)

    hot, ohot = getattr(store, "hot", store), getattr(oracle, "hot", oracle)
    assert states_bitwise_equal(ohot.state, hot.state), (
        f"recovered hot state differs from the {applied}-batch oracle "
        f"(acked {acked}, {stats})")
    if tiered:
        cold = sorted((s.gid_lo, s.gid_hi, s.n_spans)
                      for s in store.archive.snapshot())
        ocold = sorted((s.gid_lo, s.gid_hi, s.n_spans)
                       for s in oracle.archive.snapshot())
        assert cold == ocold, (
            f"cold tier differs: {cold} vs oracle {ocold}")
        for b in batches[:applied]:
            tids = sorted({s.trace_id for s in b})[:3]
            assert (store.get_spans_by_trace_ids(tids)
                    == oracle.get_spans_by_trace_ids(tids))
    if applied < total_batches:
        missing = sorted({s.trace_id for s in batches[applied]})
        got = store.get_spans_by_trace_ids(missing)
        assert not any(got), (
            f"un-acked batch {applied} partially applied: "
            f"{sum(map(len, got))} spans present")
    return {"acked": acked, "applied": applied, **stats}


if __name__ == "__main__":
    sys.exit(_child_main())
