"""In-process Kafka broker FAKE speaking the real v0 wire protocol.

The simulate-don't-mock pattern the reference uses for exactly this
situation — an external datastore its tests can't assume — is an
in-process protocol server, not a mock (its Cassandra tests boot a
thrift-speaking FakeCassandra rather than stubbing the client:
/root/reference/zipkin-cassandra/src/test/scala/com/twitter/cassie/tests/util/FakeCassandra.scala:33-61).
This module is the Kafka equivalent for the receiver/sink pair
(reference roles: KafkaProcessor.scala:25, collector/Kafka.scala): a
TCP broker implementing Metadata (api 3), Produce (api 0) and Fetch
(api 1) at protocol version 0 over real message sets (offset / size /
CRC32 / magic / attributes / key / value), with auto-created topics of
one partition each — enough surface for batching, redelivery, corrupt
payloads, and consumer-group-less offset management to be exercised
against bytes on a socket instead of injected callables.

Also here: a minimal real-protocol client pair (MinimalKafkaProducer /
MinimalKafkaConsumer). They speak the same v0 wire format — the fake
never special-cases them — so tests drive KafkaSpanSink and
KafkaSpanReceiver through actual sockets; they double as a usable
fallback transport in environments without kafka-python (this image).
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import zlib
from typing import Dict, Iterable, List, Optional, Tuple

from zipkin_tpu.ingest.scribe_server import read_exact as _read_exact

API_PRODUCE = 0
API_FETCH = 1
API_METADATA = 3

# Request frames larger than this are a protocol violation or an
# attack, not traffic (same stance as scribe_server.MAX_FRAME).
MAX_FRAME = 64 << 20

ERR_NONE = 0
ERR_UNKNOWN_TOPIC = 3
ERR_CORRUPT = 2  # CRC mismatch on a produced message


# -- wire primitives --------------------------------------------------------


def _i8(v):
    return struct.pack(">b", v)


def _i16(v):
    return struct.pack(">h", v)


def _i32(v):
    return struct.pack(">i", v)


def _i64(v):
    return struct.pack(">q", v)


def _string(s: Optional[str]) -> bytes:
    if s is None:
        return _i16(-1)
    b = s.encode()
    return _i16(len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return _i32(-1)
    return _i32(len(b)) + b


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise ValueError("short kafka frame")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def i8(self):
        return struct.unpack(">b", self._take(1))[0]

    def i16(self):
        return struct.unpack(">h", self._take(2))[0]

    def i32(self):
        return struct.unpack(">i", self._take(4))[0]

    def i64(self):
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        return None if n < 0 else self._take(n).decode()

    def nbytes(self) -> Optional[bytes]:
        n = self.i32()
        return None if n < 0 else self._take(n)


def encode_message(value: Optional[bytes], key: Optional[bytes] = None,
                   corrupt_crc: bool = False) -> bytes:
    """One v0 message (magic 0): crc covers magic..value.
    ``corrupt_crc`` writes a wrong checksum — for testing the broker's
    verification path."""
    body = _i8(0) + _i8(0) + _bytes(key) + _bytes(value)
    crc = zlib.crc32(body) & 0xFFFFFFFF
    if corrupt_crc:
        crc ^= 0xDEADBEEF
    return struct.pack(">I", crc) + body


def encode_message_set(values: Iterable[bytes], base_offset: int = 0,
                       corrupt_crc: bool = False) -> bytes:
    out = []
    for i, v in enumerate(values):
        msg = encode_message(v, corrupt_crc=corrupt_crc)
        out.append(_i64(base_offset + i) + _i32(len(msg)) + msg)
    return b"".join(out)


def decode_message_set(
    buf: bytes, strict: bool = False
) -> List[Tuple[int, Optional[bytes], Optional[bytes]]]:
    """[(offset, key, value)] — verifies each message's CRC; raises
    ValueError on mismatch. A trailing partial message is skipped per
    protocol on the FETCH side (responses truncate at max_bytes); a
    PRODUCE set must be complete, so producers pass ``strict=True`` and
    a truncated set raises instead of silently shipping a prefix."""
    out = []
    pos = 0
    while pos < len(buf):
        truncated = pos + 12 > len(buf)
        if not truncated:
            offset, size = struct.unpack(">qi", buf[pos:pos + 12])
            truncated = size < 0 or pos + 12 + size > len(buf)
        if truncated:
            if strict:
                raise ValueError("truncated message set")
            break  # partial trailing message (fetch truncation)
        msg = buf[pos + 12:pos + 12 + size]
        crc = struct.unpack(">I", msg[:4])[0]
        if zlib.crc32(msg[4:]) & 0xFFFFFFFF != crc:
            raise ValueError(f"crc mismatch at offset {offset}")
        r = _Reader(msg[4:])
        r.i8()  # magic
        r.i8()  # attributes
        key = r.nbytes()
        out.append((offset, key, r.nbytes()))
        pos += 12 + size
    return out


# -- the broker -------------------------------------------------------------


class _PartitionLog:
    """One partition's in-memory log: a list of encoded messages, each
    re-stamped with its real offset at append time."""

    def __init__(self):
        self.values: List[bytes] = []  # raw message bytes (crc..value)
        self.lock = threading.Lock()  # lock-order: 89 fake-partition

    def append(self, msgs: List[bytes]) -> int:
        with self.lock:
            base = len(self.values)
            self.values.extend(msgs)
            return base

    def fetch(self, offset: int, max_bytes: int) -> Tuple[bytes, int]:
        with self.lock:
            hw = len(self.values)
            out, size = [], 0
            for off in range(max(0, offset), hw):
                msg = self.values[off]
                entry = _i64(off) + _i32(len(msg)) + msg
                if size + len(entry) > max_bytes and out:
                    break
                out.append(entry)
                size += len(entry)
                if size >= max_bytes:
                    break
            return b"".join(out), hw


class _BrokerHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        sock = self.request
        while True:
            head = _read_exact(sock, 4)
            if head is None:
                return
            (size,) = struct.unpack(">i", head)
            if size <= 0 or size > MAX_FRAME:
                return  # protocol violation: drop the connection
            frame = _read_exact(sock, size)
            if frame is None:
                return
            resp = self.server.broker._dispatch(frame)
            if resp is not None:
                sock.sendall(_i32(len(resp)) + resp)


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class FakeKafkaBroker:
    """Single-node, single-partition-per-topic broker. Topics
    auto-create on first produce/fetch/metadata mention (the dev-mode
    kafka default the reference's quickstart assumes)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.topics: Dict[str, _PartitionLog] = {}
        self._lock = threading.Lock()  # lock-order: 87 fake-broker
        self.stats = {"produce": 0, "fetch": 0, "metadata": 0,
                      "corrupt_rejected": 0}
        self._server = _Server((host, port), _BrokerHandler)
        self._server.broker = self
        self.host, self.port = self._server.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --

    def start(self) -> "FakeKafkaBroker":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    def log(self, topic: str) -> _PartitionLog:
        with self._lock:
            if topic not in self.topics:
                self.topics[topic] = _PartitionLog()
            return self.topics[topic]

    # -- protocol --

    def _dispatch(self, frame: bytes) -> Optional[bytes]:
        r = _Reader(frame)
        api_key = r.i16()
        r.i16()  # api_version (v0 assumed)
        corr = r.i32()
        r.string()  # client_id
        if api_key == API_PRODUCE:
            acks, body = self._produce(r)
            return None if acks == 0 else _i32(corr) + body
        if api_key == API_FETCH:
            return _i32(corr) + self._fetch(r)
        if api_key == API_METADATA:
            return _i32(corr) + self._metadata(r)
        # Unknown api: drop the connection's request (close).
        return _i32(corr)

    def _produce(self, r: _Reader) -> Tuple[int, bytes]:
        self.stats["produce"] += 1
        acks = r.i16()
        r.i32()  # timeout
        out = []
        n_topics = r.i32()
        out.append(_i32(n_topics))
        for _ in range(n_topics):
            topic = r.string() or ""
            n_parts = r.i32()
            out.append(_string(topic) + _i32(n_parts))
            for _ in range(n_parts):
                partition = r.i32()
                mset = r.nbytes() or b""
                try:
                    # strict: a truncated produce set is a framing bug,
                    # not fetch truncation — reject it whole.
                    triples = decode_message_set(mset, strict=True)
                    # Re-encode key+value; offsets are assigned here.
                    msgs = [encode_message(v, key=k)
                            for _, k, v in triples]
                    base = self.log(topic).append(msgs)
                    err = ERR_NONE
                except ValueError:
                    self.stats["corrupt_rejected"] += 1
                    base, err = -1, ERR_CORRUPT
                out.append(_i32(partition) + _i16(err) + _i64(base))
        return acks, b"".join(out)

    def _fetch(self, r: _Reader) -> bytes:
        self.stats["fetch"] += 1
        r.i32()  # replica_id
        r.i32()  # max_wait_ms (the fake answers immediately)
        r.i32()  # min_bytes
        out = []
        n_topics = r.i32()
        out.append(_i32(n_topics))
        for _ in range(n_topics):
            topic = r.string() or ""
            n_parts = r.i32()
            out.append(_string(topic) + _i32(n_parts))
            for _ in range(n_parts):
                partition = r.i32()
                offset = r.i64()
                max_bytes = r.i32()
                mset, hw = self.log(topic).fetch(offset, max_bytes)
                out.append(_i32(partition) + _i16(ERR_NONE) + _i64(hw)
                           + _i32(len(mset)) + mset)
        return b"".join(out)

    def _metadata(self, r: _Reader) -> bytes:
        self.stats["metadata"] += 1
        n = r.i32()
        names = [r.string() or "" for _ in range(n)]
        with self._lock:
            if not names:
                names = sorted(self.topics)
        out = [_i32(1), _i32(0), _string(self.host), _i32(self.port)]
        out.append(_i32(len(names)))
        for name in names:
            self.log(name)  # auto-create
            out.append(_i16(ERR_NONE) + _string(name) + _i32(1)
                       + _i16(ERR_NONE) + _i32(0) + _i32(0)
                       + _i32(1) + _i32(0)      # replicas: [0]
                       + _i32(1) + _i32(0))     # isr: [0]
        return b"".join(out)


# -- minimal real-protocol clients ------------------------------------------


class _Conn:
    def __init__(self, host: str, port: int, client_id: str):
        self.sock = socket.create_connection((host, port), timeout=10)
        self.client_id = client_id
        self._corr = 0  # guarded-by: _lock
        self._lock = threading.Lock()  # lock-order: 88 fake-conn

    def request(self, api_key: int, body: bytes,
                expect_response: bool = True) -> Optional[_Reader]:
        with self._lock:
            self._corr += 1
            corr = self._corr
            frame = (_i16(api_key) + _i16(0) + _i32(corr)
                     + _string(self.client_id) + body)
            self.sock.sendall(_i32(len(frame)) + frame)
            if not expect_response:
                return None
            head = _read_exact(self.sock, 4)
            if head is None:
                raise ConnectionError("broker closed connection")
            (size,) = struct.unpack(">i", head)
            payload = _read_exact(self.sock, size)
            if payload is None:
                raise ConnectionError("short broker response")
            r = _Reader(payload)
            got = r.i32()
            if got != corr:
                raise ConnectionError(f"correlation {got} != {corr}")
            return r

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class MinimalKafkaProducer:
    """send(topic, value) over the v0 produce API, acks=1: the send
    raises on broker-reported errors (corrupt message set), matching
    the sync stance KafkaSpanSink's counters expect from a callable
    producer."""

    def __init__(self, host: str, port: int,
                 client_id: str = "zipkin-tpu-producer"):
        self._conn = _Conn(host, port, client_id)

    def __call__(self, topic: str, value: bytes) -> None:
        self.send(topic, value)

    def send(self, topic: str, value: bytes,
             corrupt_crc: bool = False) -> int:
        mset = encode_message_set([value], corrupt_crc=corrupt_crc)
        body = (_i16(1) + _i32(1000) + _i32(1) + _string(topic)
                + _i32(1) + _i32(0) + _bytes(mset))
        r = self._conn.request(API_PRODUCE, body)
        r.i32()  # topic count
        r.string()
        r.i32()  # partition count
        r.i32()  # partition
        err = r.i16()
        base = r.i64()
        if err != ERR_NONE:
            raise IOError(f"produce failed: kafka error {err}")
        return base

    def flush(self) -> None:
        pass  # acks=1 sends are synchronous

    def close(self) -> None:
        self._conn.close()


class MinimalKafkaConsumer:
    """Iterate one partition's values from ``offset`` via v0 fetch.
    No consumer group (the fake has no coordinator): offset management
    is the caller's, which is exactly the at-least-once redelivery
    model KafkaSpanReceiver documents — re-creating a consumer at an
    old offset redelivers."""

    def __init__(self, host: str, port: int, topic: str,
                 offset: int = 0, max_bytes: int = 1 << 20,
                 poll_forever: bool = False, poll_interval_s: float = 0.02,
                 client_id: str = "zipkin-tpu-consumer"):
        self._conn = _Conn(host, port, client_id)
        self.topic = topic
        self.offset = offset
        self.max_bytes = max_bytes
        self.poll_forever = poll_forever
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def _fetch_once(
        self,
    ) -> List[Tuple[int, Optional[bytes], Optional[bytes]]]:
        body = (_i32(-1) + _i32(10) + _i32(0) + _i32(1)
                + _string(self.topic) + _i32(1) + _i32(0)
                + _i64(self.offset) + _i32(self.max_bytes))
        r = self._conn.request(API_FETCH, body)
        r.i32()  # topic count
        r.string()
        r.i32()  # partition count
        r.i32()  # partition
        err = r.i16()
        r.i64()  # high watermark
        mset = r.nbytes() or b""
        if err != ERR_NONE:
            raise IOError(f"fetch failed: kafka error {err}")
        return decode_message_set(mset)

    def __iter__(self) -> Iterable[bytes]:
        import time as _time

        while not self._stop.is_set():
            pairs = self._fetch_once()
            if not pairs:
                if not self.poll_forever:
                    return
                _time.sleep(self.poll_interval_s)
                continue
            for off, _key, value in pairs:
                self.offset = off + 1
                yield value or b""

    def close(self) -> None:
        self._stop.set()
        self._conn.close()
