"""Reusable test fixtures: the store conformance suite and span builders."""
