"""Concurrency primitives for host-side store coordination.

The reference leans on ArrayBlockingQueue / synchronized / immutable
data for thread safety (SURVEY.md §5 "race detection"). Our device
store has one extra hazard the JVM design doesn't: ``ingest_step``
donates the previous state's device buffers (buffer donation is how the
ring update stays allocation-free), so a query that is still reading a
snapshot of the old state can see its buffers deleted mid-kernel.

``RWLock`` makes the swap safe: queries hold a read lock across their
kernel launches and host gathers; ingest takes the write lock to run
the donating step and swap the state pointer. Writer-preference keeps
the hot ingest path from starving behind a stream of queries.
"""

from __future__ import annotations

import contextlib
import threading


class RWLock:
    """Writer-preference readers/writer lock (non-reentrant)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()  # lock-order: 42 rwlock-internal
        self._readers = 0  # guarded-by: _cond
        self._writers_waiting = 0  # guarded-by: _cond
        self._writer = False  # guarded-by: _cond

    @contextlib.contextmanager
    def read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
        try:
            yield
        finally:
            with self._cond:
                self._readers -= 1
                if self._readers == 0:
                    self._cond.notify_all()

    @contextlib.contextmanager
    def write(self):
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()
