"""Multi-host deployment of the sharded store (SURVEY §2.8).

The reference scales out with stateless JVM collectors behind ZooKeeper
server-sets and a storage tier any collector can write to
(ScribeSpanReceiver.scala:42-56, CassieSpanStore key-range sharding).
The TPU build's storage is DEVICE-resident, so scale-out becomes a
placement problem: every trace lives on exactly one shard of a global
``jax.sharding.Mesh``, and a span must reach the HOST that owns that
shard before it can be written. This module provides the three pieces
of that story; the collectives themselves (psum/pmax summaries inside
``shard_map``) are the same code single-host uses — XLA routes them
over ICI within a slice and DCN across hosts, nothing in
``parallel/shard.py`` changes.

1. ``initialize`` — ``jax.distributed.initialize`` wrapper: one process
   per host, a coordinator address, and the global device view.
2. ``global_mesh`` / ``local_shard_ids`` — the global 1-D shard mesh
   and the slice of it this process physically owns (its addressable
   devices).
3. Trace routing: ``shard_of`` is the SAME trace-affine hash
   ``ShardedSpanStore`` uses, so the data plane can route spans to
   owner hosts *before* ingest. The intended transport is the Kafka
   path that already exists: produce with ``partition_for_trace`` (a
   topic with one partition per shard), and each host consumes exactly
   ``partitions_for_process`` — Kafka becomes the cross-host routing
   tier (the role ZooKeeper-discovered scribe fanout played for the
   reference), and every consumed span is local-by-construction.

No multi-host fabric exists in this environment, so ``initialize`` is
exercised only for its argument handling; the routing math — the part
correctness depends on — is pure and unit-tested
(tests/test_parallel.py::test_multihost_routing_math).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from zipkin_tpu.columnar.encode import to_signed64

# Keep the hash in lockstep with ShardedSpanStore._shard_of: one
# constant, two call sites, zero drift.
_GOLDEN = 0x9E3779B97F4A7C15


def shard_of(trace_id: int, n_shards: int) -> int:
    """Owning shard of a trace — identical to ShardedSpanStore's
    trace-affine routing (parallel/shard.py), applied to the GLOBAL
    shard count. Called once per span on the ingest routing path, so
    to_signed64 is bound at module scope, not per call."""
    return (to_signed64(trace_id) * _GOLDEN) % n_shards


def partition_for_trace(trace_id: int, n_shards: int) -> int:
    """Kafka partition key for a span: partition i feeds shard i. A
    producer using this guarantees every message a host consumes is for
    a shard that host owns."""
    return shard_of(trace_id, n_shards)


def initialize(coordinator_address: str, num_processes: int,
               process_id: int) -> None:
    """Join the multi-host jax runtime (one call per process, before
    any jax computation). Thin wrapper so deployments depend on this
    module, not on jax internals."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(axis: str = "shard"):
    """The global 1-D shard mesh over every device of every process.
    Single-host this is exactly the mesh the tests/dryrun build."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), axis_names=(axis,))


def local_shard_ids(mesh) -> List[int]:
    """Global shard indices whose device is addressable from THIS
    process — the shards this host feeds and serves. The mesh is the
    1-D shard mesh from ``global_mesh`` (flattened if not)."""
    import jax

    local = {d.id for d in jax.local_devices()}
    devs = list(np.asarray(mesh.devices).reshape(-1))
    return [i for i, d in enumerate(devs) if d.id in local]


def partitions_for_process(mesh) -> List[int]:
    """Kafka partitions this process must consume: exactly its local
    shards' indices (partition i ↔ shard i)."""
    return local_shard_ids(mesh)


def route_spans(spans: Sequence, n_shards: int,
                keep: Optional[Sequence[int]] = None):
    """Group spans by owning shard; ``keep`` (e.g. this process's local
    shard ids) filters to locally-owned groups. Returns
    {shard_id: [spans]} — the host-side pre-partitioning a multi-host
    feed applies before ShardedSpanStore.apply (which re-derives the
    same affinity, so a locally-complete group lands intact)."""
    keep_set = None if keep is None else set(keep)
    out = {}
    for s in spans:
        sid = shard_of(s.trace_id, n_shards)
        if keep_set is not None and sid not in keep_set:
            continue
        out.setdefault(sid, []).append(s)
    return out
