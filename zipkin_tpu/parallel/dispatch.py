"""Cross-shard query dispatcher: N concurrent sharded reads, ONE
collective launch per micro-window.

The r14 `_coll_lock` fix made concurrent sharded reads *correct* by
serializing every shard_map collective launch (the XLA CPU rendezvous
deadlock), but correctness-by-queueing is a throughput ceiling: N API
threads each pay a full collective dispatch, back to back. This module
generalizes ``query/coalesce.ResidentCoalescer`` from "batch trace-id
queries into one get_trace_ids_multi call" to "batch ANY sharded
collective read into one launch":

- **catalog reads** (``ShardedSpanStore._cat`` — service presence,
  histogram/top-k rows, HLL registers, spans_seen): ≥2 concurrent
  requests fuse into ONE catalog-bundle launch
  (``_fetch_cat_bundle``) that all-reduces every catalog array in a
  single shard_map program; the host slices each caller's row. A lone
  request keeps the cheap singular per-key kernel.
- **index top-k reads** (``get_trace_ids_by_name`` /
  ``get_trace_ids_by_annotation``): concurrent requests ride one
  ``get_trace_ids_multi`` call — the batched multi-probe mesh kernel —
  exactly the ResidentCoalescer move, one tier lower (the engine's
  coalescer batches requests per engine; this batches across
  everything hitting the store, engines included).

Both merges are host-side monoid folds of per-shard results (psum/pmax
in-graph, row slicing on the host), so batched answers are bitwise
identical to serialized ones — gated by tests/test_parallel.py and the
bench_smoke ``run_sharded`` phase.

Executor discipline matches ResidentCoalescer: one standing daemon
thread, started lazily; double-buffered pending list; ``window_s``
applies only on idle entry (a batch built while a launch ran needs no
extra wait); after ``close()`` callers degrade to inline execution.
One addition: the store's singular fallbacks re-enter the public query
methods (``get_trace_ids_multi``'s distrusted-bucket path), so a
request arriving FROM the executor thread itself executes inline
instead of enqueueing — the executor waiting on itself would deadlock.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional


class _Req:
    """One caller's request + its rendezvous state. ``ctx`` is the
    submitting thread's (trace_id, span_id) request context
    (obs.fleet.current_request_context) — the dispatcher's fused
    launch span parents under it, so an API read that rode a shared
    collective shows the shared launch as a child span. ``t_enq`` is
    the enqueue timestamp the stuck-queue watchdog ages against."""

    __slots__ = ("kind", "payload", "result", "error", "done", "ctx",
                 "t_enq")

    def __init__(self, kind: str, payload):
        self.kind = kind  # "cat" | "ids"
        self.payload = payload
        self.result = None
        self.error = None
        self.done = False
        self.ctx = None
        self.t_enq = 0.0


class CrossShardDispatcher:
    """Standing micro-batch executor for a ``ShardedSpanStore``.

    The store routes ``_cat`` and the singular top-k entry points here
    while the dispatcher is open; ``window_s`` (writable at runtime)
    widens batches when traffic is bursty rather than continuous.
    """

    def __init__(self, store, window_s: float = 0.0, registry=None):
        self.store = store
        self.window_s = window_s
        self._cv = threading.Condition()  # lock-order: 15 coalesce
        self._pending: List[_Req] = []  # guarded-by: _cv
        self._inflight = 0  # guarded-by: _cv
        self._closed = False  # guarded-by: _cv
        self.batches = 0
        self.requests = 0
        self.launches_saved = 0
        self.max_batch = 0
        from zipkin_tpu import obs

        reg = registry or obs.default_registry()
        # Requests per dispatcher batch — the amortization observable
        # (mean > 1 ⇔ concurrent sharded reads genuinely shared
        # collective launches).
        self._h_size = reg.register(obs.LatencySketch(
            "zipkin_shard_dispatch_batch_size",
            "Concurrent sharded reads sharing one dispatcher batch",
            min_value=1.0))
        # Self-trace sink (obs.fleet.LineageTracker or None): when set,
        # each executed batch records a "shard dispatch" span parented
        # under the first rider's request context — the causal link
        # from an API read to the fused collective launch it shared.
        self.span_sink = None
        self._busy_since = 0.0  # guarded-by: _cv (0.0 = idle)
        # Started lazily: a store constructed for a handful of reads
        # never pays a standing thread it didn't use.
        self._thread: Optional[threading.Thread] = None

    # -- public request surface ------------------------------------------

    def cat(self, key: str, row=None):
        """One catalog entry (optionally one row of it), batched with
        every concurrent catalog read into one fused launch."""
        return self._submit(_Req("cat", (key, row)))

    def ids(self, query: tuple):
        """One get_trace_ids_multi-style query tuple, batched with
        every concurrent index read into one multi-probe launch."""
        return self._submit(_Req("ids", query))

    def _submit(self, req: _Req):
        if self.span_sink is not None:
            from zipkin_tpu.obs import fleet as _fleet

            req.ctx = _fleet.current_request_context()
        req.t_enq = time.monotonic()
        with self._cv:
            closed = self._closed
            reentrant = threading.current_thread() is self._thread
            if not closed and not reentrant:
                self._ensure_thread()
                self._pending.append(req)
                self._cv.notify_all()
                while not req.done:
                    self._cv.wait()
                if req.error is not None:
                    raise req.error
                return req.result
        # Closed (ordered shutdown) or called FROM the executor thread
        # (a singular fallback re-entering the public query surface):
        # execute inline — enqueueing from the executor would deadlock
        # on its own batch.
        self._execute([req])
        if req.error is not None:
            raise req.error
        return req.result

    # -- executor thread -------------------------------------------------

    def _ensure_thread(self) -> None:
        # Caller holds _cv and has checked not-closed.
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="zipkin-shard-dispatch",
                daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._cv:
                waited = False
                while not self._pending and not self._closed:
                    self._cv.wait()
                    waited = True
                if self._closed and not self._pending:
                    return
            # Idle-entry window only (see ResidentCoalescer): a batch
            # built while the previous launch ran dispatches now.
            w = self.window_s
            if waited and w and w > 0:
                time.sleep(w)
            with self._cv:
                batch, self._pending = self._pending, []
                self._inflight = len(batch)
                self._busy_since = time.monotonic()
            try:
                self._execute(batch)
            finally:
                with self._cv:
                    self._inflight = 0
                    self._busy_since = 0.0
                    self._cv.notify_all()

    def _execute(self, batch: List[_Req]) -> None:
        """Resolve one batch: every cat request through ≤1 fused
        catalog launch, every ids request through ≤1 multi-probe
        launch. Per-group error fan-out (a failing catalog launch must
        not poison the index reads riding the same batch)."""
        store = self.store
        cat_reqs = [r for r in batch if r.kind == "cat"]
        ids_reqs = [r for r in batch if r.kind == "ids"]
        saved = 0
        t_exec0 = time.perf_counter()
        if cat_reqs:
            try:
                fused = (len(cat_reqs) >= 2 and all(
                    r.payload[0] in store.CAT_BUNDLE_KEYS
                    for r in cat_reqs))
                if fused:
                    bundle = store._fetch_cat_bundle()
                    saved += len(cat_reqs) - 1
                for r in cat_reqs:
                    key, row = r.payload
                    entry = (bundle[key] if fused
                             else store._cat_direct(key))
                    r.result = entry if row is None else entry[row]
            except BaseException as e:  # noqa: BLE001 — per-request
                for r in cat_reqs:
                    if r.error is None and r.result is None:
                        r.error = e
        if ids_reqs:
            try:
                if len(ids_reqs) == 1:
                    q = ids_reqs[0].payload
                    if q[0] == "name":
                        ids_reqs[0].result = (
                            store._get_trace_ids_by_name_direct(*q[1:]))
                    else:
                        ids_reqs[0].result = (
                            store._get_trace_ids_by_annotation_direct(
                                *q[1:]))
                else:
                    res = store.get_trace_ids_multi(
                        [r.payload for r in ids_reqs])
                    for r, ids in zip(ids_reqs, res):
                        r.result = ids
                    saved += len(ids_reqs) - 1
            except BaseException as e:  # noqa: BLE001 — per-request
                for r in ids_reqs:
                    if r.error is None and r.result is None:
                        r.error = e
        with self._cv:
            for r in batch:
                if r.result is None and r.error is None:
                    # A valid empty answer is [] / an array, never None
                    # — None here means the group body died before
                    # assigning.
                    if r.kind == "ids":
                        r.result = []
                r.done = True
            self.batches += 1
            self.requests += len(batch)
            self.launches_saved += saved
            self.max_batch = max(self.max_batch, len(batch))
            self._cv.notify_all()
        self._h_size.observe(max(len(batch), 1))
        sink = self.span_sink
        if sink is not None:
            # One span per executed batch, parented under the first
            # rider that carried a request context — the other riders
            # are listed in the tags rather than given duplicate spans
            # (a fused launch IS one unit of work).
            ctx = next((r.ctx for r in batch if r.ctx is not None),
                       None)
            if ctx is not None:
                dur_us = max(
                    1, int((time.perf_counter() - t_exec0) * 1e6))
                try:
                    sink.record_span(
                        ctx[0], ctx[1], "shard dispatch",
                        int(time.time() * 1e6) - dur_us, dur_us,
                        {"dispatch.batch": str(len(batch)),
                         "dispatch.cat": str(len(cat_reqs)),
                         "dispatch.ids": str(len(ids_reqs)),
                         "dispatch.saved": str(saved)})
                except Exception:  # graftlint: disable=swallowed-exception
                    pass  # tracing is advisory — a sink failure must
                    # never fail the query batch it annotates

    # -- lifecycle -------------------------------------------------------

    def drain(self) -> None:
        """Block until the executor is idle (nothing pending, nothing
        in flight) — the quiesce barrier checkpoint/close use."""
        with self._cv:
            while self._pending or self._inflight:
                self._cv.wait(timeout=0.5)

    def close(self) -> None:
        """Stop the executor thread (processing everything already
        queued); later requests execute inline."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)

    @property
    def closed(self) -> bool:
        with self._cv:
            return self._closed

    def queue_age_s(self) -> float:
        """Age of the dispatcher's oldest unfinished work: seconds the
        oldest pending request has waited, or seconds the in-flight
        batch has been executing — whichever is older; 0.0 when idle.
        The stuck-queue watchdog signal (obs.fleet): a healthy
        dispatcher turns batches over in one launch time."""
        now = time.monotonic()
        with self._cv:
            age = 0.0
            if self._pending:
                age = now - min(r.t_enq for r in self._pending)
            if self._inflight and self._busy_since:
                age = max(age, now - self._busy_since)
            return max(0.0, age)

    def stats(self) -> dict:
        with self._cv:
            return {
                "batches": self.batches,
                "requests": self.requests,
                "launches_saved": self.launches_saved,
                "max_batch": self.max_batch,
            }
