"""Multi-chip parallelism: sharded ingest + collective sketch merges.

The reference scales by running stateless collector JVMs behind
ZooKeeper server-sets and sharding storage rows (SURVEY.md §2.8). The
TPU design instead shards the *ingest stream* over a device mesh axis
("shard"): every device owns an independent store state (ring + sketch
bank) and ingests its slice of the span stream; global answers come from
XLA collectives over ICI — psum for counters/histograms/count-min, pmax
for HyperLogLog registers, and an all_gather + tree-combine for the
Moments banks. No ZooKeeper, no RPC fan-in: the "group snapshot" the
reference reads from ZK (AdaptiveSampler.scala:204-237) is one psum.
"""

from zipkin_tpu.parallel.shard import (  # noqa: F401
    ShardedStore,
    global_summary,
    make_sharded_ingest,
)
