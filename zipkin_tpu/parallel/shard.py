"""shard_map-ed ingest: N store shards, one collective summary.

Mesh layout: one axis ``shard`` = data-parallel ingest shards (the
analogue of the reference's horizontally scaled collector fleet,
ScribeSpanReceiver.scala:42-56). Store state is stacked with a leading
[n_shards] dim sharded over the axis; batches likewise. The fused
per-shard ingest is exactly store/device.ingest_step; the summary that
the sampler/query layer needs crosses shards via ICI collectives only.
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zipkin_tpu.ops import moments as M
from zipkin_tpu.store import device as dev
from zipkin_tpu.store.base import service_scan_only


def compat_shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """shard_map across jax versions: the promoted ``jax.shard_map``
    (with its ``check_vma`` flag) when present, else the
    ``jax.experimental.shard_map`` this environment ships (same
    semantics; the flag was named ``check_rep`` there)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def _stack_states(config: dev.StoreConfig, n: int):
    one = dev.init_state(config)
    return jax.tree.map(lambda x: jnp.broadcast_to(x, (n,) + x.shape), one)


DEP_SUMMARY_K = 1 << 14  # the single-chip deps-read compaction bound


def _summarize(state: dev.StoreState, axis: str,
               dep_k: int = DEP_SUMMARY_K) -> Dict[str, jnp.ndarray]:
    """Cross-shard global aggregates, computed inside shard_map."""
    # Counters and additive sketches ride a psum.
    spans_seen = jax.lax.psum(state.counters["spans_seen"], axis)
    svc_counts = jax.lax.psum(state.svc_span_counts, axis)
    svc_hist = jax.lax.psum(state.svc_hist, axis)
    cms_counts = jax.lax.psum(state.cms_trace_spans, axis)
    ann_svc_counts = jax.lax.psum(state.ann_svc_counts, axis)
    # HLL merge is an elementwise max.
    hll_regs = jax.lax.pmax(state.hll_traces, axis)
    # Moments combine is associative+commutative but not "+", so the
    # bank can't ride a psum — but its COUNT column can, and the count
    # decides which cells are live. Instead of all-gathering the full
    # [S*S, 5] bank per shard (~20 MB/shard at S=1024, EVERY ingest
    # step — VERDICT r4 weak #7), psum the counts (one column), pick
    # the global top-k live cells (identical on every shard: computed
    # from replicated input), and all-gather only those k rows — the
    # same compaction the single-chip deps read uses. When more than k
    # cells are live the compacted bank would silently drop links, so
    # a lax.cond falls back to the full gather (pred is replicated;
    # both branches produce the dense bank, selected cells combine
    # through the same Chan/Pébay tree as before).
    bank = dev.total_dep_moments(state)  # [S*S, 5]
    cells = bank.shape[0]
    if dep_k is None or dep_k >= cells:
        banks = jax.lax.all_gather(bank, axis)  # [n, S*S, 5]
        dep_moments = M.reduce_moments(banks, axis=0)
    else:
        cnt = jax.lax.psum(bank[:, 0], axis)
        nz = (cnt > 0).sum()

        def compact(b):
            _, idx = jax.lax.top_k(cnt, dep_k)
            gathered = jax.lax.all_gather(b[idx], axis)  # [n, k, 5]
            top = M.reduce_moments(gathered, axis=0)
            return jnp.zeros_like(b).at[idx].set(top)

        def full(b):
            return M.reduce_moments(jax.lax.all_gather(b, axis), axis=0)

        dep_moments = jax.lax.cond(nz > dep_k, full, compact, bank)
    return {
        "spans_seen": spans_seen,
        "svc_span_counts": svc_counts,
        "svc_hist": svc_hist,
        "cms_trace_spans": cms_counts,
        "ann_svc_counts": ann_svc_counts,
        "hll_traces": hll_regs,
        "dep_moments": dep_moments,
        "ts_min": jax.lax.pmin(state.ts_min, axis),
        "ts_max": jax.lax.pmax(state.ts_max, axis),
    }


def make_sharded_archive(mesh: Mesh, axis: str = "shard"):
    """Per-shard dependency bucket close (dev.dep_close_bucket): sweeps
    the pending ring and rotates the window bank, per shard, so the
    sharded deployment keeps the same time-windowed banks as the
    single-store path. Writes route whole traces to one shard, so the
    streaming join is shard-local."""

    def fn(state, incoming):
        del incoming  # cadence is the caller's policy; kept for compat
        state = jax.tree.map(lambda x: x[0], state)
        new_state = dev.dep_close_bucket.__wrapped__(state)
        return jax.tree.map(lambda x: x[None], new_state)

    mapped = compat_shard_map(
        fn, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0,))


def make_sharded_sweep(mesh: Mesh, axis: str = "shard"):
    """Per-shard pending sweep (dev.dep_sweep) — run before dependency
    reads so cross-batch late parents are linked on every shard."""

    def fn(state):
        state = jax.tree.map(lambda x: x[0], state)
        new_state = dev.dep_sweep.__wrapped__(state)
        return jax.tree.map(lambda x: x[None], new_state)

    mapped = compat_shard_map(
        fn, mesh=mesh, in_specs=(P(axis),), out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0,))


def make_sharded_ingest(mesh: Mesh, axis: str = "shard"):
    """Build the jitted sharded step:

    (stacked_states [n,...], stacked_batches [n,...]) →
        (stacked_states, global summary replicated)
    """

    def shard_fn(state, batch):
        # shard_map hands us blocks with the leading shard dim of size 1.
        state = jax.tree.map(lambda x: x[0], state)
        batch = jax.tree.map(lambda x: x[0], batch)
        new_state = dev.ingest_step.__wrapped__(state, batch)
        summary = _summarize(new_state, axis)
        new_state = jax.tree.map(lambda x: x[None], new_state)
        return new_state, summary

    mapped = compat_shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0,))


def stacked_incoming(device_batches) -> int:
    """Max spans any shard's batch carries, read off the stacked
    pytree. SYNCS when the stack is device-resident — call it OUTSIDE
    store locks and pass the result to ``ShardedStore.ingest``."""
    return int(np.max(np.asarray(device_batches.n_spans)))


class ShardedStore:
    """Host handle for an n-shard device store.

    Round-robins host batches across shards (callers feeding from
    multiple ingest processes would instead target their local shard).
    """

    def __init__(self, mesh: Mesh, config: dev.StoreConfig, axis: str = "shard"):
        if config.paged_enabled:
            # The page planner is per-store HOST state; the stacked
            # per-shard states have no per-shard planner yet (the
            # daemon rejects --layout paged with --shards too).
            raise ValueError(
                "layout='paged' is single-device only; the sharded "
                "store has no per-shard page planner yet")
        self.mesh = mesh
        self.axis = axis
        self.config = config
        self.n = mesh.shape[axis]
        sharding = NamedSharding(mesh, P(axis))
        self.states = jax.device_put(_stack_states(config, self.n), sharding)
        self.step = make_sharded_ingest(mesh, axis)
        self.archive_step = make_sharded_archive(mesh, axis)
        self.sweep_step = make_sharded_sweep(mesh, axis)
        self.last_summary = None
        # Host upper bound of any shard's write_pos / lower bound of any
        # shard's last bucket close — paces rotation without device
        # syncs (mirrors TpuSpanStore._maybe_archive).
        self._wp_upper = 0
        self._archived_lower = 0
        self._batches_since_sweep = 0

    # Same cadence as TpuSpanStore.SWEEP_EVERY: bounds how long a
    # cross-batch child waits for its link in per-ingest summaries.
    SWEEP_EVERY = 64

    def ingest(self, device_batches,
               incoming: Optional[int] = None) -> Dict[str, np.ndarray]:
        """device_batches: pytree stacked [n_shards, ...].

        ``incoming`` is the max spans any shard's batch carries —
        compute it HOST-SIDE (or via ``stacked_incoming`` outside any
        store lock) and pass it in. It is required: reading it off the
        device-resident stack here would put a host sync inside every
        caller's lock hold (ShardedSpanStore._apply_locked commits
        under the write lock — graftlint sync-under-lock, the r10
        group-commit stall class)."""
        if incoming is None:
            raise TypeError(
                "ShardedStore.ingest requires incoming= (max spans "
                "per shard batch); use stacked_incoming(batches) "
                "OUTSIDE store locks")
        incoming = int(incoming)
        self._maybe_archive(incoming)
        self._batches_since_sweep += 1
        if self._batches_since_sweep >= self.SWEEP_EVERY:
            self.sweep()
        self.states, summary = self.step(self.states, device_batches)
        self._wp_upper += incoming
        self.last_summary = summary
        return summary

    def sweep(self) -> None:
        """Resolve pending (late-parent) children on every shard."""
        self.states = self.sweep_step(self.states)
        self._batches_since_sweep = 0

    def _maybe_archive(self, incoming: int) -> None:
        cap = self.config.capacity
        if self._wp_upper + incoming - self._archived_lower <= cap:
            return
        self.states = self.archive_step(self.states, jnp.int64(incoming))
        self._batches_since_sweep = 0
        self._archived_lower = min(
            self._wp_upper,
            max(self._wp_upper + incoming - cap, self._wp_upper - cap // 2),
        )


def global_summary(states, mesh: Mesh, axis: str = "shard",
                   dep_k: int = DEP_SUMMARY_K):
    """One-off collective summary over stacked states (no ingest).
    ``dep_k`` bounds the dependency-bank collective (None = full
    gather; see _summarize)."""

    def fn(state):
        state = jax.tree.map(lambda x: x[0], state)
        return _summarize(state, axis, dep_k)

    mapped = compat_shard_map(
        fn, mesh=mesh, in_specs=(P(axis),), out_specs=P(), check_vma=False
    )
    return jax.jit(mapped)(states)


def stack_batches(batches) -> Tuple:
    """Host: list of n DeviceBatch → stacked pytree [n, ...]."""
    return jax.tree.map(lambda *xs: np.stack(xs), *batches)


# ---------------------------------------------------------------------------
# ShardedSpanStore — the full SpanStore SPI over the mesh
# ---------------------------------------------------------------------------


from zipkin_tpu.store.analytics import WindowedAnalytics
from zipkin_tpu.store.base import SuspectGuard


class ShardedSpanStore(WindowedAnalytics, SuspectGuard):
    """SpanStore SPI over an n-shard device mesh.

    Writes route whole traces to shards by trace-id hash (the role of
    Cassandra's key-range sharding / BucketedColumnFamily hot-row
    buckets, CassieSpanStore.scala:49,108-116), so every trace is
    resident on exactly one shard and trace-local reads stay local.
    Reads run the single-store query kernels per shard under shard_map
    and merge across shards: elementwise collectives in-graph where the
    merge is min/max/sum (durations, presence, sketches), or a host
    merge of the per-shard top-k candidates for index queries — the
    batched-cluster-read role of CassieSpanStore.scala:253-270.

    Implements the same surface the conformance suite drives against the
    in-memory and single-device stores (SpanStoreValidator.scala:27).
    """

    def __init__(self, mesh: Mesh, config: dev.StoreConfig,
                 axis: str = "shard", codec=None, registry=None,
                 dispatch_window_s: float = 0.0):
        import threading

        from zipkin_tpu import obs
        from zipkin_tpu.columnar.encode import SpanCodec
        from zipkin_tpu.concurrency import RWLock
        from zipkin_tpu.parallel.dispatch import CrossShardDispatcher
        from zipkin_tpu.store.base import PinBank
        from zipkin_tpu.store.mirror import FleetMirror, SketchMirror

        self.mesh = mesh
        self.axis = axis
        self.config = config
        self.inner = ShardedStore(mesh, config, axis)
        self.n = mesh.shape[axis]
        self.codec = codec or SpanCodec()
        self.ttls: Dict[int, float] = {}
        self.pins = PinBank()
        self._name_lc: Dict[int, int] = {}
        self._kernels: Dict = {}  # guarded-by: _kernels_lock
        # Same discipline as TpuSpanStore: _lock serializes writers and
        # host dicts; the RWLock guards the states swap (sharded ingest
        # donates the previous stacked states) against in-flight reads.
        # _kernels_lock is a dedicated LEAF for the mapped-kernel
        # compile cache: query threads build kernels while HOLDING the
        # read lock, so guarding the dict with _lock would invert the
        # encode(10) -> commit(40) order (a writer holding _lock and
        # waiting on the write lock deadlocks against a reader waiting
        # on _lock — graftlint lock-order forbids the shortcut).
        self._lock = threading.Lock()  # lock-order: 10 encode
        self._rw = RWLock()  # lock-order: 40 commit
        self._kernels_lock = threading.Lock()  # lock-order: 75 kernel-cache
        # Collective-launch serializer (the r14-noted deadlock): every
        # mapped read kernel is a shard_map program whose collectives
        # rendezvous ALL mesh devices inside one launch. The XLA CPU
        # backend runs concurrent launches on a shared device pool, so
        # two collective programs in flight can each seize a subset of
        # the per-device rendezvous slots and wait forever for the
        # rest (N API threads x psum catalogs under the SHARED read
        # lock — the read lock never excluded reader/reader). One
        # launch at a time makes the rendezvous trivially complete.
        # Dedicated LEAF below the read-lock hold (40 -> 45); never
        # held across anything that blocks on another launch.
        self._coll_lock = threading.Lock()  # lock-order: 45 collective-launch
        # Monotonic collective-launch count (one per _coll_lock hold):
        # the dispatcher-batching counter-proof reads deltas of this.
        self._coll_launches = 0  # guarded-by: _coll_lock
        # Host commit frontier: _step_seq advances inside every
        # donating write-lock hold; _read_epoch covers host-only
        # visibility changes (pin/TTL mutations) — together the query
        # engine's result-cache key (write_frontier()).
        self._step_seq = 0
        self._read_epoch = 0
        # Per-shard sketch-mirror twins (store/mirror.py), fed deltas
        # on the commit path, merged lazily into the fleet view the
        # engine sketch tier and the windowed-analytics mixin read.
        self._mirrors = [SketchMirror(config, dicts=self.codec.dicts)
                         for _ in range(self.n)]
        self._fleet_mirror = FleetMirror(config, self._mirrors,
                                         lambda: self._step_seq)
        # Durable write-ahead log (wal/sharded.ShardedWal) + pipelined
        # ingest (store/pipeline) — both optional, attached/started by
        # the deployment wiring (main/example.py --wal-dir/--pipeline).
        self.wal = None
        self._wal_marks = None  # guarded-by: _lock
        self._wal_applied = 0
        self._pipeline = None  # guarded-by: _lock
        self._registry = reg = registry or obs.default_registry()
        # Per-shard occupancy/lap gauges: hash-partition imbalance is
        # invisible in the summed counters() totals.
        self._occ_family = reg.register(obs.CallbackFamily(
            "zipkin_shard_occupancy",
            "Per-shard span ring occupancy (hash-partition skew view)",
            "shard", self._occupancy_by_shard))
        self._laps_family = reg.register(obs.CallbackFamily(
            "zipkin_shard_ring_laps",
            "Per-shard span ring laps (eviction-pressure skew view)",
            "shard", self._laps_by_shard))
        # Cross-shard query dispatcher: concurrent API reads coalesce
        # into one collective launch per micro-window instead of
        # queueing singly behind _coll_lock.
        self._dispatcher = CrossShardDispatcher(
            self, window_s=dispatch_window_s, registry=reg)

    @property
    def dicts(self):
        return self.codec.dicts

    @property
    def states(self):
        return self.inner.states

    @property
    def dispatcher(self):
        return self._dispatcher

    def collective_launches(self) -> int:
        """Monotonic count of collective query launches (each one a
        _coll_lock hold). The dispatcher-batching acceptance test
        proves N concurrent reads land in ≤2 launches by differencing
        this around the burst."""
        with self._coll_lock:
            return self._coll_launches

    def close(self) -> None:
        """Ordered shutdown: stop the dispatcher (queued reads finish;
        later ones execute inline), drain+stop the pipeline, force the
        WAL durable, and unregister the per-shard gauge families. The
        WAL object itself stays open (its owner closes it, after any
        final checkpoint truncation)."""
        d = self.__dict__.get("_dispatcher")
        if d is not None:
            d.close()
        self.stop_pipeline(raise_errors=False)
        if self.wal is not None:
            self.wal.sync()
        for fam in (self.__dict__.get("_occ_family"),
                    self.__dict__.get("_laps_family")):
            if fam is not None and self._registry.get(fam.name) is fam:
                self._registry.unregister(fam.name)

    # -- resident query engines (query/engine.py; the duck-typed twin
    # of ReadSpanStore's registry, so Collector.flush/close and
    # checkpoint.save can join the executor thread's lifecycle) ------

    def register_query_engine(self, engine) -> None:
        self.__dict__.setdefault("_query_engines", []).append(engine)

    def query_engines(self):
        return list(self.__dict__.get("_query_engines", ()))

    # -- writes ---------------------------------------------------------

    def _shard_of(self, trace_id: int) -> int:
        # Shared with the multi-host routing tier (parallel/multihost
        # partition_for_trace): one hash, no drift between the producer
        # partitioner and the store's placement.
        from zipkin_tpu.parallel.multihost import shard_of

        return shard_of(trace_id, self.n)

    def apply(self, spans) -> None:
        from zipkin_tpu.columnar.encode import to_signed64

        from zipkin_tpu.store.base import prune_ttls
        from zipkin_tpu.store.tpu import TpuSpanStore

        if not spans:
            return
        with self._lock:
            # Donating sharded ingest must not race an orphaned
            # checkpoint reader (see store.base.SuspectGuard).
            self.ensure_writable()
            for s in spans:
                self.ttls.setdefault(to_signed64(s.trace_id), 1.0)
            prune_ttls(self.ttls, TpuSpanStore.MAX_TTL_ENTRIES)
            if self.pins:
                # Pin-bank arrivals change read answers before the
                # commit bumps the frontier — invalidate cached reads.
                self._bump_read_epoch()
            self.pins.note_write(to_signed64, spans)
            self._apply_locked(list(spans))

    def _apply_locked(self, spans) -> None:  # called-under: _lock
        from zipkin_tpu.store.base import should_index
        from zipkin_tpu.store.tpu import _next_pow2, name_lc_ids

        groups = [[] for _ in range(self.n)]
        for s in spans:
            groups[self._shard_of(s.trace_id)].append(s)
        # One launch per shard must fit every ring (span AND annotation):
        # colliding slot scatters within a launch are implementation-
        # defined (see TpuSpanStore._chunk_columnar). Split-and-retry;
        # a single span fatter than an annotation ring gets truncated.
        c = self.config
        # A launch's unresolved children must also fit the pending ring
        # without self-collision (the same bound TpuSpanStore applies in
        # _max_chunk_spans): pslot = (pend_pos + rank) % pending_slots
        # would scatter colliding slots within one launch otherwise.
        cap = max(1, min(c.capacity // 2, c.pending_slots))

        def oversized(g):
            return (len(g) > cap
                    or sum(len(s.annotations) for s in g) > c.ann_capacity
                    or sum(len(s.binary_annotations) for s in g)
                    > c.bann_capacity)

        if any(oversized(g) for g in groups):
            if len(spans) > 1:
                mid = len(spans) // 2
                self._apply_locked(spans[:mid])
                self._apply_locked(spans[mid:])
                return
            import dataclasses

            s = spans[0]
            spans = [dataclasses.replace(
                s,
                annotations=tuple(s.annotations[:c.ann_capacity]),
                binary_annotations=tuple(
                    s.binary_annotations[:c.bann_capacity]
                ),
            )]
            groups = [[] for _ in range(self.n)]
            groups[self._shard_of(s.trace_id)] = spans
        batches = [self.codec.encode(g) for g in groups]
        parts = []
        for g, batch in zip(groups, batches):
            indexable = np.fromiter(
                (should_index(s) for s in g), bool, len(g)
            )
            lc = name_lc_ids(batch, self.dicts, self._name_lc)
            parts.append((batch, lc, indexable))
        unit = self._build_unit(parts)
        if self.wal is not None:
            # Journal BEFORE the donating commit (ack-after-append,
            # docs/DURABILITY.md) and under self._lock, so append
            # order == encode order == commit order — the property
            # the dictionary-delta replay chain depends on.
            unit = unit._replace(wal_seq=self._journal_unit(parts))
        if self._pipeline is not None:
            # Pipelined sharded ingest: stage 2 device_puts via
            # stage_unit, stage 3 runs _commit_unit — all shards'
            # commits ride one fused mesh launch per unit.
            self._pipeline.feed(unit)
            return
        unit = unit._replace(db=self.stage_unit(unit.db))
        self._commit_unit(unit)

    def _build_unit(self, parts):
        """Host stage-1 body shared by the serial writer, the ingest
        pipeline, and WAL replay: pad every shard's encoded part to
        fleet-wide pow2 buckets, stack host-side, and compute each
        shard's sketch-mirror delta from the PRE-PAD columns. ``parts``
        is one (SpanBatch, name_lc, indexable) triple per shard, in
        shard order. Journaled parts replayed through this same body
        re-cut bitwise-identical launches (wal/recovery)."""
        from zipkin_tpu.aggregate import windows as win_mod
        from zipkin_tpu.store.pipeline import IngestUnit
        from zipkin_tpu.store.tpu import _next_pow2

        batches = [b for b, _, _ in parts]
        pad_s = _next_pow2(max(b.n_spans for b in batches))
        pad_a = _next_pow2(max(b.n_annotations for b in batches))
        pad_b = _next_pow2(max(b.n_binary for b in batches))
        if self.config.window_enabled:
            ea, eb = win_mod.error_ids(self.dicts)
            err_of = lambda b: win_mod.span_error_flags(b, ea, eb)  # noqa: E731
        else:
            err_of = lambda b: None  # noqa: E731 — flag lowers out
        dbs = [
            dev.make_device_batch(
                b, lc, ix,
                pad_spans=pad_s, pad_anns=pad_a, pad_banns=pad_b,
                error_flag=err_of(b),
            )
            for b, lc, ix in parts
        ]
        sketch = tuple(
            m.delta_of([part])
            for m, part in zip(self._mirrors, parts)
        )
        return IngestUnit(
            stack_batches(dbs),
            sum(b.n_spans for b in batches),
            sum(b.n_annotations for b in batches),
            sum(b.n_binary for b in batches),
            self.n, False, sketch=sketch,
            # incoming from the HOST batches: reading it off the
            # stacked device pytree inside the write-lock hold was a
            # device sync stalling every reader behind the commit
            # (graftlint sync-under-lock, the r10 group-commit stall
            # class).
            incoming=max(b.n_spans for b in batches),
        )

    def stage_unit(self, db):
        """Stage-2 H2D: place the host-stacked batch pytree over the
        mesh. The pipeline's stage thread calls this hook (see
        IngestPipeline); the serial path runs it inline."""
        return jax.device_put(db, NamedSharding(self.mesh, P(self.axis)))

    def _commit_unit(self, unit) -> None:
        """Stage 3 — the ONE donating commit body behind the serial
        writer, the pipeline's commit thread, and WAL replay (the
        TpuSpanStore._commit_unit contract over the mesh). The sharded
        ingest launch (and its in-graph psum/pmax summary) runs under
        the WRITE lock, which excludes every reader — so ingest
        collectives can never overlap a query collective and need no
        _coll_lock. Mirror deltas fold inside the same hold, BEFORE
        the frontier bump, so a sketch-tier read at frontier F already
        includes commit F."""
        self.ensure_writable()
        with self._rw.write():
            self.inner.ingest(unit.db, incoming=unit.incoming)
            if unit.sketch is not None:
                for m, d in zip(self._mirrors, unit.sketch):
                    m.apply(d)
            self._step_seq += 1
            if unit.wal_seq is not None:
                self._wal_applied = unit.wal_seq

    # -- durable write-ahead log (zipkin_tpu.wal.sharded) ----------------

    def attach_wal(self, wal) -> None:
        """Journal every subsequent launch unit into ``wal`` (a
        ShardedWal: one segment log per shard + the group-commit epoch
        log) before its donating commit. Attach before live writes —
        units committed earlier are only covered by checkpoints. The
        store does not own the log's lifecycle."""
        from zipkin_tpu.wal.record import dict_sizes

        with self._lock:
            self.wal = wal
            self._wal_marks = dict_sizes(self.dicts)

    def _journal_unit(self, parts) -> int:  # called-under: _lock
        """Append one sharded launch unit — every shard's part plus
        the dictionary entries its encode step added — as one
        group-commit epoch; returns the epoch sequence. Runs on the
        encoding thread under self._lock."""
        from zipkin_tpu.wal.record import dict_sizes, dump_dict_deltas

        sizes, deltas = dump_dict_deltas(self.dicts, self._wal_marks)
        seq = self.wal.append_unit(parts, self._wal_marks, deltas)
        self._wal_marks = sizes
        return seq

    def wal_sync(self) -> None:
        """Force the attached WAL durable; no-op without one."""
        if self.wal is not None:
            self.wal.sync()

    # -- pipelined ingest lifecycle (store/pipeline) ---------------------

    PIPELINE_DEPTH = 8
    STAGE_BUFFERS = 2

    def start_pipeline(self, depth: Optional[int] = None,
                       stage_buffers: Optional[int] = None):
        """Switch the write path to the three-stage ingest pipeline:
        apply() becomes stage 1 (encode + partition + pad + host
        stack, outside the device critical section), a stage thread
        places units over the mesh (stage_unit), and a commit thread
        holds the write lock only for the fused all-shard donating
        swap — the PR 4 pipeline driving every shard's commit body
        concurrently. Same quiesce rules as TpuSpanStore."""
        from zipkin_tpu.store.pipeline import IngestPipeline

        with self._lock:
            if self._pipeline is not None:
                raise RuntimeError("ingest pipeline already running")
            self._pipeline = IngestPipeline(
                self, depth or self.PIPELINE_DEPTH,
                registry=self._registry,
                stage_buffers=stage_buffers or self.STAGE_BUFFERS)
            return self._pipeline

    def drain_pipeline(self) -> None:
        """Block until every accepted batch is committed on every
        shard (no-op when no pipeline runs); re-raises a parked
        pipeline error."""
        with self._lock:
            p = self._pipeline
        if p is not None:
            p.drain()

    def stop_pipeline(self, raise_errors: bool = True) -> None:
        """Drain, stop the pipeline threads, and return to the serial
        write path — quiesced UNDER the encode lock with the pipeline
        still published (two concurrent device writers would break the
        ring-scatter contract; see TpuSpanStore.stop_pipeline)."""
        with self._lock:
            p = self._pipeline
            if p is None:
                return
            p.stop()
            self._pipeline = None
        err = p.take_error()
        if raise_errors and err is not None:
            raise err

    @contextlib.contextmanager
    def pipelined(self, depth: Optional[int] = None):
        """Scoped pipelined ingest: drains and stops on exit."""
        pipe = self.start_pipeline(depth)
        try:
            yield pipe
        finally:
            self.stop_pipeline()

    # -- query-engine hooks (query/engine.py) ----------------------------

    def write_frontier(self) -> Tuple[int, int]:
        """Monotonic host-mirrored commit frontier — the result-cache
        key component (same contract as TpuSpanStore.write_frontier).
        No device traffic."""
        return (self._step_seq, self._read_epoch)

    def _bump_read_epoch(self) -> None:
        self._read_epoch += 1

    def ensure_sketch_mirror(self):
        """The fleet sketch mirror (FleetMirror over the per-shard
        twins), resynced from the device aggregates if a state swap
        left any shard cold (checkpoint restore) — one batched D2H of
        the stacked arrays (a plain sharded device_get, NOT a
        collective program, so no _coll_lock), after which incremental
        per-commit deltas keep every shard warm with zero device
        traffic."""
        fm = self._fleet_mirror
        if not fm.warm:
            with self._rw.read():
                st = self.states
                host = jax.device_get((
                    st.svc_hist, st.ann_svc_counts, st.name_presence,
                    st.ann_value_counts, st.bann_key_counts,
                    st.hll_traces, st.win_epoch, st.win_counts,
                    st.win_sums, st.win_mm,
                ))
                for i, m in enumerate(self._mirrors):
                    if not m.warm:
                        m.adopt(*(np.asarray(h)[i] for h in host))
        return fm

    DEFAULT_TTL_S = 1.0

    def set_time_to_live(self, trace_id: int, ttl_seconds: float) -> None:
        from zipkin_tpu.columnar.encode import to_signed64
        from zipkin_tpu.store.base import fill_pin

        tid = to_signed64(trace_id)
        with self._lock:
            self.ttls[tid] = ttl_seconds
            pin = ttl_seconds > self.DEFAULT_TTL_S
            if not pin:
                self.pins.unpin(tid)
            # Pin/unpin changes read answers without a commit — the
            # result cache must not serve the stale frontier.
            self._bump_read_epoch()
        if pin:
            fill_pin(self.pins, self._lock, tid, lambda: (
                self.get_spans_by_trace_ids([trace_id]) or [[]])[0])
            with self._lock:
                self._bump_read_epoch()

    def get_time_to_live(self, trace_id: int) -> float:
        from zipkin_tpu.columnar.encode import to_signed64

        with self._lock:
            return self.ttls[to_signed64(trace_id)]

    # -- mapped query kernels (cached per static shape) ------------------

    def _kernel(self, key, build):
        # The cache dict is shared by every API handler thread
        # (graftlint guarded-by caught the old unlocked check-then-
        # set). build() traces OUTSIDE the hold: tracing can take
        # seconds and needs no cache state — a duplicate build for a
        # racing key is cheap, a lock held across jax tracing is not.
        with self._kernels_lock:
            fn = self._kernels.get(key)
        if fn is None:
            fn = build()
            with self._kernels_lock:
                fn = self._kernels.setdefault(key, fn)
        return fn

    def _collect(self, kernel, *args):
        """Launch one mapped collective kernel and fetch its result,
        serialized behind the collective-launch leaf lock: concurrent
        shard_map programs deadlock the XLA CPU collective rendezvous
        (see _coll_lock). Callers hold the read lock; the launch AND
        the device_get complete inside the hold, so no second
        collective can be in flight."""
        with self._coll_lock:
            self._coll_launches += 1
            return jax.device_get(kernel(*args))

    def _unstack(self, state):
        return jax.tree.map(lambda x: x[0], state)

    def _q_by_service(self, limit: int):
        def build():
            def fn(state, svc, name_lc, end_ts):
                st = self._unstack(state)
                mat = dev.query_trace_ids_by_service(
                    st, svc, name_lc, end_ts, limit
                )
                return mat[None]

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh,
                in_specs=(P(self.axis), P(), P(), P()),
                out_specs=P(self.axis), check_vma=False,
            ))

        return self._kernel(("svc", limit), build)

    def _iq_by_service(self, limit: int, named: bool):
        """Index fast-path kernel: per-shard O(depth) bucket read +
        completeness flag (see dev.iquery_trace_ids_by_service). The
        named/unnamed branch is host state, so it keys the kernel
        cache, not a traced conditional."""
        c = self.config

        def build():
            def fn(state, svc, name_lc, end_ts):
                st = self._unstack(state)
                lay, _, _ = c.cand_layout
                if named:
                    fam = lay[dev.StoreConfig.CAND_NAME]
                    mat, complete, wm = dev._iq_verify_impl(
                        st.cand_idx, st.cand_pos, st.cand_wm,
                        st.row_gid, st.indexable, st.trace_id, st.ts_last,
                        c.capacity, fam, min(limit, fam[3]),
                        (svc.astype(jnp.int32), name_lc.astype(jnp.int32)),
                        end_ts, st.key_tab, st.key_wm, st.write_pos,
                        st.counters["key_claim_drops"],
                    )
                else:
                    fam = lay[dev.StoreConfig.CAND_SVC]
                    mat, complete, wm = dev._iq_service_impl(
                        st.cand_idx, st.cand_pos, st.cand_wm,
                        st.row_gid, st.indexable, st.trace_id,
                        st.ts_last, c.capacity, fam,
                        min(limit, fam[3]), svc, end_ts,
                    )
                return mat[None], complete[None], wm[None]

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh,
                in_specs=(P(self.axis), P(), P(), P()),
                out_specs=(P(self.axis),) * 3, check_vma=False,
            ))

        return self._kernel(("isvc", limit, named), build)

    def _iq_by_annotation(self, limit: int, mode: str):
        """mode: 'ann' (user annotation value), 'bkey' (binary key
        only), or 'bval' (binary key + 1-2 value forms)."""
        c = self.config

        def build():
            def fn(state, svc, ann, bkey, bval, bval2, end_ts):
                st = self._unstack(state)
                lay, _, _ = c.cand_layout
                svc32 = svc.astype(jnp.int32)
                if mode == "ann":
                    fam = lay[dev.StoreConfig.CAND_ANN]
                    mat, complete, wm = dev._iq_verify_impl(
                        st.cand_idx, st.cand_pos, st.cand_wm,
                        st.row_gid, st.indexable, st.trace_id, st.ts_last,
                        c.capacity, fam, min(limit, fam[3]),
                        (svc32, ann.astype(jnp.int32)), end_ts,
                        st.key_tab, st.key_wm, st.write_pos,
                        st.counters["key_claim_drops"],
                        st.ann_poison,
                    )
                elif mode == "bkey":
                    fam = lay[dev.StoreConfig.CAND_BANN]
                    mat, complete, wm = dev._iq_verify_impl(
                        st.cand_idx, st.cand_pos, st.cand_wm,
                        st.row_gid, st.indexable, st.trace_id, st.ts_last,
                        c.capacity, fam, min(limit, fam[3]),
                        (svc32, bkey.astype(jnp.int32), jnp.int32(-1)),
                        end_ts, st.key_tab, st.key_wm, st.write_pos,
                        st.counters["key_claim_drops"],
                        st.ann_poison,
                    )
                else:
                    fam = lay[dev.StoreConfig.CAND_BANN]
                    # 2-bucket window: clamp to 2*depth, not depth (see
                    # dev.iquery_trace_ids_by_annotation).
                    mat, complete, wm = dev._iq_verify2_impl(
                        st.cand_idx, st.cand_pos, st.cand_wm,
                        st.row_gid, st.indexable, st.trace_id, st.ts_last,
                        c.capacity, fam, min(limit, 2 * fam[3]),
                        (svc32, bkey.astype(jnp.int32),
                         bval.astype(jnp.int32)),
                        (svc32, bkey.astype(jnp.int32),
                         bval2.astype(jnp.int32)),
                        end_ts, st.key_tab, st.key_wm, st.write_pos,
                        st.counters["key_claim_drops"],
                        st.ann_poison,
                    )
                return mat[None], complete[None], wm[None]

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh,
                in_specs=(P(self.axis),) + (P(),) * 6,
                out_specs=(P(self.axis),) * 3, check_vma=False,
            ))

        return self._kernel(("iann", limit, mode), build)

    def _q_by_annotation(self, limit: int):
        def build():
            def fn(state, svc, ann, bkey, bval, bval2, end_ts):
                st = self._unstack(state)
                mat = dev.query_trace_ids_by_annotation(
                    st, svc, ann, bkey, bval, bval2, end_ts, limit
                )
                return mat[None]

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh,
                in_specs=(P(self.axis),) + (P(),) * 6,
                out_specs=P(self.axis), check_vma=False,
            ))

        return self._kernel(("ann", limit), build)

    def _q_durations(self):
        def build():
            def fn(state, qids):
                st = self._unstack(state)
                mat = dev.query_durations(st, qids)
                return jnp.stack([
                    jax.lax.pmax(mat[0], self.axis),
                    jax.lax.pmax(mat[1], self.axis),
                    jax.lax.pmin(mat[2], self.axis),
                    jax.lax.pmax(mat[3], self.axis),
                ])

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh, in_specs=(P(self.axis), P()),
                out_specs=P(), check_vma=False,
            ))

        return self._kernel(("durations",), build)

    def _iq_durations(self):
        """Trace-membership fast path (dev.iquery_durations) with the
        cross-shard min/max merge; ``exact`` requires every shard's
        queried buckets to pass the displaced-gid gate."""

        def build():
            def fn(state, qids):
                st = self._unstack(state)
                mat, exact = dev.iquery_durations(st, qids)
                merged = jnp.stack([
                    jax.lax.pmax(mat[0], self.axis),
                    jax.lax.pmax(mat[1], self.axis),
                    jax.lax.pmin(mat[2], self.axis),
                    jax.lax.pmax(mat[3], self.axis),
                ])
                all_exact = jax.lax.pmin(
                    exact.astype(jnp.int32), self.axis
                )
                return merged, all_exact

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh, in_specs=(P(self.axis), P()),
                out_specs=(P(), P()), check_vma=False,
            ))

        return self._kernel(("idurations",), build)

    def _durations_mat(self, qids):
        with self._rw.read():
            if self.config.use_index:
                mat, exact = self._collect(
                    self._iq_durations(), self.states, qids)
                if exact:
                    return mat
            return self._collect(self._q_durations(), self.states, qids)

    def _iq_gather(self, k_s: int, k_a: int, k_b: int):
        """Per-shard trace-membership gather (dev.iquery_gather_trace_rows)
        + a cross-shard AND of the exactness gates."""

        def build():
            def fn(state, qids):
                st = self._unstack(state)
                counts, s, a, b, exact = dev.iquery_gather_trace_rows(
                    st, qids, k_s, k_a, k_b
                )
                all_exact = jax.lax.pmin(
                    exact.astype(jnp.int32), self.axis
                )
                return counts[None], s[None], a[None], b[None], all_exact

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh, in_specs=(P(self.axis), P()),
                out_specs=(P(self.axis),) * 4 + (P(),), check_vma=False,
            ))

        return self._kernel(("igather", k_s, k_a, k_b), build)

    def _gather_via_index(self, qids):
        """Sharded analogue of TpuSpanStore._gather_via_index: returns
        the per-shard gather payload, or None when any shard's queried
        bucket fails its gate (caller scans)."""
        from zipkin_tpu.store.base import index_gather_with_escalation

        def fetch(k_s, k_a, k_b):
            counts, s_m, a_m, b_m, exact = self._collect(
                self._iq_gather(k_s, k_a, k_b), self.states, qids)
            return (bool(exact), int(counts[:, 0].max()),
                    int(counts[:, 1].max()), int(counts[:, 2].max()),
                    (counts, s_m, a_m, b_m))

        return index_gather_with_escalation(self.config, len(qids), fetch)

    def _q_gather(self, k_s: int, k_a: int, k_b: int):
        def build():
            def fn(state, qids):
                st = self._unstack(state)
                counts, s, a, b = dev.gather_trace_rows(
                    st, qids, k_s, k_a, k_b
                )
                return counts[None], s[None], a[None], b[None]

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh, in_specs=(P(self.axis), P()),
                out_specs=P(self.axis), check_vma=False,
            ))

        return self._kernel(("gather", k_s, k_a, k_b), build)

    def _cat_kernel(self, key: str):
        """One small collective per catalog key — all-reducing the whole
        catalog to read one scalar/row would waste device time on hot
        paths like the sampler's stored_span_count tick."""

        def build():
            def fn(state):
                st = self._unstack(state)
                if key == "hll_traces":
                    return jax.lax.pmax(st.hll_traces, self.axis)
                if key == "spans_seen":
                    return jax.lax.psum(st.counters["spans_seen"],
                                        self.axis)
                return jax.lax.psum(getattr(st, key), self.axis)

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh, in_specs=(P(self.axis),),
                out_specs=P(), check_vma=False,
            ))

        return self._kernel(("cat", key), build)

    # -- id lookups ------------------------------------------------------

    def _svc_id(self, service_name: str):
        return self.dicts.services.get(service_name.lower())

    @staticmethod
    def _shard_candidates(mats: np.ndarray, k: int):
        """Flatten per-shard candidate matrices [n, 3, kk]; truncated if
        ANY shard filled its window. The window bound is the kernel's
        ACTUAL slot count (kk = mats.shape[-1]), which may be clamped
        below the requested k by bucket geometry — comparing against
        the requested k would let a full clamped window read as
        untruncated."""
        kk = min(k, mats.shape[-1])
        cands, truncated = [], False
        for sh in range(mats.shape[0]):
            n_valid = 0
            for t, ts, v in zip(*mats[sh]):
                if v:
                    cands.append((int(t), int(ts)))
                    n_valid += 1
            truncated |= n_valid >= kk
        return cands, truncated

    def get_trace_ids_by_name(self, service_name, span_name, end_ts,
                              limit):
        """Top-k trace ids by (service[, span name]) via the
        cross-shard dispatcher: concurrent index reads ride ONE
        multi-probe mesh launch (get_trace_ids_multi) instead of
        queueing singly behind _coll_lock."""
        return self._dispatcher.ids(
            ("name", service_name, span_name, end_ts, limit))

    def _get_trace_ids_by_name_direct(self, service_name, span_name,
                                      end_ts, limit):
        from zipkin_tpu.store.base import topk_ids_with_escalation

        svc = self._svc_id(service_name)
        if svc is None or limit <= 0:
            return []
        if span_name is not None:
            name_lc = self.dicts.span_names.get(span_name.lower())
            if name_lc is None:
                return []
        else:
            name_lc = -1

        def fetch(k):
            with self._rw.read():
                mats = self._collect(
                    self._q_by_service(k), self.states, jnp.int32(svc),
                    jnp.int32(name_lc), jnp.int64(end_ts),
                )
            return self._shard_candidates(mats, k)

        def index_fetch(k):
            with self._rw.read():
                mats, complete, wm = self._collect(
                    self._iq_by_service(k, name_lc >= 0), self.states,
                    jnp.int32(svc), jnp.int32(name_lc),
                    jnp.int64(end_ts),
                )
            cands, truncated = self._shard_candidates(mats, k)
            # window > len(cands) ⇔ no shard's window truncated: only
            # then may the underfull-equals-complete claim fire.
            window = len(cands) if truncated else len(cands) + 1
            return cands, bool(np.all(complete)), int(np.max(wm)), window

        from zipkin_tpu.store.base import (index_first_topk,
                                           service_scan_only)

        if self.config.use_index and not service_scan_only(
                svc, self.config):
            return index_first_topk(
                limit, self.config.ann_capacity, index_fetch, fetch
            )
        return topk_ids_with_escalation(
            limit, self.config.ann_capacity, fetch
        )

    def get_trace_ids_by_annotation(self, service_name, annotation,
                                    value, end_ts, limit):
        """Top-k trace ids by annotation via the cross-shard
        dispatcher (see get_trace_ids_by_name)."""
        return self._dispatcher.ids(
            ("annotation", service_name, annotation, value, end_ts,
             limit))

    def _get_trace_ids_by_annotation_direct(self, service_name,
                                            annotation, value, end_ts,
                                            limit):
        from zipkin_tpu.models.constants import CORE_ANNOTATIONS
        from zipkin_tpu.store.base import resolve_annotation_query

        if annotation in CORE_ANNOTATIONS or limit <= 0:
            return []
        svc = self._svc_id(service_name)
        if svc is None:
            return []
        from zipkin_tpu.store.base import topk_ids_with_escalation

        resolved = resolve_annotation_query(self.dicts, annotation, value)
        if resolved is None:
            return []
        ann_value, bann_key, bann_value, bann_value2 = resolved

        def fetch(k):
            with self._rw.read():
                mats = self._collect(
                    self._q_by_annotation(k), self.states,
                    jnp.int32(svc), jnp.int32(ann_value),
                    jnp.int32(bann_key), jnp.int32(bann_value),
                    jnp.int32(bann_value2), jnp.int64(end_ts),
                )
            return self._shard_candidates(mats, k)

        if ann_value >= 0:
            mode = "ann"
        elif bann_value < 0 and bann_value2 < 0:
            mode = "bkey"
        else:
            mode = "bval"
        bv1 = bann_value if bann_value >= 0 else bann_value2
        bv2 = bann_value2 if bann_value2 >= 0 else bv1
        # Mixed user-annotation + binary-key names OR across families:
        # only the scan sees both sides.
        mixed = ann_value >= 0 and bann_key >= 0

        def index_fetch(k):
            with self._rw.read():
                mats, complete, wm = self._collect(
                    self._iq_by_annotation(k, mode), self.states,
                    jnp.int32(svc), jnp.int32(ann_value),
                    jnp.int32(bann_key), jnp.int32(bv1),
                    jnp.int32(bv2), jnp.int64(end_ts),
                )
            cands, truncated = self._shard_candidates(mats, k)
            window = len(cands) if truncated else len(cands) + 1
            return cands, bool(np.all(complete)), int(np.max(wm)), window

        from zipkin_tpu.store.base import (index_first_topk,
                                           service_scan_only)

        c = self.config
        if c.use_index and not mixed and not service_scan_only(svc, c):
            return index_first_topk(
                limit, c.ann_capacity + c.bann_capacity, index_fetch,
                fetch,
            )
        return topk_ids_with_escalation(
            limit, c.ann_capacity + c.bann_capacity, fetch
        )

    def _iq_multi(self, n: int, k: int):
        """Batched multi-probe index kernel over the mesh: every probe
        reads its bucket on EVERY shard in one launch (dev._iq_multi_impl
        under shard_map); the host merges per-shard candidates."""
        c = self.config

        def build():
            k_max = max(fam[3] for fam in c.cand_layout[0])

            def fn(state, b_base, s_base, n_b, depth, key1, key2, key3,
                   three, is_svc, end_ts, poison_on):
                st = self._unstack(state)
                mat, complete, wm = dev._iq_multi_impl(
                    st.cand_idx, st.cand_pos, st.cand_wm, st.row_gid,
                    st.indexable, st.trace_id, st.ts_last,
                    c.capacity, k, k_max,
                    b_base, s_base, n_b, depth, key1, key2, key3,
                    three, is_svc, end_ts, poison_on,
                    st.ann_poison, st.write_pos, st.key_tab, st.key_wm,
                    st.counters["key_claim_drops"],
                )
                return mat[None], complete[None], wm[None]

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh,
                in_specs=(P(self.axis),) + (P(),) * 11,
                out_specs=(P(self.axis),) * 3, check_vma=False,
            ))

        return self._kernel(("imulti", n, k), build)

    def get_trace_ids_multi(self, queries):
        """Batched index read over the mesh: all queries' probes ride
        one launch; distrusted buckets fall back to the singular sharded
        paths. Same trust policy as TpuSpanStore.get_trace_ids_multi
        (shared resolve/gate helpers), with per-shard saturation folded
        into each probe's flag."""
        from zipkin_tpu.store.base import ReadSpanStore
        from zipkin_tpu.store.tpu import (
            build_probe_arrays,
            gate_multi_probes,
            resolve_multi_probes,
        )

        c = self.config
        if not c.use_index or not queries:
            return ReadSpanStore.get_trace_ids_multi(self, queries)
        results, probes, limits, fallback = resolve_multi_probes(
            c, self.dicts, queries
        )
        if probes:
            # Unlike the single-device path, the mesh kernel takes the
            # clamped k directly (k_eff); the raw request k is unused.
            arrs, _, k_eff = build_probe_arrays(c, probes, limits)
            order = ("b_base", "s_base", "n_b", "depth", "key1", "key2",
                     "key3", "three", "is_svc", "end_ts", "poison_on")
            with self._rw.read():
                mats, completes, wms = self._collect(
                    self._iq_multi(len(arrs["key1"]), k_eff),
                    self.states,
                    *(jnp.asarray(arrs[name]) for name in order),
                )
            per_probe = []
            for pi, p in enumerate(probes):
                window_pi = min(k_eff, p[1][3])
                cands = []
                saturated = False
                for sh in range(mats.shape[0]):
                    mat = mats[sh, pi]
                    shard_cands = [
                        (int(t), int(ts))
                        for t, ts, v in zip(mat[0], mat[1], mat[2]) if v
                    ]
                    saturated |= len(shard_cands) >= window_pi
                    cands.extend(shard_cands)
                per_probe.append((
                    cands, bool(np.all(completes[:, pi])),
                    int(np.max(wms[:, pi])), saturated,
                ))
            gated = gate_multi_probes(probes, limits, per_probe)
            for qi, ids in gated.items():
                if ids is None:
                    fallback.append(qi)
                else:
                    results[qi] = ids
        for qi in fallback:
            q = queries[qi]
            if q[0] == "name":
                results[qi] = self.get_trace_ids_by_name(*q[1:])
            else:
                results[qi] = self.get_trace_ids_by_annotation(*q[1:])
        return [r if r is not None else [] for r in results]

    # -- trace reads -----------------------------------------------------

    def _sorted_qids(self, trace_ids) -> np.ndarray:
        from zipkin_tpu.columnar.encode import to_signed64

        # Unique for the same reason as TpuSpanStore._sorted_qids.
        return np.unique(
            np.asarray([to_signed64(t) for t in trace_ids], np.int64)
        )

    def traces_exist(self, trace_ids):
        from zipkin_tpu.columnar.encode import to_signed64

        if not trace_ids:
            return set()
        canon = {to_signed64(t): t for t in trace_ids}
        qids = self._sorted_qids(trace_ids)
        from zipkin_tpu.store.base import exist_from_duration_mat

        mat = self._durations_mat(qids)
        return exist_from_duration_mat(canon, qids, mat[0], self.pins,
                                       self._lock)

    def get_traces_duration(self, trace_ids):
        from zipkin_tpu.columnar.encode import to_signed64
        from zipkin_tpu.store.base import durations_from_mat

        if not trace_ids:
            return []
        canon = {to_signed64(t): t for t in trace_ids}
        qids = self._sorted_qids(trace_ids)
        mat = self._durations_mat(qids)
        return durations_from_mat(trace_ids, canon, qids, mat, self.pins,
                                  self._lock)

    def get_spans_by_trace_ids(self, trace_ids):
        from zipkin_tpu.columnar.encode import to_signed64
        from zipkin_tpu.store.tpu import decode_gathered

        if not trace_ids:
            return []
        from zipkin_tpu.store.base import (
            apply_pin_merges,
            gather_with_escalation,
        )

        qids = self._sorted_qids(trace_ids)
        with self._rw.read():
            payload = None
            if self.config.use_index:
                payload = self._gather_via_index(qids)
            if payload is None:
                def fetch(k_s, k_a, k_b):
                    counts, s_m, a_m, b_m = self._collect(
                        self._q_gather(k_s, k_a, k_b), self.states,
                        qids)
                    return (int(counts[:, 0].max()),
                            int(counts[:, 1].max()),
                            int(counts[:, 2].max()),
                            (counts, s_m, a_m, b_m))

                payload = gather_with_escalation(self.config, fetch)
            counts, s_m, a_m, b_m = payload
        spans = []
        for sh in range(self.n):
            spans.extend(decode_gathered(
                self.codec, int(counts[sh, 0]), int(counts[sh, 1]),
                int(counts[sh, 2]), s_m[sh], a_m[sh], b_m[sh],
            ))
        by_tid: Dict[int, list] = {}
        for span in spans:
            by_tid.setdefault(span.trace_id, []).append(span)
        with self._lock:
            apply_pin_merges(self.pins, by_tid, trace_ids, to_signed64)
        return [
            by_tid[to_signed64(tid)]
            for tid in trace_ids
            if to_signed64(tid) in by_tid
        ]

    def get_spans_by_trace_id(self, trace_id: int):
        found = self.get_spans_by_trace_ids([trace_id])
        return found[0] if found else []

    # -- name catalogs / analytics --------------------------------------

    # Catalog keys the fused bundle kernel serves — everything the
    # dispatcher may merge into ONE launch. Keys outside this set
    # (none today) would fall back to their singular kernels.
    CAT_BUNDLE_KEYS = frozenset((
        "svc_hist", "ann_svc_counts", "name_presence",
        "ann_value_counts", "bann_key_counts", "spans_seen",
        "hll_traces",
    ))

    def _cat_bundle_kernel(self):
        """ONE collective program all-reducing every catalog array the
        dispatcher can serve: ≥2 concurrent catalog reads sharing a
        micro-window cost one launch total instead of one launch each
        behind _coll_lock."""

        def build():
            def fn(state):
                st = self._unstack(state)
                out = {k: jax.lax.psum(getattr(st, k), self.axis)
                       for k in ("svc_hist", "ann_svc_counts",
                                 "name_presence", "ann_value_counts",
                                 "bann_key_counts")}
                out["spans_seen"] = jax.lax.psum(
                    st.counters["spans_seen"], self.axis)
                out["hll_traces"] = jax.lax.pmax(st.hll_traces,
                                                 self.axis)
                return out

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh, in_specs=(P(self.axis),),
                out_specs=P(), check_vma=False,
            ))

        return self._kernel(("cat_bundle",), build)

    def _fetch_cat_bundle(self):
        """Every dispatcher-servable catalog entry: one launch, one
        D2H (the dispatcher's fused path)."""
        with self._rw.read():
            with self._coll_lock:
                self._coll_launches += 1
                return jax.device_get(
                    self._cat_bundle_kernel()(self.states))

    def _cat_direct(self, key):
        """Read-locked fetch of ONE collective catalog entry — the
        cheap singular kernel, for a read with nothing to share a
        launch with."""
        with self._rw.read():
            with self._coll_lock:
                self._coll_launches += 1
                return jax.device_get(self._cat_kernel(key)(self.states))

    def _cat(self, key, row=None):
        """One catalog entry (optionally one row of it), via the
        cross-shard dispatcher: concurrent catalog reads coalesce into
        one fused bundle launch (parallel/dispatch)."""
        return self._dispatcher.cat(key, row)

    def get_all_service_names(self):
        present = self._cat("ann_svc_counts") > 0
        d = self.dicts.services
        out = {
            d.decode(i) for i in np.flatnonzero(present)
            if i < len(d) and d.decode(i)
        }
        # Dictionary-overflow services can't mark the presence array —
        # list the ones any shard's rings still hold as hosts (see
        # TpuSpanStore.get_all_service_names; OR across shards rides
        # a psum of the per-shard presence).
        S = self.config.max_services
        n_over = len(d) - S
        if n_over > 0:
            pad = 1 << max(0, (n_over - 1)).bit_length()

            def build():
                def fn(state):
                    st = self._unstack(state)
                    pres = dev.overflow_service_presence(st, pad)
                    return jax.lax.psum(
                        pres.astype(jnp.int32), self.axis) > 0

                return jax.jit(compat_shard_map(
                    fn, mesh=self.mesh, in_specs=(P(self.axis),),
                    out_specs=P(), check_vma=False,
                ))

            with self._rw.read():
                pres = self._collect(
                    self._kernel(("overflow_presence", pad), build),
                    self.states)
            out.update(
                name for i in np.flatnonzero(pres[:n_over])
                if (name := d.decode(S + int(i)))
            )
        return out

    def _scan_cat_kernel(self):
        """Overflow-service catalog reads: per-shard ring scans
        (dev.svc_scan_catalog) psum-ed across the mesh — the
        [max_services]-sized catalog arrays cannot represent services
        past the dictionary cap, and a clamped row read would serve
        service max_services-1's data under the wrong name."""
        def build():
            def fn(state, svc):
                st = self._unstack(state)
                rows = dev.svc_scan_catalog(st, svc)
                return tuple(jax.lax.psum(r, self.axis) for r in rows)

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh, in_specs=(P(self.axis), P()),
                out_specs=(P(),) * 4, check_vma=False,
            ))

        return self._kernel(("scan_catalog",), build)

    def _svc_catalog_scan(self, svc: int):
        # One-entry memo keyed on (svc, write position): the kernel
        # returns all four catalog rows per launch — see
        # TpuSpanStore._svc_catalog_scan.
        key = (svc, self.inner._wp_upper)
        cached = getattr(self, "_svc_scan_memo", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        with self._rw.read():
            rows = self._collect(self._scan_cat_kernel(), self.states,
                                 jnp.int32(svc))
        self._svc_scan_memo = (key, rows)
        return rows

    def get_span_names(self, service: str):
        svc = self._svc_id(service)
        if svc is None:
            return set()
        if service_scan_only(svc, self.config):
            row = self._svc_catalog_scan(svc)[0] > 0
        else:
            row = self._cat("name_presence", svc) > 0
        d = self.dicts.span_names
        return {
            d.decode(i) for i in np.flatnonzero(row)
            if i < len(d) and d.decode(i)
        }

    def _summary_kernel(self):
        def build():
            def fn(state):
                return _summarize(self._unstack(state), self.axis)

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh, in_specs=(P(self.axis),),
                out_specs=P(), check_vma=False,
            ))

        return self._kernel(("summary",), build)

    def _deps_range_kernel(self):
        def build():
            def fn(state, start_ts, end_ts):
                st = self._unstack(state)
                bank = dev.dep_moments_in_range(st, start_ts, end_ts)
                banks = jax.lax.all_gather(bank, self.axis)
                # ts range rides the same launch — running the full
                # summary kernel just to clip two scalars would
                # all-reduce every catalog array per windowed query.
                ts_min = jnp.maximum(jax.lax.pmin(st.ts_min, self.axis),
                                     start_ts)
                ts_max = jnp.minimum(jax.lax.pmax(st.ts_max, self.axis),
                                     end_ts)
                return M.reduce_moments(banks, axis=0), ts_min, ts_max

            return jax.jit(compat_shard_map(
                fn, mesh=self.mesh, in_specs=(P(self.axis), P(), P()),
                out_specs=(P(), P(), P()), check_vma=False,
            ))

        return self._kernel(("deps_range",), build)

    def get_dependencies(self, start_ts=None, end_ts=None):
        from zipkin_tpu.aggregate.job import dependencies_from_bank

        # Sweep first — but only when something was written since the
        # last sweep, so read-only dependency polling stays a pure read
        # (same contract as TpuSpanStore.get_dependencies).
        if self.inner._batches_since_sweep:
            with self._lock:
                if self.inner._batches_since_sweep:
                    # The sweep step donates state buffers — same
                    # suspect gate as every other donating path.
                    self.ensure_writable()
                    with self._rw.write():
                        self.inner.sweep()
        with self._rw.read():
            if start_ts is None and end_ts is None:
                with self._coll_lock:
                    self._coll_launches += 1
                    summary = self._summary_kernel()(self.states)
                    bank, ts_min, ts_max = jax.device_get(
                        (summary["dep_moments"], summary["ts_min"],
                         summary["ts_max"])
                    )
            else:
                s = dev.I64_MIN if start_ts is None else int(start_ts)
                e = dev.I64_MAX if end_ts is None else int(end_ts)
                bank, ts_min, ts_max = self._collect(
                    self._deps_range_kernel(), self.states,
                    jnp.int64(s), jnp.int64(e)
                )
        return dependencies_from_bank(
            bank, self.dicts.services, self.config.max_services,
            float(ts_min), float(ts_max),
        )

    def service_duration_quantiles(self, service: str, qs):
        from zipkin_tpu.ops import quantile as Q

        svc = self._svc_id(service)
        if svc is None:
            return None
        c = self.config
        gamma = (1.0 + c.quantile_alpha) / (1.0 - c.quantile_alpha)
        if service_scan_only(svc, c):
            counts = self._svc_catalog_scan(svc)[1]
        else:
            counts = self._cat("svc_hist", svc)
        return Q.quantiles_host(counts, gamma, 1.0, qs)

    def top_annotations(self, service: str, k: int = 10):
        svc = self._svc_id(service)
        if svc is None:
            return []
        if service_scan_only(svc, self.config):
            row = self._svc_catalog_scan(svc)[2]
        else:
            row = self._cat("ann_value_counts", svc)
        order = np.argsort(-row)[:k]
        d = self.dicts.annotations
        return [
            (d.decode(int(i)), int(row[i])) for i in order
            if row[i] > 0 and i < len(d)
        ]

    def top_binary_keys(self, service: str, k: int = 10):
        svc = self._svc_id(service)
        if svc is None:
            return []
        if service_scan_only(svc, self.config):
            row = self._svc_catalog_scan(svc)[3]
        else:
            row = self._cat("bann_key_counts", svc)
        order = np.argsort(-row)[:k]
        d = self.dicts.binary_keys
        return [
            (d.decode(int(i)), int(row[i])) for i in order
            if row[i] > 0 and i < len(d)
        ]

    def estimated_unique_traces(self) -> float:
        from zipkin_tpu.ops import hll

        regs = self._cat("hll_traces")
        return float(hll.estimate(hll.HyperLogLog(regs)))

    def stored_span_count(self) -> float:
        """psum-ed spans_seen across every shard — the sharded flow
        source for the adaptive controller (the ZK group-sum role,
        AdaptiveSampler.scala:204-237)."""
        return float(self._cat("spans_seen"))

    def _counter_blocks(self):
        """(totals dict, per-shard [n, F] block matrix), memoized on
        the host-side write clocks — same fetched-once-per-ingest-step
        contract as TpuSpanStore.counter_block, so scrapes between
        writes cost no device traffic. The per-shard matrix is a plain
        vmap over the stacked states (no collective program, so no
        _coll_lock)."""
        key = (self.inner._wp_upper, self.inner._batches_since_sweep,
               self.inner._archived_lower)
        memo = getattr(self, "_cblock_memo", None)
        if memo is not None and memo[0] == key:
            return dict(memo[1]), memo[2]
        with self._rw.read():
            blocks = np.asarray(jax.device_get(jax.vmap(
                dev.counter_block.__wrapped__
            )(self.inner.states)))
        out: Dict[str, float] = {}
        for i, name in enumerate(dev.COUNTER_BLOCK_FIELDS):
            col = blocks[:, i]
            if name == "ts_min":
                out[name] = float(col.min())
            elif name == "ts_max":
                out[name] = float(col.max())
            else:
                out[name] = float(col.sum())
        out["shards"] = float(self.n)
        self._cblock_memo = (key, dict(out), blocks)
        return dict(out), blocks

    def counters(self) -> Dict[str, float]:
        """Store-stage counters for /metrics: per-shard device counter
        blocks summed across the mesh (occupancy/laps are per-shard
        quantities, so sums read as mesh totals; ts_min/ts_max reduce
        by min/max). Per-shard SKEW — which the sums erase — is
        surfaced separately by shard_counters() and the
        zipkin_shard_occupancy{shard=}/zipkin_shard_ring_laps{shard=}
        gauge families."""
        totals, _ = self._counter_blocks()
        return totals

    def shard_counters(self):
        """One counter dict PER SHARD, in shard order — the
        hash-partition imbalance view counters()'s mesh totals sum
        away."""
        _, blocks = self._counter_blocks()
        return [
            {name: float(blocks[sh, i])
             for i, name in enumerate(dev.COUNTER_BLOCK_FIELDS)}
            for sh in range(blocks.shape[0])
        ]

    def _shard_column(self, field: str) -> Dict[str, float]:
        i = dev.COUNTER_BLOCK_FIELDS.index(field)
        _, blocks = self._counter_blocks()
        return {str(sh): float(blocks[sh, i])
                for sh in range(blocks.shape[0])}

    def _occupancy_by_shard(self) -> Dict[str, float]:
        return self._shard_column("ring_occupancy")

    def _laps_by_shard(self) -> Dict[str, float]:
        return self._shard_column("ring_laps")
