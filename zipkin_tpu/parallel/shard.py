"""shard_map-ed ingest: N store shards, one collective summary.

Mesh layout: one axis ``shard`` = data-parallel ingest shards (the
analogue of the reference's horizontally scaled collector fleet,
ScribeSpanReceiver.scala:42-56). Store state is stacked with a leading
[n_shards] dim sharded over the axis; batches likewise. The fused
per-shard ingest is exactly store/device.ingest_step; the summary that
the sampler/query layer needs crosses shards via ICI collectives only.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zipkin_tpu.ops import moments as M
from zipkin_tpu.store import device as dev


def _stack_states(config: dev.StoreConfig, n: int):
    one = dev.init_state(config)
    return jax.tree.map(lambda x: jnp.broadcast_to(x, (n,) + x.shape), one)


def _summarize(state: dev.StoreState, axis: str) -> Dict[str, jnp.ndarray]:
    """Cross-shard global aggregates, computed inside shard_map."""
    # Counters and additive sketches ride a psum.
    spans_seen = jax.lax.psum(state.counters["spans_seen"], axis)
    svc_counts = jax.lax.psum(state.svc_span_counts, axis)
    svc_hist = jax.lax.psum(state.svc_hist, axis)
    cms_counts = jax.lax.psum(state.cms_trace_spans, axis)
    ann_svc_counts = jax.lax.psum(state.ann_svc_counts, axis)
    # HLL merge is an elementwise max.
    hll_regs = jax.lax.pmax(state.hll_traces, axis)
    # Moments combine is associative+commutative but not "+": gather the
    # per-shard banks (archive + live-ring join, see dev.total_dep_moments)
    # and tree-combine.
    banks = jax.lax.all_gather(dev.total_dep_moments(state), axis)  # [n, S*S, 5]
    dep_moments = M.reduce_moments(banks, axis=0)
    return {
        "spans_seen": spans_seen,
        "svc_span_counts": svc_counts,
        "svc_hist": svc_hist,
        "cms_trace_spans": cms_counts,
        "ann_svc_counts": ann_svc_counts,
        "hll_traces": hll_regs,
        "dep_moments": dep_moments,
    }


def make_sharded_archive(mesh: Mesh, axis: str = "shard"):
    """Per-shard dependency-link archive step (dev.dep_archive_auto) so
    links survive ring eviction in the sharded deployment exactly like
    the single-store path; the watermark policy runs in-graph."""

    def fn(state, incoming):
        state = jax.tree.map(lambda x: x[0], state)
        new_state = dev.dep_archive_auto(state, incoming)
        return jax.tree.map(lambda x: x[None], new_state)

    mapped = jax.shard_map(
        fn, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(mapped)


def make_sharded_ingest(mesh: Mesh, axis: str = "shard"):
    """Build the jitted sharded step:

    (stacked_states [n,...], stacked_batches [n,...]) →
        (stacked_states, global summary replicated)
    """

    def shard_fn(state, batch):
        # shard_map hands us blocks with the leading shard dim of size 1.
        state = jax.tree.map(lambda x: x[0], state)
        batch = jax.tree.map(lambda x: x[0], batch)
        new_state = dev.ingest_step.__wrapped__(state, batch)
        summary = _summarize(new_state, axis)
        new_state = jax.tree.map(lambda x: x[None], new_state)
        return new_state, summary

    mapped = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0,))


class ShardedStore:
    """Host handle for an n-shard device store.

    Round-robins host batches across shards (callers feeding from
    multiple ingest processes would instead target their local shard).
    """

    def __init__(self, mesh: Mesh, config: dev.StoreConfig, axis: str = "shard"):
        self.mesh = mesh
        self.axis = axis
        self.config = config
        self.n = mesh.shape[axis]
        sharding = NamedSharding(mesh, P(axis))
        self.states = jax.device_put(_stack_states(config, self.n), sharding)
        self.step = make_sharded_ingest(mesh, axis)
        self.archive_step = make_sharded_archive(mesh, axis)
        self.last_summary = None
        # Host upper bound of any shard's write_pos / lower bound of any
        # shard's archive watermark — gates the archive trigger without
        # device syncs (mirrors TpuSpanStore._maybe_archive).
        self._wp_upper = 0
        self._archived_lower = 0

    def ingest(self, device_batches) -> Dict[str, np.ndarray]:
        """device_batches: pytree stacked [n_shards, ...]."""
        incoming = int(np.max(np.asarray(device_batches.n_spans)))
        self._maybe_archive(incoming)
        self.states, summary = self.step(self.states, device_batches)
        self._wp_upper += incoming
        self.last_summary = summary
        return summary

    def _maybe_archive(self, incoming: int) -> None:
        cap = self.config.capacity
        if self._wp_upper + incoming - self._archived_lower <= cap:
            return
        self.states = self.archive_step(self.states, jnp.int64(incoming))
        self._archived_lower = min(
            self._wp_upper,
            max(self._wp_upper + incoming - cap, self._wp_upper - cap // 2),
        )


def global_summary(states, mesh: Mesh, axis: str = "shard"):
    """One-off collective summary over stacked states (no ingest)."""

    def fn(state):
        state = jax.tree.map(lambda x: x[0], state)
        return _summarize(state, axis)

    mapped = jax.shard_map(
        fn, mesh=mesh, in_specs=(P(axis),), out_specs=P(), check_vma=False
    )
    return jax.jit(mapped)(states)


def stack_batches(batches) -> Tuple:
    """Host: list of n DeviceBatch → stacked pytree [n, ...]."""
    return jax.tree.map(lambda *xs: np.stack(xs), *batches)
