"""Vectorized equi-join primitives (sort-merge, no pointers).

The device-side replacement for the reference's shuffle joins — the
Scalding ``parentSpans join childSpans on (parentId, traceId)``
(ZipkinAggregateJob.scala:26-33) and the SQL self-joins
(AnormAggregator.scala:32-90) — re-expressed as ONE single-key sort
over the union of build and probe rows plus a forward-fill, which XLA
lowers to its O(n log n) sort: no hash tables, no dynamic shapes.

The sort key is a 64-bit hash of the composite key (equality is
re-verified on the original columns after the sort, so a hash collision
can only cause a one-in-2^63 missed match, never a wrong one). A
multi-operand lexsort would be semantically cleaner, but XLA's TPU sort
compile time explodes with i64 operand count at multi-million-row
shapes (measured: 3×i64 lexsort at 8M rows compiles for >10 minutes vs
~50s for one key) — the hash key keeps the whole archive pass a
~50s-once compile.

``lookup``: for each probe key, find the payload of the (single) build
row with an equal composite key. Keys are tuples of integer columns
(e.g. (trace_id, span_id) as int64 columns in x64 mode).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from zipkin_tpu.ops.hashing import mix_keys64


def _forward_fill_last_true_index(flag):
    """For each i: the largest j <= i with flag[j], else -1.

    lax.cummax in int32 — the generic associative_scan compiles for
    >9 minutes at 8M rows on TPU (measured), cummax in ~3s."""
    n = flag.shape[0]
    idx = jnp.where(flag, jnp.arange(n, dtype=jnp.int32), jnp.int32(-1))
    return jax.lax.cummax(idx)


def _planes(x):
    """i64[n] -> i32[n, 2] bit-planes (free bitcast). The join's
    post-sort re-verification gathers key columns twice per column;
    in plane form both are contiguous 8-byte i32 ROW gathers instead
    of i64 gathers — the serialized cost class on this device family
    (NOTES_r05 §2) — while equality on both planes is bitwise the
    i64 equality."""
    return jax.lax.bitcast_convert_type(jnp.asarray(x, jnp.int64),
                                        jnp.int32)


def lookup(
    build_keys: Sequence[jnp.ndarray],
    build_valid: jnp.ndarray,
    build_values: jnp.ndarray,
    probe_keys: Sequence[jnp.ndarray],
    probe_valid: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Return (found, values) for each probe row.

    found[i] is True iff some valid build row's composite key equals probe
    i's key; values[i] is that row's payload (0 where not found). If
    multiple build rows share a key, the one latest in sort order wins.
    """
    n_b = build_keys[0].shape[0]
    n_q = probe_keys[0].shape[0]
    n = n_b + n_q
    keys = [
        jnp.concatenate([jnp.asarray(b), jnp.asarray(q)])
        for b, q in zip(build_keys, probe_keys)
    ]
    is_build = jnp.concatenate(
        [jnp.asarray(build_valid, bool), jnp.zeros(n_q, bool)]
    )
    # Tie-break so build rows sort before the probes that match them:
    # the hash rides the high 63 bits, the build/probe tag the low bit.
    tag = jnp.concatenate(
        [jnp.zeros(n_b, jnp.uint64), jnp.ones(n_q, jnp.uint64)]
    )
    payload = jnp.concatenate(
        [jnp.asarray(build_values), jnp.zeros(n_q, jnp.asarray(build_values).dtype)]
    )
    sort_key = (mix_keys64(keys) << 1) | tag
    order = jnp.argsort(sort_key)
    s_key_planes = [_planes(k)[order] for k in keys]
    s_build = is_build[order]
    s_payload = payload[order]
    src = _forward_fill_last_true_index(s_build)
    src_c = jnp.clip(src, 0, n - 1)
    same_key = src >= 0
    for kp in s_key_planes:
        same_key = same_key & (kp[src_c] == kp).all(axis=-1)
    hit = same_key & ~s_build
    val = jnp.where(hit, s_payload[src_c], 0)
    # Scatter back to original probe order (build rows routed to the OOB
    # slot n_q and dropped — negative indices would wrap, not drop).
    probe_pos = jnp.concatenate(
        [jnp.full(n_b, n_q, jnp.int32), jnp.arange(n_q, dtype=jnp.int32)]
    )[order]
    found = jnp.zeros(n_q, bool).at[probe_pos].set(hit, mode="drop")
    values = jnp.zeros(n_q, payload.dtype).at[probe_pos].set(val, mode="drop")
    found = found & jnp.asarray(probe_valid, bool)
    return found, values
