"""Device-side streaming operators (the framework's "kernels").

Everything here is pure-functional, jit/vmap/shard_map friendly, and uses
only 32-bit integer arithmetic (TPU-native: 64-bit ids travel as
(hi, lo) uint32 word pairs, see ops/hashing.py). Every sketch has a
``merge`` that is associative+commutative so cross-shard combination is a
plain tree reduction / ``psum``-style collective.

Role parity with the reference (SURVEY.md §2.8 native-role table):
algebird ``Moments`` → ops.moments; dependency-link & heavy-hitter
counting → ops.cms/ops.topk; cardinality → ops.hll; latency
percentiles → ops.quantile.
"""

from zipkin_tpu.ops import cms, hashing, hll, moments, quantile, topk  # noqa: F401
