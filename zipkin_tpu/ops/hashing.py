"""32-bit hashing primitives for 64-bit keys on TPU.

TPUs emulate 64-bit integer ops, so device code works on (hi, lo) uint32
word pairs. Host code splits numpy int64 columns once at upload time.

The mixer is murmur3's fmix32 finalizer — full avalanche on 32 bits —
composed over the two words with distinct odd multipliers per seed, which
gives the independent hash families the sketches need (count-min rows,
HLL index/rank).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

GOLDEN32 = np.uint32(0x9E3779B9)


def split64(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host: int64 column → (hi, lo) uint32 columns."""
    u = x.astype(np.int64).view(np.uint64)
    return (u >> np.uint64(32)).astype(np.uint32), (
        u & np.uint64(0xFFFFFFFF)
    ).astype(np.uint32)


def join64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """Host: (hi, lo) uint32 columns → int64 column."""
    u = (np.asarray(hi, np.uint64) << np.uint64(32)) | np.asarray(lo, np.uint64)
    return u.view(np.int64)


def dev_split64(x):
    """Device: int64 array → (hi, lo) uint32 arrays (requires x64 mode)."""
    u = jnp.asarray(x).astype(jnp.uint64)
    return (u >> 32).astype(jnp.uint32), (u & jnp.uint64(0xFFFFFFFF)).astype(
        jnp.uint32
    )


def fmix32(h):
    """murmur3 finalizer: full-avalanche bijective mixer on uint32."""
    h = jnp.asarray(h, jnp.uint32)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def hash2_32(hi, lo, seed):
    """Hash a (hi, lo) 64-bit key to 32 bits under an integer ``seed``.

    Distinct seeds give (empirically) independent hash functions; used as
    the hash family for count-min rows and the HLL index/rank pair.
    """
    s = jnp.uint32(seed) * GOLDEN32 + jnp.uint32(1)
    h = fmix32(jnp.asarray(lo, jnp.uint32) ^ s)
    h = fmix32(h ^ jnp.asarray(hi, jnp.uint32) ^ (s * jnp.uint32(0x85EBCA6B)))
    return h


def mix_keys64(keys):
    """Device: fold N int64 key columns into one well-dispersed uint64
    (splitmix64-style finalizer). Used to turn multi-key sorts into
    single-key sorts: XLA's TPU sort compile time grows drastically with
    operand count at multi-million row shapes, while equal composite
    keys still collide to equal hashes (callers re-verify equality on
    the original keys after the sort)."""
    acc = jnp.uint64(0x243F6A8885A308D3)  # pi
    for k in keys:
        acc = (acc ^ jnp.asarray(k).astype(jnp.uint64)) * jnp.uint64(
            0x9E3779B97F4A7C15
        )
        acc ^= acc >> 29
    acc *= jnp.uint64(0xBF58476D1CE4E5B9)
    acc ^= acc >> 32
    acc *= jnp.uint64(0x94D049BB133111EB)
    acc ^= acc >> 29
    return acc


def np_mix_keys64(keys):
    """Host (numpy) mirror of mix_keys64 — bit-identical, so host-side
    migrations can seed device hash structures (checkpoint.py) and the
    device probes find the keys."""
    arrs = [np.asarray(k, np.int64).astype(np.uint64) for k in keys]
    acc = np.full(arrs[0].shape, 0x243F6A8885A308D3, np.uint64)
    with np.errstate(over="ignore"):
        for a in arrs:
            acc = (acc ^ a) * np.uint64(0x9E3779B97F4A7C15)
            acc ^= acc >> np.uint64(29)
        acc *= np.uint64(0xBF58476D1CE4E5B9)
        acc ^= acc >> np.uint64(32)
        acc *= np.uint64(0x94D049BB133111EB)
        acc ^= acc >> np.uint64(29)
    return acc


def clz32(x):
    """Count leading zeros of uint32 (vectorized, integer-only)."""
    x = jnp.asarray(x, jnp.uint32)
    n = jnp.zeros(x.shape, jnp.int32)
    zero = x == 0
    for bits, mask in (
        (16, jnp.uint32(0xFFFF0000)),
        (8, jnp.uint32(0xFF000000)),
        (4, jnp.uint32(0xF0000000)),
        (2, jnp.uint32(0xC0000000)),
        (1, jnp.uint32(0x80000000)),
    ):
        hi_clear = (x & mask) == 0
        n = jnp.where(hi_clear, n + bits, n)
        x = jnp.where(hi_clear, x << bits, x)
    return jnp.where(zero, jnp.int32(32), n)
