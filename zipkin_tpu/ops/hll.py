"""HyperLogLog cardinality sketch over 64-bit keys.

Distinct-count estimation (unique trace ids, unique endpoints) with
``1.04/sqrt(m)`` relative standard error (~0.8% at the default p=14).

TPU-native twist: instead of slicing one 64-bit hash we draw two
independent 32-bit hashes — one for the register index, one for the rank
(leading-zero count) — so all arithmetic stays uint32. Rank ≤ 33 caps the
estimator around 2^33 distinct keys per register draw, beyond the 1B-span
target. Update is a scatter-max; merge is elementwise max (idempotent,
commutative — safe to combine shards via ``lax.max`` tree reduction).

Small-range bias is corrected with linear counting below 2.5m, as in
Flajolet et al. 2007.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from zipkin_tpu.ops.hashing import clz32, hash2_32

DEFAULT_P = 14


class HyperLogLog(NamedTuple):
    registers: jnp.ndarray  # [2^p] int32 max-rank per register

    @property
    def m(self) -> int:
        return self.registers.shape[0]


def init(p: int = DEFAULT_P) -> HyperLogLog:
    return HyperLogLog(jnp.zeros(1 << p, jnp.int32))


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


def update(sketch: HyperLogLog, key_hi, key_lo, valid=None) -> HyperLogLog:
    key_hi = jnp.asarray(key_hi, jnp.uint32)
    key_lo = jnp.asarray(key_lo, jnp.uint32)
    idx = (hash2_32(key_hi, key_lo, 101) & jnp.uint32(sketch.m - 1)).astype(jnp.int32)
    rank = clz32(hash2_32(key_hi, key_lo, 202)) + 1  # 1..33
    if valid is not None:
        rank = jnp.where(jnp.asarray(valid, bool), rank, 0)
    return HyperLogLog(sketch.registers.at[idx].max(rank))


def merge(a: HyperLogLog, b: HyperLogLog) -> HyperLogLog:
    return HyperLogLog(jnp.maximum(a.registers, b.registers))


def estimate(sketch: HyperLogLog):
    """Estimated distinct-key count (float32 scalar on device)."""
    m = sketch.m
    regs = sketch.registers.astype(jnp.float32)
    raw = _alpha(m) * m * m / jnp.sum(jnp.exp2(-regs))
    zeros = jnp.sum(sketch.registers == 0).astype(jnp.float32)
    linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    return jnp.where((raw <= 2.5 * m) & (zeros > 0), linear, raw)
