"""Pallas TPU kernels for the scatter-heavy ingest ops.

The fused ingest step is dominated by scatter-adds into modest-size
count arrays (per-service histograms [S*B], count-min rows [D*W],
presence matrices). XLA lowers scatter-add to a sort+segment pipeline
through HBM; these kernels instead keep the whole count array resident
in VMEM and apply updates with on-chip scalar stores — grid steps run
sequentially on a TPU core, so the output block accumulates across
tiles without atomics (pallas_guide.md: grids are sequential; revisited
blocks stay in VMEM).

The count array must fit VMEM (~16MB): S*B = 256×2048 f32 = 2MB and
CMS 4×65536 i32 = 1MB both do. On CPU the kernels run in interpreter
mode (tests); on TPU they compile natively. ``flat_histogram`` is the
generic primitive; ``cms_update`` reuses it per sketch row.

Why the INDEX-FAMILY scatter block was NOT a Pallas kernel at bench
geometry (the r6 decision, NOTES_r06.md §3 carries the arithmetic):
the VMEM-residency trick above is what makes these kernels win, and it
does not transfer to arenas that dwarf VMEM. The unified index arena
at the bench geometry is ~0.5-1.6 GB ([slots, 3] i64 entries) —
30-100x VMEM — and the destination slots are hash-scattered across ALL
of it, so a Pallas version must stream HBM tiles exactly like XLA's
scatter does, with no reuse to amortize: each of the ~1.4M batch rows
touches 24 bytes of a ~1 GB array once. The measured fast path
(unique-index i32 plane scatters at ~4.5 ns/row,
scripts/profile_scatter*.py) already runs within ~2x of the pure HBM
write-bandwidth bound for that access pattern; the remaining gap is
random-access DMA latency, which a hand-rolled kernel pays
identically.

r12 re-opens the SMALL-arena half of that question with
``arena_claim_scatter``: when the whole [slots, 3] arena (as six i32
bit-planes) plus the per-bucket cursor walk DOES fit VMEM, a
grid-sequential kernel fuses the FIFO slot claim (a running cursor
histogram — the work the XLA path buys with a rank sort) and the
six-plane entry scatter into one pass with zero atomics (TPU grids run
sequentially, pallas_guide.md). ``arena_scatter_supported`` is the
VMEM-fit oracle; bigger arenas keep the XLA plane-scatter path and the
r6 roofline conclusion stands for them unchanged. Gated behind
``StoreConfig.use_pallas`` (default OFF) until the profile arms
(scripts/profile_ingest.py --arena-arm, bench.py --ingest-matrix)
prove it on-chip.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
DEFAULT_TILE = 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _hist_kernel(idx_ref, w_ref, out_ref):
    # idx_ref/w_ref are SMEM-resident rank-1 blocks of ``tile`` scalars:
    # SMEM is the TPU memory built for data-dependent SCALAR reads, so
    # ``idx_ref[t]`` with a loop-carried ``t`` lowers cleanly — the
    # round-3 VMEM variant's dynamic LANE index was what Mosaic rejected
    # ("cannot statically prove index in dimension 2 is a multiple of
    # 128", NOTES_r03.md §6), and a rank-2 (1, tile) SMEM block trips
    # the block-shape rule (second-to-last dim must be divisible by 8 or
    # equal the array dim). Rank-1 blocks only constrain the LAST dim
    # (tile % 128 == 0, asserted by the caller). The output stays
    # VMEM-resident across the whole grid (same block for every step);
    # updates are row-granular read-modify-writes with a one-hot lane
    # add — dynamic SUBLANE indexing is legal.
    i = pl.program_id(0)
    tile = idx_ref.shape[0]

    @pl.when(i == 0)
    def _():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    # Shift/mask instead of //,% — LANES is 128 — int32 loop bounds and
    # a None carry: pallas TPU has no 64-bit lowering, and x64 mode
    # would make a plain python-int bound or carry int64 (Mosaic then
    # fails to legalize the loop's i64 func.return).
    def body(t, carry):
        b = idx_ref[t]

        @pl.when(b >= 0)
        def _():
            r = b >> 7
            c = b & 127
            row = out_ref[pl.ds(r, 1), :]
            lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
            onehot = (lane == c).astype(row.dtype) * w_ref[t]
            out_ref[pl.ds(r, 1), :] = row + onehot

        return carry

    jax.lax.fori_loop(jnp.int32(0), jnp.int32(tile), body, None)


@functools.partial(jax.jit, static_argnames=("m", "tile"))
def flat_histogram(idx, weights, m: int, tile: int = DEFAULT_TILE):
    """Scatter-add ``weights`` at flat positions ``idx`` into a length-m
    array (m must be a multiple of 128). Negative idx rows are dropped.

    Returns the [m] histogram delta (caller adds it to running state).
    """
    assert m % LANES == 0, "histogram size must be a multiple of 128"
    assert tile % LANES == 0, "tile must be a multiple of 128"
    n = idx.shape[0]
    if n == 0:
        # Zero-length SMEM operands fail Mosaic layout verification, and
        # a (0,) grid would skip the i==0 output zeroing anyway.
        return jnp.zeros(m, jnp.asarray(weights).dtype)
    n_tiles = -(-n // tile)
    pad = n_tiles * tile - n
    idx = jnp.pad(jnp.asarray(idx, jnp.int32), (0, pad), constant_values=-1)
    weights = jnp.pad(jnp.asarray(weights), (0, pad))
    # Index maps must return i32: with jax_enable_x64 on (package-wide),
    # a literal python 0 traces as i64 and Mosaic fails to legalize the
    # map's func.return. ``i - i`` stays in the i32 program-id type.
    out = pl.pallas_call(
        _hist_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((tile,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((m // LANES, LANES), lambda i: (i - i, i - i)),
        out_shape=jax.ShapeDtypeStruct((m // LANES, LANES), weights.dtype),
        interpret=_interpret(),
    )(idx, weights)
    return out.reshape(m)


def histogram_update(counts, idx, weights=None, tile: int = DEFAULT_TILE):
    """counts[m] += scatter(idx, weights) via the VMEM-resident kernel."""
    m = counts.shape[-1] if counts.ndim == 1 else counts.size
    flat = counts.reshape(-1)
    if weights is None:
        weights = jnp.ones(idx.shape, flat.dtype)
    delta = flat_histogram(idx, weights.astype(flat.dtype), int(m), tile)
    return (flat + delta).reshape(counts.shape)


def cms_update(counts, idx_rows, weights=None, tile: int = DEFAULT_TILE):
    """Count-min update: counts [D, W] += per-row scatter of idx_rows
    [D, N] (bucket per key per row). One flat histogram over D*W."""
    d, w = counts.shape
    n = idx_rows.shape[1]
    flat_idx = (
        idx_rows + (jnp.arange(d, dtype=jnp.int32) * w)[:, None]
    ).reshape(-1)
    flat_idx = jnp.where(idx_rows.reshape(-1) >= 0, flat_idx, -1)
    if weights is None:
        wts = jnp.ones(d * n, counts.dtype)
    else:
        wts = jnp.broadcast_to(weights, (d, n)).reshape(-1).astype(counts.dtype)
    delta = flat_histogram(flat_idx, wts, d * w, tile)
    return counts + delta.reshape(d, w)


# ---------------------------------------------------------------------------
# Fused index-arena claim + entry scatter (r12)
# ---------------------------------------------------------------------------

# VMEM budget for the arena kernel's resident state: 6 input + 6 output
# entry planes + the cursor histogram, all i32. ~10 MB leaves headroom
# for the SMEM row tiles and compiler temporaries inside the ~16 MB
# core budget.
ARENA_VMEM_BUDGET = 10 << 20
ARENA_TILE = 512


def arena_scatter_supported(total_slots: int, n_buckets: int) -> bool:
    """True when the unified arena fits the kernel's VMEM-resident
    model (the r6 roofline boundary: past this, any kernel degenerates
    to the same random-access HBM DMA XLA already issues). Also guards
    the kernel's i32 slot arithmetic."""
    if total_slots <= 0 or total_slots >= (1 << 31):
        return False
    if n_buckets <= 0 or n_buckets >= (1 << 31):
        return False
    sp = -(-total_slots // LANES) * LANES
    bp = -(-n_buckets // LANES) * LANES
    return (12 * sp + bp) * 4 <= ARENA_VMEM_BUDGET


def _arena_kernel(bucket_ref, base_ref, slot0_ref, dmask_ref, valid_ref,
                  v0, v1, v2, v3, v4, v5,
                  e0, e1, e2, e3, e4, e5,
                  o0, o1, o2, o3, o4, o5,
                  cur_ref):
    # Same Mosaic discipline as _hist_kernel: per-row scalars from
    # rank-1 SMEM blocks, VMEM state updated by row-granular RMWs with
    # one-hot lane selects (dynamic SUBLANE indexing is legal, dynamic
    # LANE indexing is not), i32 everywhere (no 64-bit lowering on TPU
    # pallas — the arena travels as bit-planes).
    i = pl.program_id(0)
    tile = bucket_ref.shape[0]
    vins = (v0, v1, v2, v3, v4, v5)
    eins = (e0, e1, e2, e3, e4, e5)
    outs = (o0, o1, o2, o3, o4, o5)

    @pl.when(i == 0)
    def _():
        # The cursor walk starts from zero: ``base`` already carries
        # each row's bucket cursor (pos low word), so the kernel only
        # counts THIS launch's same-bucket predecessors — exactly the
        # FIFO rank the argsort/counting paths compute.
        cur_ref[:, :] = jnp.zeros_like(cur_ref)
        for e, o in zip(eins, outs):
            o[:, :] = e[:, :]

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)

    def body(t, carry):
        @pl.when(valid_ref[t] != 0)
        def _():
            b = bucket_ref[t]
            onehot_b = (lane == (b & 127)).astype(jnp.int32)
            crow = cur_ref[pl.ds(b >> 7, 1), :]
            c = jnp.sum(crow * onehot_b)
            cur_ref[pl.ds(b >> 7, 1), :] = crow + onehot_b
            # The claim: this row's FIFO slot, from the bucket's live
            # cursor. Writes land in arrival order, so an in-batch
            # overflow row is overwritten by its newest same-slot
            # successor — the final arena equals the rank-gated unique
            # scatter's bitwise (store/device._index_write).
            slot = slot0_ref[t] + ((base_ref[t] + c) & dmask_ref[t])
            hit = lane == (slot & 127)
            for v, o in zip(vins, outs):
                row = o[pl.ds(slot >> 7, 1), :]
                o[pl.ds(slot >> 7, 1), :] = jnp.where(hit, v[t], row)

        return carry

    jax.lax.fori_loop(jnp.int32(0), jnp.int32(tile), body, None)


@functools.partial(jax.jit, static_argnames=("n_buckets", "tile"))
def arena_claim_scatter(entries, bucket, base, slot0, depth, vals,
                        valid, n_buckets: int, tile: int = ARENA_TILE):
    """Fused FIFO claim + entry-row scatter over the unified [slots, 3]
    i64 index arena. Per valid row: claim the bucket's next FIFO slot
    (``slot0 + ((base + cursor++) & (depth - 1))``) and store the row's
    three i64 columns as six i32 planes. Grid steps run sequentially on
    a TPU core, so the cursor walk needs no atomics and write order is
    arrival order — the final arena is bitwise-identical to the XLA
    path's rank-gated unique scatter (fuzz-gated by
    tests/test_pallas_kernels.py).

    ``bucket`` must be clipped to [0, n_buckets); ``base`` is each
    row's bucket cursor low word (pos_lo[bucket], already gathered by
    the caller); ``depth`` per-row powers of two; callers check
    ``arena_scatter_supported`` first (whole-arena VMEM residency).
    """
    S = entries.shape[0]
    n = bucket.shape[0]
    if n == 0:
        return entries
    sp = -(-S // LANES) * LANES
    bp = -(-n_buckets // LANES) * LANES
    n_tiles = -(-n // tile)
    pad = n_tiles * tile - n
    # Arena -> six plane-major i32 buffers ([S] each, lane-padded): a
    # row's (gid, verify, ts) i64 columns become planes 2c (lo) and
    # 2c+1 (hi) — the same bitcast _p32 uses, kept plane-major so each
    # kernel write is one contiguous VMEM row RMW.
    p = jax.lax.bitcast_convert_type(entries, jnp.int32).reshape(S, 6)
    planes = jnp.pad(jnp.moveaxis(p, 0, 1), ((0, 0), (0, sp - S)))
    planes = planes.reshape(6, sp // LANES, LANES)
    v = jax.lax.bitcast_convert_type(
        jnp.asarray(vals, jnp.int64), jnp.int32).reshape(n, 6)

    def padi(x, dtype=jnp.int32):
        return jnp.pad(jnp.asarray(x, dtype), (0, pad))

    row_ins = [
        padi(bucket), padi(base), padi(slot0), padi(
            jnp.asarray(depth, jnp.int32) - 1),
        padi(jnp.asarray(valid).astype(jnp.int32)),
    ] + [padi(v[:, j]) for j in range(6)]
    smem = pl.BlockSpec((tile,), lambda i: (i,),
                        memory_space=pltpu.SMEM)
    vblock = pl.BlockSpec((sp // LANES, LANES),
                          lambda i: (i - i, i - i))
    outs = pl.pallas_call(
        _arena_kernel,
        grid=(n_tiles,),
        in_specs=[smem] * 11 + [vblock] * 6,
        out_specs=[vblock] * 6,
        out_shape=[
            jax.ShapeDtypeStruct((sp // LANES, LANES), jnp.int32)
        ] * 6,
        scratch_shapes=[pltpu.VMEM((bp // LANES, LANES), jnp.int32)],
        interpret=_interpret(),
    )(*row_ins, *(planes[j] for j in range(6)))
    flat = jnp.stack(outs).reshape(6, sp)[:, :S]
    return jax.lax.bitcast_convert_type(
        jnp.moveaxis(flat, 0, 1).reshape(S, 3, 2), jnp.int64)


# ---------------------------------------------------------------------------
# Paged trace-assembly block gather (r19)
# ---------------------------------------------------------------------------

# VMEM model for the page gather: the kernel streams one (W, R) i32
# page block per grid step (double-buffered in/out DMA), so residency
# is a handful of blocks, not the pool — but keep an explicit ceiling
# so absurd page_rows (or a plane count change) degrade to the XLA
# take fallback instead of a Mosaic allocation failure, mirroring the
# arena_claim_scatter gate.
PAGED_GATHER_VMEM_BUDGET = 10 << 20


def paged_gather_supported(capacity: int, page_rows: int,
                           n_cols: int, n_pages_req: int) -> bool:
    """True when the paged trace gather may take the Pallas block
    kernel. Lane alignment: the (W, page_rows) block's last dim must be
    a multiple of 128 and the plane matrix [W, capacity] must tile
    evenly into page blocks. VMEM: ~4 in+out blocks resident
    (double-buffered DMA) under the ceiling."""
    W = 2 * n_cols
    if page_rows % LANES != 0 or capacity % page_rows != 0:
        return False
    if n_pages_req <= 0:
        return False
    return 4 * W * page_rows * 4 <= PAGED_GATHER_VMEM_BUDGET


def _paged_gather_kernel(pages_ref, in_ref, out_ref):
    # One grid step per requested page: the scalar-prefetched page list
    # drives the INPUT block index map (a block-level gather — no
    # in-kernel dynamic slicing, so no Mosaic divisibility proofs
    # beyond the lane-aligned block shape), and the body just forwards
    # the block. Holes (-1 pages, the pad) are clamped to block 0 by
    # the index map and zero-filled here so both gather paths mask
    # identically downstream.
    i = pl.program_id(0)

    @pl.when(pages_ref[i] < 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(pages_ref[i] >= 0)
    def _():
        out_ref[...] = in_ref[...]


@functools.partial(jax.jit, static_argnames=("page_rows",))
def paged_page_gather(planes, pages, page_rows: int):
    """Gather page blocks out of the plane matrix.

    ``planes`` [W, capacity] i32 — the span columns as lo/hi bit-planes
    (W = 2 * n_cols, built by the caller with one free bitcast);
    ``pages`` [K] i32 page ids, -1 for holes. Returns [W, K *
    page_rows] i32: output block i is page ``pages[i]``'s rows (zeros
    for holes). The W axis rides the "second-to-last dim equals the
    array dim" Mosaic block rule, so any lane-aligned page_rows works.
    Callers check ``paged_gather_supported`` first."""
    W, _ = planes.shape
    K = pages.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(K,),
        in_specs=[pl.BlockSpec(
            (W, page_rows),
            lambda i, pages: (i - i, jnp.maximum(pages[i], 0)),
        )],
        out_specs=pl.BlockSpec((W, page_rows), lambda i, pages: (i - i, i)),
    )
    return pl.pallas_call(
        _paged_gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((W, K * page_rows), jnp.int32),
        interpret=_interpret(),
    )(jnp.asarray(pages, jnp.int32), planes)


def scatter_histogram_xla(counts, idx, weights=None):
    """XLA reference path (what store/device.py uses today); kept for
    benchmarking the pallas kernel against on real hardware."""
    flat = counts.reshape(-1)
    m = flat.shape[0]
    if weights is None:
        weights = jnp.ones(idx.shape, flat.dtype)
    safe = jnp.where(idx >= 0, idx, m)
    out = jnp.concatenate([flat, jnp.zeros(1, flat.dtype)])
    out = out.at[safe].add(weights.astype(flat.dtype))
    return out[:m].reshape(counts.shape)
