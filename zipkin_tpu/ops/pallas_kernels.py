"""Pallas TPU kernels for the scatter-heavy ingest ops.

The fused ingest step is dominated by scatter-adds into modest-size
count arrays (per-service histograms [S*B], count-min rows [D*W],
presence matrices). XLA lowers scatter-add to a sort+segment pipeline
through HBM; these kernels instead keep the whole count array resident
in VMEM and apply updates with on-chip scalar stores — grid steps run
sequentially on a TPU core, so the output block accumulates across
tiles without atomics (pallas_guide.md: grids are sequential; revisited
blocks stay in VMEM).

The count array must fit VMEM (~16MB): S*B = 256×2048 f32 = 2MB and
CMS 4×65536 i32 = 1MB both do. On CPU the kernels run in interpreter
mode (tests); on TPU they compile natively. ``flat_histogram`` is the
generic primitive; ``cms_update`` reuses it per sketch row.

Why the INDEX-FAMILY scatter block is NOT a Pallas kernel (the r6
decision, NOTES_r06.md §3 carries the arithmetic): the VMEM-residency
trick above is what makes these kernels win, and it fundamentally does
not transfer. The unified index arena at the bench geometry is
~0.5-1.6 GB ([slots, 3] i64 entries) — 30-100x VMEM — and the
destination slots are hash-scattered across ALL of it, so a Pallas
version must stream HBM tiles exactly like XLA's scatter does, with no
reuse to amortize: each of the ~1.4M batch rows touches 24 bytes of a
~1 GB array once. The measured fast path (unique-index i32 plane
scatters at ~4.5 ns/row, scripts/profile_scatter*.py) already runs
within ~2x of the pure HBM write-bandwidth bound for that access
pattern; the remaining gap is random-access DMA latency, which a
hand-rolled kernel pays identically. The wins that WERE available —
fewer passes over the rows (one rank sort, one displaced-row gather,
one shared watermark scatter for all seven families) — are
access-PATTERN restructurings, landed in store/device.py where XLA
fuses them fine. A Pallas arena kernel would re-derive the same DMA
schedule at much higher maintenance cost.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
DEFAULT_TILE = 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _hist_kernel(idx_ref, w_ref, out_ref):
    # idx_ref/w_ref are SMEM-resident rank-1 blocks of ``tile`` scalars:
    # SMEM is the TPU memory built for data-dependent SCALAR reads, so
    # ``idx_ref[t]`` with a loop-carried ``t`` lowers cleanly — the
    # round-3 VMEM variant's dynamic LANE index was what Mosaic rejected
    # ("cannot statically prove index in dimension 2 is a multiple of
    # 128", NOTES_r03.md §6), and a rank-2 (1, tile) SMEM block trips
    # the block-shape rule (second-to-last dim must be divisible by 8 or
    # equal the array dim). Rank-1 blocks only constrain the LAST dim
    # (tile % 128 == 0, asserted by the caller). The output stays
    # VMEM-resident across the whole grid (same block for every step);
    # updates are row-granular read-modify-writes with a one-hot lane
    # add — dynamic SUBLANE indexing is legal.
    i = pl.program_id(0)
    tile = idx_ref.shape[0]

    @pl.when(i == 0)
    def _():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    # Shift/mask instead of //,% — LANES is 128 — int32 loop bounds and
    # a None carry: pallas TPU has no 64-bit lowering, and x64 mode
    # would make a plain python-int bound or carry int64 (Mosaic then
    # fails to legalize the loop's i64 func.return).
    def body(t, carry):
        b = idx_ref[t]

        @pl.when(b >= 0)
        def _():
            r = b >> 7
            c = b & 127
            row = out_ref[pl.ds(r, 1), :]
            lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
            onehot = (lane == c).astype(row.dtype) * w_ref[t]
            out_ref[pl.ds(r, 1), :] = row + onehot

        return carry

    jax.lax.fori_loop(jnp.int32(0), jnp.int32(tile), body, None)


@functools.partial(jax.jit, static_argnames=("m", "tile"))
def flat_histogram(idx, weights, m: int, tile: int = DEFAULT_TILE):
    """Scatter-add ``weights`` at flat positions ``idx`` into a length-m
    array (m must be a multiple of 128). Negative idx rows are dropped.

    Returns the [m] histogram delta (caller adds it to running state).
    """
    assert m % LANES == 0, "histogram size must be a multiple of 128"
    assert tile % LANES == 0, "tile must be a multiple of 128"
    n = idx.shape[0]
    if n == 0:
        # Zero-length SMEM operands fail Mosaic layout verification, and
        # a (0,) grid would skip the i==0 output zeroing anyway.
        return jnp.zeros(m, jnp.asarray(weights).dtype)
    n_tiles = -(-n // tile)
    pad = n_tiles * tile - n
    idx = jnp.pad(jnp.asarray(idx, jnp.int32), (0, pad), constant_values=-1)
    weights = jnp.pad(jnp.asarray(weights), (0, pad))
    # Index maps must return i32: with jax_enable_x64 on (package-wide),
    # a literal python 0 traces as i64 and Mosaic fails to legalize the
    # map's func.return. ``i - i`` stays in the i32 program-id type.
    out = pl.pallas_call(
        _hist_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((tile,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((m // LANES, LANES), lambda i: (i - i, i - i)),
        out_shape=jax.ShapeDtypeStruct((m // LANES, LANES), weights.dtype),
        interpret=_interpret(),
    )(idx, weights)
    return out.reshape(m)


def histogram_update(counts, idx, weights=None, tile: int = DEFAULT_TILE):
    """counts[m] += scatter(idx, weights) via the VMEM-resident kernel."""
    m = counts.shape[-1] if counts.ndim == 1 else counts.size
    flat = counts.reshape(-1)
    if weights is None:
        weights = jnp.ones(idx.shape, flat.dtype)
    delta = flat_histogram(idx, weights.astype(flat.dtype), int(m), tile)
    return (flat + delta).reshape(counts.shape)


def cms_update(counts, idx_rows, weights=None, tile: int = DEFAULT_TILE):
    """Count-min update: counts [D, W] += per-row scatter of idx_rows
    [D, N] (bucket per key per row). One flat histogram over D*W."""
    d, w = counts.shape
    n = idx_rows.shape[1]
    flat_idx = (
        idx_rows + (jnp.arange(d, dtype=jnp.int32) * w)[:, None]
    ).reshape(-1)
    flat_idx = jnp.where(idx_rows.reshape(-1) >= 0, flat_idx, -1)
    if weights is None:
        wts = jnp.ones(d * n, counts.dtype)
    else:
        wts = jnp.broadcast_to(weights, (d, n)).reshape(-1).astype(counts.dtype)
    delta = flat_histogram(flat_idx, wts, d * w, tile)
    return counts + delta.reshape(d, w)


def scatter_histogram_xla(counts, idx, weights=None):
    """XLA reference path (what store/device.py uses today); kept for
    benchmarking the pallas kernel against on real hardware."""
    flat = counts.reshape(-1)
    m = flat.shape[0]
    if weights is None:
        weights = jnp.ones(idx.shape, flat.dtype)
    safe = jnp.where(idx >= 0, idx, m)
    out = jnp.concatenate([flat, jnp.zeros(1, flat.dtype)])
    out = out.at[safe].add(weights.astype(flat.dtype))
    return out[:m].reshape(counts.shape)
