"""Count-min sketch over 64-bit keys (as (hi, lo) uint32 pairs).

Point-queryable frequency counts for *unbounded* key domains (trace ids,
(parent, child) service pairs before dictionary encoding, annotation
values). Never under-estimates; over-estimation bounded by
``e * total / width`` per row, minimised over ``depth`` rows.

State is a plain ``[depth, width]`` count array; ``merge`` is ``+`` so
cross-shard combination is a ``psum``. Width must be a power of two
(index is a mask, not a modulo — cheap on the VPU).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from zipkin_tpu.ops.hashing import hash2_32

DEFAULT_DEPTH = 4
DEFAULT_WIDTH = 1 << 16


class CountMin(NamedTuple):
    counts: jnp.ndarray  # [depth, width]

    @property
    def depth(self) -> int:
        return self.counts.shape[0]

    @property
    def width(self) -> int:
        return self.counts.shape[1]


def init(depth: int = DEFAULT_DEPTH, width: int = DEFAULT_WIDTH, dtype=jnp.int32) -> CountMin:
    assert width & (width - 1) == 0, "width must be a power of two"
    return CountMin(jnp.zeros((depth, width), dtype))


def _indices(sketch: CountMin, key_hi, key_lo):
    """[depth, n] bucket indices for each key under each row's hash."""
    rows = jnp.arange(sketch.depth, dtype=jnp.uint32)[:, None]
    h = hash2_32(key_hi[None, :], key_lo[None, :], 0) ^ (
        hash2_32(key_hi[None, :], key_lo[None, :], 1)
        * (rows * jnp.uint32(2) + jnp.uint32(1))
    )
    return (h & jnp.uint32(sketch.width - 1)).astype(jnp.int32)


def update(sketch: CountMin, key_hi, key_lo, weights=None) -> CountMin:
    """Add ``weights`` (default 1) for each key. Duplicate keys accumulate."""
    key_hi = jnp.asarray(key_hi, jnp.uint32)
    key_lo = jnp.asarray(key_lo, jnp.uint32)
    idx = _indices(sketch, key_hi, key_lo)  # [depth, n]
    if weights is None:
        w = jnp.ones(key_hi.shape, sketch.counts.dtype)
    else:
        w = jnp.asarray(weights, sketch.counts.dtype)
    flat = idx + (jnp.arange(sketch.depth, dtype=jnp.int32) * sketch.width)[:, None]
    counts = (
        sketch.counts.reshape(-1)
        .at[flat.reshape(-1)]
        .add(jnp.broadcast_to(w, idx.shape).reshape(-1))
        .reshape(sketch.counts.shape)
    )
    return CountMin(counts)


def query(sketch: CountMin, key_hi, key_lo):
    """Estimated count per key (min over rows). Never underestimates."""
    key_hi = jnp.asarray(key_hi, jnp.uint32)
    key_lo = jnp.asarray(key_lo, jnp.uint32)
    idx = _indices(sketch, key_hi, key_lo)
    vals = jnp.take_along_axis(sketch.counts, idx, axis=1)  # [depth, n]
    return vals.min(axis=0)


def merge(a: CountMin, b: CountMin) -> CountMin:
    return CountMin(a.counts + b.counts)


def total(sketch: CountMin):
    """Total weight inserted (exact: every row sums to it)."""
    return sketch.counts[0].sum()
