"""Mergeable log-histogram quantile sketch (DDSketch-style).

Latency percentiles (p50/p95/p99 per service) with a *relative* accuracy
guarantee: with ``alpha`` = 0.01, any returned quantile is within ±1% of
a true quantile value. Chosen over t-digest because its update is a pure
scatter-add into a fixed-size array and its merge is ``+`` — exactly the
shape the TPU wants (t-digest's centroid list is sequential and
data-dependent; cf. the moment-sketch line of work in PAPERS.md, which we
also expose via ops.moments).

Bucket ``i`` covers values in ``(min_value * gamma^(i-1), min_value *
gamma^i]`` with ``gamma = (1+alpha)/(1-alpha)``; values ≤ min_value land
in bucket 0. Durations are microseconds, so ``min_value=1.0`` and 2048
buckets cover up to ~10^17 µs at alpha=0.01.

State supports leading batch dims: ``[..., n_buckets]`` — a per-service
sketch bank is just ``[n_services, n_buckets]`` updated with one 2-D
scatter-add.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp

DEFAULT_ALPHA = 0.01
DEFAULT_BUCKETS = 2048


@jax.tree_util.register_pytree_node_class
@dataclass
class LogHistogram:
    counts: jnp.ndarray  # [..., n_buckets]
    gamma: float  # static (pytree aux): never traced
    min_value: float  # static (pytree aux)

    @property
    def n_buckets(self) -> int:
        return self.counts.shape[-1]

    def _replace(self, **kw) -> "LogHistogram":
        return replace(self, **kw)

    def tree_flatten(self):
        return (self.counts,), (self.gamma, self.min_value)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0], aux[1])


def init(
    shape=(),
    n_buckets: int = DEFAULT_BUCKETS,
    alpha: float = DEFAULT_ALPHA,
    min_value: float = 1.0,
    dtype=jnp.float32,
) -> LogHistogram:
    gamma = (1.0 + alpha) / (1.0 - alpha)
    return LogHistogram(
        jnp.zeros(tuple(shape) + (n_buckets,), dtype), gamma, min_value
    )


def bucket_index(sketch: LogHistogram, values):
    """Bucket index per value (int32), clipped into range."""
    v = jnp.asarray(values, jnp.float32)
    scaled = jnp.log(jnp.maximum(v, sketch.min_value) / sketch.min_value)
    idx = jnp.ceil(scaled / math.log(sketch.gamma))
    return jnp.clip(idx.astype(jnp.int32), 0, sketch.n_buckets - 1)


def update(sketch: LogHistogram, values, valid=None) -> LogHistogram:
    """Flat (no leading dims) update: add each value to its bucket."""
    idx = bucket_index(sketch, values)
    w = (
        jnp.ones(idx.shape, sketch.counts.dtype)
        if valid is None
        else jnp.asarray(valid, sketch.counts.dtype)
    )
    return sketch._replace(counts=sketch.counts.at[idx].add(w))


def update_grouped(sketch: LogHistogram, group_ids, values, valid=None) -> LogHistogram:
    """Banked update: sketch [G, B]; value i goes to (group_ids[i], bucket)."""
    idx = bucket_index(sketch, values)
    g = jnp.asarray(group_ids, jnp.int32)
    w = (
        jnp.ones(idx.shape, sketch.counts.dtype)
        if valid is None
        else jnp.asarray(valid, sketch.counts.dtype)
    )
    n_groups = sketch.counts.shape[0]
    g = jnp.clip(g, 0, n_groups - 1)
    flat = g * sketch.n_buckets + idx
    counts = (
        sketch.counts.reshape(-1).at[flat].add(w).reshape(sketch.counts.shape)
    )
    return sketch._replace(counts=counts)


def merge(a: LogHistogram, b: LogHistogram) -> LogHistogram:
    assert a.gamma == b.gamma and a.min_value == b.min_value
    return a._replace(counts=a.counts + b.counts)


def quantile(sketch: LogHistogram, q):
    """q-quantile value estimate per leading dim; NaN where count is 0.

    Returns the geometric midpoint of the matched bucket, which meets the
    ±alpha relative guarantee.
    """
    # Explicit float32 throughout: under x64, python-float promotion
    # would produce float64 ops, which TPUs don't support.
    counts = sketch.counts.astype(jnp.float32)
    total = counts.sum(axis=-1, keepdims=True)
    ranks = jnp.float32(q) * jnp.maximum(total - 1, 0)
    cum = jnp.cumsum(counts, axis=-1)
    b = jnp.sum(cum <= ranks, axis=-1)  # first bucket with cum > rank
    b = jnp.minimum(b, sketch.n_buckets - 1)
    g = jnp.float32(sketch.gamma)
    mid = (
        jnp.float32(sketch.min_value)
        * jnp.power(g, b.astype(jnp.float32))
        * (jnp.float32(2.0) / (jnp.float32(1.0) + g))
    )
    mid = jnp.where(b == 0, jnp.float32(sketch.min_value), mid)
    return jnp.where(total[..., 0] > 0, mid, jnp.nan)


def count(sketch: LogHistogram):
    return sketch.counts.sum(axis=-1)


def quantiles_host(counts, gamma: float, min_value: float, qs):
    """Pure-numpy twin of ``quantile`` for a single already-fetched
    [n_buckets] row — serving layers call this on host data; eager jnp
    here would bounce the row back through the device per quantile."""
    import numpy as np

    counts = np.asarray(counts, np.float64)
    total = float(counts.sum())
    if total <= 0:
        return [float("nan")] * len(qs)
    cum = np.cumsum(counts)
    out = []
    for q in qs:
        rank = q * max(total - 1.0, 0.0)
        b = min(int(np.searchsorted(cum, rank, side="right")),
                len(counts) - 1)
        mid = min_value if b == 0 else (
            min_value * gamma**b * (2.0 / (1.0 + gamma))
        )
        out.append(float(mid))
    return out
