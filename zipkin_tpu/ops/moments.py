"""Vectorized streaming central moments (algebird ``Moments`` on device).

State layout: a trailing-dim-5 array ``[..., (n, mean, m2, m3, m4)]`` —
same central form as models/dependencies.Moments and the thrift wire
m0..m4 (zipkinDependencies.thrift). ``combine`` is the Chan/Pébay
pairwise formula, identical to ``Moments.__add__`` on the host, so
device-aggregated moments and host-aggregated moments agree bit-for-bit
up to dtype.

``segment_moments`` computes exact per-segment moments in two
``segment_sum`` passes (mean first, then centered powers) — the
device-side replacement for the reference's per-link
``Moments(child.duration)`` monoid-sum (ZipkinAggregateJob.scala:36-46).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

N_FIELDS = 5  # n, mean, m2, m3, m4


def zero(shape=(), dtype=jnp.float32):
    return jnp.zeros(tuple(shape) + (N_FIELDS,), dtype)


def of(x):
    """Moments of single observations: x[...] → [..., 5]."""
    x = jnp.asarray(x)
    z = jnp.zeros_like(x)
    return jnp.stack([jnp.ones_like(x), x, z, z, z], axis=-1)


def combine(a, b):
    """Pairwise combine, elementwise over leading dims ([...,5],[...,5])."""
    na, ma, m2a, m3a, m4a = [a[..., i] for i in range(N_FIELDS)]
    nb, mb, m2b, m3b, m4b = [b[..., i] for i in range(N_FIELDS)]
    n = na + nb
    safe_n = jnp.where(n > 0, n, 1)
    delta = mb - ma
    d_n = delta / safe_n
    mean = ma + nb * d_n
    m2 = m2a + m2b + delta * d_n * na * nb
    m3 = (
        m3a
        + m3b
        + delta * d_n * d_n * na * nb * (na - nb)
        + 3.0 * d_n * (na * m2b - nb * m2a)
    )
    m4 = (
        m4a
        + m4b
        + delta * d_n**3 * na * nb * (na * na - na * nb + nb * nb)
        + 6.0 * d_n * d_n * (na * na * m2b + nb * nb * m2a)
        + 4.0 * d_n * (na * m3b - nb * m3a)
    )
    out = jnp.stack([n, mean, m2, m3, m4], axis=-1)
    # Monoid identities: empty side contributes nothing.
    out = jnp.where((na == 0)[..., None], b, out)
    out = jnp.where((nb == 0)[..., None], a, out)
    return out


def segment_moments(values, segment_ids, num_segments, valid=None, dtype=jnp.float32):
    """Exact per-segment moments: values[i] → segment segment_ids[i].

    ``valid`` masks out padding rows. Returns [num_segments, 5].
    Two-pass: segment mean, then segment sums of centered powers — exact
    (not an approximation of sequential updates) and scatter-add only.
    """
    x = jnp.asarray(values, dtype)
    seg = jnp.asarray(segment_ids, jnp.int32)
    w = jnp.ones_like(x) if valid is None else jnp.asarray(valid, dtype)
    # Route masked rows to a scratch segment so they can't pollute real ones.
    seg = jnp.where(w > 0, seg, num_segments)
    n = jax.ops.segment_sum(w, seg, num_segments + 1)
    sx = jax.ops.segment_sum(w * x, seg, num_segments + 1)
    mean = sx / jnp.where(n > 0, n, 1)
    c = (x - mean[seg]) * w
    m2 = jax.ops.segment_sum(c * c, seg, num_segments + 1)
    m3 = jax.ops.segment_sum(c * c * c, seg, num_segments + 1)
    m4 = jax.ops.segment_sum(c * c * c * c, seg, num_segments + 1)
    return jnp.stack([n, mean, m2, m3, m4], axis=-1)[:num_segments]


def reduce_moments(m, axis: int = 0):
    """Tree-reduce a stack of moments [..., k, 5] along ``axis`` via combine.

    log2(k) combine steps — the in-graph analogue of algebird's monoid
    ``sum`` over a collection of Moments.
    """
    m = jnp.moveaxis(m, axis, 0)
    k = m.shape[0]
    while k > 1:
        if k % 2:
            m = jnp.concatenate([m, zero(m.shape[1:-1], m.dtype)[None]], axis=0)
            k += 1
        m = combine(m[0::2], m[1::2])
        k = m.shape[0]
    return m[0]


def variance(m):
    n = m[..., 0]
    return m[..., 2] / jnp.where(n > 0, n, 1)


def mean(m):
    return m[..., 1]


def count(m):
    return m[..., 0]
