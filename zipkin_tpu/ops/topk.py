"""Top-k heavy hitters over dictionary-encoded (bounded) key domains.

Because the host dictionary encoder gives services/span-names/annotation
keys *dense small ids*, exact counting into a fixed counter array beats
probabilistic heavy-hitter sketches: update is one scatter-add, merge is
``+``, and top-k is a single ``lax.top_k`` over the counter array. This
replaces the reference's ``TopAnnotations`` CF + Scalding count jobs
(CassieSpanStore.scala, zipkin-aggregate) with an O(capacity) array.

For genuinely unbounded keys, pair ops.cms (estimates) with a host-side
candidate list; ``topk_from_cms`` supports that path.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from zipkin_tpu.ops import cms


class Counters(NamedTuple):
    counts: jnp.ndarray  # [capacity]

    @property
    def capacity(self) -> int:
        return self.counts.shape[0]


def init(capacity: int, dtype=jnp.float32) -> Counters:
    return Counters(jnp.zeros(capacity, dtype))


def update(state: Counters, ids, weights=None, valid=None) -> Counters:
    """Add ``weights`` (default 1) at each id; ids outside capacity and
    invalid rows are dropped (routed to a scratch slot)."""
    ids = jnp.asarray(ids, jnp.int32)
    w = (
        jnp.ones(ids.shape, state.counts.dtype)
        if weights is None
        else jnp.asarray(weights, state.counts.dtype)
    )
    ok = (ids >= 0) & (ids < state.capacity)
    if valid is not None:
        ok = ok & jnp.asarray(valid, bool)
    padded = jnp.concatenate([state.counts, jnp.zeros(1, state.counts.dtype)])
    idx = jnp.where(ok, ids, state.capacity)
    return Counters(padded.at[idx].add(w)[:-1])


def merge(a: Counters, b: Counters) -> Counters:
    return Counters(a.counts + b.counts)


def top_k(state: Counters, k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(counts, ids) of the k largest counters (lax.top_k, MXU-free)."""
    k = min(k, state.capacity)
    return jax.lax.top_k(state.counts, k)


def topk_from_cms(
    sketch: cms.CountMin, cand_hi, cand_lo, k: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Estimated counts + positions of the top-k among candidate keys."""
    est = cms.query(sketch, cand_hi, cand_lo)
    k = min(k, int(est.shape[0]))
    vals, pos = jax.lax.top_k(est, k)
    return vals, pos
