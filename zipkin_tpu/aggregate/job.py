"""Dependency-link aggregation jobs."""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from zipkin_tpu.models.dependencies import (
    Dependencies,
    DependencyLink,
    Moments,
    merge_dependency_links,
)
from zipkin_tpu.models.span import Span, merge_by_span_id


def aggregate_spans(
    spans: Iterable[Span],
    start_ts: Optional[float] = None,
    end_ts: Optional[float] = None,
) -> Dependencies:
    """Pure-python oracle with the batch job's exact semantics
    (ZipkinAggregateJob.scala:21-46):

    1. merge span halves by (id, trace_id); drop invalid merges;
    2. join children to parents on (parent_id, trace_id);
    3. one Moments(child.duration) per joined pair, summed per
       (parent.service, child.service) link.
    """
    by_key: Dict[Tuple[int, int], Span] = {}
    for s in spans:
        key = (s.id, s.trace_id)
        by_key[key] = by_key[key].merge(s) if key in by_key else s
    merged = {k: s for k, s in by_key.items() if s.is_valid()}

    links: List[DependencyLink] = []
    ts_seen: List[int] = []
    for (sid, tid), child in merged.items():
        if child.parent_id is None:
            continue
        parent = merged.get((child.parent_id, tid))
        if parent is None:
            continue
        p_name, c_name = parent.service_name, child.service_name
        if p_name is None or c_name is None:
            continue
        d = child.duration
        moments = Moments.of(float(d)) if d is not None else Moments.zero()
        links.append(DependencyLink(p_name, c_name, moments))
        if child.first_timestamp is not None:
            ts_seen.append(child.first_timestamp)
            ts_seen.append(child.last_timestamp)
    if start_ts is None:
        start_ts = min(ts_seen) if ts_seen else float("inf")
    if end_ts is None:
        end_ts = max(ts_seen) if ts_seen else float("-inf")
    return Dependencies(
        float(start_ts), float(end_ts),
        tuple(merge_dependency_links(links)),
    )


def links_from_bank(bank, services_dict, n_services: int
                    ) -> List[DependencyLink]:
    """Decode a [S*S, 5] device Moments bank into DependencyLinks."""
    bank = np.asarray(bank, np.float64)
    links = []
    for li in np.flatnonzero(bank[:, 0] > 0):
        parent, child = divmod(int(li), n_services)
        if parent >= len(services_dict) or child >= len(services_dict):
            continue
        links.append(DependencyLink(
            services_dict.decode(parent), services_dict.decode(child),
            Moments.from_central(*bank[li]),
        ))
    return links


def dependencies_from_bank(bank, services_dict, n_services: int,
                           ts_min: float, ts_max: float) -> Dependencies:
    links = links_from_bank(bank, services_dict, n_services)
    if not links and ts_min > ts_max:
        return Dependencies.zero()
    return Dependencies(float(ts_min), float(ts_max), tuple(links))


def recompute_dependencies(tpu_store) -> Dependencies:
    """Re-derive dependencies from the device store's live span ring
    (ignores the streaming bank) — the idempotent-rerunnable batch job.
    Only sees spans still in retention, unlike the streaming bank."""
    from zipkin_tpu.store.device import recompute_dep_moments

    with tpu_store._rw.read():
        st = tpu_store.state
        bank = np.asarray(recompute_dep_moments(st))
        ts_min, ts_max = float(st.ts_min), float(st.ts_max)
    return dependencies_from_bank(
        bank,
        tpu_store.dicts.services,
        tpu_store.config.max_services,
        ts_min,
        ts_max,
    )


class IncrementalAggregator:
    """Resumable aggregation over a span feed (AnormAggregator.scala:32-90).

    Processes spans in batches of at most ``batch_size`` (the reference's
    10k bound), folds each batch's links into the running Dependencies,
    and tracks the aggregated high-water mark so a restart resumes from
    ``resume_from()`` — the MAX(end_ts)-in-zipkin_dependencies behavior.
    """

    BATCH_SIZE = 10_000

    def __init__(self, batch_size: int = BATCH_SIZE,
                 resume_ts: Optional[float] = None):
        self.batch_size = batch_size
        self.deps = Dependencies.zero()
        self._resume_ts = resume_ts

    def resume_from(self) -> Optional[float]:
        """Timestamp to restart the feed from after a crash."""
        if self.deps.end_time > self.deps.start_time:
            return self.deps.end_time
        return self._resume_ts

    def offer(self, spans: Sequence[Span]) -> None:
        resume = self._resume_ts
        if resume is not None:
            spans = [
                s for s in spans
                if s.last_timestamp is None or s.last_timestamp > resume
            ]
        # Dependency joins are trace-local, so batches are packed on
        # whole-trace boundaries: the per-batch monoid fold then equals
        # the one-shot aggregate.
        by_trace: Dict[int, List[Span]] = {}
        for s in spans:
            by_trace.setdefault(s.trace_id, []).append(s)
        batch: List[Span] = []
        for trace_spans in by_trace.values():
            if batch and len(batch) + len(trace_spans) > self.batch_size:
                self.deps = self.deps + aggregate_spans(batch)
                batch = []
            batch.extend(trace_spans)
        if batch:
            self.deps = self.deps + aggregate_spans(batch)

    def result(self) -> Dependencies:
        return self.deps
