"""Dependency aggregation: offline/batch jobs + the streaming parity.

Reference: zipkin-aggregate's Scalding job (ZipkinAggregateJob.scala:10-47
— merge span halves, join parents×children, Moments per link, monoid
sum) and the incremental SQL aggregator (AnormAggregator.scala:32-90 —
≤10k-span batches, resume from the last aggregated end_ts).

Three forms here:
- ``aggregate_spans``: the pure-python oracle with full merge semantics;
- ``recompute_dependencies``: device kernel over the TPU store's ring
  (store/device.recompute_dep_moments) — the rerunnable batch job;
- ``IncrementalAggregator``: resumable batch-driven aggregation with the
  reference's resume-from-MAX(end_ts) behavior.
"""

from zipkin_tpu.aggregate.job import (  # noqa: F401
    IncrementalAggregator,
    aggregate_spans,
    recompute_dependencies,
)
