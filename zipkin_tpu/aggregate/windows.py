"""Windowed Moments-sketch analytics arena: the host math.

The store keeps a dense ``[S, W, k]`` grid of integer Moments-sketch
cells keyed by (service, time bucket): per cell a count triple
(total spans, error spans, duration-carrying spans), the power sums
``Σx, Σx², Σx³, Σx⁴`` of the QUANTIZED log-duration ``x``, and the
cell's (min, max) of ``x``. Merging two cells — and therefore
answering ANY ad-hoc window [b0, b1] — is a vector add (+ min/max),
the Moments-sketch property (PAPERS.md: "Moment-Based Quantile
Sketches…", with the time/space cell-grid layout of "Sketch
Disaggregation Across Time and Space"). Time buckets are
RING-indexed: absolute bucket ``a = ts_first // window_us`` lives at
slot ``a % W`` stamped with ``a`` in the epoch array, so a stale slot
self-clears the first time a newer bucket lands on it — no sweep.

Quantization (why integers, not the paper's floats): every cell field
is an int32/int64 accumulated by scatter-add/-max, so device cells and
the numpy mirror twins agree BITWISE regardless of accumulation order
(float sums would diverge between XLA scatter order and np.add.at).
``x`` is the span duration's ``ops.quantile.bucket_index`` in the
store's log-histogram geometry, right-shifted so x < 2^MAX_X_BITS:
moments of x are log-duration moments up to a known affine map, which
is exactly the paper's log-transform for long-tailed data, and the
shift bounds ``Σx⁴`` so a cell holds ~1e8 worst-case spans before
int64 overflow (documented in docs/OBSERVABILITY.md).

Reads solve the classic maximum-entropy problem over the cell's
bounded integer support (min_x..max_x): Newton iterations on a
Chebyshev-basis exponential-family density, with a Gaussian
(moment-matched) fallback when the solve degenerates. Quantile error
is a RANK-space tolerance (``SOLVER_RANK_TOL``), the paper's metric —
cell SUMS are exact (bitwise vs any oracle using the same
quantization); only the density reconstruction is approximate.

Everything here is pure numpy — it runs identically against the
host mirror twins (store/mirror.SketchMirror) and against
device-fetched arrays, which is what the bitwise gates compare.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Sequence

import numpy as np

from zipkin_tpu.store.archive.sketches import hist_bucket_index

I32_MIN = np.int32(-(1 << 31))
# Cell layout widths (the k axis of the three state arrays).
N_COUNT_FIELDS = 3  # total, err, n(duration-carrying)
N_SUM_FIELDS = 4  # Σx, Σx², Σx³, Σx⁴
N_MM_FIELDS = 2  # max(-x) (i.e. -min x), max(x)
# x < 2^MAX_X_BITS after the shift: Σx⁴ < n · 2^36, so an int64 cell
# sum is exact to ~1.3e8 spans per (service, bucket) cell even with
# every span in the top duration bucket.
MAX_X_BITS = 9
# Documented solver tolerance: the maxent quantile estimate's CDF rank
# at the true distribution is within this of the requested q (the
# Moments-sketch paper's ε_avg metric; tests/test_windows.py gates it).
SOLVER_RANK_TOL = 0.10

DEFAULT_BURN_WINDOWS_S = (300, 1800, 3600, 21600)
DEFAULT_OBJECTIVE = 0.999
DEFAULT_HEATMAP_BANDS = 12


def win_x_shift(quantile_buckets: int) -> int:
    """Right-shift applied to the fine histogram bucket index so the
    window cells' x domain stays under 2^MAX_X_BITS."""
    return max(0, (quantile_buckets - 1).bit_length() - MAX_X_BITS)


def duration_x(durations, quantile_buckets: int, gamma: float) -> np.ndarray:
    """Quantized log-duration (int32): fine bucket index >> shift.
    The fine index is the float32 twin the mirror already shares with
    the device (archive.sketches.hist_bucket_index)."""
    fine = hist_bucket_index(durations, quantile_buckets, gamma, 1.0)
    return (fine >> win_x_shift(quantile_buckets)).astype(np.int32)


def x_to_duration(x: float, gamma: float, shift: int,
                  min_value: float = 1.0) -> float:
    """Geometric midpoint of coarse bucket ``x`` in µs — the same
    bucket→value convention as ops.quantile.quantiles_host, at the
    coarse bucket's center fine index."""
    if x <= 0:
        return float(min_value)
    fine = x * (1 << shift) + ((1 << shift) - 1) / 2.0
    return float(min_value * gamma ** fine * (2.0 / (1.0 + gamma)))


def x_edge_duration(x: float, gamma: float, shift: int,
                    min_value: float = 1.0) -> float:
    """LOWER boundary (µs) of coarse bucket ``x`` — heatmap band
    edges, vs the midpoint convention quantiles report."""
    if x <= 0:
        return float(min_value)
    return float(min_value * gamma ** (x * (1 << shift)))


# -- error spans -------------------------------------------------------------


def error_ids(dicts) -> tuple:
    """(annotation-value id, binary-key id) of the "error" convention
    strings, -1 when never interned. Deterministic given dictionary
    state, so WAL replay recomputes identical flags."""
    ea = dicts.annotations.get("error")
    eb = dicts.binary_keys.get("error")
    return (-1 if ea is None else int(ea), -1 if eb is None else int(eb))


def span_error_flags(batch, err_ann_id: int, err_bann_id: int) -> np.ndarray:
    """Per-span bool: carries an annotation valued "error" or a binary
    annotation keyed "error" (the zipkin error convention). Pure
    function of the encoded SpanBatch — stage 1 computes it once for
    the device batch and once for the mirror delta, identically."""
    flags = np.zeros(batch.n_spans, bool)
    if err_ann_id >= 0 and batch.n_annotations:
        sel = batch.ann_value_id[: batch.n_annotations] == err_ann_id
        flags[batch.ann_span_idx[: batch.n_annotations][sel]] = True
    if err_bann_id >= 0 and batch.n_binary:
        sel = batch.bann_key_id[: batch.n_binary] == err_bann_id
        flags[batch.bann_span_idx[: batch.n_binary][sel]] = True
    return flags


# -- stage-1 planning + the numpy fold (the device step's twin) --------------


class WindowUpdate(NamedTuple):
    """One launch CHUNK's pre-masked window rows (COO). Chunks must
    fold in launch order: the epoch war + stale-clear is stateful, and
    a chained group runs one device step per chunk."""

    svc: np.ndarray  # int32 [N]
    bucket: np.ndarray  # int64 [N] — absolute time bucket
    x: np.ndarray  # int32 [N]; -1 = span carries no duration
    err: np.ndarray  # bool [N]


def plan_window_update(batch, error_flags, config) -> WindowUpdate:
    """The mirror twin of the device masking: rows with a
    representable owning service and a timestamp. Pure host function
    (stage 1)."""
    n = batch.n_spans
    svc = np.asarray(batch.service_id[:n], np.int64)
    tsf = np.asarray(batch.ts_first[:n], np.int64)
    ok = (svc >= 0) & (svc < config.max_services) & (tsf >= 0)
    dur = np.asarray(batch.duration[:n], np.int64)
    gamma = (1.0 + config.quantile_alpha) / (1.0 - config.quantile_alpha)
    x = duration_x(dur, config.quantile_buckets, gamma)
    x = np.where(dur >= 0, x, np.int32(-1))
    bucket = tsf // np.int64(config.window_us)
    err = np.asarray(error_flags, bool)[:n]
    return WindowUpdate(
        svc[ok].astype(np.int32), bucket[ok], x[ok], err[ok]
    )


def apply_window_update(u: WindowUpdate, epoch: np.ndarray,
                        counts: np.ndarray, sums: np.ndarray,
                        mm: np.ndarray) -> tuple:
    """Fold one chunk's rows into the (epoch, counts, sums, mm) arena
    IN PLACE — integer-for-integer what the device step does, so
    mirror cells match device cells bitwise. Returns (spans, errors)
    folded (the zipkin_window_* counters)."""
    W = epoch.shape[0]
    if u.svc.size == 0:
        return 0, 0
    slot = (u.bucket % W).astype(np.int64)
    new_epoch = epoch.copy()
    np.maximum.at(new_epoch, slot, u.bucket)
    stale = new_epoch != epoch
    if stale.any():
        counts[:, stale, :] = 0
        sums[:, stale, :] = 0
        mm[:, stale, :] = I32_MIN
    epoch[:] = new_epoch
    live = u.bucket == new_epoch[slot]
    svc = u.svc[live].astype(np.int64)
    cid = svc * W + slot[live]
    np.add.at(counts.reshape(-1), cid * N_COUNT_FIELDS, np.int32(1))
    err = u.err[live]
    np.add.at(counts.reshape(-1), cid[err] * N_COUNT_FIELDS + 1,
              np.int32(1))
    x = u.x[live]
    d = x >= 0
    cid_d = cid[d]
    np.add.at(counts.reshape(-1), cid_d * N_COUNT_FIELDS + 2,
              np.int32(1))
    xi = x[d].astype(np.int64)
    flat_sums = sums.reshape(-1)
    base = cid_d * N_SUM_FIELDS
    np.add.at(flat_sums, base, xi)
    np.add.at(flat_sums, base + 1, xi * xi)
    np.add.at(flat_sums, base + 2, xi * xi * xi)
    np.add.at(flat_sums, base + 3, xi * xi * xi * xi)
    flat_mm = mm.reshape(-1)
    x32 = x[d].astype(np.int32)
    np.maximum.at(flat_mm, cid_d * N_MM_FIELDS, -x32)
    np.maximum.at(flat_mm, cid_d * N_MM_FIELDS + 1, x32)
    return int(live.sum()), int(err.sum())


# -- merged-cell reads -------------------------------------------------------


class WindowSum(NamedTuple):
    """A merged (service × bucket-range) Moments-sketch cell."""

    total: int
    err: int
    n: int
    s1: int
    s2: int
    s3: int
    s4: int
    min_x: int
    max_x: int

    @property
    def error_rate(self) -> float:
        return (self.err / self.total) if self.total else 0.0


def live_slots(epoch: np.ndarray, b0: int, b1: int) -> np.ndarray:
    """Ring slots whose stamped absolute bucket lies in [b0, b1]."""
    return np.flatnonzero((epoch >= b0) & (epoch <= b1))


def merge_cells(epoch: np.ndarray, counts_row: np.ndarray,
                sums_row: np.ndarray, mm_row: np.ndarray,
                b0: int, b1: int) -> WindowSum:
    """Sum one service's live cells over absolute buckets [b0, b1] —
    the O(1)-per-cell vector-add merge that makes any ad-hoc window a
    cell-sum instead of a segment scan. Row arrays are [W, k] (the
    mirror's ``window_row`` slices)."""
    slots = live_slots(epoch, b0, b1)
    if slots.size == 0:
        return WindowSum(0, 0, 0, 0, 0, 0, 0, 0, 0)
    c = counts_row[slots, :].astype(np.int64).sum(axis=0)
    s = sums_row[slots, :].sum(axis=0)
    m = mm_row[slots, :]
    have = counts_row[slots, 2] > 0
    if have.any():
        min_x = int(-m[have, 0].max())
        max_x = int(m[have, 1].max())
    else:
        min_x = max_x = 0
    return WindowSum(int(c[0]), int(c[1]), int(c[2]),
                     int(s[0]), int(s[1]), int(s[2]), int(s[3]),
                     min_x, max_x)


def cell_sums(slots: np.ndarray, counts_row, sums_row, mm_row):
    """Per-slot WindowSum list (heatmap columns)."""
    out = []
    for w in np.asarray(slots, np.int64):
        c = counts_row[w, :]
        s = sums_row[w, :]
        n = int(c[2])
        out.append(WindowSum(
            int(c[0]), int(c[1]), n,
            int(s[0]), int(s[1]), int(s[2]), int(s[3]),
            int(-mm_row[w, 0]) if n else 0,
            int(mm_row[w, 1]) if n else 0,
        ))
    return out


# -- maximum-entropy density reconstruction ----------------------------------


def _power_moments(ws: WindowSum) -> np.ndarray:
    """E[x^k] for k = 0..4 (float64)."""
    n = float(ws.n)
    return np.array([1.0, ws.s1 / n, ws.s2 / n, ws.s3 / n, ws.s4 / n])


def _cheb_recurrence(u: np.ndarray, k: int) -> np.ndarray:
    """[k+1, len(u)] Chebyshev T_0..T_k on points u ∈ [-1, 1]."""
    T = np.empty((k + 1, u.shape[0]))
    T[0] = 1.0
    if k >= 1:
        T[1] = u
    for i in range(2, k + 1):
        T[i] = 2.0 * u * T[i - 1] - T[i - 2]
    return T


def maxent_pmf(ws: WindowSum) -> Optional[tuple]:
    """(support xs, pmf) solving the 4-moment maximum-entropy problem
    over the integer support [min_x, max_x] (the Moments-sketch
    solver, discrete form): Newton on the dual potential in a
    Chebyshev basis, Gaussian moment-matched fallback when the solve
    degenerates. Deterministic (no randomness)."""
    if ws.n <= 0:
        return None
    if ws.max_x <= ws.min_x:
        return np.array([ws.min_x]), np.array([1.0])
    xs = np.arange(ws.min_x, ws.max_x + 1, dtype=np.int64)
    c = 0.5 * (ws.min_x + ws.max_x)
    h = 0.5 * (ws.max_x - ws.min_x)
    m = _power_moments(ws)
    # E[u^k] via binomial expansion of ((x - c)/h)^k.
    mu = np.zeros(5)
    for k in range(5):
        acc = 0.0
        for j in range(k + 1):
            acc += (math.comb(k, j) * ((-c) ** (k - j)) * m[j])
        mu[k] = acc / (h ** k)
    # Chebyshev targets from normalized power moments.
    t = np.array([
        mu[1],
        2.0 * mu[2] - 1.0,
        4.0 * mu[3] - 3.0 * mu[1],
        8.0 * mu[4] - 8.0 * mu[2] + 1.0,
    ])
    u = (xs - c) / h
    T = _cheb_recurrence(u, 4)[1:]  # [4, n] — T_1..T_4
    theta = np.zeros(4)

    def density(th):
        z = th @ T
        z -= z.max()
        p = np.exp(z)
        return p / p.sum()

    converged = False
    for _ in range(60):
        p = density(theta)
        e = T @ p
        grad = e - t
        if np.abs(grad).max() < 1e-9:
            converged = True
            break
        cov = (T * p) @ T.T - np.outer(e, e)
        try:
            step = np.linalg.solve(cov + 1e-10 * np.eye(4), grad)
        except np.linalg.LinAlgError:
            break
        # Backtracking on the dual potential F(θ) = log Z(θ) - θ·t.
        def potential(th):
            z = th @ T
            zm = z.max()
            return zm + math.log(np.exp(z - zm).sum()) - th @ t

        f0 = potential(theta)
        scale = 1.0
        for _bt in range(25):
            cand = theta - scale * step
            if potential(cand) < f0:
                theta = cand
                break
            scale *= 0.5
        else:
            break
    else:
        converged = np.abs(T @ density(theta) - t).max() < 1e-5
    p = density(theta)
    if not converged or not np.isfinite(p).all():
        # Gaussian moment-matched fallback on the same support.
        mean = m[1]
        var = max(m[2] - m[1] * m[1], 1e-12)
        z = -0.5 * (xs - mean) ** 2 / var
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
    return xs, p


def quantiles_from_sums(ws: WindowSum, qs: Sequence[float],
                        gamma: float, shift: int) -> Optional[list]:
    """Quantile estimates (µs) from one merged cell: maxent pmf →
    CDF inversion → coarse-bucket geometric midpoint. None when the
    window holds no duration-carrying span."""
    solved = maxent_pmf(ws)
    if solved is None:
        return None
    xs, p = solved
    cdf = np.cumsum(p)
    out = []
    for q in qs:
        i = int(np.searchsorted(cdf, min(max(q, 0.0), 1.0) - 1e-12))
        i = min(i, xs.shape[0] - 1)
        out.append(x_to_duration(float(xs[i]), gamma, shift))
    return out


def band_edges_x(min_x: int, max_x: int, bands: int) -> np.ndarray:
    """Integer band edges (len bands+1) covering [min_x, max_x+1) —
    the duration axis of the heatmap, even in log space because x
    already is log-duration."""
    bands = max(1, int(bands))
    edges = np.unique(np.round(
        np.linspace(min_x, max_x + 1, bands + 1)).astype(np.int64))
    if edges.shape[0] < 2:
        edges = np.array([min_x, max_x + 1], np.int64)
    return edges


def band_masses(ws: WindowSum, edges: np.ndarray) -> np.ndarray:
    """Expected span count per duration band for one cell: pmf mass
    within each [edges[i], edges[i+1]) times the cell count."""
    out = np.zeros(edges.shape[0] - 1)
    solved = maxent_pmf(ws)
    if solved is None:
        return out
    xs, p = solved
    idx = np.clip(np.searchsorted(edges, xs, side="right") - 1, 0,
                  out.shape[0] - 1)
    np.add.at(out, idx, p * ws.n)
    return out
