"""Checkpoint/restore for the device store (durability).

The reference's durability IS its storage backend (Cassandra TTLs,
CassieSpanStore.scala:47-48); the TPU store's state lives in HBM, so
durability is an explicit snapshot: device state pytree → host npz +
dictionaries/TTL map → json. Restore rebuilds an equivalent
TpuSpanStore (SURVEY.md §5 checkpoint/resume).

Snapshots are atomic (write to a temp dir, rename) so a crash mid-save
leaves the previous checkpoint intact.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Optional

import jax
import numpy as np

from zipkin_tpu.columnar.dictionary import DictionarySet
from zipkin_tpu.columnar.encode import SpanCodec
from zipkin_tpu.store import device as dev
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.testing.crash import kill_point

_STATE_FILE = "state.npz"
_META_FILE = "meta.json"
_PINS_FILE = "pins.pkl"
# Bump when the StoreState schema changes in a way load() must adapt to.
# 7: span_tab empty sentinel 0 → _TAB_EMPTY (deterministic min-insert);
#    ann_poison middle-host trust array added.
# 8: key_claim_drops counter added — the negative-lookup gate's proof
#    obligation. Snapshots predating it never counted drops, so their
#    restores must keep the gate OFF (drops forced >= 1).
# 9: key_tab stores i32 fingerprints instead of exact i64 key words
#    (the i64 claim war serialized on TPU; see device._index_write).
#    Older tables are tombstoned on restore and the drop floor above
#    extends to revision 8 snapshots.
# 10: trace-membership depths doubled (32/64/32 -> 64/128/64, 4x-ring
#    coverage — 2x measurably let Poisson trace-clumping wrap 13-30% of
#    buckets per lap). Older snapshots carry half-size tr_idx arrays,
#    so their trace families restore poisoned (scan serves) instead of
#    silently misaligned.
# 11: the trace-membership families merged into the candidate arena
#    (one [slots, 3] entry array + one cursor/watermark pair for all
#    seven families — tr_idx/tr_pos/tr_wm no longer exist), candidate
#    ts watermarks war coarsely (stored values round UP to 2^20 µs —
#    still upper bounds, so old exact values restore compatibly), and
#    span_tab became [H, 2] i32 bit-planes (bitcast-identical; migrated
#    losslessly below). Pre-11 cand_*/tr_* arrays are dropped: the
#    candidate segment restores permanently untrusted (scan serves, the
#    pre-index treatment) while the trace segment seeds wm = write_pos
#    and self-heals after one ring lap.
# 12: cold-tier archive (store/archive): saving a TieredSpanStore adds
#    meta["archive"] (sketch params + captured-gid watermark + segment
#    manifest) and one immutable blob per segment under ``segments/``;
#    load() rebuilds the TieredSpanStore around the restored device
#    store and re-aligns the capture clocks with one capture_now()
#    flush. Snapshots without the key restore plain stores unchanged,
#    and pre-12 loaders simply ignore the extra files.
# 13: durability (zipkin_tpu.wal): single-device snapshots add
#    meta["clocks"] — the host pacing mirrors (write/capture/sweep/
#    archive clocks, sealed frontier) plus the last-applied WAL
#    sequence — making restore EXACT instead of re-seeded ("just
#    rotated" / capture_now flush), which is what lets WAL replay
#    land a bitwise-identical state; and meta["slab_crc32"] — a CRC32
#    per state leaf, verified on restore (CorruptSlabError) so a
#    rotted slab fails fast instead of feeding garbage into
#    device_put. Pre-13 snapshots restore exactly as before (clocks
#    re-seeded, no CRC check); pre-13 loaders ignore both keys.
# 14: windowed Moments-sketch arena (aggregate/windows.py): four new
#    state leaves — win_epoch / win_counts / win_sums / win_mm, the
#    (service × ring-indexed time bucket) integer cell grid — ride the
#    generic leaf save/restore. Pre-14 snapshots simply lack the keys,
#    so they restore with an EMPTY arena at init defaults (windowed
#    answers cover post-restore ingest only — correct, since the ring
#    retains at most window_seconds × window_buckets anyway); the
#    sketch-mirror cold resync below already re-adopts the window
#    twins with the other aggregates. Pre-14 loaders drop the unknown
#    leaves via the `known` filter.
# 18: paged span layout (store/paged): snapshots of a paged store add
#    meta["paged"] — the host page allocator + per-trace page-table
#    snapshot, including the recent claim-plan memo keyed by WAL seq
#    (the pipelined-save window: units planned ahead of the gathered
#    device frontier replay from recorded claims, not re-planning).
#    The StoreState leaf schema is UNCHANGED — the paged layout reuses
#    the ring arenas with epoch-encoded gids — so pre-18 ring
#    snapshots restore exactly as before (StoreConfig defaults fill
#    layout="ring"), and a paged store restoring a snapshot WITHOUT
#    the key rebuilds its page table from the resident row_gid /
#    trace_id columns (PagePlanner.rebuild; partial pages stay
#    closed). Revisions 15-17 were consumed by the replication /
#    sharded-serving line (sharded clocks, fleet WAL shipping); their
#    snapshots restore through the same revision-tolerant key checks.
_REVISION = 18
_SEGMENTS_DIR = "segments"


class CorruptSlabError(RuntimeError):
    """A checkpoint state slab failed its manifest CRC32 — the
    snapshot is damaged (torn copy, disk rot, or mixed cuts). Restore
    refuses to feed the corrupt leaf to the device; recover from the
    ``.old`` snapshot or an earlier checkpoint plus the WAL."""


def _slab_crc(arr) -> int:
    """CRC32 over a leaf's raw C-order bytes (dtype/shape are pinned
    by the npy header, so content bytes are the integrity surface)."""
    import zlib

    a = np.ascontiguousarray(arr)
    return zlib.crc32(memoryview(a).cast("B"))


def _host_clocks(store) -> Optional[dict]:
    """The single-device store's host pacing clocks, captured under
    the same read lock as the state gather (the mirrors advance inside
    the commit's write-lock hold, so this pair is exact)."""
    if not hasattr(store, "_cap_upto"):
        return None
    # The capture clocks are _cap_lock-guarded, but taking _cap_lock
    # HERE (under the gather's read lock) would invert the canonical
    # _cap_lock(30) -> _rw(40) order — the capture pull holds the
    # capture lock while acquiring the read lock, and a reader-
    # triggered pending sweep is a WRITER, so the inversion is a real
    # deadlock triangle (graftlint lock-order). Instead save() relies
    # on its quiesce protocol: the pipeline is drained, the seal
    # barrier ran under this same read-lock hold, GIL-atomic int reads
    # can't tear, and restore's min(cap_upto, sealed_upto) tolerates
    # the one benign race left (a serial writer stamping clocks before
    # it reaches the write lock).
    return {
        "wp": int(store._wp),
        "awp": int(store._awp),
        "bwp": int(store._bwp),
        "archived": int(store._archived),
        "batches_since_sweep": int(store._batches_since_sweep),
        "cap_upto": int(store._cap_upto),  # graftlint: disable=guarded-by
        "cap_a": int(store._cap_a),  # graftlint: disable=guarded-by
        "cap_b": int(store._cap_b),  # graftlint: disable=guarded-by
        "sealed_upto": int(store._sealed_upto),  # graftlint: disable=guarded-by
        "wal_applied": int(getattr(store, "_wal_applied", 0)),
    }


def _sharded_clocks(store) -> Optional[dict]:
    """The sharded store's host pacing clocks, captured under the same
    read lock as the stacked-state gather. The top-level
    ``wal_applied`` key keeps save()'s WAL-truncation coordination
    identical across store kinds (a ShardedWal truncates by epoch
    sequence exactly as a WriteAheadLog does by record sequence)."""
    inner = getattr(store, "inner", None)
    if inner is None or not hasattr(inner, "_wp_upper"):
        return None
    return {
        "sharded": 1,
        "wp_upper": int(inner._wp_upper),
        "archived_lower": int(inner._archived_lower),
        "batches_since_sweep": int(inner._batches_since_sweep),
        "step_seq": int(getattr(store, "_step_seq", 0)),
        "wal_applied": int(getattr(store, "_wal_applied", 0)),
    }


def _dict_dump(d) -> list:
    # One entry codec shared with the WAL's dictionary deltas
    # (wal/record.py): replay equality-verifies restored entries
    # against delta values, so the two must never diverge.
    from zipkin_tpu.wal.record import dump_value

    return [dump_value(v) for v in d.values()]


def _dict_load(dictionary, values: list) -> None:
    from zipkin_tpu.wal.record import load_value

    for item in values:
        dictionary.encode(load_value(item))


def _savez_fast(path: str, leaves: dict) -> None:
    """npz-compatible writer at deflate level 1. np.savez_compressed is
    hardwired to zlib level 6 on one core — measured 177 s for a 412 MB
    snapshot of a 2^22-ring store; level 1 compresses the same state
    ~5x faster within a few percent of the size, and np.load reads any
    deflate-compressed zip member unchanged."""
    import zipfile

    with zipfile.ZipFile(path, "w", compression=zipfile.ZIP_DEFLATED,
                         compresslevel=1, allowZip64=True) as zf:
        for name, arr in leaves.items():
            with zf.open(name + ".npy", "w", force_zip64=True) as f:
                np.lib.format.write_array(
                    f, np.asanyarray(arr), allow_pickle=False
                )


_SLAB_BYTES = 64 << 20  # transfer granularity for big leaves
_GEN_FILE = "generation.json"


def _bounded_get(x, deadline_s: Optional[float]):
    """jax.device_get with a deadline. A wedged tunnel transfer is
    uninterruptible from Python (round 4: one 544 MB device_get hung
    >70 min after completing in ~6 min earlier the same day), so the
    fetch runs on an abandonable daemon thread; on timeout the thread
    is orphaned and TimeoutError raised — the caller retries or gives
    up, but never loses work already staged to disk."""
    if deadline_s is None:
        return jax.device_get(x)
    import threading

    box = {}

    def run():
        try:
            box["v"] = jax.device_get(x)
        except Exception as e:  # noqa: BLE001 — re-raised below
            box["e"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(deadline_s)
    if t.is_alive():
        err = TimeoutError(
            f"device_get exceeded {deadline_s:.0f}s (wedged transfer?)")
        # The abandoned thread may keep READING state buffers after the
        # caller's locks release; carry it so save() can stamp the store
        # suspect (store.base.SuspectGuard) and later joins can clear it.
        err.orphan = t
        raise err
    if "e" in box:
        raise box["e"]
    return box["v"]


def _fetch_leaf(arr, deadline_s, retries: int, stats: Optional[dict]):
    """Fetch one device leaf as slabs of <= _SLAB_BYTES (sliced on
    device along the leading axis), each slab under its own deadline.

    FAIL-FAST: the first slab timeout raises immediately (ADVICE r5
    #2). The old per-slab retry+backoff ran while save() held the
    writer-blocking read lock, and on a one-at-a-time tunnel the retry
    enqueues BEHIND the wedged transfer — it could never succeed until
    the wedge cleared, so every retry only extended the lock hold (and
    the ingest stall) by another deadline + backoff. The save now fails
    on the first timeout, the store is stamped suspect by the caller,
    and recovery is the staged resume: a retry of save() skips every
    leaf already on disk. ``retries`` is accepted for call-site
    compatibility and deliberately ignored."""
    import time

    del retries  # fail-fast: no in-lock retry, see docstring
    nbytes = arr.size * getattr(arr, "dtype", np.dtype(np.int64)).itemsize
    shape = getattr(arr, "shape", ())
    if deadline_s is None or not shape or nbytes <= _SLAB_BYTES:
        slabs = [arr]
    else:
        rows = shape[0]
        row_bytes = max(1, nbytes // max(rows, 1))
        step = max(1, _SLAB_BYTES // row_bytes)
        slabs = [arr[i:i + step] for i in range(0, rows, step)]
    out = []
    for slab in slabs:
        t0 = time.perf_counter()
        try:
            h = _bounded_get(slab, deadline_s)
        except TimeoutError:
            if stats is not None:
                stats["slab_timeouts"] = stats.get("slab_timeouts",
                                                   0) + 1
            raise
        dt = time.perf_counter() - t0
        h = np.asarray(h)
        if stats is not None:
            stats["slabs"] = stats.get("slabs", 0) + 1
            stats["bytes"] = stats.get("bytes", 0) + h.nbytes
            stats["slab_s"] = stats.get("slab_s", 0.0) + dt
            mbps = h.nbytes / 1e6 / max(dt, 1e-9)
            stats["mb_per_s_min"] = round(min(
                stats.get("mb_per_s_min", mbps), mbps), 2)
            stats["mb_per_s_max"] = round(max(
                stats.get("mb_per_s_max", mbps), mbps), 2)
        out.append(h)
    return out[0] if len(out) == 1 else np.concatenate(out, axis=0)


def _state_generation(store, n_shards, deadline_s) -> list:
    """A cheap scalar fingerprint of the device state's write history:
    equal generations mean no ingest/sweep/archive touched the state
    between two save attempts, so staged leaves from the earlier
    attempt are still a consistent cut and may be reused."""
    state = store.states if n_shards else store.state
    gen = {
        "write_pos": state.write_pos,
        "ann_write_pos": state.ann_write_pos,
        "bann_write_pos": state.bann_write_pos,
        "pend_pos": state.pend_pos,
        "dep_bank_seq": state.dep_bank_seq,
        "ts_max": state.ts_max,
        **{f"counters.{k}": v for k, v in state.counters.items()},
    }
    host = _bounded_get(gen, deadline_s)
    # Lists, not tuples: the fingerprint round-trips through JSON and
    # must compare equal to its own deserialization.
    return sorted(
        [k, np.asarray(v).reshape(-1).tolist()] for k, v in host.items()
    )


def _seal_barrier(store) -> None:
    """Wait for the store's async capture sealer (if any) to finish
    every pulled window — see the call sites in save() for why this
    must run under the state read lock."""
    barrier = getattr(store, "seal_barrier", None)
    if barrier is not None:
        barrier()


def save(store, path: str, chunk_deadline_s: Optional[float] = None,
         slab_retries: int = 1) -> dict:
    """Snapshot a TpuSpanStore OR a ShardedSpanStore to ``path`` (a
    directory), atomically. Sharded stores save their stacked
    [n_shards, ...] state; load() re-shards it over a mesh.

    With ``chunk_deadline_s`` set, the device→host gather is CHUNKED
    and RESUMABLE: each leaf transfers in <= 64 MB slabs, each under
    its own deadline (+ ``slab_retries`` re-requests), and completed
    leaves persist in a ``<path>.staging`` directory — if a degraded
    tunnel wedges a transfer, the failed save raises but a retry skips
    everything already staged (guarded by a state-generation
    fingerprint so a write between attempts discards the stage rather
    than mixing two cuts). Returns transfer stats (slab count/bytes/
    bandwidth, resumed leaf count)."""
    # Resident-query-executor quiesce (query/engine.py): wait for any
    # in-flight coalesced query launch to finish before the gather
    # begins, so the snapshot's device cut never interleaves with a
    # standing executor's batch mid-dispatch (the ordered-shutdown
    # contract: drain-queries → drain-pipeline → seal → gather).
    for eng in getattr(store, "query_engines", lambda: ())():
        eng.drain()
    # Same quiesce for the sharded store's cross-shard dispatcher — a
    # fused catalog/index launch mid-dispatch must finish before the
    # gather's cut.
    dispatcher = getattr(store, "dispatcher", None)
    if dispatcher is not None:
        dispatcher.drain()
    # A TieredSpanStore (store/archive) snapshots as its hot device
    # store plus the segment manifest; the segments themselves are
    # immutable host blobs, so they add host IO only — never device
    # transfer time under the read lock.
    tiered = (store if getattr(store, "archive", None) is not None
              and hasattr(store, "hot") else None)
    if tiered is not None:
        store = tiered.hot
    n_shards = getattr(store, "n", None) if hasattr(store, "states") else None
    # A PRIOR save's timeout may have left an orphaned transfer thread
    # still reading the state; a fresh consistent cut must not race it.
    # Give the orphan a short grace to finish, else refuse
    # (StoreSuspectError) — the same gate the donating write paths use.
    ensure = getattr(store, "ensure_writable", None)
    if ensure is not None and getattr(store, "suspect", False):
        ensure(wait_s=5.0)
    # Pipelined-ingest quiesce: batches accepted by apply() but still
    # in the prefetch/staging queues must land in this cut, or a
    # restore would silently drop them (the collector already counted
    # them stored). No-op for serial stores and shard stores.
    drain = getattr(store, "drain_pipeline", None)
    if drain is not None:
        drain()
    stats: dict = {"resumed_leaves": 0, "chunked": chunk_deadline_s
                   is not None}
    staging = os.path.abspath(path) + ".staging"
    leaves = {}
    if chunk_deadline_s is None:
        # Fast path (the default, e.g. the daemon's SIGTERM save): ONE
        # batched device_get of the whole pytree under the read lock —
        # per-leaf transfers and a staged double-write would be a pure
        # latency/IO regression for callers that never asked for
        # resumability. Ingest donates the previous state's buffers, so
        # the lock must cover the gather.
        with store._rw.read():
            # Capture-backlog quiesce, UNDER the read lock: any window
            # pulled before this point seals now; a window pulled
            # after cannot lose rows from this cut (its overwriting
            # write blocks on the write lock until the gather is done,
            # so the rows are still resident in the gathered state).
            _seal_barrier(store)
            # Host clocks under the SAME read lock as the gather: the
            # mirrors advance inside the commit's write-lock hold, so
            # (state, clocks, applied WAL seq) is one consistent cut —
            # the anchor deterministic replay resumes from.
            clocks = (_sharded_clocks(store) if n_shards
                      else _host_clocks(store))
            state = store.states if n_shards else store.state
            host_state = jax.device_get(state)
        for name in dev.StoreState._FIELDS:
            value = getattr(host_state, name)
            if name == "counters":
                for k, v in value.items():
                    leaves[f"counters.{k}"] = np.asarray(v)
            else:
                leaves[name] = np.asarray(value)
    else:
        # Chunked+resumable path. The read lock covers the whole
        # gather (consistent cut; writers block). On timeout the
        # orphaned transfer thread may still be reading state buffers
        # after the lock releases, so the store is STAMPED SUSPECT
        # below (ADVICE r5): donating ingest and the next save refuse
        # to run (StoreSuspectError) until the orphan is joined —
        # nothing relies on callers reading a docstring anymore.
        try:
            with store._rw.read():
                _seal_barrier(store)  # same argument as the fast path
                clocks = (_sharded_clocks(store) if n_shards
                          else _host_clocks(store))
                gen = _state_generation(store, n_shards,
                                        chunk_deadline_s)
                if os.path.isdir(staging):
                    try:
                        with open(os.path.join(staging, _GEN_FILE)) as f:
                            prior = json.load(f)
                    except (OSError, ValueError):
                        prior = None
                    if prior != gen:
                        shutil.rmtree(staging, ignore_errors=True)
                os.makedirs(staging, exist_ok=True)
                with open(os.path.join(staging, _GEN_FILE), "w") as f:
                    json.dump(gen, f)
                state = store.states if n_shards else store.state
                for name in dev.StoreState._FIELDS:
                    value = getattr(state, name)
                    items = ([(f"counters.{k}", v)
                              for k, v in value.items()]
                             if name == "counters" else [(name, value)])
                    for key, leaf in items:
                        dest = os.path.join(staging, key + ".npy")
                        if os.path.exists(dest):
                            stats["resumed_leaves"] += 1
                            continue
                        host = _fetch_leaf(leaf, chunk_deadline_s,
                                           slab_retries, stats)
                        tmp_leaf = dest + ".tmp"
                        with open(tmp_leaf, "wb") as f:
                            np.save(f, host, allow_pickle=False)
                        os.replace(tmp_leaf, dest)
        except TimeoutError as e:
            mark = getattr(store, "mark_suspect", None)
            if mark is not None:
                mark(getattr(e, "orphan", None))
            raise
        if stats.get("slab_s"):
            stats["mb_per_s_avg"] = round(
                stats["bytes"] / 1e6 / stats["slab_s"], 2)
        for fname in os.listdir(staging):
            if fname.endswith(".npy"):
                # mmap: the finalize zip streams straight from the
                # staged files instead of doubling the snapshot in RAM.
                leaves[fname[:-4]] = np.load(
                    os.path.join(staging, fname), mmap_mode="r",
                    allow_pickle=False)
    archive_meta = None
    seg_blobs = []
    # Paged layout (revision 18): snapshot the page allocator + page
    # table. plan_unit keys each claim plan to its WAL seq atomically
    # under the planner lock, so this cut is self-consistent at ANY
    # boundary: plans at seq <= the snapshot's last_seq replay from
    # the recorded memo; later ones re-derive deterministically.
    planner = getattr(store, "_planner", None)
    paged_meta = planner.snapshot() if planner is not None else None
    with store._lock:
        # Pinned traces' eviction-exempt banks must survive restarts —
        # the TTL alone restoring while the spans vanish would break the
        # retention contract pinning exists for (SpanStore.scala:66).
        # Pickled (not wire-encoded): both the JSON and thrift codecs
        # normalize bytes-vs-str values, and the bank must restore the
        # exact objects reads were returning before the restart.
        pins_snapshot = {
            tid: list(bank) for tid, bank in store.pins.items()
        }
        ttls_snapshot = {str(k): v for k, v in store.ttls.items()}
        if tiered is not None:
            # The manifest cuts at the SEALED frontier, not the pull
            # clock: with an async sealer, _cap_upto can run ahead of
            # the last appended segment, and claiming an unsealed
            # window would lose it on restore (restore re-captures
            # only [captured_upto, wp) from the restored rings).
            # Inline sealing keeps the two equal. ORDER MATTERS: the
            # clock reads come BEFORE the segment snapshot — segments
            # only grow, so every window sealed before the clock read
            # has its segment in the (later) snapshot; a pipelined
            # store's commit thread doesn't hold store._lock, and the
            # reverse order could claim a window sealed between the
            # two reads without shipping its segment. The segment
            # list may then cover gids PAST captured_upto — a harmless
            # superset (gid dedup), never a loss. Windows pulled after
            # save's under-lock seal barrier can't lose rows from this
            # cut either way: their overwriting writes blocked on the
            # write lock until the state gather finished, so the rows
            # are resident in the gathered ring state.
            # Unlocked clock reads, same justification (and same
            # lock-order constraint) as _host_clocks above: the min()
            # makes the cut safe against the one benign race.
            captured_upto = int(min(
                store._cap_upto,  # graftlint: disable=guarded-by
                getattr(store, "_sealed_upto",
                        store._cap_upto)))  # graftlint: disable=guarded-by
            segs = tiered.archive.snapshot()
            archive_meta = {
                "params": tiered.params._asdict(),
                "captured_upto": captured_upto,
                "segments": [
                    {"seg_id": s.seg_id, "gid_lo": s.gid_lo,
                     "gid_hi": s.gid_hi, "n_spans": s.n_spans,
                     "file": f"seg-{s.seg_id:08d}.bin"}
                    for s in segs
                ],
            }
            seg_blobs = [(f"seg-{s.seg_id:08d}.bin", s) for s in segs]
    meta = {
        "revision": _REVISION,
        "config": store.config._asdict(),
        "shards": n_shards,
        # Per-slab integrity: verified on restore (CorruptSlabError).
        # For staged leaves this re-reads the .npy files (host IO only,
        # never device time under a lock).
        "slab_crc32": {k: _slab_crc(v) for k, v in leaves.items()},
        "ttls": ttls_snapshot,
        "name_lc": {str(k): v for k, v in store._name_lc.items()},
        "dicts": {
            "services": _dict_dump(store.dicts.services),
            "span_names": _dict_dump(store.dicts.span_names),
            "annotations": _dict_dump(store.dicts.annotations),
            "binary_keys": _dict_dump(store.dicts.binary_keys),
            "binary_values": _dict_dump(store.dicts.binary_values),
            "endpoints": _dict_dump(store.dicts.endpoints),
        },
    }
    if archive_meta is not None:
        meta["archive"] = archive_meta
    if clocks is not None:
        meta["clocks"] = clocks
    if paged_meta is not None:
        meta["paged"] = paged_meta
    parent = os.path.dirname(os.path.abspath(path)) or "."
    tmp = tempfile.mkdtemp(prefix=".ckpt-", dir=parent)
    old = path + ".old"
    try:
        _savez_fast(os.path.join(tmp, _STATE_FILE), leaves)
        with open(os.path.join(tmp, _META_FILE), "w") as f:
            json.dump(meta, f)
        if seg_blobs:
            # Segments are immutable, so a blob already present in the
            # live snapshot CAN be hard-linked (or copied) instead of
            # re-serialized — per-save archive cost O(new segments),
            # not O(history). Reuse is gated on the blob's own header
            # matching the live segment (id + gid range + row count +
            # size), not the filename alone: a restored-older-copy
            # lineage can re-mint a seg id, and filename-only reuse
            # would silently link the WRONG bytes (the state leaves'
            # generation fingerprint guards the same staleness class).
            seg_dir = os.path.join(tmp, _SEGMENTS_DIR)
            os.makedirs(seg_dir)
            prev_dir = os.path.join(path, _SEGMENTS_DIR)
            for fname, seg in seg_blobs:
                dest = os.path.join(seg_dir, fname)
                prev = os.path.join(prev_dir, fname)
                if _segment_blob_matches(prev, seg):
                    try:
                        os.link(prev, dest)
                        stats["reused_segments"] = stats.get(
                            "reused_segments", 0) + 1
                        continue
                    except OSError:
                        try:
                            shutil.copyfile(prev, dest)
                            stats["reused_segments"] = stats.get(
                                "reused_segments", 0) + 1
                            continue
                        except OSError:
                            pass
                with open(dest, "wb") as f:
                    f.write(seg.to_bytes())
        if pins_snapshot:
            import pickle

            with open(os.path.join(tmp, _PINS_FILE), "wb") as f:
                pickle.dump(pins_snapshot, f)
        # Keep the previous checkpoint alive until the new one is in
        # place: path → path.old, tmp → path, then drop path.old. A crash
        # at any point leaves either path or path.old restorable (load()
        # falls back to path.old).
        shutil.rmtree(old, ignore_errors=True)
        if os.path.isdir(path):
            os.replace(path, old)
        # Crash-harness injection site (testing/crash.py): dying HERE
        # is the worst mid-swap moment — only ``path.old`` (or nothing,
        # on the first save) is restorable, and the WAL was not yet
        # truncated, so recovery must fall back + replay.
        kill_point("mid-checkpoint")
        os.replace(tmp, path)
        shutil.rmtree(old, ignore_errors=True)
        # The staged cut is fully inside the finalized snapshot now.
        del leaves
        shutil.rmtree(staging, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    # Checkpoint-coordinated WAL truncation: the finalized snapshot
    # (which includes the sealed cold-tier frontier — the seal barrier
    # ran under the gather's read lock) covers every record up to its
    # applied sequence, so those segments can go. Runs ONLY after the
    # rename landed — a failed save never shrinks the log.
    wal = getattr(store, "wal", None)
    if wal is not None and clocks is not None:
        stats["wal_truncated_segments"] = wal.truncate(
            int(clocks["wal_applied"]))
    return stats


def _segment_blob_matches(blob_path: str, seg) -> bool:
    """True iff the blob at ``blob_path`` has the SAME identity header
    as the live segment — a header-only read (~1 KB), never the full
    blob. See the reuse note in save()."""
    import struct

    try:
        with open(blob_path, "rb") as f:
            head = f.read(9)
            if head[:5] != b"ZSEG1":
                return False
            (hlen,) = struct.unpack(">I", head[5:9])
            if hlen > 1 << 22:
                return False
            header = json.loads(f.read(hlen).decode("utf-8"))
    except (OSError, ValueError, struct.error):
        return False
    return (
        header.get("seg_id") == seg.seg_id
        and header.get("gid_lo") == seg.gid_lo
        and header.get("gid_hi") == seg.gid_hi
        and header.get("n_spans") == seg.n_spans
        and header.get("comp_bytes") == seg.comp_bytes
    )


def exists(path) -> bool:
    """True when ``load(path)`` has a snapshot to restore — the
    directory itself, or the ``.old`` fallback a crash mid-swap leaves
    behind. The ONE restorability predicate (example.py's boot and
    wal/recovery.recover share it): a boot path that only checked
    ``path`` would build a FRESH store after a mid-swap crash and
    replay the WAL tail against empty dictionaries."""
    return bool(path) and (os.path.isdir(path)
                           or os.path.isdir(path + ".old"))


def load(path: str, mesh=None, config_defaults=None):
    """Restore a store from a snapshot directory (falling back to the
    ``.old`` snapshot if a save crashed mid-swap).

    Single-device snapshots restore a TpuSpanStore. Sharded snapshots
    (saved from a ShardedSpanStore) restore a ShardedSpanStore over
    ``mesh`` — or a mesh built from the first n visible devices when
    not given; the shard count must match the snapshot's.

    ``config_defaults`` fills config keys the snapshot's meta does NOT
    carry (a knob newer than the snapshot's revision) — keys present
    in the meta always win, since the saved leaves were shaped by
    them. The daemon passes its --window-seconds/--window-buckets here
    so a pre-rev-14 snapshot restores with an EMPTY window arena at
    the flag geometry instead of silently disabling the feature."""
    if not os.path.isdir(path) and os.path.isdir(path + ".old"):
        path = path + ".old"
    with open(os.path.join(path, _META_FILE)) as f:
        meta = json.load(f)
    cfg_map = dict(meta["config"])
    for k, v in (config_defaults or {}).items():
        cfg_map.setdefault(k, v)
    config = dev.StoreConfig(**cfg_map)

    dicts = DictionarySet.__new__(DictionarySet)
    from zipkin_tpu.columnar.dictionary import Dictionary
    from zipkin_tpu.models.constants import (
        CORE_ANNOTATION_IDS,
        FIRST_USER_ANNOTATION_ID,
    )

    dicts.services = Dictionary()
    dicts.span_names = Dictionary()
    dicts.annotations = Dictionary(reserved=dict(CORE_ANNOTATION_IDS))
    dicts.binary_keys = Dictionary()
    dicts.binary_values = Dictionary()
    dicts.endpoints = Dictionary()
    d = meta["dicts"]
    # Annotation dict dump includes the reserved entries; replay in order.
    for name in ("services", "span_names", "binary_keys",
                 "binary_values", "endpoints"):
        _dict_load(getattr(dicts, name), d[name])
    ann = Dictionary()
    _dict_load(ann, d["annotations"])
    dicts.annotations = ann

    n_shards = meta.get("shards")
    if n_shards:
        from jax.sharding import Mesh

        from zipkin_tpu.parallel.shard import ShardedSpanStore

        if mesh is None:
            devices = jax.devices()
            if len(devices) < n_shards:
                raise ValueError(
                    f"snapshot has {n_shards} shards but only "
                    f"{len(devices)} devices are visible"
                )
            mesh = Mesh(np.array(devices[:n_shards]),
                        axis_names=("shard",))
        if "shard" not in mesh.shape:
            raise ValueError(
                f"mesh must have a 'shard' axis (ShardedSpanStore's "
                f"axis); got axes {tuple(mesh.shape)}"
            )
        if mesh.shape["shard"] != n_shards:
            raise ValueError(
                f"snapshot has {n_shards} shards; mesh has "
                f"{mesh.shape['shard']}"
            )
        store = ShardedSpanStore(mesh, config, codec=SpanCodec(dicts))
    else:
        store = TpuSpanStore(config, codec=SpanCodec(dicts))
    store.ttls = {int(k): v for k, v in meta["ttls"].items()}
    store._name_lc = {int(k): v for k, v in meta["name_lc"].items()}
    pins_path = os.path.join(path, _PINS_FILE)
    if os.path.exists(pins_path):
        import pickle

        with open(pins_path, "rb") as f:
            for tid, bank in pickle.load(f).items():
                store.pins.pin(int(tid), bank)

    data = np.load(os.path.join(path, _STATE_FILE))
    # Slab integrity (revision 13): every leaf checks against its
    # manifest CRC32 BEFORE anything reaches device_put — a rotted
    # slab is a named, immediate failure, not device garbage. Pre-13
    # snapshots carry no CRCs and skip the check.
    crcs = meta.get("slab_crc32") or {}

    def _leaf(key):
        arr = np.asarray(data[key])
        want = crcs.get(key)
        if want is not None and _slab_crc(arr) != int(want):
            raise CorruptSlabError(
                f"checkpoint slab '{key}' fails its manifest CRC32 — "
                f"snapshot at {path} is damaged; restore from the "
                f".old snapshot or an earlier checkpoint + WAL replay"
            )
        return arr

    upd = {}
    # Counters the snapshot predates keep their init defaults — the
    # schema may grow counters (e.g. key_claim_drops) and ingest
    # addresses them by name.
    base_state = store.inner.states if n_shards else store.state
    counters = dict(base_state.counters)
    for key in data.files:
        if key.startswith("counters."):
            counters[key.split(".", 1)[1]] = jax.numpy.asarray(
                _leaf(key))
        else:
            upd[key] = jax.numpy.asarray(_leaf(key))
    # Drop snapshot counters the current schema no longer carries.
    counters = {
        k: v for k, v in counters.items() if k in base_state.counters
    }
    if meta.get("revision", 1) < 9:
        # Pre-rev-8 stores never counted key-claim drops, and rev-8
        # tables stored exact key words that the rev-9 fingerprint
        # schema tombstones on restore (see below): either way a key
        # may have bucket entries but no record, which the negative-
        # lookup gate would misread as "never indexed". Force the gate
        # off for the restored store's lifetime.
        counters["key_claim_drops"] = jax.numpy.maximum(
            jax.numpy.asarray(counters["key_claim_drops"],
                              jax.numpy.int64),
            jax.numpy.int64(1),
        )
    upd["counters"] = counters
    # Leaves the current schema no longer carries (e.g. the r2 watermark
    # dep_archived_gid, retired with the streaming hash join) are
    # dropped; leaves the snapshot predates (span_tab, pending ring,
    # dep_window) keep their init_state defaults — the table rebuilds as
    # new spans arrive, and any SAVED state's links were already folded
    # into dep_moments/dep_banks by the pre-upgrade archive policy.
    known = set(dev.StoreState._FIELDS)
    revision = meta.get("revision", 1)
    legacy = revision < 4
    if revision < 11:
        # Revision 11 merged every index family into ONE arena: a
        # pre-11 cand_idx/cand_pos/cand_wm (candidate families only)
        # or tr_idx/tr_pos/tr_wm (gone from the schema) would misalign
        # against the unified slot math while its cursors still claimed
        # exactness. Drop the stale arrays and poison trust per
        # segment:
        # - candidate prefix: cursor past depth + wm at +inf — the
        #   ts-watermark gate has no eviction-horizon analogue to heal
        #   through, so restored candidate queries scan for the store's
        #   remaining lifetime (the pre-index snapshot treatment);
        # - trace suffix: wm seeds at the restore-time write_pos, NOT
        #   +inf — wm = wp claims "any restored-era gid may have been
        #   displaced", which the displaced-gid gate (wm < write_pos -
        #   capacity) re-opens after one full ring lap, once every
        #   restored span is evicted and the fresh entries are
        #   authoritative (ann_poison's self-healing pattern).
        for k in ("tr_idx", "tr_pos", "tr_wm",
                  "cand_idx", "cand_pos", "cand_wm"):
            upd.pop(k, None)
        n_total = config.idx_layout[1]
        n_cand = config.cand_layout[1]
        shape = (n_total,)
        if n_shards:
            shape = (n_shards,) + shape  # stacked sharded state
        big = jax.numpy.int64(1) << 60
        upd["cand_pos"] = jax.numpy.full(shape, big, jax.numpy.int64)
        is_cand = jax.numpy.arange(n_total) < n_cand
        wp = upd.get("write_pos")
        if wp is None:
            tr_seed = jax.numpy.full(shape, dev.I64_MAX,
                                     jax.numpy.int64)
        else:
            wp = jax.numpy.asarray(wp, jax.numpy.int64)
            if n_shards:
                wp = wp.reshape((-1, 1))  # [n_shards] -> broadcastable
            tr_seed = jax.numpy.broadcast_to(wp, shape)
        upd["cand_wm"] = jax.numpy.where(
            is_cand, jax.numpy.int64(dev.I64_MAX), tr_seed
        )
    if revision < 9 and "key_tab" in upd:
        # Revisions < 9 stored exact 64-bit key words; the table is now
        # 31-bit fingerprints (i32). The packed words are recoverable
        # (fp31 of the stored key48), but the claim-is-first-record
        # invariant can't be re-certified across the schema change, so
        # tombstone the table (INT32_MIN: unclaimable, matches no
        # fingerprint) and let load()'s pre-rev-8 drop-counter floor
        # keep the negative gate off; bucket gates serve as before.
        upd["key_tab"] = jax.numpy.full(
            np.asarray(upd["key_tab"]).shape, dev._FP_TOMB,
            jax.numpy.int32,
        )
        if "key_wm" in upd:
            upd["key_wm"] = jax.numpy.full(
                np.asarray(upd["key_wm"]).shape, dev.I64_MAX,
                jax.numpy.int64,
            )
    # Snapshots predating (parts of) the index families — or carrying
    # the pre-unification per-family layout — would restore empty
    # buckets whose zero cursors claim completeness, hiding every
    # restored span from the fast paths. Poison index trust so the
    # exact scan kernels serve instead (load() applies below).
    pre_index = revision < 6
    # Revision < 7: the span table used 0 as its empty sentinel (now
    # _TAB_EMPTY, for deterministic min-insert), and ann_poison didn't
    # exist — any restored span might be a 3+-distinct-host span whose
    # middle hosts were never indexed, so stamp every service poisoned
    # until the ring turns over (dev.poison_ann_trust below).
    pre_poison = revision < 7
    upd = {k: v for k, v in upd.items() if k in known}
    if "span_tab" in upd and np.asarray(upd["span_tab"]).dtype == np.int64:
        # Pre-rev-11 snapshots store the dep-join table as packed i64
        # words; rev 11 keeps [H, 2] i32 bit-planes — a pure
        # representation change, so the migration is a lossless bitcast
        # (little-endian: word 0 is the low plane, matching
        # lax.bitcast_convert_type). Gated on the stored DTYPE, not the
        # revision, so a snapshot that already carries planes (however
        # its meta is labeled) passes through untouched.
        tab = np.asarray(upd["span_tab"])
        if pre_poison:
            # Rev < 7 used 0 as the empty sentinel (now _TAB_EMPTY).
            tab = np.where(tab == 0, dev._TAB_EMPTY, tab)
        tab = np.ascontiguousarray(tab)
        upd["span_tab"] = jax.numpy.asarray(
            tab.view(np.int32).reshape(tab.shape + (2,))
        )
    if legacy:
        _migrate_legacy_live_links(data, upd, config, n_shards)
    if "dep_banks" not in upd:
        # Pre-revision-3 snapshot (single archive bank, no time tags):
        # the saved dep_moments becomes the all-time tail. Its ts range
        # is unknown, so mark the tail as covering every window (a zero
        # bank contributes nothing either way); the bucket ring starts
        # empty at the init_state defaults.
        if float(np.asarray(data["dep_moments"])[:, 0].sum()) > 0:
            upd["dep_overflow_ts"] = jax.numpy.asarray(
                np.array([dev.I64_MIN, dev.I64_MAX], np.int64)
            )
    if n_shards:
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(mesh, P("shard"))

        def place(x):
            return jax.device_put(jax.numpy.asarray(x), sharding)

        upd = {
            k: ({ck: place(cv) for ck, cv in v.items()}
                if k == "counters" else place(v))
            for k, v in upd.items()
        }
        with store._rw.write():
            store.inner.states = store.inner.states.replace(**upd)
            if pre_index:
                store.inner.states = dev.poison_index_trust(
                    store.inner.states
                )
            if pre_poison:
                store.inner.states = dev.poison_ann_trust(
                    store.inner.states
                )
            if legacy:
                store.inner.states = _sharded_rebuild_tab(
                    mesh, store.inner.states
                )
        wps = np.asarray(jax.device_get(store.inner.states.write_pos))
        store.inner._wp_upper = int(wps.max())
        # Links resolve at ingest now; the mirror only paces time-bucket
        # rotation, so resume with the cadence clock at "just rotated".
        store.inner._archived_lower = store.inner._wp_upper
        # The restored aggregates were never deltas on this process's
        # per-shard mirror twins: resync lazily on the first
        # sketch-tier read (FleetMirror.mark_cold cascades).
        fm = getattr(store, "_fleet_mirror", None)
        if fm is not None:
            fm.mark_cold()
        clocks = meta.get("clocks")
        if clocks and clocks.get("sharded"):
            # Revision-16 sharded snapshots carry the fleet pacing
            # clocks: restore them EXACTLY so a ShardedWal tail replay
            # re-cuts the uncrashed fleet's launches bitwise — the
            # same contract as the single-device clocks below.
            store.inner._wp_upper = int(clocks["wp_upper"])
            store.inner._archived_lower = int(clocks["archived_lower"])
            store.inner._batches_since_sweep = int(
                clocks["batches_since_sweep"])
            # The store is load-local (not yet published to any
            # reader/writer thread), so the bare clock store is
            # race-free.
            store._step_seq = int(  # graftlint: disable=guarded-by
                clocks.get("step_seq", 0))
            store._wal_applied = int(clocks.get("wal_applied", 0))
        return store
    with store._rw.write():
        store.state = store.state.replace(**upd)
        if pre_index:
            store.state = dev.poison_index_trust(store.state)
        if pre_poison:
            store.state = dev.poison_ann_trust(store.state)
        if legacy:
            # The pre-rev-4 schema had no span table: re-insert resident
            # spans so post-restore children still find their parents.
            store.state = dev.rebuild_span_tab(store.state)
    # Re-seed the host mirrors that pace dependency bucket rotation —
    # or, for revision-13 snapshots, restore them EXACTLY: the saved
    # clocks were captured under the gather's read lock, so sweep and
    # bucket-rotation cadence resume mid-stride and a WAL replay
    # re-cuts the uncrashed drive's launches bitwise (wal/recovery).
    store._wp = int(store.state.write_pos)
    store._archived = store._wp
    # The restored aggregates were never deltas on this process's
    # sketch mirror: resync lazily on first sketch-tier read.
    if hasattr(store, "sketch_mirror"):
        store.sketch_mirror.mark_cold()
    clocks = meta.get("clocks")
    if clocks:
        store._archived = int(clocks["archived"])
        store._batches_since_sweep = int(clocks["batches_since_sweep"])
        store._awp = int(clocks["awp"])
        store._bwp = int(clocks["bwp"])
        with store._cap_lock:
            store._cap_upto = int(clocks["cap_upto"])
            store._cap_a = int(clocks["cap_a"])
            store._cap_b = int(clocks["cap_b"])
            store._sealed_upto = int(clocks["sealed_upto"])
        store._wal_applied = int(clocks.get("wal_applied", 0))
    # Paged layout (revision 18): restore the page allocator + page
    # table — or, for a paged config pointed at a snapshot saved
    # without it (pre-18, or a ring store's), rebuild the table from
    # the resident device columns.
    if getattr(store, "_planner", None) is not None:
        pmeta = meta.get("paged")
        if pmeta:
            store._planner.restore(pmeta)
        else:
            row_gid, trace_col = jax.device_get(
                (store.state.row_gid, store.state.trace_id))
            store._planner.rebuild(row_gid, trace_col,
                                   wal_applied=store._wal_applied)
    arch = meta.get("archive")
    if arch:
        return _restore_tiered(path, store, arch,
                               exact_clocks=bool(clocks))
    return store


def _restore_tiered(path: str, store, arch: dict,
                    exact_clocks: bool = False):
    """Rebuild the TieredSpanStore around a restored device store:
    segments load from their immutable blobs, the captured-gid
    watermark restores from the manifest, and one capture_now() flush
    re-aligns the side-ring capture clocks (the host annotation/binary
    mirrors don't survive a restart — flushing the resident uncaptured
    window to a fresh segment makes every clock zero-delta again; the
    row overlap with the ring is the tiers' normal state and gid-level
    dedupe absorbs it).

    ``exact_clocks`` (revision-13 snapshots): the capture clocks were
    saved exactly, so the reseed + flush is SKIPPED — capture resumes
    mid-stride, which keeps a WAL replay's capture windows (and hence
    its cold segments) identical to the uncrashed drive's."""
    from zipkin_tpu.store.archive import (
        ArchiveParams,
        Segment,
        SegmentDirectory,
        TieredSpanStore,
    )

    params = ArchiveParams(**arch["params"])
    directory = SegmentDirectory(params, store.codec)
    segs = []
    for ent in arch["segments"]:
        with open(os.path.join(path, _SEGMENTS_DIR, ent["file"]),
                  "rb") as f:
            segs.append(Segment.from_bytes(f.read()))
    for seg in segs:
        # Dictionary-delta validation: every id a segment references
        # lies below its seal-time high-water marks; the restored
        # dictionaries (saved in the same snapshot) must cover them.
        sizes = (len(store.dicts.services), len(store.dicts.span_names),
                 len(store.dicts.annotations),
                 len(store.dicts.binary_keys),
                 len(store.dicts.binary_values),
                 len(store.dicts.endpoints))
        if any(have < need for have, need in zip(sizes,
                                                 seg.dict_sizes)):
            raise ValueError(
                f"segment {seg.seg_id} references dictionary ids past "
                f"the restored dictionaries ({sizes} < "
                f"{seg.dict_sizes}); snapshot is inconsistent"
            )
    directory.restore(
        segs, max((s.seg_id for s in segs), default=-1) + 1)
    tiered = TieredSpanStore(store, params=params, directory=directory)
    if exact_clocks:
        return tiered
    # The save-time manifest may ship a segment sealed just past its
    # captured_upto clock read (harmless superset, see save()); adopt
    # the segments' CONTIGUOUS frontier so the capture_now flush below
    # starts exactly where sealed coverage ends — keeping cold
    # coverage contiguous and overlap-free. Walking contiguity (not
    # max(gid_hi)) matters when a failed async seal left a hole: the
    # frontier must stop below the hole so the flush re-captures
    # whatever of it the restored rings still hold.
    frontier = int(arch.get("captured_upto", 0))
    for s in sorted(segs, key=lambda s: s.gid_lo):
        if s.gid_lo <= frontier:
            frontier = max(frontier, s.gid_hi)
    with store._cap_lock:
        store._cap_upto = min(frontier, store._wp)
        store._sealed_upto = store._cap_upto
        store._cap_a = store._cap_b = 0
    store._awp = store._bwp = 0
    tiered.capture_now()
    return tiered


def _sharded_rebuild_tab(mesh, states):
    """Per-shard rebuild_span_tab for legacy sharded snapshots."""
    from jax.sharding import PartitionSpec as P

    from zipkin_tpu.parallel.shard import compat_shard_map

    def fn(state):
        state = jax.tree.map(lambda x: x[0], state)
        new_state = dev.rebuild_span_tab.__wrapped__(state)
        return jax.tree.map(lambda x: x[None], new_state)

    mapped = compat_shard_map(
        fn, mesh=mesh, in_specs=(P("shard"),), out_specs=P("shard"),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0,))(states)


def _migrate_legacy_live_links(data, upd, config, n_shards) -> None:
    """Pre-revision-4 snapshots carry links only in dep_moments/dep_banks
    plus an eviction watermark (dep_archived_gid): links of UNARCHIVED
    resident children existed only implicitly, computed on demand by the
    retired ring join. Reconstruct exactly those links here (host numpy,
    same segmented-Moments arithmetic) and seed the new streaming-join
    window bank with them — and queue children whose parent was NOT
    resident into the pending ring (packed with the bit-identical host
    mixer, hashing.np_mix_keys64), so a parent arriving after the
    upgrade still links. An upgrade loses nothing."""
    from zipkin_tpu.columnar.schema import FLAG_HAS_PARENT
    from zipkin_tpu.ops.hashing import np_mix_keys64
    from zipkin_tpu.store.device import _SVC_MASK

    S = config.max_services
    Q = config.pending_slots

    def one(slice_of):
        gid = slice_of("row_gid")
        live = gid >= 0
        flags = slice_of("flags")
        has_parent = (flags & int(FLAG_HAS_PARENT)) != 0
        archived = np.int64(slice_of("dep_archived_gid"))
        tid = slice_of("trace_id")
        sid = slice_of("span_id")
        pid = slice_of("parent_id")
        svc = slice_of("service_id")
        dur = slice_of("duration")
        tsf = slice_of("ts_first")
        tsl = slice_of("ts_last")
        probe = live & has_parent & (gid >= archived)
        window = np.zeros((S * S, 5), np.float32)
        wts = np.array([dev.I64_MAX, dev.I64_MIN], np.int64)
        pend = {
            "pend_key": np.zeros(Q, np.int64),
            "pend_dur": np.zeros(Q, np.int64),
            "pend_tsf": np.zeros(Q, np.int64),
            "pend_tsl": np.zeros(Q, np.int64),
            "pend_pos": np.int64(0),
        }
        if not probe.any():
            return window, wts, pend
        order = np.lexsort((sid[live], tid[live]))
        b_tid, b_sid = tid[live][order], sid[live][order]
        b_svc = svc[live][order]
        q_tid, q_pid = tid[probe], pid[probe]
        # Two-key search: positions where (tid, sid) == (q_tid, q_pid).
        bk = np.rec.fromarrays([b_tid, b_sid])
        qk = np.rec.fromarrays([q_tid, q_pid])
        pos = np.searchsorted(bk, qk)
        pos_c = np.clip(pos, 0, len(bk) - 1)
        found = (len(bk) > 0) & (bk[pos_c] == qk)
        psvc = np.where(found, b_svc[pos_c], -1)
        csvc = svc[probe]
        d = dur[probe]
        ok = found & (psvc >= 0) & (csvc >= 0) & (psvc < S) \
            & (csvc < S) & (d >= 0)
        # Children with no resident parent: queue them (newest Q) so a
        # parent arriving after the upgrade still links via dep_sweep —
        # the same gate the device ingest uses for its pending pushes.
        pend_mask = ~found & (csvc >= 0) & (csvc < S) & (d >= 0)
        if pend_mask.any():
            sel = np.flatnonzero(pend_mask)[-Q:]
            nq = sel.size
            key48 = np_mix_keys64(
                [q_tid[sel], q_pid[sel]]
            ) >> np.uint64(16)
            svc_part = (np.clip(csvc[sel], -1, _SVC_MASK - 2)
                        .astype(np.uint64) + np.uint64(1))
            packed = ((key48 << np.uint64(16))
                      | (svc_part << np.uint64(1))
                      | np.uint64(1)).view(np.int64)
            pend["pend_key"][:nq] = packed
            pend["pend_dur"][:nq] = d[sel]
            pend["pend_tsf"][:nq] = tsf[probe][sel]
            pend["pend_tsl"][:nq] = tsl[probe][sel]
            pend["pend_pos"] = np.int64(nq)
        if not ok.any():
            return window, wts, pend
        link = (psvc.astype(np.int64) * S + csvc)[ok]
        dv = d[ok].astype(np.float64)
        n = np.bincount(link, minlength=S * S).astype(np.float64)
        sx = np.bincount(link, weights=dv, minlength=S * S)
        mean = np.divide(sx, n, out=np.zeros_like(sx), where=n > 0)
        c = dv - mean[link]
        m2 = np.bincount(link, weights=c * c, minlength=S * S)
        m3 = np.bincount(link, weights=c * c * c, minlength=S * S)
        m4 = np.bincount(link, weights=c * c * c * c, minlength=S * S)
        window = np.stack([n, mean, m2, m3, m4], axis=-1).astype(
            np.float32
        )
        ptsf, ptsl = tsf[probe][ok], tsl[probe][ok]
        lo = ptsf[ptsf >= 0]
        hi = ptsl[ptsl >= 0]
        if lo.size:
            wts[0] = lo.min()
        if hi.size:
            wts[1] = hi.max()
        return window, wts, pend

    def col(name):
        if name in data.files:
            return np.asarray(data[name])
        if name == "dep_archived_gid":
            # Revision-1 layout: no watermark leaf, but its dep_moments
            # bank was the complete link state — treat the ring as fully
            # archived or every resident link would double-count.
            return np.asarray(data["write_pos"])
        return np.int64(0)

    if n_shards:
        windows, tss = [], []
        pends = {k: [] for k in ("pend_key", "pend_dur", "pend_tsf",
                                 "pend_tsl", "pend_pos")}
        for sh in range(n_shards):
            def slice_of(name, sh=sh):
                v = col(name)
                return v[sh] if getattr(v, "ndim", 0) > 0 else v
            w, t, p = one(slice_of)
            windows.append(w)
            tss.append(t)
            for k in pends:
                pends[k].append(p[k])
        upd["dep_window"] = jax.numpy.asarray(np.stack(windows))
        upd["dep_window_ts"] = jax.numpy.asarray(np.stack(tss))
        for k, vs in pends.items():
            upd[k] = jax.numpy.asarray(np.stack(vs))
    else:
        w, t, p = one(col)
        upd["dep_window"] = jax.numpy.asarray(w)
        upd["dep_window_ts"] = jax.numpy.asarray(t)
        for k, v in p.items():
            upd[k] = jax.numpy.asarray(v)
