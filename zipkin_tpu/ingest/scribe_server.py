"""Raw-TCP framed-thrift Scribe server — the real transport endpoint.

Implements the Scribe service's ``Log(messages: list<LogEntry>)`` RPC
(scribe.thrift:25-30: ``LogEntry {1: string category, 2: string
message}``, result ``ResultCode {OK=0, TRY_LATER=1}``) over
TFramedTransport + TBinaryProtocol — the wire format finagle's
ThriftMux-less thrift clients and original scribe emitters speak
(reference server: ScribeSpanReceiver.scala:69-78). Base64 payload
decode and span parsing happen in the ScribeReceiver/Collector behind
``receiver.log``.

Both strict (versioned) and old-style unversioned message headers are
accepted. Unknown methods get a TApplicationException so well-behaved
clients fail fast instead of hanging.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import List, Optional, Tuple

from zipkin_tpu.ingest.receiver import ResultCode, ScribeReceiver
from zipkin_tpu.wire.thrift import (
    T_I32,
    T_LIST,
    T_STOP,
    T_STRING,
    T_STRUCT,
    ThriftError,
    _Reader,
)

VERSION_1 = 0x80010000
MSG_CALL = 1
MSG_REPLY = 2
MSG_EXCEPTION = 3

MAX_FRAME = 64 << 20  # a 64MB frame bound keeps a bad client from OOMing us


def _read_message_header(r: _Reader) -> Tuple[str, int]:
    first = r.i32()
    if first < 0:
        if (first & 0xFFFF0000) != (VERSION_1 & 0xFFFF0000):
            raise ThriftError("bad thrift version")
        mtype = first & 0xFF
        if mtype != MSG_CALL:
            raise ThriftError(f"unexpected message type {mtype}")
        name = r.take(r.i32()).decode("utf-8", "replace")
        seqid = r.i32()
    else:
        # Old-style unversioned: name (we already consumed its length),
        # then a type byte and seqid.
        name = r.take(first).decode("utf-8", "replace")
        mtype = r.u8()
        if mtype != MSG_CALL:
            raise ThriftError(f"unexpected message type {mtype}")
        seqid = r.i32()
    return name, seqid


def _parse_log_args(r: _Reader) -> List[Tuple[str, str]]:
    """Scribe.Log args struct: {1: list<LogEntry>}."""
    entries: List[Tuple[str, str]] = []
    while True:
        ftype = r.u8()
        if ftype == T_STOP:
            break
        fid = r.i16()
        if fid == 1 and ftype == T_LIST:
            etype = r.u8()
            n = r.i32()
            if etype != T_STRUCT or n < 0:
                raise ThriftError("bad LogEntry list")
            for _ in range(n):
                category = message = ""
                while True:
                    et = r.u8()
                    if et == T_STOP:
                        break
                    eid = r.i16()
                    if eid == 1 and et == T_STRING:
                        category = r.string().decode("utf-8", "replace")
                    elif eid == 2 and et == T_STRING:
                        message = r.string().decode("utf-8", "replace")
                    else:
                        r.skip(et)
                entries.append((category, message))
        else:
            r.skip(ftype)
    return entries


def _reply(name: str, seqid: int, code: ResultCode) -> bytes:
    # Thrift string length is the UTF-8 byte count, not code points — a
    # non-ASCII method name (e.g. from a 'replace'-decoded bad frame)
    # must not desync the reply framing.
    nb = name.encode()
    body = [
        struct.pack(">I", (VERSION_1 | MSG_REPLY) & 0xFFFFFFFF),
        struct.pack(">i", len(nb)), nb,
        struct.pack(">i", seqid),
        # result struct: {0: i32 success}
        struct.pack(">bh", T_I32, 0), struct.pack(">i", code.value),
        b"\x00",
    ]
    return b"".join(body)


def _exception_reply(name: str, seqid: int, message: str) -> bytes:
    nb = name.encode()
    mb = message.encode()
    body = [
        struct.pack(">I", (VERSION_1 | MSG_EXCEPTION) & 0xFFFFFFFF),
        struct.pack(">i", len(nb)), nb,
        struct.pack(">i", seqid),
        # TApplicationException {1: string message, 2: i32 type}
        struct.pack(">bh", T_STRING, 1),
        struct.pack(">i", len(mb)), mb,
        struct.pack(">bh", T_I32, 2), struct.pack(">i", 1),  # UNKNOWN_METHOD
        b"\x00",
    ]
    return b"".join(body)


def handle_call(receiver: ScribeReceiver, frame: bytes) -> Optional[bytes]:
    """One framed thrift CALL → reply frame payload (None = drop conn)."""
    r = _Reader(frame)
    name, seqid = _read_message_header(r)
    if name != "Log":
        return _exception_reply(name, seqid, f"unknown method {name!r}")
    entries = _parse_log_args(r)
    code = receiver.log(entries)
    return _reply(name, seqid, code)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        sock: socket.socket = self.request
        sock.settimeout(self.server.io_timeout_s)  # type: ignore[attr-defined]
        receiver = self.server.receiver  # type: ignore[attr-defined]
        try:
            while True:
                header = self._read_exact(sock, 4)
                if header is None:
                    return
                (n,) = struct.unpack(">i", header)
                if n <= 0 or n > MAX_FRAME:
                    return
                frame = self._read_exact(sock, n)
                if frame is None:
                    return
                try:
                    out = handle_call(receiver, frame)
                except ThriftError:
                    return
                if out is None:
                    return
                sock.sendall(struct.pack(">i", len(out)) + out)
        except (socket.timeout, ConnectionError, OSError):
            return

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
        return read_exact(sock, n)


def read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes or None on disconnect/socket error —
    shared by every framed-TCP server here (scribe, the kafka fake)."""
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


class ScribeServer(socketserver.ThreadingTCPServer):
    """Threaded framed-thrift scribe endpoint bound to (host, port)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, receiver: ScribeReceiver, host: str = "0.0.0.0",
                 port: int = 9410, io_timeout_s: float = 60.0):
        super().__init__((host, port), _Handler)
        self.receiver = receiver
        self.io_timeout_s = io_timeout_s

    def serve_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t


def encode_log_call(entries: List[Tuple[str, str]], seqid: int = 0) -> bytes:
    """Client-side Scribe.Log frame (for tests and the tracegen feeder)."""
    body = [
        struct.pack(">I", (VERSION_1 | MSG_CALL) & 0xFFFFFFFF),
        struct.pack(">i", 3), b"Log",
        struct.pack(">i", seqid),
        struct.pack(">bh", T_LIST, 1),
        struct.pack(">bi", T_STRUCT, len(entries)),
    ]
    for category, message in entries:
        c = category.encode()
        m = message.encode()
        body.append(struct.pack(">bh", T_STRING, 1))
        body.append(struct.pack(">i", len(c)) + c)
        body.append(struct.pack(">bh", T_STRING, 2))
        body.append(struct.pack(">i", len(m)) + m)
        body.append(b"\x00")
    body.append(b"\x00")
    payload = b"".join(body)
    return struct.pack(">i", len(payload)) + payload


def decode_log_reply(frame: bytes) -> ResultCode:
    """Client-side reply decode (tests / tracegen)."""
    r = _Reader(frame)
    first = r.i32()
    if first >= 0:
        r.take(first)
        mtype = r.u8()
        r.i32()
    else:
        mtype = first & 0xFF
        r.take(r.i32())
        r.i32()
    if mtype == MSG_EXCEPTION:
        raise ThriftError("server exception")
    code = ResultCode.OK
    while True:
        ftype = r.u8()
        if ftype == T_STOP:
            break
        fid = r.i16()
        if fid == 0 and ftype == T_I32:
            code = ResultCode(r.i32())
        else:
            r.skip(ftype)
    return code


class ScribeClient:
    """Minimal blocking scribe client (the CarelessScribe role in the
    ruby gem, zipkin-tracer.rb) — used by tracegen's smoke feed."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.addr = (host, port)
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._seq = 0

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.addr, self.timeout_s)
            self._sock.settimeout(self.timeout_s)
        return self._sock

    def log(self, entries: List[Tuple[str, str]]) -> ResultCode:
        self._seq += 1
        sock = self._connect()
        try:
            sock.sendall(encode_log_call(entries, self._seq))
            header = _Handler._read_exact(sock, 4)
            if header is None:
                raise ConnectionError("scribe server closed connection")
            (n,) = struct.unpack(">i", header)
            frame = _Handler._read_exact(sock, n)
            if frame is None:
                raise ConnectionError("scribe server closed connection")
            return decode_log_reply(frame)
        except (OSError, ConnectionError):
            self.close()
            raise

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
