"""Kafka-style streaming receiver (consumer-agnostic).

Reference: zipkin-receiver-kafka (KafkaProcessor.scala:25,
KafkaStreamProcessor.scala:8) — N consumer streams, each decoding thrift
span payloads and pushing into the collector with retry-on-pushback.

No kafka client library ships in this environment, so the transport is
injected: a *consumer* here is any iterable of ``bytes`` messages (a
real kafka consumer's message-value iterator fits directly). The decode
and pushback semantics are the receiver's.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, List, Optional, Sequence

from zipkin_tpu.ingest.queue import QueueFullException
from zipkin_tpu.models.span import Span
from zipkin_tpu.wire.thrift import ThriftError, spans_from_bytes


class KafkaSpanReceiver:
    """Drains message streams into the collector.

    ``streams``: one iterable of raw message bytes per worker thread
    (the reference's consumer streams). On QueueFullException the
    message is retried with backoff — kafka's at-least-once stance —
    rather than dropped.
    """

    def __init__(
        self,
        process: Callable[[Sequence[Span]], None],
        streams: Sequence[Iterable[bytes]],
        retry_backoff_s: float = 0.05,
        max_retries: int = 100,
        process_thrift: Optional[Callable[[bytes], None]] = None,
    ):
        self.process = process
        self.process_thrift = process_thrift
        self.streams = streams
        self.retry_backoff_s = retry_backoff_s
        self.max_retries = max_retries
        self.stats = {"messages": 0, "bad": 0, "retries": 0, "dropped": 0}
        self._threads: List[threading.Thread] = []

    def _drain(self, stream: Iterable[bytes]) -> None:
        for message in stream:
            self.stats["messages"] += 1
            if not message:
                continue
            if self.process_thrift is not None:
                # Fast path: raw bytes straight to the collector; the
                # columnar parse happens on its worker (malformed
                # payloads count there as bad_payloads).
                self._offer(self.process_thrift, message)
                continue
            try:
                spans = spans_from_bytes(message)
            except ThriftError:
                self.stats["bad"] += 1
                continue
            if not spans:
                continue
            self._offer(self.process, spans)

    def _offer(self, fn, item) -> None:
        for attempt in range(self.max_retries + 1):
            try:
                fn(item)
                break
            except QueueFullException:
                if attempt == self.max_retries:
                    self.stats["dropped"] += 1
                    break
                self.stats["retries"] += 1
                time.sleep(self.retry_backoff_s)

    def run(self) -> None:
        """Drain every stream to exhaustion on worker threads and join
        (a real deployment's streams never exhaust)."""
        self._threads = [
            threading.Thread(target=self._drain, args=(s,), daemon=True)
            for s in self.streams
        ]
        for t in self._threads:
            t.start()
        for t in self._threads:
            t.join()
