"""Kafka-style streaming receiver + producer sink (client-agnostic).

Reference: zipkin-receiver-kafka (KafkaProcessor.scala:25,
KafkaStreamProcessor.scala:8) — N consumer streams, each decoding thrift
span payloads and pushing into the collector with retry-on-pushback —
and zipkin-kafka's producer sink (collector/Kafka.scala: a
``Service[Span, Unit]`` publishing thrift-encoded spans to a topic).

No kafka client library ships in this environment, so the transport is
injected: a *consumer* here is any iterable of ``bytes`` messages (a
real kafka consumer's message-value iterator fits directly), and a
*producer* is any ``send(topic, bytes)`` callable (kafka-python's
``KafkaProducer.send`` fits directly). The decode/encode and pushback
semantics are this module's.

INTEGRATION CONTRACT (what a real client must provide / may assume):

Consumer side (``KafkaSpanReceiver``):
- Each element of ``streams`` is an iterable yielding message VALUES as
  ``bytes``. One worker thread drains each stream; run one consumer
  INSTANCE per stream, all in one consumer group — Kafka's group
  protocol then balances partitions across the workers exactly like the
  reference's N KafkaStreams (KafkaProcessor.scala:25).
- Message payload: one or more back-to-back TBinaryProtocol Span
  structs (the scribe/zipkin wire form). A partial/garbage payload
  raises inside the decoder and is COUNTED (``stats['bad']``), never
  fatal — consumers may deliver duplicates or corruption freely.
- Delivery: at-least-once. On collector pushback (QueueFullException)
  the message retries with backoff up to ``max_retries`` before being
  counted dropped; a client that wants zero drops should disable
  auto-commit and commit offsets AFTER ``process`` returns — the
  receiver itself never commits (it has no client handle).
- Rebalance: safe by construction — the receiver keeps no per-partition
  state; a replayed message is just a duplicate span, which the store
  tolerates (same-id spans merge downstream).

Producer side (``KafkaSpanSink``):
- ``producer(topic, value)`` may be sync (returns anything) or async
  (returns a future exposing ``add_callback``/``add_errback`` —
  kafka-python's FutureRecordMetadata shape). Broker errors surface via
  the errback and are counted, never raised into the write pipeline
  (the reference sink's swallow-and-count stance).
- ``close()`` calls ``producer.flush()`` when present; callers that
  need delivery confirmation before shutdown must close the sink.

``connect_kafka_python`` below wires all of this to kafka-python when
that library is importable (it is not baked into this environment —
the function degrades to a clear error). The semantics above are
exercised two ways: against injected transports (tests/test_ingest.py)
and against BYTES ON A SOCKET via the v0 wire-protocol broker fake +
minimal real-protocol client in zipkin_tpu/testing/kafka_fake.py
(tests/test_kafka_wire.py) — framing, CRC, batching, pushback retry,
corrupt payloads, and at-least-once redelivery all cross a real TCP
connection. MinimalKafkaProducer/MinimalKafkaConsumer speak protocol
v0 only, one partition, no consumer group — a test/dev transport for
the in-process fake, NOT a client for production brokers (modern Kafka
has removed the v0 message format; use kafka-python there).
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, Iterable, List, Optional, Sequence

from zipkin_tpu.ingest.queue import QueueFullException
from zipkin_tpu.models.span import Span
from zipkin_tpu.wire.thrift import ThriftError, spans_from_bytes

# Wire-path compression framing: an optional ONE-BYTE negotiation
# prefix on each message value. 0x01 = the rest is a raw-deflate
# (zlib) stream of concatenated thrift Span structs; 0x00 = the rest
# is those structs uncompressed (framed but not worth compressing).
# Any other first byte is a LEGACY unframed payload: a TBinaryProtocol
# Span struct always starts with a field-type byte >= 0x02 (trace_id
# i64 => 0x0a), so the two framed markers can never collide with real
# spans — old producers and new consumers interoperate byte-for-byte.
FRAME_DEFLATE = 0x01
FRAME_RAW = 0x00
# Tiny payloads inflate under deflate (header + dictionary overhead);
# below this the sink ships the framed-raw form instead.
COMPRESS_MIN_BYTES = 128


def encode_frame(payload: bytes, compress: bool,
                 min_bytes: int = COMPRESS_MIN_BYTES) -> bytes:
    if not compress:
        return payload  # legacy unframed (backward compatible)
    if len(payload) < min_bytes:
        return bytes([FRAME_RAW]) + payload
    return bytes([FRAME_DEFLATE]) + zlib.compress(payload, 6)


def decode_frame(message: bytes) -> bytes:
    """Unframe a message value; raises ThriftError on a corrupt
    deflate stream (counted like any bad payload, never fatal)."""
    if not message:
        return message
    marker = message[0]
    if marker == FRAME_DEFLATE:
        try:
            return zlib.decompress(message[1:])
        except zlib.error as e:
            raise ThriftError(f"bad deflate frame: {e}") from e
    if marker == FRAME_RAW:
        return message[1:]
    return message  # legacy unframed


class KafkaSpanReceiver:
    """Drains message streams into the collector.

    ``streams``: one iterable of raw message bytes per worker thread
    (the reference's consumer streams). On QueueFullException the
    message is retried with backoff — kafka's at-least-once stance —
    rather than dropped.
    """

    def __init__(
        self,
        process: Callable[[Sequence[Span]], None],
        streams: Sequence[Iterable[bytes]],
        retry_backoff_s: float = 0.05,
        max_retries: int = 100,
        process_thrift: Optional[Callable[[bytes], None]] = None,
    ):
        self.process = process
        self.process_thrift = process_thrift
        self.streams = streams
        self.retry_backoff_s = retry_backoff_s
        self.max_retries = max_retries
        self.stats = {"messages": 0, "bad": 0, "retries": 0, "dropped": 0}
        self._threads: List[threading.Thread] = []

    def _drain(self, stream: Iterable[bytes]) -> None:
        for message in stream:
            self.stats["messages"] += 1
            if not message:
                continue
            try:
                # Negotiation byte first: framed-deflate payloads
                # decompress here, framed-raw strip the marker, and
                # legacy unframed bytes pass through untouched.
                message = decode_frame(message)
            except ThriftError:
                self.stats["bad"] += 1
                continue
            if not message:
                continue
            if self.process_thrift is not None:
                # Fast path: raw bytes straight to the collector; the
                # columnar parse happens on its worker (malformed
                # payloads count there as bad_payloads).
                self._offer(self.process_thrift, message)
                continue
            try:
                spans = spans_from_bytes(message)
            except ThriftError:
                self.stats["bad"] += 1
                continue
            if not spans:
                continue
            self._offer(self.process, spans)

    def _offer(self, fn, item) -> None:
        for attempt in range(self.max_retries + 1):
            try:
                fn(item)
                break
            except QueueFullException:
                if attempt == self.max_retries:
                    self.stats["dropped"] += 1
                    break
                self.stats["retries"] += 1
                time.sleep(self.retry_backoff_s)

    def run(self) -> None:
        """Drain every stream to exhaustion on worker threads and join
        (a real deployment's streams never exhaust)."""
        self._threads = [
            threading.Thread(target=self._drain, args=(s,), daemon=True)
            for s in self.streams
        ]
        for t in self._threads:
            t.start()
        for t in self._threads:
            t.join()


class KafkaSpanSink:
    """Producer side: publish spans to a kafka topic as thrift bytes —
    the zipkin-kafka role (collector/Kafka.scala's Service[Span, Unit]
    with its SpanEncoder), so a collector can fan spans out to a topic
    (e.g. for an offline aggregation consumer) alongside storage.

    ``producer``: any ``send(topic: str, value: bytes)`` callable —
    kafka-python's ``KafkaProducer.send`` fits directly; tests inject a
    list-appender. Usable as a FanoutWriteSpanStore member: ``apply``
    publishes, ``set_time_to_live`` is a no-op (a topic has no per-trace
    retention; parity with the reference sink, which only writes).
    """

    def __init__(self, producer: Callable[[str, bytes], object],
                 topic: str = "zipkin",
                 batch: bool = False,
                 compress: bool = False,
                 compress_min_bytes: int = COMPRESS_MIN_BYTES):
        from zipkin_tpu.wire.thrift import span_to_bytes

        self._encode = span_to_bytes
        self.producer = producer
        self.topic = topic
        self.batch = batch
        # ``compress`` turns on the negotiation-byte framing (see
        # encode_frame): deflate for payloads past compress_min_bytes,
        # framed-raw below it. Off by default — unframed output stays
        # byte-identical for legacy consumers.
        self.compress = compress
        self.compress_min_bytes = compress_min_bytes
        self.stats = {"published": 0, "errors": 0,
                      "bytes_raw": 0, "bytes_wire": 0}
        # Async producers report delivery on their returned future from
        # an IO thread; counters need the lock either way.
        self._stats_lock = threading.Lock()  # lock-order: 82 kafka-stats

    def _count(self, key: str, n: int) -> None:
        with self._stats_lock:
            self.stats[key] += n

    def apply(self, spans: Sequence[Span]) -> None:
        if self.batch:
            # One message per batch (concatenated Span structs — the
            # form KafkaSpanReceiver/spans_from_bytes decodes).
            payload = b"".join(self._encode(s) for s in spans)
            self._send(payload, len(spans))
            return
        for s in spans:
            self._send(self._encode(s), 1)

    def _send(self, payload: bytes, n: int) -> None:
        wire = encode_frame(payload, self.compress,
                            self.compress_min_bytes)
        self._count("bytes_raw", len(payload))
        self._count("bytes_wire", len(wire))
        try:
            result = self.producer(self.topic, wire)
        except Exception:
            # The reference sink swallows-and-counts producer errors
            # rather than failing the write pipeline.
            self._count("errors", n)
            return
        # Async producers (kafka-python) surface broker errors on the
        # returned future, not synchronously — hook its callbacks so a
        # down broker counts as errors instead of phantom publishes.
        errback = getattr(result, "add_errback", None)
        callback = getattr(result, "add_callback", None)
        if callable(errback) and callable(callback):
            callback(lambda *_: self._count("published", n))
            errback(lambda *_: self._count("errors", n))
        else:
            self._count("published", n)

    def set_time_to_live(self, trace_id: int, ttl_seconds: float) -> None:
        pass

    def close(self) -> None:
        flush = getattr(self.producer, "flush", None)
        if callable(flush):
            flush()


def record_value_stream(consumer) -> Iterable[bytes]:
    """Adapt a kafka-python style consumer (iterating records that carry
    ``.value`` bytes) into the raw-bytes stream KafkaSpanReceiver
    drains. Also accepts already-raw byte iterables unchanged."""
    for rec in consumer:
        yield rec.value if hasattr(rec, "value") else rec


def connect_kafka_python(
    process: Callable[[Sequence[Span]], None],
    bootstrap_servers,
    topic: str = "zipkin",
    group_id: str = "zipkin-tpu",
    n_streams: int = 1,
    process_thrift: Optional[Callable[[bytes], None]] = None,
    **consumer_kwargs,
) -> "KafkaSpanReceiver":
    """Build a KafkaSpanReceiver over REAL kafka-python consumers: one
    consumer instance per worker stream, all in ``group_id`` so the
    broker balances partitions across them (the N-streams topology of
    KafkaProcessor.scala:25). The kafka-python library is not baked
    into this environment; when absent this raises a RuntimeError that
    restates the integration contract instead of failing obscurely.

    The constructed clients are exposed on the returned receiver as
    ``receiver.consumers`` — for the zero-drop variant described in the
    module contract, pass ``enable_auto_commit=False`` through
    ``consumer_kwargs`` and call ``commit()`` on them from your
    ``process`` callable; call ``close()`` on them at shutdown."""
    try:
        from kafka import KafkaConsumer  # type: ignore
    except ImportError as e:
        raise RuntimeError(
            "kafka-python is not installed. KafkaSpanReceiver only needs "
            "iterables of message-value bytes — adapt any client via "
            "record_value_stream(consumer); see the module docstring's "
            "integration contract."
        ) from e
    consumers = []
    try:
        for _ in range(n_streams):
            consumers.append(KafkaConsumer(
                topic, bootstrap_servers=bootstrap_servers,
                group_id=group_id, **consumer_kwargs,
            ))
    except Exception:
        # Don't leak sockets / phantom group members when a later
        # consumer fails to construct.
        for c in consumers:
            try:
                c.close()
            except Exception:  # graftlint: disable=swallowed-exception
                pass  # best-effort cleanup; the original error re-raises
        raise
    receiver = KafkaSpanReceiver(
        process=process,
        streams=[record_value_stream(c) for c in consumers],
        process_thrift=process_thrift,
    )
    # Expose the client handles: manual offset commits (the zero-drop
    # recipe above) and clean shutdown both need them.
    receiver.consumers = consumers
    return receiver
