"""Collector: receiver → bounded queue → sampler filter → store(s).

Reference wiring (ZipkinCollectorFactory.scala:40-76): the receiver
pushes span batches into the ItemQueue; worker threads run the filter
chain (sampling: keep iff debug or the rate test passes,
SpanSamplerFilter.scala:40-47) and hand survivors to the WriteSpanStore.
The adaptive controller reads the flow from the store counters and
moves the sampler's rate (AdaptiveSampler wiring, SURVEY.md §3.5).

Stats live in the telemetry registry (zipkin_tpu.obs): the old
``_stats_lock`` dict is gone — every counter bump is an obs.Counter
increment (one lock per bump, none lost under concurrent queue
workers, including the failure paths), and each processed batch feeds
the batch-size and write-latency sketches. With ``self_trace=True``
the collector also records one genuine Zipkin span per ingest step
under the ``zipkin-tpu`` service name, written STRAIGHT to the store
(bypassing queue + sampler, so the tracer can never feed back into the
stream it measures)."""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

from zipkin_tpu import obs
from zipkin_tpu.ingest.queue import ItemQueue
from zipkin_tpu.models.span import Span
from zipkin_tpu.sampler.adaptive import (
    AdaptiveConfig,
    AdaptiveSampleRateController,
    FlowEstimator,
)
from zipkin_tpu.sampler.core import Sampler
from zipkin_tpu.store.base import WriteSpanStore


class _ThriftPayload:
    """Queue item marking raw thrift bytes for the columnar fast path.

    ``segments`` keeps transport-level message boundaries (one scribe
    LogEntry / kafka message each) so a corrupt segment can be isolated
    instead of poisoning the whole batch."""

    __slots__ = ("segments",)

    def __init__(self, segments: Sequence[bytes]):
        self.segments = list(segments)


class Collector:
    def __init__(
        self,
        store: WriteSpanStore,
        sampler: Optional[Sampler] = None,
        adaptive: Optional[AdaptiveConfig] = None,
        max_queue: int = 500,
        concurrency: int = 10,
        registry: Optional[obs.Registry] = None,
        self_trace: bool = False,
        self_service_name: str = "zipkin-tpu",
        pipeline_depth: int = 0,
    ):
        self.store = store
        # Pipelined ingest (store/pipeline): queue workers become the
        # pipeline's stage-1 producers (encode + pad outside the device
        # critical section) and the store's commit thread feeds the
        # accelerator. flush()/close() drain it so "flushed" keeps
        # meaning "visible to reads".
        if pipeline_depth:
            start = getattr(store, "start_pipeline", None)
            if start is None:
                raise ValueError(
                    "pipeline_depth requires a store with pipelined "
                    "ingest (TpuSpanStore / TieredSpanStore)"
                )
            start(pipeline_depth)
        self.sampler = sampler or Sampler(1.0)
        reg = registry or obs.default_registry()
        self.queue: ItemQueue = ItemQueue(
            self._write, max_size=max_queue, concurrency=concurrency,
            registry=reg,
        )
        self.controller = (
            AdaptiveSampleRateController(adaptive) if adaptive else None
        )
        self._flow = FlowEstimator()
        self._last_tick_s: Optional[float] = None
        self._c_stored = reg.register(obs.Counter(
            "zipkin_collector_spans_stored_total",
            "Spans written to the store after the sampler filter"))
        self._c_dropped = reg.register(obs.Counter(
            "zipkin_collector_spans_dropped_total",
            "Spans dropped by the sampler"))
        self._c_bad = reg.register(obs.Counter(
            "zipkin_collector_bad_payloads_total",
            "Transport segments that failed thrift decode"))
        self._h_batch = reg.register(obs.LatencySketch(
            "zipkin_collector_batch_spans",
            "Spans per processed collector batch (size distribution)",
            min_value=1.0))
        self._h_write = reg.register(obs.LatencySketch(
            "zipkin_collector_write_seconds",
            "Collector batch processing latency: decode + sample + "
            "store write, per queue item"))
        # Sampler-stage metrics ride the collector's registration (the
        # sampler already locks its own counts; these adapt them).
        reg.register(obs.Gauge(
            "zipkin_sampler_rate", "Current sample rate [0, 1]",
            fn=lambda: self.sampler.rate))
        reg.register(obs.Counter(
            "zipkin_sampler_allowed_total",
            "Trace-id sampler decisions that kept the span",
            fn=lambda: self.sampler.snapshot()[0]))
        reg.register(obs.Counter(
            "zipkin_sampler_denied_total",
            "Trace-id sampler decisions that dropped the span",
            fn=lambda: self.sampler.snapshot()[1]))
        # Ingest-step self-tracing (SURVEY §5): transport writes DIRECT
        # to the store — never through accept()/the queue — so a
        # self-trace span can't generate another self-trace span.
        # Spans buffer and flush in batches: a device store pays a full
        # padded ingest launch per apply(), so one launch PER PROCESSED
        # ITEM would double ingest dispatches and pollute the store's
        # own launch metrics with 1-span steps.
        self.tracer = None
        self._self_buf = []  # guarded-by: _self_lock
        self._self_lock = threading.Lock()  # lock-order: 79 self-trace
        # Self-trace batches dropped because the store write failed —
        # self-tracing must never fail ingest, but a silent drop hid
        # every such failure (graftlint swallowed-exception).
        self._c_self_drops = reg.register(obs.Counter(
            "zipkin_collector_self_trace_drops_total",
            "Self-trace span batches dropped by a failed store write"))
        if self_trace:
            from zipkin_tpu.client import Tracer

            self.tracer = Tracer(self_service_name, self._self_transport)
        # The fast path needs both the native parser and a store that
        # accepts raw thrift (TpuSpanStore.write_thrift); probed once.
        self._fast_ok: Optional[bool] = None

    # -- registry-backed stats (read by /metrics json + the controller) -

    @property
    def spans_stored(self) -> int:
        return int(self._c_stored.value)

    @property
    def spans_dropped(self) -> int:
        return int(self._c_dropped.value)

    @property
    def bad_payloads(self) -> int:
        return int(self._c_bad.value)

    # -- pipeline -------------------------------------------------------

    def accept(self, spans: Sequence[Span]) -> None:
        """Receiver-facing entry; raises QueueFullException when full."""
        self.queue.add(list(spans))

    def accept_thrift(self, payload) -> None:
        """Raw thrift Span-sequence entry (scribe/kafka fast path): the
        payload — one bytes blob or a sequence of per-message segments —
        decodes on a worker via the native columnar parser when
        available (ScribeSpanReceiver.scala:96-107's scrooge hot decode),
        falling back to the python codec. Sampling is applied either
        way. Raises QueueFullException when full."""
        segments = [payload] if isinstance(payload, (bytes, bytearray)) \
            else list(payload)
        self.queue.add(_ThriftPayload(segments))

    # -- durable (ack-after-append) entries -----------------------------
    #
    # With a write-ahead log attached to the store, a receiver that
    # promises durability on ack (scribe returning OK, a kafka client
    # committing offsets after ``process`` returns) must not ack from
    # the async queue — an accepted-but-unprocessed batch would be
    # acked yet absent from the log at a crash. These entries run the
    # same decode + sample + store path SYNCHRONOUSLY on the calling
    # thread (the store's write path journals before committing) and
    # then block on the WAL's durable frontier: under the group-commit
    # fsync policy, concurrent ackers share one fsync per commit
    # window. Wire them as the receiver's ``process``/
    # ``process_thrift`` callables (main/example.py does when
    # --wal-dir is set); see docs/DURABILITY.md.

    def ingest_durable(self, spans: Sequence[Span]) -> int:
        """Synchronous span ingest + durable-append barrier; returns
        the stored count. Drop-in ``process`` target for receivers."""
        stored = self._write_spans(list(spans))
        self._wal_barrier()
        return stored

    def ingest_thrift_durable(self, payload) -> int:
        """Synchronous raw-thrift ingest + durable-append barrier;
        drop-in ``process_thrift`` target for receivers."""
        segments = [payload] if isinstance(payload, (bytes, bytearray)) \
            else list(payload)
        stored = self._write_thrift(segments)
        self._wal_barrier()
        return stored

    def _wal_barrier(self) -> None:
        """Block until every record appended so far is fsynced (the
        group-commit ack barrier). No-op without a WAL. Raises
        WalDurabilityError when the frontier cannot be covered (fsync
        failing, or the wait timed out) — the caller must NOT ack;
        receivers map it to scribe TRY_LATER."""
        wal = getattr(self.store, "wal", None)
        if wal is not None:
            from zipkin_tpu.wal.log import WalDurabilityError

            if not wal.wait_durable(wal.last_seq):
                raise WalDurabilityError(
                    "timed out waiting for the WAL durable frontier; "
                    "refusing to ack")

    def _fast_path_available(self) -> bool:
        if self._fast_ok is None:
            if getattr(self.store, "write_thrift", None) is None:
                self._fast_ok = False
            else:
                from zipkin_tpu import native

                self._fast_ok = native.available()
        return self._fast_ok

    # Self spans per store write: amortizes the device store's
    # per-launch dispatch floor over many ingest-step spans.
    SELF_TRACE_FLUSH = 64

    def _self_transport(self, spans) -> None:
        with self._self_lock:
            self._self_buf.extend(spans)
            if len(self._self_buf) < self.SELF_TRACE_FLUSH:
                return
            batch, self._self_buf = self._self_buf, []
        try:
            self.store.apply(batch)
        except Exception:
            # Counted, never raised: self-tracing must not fail the
            # ingest step it annotates.
            self._c_self_drops.inc()

    def _flush_self_spans(self) -> None:
        with self._self_lock:
            batch, self._self_buf = self._self_buf, []
        if batch:
            try:
                self.store.apply(batch)
            except Exception:
                self._c_self_drops.inc()  # see _self_transport

    def _write(self, item) -> None:
        """Queue worker entry: time the step, process, self-trace."""
        t0 = time.perf_counter()
        stored = 0
        try:
            if isinstance(item, _ThriftPayload):
                stored = self._write_thrift(item.segments)
            else:
                stored = self._write_spans(item)
        finally:
            dt = time.perf_counter() - t0
            self._h_write.observe(dt)
            if self.tracer is not None:
                self._emit_self_span(dt, stored)

    def _emit_self_span(self, dt_s: float, stored: int) -> None:
        from zipkin_tpu.client import B3Headers

        end_us = int(time.time() * 1e6)
        resolved = self.tracer.resolve(B3Headers())
        self.tracer.server_span(
            "collector ingest", resolved,
            start_us=end_us - max(int(dt_s * 1e6), 1), end_us=end_us,
            tags={"ingest.stored": str(stored)},
        )

    def _write_spans(self, spans) -> int:
        """Sample + store one span batch; returns the stored count."""
        kept = [s for s in spans if s.debug or self.sampler.decide(s.trace_id)]
        # One locked counter update per batch (debug spans bypass the
        # sampler and are not counted, matching the fast path).
        n_debug = sum(1 for s in kept if s.debug)
        self.sampler.count(len(kept) - n_debug, len(spans) - len(kept))
        self._h_batch.observe(len(spans))
        self._c_dropped.inc(len(spans) - len(kept))
        if kept:
            self.store.apply(kept)
            self._c_stored.inc(len(kept))
        return len(kept)

    def _write_thrift(self, segments) -> int:
        """Fast-path write; returns the stored count (summed across
        split-and-retry recursion)."""
        if not self._fast_path_available():
            return self._decode_segments_slow(segments)
        from zipkin_tpu.native import ParseCapacityError

        try:
            written, dropped, written_debug = self.store.write_thrift(
                b"".join(segments), sample_threshold=self.sampler.threshold
            )
        except ParseCapacityError:
            # Valid but oversized: halve and retry (single segments that
            # still don't fit go through the chunking python path).
            if len(segments) > 1:
                mid = len(segments) // 2
                return (self._write_thrift(segments[:mid])
                        + self._write_thrift(segments[mid:]))
            return self._decode_segments_slow(segments)
        except ValueError:
            # A corrupt segment poisons the concatenated parse; isolate
            # it by decoding per segment (slow-path semantics: skip bad,
            # keep good — ScribeReceiver's per-entry 'bad' accounting).
            return self._decode_segments_slow(segments)
        # Slow-path counter parity: debug spans never hit the sampler.
        self.sampler.count(written - written_debug, dropped)
        self._h_batch.observe(max(written + dropped, 1))
        self._c_stored.inc(written)
        self._c_dropped.inc(dropped)
        return written

    def _decode_segments_slow(self, segments) -> int:
        from zipkin_tpu.wire.thrift import ThriftError, spans_from_bytes

        spans = []
        for seg in segments:
            try:
                spans.extend(spans_from_bytes(seg))
            except ThriftError:
                self._c_bad.inc()
        if spans:
            return self._write_spans(spans)
        return 0

    # -- control loop (call periodically, e.g. every 30s) ---------------

    def control_tick(self, now_s: Optional[float] = None) -> Optional[float]:
        """Feed the store rate into the adaptive controller; returns the
        new sample rate when it moves. Single-controller: this replaces
        the ZK group + leader election (AdaptiveSampler.scala:177-237).

        Safe to call at any cadence — observations are gated to the
        controller's update_freq_s so a tight daemon loop doesn't shrink
        the adaptive windows.
        """
        if self.controller is None:
            return None
        now_s = time.time() if now_s is None else now_s
        freq = self.controller.config.update_freq_s
        if self._last_tick_s is not None and now_s - self._last_tick_s < freq:
            return None
        self._last_tick_s = now_s
        # Flow source: the store's own counters (the device spans_seen
        # scalar on the TPU store; a psum-ed shard summary when sharded)
        # — BASELINE's "sampler reads its counts directly from the
        # on-device sketches". Host accounting is only the fallback for
        # stores without counters.
        stored = self.store.stored_span_count()
        if stored is None:
            stored = float(self.spans_stored)
        rate = self._flow.observe(stored, now_s)
        if rate is None:
            return None
        new_rate = self.controller.observe(rate, now_s)
        if new_rate is not None:
            self.sampler.rate = new_rate
        return new_rate

    def _drain_store_pipeline(self) -> None:
        drain = getattr(self.store, "drain_pipeline", None)
        if drain is not None:
            drain()

    def _drain_query_engines(self) -> None:
        """Quiesce the resident query executors registered on the
        store (query/engine.py): wait until no coalesced query launch
        is in flight, so the drain→seal→fsync→checkpoint sequence
        below never interleaves with a standing executor's dispatch."""
        for engine in getattr(self.store, "query_engines",
                              lambda: ())():
            engine.drain()

    def _quiesce_store(self) -> None:
        """Durability-ordered drain of the store's async machinery:
        drain-queries → drain-pipeline → seal-barrier → WAL-fsync
        (docs/DURABILITY.md shutdown ordering — each step's output is
        the next step's input: committed units may pull capture
        windows, sealed windows advance the frontier a checkpoint cuts
        at, and the fsync makes every journaled record durable before
        any checkpoint claims to cover it)."""
        self._drain_query_engines()
        self._drain_store_pipeline()
        barrier = getattr(self.store, "seal_barrier", None)
        if barrier is not None:
            barrier()
        sync = getattr(self.store, "wal_sync", None)
        if sync is not None:
            sync()

    def flush(self) -> None:
        """Drain everything accepted so far: queue workers, buffered
        self-trace spans, the ingest pipeline, pending capture seals,
        and the WAL (fsync) — after this, 'flushed' means visible to
        reads AND durable in the log."""
        self.queue.join()
        self._flush_self_spans()
        self._quiesce_store()

    def close(self) -> None:
        self.queue.close()
        self._flush_self_spans()
        self._quiesce_store()
        # Stop the resident query executors for good BEFORE the store
        # tears down its own async machinery — a standing executor
        # thread must not launch against a closing store. Queries
        # after this still answer (inline, uncoalesced).
        for engine in getattr(self.store, "query_engines",
                              lambda: ())():
            engine.close()
        # store.close() stops the ingest pipeline (draining accepted
        # batches) and the capture sealer before returning.
        self.store.close()
