"""Collector: receiver → bounded queue → sampler filter → store(s).

Reference wiring (ZipkinCollectorFactory.scala:40-76): the receiver
pushes span batches into the ItemQueue; worker threads run the filter
chain (sampling: keep iff debug or the rate test passes,
SpanSamplerFilter.scala:40-47) and hand survivors to the WriteSpanStore.
The adaptive controller reads the flow from the store counters and
moves the sampler's rate (AdaptiveSampler wiring, SURVEY.md §3.5).
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from zipkin_tpu.ingest.queue import ItemQueue
from zipkin_tpu.models.span import Span
from zipkin_tpu.sampler.adaptive import (
    AdaptiveConfig,
    AdaptiveSampleRateController,
    FlowEstimator,
)
from zipkin_tpu.sampler.core import Sampler
from zipkin_tpu.store.base import WriteSpanStore


class Collector:
    def __init__(
        self,
        store: WriteSpanStore,
        sampler: Optional[Sampler] = None,
        adaptive: Optional[AdaptiveConfig] = None,
        max_queue: int = 500,
        concurrency: int = 10,
    ):
        self.store = store
        self.sampler = sampler or Sampler(1.0)
        self.queue: ItemQueue = ItemQueue(
            self._write, max_size=max_queue, concurrency=concurrency
        )
        self.controller = (
            AdaptiveSampleRateController(adaptive) if adaptive else None
        )
        self._flow = FlowEstimator()
        self._last_tick_s: Optional[float] = None
        self.spans_dropped = 0
        self.spans_stored = 0

    # -- pipeline -------------------------------------------------------

    def accept(self, spans: Sequence[Span]) -> None:
        """Receiver-facing entry; raises QueueFullException when full."""
        self.queue.add(list(spans))

    def _write(self, spans) -> None:
        kept = [s for s in spans if s.debug or self.sampler(s.trace_id)]
        self.spans_dropped += len(spans) - len(kept)
        if kept:
            self.store.apply(kept)
            self.spans_stored += len(kept)

    # -- control loop (call periodically, e.g. every 30s) ---------------

    def control_tick(self, now_s: Optional[float] = None) -> Optional[float]:
        """Feed the store rate into the adaptive controller; returns the
        new sample rate when it moves. Single-controller: this replaces
        the ZK group + leader election (AdaptiveSampler.scala:177-237).

        Safe to call at any cadence — observations are gated to the
        controller's update_freq_s so a tight daemon loop doesn't shrink
        the adaptive windows.
        """
        if self.controller is None:
            return None
        now_s = time.time() if now_s is None else now_s
        freq = self.controller.config.update_freq_s
        if self._last_tick_s is not None and now_s - self._last_tick_s < freq:
            return None
        self._last_tick_s = now_s
        rate = self._flow.observe(float(self.spans_stored), now_s)
        if rate is None:
            return None
        new_rate = self.controller.observe(rate, now_s)
        if new_rate is not None:
            self.sampler.rate = new_rate
        return new_rate

    def flush(self) -> None:
        self.queue.join()

    def close(self) -> None:
        self.queue.close()
        self.store.close()
