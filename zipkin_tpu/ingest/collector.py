"""Collector: receiver → bounded queue → sampler filter → store(s).

Reference wiring (ZipkinCollectorFactory.scala:40-76): the receiver
pushes span batches into the ItemQueue; worker threads run the filter
chain (sampling: keep iff debug or the rate test passes,
SpanSamplerFilter.scala:40-47) and hand survivors to the WriteSpanStore.
The adaptive controller reads the flow from the store counters and
moves the sampler's rate (AdaptiveSampler wiring, SURVEY.md §3.5).
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

from zipkin_tpu.ingest.queue import ItemQueue
from zipkin_tpu.models.span import Span
from zipkin_tpu.sampler.adaptive import (
    AdaptiveConfig,
    AdaptiveSampleRateController,
    FlowEstimator,
)
from zipkin_tpu.sampler.core import Sampler
from zipkin_tpu.store.base import WriteSpanStore


class _ThriftPayload:
    """Queue item marking raw thrift bytes for the columnar fast path.

    ``segments`` keeps transport-level message boundaries (one scribe
    LogEntry / kafka message each) so a corrupt segment can be isolated
    instead of poisoning the whole batch."""

    __slots__ = ("segments",)

    def __init__(self, segments: Sequence[bytes]):
        self.segments = list(segments)


class Collector:
    def __init__(
        self,
        store: WriteSpanStore,
        sampler: Optional[Sampler] = None,
        adaptive: Optional[AdaptiveConfig] = None,
        max_queue: int = 500,
        concurrency: int = 10,
    ):
        self.store = store
        self.sampler = sampler or Sampler(1.0)
        self.queue: ItemQueue = ItemQueue(
            self._write, max_size=max_queue, concurrency=concurrency
        )
        self.controller = (
            AdaptiveSampleRateController(adaptive) if adaptive else None
        )
        self._flow = FlowEstimator()
        self._last_tick_s: Optional[float] = None
        self.spans_dropped = 0
        self.spans_stored = 0
        self.bad_payloads = 0
        # Counters are read-modify-written from every queue worker; the
        # adaptive controller reads them, so lost increments skew rates.
        self._stats_lock = threading.Lock()
        # The fast path needs both the native parser and a store that
        # accepts raw thrift (TpuSpanStore.write_thrift); probed once.
        self._fast_ok: Optional[bool] = None

    # -- pipeline -------------------------------------------------------

    def accept(self, spans: Sequence[Span]) -> None:
        """Receiver-facing entry; raises QueueFullException when full."""
        self.queue.add(list(spans))

    def accept_thrift(self, payload) -> None:
        """Raw thrift Span-sequence entry (scribe/kafka fast path): the
        payload — one bytes blob or a sequence of per-message segments —
        decodes on a worker via the native columnar parser when
        available (ScribeSpanReceiver.scala:96-107's scrooge hot decode),
        falling back to the python codec. Sampling is applied either
        way. Raises QueueFullException when full."""
        segments = [payload] if isinstance(payload, (bytes, bytearray)) \
            else list(payload)
        self.queue.add(_ThriftPayload(segments))

    def _fast_path_available(self) -> bool:
        if self._fast_ok is None:
            if getattr(self.store, "write_thrift", None) is None:
                self._fast_ok = False
            else:
                from zipkin_tpu import native

                self._fast_ok = native.available()
        return self._fast_ok

    def _write(self, item) -> None:
        if isinstance(item, _ThriftPayload):
            self._write_thrift(item.segments)
            return
        spans = item
        kept = [s for s in spans if s.debug or self.sampler.decide(s.trace_id)]
        # One locked counter update per batch (debug spans bypass the
        # sampler and are not counted, matching the fast path).
        n_debug = sum(1 for s in kept if s.debug)
        self.sampler.count(len(kept) - n_debug, len(spans) - len(kept))
        with self._stats_lock:
            self.spans_dropped += len(spans) - len(kept)
        if kept:
            self.store.apply(kept)
            with self._stats_lock:
                self.spans_stored += len(kept)

    def _write_thrift(self, segments) -> None:
        if not self._fast_path_available():
            self._decode_segments_slow(segments)
            return
        from zipkin_tpu.native import ParseCapacityError

        try:
            written, dropped, written_debug = self.store.write_thrift(
                b"".join(segments), sample_threshold=self.sampler.threshold
            )
        except ParseCapacityError:
            # Valid but oversized: halve and retry (single segments that
            # still don't fit go through the chunking python path).
            if len(segments) > 1:
                mid = len(segments) // 2
                self._write_thrift(segments[:mid])
                self._write_thrift(segments[mid:])
            else:
                self._decode_segments_slow(segments)
            return
        except ValueError:
            # A corrupt segment poisons the concatenated parse; isolate
            # it by decoding per segment (slow-path semantics: skip bad,
            # keep good — ScribeReceiver's per-entry 'bad' accounting).
            self._decode_segments_slow(segments)
            return
        # Slow-path counter parity: debug spans never hit the sampler.
        self.sampler.count(written - written_debug, dropped)
        with self._stats_lock:
            self.spans_stored += written
            self.spans_dropped += dropped

    def _decode_segments_slow(self, segments) -> None:
        from zipkin_tpu.wire.thrift import ThriftError, spans_from_bytes

        spans = []
        for seg in segments:
            try:
                spans.extend(spans_from_bytes(seg))
            except ThriftError:
                with self._stats_lock:
                    self.bad_payloads += 1
        if spans:
            self._write(spans)

    # -- control loop (call periodically, e.g. every 30s) ---------------

    def control_tick(self, now_s: Optional[float] = None) -> Optional[float]:
        """Feed the store rate into the adaptive controller; returns the
        new sample rate when it moves. Single-controller: this replaces
        the ZK group + leader election (AdaptiveSampler.scala:177-237).

        Safe to call at any cadence — observations are gated to the
        controller's update_freq_s so a tight daemon loop doesn't shrink
        the adaptive windows.
        """
        if self.controller is None:
            return None
        now_s = time.time() if now_s is None else now_s
        freq = self.controller.config.update_freq_s
        if self._last_tick_s is not None and now_s - self._last_tick_s < freq:
            return None
        self._last_tick_s = now_s
        # Flow source: the store's own counters (the device spans_seen
        # scalar on the TPU store; a psum-ed shard summary when sharded)
        # — BASELINE's "sampler reads its counts directly from the
        # on-device sketches". Host accounting is only the fallback for
        # stores without counters.
        stored = self.store.stored_span_count()
        if stored is None:
            stored = float(self.spans_stored)
        rate = self._flow.observe(stored, now_s)
        if rate is None:
            return None
        new_rate = self.controller.observe(rate, now_s)
        if new_rate is not None:
            self.sampler.rate = new_rate
        return new_rate

    def flush(self) -> None:
        self.queue.join()

    def close(self) -> None:
        self.queue.close()
        self.store.close()
