"""Span receivers: transport payloads → spans → the collector pipeline.

Reference: SpanReceiver (zipkin-collector/.../SpanReceiver.scala:27) and
the scribe receiver's decode/whitelist/pushback behavior
(ScribeSpanReceiver.scala:78-141). The kafka receiver's consumer loop is
a transport concern; its decode path is identical to scribe's minus the
base64 (KafkaProcessor.scala:25) and is covered by ``decode_thrift``.
"""

from __future__ import annotations

import enum
import json
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from zipkin_tpu.ingest.queue import QueueFullException
from zipkin_tpu.wal.log import WalDurabilityError
from zipkin_tpu.models.span import (
    Annotation,
    AnnotationType,
    BinaryAnnotation,
    Endpoint,
    Span,
)
from zipkin_tpu.wire.thrift import (
    ThriftError,
    scribe_message_to_span,
    spans_from_bytes,
)


class ResultCode(enum.Enum):
    """Scribe result codes (scribe.thrift): TRY_LATER = backpressure."""

    OK = 0
    TRY_LATER = 1


class ScribeReceiver:
    """Scribe Log() endpoint: base64-thrift LogEntries → spans → process.

    ``process`` is typically Collector.accept (→ ItemQueue.add); a
    QueueFullException surfaces as TRY_LATER so scribe clients buffer
    and retry (ScribeSpanReceiver.scala:133-141).
    """

    def __init__(
        self,
        process: Callable[[Sequence[Span]], None],
        categories: Iterable[str] = ("zipkin",),
        process_thrift: Optional[Callable[[bytes], None]] = None,
    ):
        self.process = process
        self.process_thrift = process_thrift
        self.categories = {c.lower() for c in categories}
        # Bumped from every API handler thread; unlocked += would lose
        # increments under concurrent Log() calls.
        self._stats_lock = threading.Lock()  # lock-order: 82 receiver-stats
        self.stats: Dict[str, int] = {
            "received": 0, "ignored": 0, "bad": 0, "pushed_back": 0,
        }

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    def log(self, entries: Sequence[tuple]) -> ResultCode:
        """entries: (category, message) pairs — the Scribe.Log call.

        With ``process_thrift`` wired (Collector.accept_thrift), decoded
        payloads stay raw thrift bytes end-to-end and the columnar
        native parser runs on the collector worker — span objects are
        never built on the hot path (the scrooge-decode role,
        ScribeSpanReceiver.scala:96-107).
        """
        if self.process_thrift is not None:
            return self._log_fast(entries)
        spans: List[Span] = []
        for category, message in entries:
            self._bump("received")
            if category.lower() not in self.categories:
                self._bump("ignored")
                continue
            try:
                spans.append(scribe_message_to_span(message))
            except ThriftError:
                self._bump("bad")
        if not spans:
            return ResultCode.OK
        try:
            self.process(spans)
        except (QueueFullException, WalDurabilityError):
            # Queue full and not-yet-durable are the same answer on
            # the wire: don't ack, client retries (the ack-after-
            # durable-append contract, docs/DURABILITY.md).
            self._bump("pushed_back")
            return ResultCode.TRY_LATER
        except Exception:
            # The durable entries run the whole store write path on
            # this handler thread, so its exception surface (suspect
            # store, closing store) lands here; any of it maps to
            # TRY_LATER — a torn connection would read as a lost batch
            # to clients that only retry on the wire code.
            self._bump("pushed_back")
            return ResultCode.TRY_LATER
        return ResultCode.OK

    def _log_fast(self, entries: Sequence[tuple]) -> ResultCode:
        import base64
        import binascii

        raws: List[bytes] = []
        for category, message in entries:
            self._bump("received")
            if category.lower() not in self.categories:
                self._bump("ignored")
                continue
            try:
                if isinstance(message, str):
                    message = message.encode("ascii")
                raws.append(base64.b64decode(message, validate=False))
            except (binascii.Error, ValueError):
                self._bump("bad")
        if not raws:
            return ResultCode.OK
        try:
            # Segments keep entry boundaries so the collector can
            # isolate a thrift-corrupt entry instead of dropping the
            # whole batch.
            self.process_thrift(raws)
        except (QueueFullException, WalDurabilityError):
            # See log(): not-yet-durable == backpressure on the wire.
            self._bump("pushed_back")
            return ResultCode.TRY_LATER
        except Exception:
            # See log(): any store-path failure is TRY_LATER, never a
            # torn connection.
            self._bump("pushed_back")
            return ResultCode.TRY_LATER
        return ResultCode.OK


def decode_thrift(payload: bytes) -> List[Span]:
    """Raw thrift span sequence → spans (the kafka message decode path)."""
    return spans_from_bytes(payload)


class JsonReceiver:
    """JSON span receiver for HTTP-posted spans (the tracegen/web feed).

    Accepts a list of span dicts in the shape the web API emits; not a
    reference transport, but the natural REST ingest door for a modern
    deployment.
    """

    def __init__(self, process: Callable[[Sequence[Span]], None]):
        self.process = process

    def post(self, body: bytes) -> ResultCode:
        spans = [span_from_json(d) for d in json.loads(body)]
        try:
            self.process(spans)
        except QueueFullException:
            return ResultCode.TRY_LATER
        return ResultCode.OK


def _endpoint_from_json(d: Optional[dict]) -> Optional[Endpoint]:
    if not d:
        return None
    return Endpoint(
        ipv4=int(d.get("ipv4", 0)),
        port=int(d.get("port", 0)),
        service_name=d.get("serviceName", "unknown"),
    )


def span_from_json(d: dict) -> Span:
    anns = tuple(
        Annotation(
            timestamp=int(a["timestamp"]),
            value=a["value"],
            host=_endpoint_from_json(a.get("endpoint")),
        )
        for a in d.get("annotations", ())
    )
    banns = []
    for b in d.get("binaryAnnotations", ()):
        t = AnnotationType[b.get("type", "STRING")]
        value = b.get("value", "")
        if t == AnnotationType.BYTES and isinstance(value, str):
            import base64

            value = base64.b64decode(value)
        banns.append(
            BinaryAnnotation(
                key=b["key"], value=value, annotation_type=t,
                host=_endpoint_from_json(b.get("endpoint")),
            )
        )
    def _id(v):
        """Hex string (the wire form) or number → canonical SIGNED
        int64 — keeps span_to_json → span_from_json an exact round
        trip for ids with the top bit set."""
        u = int(v, 16) if isinstance(v, str) else int(v)
        return u - (1 << 64) if u >= (1 << 63) else u

    return Span(
        trace_id=_id(d["traceId"]),
        name=d.get("name", ""),
        id=_id(d["id"]),
        parent_id=(
            None if d.get("parentId") in (None, "")
            else _id(d["parentId"])
        ),
        annotations=anns,
        binary_annotations=tuple(banns),
        debug=bool(d.get("debug", False)),
    )


def _hex_id(v: int) -> str:
    return f"{v & (2**64 - 1):x}"


def endpoint_to_json(e: Optional[Endpoint]):
    if e is None:
        return None
    return {"ipv4": e.ipv4, "port": e.port, "serviceName": e.service_name}


def binary_annotation_to_json(b) -> dict:
    value = b.value
    if isinstance(value, (bytes, bytearray)):
        if b.annotation_type == AnnotationType.BYTES:
            import base64

            value = base64.b64encode(bytes(value)).decode("ascii")
        else:
            value = bytes(value).decode("utf-8", "replace")
    return {
        "key": b.key, "value": value,
        "type": b.annotation_type.name,
        "endpoint": endpoint_to_json(b.host),
    }


def span_to_json(s: Span) -> dict:
    ep = endpoint_to_json
    banns = [binary_annotation_to_json(b) for b in s.binary_annotations]
    # Ids serialize as unsigned hex STRINGS (upstream zipkin JSON
    # convention, and span_from_json's string interpretation): a JSON
    # number round-trips through JS float64, which silently rounds ids
    # above 2^53 — the UI would then fetch the wrong trace.
    return {
        "traceId": _hex_id(s.trace_id),
        "name": s.name,
        "id": _hex_id(s.id),
        "parentId": None if s.parent_id is None else _hex_id(s.parent_id),
        "annotations": [
            {"timestamp": a.timestamp, "value": a.value,
             "endpoint": ep(a.host)}
            for a in s.annotations
        ],
        "binaryAnnotations": banns,
        "debug": s.debug,
    }
