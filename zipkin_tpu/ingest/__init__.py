"""Ingest runtime: bounded queue, receivers, and the collector assembly.

Reference parity: zipkin-collector's ItemQueue pipeline
(ItemQueue.scala:39, SpanReceiver.scala:27, ZipkinCollectorFactory.scala:40-76)
and the scribe/kafka receivers — the host-side runtime that feeds the
device. Backpressure semantics carry over exactly: a full queue raises
QueueFullException, which receivers surface as TRY_LATER so upstream
transports buffer and retry.
"""

from zipkin_tpu.ingest.queue import ItemQueue, QueueFullException  # noqa: F401
from zipkin_tpu.ingest.receiver import (  # noqa: F401
    JsonReceiver,
    ResultCode,
    ScribeReceiver,
)
from zipkin_tpu.ingest.collector import Collector  # noqa: F401
