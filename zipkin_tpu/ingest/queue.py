"""Bounded work queue with worker pool and graceful drain.

Reference semantics (ItemQueue.scala:24-68): bounded buffer (default 500)
with N concurrent workers (default 10); ``add`` fails fast with
QueueFullException when the buffer is full (no blocking — pushback
propagates to the transport); ``close`` stops intake, drains what's
queued, then joins the workers. Gauges (size, active workers) mirror the
reference's stats — served through the telemetry registry, which also
fixes the old unlocked ``processed += 1`` read-modify-write: every
worker bumped the same plain int, so concurrent batches could lose
increments (obs.Counter takes a lock per bump)."""

from __future__ import annotations

import queue
import threading
from typing import Callable, Generic, List, Optional, TypeVar

from zipkin_tpu import obs

T = TypeVar("T")

DEFAULT_MAX_SIZE = 500
DEFAULT_CONCURRENCY = 10


class QueueFullException(RuntimeError):
    """The ingest buffer is full; callers should answer TRY_LATER."""


class ItemQueue(Generic[T]):
    def __init__(
        self,
        process: Callable[[T], None],
        max_size: int = DEFAULT_MAX_SIZE,
        concurrency: int = DEFAULT_CONCURRENCY,
        on_error: Optional[Callable[[T, Exception], None]] = None,
        registry: Optional[obs.Registry] = None,
    ):
        self._process = process
        self._on_error = on_error
        self._q: "queue.Queue[T]" = queue.Queue(maxsize=max_size)
        self._closed = threading.Event()
        self._active = 0  # guarded-by: _active_lock
        self._active_lock = threading.Lock()  # lock-order: 81 queue-active
        reg = registry or obs.default_registry()
        self._c_enqueued = reg.register(obs.Counter(
            "zipkin_queue_enqueued_total",
            "Items accepted into the ingest queue"))
        self._c_rejected = reg.register(obs.Counter(
            "zipkin_queue_rejected_total",
            "Enqueue attempts dropped because the queue was full or "
            "closed (TRY_LATER pushback)"))
        self._c_processed = reg.register(obs.Counter(
            "zipkin_queue_processed_total",
            "Items fully processed by queue workers"))
        self._c_errors = reg.register(obs.Counter(
            "zipkin_queue_errors_total",
            "Items whose processing raised (swallow-and-count)"))
        reg.register(obs.Gauge(
            "zipkin_queue_depth", "Items waiting in the ingest queue",
            fn=self._q.qsize))
        reg.register(obs.Gauge(
            "zipkin_queue_active_workers",
            "Workers currently processing an item",
            fn=lambda: self.active_workers))
        self._workers: List[threading.Thread] = [
            threading.Thread(target=self._loop, name=f"item-queue-{i}",
                             daemon=True)
            for i in range(concurrency)
        ]
        for w in self._workers:
            w.start()

    # -- gauges (ItemQueue.scala:43-48) ---------------------------------

    @property
    def size(self) -> int:
        return self._q.qsize()

    @property
    def active_workers(self) -> int:
        with self._active_lock:
            return self._active

    @property
    def processed(self) -> int:
        return int(self._c_processed.value)

    @property
    def errors(self) -> int:
        return int(self._c_errors.value)

    @property
    def rejected(self) -> int:
        return int(self._c_rejected.value)

    # -- intake ---------------------------------------------------------

    def add(self, item: T) -> None:
        if self._closed.is_set():
            self._c_rejected.inc()
            raise QueueFullException("queue is closed")
        try:
            self._q.put_nowait(item)
        except queue.Full:
            self._c_rejected.inc()
            raise QueueFullException(
                f"ingest queue full ({self._q.maxsize})"
            ) from None
        self._c_enqueued.inc()

    # -- workers --------------------------------------------------------

    def _loop(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._closed.is_set():
                    return
                continue
            with self._active_lock:
                self._active += 1
            try:
                self._process(item)
                self._c_processed.inc()
            except Exception as e:  # swallow-and-count, like the reference
                self._c_errors.inc()
                if self._on_error is not None:
                    self._on_error(item, e)
            finally:
                with self._active_lock:
                    self._active -= 1
                self._q.task_done()

    def join(self) -> None:
        """Block until everything currently queued is processed."""
        self._q.join()

    def close(self, timeout: float = 30.0) -> None:
        """Stop intake, drain the queue, join workers
        (ItemQueue.scala:65-68; 30s default mirrors the collector flag)."""
        self._closed.set()
        self._q.join()
        for w in self._workers:
            w.join(timeout=timeout / max(1, len(self._workers)))
