"""graftlint lock rules: acquisition-order, cycles, unannotated locks,
device-sync-under-write-lock, and called-under call-site checks.

The canonical acquisition order is DECLARED IN CODE: every lock
creation line carries ``# lock-order: <rank>`` (lower = outermost).
The analyzer rebuilds the acquisition graph (lexical with-nesting plus
resolvable-call propagation) and flags:

- ``lock-order``  — acquiring a lock whose rank is <= an already-held
  lock's rank (the ordering that makes ABBA deadlocks impossible);
- ``lock-cycle``  — a cycle in the acquisition graph (including
  self-edges on non-reentrant locks);
- ``unannotated-lock`` — a Lock/RLock/Condition/RWLock creation with
  no ``# lock-order`` annotation (every lock must place itself);
- ``sync-under-lock`` — jax.device_get / block_until_ready /
  np.asarray reachable while holding an RWLock WRITE region (the
  donating-commit stall class r10 fixed by hand in the WAL group
  commit), or any lock annotated ``no-sync``;
- ``called-under`` — a call to a method annotated
  ``# called-under: <lock>`` from a site that doesn't hold it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from zipkin_tpu.analysis.model import (
    CALLED_UNDER,
    Finding,
    LOCK_CYCLE,
    LOCK_ORDER,
    SYNC_UNDER_LOCK,
    UNANNOTATED_LOCK,
)
from zipkin_tpu.analysis.project import Project

# One acquisition-graph edge: held -> acquired, with its evidence site.
Edge = Tuple[str, str, str, int, str, str]  # a, b, path, line, func, via


def build_edges(project: Project) -> List[Edge]:
    """Acquisition edges, memoized on the Project (check_lock_order and
    check_lock_cycles both consume the same list in one analyze run)."""
    cached = getattr(project, "_edge_cache", None)
    if cached is not None:
        return cached
    edges: List[Edge] = []
    for m in project.modules:
        for f in m.all_funcs():
            for acq in f.acquisitions:
                b = project.canon_lock(m, f, acq.ref)
                if not b:
                    continue
                for href in acq.held:
                    a = project.canon_lock(m, f, href)
                    if a and a != b:
                        edges.append((a, b, m.path, acq.line,
                                      f.qualname, "with"))
                    elif a == b and acq.ref[2] is None:
                        # Re-entering a non-reentrant lock.
                        kind = project.locks.get(b)
                        if kind is not None and kind.kind != "rlock":
                            edges.append((a, b, m.path, acq.line,
                                          f.qualname, "re-enter"))
            for call in f.calls:
                if not call.held:
                    continue
                target = project.resolve_call(m, f, call.callee)
                if target is None:
                    continue
                inner = project.may_acquire(target)
                if not inner:
                    continue
                held_keys = set()
                for href in call.held:
                    a = project.canon_lock(m, f, href)
                    if a:
                        held_keys.add(a)
                for (b, _mode) in inner:
                    for a in held_keys:
                        if a != b:
                            edges.append((
                                a, b, m.path, call.line, f.qualname,
                                f"call {target[1]}"))
    project._edge_cache = edges
    return edges


def check_lock_order(project: Project) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[Tuple[str, str, str, str]] = set()
    for a, b, path, line, func, via in build_edges(project):
        if via == "re-enter":
            continue  # reported by lock-cycle as a self-cycle
        da, db = project.locks.get(a), project.locks.get(b)
        if da is None or db is None:
            continue
        if da.rank is None or db.rank is None:
            continue  # unannotated-lock reports the missing rank
        if da.rank >= db.rank:
            key = (a, b, path, func)
            if key in seen:
                continue
            seen.add(key)
            out.append(Finding(
                rule=LOCK_ORDER, path=path, line=line, scope=func,
                message=(f"acquires {b} (rank {db.rank}) while "
                         f"holding {a} (rank {da.rank}) via {via}; "
                         "canonical order requires strictly "
                         "increasing ranks"),
                detail=f"{a}->{b}"))
    return out


def check_lock_cycles(project: Project) -> List[Finding]:
    edges = build_edges(project)
    graph: Dict[str, Set[str]] = {}
    evidence: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    out: List[Finding] = []
    for a, b, path, line, func, via in edges:
        if via == "re-enter":
            out.append(Finding(
                rule=LOCK_CYCLE, path=path, line=line, scope=func,
                message=f"re-enters non-reentrant lock {a} "
                        "(self-deadlock)",
                detail=f"self:{a}"))
            continue
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
        evidence.setdefault((a, b), (path, line, func))
    # Tarjan SCC over the acquisition graph.
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in onstack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    for scc in sccs:
        a = scc[0]
        nxt = next((b for b in scc[1:] if b in graph.get(a, ())), a)
        path, line, func = evidence.get((a, nxt), ("", 0, "?"))
        out.append(Finding(
            rule=LOCK_CYCLE, path=path, line=line, scope=func,
            message=("lock acquisition cycle: "
                     + " -> ".join(scc + [scc[0]])),
            detail="cycle:" + ",".join(scc)))
    return out


def check_unannotated(project: Project) -> List[Finding]:
    out = []
    for key in sorted(project.locks):
        d = project.locks[key]
        if d.rank is None:
            out.append(Finding(
                rule=UNANNOTATED_LOCK, path=d.path, line=d.line,
                scope=key,
                message=(f"lock {key} has no '# lock-order: <rank>' "
                         "annotation — every lock must declare its "
                         "place in the canonical acquisition order"),
                detail=key))
    return out


def _write_regions_held(project: Project, module, func,
                        held) -> Optional[str]:
    """The canonical key of a held no-sync region (an RWLock held in
    write mode, or any lock flagged ``no-sync``), else None."""
    for href in held:
        key = project.canon_lock(module, func, href)
        if key is None:
            continue
        d = project.locks.get(key)
        if d is None:
            continue
        if d.kind == "rwlock" and href[2] == "write":
            return key
        if "no-sync" in d.flags:
            return key
    return None


def check_sync_under_lock(project: Project) -> List[Finding]:
    out: List[Finding] = []
    seen = set()
    for m in project.modules:
        for f in m.all_funcs():
            for s in f.syncs:
                key = _write_regions_held(project, m, f, s.held)
                if key is None:
                    continue
                fp = (m.path, f.qualname, s.what, key)
                if fp in seen:
                    continue
                seen.add(fp)
                out.append(Finding(
                    rule=SYNC_UNDER_LOCK, path=m.path, line=s.line,
                    scope=f.qualname,
                    message=(f"{s.what} inside the {key} write-lock "
                             "region — a host/device sync stalls "
                             "every writer behind this hold"),
                    detail=f"{s.what}|{key}"))
            for call in f.calls:
                key = _write_regions_held(project, m, f, call.held)
                if key is None:
                    continue
                target = project.resolve_call(m, f, call.callee)
                if target is None:
                    continue
                inner = project.may_sync(target)
                if not inner:
                    continue
                what = ",".join(sorted(inner))
                fp = (m.path, f.qualname, target[1], key)
                if fp in seen:
                    continue
                seen.add(fp)
                out.append(Finding(
                    rule=SYNC_UNDER_LOCK, path=m.path, line=call.line,
                    scope=f.qualname,
                    message=(f"call to {target[1]} (which may run "
                             f"{what}) inside the {key} write-lock "
                             "region"),
                    detail=f"call:{target[1]}|{key}"))
    return out


def check_called_under(project: Project) -> List[Finding]:
    """Call sites of ``# called-under:``-annotated methods must hold
    the declared lock (attr+mode matched; base expression is not
    required to match — a linter-grade check, not a proof)."""
    out: List[Finding] = []
    annotated: Dict[Tuple[str, str], Tuple[str, Optional[str]]] = {}
    for m in project.modules:
        for f in m.all_funcs():
            for (base, attr, mode) in f.called_under:
                annotated[(m.modname, f.qualname)] = (attr, mode)
    if not annotated:
        return out
    for m in project.modules:
        for f in m.all_funcs():
            for call in f.calls:
                target = project.resolve_call(m, f, call.callee)
                if target is None or target not in annotated:
                    continue
                attr, mode = annotated[target]
                ok = False
                for (_b, a, hm) in call.held + tuple(f.called_under):
                    if a != attr:
                        continue
                    if mode is None or hm == mode or hm == "write":
                        ok = True
                        break
                if not ok:
                    out.append(Finding(
                        rule=CALLED_UNDER, path=m.path,
                        line=call.line, scope=f.qualname,
                        message=(f"calls {target[1]} without holding "
                                 f"{attr}"
                                 + (f".{mode}" if mode else "")
                                 + f" (declared '# called-under' on "
                                   f"{target[1]})"),
                        detail=f"{target[1]}|{attr}"))
    return out
