"""graftlint: the project's AST-based concurrency & JAX-hazard
analyzer (docs/STATIC_ANALYSIS.md).

The five-thread write machine built across PRs 4-8 (pipeline stages,
EvictionSealer, WAL group commit, ResidentCoalescer, mirror folds
under the commit write lock) rests on conventions — canonical lock
order, guarded-by ownership of shared attributes, no device sync under
the write lock, zero steady-state recompiles, donated buffers never
reused. graftlint turns each convention into a named, suppressible,
baselined rule so the next concurrency layer (sharding, replication,
multi-tenant) grows against a machine-checked contract instead of
whichever test happens to trip first.

Entry points: ``scripts/lint.py`` (CLI), :func:`analyze` +
:func:`load_project` (library), the fixture corpus under
``tests/graftlint_corpus/`` (per-rule true/false-positive pins).
"""

from zipkin_tpu.analysis.cli import analyze, main
from zipkin_tpu.analysis.model import ALL_RULES, Finding
from zipkin_tpu.analysis.project import Project, load_project

__all__ = [
    "ALL_RULES",
    "Finding",
    "Project",
    "analyze",
    "load_project",
    "main",
]
