"""graftlint data model: findings, annotations, and the per-module
facts the AST visitor extracts.

The analyzer enforces the concurrency/JAX conventions the write path
has accumulated since PR 4 (lock ordering, mirror-fold-under-write-
lock, no device sync while holding the commit write lock, zero
steady-state recompiles) as named, suppressible rules — see
docs/STATIC_ANALYSIS.md for the catalog. Everything here is plain
dataclasses; the visitor (visitor.py) fills them, the project loader
(project.py) links them across modules, and the rule modules
(rules_*.py) read them.

Annotation conventions (comments the visitor parses):

- ``# lock-order: <rank> [prose]`` on a lock's creation line — declares
  the lock's position in the canonical acquisition order (lower rank =
  acquired first / outermost).
- ``# guarded-by: <lockattr>`` on a shared attribute's ``__init__``
  assignment — every non-init access of the attribute must hold that
  lock. For RWLock-guarded attributes, ``# guarded-by: <attr>.write``
  requires the write lock for stores and either mode for loads.
- ``# called-under: <lockattr>[.read|.write]`` on a ``def`` line — the
  method runs with that lock already held; resolvable call sites are
  checked for it, and the body is analyzed as if holding it.
- ``# graftlint: disable=<rule>[,<rule>]`` on a finding's line (or its
  ``def`` line, suppressing the whole function) — inline suppression.
- ``# graftlint: disable-file=<rule>[,<rule>]`` anywhere — suppresses
  a rule for the whole file.
"""

from __future__ import annotations

import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# Rule ids (the catalog; docs/STATIC_ANALYSIS.md documents each).
LOCK_ORDER = "lock-order"
LOCK_CYCLE = "lock-cycle"
UNANNOTATED_LOCK = "unannotated-lock"
GUARDED_BY = "guarded-by"
CALLED_UNDER = "called-under"
SYNC_UNDER_LOCK = "sync-under-lock"
JIT_TRACED_BRANCH = "jit-traced-branch"
JIT_NONSTATIC_CLOSURE = "jit-nonstatic-closure"
USE_AFTER_DONATE = "use-after-donate"
SWALLOWED_EXCEPTION = "swallowed-exception"
COLLECTIVE_UNDER_READ_LOCK = "collective-under-read-lock"

ALL_RULES = (
    LOCK_ORDER, LOCK_CYCLE, UNANNOTATED_LOCK, GUARDED_BY, CALLED_UNDER,
    SYNC_UNDER_LOCK, JIT_TRACED_BRANCH, JIT_NONSTATIC_CLOSURE,
    USE_AFTER_DONATE, SWALLOWED_EXCEPTION, COLLECTIVE_UNDER_READ_LOCK,
)


@dataclass(frozen=True)
class Finding:
    """One rule violation. ``fingerprint`` is line-number-free so the
    baseline survives unrelated edits: (rule, path, scope, detail)."""

    rule: str
    path: str  # repo-relative
    line: int
    scope: str  # enclosing qualname ("mod", "Class.meth", ...)
    message: str
    detail: str  # stable discriminator (no line numbers)

    @property
    def fingerprint(self) -> str:
        return f"{self.path}::{self.scope}::{self.detail}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] "
                f"{self.message}  (in {self.scope})")


# A lock reference as the visitor sees an acquisition or annotation:
# (base, attr, mode). ``base`` is the owner expression ("self",
# "store", "self._store", or "<module>" for module-level locks);
# ``mode`` is "read"/"write" for RWLock acquisitions, None for plain
# Lock/RLock/Condition.
LockRef = Tuple[str, str, Optional[str]]


@dataclass
class LockDef:
    """One lock creation site (``self._x = threading.Lock()`` or a
    module-level twin)."""

    key: str  # canonical "Class.attr" or "module.attr"
    kind: str  # "lock" | "rlock" | "condition" | "rwlock"
    path: str
    line: int
    rank: Optional[int] = None  # from "# lock-order: N"
    flags: Tuple[str, ...] = ()  # extra markers after the rank


@dataclass
class Acquisition:
    """One ``with <lock>:`` entered while ``held`` were already held
    (innermost-last)."""

    ref: LockRef
    held: Tuple[LockRef, ...]
    line: int
    func: str  # qualname of the enclosing function


@dataclass
class AttrAccess:
    """One attribute read/write: ``base.attr`` with the lexically held
    locks at that point."""

    base: str
    attr: str
    is_store: bool
    held: Tuple[LockRef, ...]
    line: int
    func: str


@dataclass
class CallSite:
    """One call with enough structure to resolve package-internal
    targets. ``callee`` is one of:
    ("self", meth) / ("name", fn) / ("mod", alias, fn) /
    ("selfattr", attr, meth) / ("local", var, meth)."""

    callee: Tuple[str, ...]
    held: Tuple[LockRef, ...]
    line: int
    func: str


@dataclass
class SyncCall:
    """A host-synchronizing call (jax.device_get /
    block_until_ready / np.asarray) and the locks held around it."""

    what: str
    held: Tuple[LockRef, ...]
    line: int
    func: str


@dataclass
class ExceptInfo:
    """One broad ``except`` clause (Exception/BaseException/bare)."""

    line: int
    func: str
    bound_name: Optional[str]
    handles: bool  # re-raises, uses the exception, or logs/counts


@dataclass
class JitFunc:
    """A module-level jitted function (@partial(jax.jit, ...) or
    ``name = jax.jit(fn, ...)``)."""

    name: str
    params: Tuple[str, ...]
    static_params: Tuple[str, ...]
    donate_params: Tuple[str, ...]
    donate_idx: Tuple[int, ...]
    line: int


@dataclass
class FuncModel:
    qualname: str
    line: int
    cls: Optional[str]  # owning class name or None
    called_under: Tuple[LockRef, ...] = ()
    acquisitions: List[Acquisition] = field(default_factory=list)
    accesses: List[AttrAccess] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    syncs: List[SyncCall] = field(default_factory=list)
    excepts: List[ExceptInfo] = field(default_factory=list)
    suppressed: Tuple[str, ...] = ()  # def-line disable=... rules


@dataclass
class ClassModel:
    name: str
    line: int
    bases: Tuple[str, ...]
    lock_attrs: Dict[str, LockDef] = field(default_factory=dict)
    # attr -> (lock attr, mode) from "# guarded-by:" annotations
    guarded: Dict[str, Tuple[str, Optional[str]]] = (
        field(default_factory=dict))
    # attr -> class name (resolved in-package) for self.attr.m() calls
    attr_types: Dict[str, str] = field(default_factory=dict)
    # attr -> assignment line in __init__ (for --fix-annotations)
    attr_init_lines: Dict[str, int] = field(default_factory=dict)
    methods: Dict[str, FuncModel] = field(default_factory=dict)


@dataclass
class ModuleModel:
    path: str  # repo-relative
    modname: str  # dotted ("zipkin_tpu.store.tpu")
    classes: Dict[str, ClassModel] = field(default_factory=dict)
    functions: Dict[str, FuncModel] = field(default_factory=dict)
    module_locks: Dict[str, LockDef] = field(default_factory=dict)
    jit_funcs: Dict[str, JitFunc] = field(default_factory=dict)
    # import alias -> dotted module ("dev" -> "zipkin_tpu.store.device")
    imports: Dict[str, str] = field(default_factory=dict)
    # imported name -> (module, name) for "from X import Y [as Z]"
    from_imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    file_suppressed: Set[str] = field(default_factory=set)
    comments: Dict[int, str] = field(default_factory=dict)
    findings: List[Finding] = field(default_factory=list)

    def all_funcs(self) -> List[FuncModel]:
        out = list(self.functions.values())
        for c in self.classes.values():
            out.extend(c.methods.values())
        return out


_DISABLE_RE = re.compile(r"graftlint:\s*disable=([\w,\- ]+)")
_DISABLE_FILE_RE = re.compile(r"graftlint:\s*disable-file=([\w,\- ]+)")
_LOCK_ORDER_RE = re.compile(r"lock-order:\s*(\d+)((?:\s+[\w\-]+)*)")
_GUARDED_RE = re.compile(r"guarded-by:\s*([\w\.]+)")
_CALLED_UNDER_RE = re.compile(r"called-under:\s*([\w\.]+)")


def extract_comments(source: str) -> Dict[int, str]:
    """line -> comment text, via tokenize (robust against '#' inside
    strings, which a regex scan would misread)."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        pass
    return out


def parse_disables(comment: str) -> Tuple[str, ...]:
    m = _DISABLE_RE.search(comment)
    if not m:
        return ()
    return tuple(r.strip() for r in m.group(1).split(",") if r.strip())


def parse_file_disables(comment: str) -> Tuple[str, ...]:
    m = _DISABLE_FILE_RE.search(comment)
    if not m:
        return ()
    return tuple(r.strip() for r in m.group(1).split(",") if r.strip())


def parse_lock_order(comment: str):
    """(rank, flags) from '# lock-order: 40 no-sync ...', or None."""
    m = _LOCK_ORDER_RE.search(comment)
    if not m:
        return None
    flags = tuple(f for f in m.group(2).split() if f)
    return int(m.group(1)), flags


def parse_guarded_by(comment: str):
    """(lock attr, mode) from '# guarded-by: _lock' or
    '# guarded-by: _rw.write', or None."""
    m = _GUARDED_RE.search(comment)
    if not m:
        return None
    spec = m.group(1)
    if "." in spec:
        attr, mode = spec.split(".", 1)
        return attr, mode
    return spec, None


def parse_called_under(comment: str):
    m = _CALLED_UNDER_RE.search(comment)
    if not m:
        return None
    spec = m.group(1)
    if "." in spec:
        attr, mode = spec.split(".", 1)
        return ("self", attr, mode)
    return ("self", spec, None)
