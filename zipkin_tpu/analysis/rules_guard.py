"""graftlint guarded-by rule: shared attributes annotated
``# guarded-by: <lock>`` on their ``__init__`` assignment may only be
read/written while holding that lock.

- Self accesses are enforced in the declaring class and its in-package
  subclasses; ``__init__`` is exempt (single-threaded construction).
- Foreign accesses (``store._sealed_upto`` from another module) are
  enforced when the attribute is annotated in exactly ONE class: the
  access must sit inside ``with <same base>.<lock>`` textually.
- RWLock-guarded attributes (``# guarded-by: _rw.write``): stores
  require the write lock; loads accept read or write.
- A method annotated ``# called-under: <lock>`` is analyzed as holding
  it (the *_locked helper pattern); rules_locks checks its call sites.

``suggest_annotations`` powers ``scripts/lint.py --fix-annotations``:
attributes consistently accessed under exactly one of the class's own
locks get the annotation written for them.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from zipkin_tpu.analysis.model import (
    Finding,
    GUARDED_BY,
)
from zipkin_tpu.analysis.project import Project


def _mode_ok(required_mode: Optional[str], is_store: bool,
             held_mode: Optional[str]) -> bool:
    if required_mode is None:
        return True
    if is_store:
        return held_mode == "write"
    return held_mode in ("read", "write")


def _held_satisfies(held, base: str, lock_attr: str,
                    required_mode: Optional[str],
                    is_store: bool) -> bool:
    for (hb, ha, hm) in held:
        if ha != lock_attr:
            continue
        if hb != base:
            continue
        if _mode_ok(required_mode, is_store, hm):
            return True
    return False


def _subclasses_of(project: Project, name: str) -> List[str]:
    out = [name]
    changed = True
    while changed:
        changed = False
        for cname, (_m, cm) in project.classes.items():
            if cname in out:
                continue
            for b in cm.bases:
                if b.rsplit(".", 1)[-1] in out:
                    out.append(cname)
                    changed = True
                    break
    return out


def check_guarded_by(project: Project) -> List[Finding]:
    out: List[Finding] = []
    # Global map for foreign-access enforcement: attr -> unique
    # (class, lock, mode) or None when ambiguous.
    foreign: Dict[str, Optional[Tuple[str, str, Optional[str]]]] = {}
    for cname, (_m, cm) in project.classes.items():
        for attr, (lock, mode) in cm.guarded.items():
            if attr in foreign:
                foreign[attr] = None  # ambiguous across classes
            else:
                foreign[attr] = (cname, lock, mode)

    # Self accesses, per declaring class + in-package subclasses.
    for cname, (mod, cm) in project.classes.items():
        if not cm.guarded:
            continue
        family = _subclasses_of(project, cname)
        for sub in family:
            smod, scm = project.classes[sub]
            for mname, f in scm.methods.items():
                if mname == "__init__":
                    continue
                for acc in f.accesses:
                    if acc.base != "self" or acc.attr not in cm.guarded:
                        continue
                    lock, mode = cm.guarded[acc.attr]
                    if _held_satisfies(
                            acc.held + tuple(f.called_under), "self",
                            lock, mode, acc.is_store):
                        continue
                    kind = "write of" if acc.is_store else "read of"
                    out.append(Finding(
                        rule=GUARDED_BY, path=smod.path, line=acc.line,
                        scope=f.qualname,
                        message=(f"{kind} {cname}.{acc.attr} without "
                                 f"holding {lock}"
                                 + (f".{mode}" if mode else "")
                                 + " (declared '# guarded-by' on its "
                                   "__init__ assignment)"),
                        detail=f"{cname}.{acc.attr}|"
                               f"{'store' if acc.is_store else 'load'}"))

    # Foreign accesses: obj._attr where _attr is uniquely annotated.
    # PRIVATE attrs only — public twin names are shared by design
    # (the device StoreState fields mirror SketchMirror's arrays), so
    # name-matching a public attr across types would cry wolf.
    for m in project.modules:
        for f in m.all_funcs():
            for acc in f.accesses:
                if acc.base in ("self", "<expr>"):
                    continue
                if not acc.attr.startswith("_"):
                    continue
                spec = foreign.get(acc.attr)
                if spec is None:
                    continue
                cname, lock, mode = spec
                # Skip accesses from the declaring family (handled
                # above via self; other bases in-family are aliases we
                # can't type — only flag clearly-foreign modules).
                if f.cls and f.cls in _subclasses_of(project, cname):
                    continue
                if _held_satisfies(acc.held, acc.base, lock, mode,
                                   acc.is_store):
                    continue
                kind = "write of" if acc.is_store else "read of"
                out.append(Finding(
                    rule=GUARDED_BY, path=m.path, line=acc.line,
                    scope=f.qualname,
                    message=(f"{kind} {acc.base}.{acc.attr} "
                             f"({cname}.{acc.attr} is guarded by "
                             f"{lock}"
                             + (f".{mode}" if mode else "")
                             + f") outside 'with {acc.base}.{lock}'"),
                    detail=f"foreign:{cname}.{acc.attr}|"
                           f"{acc.base}|"
                           f"{'store' if acc.is_store else 'load'}"))
    return out


def suggest_annotations(project: Project) -> List[Tuple[str, int, str,
                                                        str]]:
    """(path, line, attr, lock) proposals: private attrs assigned in
    __init__, unannotated, accessed >= 2 times outside __init__, and
    ALWAYS under exactly one of the class's own locks."""
    out = []
    for cname in sorted(project.classes):
        mod, cm = project.classes[cname]
        if not cm.lock_attrs:
            continue
        for attr, line in sorted(cm.attr_init_lines.items()):
            if (not attr.startswith("_") or attr in cm.guarded
                    or attr in cm.lock_attrs):
                continue
            locks_seen = set()
            n = 0
            ok = True
            for mname, f in cm.methods.items():
                if mname == "__init__":
                    continue
                for acc in f.accesses:
                    if acc.base != "self" or acc.attr != attr:
                        continue
                    n += 1
                    held_own = {
                        ha for (hb, ha, _hm) in
                        acc.held + tuple(f.called_under)
                        if hb == "self" and ha in cm.lock_attrs
                    }
                    if not held_own:
                        ok = False
                    locks_seen.update(held_own)
            if ok and n >= 2 and len(locks_seen) == 1:
                out.append((mod.path, line, attr, locks_seen.pop()))
    return out


def apply_annotations(repo_root: str,
                      proposals: List[Tuple[str, int, str, str]],
                      ) -> List[str]:
    """Append '# guarded-by: <lock>' to each proposed __init__
    assignment line. Returns human-readable edit descriptions."""
    import os

    edits: Dict[str, List[Tuple[int, str, str]]] = {}
    for path, line, attr, lock in proposals:
        edits.setdefault(path, []).append((line, attr, lock))
    done = []
    for path, items in edits.items():
        full = os.path.join(repo_root, path)
        with open(full, "r", encoding="utf-8") as fh:
            lines = fh.readlines()
        for line, attr, lock in sorted(items, reverse=True):
            idx = line - 1
            if idx >= len(lines) or "guarded-by" in lines[idx]:
                continue
            text = lines[idx].rstrip("\n")
            lines[idx] = f"{text}  # guarded-by: {lock}\n"
            done.append(f"{path}:{line}: {attr} -> guarded-by: {lock}")
        with open(full, "w", encoding="utf-8") as fh:
            fh.writelines(lines)
    return done
