"""graftlint CLI (the engine behind ``scripts/lint.py``).

Usage:
    python scripts/lint.py [paths...] [--baseline FILE]
        [--write-baseline] [--fix-annotations] [--rules r1,r2]
        [--format text|json] [--list-rules] [--with-pyflakes]

Exit status: 0 when every finding is covered by the baseline (or there
are none), 1 when NEW findings exist, 2 on usage errors. See
docs/STATIC_ANALYSIS.md for the rule catalog and workflows.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

from zipkin_tpu.analysis import baseline as baseline_mod
from zipkin_tpu.analysis.model import ALL_RULES, Finding
from zipkin_tpu.analysis.project import Project, load_project
from zipkin_tpu.analysis.rules_guard import (
    apply_annotations,
    check_guarded_by,
    suggest_annotations,
)
from zipkin_tpu.analysis.rules_jax import (
    check_collective_read_lock,
    check_jit_rules,
    check_use_after_donate,
)
from zipkin_tpu.analysis.rules_locks import (
    check_called_under,
    check_lock_cycles,
    check_lock_order,
    check_sync_under_lock,
    check_unannotated,
)
from zipkin_tpu.analysis.rules_misc import check_swallowed

DEFAULT_BASELINE = "graftlint-baseline.json"

_CHECKS = (
    check_lock_order,
    check_lock_cycles,
    check_unannotated,
    check_guarded_by,
    check_called_under,
    check_sync_under_lock,
    check_jit_rules,
    check_use_after_donate,
    check_collective_read_lock,
    check_swallowed,
)


def analyze(project: Project,
            rules: Optional[List[str]] = None) -> List[Finding]:
    """All findings for ``project``, suppressions applied, sorted."""
    findings: List[Finding] = []
    for check in _CHECKS:
        findings.extend(check(project))
    if rules:
        findings = [f for f in findings if f.rule in rules]
    return sorted(_apply_suppressions(project, findings),
                  key=lambda f: (f.path, f.line, f.rule, f.detail))


def _apply_suppressions(project: Project,
                        findings: List[Finding]) -> List[Finding]:
    mods = {m.path: m for m in project.modules}
    func_suppress: Dict[str, Dict[str, tuple]] = {}
    for m in project.modules:
        func_suppress[m.path] = {
            f.qualname: f.suppressed for f in m.all_funcs()
        }
    out = []
    for f in findings:
        m = mods.get(f.path)
        if m is not None:
            if f.rule in m.file_suppressed:
                continue
            from zipkin_tpu.analysis.model import parse_disables

            line_dis = parse_disables(m.comments.get(f.line, ""))
            if f.rule in line_dis:
                continue
            if f.rule in func_suppress[f.path].get(f.scope, ()):
                continue
        out.append(f)
    return out


def run_external_linters(repo_root: str, paths: List[str]) -> int:
    """Optional ruff/pyflakes pass (generic pyflakes-class checks stay
    out of graftlint, which carries only project-specific rules). Both
    are soft dependencies: absent tools are skipped, not failures —
    the container does not bake them in."""
    rc = 0
    for tool in (("ruff", "check"), ("pyflakes",)):
        probe = subprocess.run(
            [sys.executable, "-m", tool[0], "--version"],
            capture_output=True, cwd=repo_root)
        if probe.returncode != 0:
            print(f"graftlint: {tool[0]} not installed; skipping",
                  file=sys.stderr)
            continue
        got = subprocess.run(
            [sys.executable, "-m", *tool, *paths], cwd=repo_root)
        rc = rc or got.returncode
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="zipkin-tpu concurrency/JAX-hazard analyzer")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: zipkin_tpu)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline JSON (default: {DEFAULT_BASELINE} "
                         "at the repo root when present)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from current "
                         "findings and exit 0")
    ap.add_argument("--fix-annotations", action="store_true",
                    help="insert '# guarded-by:' annotations for "
                         "attributes consistently accessed under "
                         "exactly one lock")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--with-external", action="store_true",
                    help="also run ruff/pyflakes when installed")
    ap.add_argument("--repo-root", default=None)
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(r)
        return 0

    # cli.py lives at zipkin_tpu/analysis/cli.py: the repo root is two
    # levels up; --repo-root overrides for odd layouts.
    repo_root = os.path.abspath(
        args.repo_root if args.repo_root else os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..", ".."))

    paths = args.paths or [os.path.join(repo_root, "zipkin_tpu")]
    paths = [p if os.path.isabs(p) else os.path.join(repo_root, p)
             for p in paths]
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if rules:
        unknown = [r for r in rules if r not in ALL_RULES]
        if unknown:
            print(f"graftlint: unknown rules {unknown}; see "
                  "--list-rules", file=sys.stderr)
            return 2

    t0 = time.perf_counter()
    project = load_project(paths, repo_root)

    if args.fix_annotations:
        proposals = suggest_annotations(project)
        edits = apply_annotations(repo_root, proposals)
        for e in edits:
            print(e)
        print(f"graftlint: annotated {len(edits)} attribute(s)")
        return 0

    findings = analyze(project, rules)

    baseline_path = args.baseline or os.path.join(
        repo_root, DEFAULT_BASELINE)
    if args.write_baseline:
        baseline_mod.save(baseline_path, findings)
        print(f"graftlint: wrote {len(findings)} finding(s) to "
              f"{os.path.relpath(baseline_path, repo_root)}")
        return 0

    if os.path.exists(baseline_path):
        base = baseline_mod.load(baseline_path)
        new, stale = baseline_mod.diff(findings, base)
    else:
        new, stale = findings, []

    elapsed = time.perf_counter() - t0
    if args.format == "json":
        print(json.dumps({
            "metric": "graftlint",
            "files": len(project.modules),
            "findings_total": len(findings),
            "findings_new": len(new),
            "stale_baseline_entries": len(stale),
            "elapsed_s": round(elapsed, 3),
            "new": [f.__dict__ for f in new],
        }))
    else:
        for f in new:
            print(f.render())
        for s in stale:
            print(f"note: stale baseline entry (no longer occurs): {s}",
                  file=sys.stderr)
        print(f"graftlint: {len(project.modules)} files, "
              f"{len(findings)} finding(s), {len(new)} new, "
              f"{len(stale)} stale baseline entr(ies) "
              f"in {elapsed:.2f}s", file=sys.stderr)

    rc = 1 if new else 0
    if args.with_external:
        rc = rc or run_external_linters(
            repo_root, [os.path.relpath(p, repo_root) for p in paths])
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
