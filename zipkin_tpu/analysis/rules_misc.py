"""graftlint swallowed-exception rule: a broad ``except Exception:``
(or bare ``except:`` / ``except BaseException:``) must re-raise, use
the bound exception (park it, wrap it, attach it), or log/count it
(logging call or an obs-registry counter bump). A handler that
silently drops the error hides exactly the class of failure the obs
layer (PR 2) exists to surface.
"""

from __future__ import annotations

from typing import Dict, List

from zipkin_tpu.analysis.model import Finding, SWALLOWED_EXCEPTION
from zipkin_tpu.analysis.project import Project


def check_swallowed(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for m in project.modules:
        ordinals: Dict[str, int] = {}
        for f in m.all_funcs():
            for exc in f.excepts:
                n = ordinals.get(f.qualname, 0)
                ordinals[f.qualname] = n + 1
                if exc.handles:
                    continue
                out.append(Finding(
                    rule=SWALLOWED_EXCEPTION, path=m.path,
                    line=exc.line, scope=f.qualname,
                    message=("broad except swallows the exception — "
                             "re-raise, park/log it, or count it via "
                             "the obs registry"),
                    detail=f"{f.qualname}#{n}"))
    return out
