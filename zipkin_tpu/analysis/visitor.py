"""graftlint AST visitor: one pass per module extracting the facts the
rules need (lock defs + acquisitions with held-lock context, attribute
accesses, resolvable call sites, host-sync calls, broad excepts, jit
decorations). No rule logic lives here — see rules_*.py.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from zipkin_tpu.analysis.model import (
    Acquisition,
    AttrAccess,
    CallSite,
    ClassModel,
    ExceptInfo,
    FuncModel,
    JitFunc,
    LockDef,
    LockRef,
    ModuleModel,
    SyncCall,
    extract_comments,
    parse_called_under,
    parse_disables,
    parse_file_disables,
    parse_guarded_by,
    parse_lock_order,
)

# threading/concurrency constructors that define a lock.
_LOCK_CTORS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "RWLock": "rwlock",
}

# Calls that force a host<->device synchronization (the class of stall
# r10 moved off the append lock by hand; sync-under-lock gates it).
_SYNC_FUNCS = {
    ("jax", "device_get"): "jax.device_get",
    ("jax", "block_until_ready"): "jax.block_until_ready",
    ("np", "asarray"): "np.asarray",
    ("numpy", "asarray"): "np.asarray",
}

# Handler-body call names that count as "handled" for
# swallowed-exception (logging, obs counters, error parking...).
_HANDLING_NAMES = {
    "log", "debug", "info", "warning", "warn", "error", "exception",
    "critical", "inc", "observe", "record", "count", "add", "put",
    "_bump", "_count", "_park_error", "park_error", "fail", "kill",
    "notify_all", "print_exc",
}


def _expr_str(node: ast.AST) -> str:
    """Compact source-ish rendering of a name/attribute chain; opaque
    expressions collapse to '<expr>' so fingerprints stay stable."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_expr_str(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return f"{_expr_str(node.func)}()"
    if isinstance(node, ast.Subscript):
        return f"{_expr_str(node.value)}[]"
    return "<expr>"


def _ctor_kind(call: ast.AST) -> Optional[str]:
    """'lock'/'rlock'/'condition'/'rwlock' when ``call`` constructs
    one (threading.Lock(), Condition(), RWLock(), ...)."""
    if not isinstance(call, ast.Call):
        return None
    f = call.func
    if isinstance(f, ast.Attribute):
        return _LOCK_CTORS.get(f.attr)
    if isinstance(f, ast.Name):
        return _LOCK_CTORS.get(f.id)
    return None


class _FuncScanner(ast.NodeVisitor):
    """Walks ONE function body tracking the lexically held lock stack.
    Nested function defs are skipped (they run later, under different
    locks); nested lambdas are walked WITHOUT the held context for
    accesses (gauge callbacks run on the exposition thread)."""

    def __init__(self, module: "ModuleVisitor", fm: FuncModel,
                 lock_attr_names: Set[str],
                 module_lock_names: Set[str]):
        self.module = module
        self.fm = fm
        self.lock_attr_names = lock_attr_names
        self.module_lock_names = module_lock_names
        self.held: List[LockRef] = list(fm.called_under)
        # Local aliases: var -> ("selfattr", attr) for x = self.attr.
        self.aliases: Dict[str, Tuple[str, str]] = {}

    # -- lock reference recognition --------------------------------------

    def _lock_ref(self, expr: ast.AST) -> Optional[LockRef]:
        # with self._rw.read(): / .write()
        if (isinstance(expr, ast.Call) and not expr.args
                and isinstance(expr.func, ast.Attribute)
                and expr.func.attr in ("read", "write")):
            inner = expr.func.value
            if (isinstance(inner, ast.Attribute)
                    and inner.attr in self.lock_attr_names):
                return (_expr_str(inner.value), inner.attr,
                        expr.func.attr)
            if (isinstance(inner, ast.Name)
                    and inner.id in self.module_lock_names):
                return ("<module>", inner.id, expr.func.attr)
            return None
        # with self._lock: / store._cap_lock:
        if (isinstance(expr, ast.Attribute)
                and expr.attr in self.lock_attr_names):
            return (_expr_str(expr.value), expr.attr, None)
        # with _MODULE_LOCK:
        if (isinstance(expr, ast.Name)
                and expr.id in self.module_lock_names):
            return ("<module>", expr.id, None)
        return None

    # -- visitors --------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        refs = []
        for item in node.items:
            r = self._lock_ref(item.context_expr)
            if r is not None:
                refs.append(r)
                self.fm.acquisitions.append(Acquisition(
                    ref=r, held=tuple(self.held), line=node.lineno,
                    func=self.fm.qualname))
            else:
                # Still scan non-lock context managers (open(), ...).
                self.visit(item.context_expr)
        self.held.extend(refs)
        for stmt in node.body:
            self.visit(stmt)
        if refs:
            del self.held[-len(refs):]

    def visit_FunctionDef(self, node) -> None:
        pass  # nested defs: separate execution context

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # A lambda (gauge callback, key fn) executes later on some
        # other thread: record its accesses with NO held locks.
        saved, self.held = self.held, []
        self.visit(node.body)
        self.held = saved

    def visit_Attribute(self, node: ast.Attribute) -> None:
        base = _expr_str(node.value)
        self.fm.accesses.append(AttrAccess(
            base=base, attr=node.attr,
            is_store=isinstance(node.ctx, (ast.Store, ast.Del)),
            held=tuple(self.held), line=node.lineno,
            func=self.fm.qualname))
        self.visit(node.value)

    def visit_Assign(self, node: ast.Assign) -> None:
        # Track x = self.attr aliases for call resolution.
        if (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Attribute)
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "self"):
            self.aliases[node.targets[0].id] = (
                "selfattr", node.value.attr)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        callee: Optional[Tuple[str, ...]] = None
        if isinstance(f, ast.Name):
            callee = ("name", f.id)
        elif isinstance(f, ast.Attribute):
            owner = f.value
            if isinstance(owner, ast.Name):
                if owner.id == "self":
                    callee = ("self", f.attr)
                elif owner.id in self.aliases:
                    callee = ("local-" + self.aliases[owner.id][0],
                              self.aliases[owner.id][1], f.attr)
                else:
                    callee = ("mod", owner.id, f.attr)
                key = (owner.id, f.attr)
                if key in _SYNC_FUNCS:
                    self.fm.syncs.append(SyncCall(
                        what=_SYNC_FUNCS[key], held=tuple(self.held),
                        line=node.lineno, func=self.fm.qualname))
            elif (isinstance(owner, ast.Attribute)
                  and isinstance(owner.value, ast.Name)
                  and owner.value.id == "self"):
                callee = ("selfattr", owner.attr, f.attr)
            # Method-style sync on an arbitrary object (x.block_until_
            # ready()); the jax.block_until_ready form was already
            # recorded by the table above — don't double-count it.
            if (f.attr == "block_until_ready"
                    and not (isinstance(owner, ast.Name)
                             and (owner.id, f.attr) in _SYNC_FUNCS)):
                self.fm.syncs.append(SyncCall(
                    what=".block_until_ready", held=tuple(self.held),
                    line=node.lineno, func=self.fm.qualname))
        if callee is not None:
            if callee[0] == "local-selfattr":
                callee = ("selfattr", callee[1], callee[2])
            self.fm.calls.append(CallSite(
                callee=callee, held=tuple(self.held), line=node.lineno,
                func=self.fm.qualname))
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        broad = node.type is None or (
            isinstance(node.type, ast.Name)
            and node.type.id in ("Exception", "BaseException"))
        if broad:
            self.fm.excepts.append(ExceptInfo(
                line=node.lineno, func=self.fm.qualname,
                bound_name=node.name,
                handles=_handler_handles(node)))
        self.generic_visit(node)


def _handler_handles(node: ast.ExceptHandler) -> bool:
    """True when the handler re-raises, uses the bound exception, or
    calls something that looks like logging/counting/parking."""
    names_used: Set[str] = set()
    for sub in ast.walk(node):
        if sub is node:
            continue
        if isinstance(sub, ast.Raise):
            return True
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
            names_used.add(sub.id)
        if isinstance(sub, ast.Call):
            f = sub.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if name in _HANDLING_NAMES:
                return True
    return node.name is not None and node.name in names_used


def _jit_decoration(node) -> Optional[Tuple[Tuple[int, ...],
                                            Tuple[str, ...],
                                            Tuple[int, ...]]]:
    """(static_argnums, static_argnames, donate_argnums) when the
    function is decorated @jax.jit or @partial(jax.jit, ...)."""
    for dec in node.decorator_list:
        target = dec
        kw = {}
        if isinstance(dec, ast.Call):
            fname = _expr_str(dec.func)
            if fname in ("partial", "functools.partial") and dec.args:
                if _expr_str(dec.args[0]) != "jax.jit":
                    continue
                kw = {k.arg: k.value for k in dec.keywords}
            elif fname == "jax.jit":
                kw = {k.arg: k.value for k in dec.keywords}
            else:
                continue
        elif _expr_str(target) != "jax.jit":
            continue

        def ints(key):
            v = kw.get(key)
            if v is None:
                return ()
            try:
                got = ast.literal_eval(v)
            except ValueError:
                return ()
            if isinstance(got, int):
                return (got,)
            return tuple(int(x) for x in got)

        def strs(key):
            v = kw.get(key)
            if v is None:
                return ()
            try:
                got = ast.literal_eval(v)
            except ValueError:
                return ()
            if isinstance(got, str):
                return (got,)
            return tuple(str(x) for x in got)

        return ints("static_argnums"), strs("static_argnames"), (
            ints("donate_argnums"))
    return None


class ModuleVisitor:
    """Builds a ModuleModel for one source file."""

    def __init__(self, path: str, modname: str, source: str,
                 lock_attr_names: Set[str]):
        self.model = ModuleModel(path=path, modname=modname)
        self.model.comments = extract_comments(source)
        for c in self.model.comments.values():
            self.model.file_suppressed.update(parse_file_disables(c))
        self.lock_attr_names = lock_attr_names
        self.tree = ast.parse(source)

    # -- helpers ---------------------------------------------------------

    def _comment(self, line: int) -> str:
        return self.model.comments.get(line, "")

    def _def_comment(self, node) -> str:
        """Comment on the def line, any decorator line, or the line
        directly above the first decorator/def."""
        lines = [node.lineno]
        lines.extend(d.lineno for d in node.decorator_list)
        lines.append(min(lines) - 1)
        return " ".join(self._comment(ln) for ln in lines)

    def _lockdef(self, owner: str, attr: str, kind: str,
                 line: int) -> LockDef:
        ann = parse_lock_order(self._comment(line))
        rank, flags = ann if ann else (None, ())
        return LockDef(key=f"{owner}.{attr}", kind=kind,
                       path=self.model.path, line=line, rank=rank,
                       flags=flags)

    # -- top-level walk --------------------------------------------------

    def run(self) -> ModuleModel:
        m = self.model
        for node in self.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    m.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    m.from_imports[a.asname or a.name] = (
                        node.module, a.name)
            elif isinstance(node, ast.Assign):
                kind = _ctor_kind(node.value)
                for t in node.targets:
                    if kind and isinstance(t, ast.Name):
                        m.module_locks[t.id] = self._lockdef(
                            m.modname.rsplit(".", 1)[-1], t.id, kind,
                            node.lineno)
                # name = jax.jit(fn, donate_argnums=...)
                self._maybe_jit_assign(node)
            elif isinstance(node, ast.ClassDef):
                self._scan_class(node)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self._scan_function(node, cls=None)
        return m

    def _maybe_jit_assign(self, node: ast.Assign) -> None:
        v = node.value
        if not (isinstance(v, ast.Call)
                and _expr_str(v.func) == "jax.jit" and v.args):
            return
        kw = {k.arg: k.value for k in v.keywords}
        donate = kw.get("donate_argnums")
        idx: Tuple[int, ...] = ()
        if donate is not None:
            try:
                got = ast.literal_eval(donate)
                idx = (got,) if isinstance(got, int) else tuple(got)
            except ValueError:
                idx = ()
        for t in node.targets:
            if isinstance(t, ast.Name):
                self.model.jit_funcs[t.id] = JitFunc(
                    name=t.id, params=(), static_params=(),
                    donate_params=(), donate_idx=idx,
                    line=node.lineno)

    # -- class scan ------------------------------------------------------

    def _scan_class(self, node: ast.ClassDef) -> None:
        cm = ClassModel(name=node.name, line=node.lineno,
                        bases=tuple(_expr_str(b) for b in node.bases))
        self.model.classes[node.name] = cm
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(item, cls=node.name)
                if item.name == "__init__":
                    self._scan_init(item, cm)
            elif isinstance(item, ast.AnnAssign) and isinstance(
                    item.target, ast.Name):
                g = parse_guarded_by(self._comment(item.lineno))
                if g:
                    cm.guarded[item.target.id] = g

    def _scan_init(self, init, cm: ClassModel) -> None:
        """__init__ pass: lock defs, guarded-by annotations, and
        attribute types for call resolution."""
        for stmt in ast.walk(init):
            target = None
            value = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target, value = stmt.targets[0], stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                target, value = stmt.target, stmt.value
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            attr = target.attr
            cm.attr_init_lines.setdefault(attr, stmt.lineno)
            kind = _ctor_kind(value)
            if kind:
                cm.lock_attrs[attr] = self._lockdef(
                    cm.name, attr, kind, stmt.lineno)
            g = parse_guarded_by(self._comment(stmt.lineno))
            if g:
                cm.guarded[attr] = g
            # self.x = ClassName(...) -> attr type (package classes
            # resolve later; store the bare callee name).
            if isinstance(value, ast.Call):
                callee = value.func
                if isinstance(callee, ast.Name):
                    cm.attr_types[attr] = callee.id
            # self.x: Optional[ClassName] = None  -> annotation name
            if (isinstance(stmt, ast.AnnAssign)
                    and attr not in cm.attr_types):
                for sub in ast.walk(stmt.annotation):
                    if (isinstance(sub, ast.Name)
                            and sub.id[0].isupper()
                            and sub.id not in ("Optional", "Dict",
                                               "List", "Tuple", "Set")):
                        cm.attr_types[attr] = sub.id
                        break

    # -- function scan ---------------------------------------------------

    def _scan_function(self, node, cls: Optional[str]) -> None:
        qual = f"{cls}.{node.name}" if cls else node.name
        defc = self._def_comment(node)
        cu = parse_called_under(defc)
        fm = FuncModel(
            qualname=qual, line=node.lineno, cls=cls,
            called_under=(cu,) if cu else (),
            suppressed=parse_disables(defc))
        params = tuple(a.arg for a in node.args.args)
        jit = _jit_decoration(node)
        if jit is not None and cls is None:
            static_idx, static_names, donate_idx = jit
            static = set(static_names)
            static.update(params[i] for i in static_idx
                          if i < len(params))
            self.model.jit_funcs[node.name] = JitFunc(
                name=node.name, params=params,
                static_params=tuple(static),
                donate_params=tuple(params[i] for i in donate_idx
                                    if i < len(params)),
                donate_idx=donate_idx, line=node.lineno)
        scanner = _FuncScanner(
            self, fm, self.lock_attr_names,
            set(self.model.module_locks))
        # Param annotations seed alias types: pipe: IngestPipeline.
        for a in node.args.args:
            if a.annotation is not None:
                for sub in ast.walk(a.annotation):
                    if (isinstance(sub, ast.Name)
                            and sub.id[0].isupper()
                            and sub.id not in ("Optional", "Dict",
                                               "List", "Tuple", "Set",
                                               "Sequence", "Callable")):
                        scanner.aliases[a.arg] = ("paramtype", sub.id)
                        break
        for stmt in node.body:
            scanner.visit(stmt)
        if cls:
            self.model.classes[cls].methods[node.name] = fm
        else:
            self.model.functions[node.name] = fm


def collect_lock_attr_names(sources: Sequence[str]) -> Set[str]:
    """Pre-pass over every file: the set of attribute names ever
    assigned a Lock/RLock/Condition/RWLock — the vocabulary the
    with-statement recognizer keys on."""
    names: Set[str] = set()
    for src in sources:
        try:
            tree = ast.parse(src)
        except SyntaxError:  # pragma: no cover — repo always parses
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and _ctor_kind(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        names.add(t.attr)
                    elif isinstance(t, ast.Name):
                        names.add(t.id)
    return names
