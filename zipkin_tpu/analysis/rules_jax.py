"""graftlint JAX hazard rules.

- ``jit-traced-branch`` — a Python ``if``/``while`` inside a jitted
  function whose test reads a NON-static parameter: the branch runs at
  trace time on a tracer (ConcretizationTypeError at best, a silent
  per-value recompile at worst). Static parameters (static_argnums /
  static_argnames) legitimately branch.
- ``jit-nonstatic-closure`` — a jitted function closing over a
  lowercase module-level scalar (or a module global assigned more than
  once): each new value bakes a new compile-cache entry, breaking the
  zero-steady-state-recompile gate.
- ``use-after-donate`` — an argument passed in a ``donate_argnums``
  position is read again after the call without being rebound: its
  device buffer was donated and may already be freed/reused.
- ``collective-under-read-lock`` — launching a shard_map/pjit-built
  kernel while holding an RWLock in READ mode without also holding a
  lock flagged ``collective-launch`` (``# lock-order: 45
  collective-launch``). Concurrent read-mode holders run in parallel,
  so two of them dispatching collectives concurrently deadlock XLA's
  CPU cross-device rendezvous — the r14 hazard the sharded store's
  ``_coll_lock`` (and above it the cross-shard dispatcher,
  parallel/dispatch.py) exists to serialize.

All of these are intentionally narrow heuristics (fixture-corpus-pinned
in tests/test_analysis.py); anything subtler belongs in review, not in
a gate that must never cry wolf.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from zipkin_tpu.analysis.model import (
    COLLECTIVE_UNDER_READ_LOCK,
    Finding,
    JIT_NONSTATIC_CLOSURE,
    JIT_TRACED_BRANCH,
    USE_AFTER_DONATE,
)
from zipkin_tpu.analysis.project import Project
from zipkin_tpu.analysis.visitor import _expr_str


def _walk_pruned(root: ast.AST):
    """ast.walk minus nested function/lambda subtrees: they execute in
    a different trace scope (ast.walk cannot prune — a bare `continue`
    skips only the def node itself, not its children)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _parse(project: Project, module) -> Optional[ast.Module]:
    full = os.path.join(project.repo_root, module.path)
    try:
        with open(full, "r", encoding="utf-8") as fh:
            return ast.parse(fh.read())
    except (OSError, SyntaxError):  # pragma: no cover
        return None


def _local_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            out.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(node.name)
        elif isinstance(node, ast.comprehension):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
    return out


def _module_scalars(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    """(literal-scalar assigns, reassigned-names) at module level."""
    counts: Dict[str, int] = {}
    literal: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    counts[t.id] = counts.get(t.id, 0) + 1
                    if isinstance(node.value, ast.Constant) and (
                            isinstance(node.value.value,
                                       (int, float, bool))):
                        literal.add(t.id)
        elif isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name):
            counts[node.target.id] = counts.get(node.target.id, 0) + 2
    reassigned = {n for n, c in counts.items() if c > 1}
    return literal, reassigned


def _is_none_check(test: ast.AST, name: str) -> bool:
    """True when every occurrence of ``name`` in ``test`` is an
    ``is None`` / ``is not None`` operand — Noneness of an optional
    argument is STRUCTURAL at trace time (it keys the jit cache), not
    a branch on a traced value."""
    safe = set()
    for node in ast.walk(test):
        if (isinstance(node, ast.Compare)
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops)):
            for side in (node.left, *node.comparators):
                if isinstance(side, ast.Name):
                    safe.add(id(side))
    for node in ast.walk(test):
        if (isinstance(node, ast.Name) and node.id == name
                and isinstance(node.ctx, ast.Load)
                and id(node) not in safe):
            return False
    return True


def _jit_fn_defs(project: Project, module,
                 tree: ast.Module) -> List[ast.FunctionDef]:
    names = set(module.jit_funcs)
    return [n for n in tree.body
            if isinstance(n, ast.FunctionDef) and n.name in names]


def check_jit_rules(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for m in project.modules:
        if not m.jit_funcs:
            continue
        tree = _parse(project, m)
        if tree is None:
            continue
        literal_scalars, reassigned = _module_scalars(tree)
        for fn in _jit_fn_defs(project, m, tree):
            info = m.jit_funcs[fn.name]
            traced = set(info.params) - set(info.static_params)
            locals_ = _local_names(fn) | set(info.params)
            seen_branch: Set[str] = set()
            seen_closure: Set[str] = set()
            for node in _walk_pruned(fn):
                if isinstance(node, (ast.If, ast.While)):
                    for name in ast.walk(node.test):
                        if (isinstance(name, ast.Name)
                                and isinstance(name.ctx, ast.Load)
                                and name.id in traced
                                and name.id not in seen_branch
                                and not _is_none_check(node.test,
                                                       name.id)):
                            seen_branch.add(name.id)
                            out.append(Finding(
                                rule=JIT_TRACED_BRANCH, path=m.path,
                                line=node.lineno, scope=fn.name,
                                message=(
                                    f"Python branch on traced "
                                    f"parameter '{name.id}' inside "
                                    f"jitted {fn.name} — use lax.cond/"
                                    "jnp.where, or mark the argument "
                                    "static"),
                                detail=f"{fn.name}|{name.id}"))
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id not in locals_
                        and node.id not in seen_closure):
                    bad_literal = (node.id in literal_scalars
                                   and not node.id.isupper())
                    if bad_literal or node.id in reassigned:
                        why = ("reassigned module global"
                               if node.id in reassigned else
                               "lowercase module-level scalar")
                        seen_closure.add(node.id)
                        out.append(Finding(
                            rule=JIT_NONSTATIC_CLOSURE, path=m.path,
                            line=node.lineno, scope=fn.name,
                            message=(
                                f"jitted {fn.name} closes over "
                                f"{why} '{node.id}' — each new value "
                                "is a fresh compile-cache entry "
                                "(steady-state recompile hazard)"),
                            detail=f"{fn.name}|{node.id}"))
    return out


# -- use-after-donate -----------------------------------------------------


def _donating_registry(project: Project) -> Dict[Tuple[str, str],
                                                 Tuple[int, ...]]:
    """(modname, fn name) -> donate_argnums for every module-level
    jitted function that donates."""
    out = {}
    for m in project.modules:
        for jf in m.jit_funcs.values():
            if jf.donate_idx:
                out[(m.modname, jf.name)] = jf.donate_idx
    return out


class _DonateScanner:
    """Linear statement walk of one function body: donations enter a
    live set keyed by the donated argument's expression string; a
    rebind clears it; a later read of a live donated expression is a
    finding. Branches are scanned in order; loop bodies once (a
    donation rebound by its own enclosing statement never enters the
    set, so the common ``state = step(state, ...)`` loop is clean)."""

    def __init__(self, project: Project, module, registry):
        self.project = project
        self.module = module
        self.registry = registry
        # Local aliases of donating callables:
        #   step = dev.ingest_steps if chained else dev.ingest_step
        self.aliases: Dict[str, Tuple[int, ...]] = {}
        self.donated: Dict[str, int] = {}
        self.findings: List[Finding] = []
        self.scope = "?"

    def _donate_idx_of(self, func: ast.AST) -> Optional[Tuple[int, ...]]:
        if isinstance(func, ast.Name):
            if func.id in self.aliases:
                return self.aliases[func.id]
            key = (self.module.modname, func.id)
            if key in self.registry:
                return self.registry[key]
            imp = self.module.from_imports.get(func.id)
            if imp and (imp[0], imp[1]) in self.registry:
                return self.registry[(imp[0], imp[1])]
        elif isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name):
            target = self.module.imports.get(func.value.id)
            if target and (target, func.attr) in self.registry:
                return self.registry[(target, func.attr)]
        return None

    def _alias_value_idx(self, value: ast.AST) -> Optional[Tuple[int, ...]]:
        if isinstance(value, ast.IfExp):
            a = self._alias_value_idx(value.body)
            b = self._alias_value_idx(value.orelse)
            if a and b:
                return tuple(sorted(set(a) | set(b)))
            return a or b
        if isinstance(value, (ast.Name, ast.Attribute)):
            return self._donate_idx_of(value)
        return None

    def run(self, fn, scope: str) -> None:
        self.scope = scope
        self.donated.clear()
        self.aliases.clear()
        self._stmts(fn.body)

    def _stmts(self, body) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        # Compound statements: scan only the header expression here,
        # then recurse into the bodies statement-by-statement (so a
        # donation in an earlier statement is live for later ones, and
        # nothing is scanned twice).
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_reads(stmt.test, [])
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, ast.For):
            self._scan_reads(stmt.iter, [])
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._scan_reads(item.context_expr, [])
            self._stmts(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self._stmts(stmt.body)
            for h in stmt.handlers:
                self._stmts(h.body)
            self._stmts(stmt.orelse)
            self._stmts(stmt.finalbody)
            return
        targets: List[str] = []
        if isinstance(stmt, ast.Assign):
            idx = self._alias_value_idx(stmt.value)
            tnames = [t for t in stmt.targets if isinstance(t, ast.Name)]
            if idx and len(tnames) == len(stmt.targets) == 1:
                self.aliases[tnames[0].id] = idx
                return
            targets = [_expr_str(t) for t in stmt.targets]
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [_expr_str(stmt.target)]
        # Reads first (RHS evaluates before the rebind), except the
        # donating call's own arguments.
        self._scan_reads(stmt, targets)
        for t in targets:
            self.donated.pop(t, None)

    def _scan_reads(self, stmt, rebinds: List[str]) -> None:
        nodes = ([stmt] if not isinstance(stmt, ast.stmt)
                 else []) + list(_walk_pruned(stmt))
        for node in nodes:
            if isinstance(node, ast.Call):
                idx = self._donate_idx_of(node.func)
                if idx:
                    for i in idx:
                        if i < len(node.args):
                            e = _expr_str(node.args[i])
                            if e != "<expr>" and e not in rebinds:
                                self.donated[e] = node.lineno
            elif isinstance(node, (ast.Name, ast.Attribute)) and (
                    isinstance(getattr(node, "ctx", None), ast.Load)):
                e = _expr_str(node)
                if e in self.donated:
                    # The donating call itself (its args walk through
                    # here) — skip reads on the donation line.
                    if node.lineno == self.donated[e]:
                        continue
                    self.findings.append(Finding(
                        rule=USE_AFTER_DONATE, path=self.module.path,
                        line=node.lineno, scope=self.scope,
                        message=(
                            f"'{e}' was donated to a jitted function "
                            f"(donate_argnums) and read again — its "
                            "device buffer may already be freed; "
                            "rebind the result or copy first"),
                        detail=f"{self.scope}|{e}"))
                    self.donated.pop(e, None)


# -- collective-under-read-lock -------------------------------------------

# Callables whose result is a cross-device collective program: calling
# it dispatches a launch that must rendezvous with every other device.
_COLLECTIVE_CTORS = {"shard_map", "compat_shard_map", "pjit"}


def _builds_collective(value: Optional[ast.AST]) -> bool:
    """True when ``value`` contains a shard_map/pjit constructor call
    anywhere in its wrapper chain (``jax.jit(shard_map(...))``
    included)."""
    if value is None:
        return False
    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if name in _COLLECTIVE_CTORS:
                return True
    return False


def _collective_registry(tree: ast.Module) -> Tuple[Set[str],
                                                    Dict[str, Set[str]]]:
    """(module-level kernel names, class name -> self-attr kernel
    names): every name/attr assigned a collective program anywhere in
    the file."""
    mod_kernels: Set[str] = set()
    cls_kernels: Dict[str, Set[str]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and _builds_collective(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    mod_kernels.add(t.id)
        elif isinstance(node, ast.ClassDef):
            attrs: Set[str] = set()
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Assign)
                        and _builds_collective(sub.value)):
                    continue
                for t in sub.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        attrs.add(t.attr)
            if attrs:
                cls_kernels[node.name] = attrs
    return mod_kernels, cls_kernels


class _CollectiveScanner:
    """Lexical walk of one function body: held-lock stack (the
    _FuncScanner discipline) + collective-launch detection. A launch is
    a call of a registered kernel name/self-attr, or an immediate
    ``shard_map(...)(args)``. Flags launches inside a read-mode RWLock
    hold with no held lock carrying the ``collective-launch`` flag."""

    def __init__(self, project: Project, module, scope: str,
                 mod_kernels: Set[str], attr_kernels: Set[str]):
        self.project = project
        self.module = module
        self.scope = scope
        self.mod_kernels = mod_kernels
        self.attr_kernels = attr_kernels
        self.local_kernels: Set[str] = set()
        self.lock_attrs = set(project.locks_by_attr)
        self.held: List[Tuple[str, Optional[str]]] = []
        self.findings: List[Finding] = []
        self.seen: Set[str] = set()

    def _lock_ref(self, expr: ast.AST) -> Optional[Tuple[str,
                                                         Optional[str]]]:
        if (isinstance(expr, ast.Call) and not expr.args
                and isinstance(expr.func, ast.Attribute)
                and expr.func.attr in ("read", "write")):
            inner = expr.func.value
            if (isinstance(inner, ast.Attribute)
                    and inner.attr in self.lock_attrs):
                return inner.attr, expr.func.attr
            return None
        if (isinstance(expr, ast.Attribute)
                and expr.attr in self.lock_attrs):
            return expr.attr, None
        if isinstance(expr, ast.Name) and expr.id in self.lock_attrs:
            return expr.id, None
        return None

    def _launch_safe(self) -> bool:
        """True when some held lock is flagged ``collective-launch``."""
        for attr, _mode in self.held:
            for d in self.project.locks_by_attr.get(attr, ()):
                if "collective-launch" in d.flags:
                    return True
        return False

    def _kernel_name(self, func: ast.AST) -> Optional[str]:
        if isinstance(func, ast.Name) and func.id in (
                self.mod_kernels | self.local_kernels):
            return func.id
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and func.attr in self.attr_kernels):
            return f"self.{func.attr}"
        if isinstance(func, ast.Call) and _builds_collective(func):
            return "<inline-collective>"
        return None

    def _stmts(self, body) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # different execution context
        if isinstance(stmt, ast.With):
            refs = []
            for item in stmt.items:
                r = self._lock_ref(item.context_expr)
                if r is not None:
                    refs.append(r)
                else:
                    self._expr(item.context_expr)
            self.held.extend(refs)
            self._stmts(stmt.body)
            if refs:
                del self.held[-len(refs):]
            return
        if isinstance(stmt, ast.Assign):
            # kern = shard_map(...) / kern = self._kernel_attr
            if _builds_collective(stmt.value) or (
                    isinstance(stmt.value, ast.Attribute)
                    and isinstance(stmt.value.value, ast.Name)
                    and stmt.value.value.id == "self"
                    and stmt.value.attr in self.attr_kernels):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.local_kernels.add(t.id)
        if isinstance(stmt, (ast.If, ast.While)):
            self._expr(stmt.test)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, ast.For):
            self._expr(stmt.iter)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self._stmts(stmt.body)
            for h in stmt.handlers:
                self._stmts(h.body)
            self._stmts(stmt.orelse)
            self._stmts(stmt.finalbody)
            return
        self._expr(stmt)

    def _expr(self, node: ast.AST) -> None:
        for sub in [node] + list(_walk_pruned(node)):
            if not isinstance(sub, ast.Call):
                continue
            kern = self._kernel_name(sub.func)
            if kern is None:
                continue
            read_hold = next(
                (a for a, m in self.held if m == "read"), None)
            if read_hold is None or self._launch_safe():
                continue
            if kern in self.seen:
                continue
            self.seen.add(kern)
            self.findings.append(Finding(
                rule=COLLECTIVE_UNDER_READ_LOCK, path=self.module.path,
                line=sub.lineno, scope=self.scope,
                message=(
                    f"collective launch {kern}(...) under the shared "
                    f"read lock {read_hold} without a collective-"
                    "launch leaf lock — concurrent readers would "
                    "dispatch overlapping collectives and deadlock "
                    "the cross-device rendezvous; hold the "
                    "'# lock-order: 45 collective-launch' lock (or "
                    "route through the cross-shard dispatcher)"),
                detail=f"{self.scope}|{kern}"))


def check_collective_read_lock(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for m in project.modules:
        tree = _parse(project, m)
        if tree is None:
            continue
        mod_kernels, cls_kernels = _collective_registry(tree)
        if not mod_kernels and not cls_kernels and (
                "shard_map" not in m.from_imports
                and "pjit" not in m.from_imports):
            continue

        def scan(fn, scope, attr_kernels):
            s = _CollectiveScanner(project, m, scope,
                                   mod_kernels, attr_kernels)
            s._stmts(fn.body)
            out.extend(s.findings)

        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                scan(node, node.name, set())
            elif isinstance(node, ast.ClassDef):
                attrs = cls_kernels.get(node.name, set())
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        scan(sub, f"{node.name}.{sub.name}", attrs)
    return out


def check_use_after_donate(project: Project) -> List[Finding]:
    registry = _donating_registry(project)
    if not registry:
        return []
    out: List[Finding] = []
    for m in project.modules:
        tree = _parse(project, m)
        if tree is None:
            continue
        scanner = _DonateScanner(project, m, registry)

        def scan(fn, scope):
            scanner.findings = []
            scanner.run(fn, scope)
            out.extend(scanner.findings)

        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                scan(node, node.name)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        scan(sub, f"{node.name}.{sub.name}")
    return out
