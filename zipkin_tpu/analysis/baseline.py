"""graftlint baseline: accepted pre-existing findings, checked in as
JSON so the tier-1 gate fails only on NEW violations.

Fingerprints are line-number-free (rule + path + scope + detail), so
unrelated edits don't churn the file; counts allow N accepted
instances of the same fingerprint. ``--write-baseline`` regenerates it
(review the diff like any other code change — a GROWING baseline is a
new violation being grandfathered).
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Dict, List, Tuple

from zipkin_tpu.analysis.model import Finding

VERSION = 1


def to_baseline(findings: List[Finding]) -> dict:
    per_rule: Dict[str, Counter] = {}
    for f in findings:
        per_rule.setdefault(f.rule, Counter())[f.fingerprint] += 1
    return {
        "version": VERSION,
        "findings": {
            rule: dict(sorted(per_rule[rule].items()))
            for rule in sorted(per_rule)
        },
    }


def save(path: str, findings: List[Finding]) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(to_baseline(findings), fh, indent=1, sort_keys=True)
        fh.write("\n")


def load(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != VERSION:
        raise ValueError(
            f"unsupported graftlint baseline version "
            f"{data.get('version')!r} in {path}")
    return data


def diff(findings: List[Finding],
         baseline: dict) -> Tuple[List[Finding], List[str]]:
    """(new findings not covered by the baseline, stale baseline
    fingerprints that no longer occur)."""
    accepted: Dict[Tuple[str, str], int] = {}
    for rule, fps in baseline.get("findings", {}).items():
        for fp, n in fps.items():
            accepted[(rule, fp)] = int(n)
    used: Counter = Counter()
    new: List[Finding] = []
    for f in sorted(findings, key=lambda x: (x.path, x.line)):
        key = (f.rule, f.fingerprint)
        if used[key] < accepted.get(key, 0):
            used[key] += 1
        else:
            new.append(f)
    stale = sorted(
        f"{rule}:{fp}" for (rule, fp), n in accepted.items()
        if used[(rule, fp)] < n
    )
    return new, stale
