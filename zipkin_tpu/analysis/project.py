"""graftlint project loader: parses every package file, links classes
and call sites across modules, and computes the fixed-point transitive
facts the lock rules need (which locks a call may acquire, which calls
may synchronize with the device).
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from zipkin_tpu.analysis.model import (
    ClassModel,
    FuncModel,
    LockDef,
    LockRef,
    ModuleModel,
)
from zipkin_tpu.analysis.visitor import (
    ModuleVisitor,
    collect_lock_attr_names,
)

# Function key: (modname, qualname) — unique across the project.
FuncKey = Tuple[str, str]


def _modname_for(root: str, path: str) -> str:
    rel = os.path.relpath(path, os.path.dirname(root))
    return rel[:-3].replace(os.sep, ".")


def iter_py_files(pkg_dir: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


class Project:
    """Every module's model plus cross-module resolution tables."""

    def __init__(self, modules: List[ModuleModel], repo_root: str):
        self.modules = modules
        self.repo_root = repo_root
        # Class name -> (module, ClassModel). Private class names are
        # unique enough in one package; a collision keeps the first
        # and rules fall back to per-module lookups.
        self.classes: Dict[str, Tuple[ModuleModel, ClassModel]] = {}
        for m in modules:
            for c in m.classes.values():
                self.classes.setdefault(c.name, (m, c))
        self.funcs: Dict[FuncKey, FuncModel] = {}
        for m in modules:
            for f in m.all_funcs():
                self.funcs[(m.modname, f.qualname)] = f
        # lock key -> LockDef (class-attr + module-level locks).
        self.locks: Dict[str, LockDef] = {}
        for m in modules:
            for c in m.classes.values():
                for d in c.lock_attrs.values():
                    self.locks[d.key] = d
            for d in m.module_locks.values():
                self.locks[d.key] = d
        # attr name -> set of lock keys sharing it (canonicalizing a
        # LockRef by attr when the owner expression isn't typeable).
        self.locks_by_attr: Dict[str, List[LockDef]] = {}
        for d in self.locks.values():
            self.locks_by_attr.setdefault(
                d.key.rsplit(".", 1)[-1], []).append(d)
        self._transitive_acqs: Dict[FuncKey, Set[Tuple[str,
                                                       Optional[str]]]] = {}
        self._transitive_syncs: Dict[FuncKey, Set[str]] = {}
        self._edge_cache = None  # filled by rules_locks.build_edges
        self._compute_fixed_points()

    # -- lock canonicalization -------------------------------------------

    def canon_lock(self, module: ModuleModel, func: FuncModel,
                   ref: LockRef) -> Optional[str]:
        """LockRef -> canonical 'Class.attr' / 'module.attr' key.
        Resolution order: owner is self and the enclosing class (or a
        base) defines the attr; owner types known via attr_types;
        otherwise the attr name maps to exactly one project lock."""
        base, attr, _mode = ref
        if base == "<module>":
            d = module.module_locks.get(attr)
            return d.key if d else None
        if func.cls:
            cm = module.classes.get(func.cls)
            own = self._class_lock(cm, attr)
            if base == "self" and own:
                return own
            if base.startswith("self."):
                tname = cm.attr_types.get(base[5:]) if cm else None
                if tname and tname in self.classes:
                    got = self._class_lock(self.classes[tname][1], attr)
                    if got:
                        return got
        cands = {d.key for d in self.locks_by_attr.get(attr, ())}
        if len(cands) == 1:
            return next(iter(cands))
        if base == "self" and func.cls:
            # Unlisted attr on a known class (inherited off-package):
            # treat as that class's own lock.
            return f"{func.cls}.{attr}"
        return None

    def _class_lock(self, cm: Optional[ClassModel],
                    attr: str) -> Optional[str]:
        seen = set()
        while cm is not None and cm.name not in seen:
            seen.add(cm.name)
            if attr in cm.lock_attrs:
                return cm.lock_attrs[attr].key
            nxt = None
            for b in cm.bases:
                bname = b.rsplit(".", 1)[-1]
                if bname in self.classes:
                    nxt = self.classes[bname][1]
                    break
            cm = nxt
        return None

    # -- call resolution -------------------------------------------------

    def resolve_call(self, module: ModuleModel, func: FuncModel,
                     callee: Tuple[str, ...]) -> Optional[FuncKey]:
        kind = callee[0]
        if kind == "self" and func.cls:
            cm = module.classes.get(func.cls)
            cur_mod = module
            seen = set()
            while cm is not None and cm.name not in seen:
                seen.add(cm.name)
                if callee[1] in cm.methods:
                    return (cur_mod.modname, f"{cm.name}.{callee[1]}")
                nxt = None
                for b in cm.bases:
                    bname = b.rsplit(".", 1)[-1]
                    if bname in self.classes:
                        cur_mod, nxt = self.classes[bname]
                        break
                cm = nxt
            return None
        if kind == "name":
            name = callee[1]
            if name in module.functions:
                return (module.modname, name)
            imp = module.from_imports.get(name)
            if imp:
                target_mod, target_name = imp
                key = (target_mod, target_name)
                if key in self.funcs:
                    return key
            return None
        if kind == "mod":
            alias, fname = callee[1], callee[2]
            target = module.imports.get(alias)
            if target is None:
                imp = module.from_imports.get(alias)
                if imp:
                    target = f"{imp[0]}.{imp[1]}"
            if target and (target, fname) in self.funcs:
                return (target, fname)
            return None
        if kind in ("selfattr", "paramtype"):
            if kind == "selfattr":
                if not func.cls:
                    return None
                cm = module.classes.get(func.cls)
                tname = cm.attr_types.get(callee[1]) if cm else None
            else:
                tname = callee[1]
            if tname and tname in self.classes:
                mod, cm = self.classes[tname]
                if callee[2] in cm.methods:
                    return (mod.modname, f"{cm.name}.{callee[2]}")
        return None

    def module_of(self, key: FuncKey) -> ModuleModel:
        for m in self.modules:
            if m.modname == key[0]:
                return m
        raise KeyError(key)  # pragma: no cover

    # -- fixed points -----------------------------------------------------

    def _compute_fixed_points(self) -> None:
        """Transitive 'may acquire' lock sets and 'may device-sync'
        sets per function, over the resolvable call graph."""
        acqs: Dict[FuncKey, Set[Tuple[str, Optional[str]]]] = {}
        syncs: Dict[FuncKey, Set[str]] = {}
        callees: Dict[FuncKey, List[FuncKey]] = {}
        for m in self.modules:
            for f in m.all_funcs():
                key = (m.modname, f.qualname)
                a = set()
                for acq in f.acquisitions:
                    ck = self.canon_lock(m, f, acq.ref)
                    if ck:
                        a.add((ck, acq.ref[2]))
                acqs[key] = a
                syncs[key] = {s.what for s in f.syncs}
                callees[key] = [
                    r for c in f.calls
                    if (r := self.resolve_call(m, f, c.callee))
                    is not None and r in self.funcs
                ]
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for key, outs in callees.items():
                for o in outs:
                    if not acqs[o] <= acqs[key]:
                        acqs[key] |= acqs[o]
                        changed = True
                    if not syncs[o] <= syncs[key]:
                        syncs[key] |= syncs[o]
                        changed = True
        self._transitive_acqs = acqs
        self._transitive_syncs = syncs

    def may_acquire(self, key: FuncKey) -> Set[Tuple[str, Optional[str]]]:
        return self._transitive_acqs.get(key, set())

    def may_sync(self, key: FuncKey) -> Set[str]:
        return self._transitive_syncs.get(key, set())


def load_project(paths: Iterable[str], repo_root: str) -> Project:
    """Parse ``paths`` (files or package dirs) into a linked Project."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(iter_py_files(p))
        else:
            files.append(p)
    sources = {}
    for f in files:
        with open(f, "r", encoding="utf-8") as fh:
            sources[f] = fh.read()
    lock_attrs = collect_lock_attr_names(list(sources.values()))
    modules = []
    for f in files:
        rel = os.path.relpath(f, repo_root)
        modname = rel[:-3].replace(os.sep, ".")
        mv = ModuleVisitor(rel, modname, sources[f], lock_attrs)
        modules.append(mv.run())
    return Project(modules, repo_root)
