"""Trace generators: object-level (reference parity) and columnar (scale).

Reference: zipkin-tracegen/.../TraceGen.scala:50 — random span trees up
to depth 7, randomized rpc/service names, core annotation pairs with
realistic timing, custom ("some custom annotation") and binary
annotations. Re-expressed, not translated: the columnar generator plays
the role the reference never needed — feeding a device at line rate.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from zipkin_tpu.columnar.dictionary import DictionarySet
from zipkin_tpu.columnar.schema import (
    FLAG_HAS_PARENT,
    NO_TS,
    SpanBatch,
)
from zipkin_tpu.models.span import (
    Annotation,
    AnnotationType,
    BinaryAnnotation,
    Endpoint,
    Span,
)

_WORDS = (
    "lorem", "ipsum", "dolor", "sit", "amet", "consectetur", "adipiscing",
    "elit", "vivamus", "posuere", "mauris", "tortor", "gravida", "sodales",
)


def _name(rng: np.random.Generator, n_words: int = 2) -> str:
    return "-".join(rng.choice(_WORDS, size=n_words))


def generate_traces(
    n_traces: int = 5,
    max_depth: int = 7,
    rng: Optional[np.random.Generator] = None,
    base_ts: int = 1_000_000_000_000,
    n_services: int = 10,
) -> List[List[Span]]:
    """Random span trees, one list per trace (TraceGen.scala:50 shape)."""
    rng = rng or np.random.default_rng(0)
    services = [f"{_name(rng, 1)}-{i}" for i in range(n_services)]
    traces = []
    for _ in range(n_traces):
        trace_id = int(rng.integers(1, 2**62))
        spans: List[Span] = []
        t0 = base_ts + int(rng.integers(0, 10_000_000))

        def walk(parent_id, depth, start, budget, client_svc):
            span_id = int(rng.integers(1, 2**62))
            svc = services[int(rng.integers(0, len(services)))]
            client = Endpoint(int(rng.integers(1, 2**31)), 80, client_svc)
            server = Endpoint(int(rng.integers(1, 2**31)), 443, svc)
            end = start + budget
            anns = (
                Annotation(start, "cs", client),
                Annotation(start + 1, "sr", server),
                Annotation(start + budget // 2, _name(rng), server),
                # The fixed value the module docstring promises (and the
                # annotation-query tests/benchmarks probe for) — same
                # vocabulary as ColumnarTraceGen.
                Annotation(start + budget // 2 + 1,
                           "some custom annotation", server),
                Annotation(end - 1, "ss", server),
                Annotation(end, "cr", client),
            )
            banns = (
                BinaryAnnotation(
                    _name(rng, 1), _name(rng, 3).encode(),
                    AnnotationType.BYTES, server,
                ),
                BinaryAnnotation(
                    "http.uri", b"/api/widgets", AnnotationType.BYTES,
                    server,
                ),
            )
            spans.append(
                Span(trace_id, _name(rng), span_id, parent_id, anns, banns)
            )
            if depth < max_depth:
                n_children = int(rng.integers(0, 3))
                for c in range(n_children):
                    child_budget = max(2, budget // (2 + c))
                    child_start = start + 1 + int(
                        rng.integers(0, max(1, budget - child_budget))
                    )
                    walk(span_id, depth + 1, child_start, child_budget, svc)

        walk(None, 1, t0, int(rng.integers(10_000, 1_000_000)), services[0])
        traces.append(spans)
    return traces


class ColumnarTraceGen:
    """Vectorized generator emitting SpanBatch columns directly.

    Every trace is a ``spans_per_trace``-node heap-shaped tree (parent of
    span j is span (j-1)//2, root parentless) — depth ≤ 7 holds for
    spans_per_trace ≤ 127, mirroring the reference's depth bound while
    keeping generation branch-free.

    Dictionaries are pre-seeded so the device batch can be built without
    per-span python; callers share ``dicts`` with their store/codec.
    """

    def __init__(
        self,
        dicts: DictionarySet,
        n_services: int = 100,
        n_span_names: int = 200,
        spans_per_trace: int = 7,
        seed: int = 0,
        topology: bool = False,
    ):
        """``topology=True`` assigns services from a fixed sparse call
        graph (each service calls two deterministic callees per child
        slot) instead of uniformly at random — real microservice fleets
        have O(S) dependency links, not O(S^2); uniform assignment makes
        every benchmark dep-link bank artificially dense."""
        self.dicts = dicts
        self.spans_per_trace = spans_per_trace
        self.topology = topology
        self.rng = np.random.default_rng(seed)
        self.service_ids = np.array(
            [dicts.services.encode(f"svc-{i:04d}") for i in range(n_services)],
            np.int32,
        )
        self.name_ids = np.array(
            [dicts.span_names.encode(f"op-{i:04d}") for i in range(n_span_names)],
            np.int32,
        )
        # Lowercased ids coincide (names are already lowercase).
        self.custom_ann_id = dicts.annotations.encode("some custom annotation")
        self.endpoint_ids = np.array(
            [
                dicts.endpoints.encode((0x0A000000 + i, 9410, f"svc-{i:04d}"))
                for i in range(n_services)
            ],
            np.int32,
        )
        self._next_trace = 1

    def next_batch(
        self, n_traces: int, base_ts: int = 1_000_000_000_000
    ) -> Tuple[SpanBatch, np.ndarray, np.ndarray]:
        """Returns (batch, name_lc_id, indexable) ready for
        TpuSpanStore.write_batch / device upload."""
        rng = self.rng
        spt = self.spans_per_trace
        n = n_traces * spt
        tid_base = np.arange(self._next_trace, self._next_trace + n_traces,
                             dtype=np.int64)
        self._next_trace += n_traces
        trace_id = np.repeat(
            (tid_base.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15))
            .view(np.int64),
            spt,
        )
        j = np.tile(np.arange(spt, dtype=np.int64), n_traces)  # node index
        span_id = trace_id ^ (j + 1)
        parent_j = (j - 1) // 2
        has_parent = j > 0
        parent_id = np.where(has_parent, trace_id ^ (parent_j + 1), 0)

        S = len(self.service_ids)
        if self.topology:
            # Root service random; child j's service is a fixed function
            # of its parent's service and child slot (heap parent
            # (j-1)//2, slot 1 or 2) — a sparse static call graph.
            cols = [rng.integers(0, S, size=n_traces)]
            for jj in range(1, spt):
                pj = (jj - 1) // 2
                slot = jj - 2 * pj
                cols.append((cols[pj] * 31 + slot) % S)
            svc_idx = np.stack(cols, axis=1).reshape(-1)
        else:
            svc_idx = rng.integers(0, S, size=n)
        service_id = self.service_ids[svc_idx]
        name_id = self.name_ids[rng.integers(0, len(self.name_ids), size=n)]

        # Timing: root spans start at base_ts + trace offset; children
        # nest inside with lognormal durations shrinking with depth.
        depth = np.floor(np.log2(j + 1)).astype(np.int64)
        trace_t0 = base_ts + np.repeat(
            rng.integers(0, 60_000_000, size=n_traces), spt
        )
        duration = (
            rng.lognormal(11.0, 1.0, size=n) / (1.0 + depth)
        ).astype(np.int64) + 4
        start = trace_t0 + j * 1000
        end = start + duration

        batch = SpanBatch.empty(n, 2 * n, n)
        batch.trace_id[:] = trace_id
        batch.span_id[:] = span_id
        batch.parent_id[:] = parent_id
        batch.name_id[:] = name_id
        batch.service_id[:] = service_id
        batch.flags[:] = np.where(has_parent, FLAG_HAS_PARENT, 0).astype(np.uint8)
        batch.ts_cs[:] = start
        batch.ts_sr[:] = start + 1
        batch.ts_ss[:] = end - 1
        batch.ts_cr[:] = end
        batch.ts_first[:] = start
        batch.ts_last[:] = end
        batch.duration[:] = duration

        # Two annotation rows per span: sr (server side, owning service)
        # and the custom annotation — enough to exercise the service
        # index and top-annotation paths at full rate.
        idx = np.arange(n, dtype=np.int32)
        batch.ann_span_idx[0::2] = idx
        batch.ann_span_idx[1::2] = idx
        batch.ann_ts[0::2] = start + 1
        batch.ann_ts[1::2] = (start + duration // 2)
        batch.ann_value_id[0::2] = 2  # CORE_ANNOTATION_IDS["sr"]
        batch.ann_value_id[1::2] = self.custom_ann_id
        batch.ann_service_id[0::2] = service_id
        batch.ann_service_id[1::2] = service_id
        batch.ann_endpoint_id[0::2] = self.endpoint_ids[svc_idx]
        batch.ann_endpoint_id[1::2] = self.endpoint_ids[svc_idx]

        # One binary annotation per span.
        key_id = self.dicts.binary_keys.encode("http.uri")
        val_id = self.dicts.binary_values.encode(b"/api/widgets")
        batch.bann_span_idx[:] = idx
        batch.bann_key_id[:] = key_id
        batch.bann_value_id[:] = val_id
        batch.bann_type[:] = int(AnnotationType.BYTES)
        batch.bann_service_id[:] = service_id
        batch.bann_endpoint_id[:] = self.endpoint_ids[svc_idx]

        name_lc = batch.name_id.copy()  # generator names are lowercase
        indexable = np.ones(n, bool)
        return batch, name_lc, indexable
