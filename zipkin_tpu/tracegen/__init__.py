"""Synthetic trace generation (zipkin-tracegen parity + vectorized scale).

Two generators:

- ``generate_traces``: python Span objects with the reference generator's
  shape (TraceGen.scala:50 — random tree depth ≤ 7, lorem-ish
  service/rpc names, cs/sr/ss/cr core annotations, one custom and one
  binary annotation per span). Feeds any SpanStore; used by the
  end-to-end smoke test (tracegen/Main.scala:48-117 analogue).

- ``ColumnarTraceGen``: vectorized numpy generator that emits SpanBatch
  columns directly — no python span objects — so the ingest benchmark
  can stream 100M+ spans (BASELINE.md config #2) without the host
  object layer becoming the bottleneck.
"""

from zipkin_tpu.tracegen.gen import (  # noqa: F401
    ColumnarTraceGen,
    generate_traces,
)
