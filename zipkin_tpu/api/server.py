"""Threaded HTTP server exposing the query + ingest surface.

Plays zipkin-web's server role (web/Main.scala:31-89) minus the mustache
UI: JSON in/out, stdlib-only (ThreadingHTTPServer), fronted by the
QueryService and Collector. Trace pinning adjusts TTL exactly like the
reference (Handlers.scala:461-490: pin=true → webPinTtl, pin=false →
default TTL).
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qsl, urlparse

from zipkin_tpu import obs
from zipkin_tpu.api.query_extractor import extract_query
from zipkin_tpu.ingest.collector import Collector
from zipkin_tpu.ingest.receiver import (
    JsonReceiver,
    ResultCode,
    ScribeReceiver,
    _hex_id,
    binary_annotation_to_json,
    span_to_json,
)
from zipkin_tpu.query.request import QueryException
from zipkin_tpu.query.service import QueryService
from zipkin_tpu.store.base import StorageException

DEFAULT_PIN_TTL_S = 30 * 24 * 3600  # webPinTtl default 30 days
DEFAULT_TTL_S = 1.0


class RawResponse:
    """Non-JSON payload (the static UI) with its content type."""

    def __init__(self, content_type: str, body: bytes):
        self.content_type = content_type
        self.body = body


def _trace_json(trace):
    return [span_to_json(s) for s in trace.spans]


def _timeline_json(tl):
    return {
        "traceId": _hex_id(tl.trace_id),
        "rootSpanId": _hex_id(tl.root_span_id),
        "annotations": [
            {
                "timestamp": a.timestamp, "value": a.value,
                "spanId": _hex_id(a.span_id),
                "parentId": None if a.parent_id is None
                else _hex_id(a.parent_id),
                "serviceName": a.service_name, "spanName": a.span_name,
            }
            for a in tl.annotations
        ],
        "binaryAnnotations": [
            binary_annotation_to_json(b) for b in tl.binary_annotations
        ],
    }


def _summary_json(s):
    return {
        "traceId": _hex_id(s.trace_id),
        "startTimestamp": s.start_timestamp,
        "endTimestamp": s.end_timestamp,
        "durationMicro": s.duration_micro,
        "endpoints": [
            {"ipv4": e.ipv4, "port": e.port, "serviceName": e.service_name}
            for e in s.endpoints
        ],
    }


def _finite_or_none(v):
    """JSON-safe float: json.dumps serializes inf/nan as the bare
    tokens Infinity/NaN, which are NOT JSON — JSON.parse in every
    browser rejects them. The Dependencies monoid zero is
    (+inf, -inf) (Time.Top/Bottom, models/dependencies.py), so an
    empty store's /api/dependencies used to emit invalid JSON. No data
    serializes as null, the /api/quantiles convention."""
    return v if v == v and abs(v) != float("inf") else None


def _moments_json(m):
    return {
        "count": m.count,
        "mean": _finite_or_none(m.mean),
        "stddev": _finite_or_none(m.stddev),
        "m2": _finite_or_none(m.m2),
        "m3": _finite_or_none(m.m3),
        "m4": _finite_or_none(m.m4),
    }


class ApiServer:
    """Route table + handlers, decoupled from the HTTP plumbing so tests
    can drive it without sockets."""

    def __init__(self, query: QueryService, collector: Optional[Collector] = None,
                 pin_ttl_s: float = DEFAULT_PIN_TTL_S,
                 self_trace: bool = True,
                 self_service_name: str = "zipkin-tpu",
                 registry: Optional[obs.Registry] = None,
                 replication=None, fleet=None):
        self.query = query
        self.collector = collector
        self.pin_ttl_s = pin_ttl_s
        # /api/replication status provider: a zero-arg callable — the
        # primary's WalShipper.status or a follower's Follower.status
        # (docs/REPLICATION.md); None answers {"role": "none"}.
        self.replication = replication
        # Fleet observability hub (obs.fleet.FleetObs): serves
        # /api/health (watchdog readiness), /api/fleet (merged roll-up
        # status), /debug/events (flight recorder) and the federated
        # /metrics?fleet=1 view; None degrades each to its
        # single-process answer (docs/OBSERVABILITY.md).
        self.fleet = fleet
        self.registry = registry or obs.default_registry()
        # Query-stage latency sketch: p50/p99 per normalized route
        # (moments + log-histogram, see obs.LatencySketch).
        self.request_latency = self.registry.register(obs.LatencySketch(
            "zipkin_api_request_seconds",
            "API request handling latency per route",
            labelnames=("route",)))
        self.requests_total = self.registry.register(obs.Counter(
            "zipkin_api_requests_total", "API requests handled",
            labelnames=("route",)))
        self._c_self_drops = self.registry.register(obs.Counter(
            "zipkin_api_self_trace_drops_total",
            "API self-trace span batches dropped by a failed "
            "collector accept"))
        coal = getattr(query, "coalescer", None)
        if coal is not None:
            for attr, help_ in (
                ("batches", "Coalesced query batches executed"),
                ("queries", "Trace-id queries served through the "
                            "coalescer"),
                ("launches_saved", "Device dispatches removed by "
                                   "cross-request coalescing"),
                ("max_batch", "Largest coalesced batch so far"),
            ):
                self.registry.register(obs.Gauge(
                    f"zipkin_query_coalesce_{attr}", help_,
                    fn=(lambda a=attr: getattr(coal, a))))
        disp = getattr(query.store, "dispatcher", None)
        if disp is not None:
            for attr, help_ in (
                ("batches", "Cross-shard dispatcher batches executed"),
                ("requests", "Sharded reads served through the "
                             "dispatcher"),
                ("launches_saved", "Collective launches removed by "
                                   "cross-shard batching"),
                ("max_batch", "Largest dispatcher batch so far"),
            ):
                self.registry.register(obs.Gauge(
                    f"zipkin_shard_dispatch_{attr}", help_,
                    fn=(lambda a=attr: getattr(disp, a))))
        counters = getattr(query.store, "counters", None)
        if callable(counters):
            self.registry.register(obs.CallbackFamily(
                "zipkin_store_counter",
                "Store counters (device counter block + host guards)",
                "name", counters))
        # Self-tracing (SURVEY §5): the query service records a server
        # span per API request into its own collector, continuing any
        # incoming B3 trace — the finagle-zipkin role the reference
        # wires everywhere (ThriftQueryService.scala:139-144,
        # QueryService.scala:216-222).
        self.tracer = None
        if collector is not None and self_trace:
            from zipkin_tpu.client import Tracer

            self.tracer = Tracer(self_service_name, self._self_transport)
        # Scribe rides the columnar fast path (raw thrift bytes →
        # native parse on a collector worker); the collector falls back
        # to the python codec when the native library is unavailable.
        self.scribe = (
            ScribeReceiver(collector.accept,
                           process_thrift=collector.accept_thrift)
            if collector is not None else None
        )
        self.json_ingest = (
            JsonReceiver(collector.accept) if collector is not None else None
        )
        if self.scribe is not None:
            scribe = self.scribe
            self.registry.register(obs.CallbackFamily(
                "zipkin_scribe_entries",
                "Scribe receiver entry accounting "
                "(received/ignored/bad/pushed_back)",
                "result", lambda: dict(scribe.stats)))
        # Runtime-adjustable vars (HttpVar.scala:30 / the old
        # /config/sampleRate endpoint): name → (getter, setter).
        self.vars = {}
        if collector is not None:
            self.vars["sampleRate"] = (
                lambda: collector.sampler.rate,
                lambda v: setattr(collector.sampler, "rate", float(v)),
            )
        # The resident executor's micro-batch window, adjustable at
        # runtime (ms — matches the daemon's --query-window-ms flag):
        # GET /vars/queryWindowMs, POST /vars/queryWindowMs <number>.
        if coal is not None and hasattr(coal, "window_s"):
            self.vars["queryWindowMs"] = (
                lambda: coal.window_s * 1000.0,
                lambda v: setattr(coal, "window_s", float(v) / 1000.0),
            )
        # Windowed-arena geometry echo (the daemon's --window-seconds /
        # --window-buckets): READ-ONLY — the grid is static device
        # state; changing it means a new store.
        def _static(_v):
            raise QueryException(
                "static store state (window geometry / span-plane "
                "layout shape device arrays; restart with the "
                "matching flag to change them)")

        backing = getattr(query.store, "hot", query.store)
        store_cfg = getattr(backing, "config", None)
        if store_cfg is not None and hasattr(store_cfg,
                                             "window_seconds"):
            self.vars["windowSeconds"] = (
                lambda: store_cfg.window_seconds, _static)
            self.vars["windowBuckets"] = (
                lambda: store_cfg.window_buckets, _static)
        # Span-plane layout echo (the daemon's --layout/--page-rows):
        # READ-ONLY like the window geometry — the layout shapes the
        # device planes and the page planner; changing it means a new
        # store (rebuild via checkpoint restore, docs/MIGRATION.md).
        if store_cfg is not None and hasattr(store_cfg, "layout"):
            self.vars["layout"] = (lambda: store_cfg.layout, _static)
            self.vars["pageRows"] = (
                lambda: store_cfg.page_rows, _static)
        elif hasattr(backing, "window_seconds"):
            # Scan backends (memory store): bucket width only — the
            # exact scan has no ring, so no windowBuckets to echo.
            self.vars["windowSeconds"] = (
                lambda: backing.window_seconds, _static)

    # -- dispatch -------------------------------------------------------

    def _self_transport(self, spans) -> None:
        try:
            self.collector.accept(spans)
        except Exception:
            # Counted, never raised: self-tracing must not fail the
            # request it annotates (graftlint swallowed-exception).
            self._c_self_drops.inc()

    def _should_self_trace(self, method: str, path: str) -> bool:
        if self.tracer is None or not path.startswith("/api/"):
            return False
        # Don't trace the ingest doors — a span per accepted span batch
        # would feed back into the stream it measures.
        return not (method == "POST" and path in ("/api/spans",
                                                  "/api/v1/spans"))

    def handle(self, method: str, path: str, params: dict,
               body: bytes = b"", headers: Optional[dict] = None,
               response_headers: Optional[list] = None
               ) -> Tuple[int, object]:
        t0 = time.perf_counter()
        try:
            return self._handle_traced(method, path, params, body,
                                       headers, response_headers)
        finally:
            route = _route_label(path)
            self.requests_total.labels(route=route).inc()
            self.request_latency.labels(route=route).observe(
                time.perf_counter() - t0)

    def _handle_traced(self, method: str, path: str, params: dict,
                       body: bytes = b"",
                       headers: Optional[dict] = None,
                       response_headers: Optional[list] = None
                       ) -> Tuple[int, object]:
        if not self._should_self_trace(method, path):
            return self._dispatch(method, path, params, body)
        import time as _time

        from zipkin_tpu.client import B3Headers

        b3 = B3Headers.parse(headers or {})
        # Resolve ids up front so the response can echo X-B3-TraceId
        # (the devtools extension's signal, web/extension/) with
        # exactly the ids the recorded span carries — the one contract
        # site is Tracer.resolve (unsampled requests echo only
        # X-B3-Sampled: 0, never a dead trace link). child=True: an
        # inbound B3 context is JOINED as a proper child span (fresh
        # id, parent = the caller's span id) instead of the legacy
        # shared-span reuse, so external probes and the web UI see the
        # API's server span as a distinct hop in their own trace.
        resolved = self.tracer.resolve(b3, child=True)
        if response_headers is not None:
            response_headers.extend(resolved.emit().items())
        start_us = int(_time.time() * 1e6)
        status = 500
        token = None
        if resolved.trace_id is not None:
            # Publish this request's (trace, span) to the thread/task
            # context so downstream shared work — the cross-shard
            # dispatcher's fused launches — can parent spans under it.
            from zipkin_tpu.obs import fleet as _fleet

            token = _fleet.set_request_context(resolved.trace_id,
                                               resolved.span_id)
        try:
            status, payload = self._dispatch(method, path, params, body)
            return status, payload
        finally:
            if token is not None:
                _fleet.reset_request_context(token)
            self.tracer.server_span(
                f"{method.lower()} {path}", resolved,
                start_us=start_us, end_us=int(_time.time() * 1e6),
                tags={"http.uri": path, "http.method": method,
                      "http.status": str(status)},
            )

    def _dispatch(self, method: str, path: str, params: dict,
                  body: bytes) -> Tuple[int, object]:
        try:
            return self._route(method, path, params, body)
        except QueryException as e:
            return 400, {"error": str(e)}
        except KeyError as e:
            return 404, {"error": f"not found: {e}"}
        except (ValueError, json.JSONDecodeError) as e:
            return 400, {"error": str(e)}
        except StorageException as e:
            # A write reaching a read replica (store/replica.py), or a
            # suspect/closing store: the request is routable elsewhere.
            return 503, {"error": str(e)}

    def _route(self, method, path, params, body):
        if path in ("/", "/index.html", "/traces", "/aggregate"):
            # The SPA serves every page route (web/Main.scala:77-89's
            # /, /traces/:id, /aggregate mustache pages collapse into
            # one client-rendered file).
            from zipkin_tpu import web

            return 200, RawResponse("text/html; charset=utf-8",
                                    web.index_html())
        if path == "/health":
            return 200, {"status": "ok"}
        if path == "/api/health":
            # Watchdog-backed liveness/readiness with reasons
            # (docs/OBSERVABILITY.md runbook). Without a fleet hub the
            # process is trivially ready — /health's contract with a
            # structured body.
            if self.fleet is None:
                return 200, {"live": True, "ready": True, "reasons": []}
            h = self.fleet.health()
            return (200 if h.get("ready") else 503), h
        if path == "/api/fleet":
            if self.fleet is None:
                return 200, {"role": "none"}
            return 200, self.fleet.status()
        if path == "/debug/events":
            limit = params.get("limit")
            events = ([] if self.fleet is None
                      else self.fleet.events(int(limit) if limit
                                             else None))
            return 200, {"events": events}
        if path == "/metrics":
            # Prometheus text exposition by default; the legacy JSON
            # dict stays at ?format=json (docs/MIGRATION.md).
            if params.get("format") == "json":
                return 200, self._metrics()
            if params.get("fleet") and self.fleet is not None:
                # Federated scrape: this process's registry plus every
                # pushed follower/shard snapshot, label-distinguished
                # (obs.fleet.render_federated — no double counting).
                return 200, RawResponse(
                    "text/plain; version=0.0.4; charset=utf-8",
                    self.fleet.federated_text().encode("utf-8"),
                )
            return 200, RawResponse(
                "text/plain; version=0.0.4; charset=utf-8",
                self.registry.render_text().encode("utf-8"),
            )
        if method == "POST" and path == "/debug/profile":
            return self._profile(params)
        if path == "/api/query":
            return self._query(params)
        if path == "/api/services":
            return 200, sorted(self.query.get_service_names())
        if path == "/api/spans" and method == "GET":
            return 200, sorted(self.query.get_span_names(
                _require(params, "serviceName")))
        if path == "/api/top_annotations":
            return 200, self.query.get_top_annotations(
                _require(params, "serviceName"))
        if path == "/api/top_kv_annotations":
            return 200, self.query.get_top_key_value_annotations(
                _require(params, "serviceName"))
        if path == "/api/quantiles":
            qs = [float(x) for x in
                  params.get("q", "0.5,0.95,0.99").split(",")]
            vals = self.query.get_service_duration_quantiles(
                _require(params, "serviceName"), qs)
            # An empty histogram yields NaNs, which json.dumps would
            # emit as BARE NaN — invalid JSON that breaks JSON.parse
            # in the browser. No data serializes as null.
            if vals is not None:
                vals = [round(v, 1) for v in vals]
                if any(v != v for v in vals):
                    vals = None
            return 200, {"quantiles": qs, "durationsMicro": vals}
        if path == "/api/windowed_quantiles":
            return self._windowed_quantiles(params)
        if path == "/api/slo_burn":
            return self._slo_burn(params)
        if path == "/api/latency_heatmap":
            return self._latency_heatmap(params)
        if path == "/api/span_durations":
            return self._span_durations(params)
        if path == "/api/service_names_to_trace_ids":
            return self._service_names_to_trace_ids(params)
        if path == "/api/data_ttl":
            return 200, {
                "dataTimeToLive": self.query.get_data_time_to_live()
            }
        if path == "/api/replication":
            if self.replication is None:
                return 200, {"role": "none"}
            return 200, self.replication()
        if path == "/api/dependencies" or re.match(r"^/api/dependencies/", path):
            return self._dependencies(path, params)
        if path == "/api/traces_exist":
            return self._traces_exist(params)
        # Trace ids in paths are unsigned hex (upstream zipkin URL
        # convention; span_to_json emits the same form). A leading "-"
        # keeps accepting legacy signed-decimal callers unambiguously.
        m = re.match(r"^/api/(?:trace|get)/(-?[0-9a-fA-F]+)$", path)
        if m:
            return self._trace(_parse_trace_id(m.group(1)), params)
        # Thrift query-surface parity beyond the web routes:
        # getTraceTimelinesByIds / getTraceCombosByIds
        # (zipkinQuery.thrift:109-251).
        m = re.match(r"^/api/timeline/(-?[0-9a-fA-F]+)$", path)
        if m:
            return self._timeline(_parse_trace_id(m.group(1)), params)
        m = re.match(r"^/api/combo/(-?[0-9a-fA-F]+)$", path)
        if m:
            return self._combo(_parse_trace_id(m.group(1)), params)
        m = re.match(r"^/api/is_pinned/(-?[0-9a-fA-F]+)$", path)
        if m:
            return self._is_pinned(_parse_trace_id(m.group(1)))
        m = re.match(r"^/api/pin/(-?[0-9a-fA-F]+)/(true|false)$", path)
        if m and method == "POST":
            return self._pin(_parse_trace_id(m.group(1)),
                             m.group(2) == "true")
        if method == "POST" and path in ("/api/spans", "/api/v1/spans"):
            return self._ingest_json(body)
        if method == "POST" and path == "/scribe":
            return self._ingest_scribe(body)
        m = re.match(r"^/vars/(\w+)$", path)
        if m:
            return self._var(m.group(1), method, body)
        raise KeyError(path)

    def _var(self, name: str, method: str, body: bytes):
        getter_setter = self.vars.get(name)
        if getter_setter is None:
            raise KeyError(name)
        getter, setter = getter_setter
        if method == "POST":
            setter(json.loads(body or b"null"))
        return 200, {name: getter()}

    # -- handlers -------------------------------------------------------

    def _query(self, params):
        qr = extract_query(params)
        if qr is None:
            return 400, {"error": "serviceName is required"}
        resp = self.query.get_trace_ids(qr)
        summaries = self.query.get_trace_summaries_by_ids(resp.trace_ids)
        return 200, {
            "traceIds": [_hex_id(t) for t in resp.trace_ids],
            "startTs": resp.start_ts,
            "endTs": resp.end_ts,
            "summaries": [_summary_json(s) for s in summaries],
        }

    def _trace(self, trace_id: int, params):
        adjust = params.get("adjust_clock_skew", "true") != "false"
        traces = self.query.get_traces_by_ids([trace_id], adjust=adjust)
        if not traces:
            raise KeyError(trace_id)
        return 200, _trace_json(traces[0])

    def _timeline(self, trace_id: int, params):
        adjust = params.get("adjust_clock_skew", "true") != "false"
        tls = self.query.get_trace_timelines_by_ids([trace_id],
                                                    adjust=adjust)
        if not tls:
            raise KeyError(trace_id)
        return 200, _timeline_json(tls[0])

    def _combo(self, trace_id: int, params):
        adjust = params.get("adjust_clock_skew", "true") != "false"
        combos = self.query.get_trace_combos_by_ids([trace_id],
                                                    adjust=adjust)
        if not combos or not combos[0].trace.spans:
            raise KeyError(trace_id)
        c = combos[0]
        return 200, {
            "trace": _trace_json(c.trace),
            "summary": None if c.summary is None
            else _summary_json(c.summary),
            "timeline": None if c.timeline is None
            else _timeline_json(c.timeline),
            "spanDepths": None if c.span_depths is None else {
                _hex_id(k): v for k, v in c.span_depths.items()
            },
        }

    def _dependencies(self, path, params):
        """Optionally windowed: /api/dependencies/<startTs>/<endTs> or
        ?startTime=&endTime= (µs) — Aggregates.getDependencies(start,
        end), web route parity with /api/dependencies (Main.scala:85)."""
        m = re.match(r"^/api/dependencies/(-?\d+)(?:/(-?\d+))?$", path)
        start_ts = end_ts = None
        if m:
            start_ts = int(m.group(1))
            end_ts = int(m.group(2)) if m.group(2) else None
        for key, val in (("startTime", "start"), ("endTime", "end"),
                         ("startTs", "start"), ("endTs", "end")):
            raw = params.get(key)
            if raw is not None:
                if val == "start":
                    start_ts = int(raw)
                else:
                    end_ts = int(raw)
        deps = self.query.get_dependencies(start_ts, end_ts)
        return 200, {
            "startTime": _finite_or_none(deps.start_time),
            "endTime": _finite_or_none(deps.end_time),
            "links": [
                {
                    "parent": l.parent,
                    "child": l.child,
                    "durationMoments": _moments_json(l.duration_moments),
                }
                for l in deps.links
            ],
        }

    @staticmethod
    def _slice_params(params):
        """(timeStamp, serviceName, spanName) for the thrift slice
        methods: timeStamp defaults to 'everything so far' and spanName
        'all' means no rpc-name restriction (the query-extractor
        convention)."""
        ts_raw = params.get("timeStamp") or params.get("endTs")
        time_stamp = int(ts_raw) if ts_raw else (1 << 62)
        span_name = params.get("spanName")
        if span_name == "all":
            span_name = None
        return time_stamp, params.get("serviceName"), span_name

    @staticmethod
    def _opt_int(params, *keys):
        for k in keys:
            raw = params.get(k)
            if raw is not None and raw != "":
                return int(raw)
        return None

    def _windowed_quantiles(self, params):
        """Windowed latency quantiles off the (service × time-bucket)
        Moments-sketch cells (docs/OBSERVABILITY.md): any [startTs,
        endTs) µs window answers as a cell-sum + one Moments solve —
        no segment scan, no device dispatch. null durations = no
        duration-carrying span in the window (or no arena)."""
        qs = [float(x) for x in
              params.get("q", "0.5,0.95,0.99").split(",")]
        vals = self.query.get_windowed_quantiles(
            _require(params, "serviceName"), qs,
            start_us=self._opt_int(params, "startTs", "startTime"),
            end_us=self._opt_int(params, "endTs", "endTime"))
        if vals is not None:
            vals = [round(v, 1) for v in vals]
            if any(v != v for v in vals):
                vals = None
        return 200, {"quantiles": qs, "durationsMicro": vals}

    def _slo_burn(self, params):
        """Multi-window error-budget burn rate: per lookback window
        (seconds, comma list), error rate over the windowed cells'
        error/total counts divided by the budget (1 - objective)."""
        windows = params.get("windows")
        windows_s = ([int(x) for x in windows.split(",") if x]
                     if windows else None)
        objective = params.get("objective")
        out = self.query.get_slo_burn(
            _require(params, "serviceName"),
            objective=float(objective) if objective else None,
            windows_s=windows_s,
            now_us=self._opt_int(params, "nowTs"))
        if out is None:
            return 200, {"windows": None}
        return 200, out

    def _latency_heatmap(self, params):
        """Service × time × duration-band grid from the windowed
        cells: one column per live time bucket, log-spaced duration
        bands, per-cell mass from the Moments solve."""
        bands = params.get("bands")
        out = self.query.get_latency_heatmap(
            _require(params, "serviceName"),
            start_us=self._opt_int(params, "startTs", "startTime"),
            end_us=self._opt_int(params, "endTs", "endTime"),
            bands=int(bands) if bands else None)
        if out is None:
            return 200, {"cells": None}
        return 200, out

    def _span_durations(self, params):
        """getSpanDurations (zipkinQuery.thrift) over HTTP: durations
        (µs) of spans named spanName, grouped by owning service."""
        time_stamp, service, span_name = self._slice_params(params)
        if not service:
            raise QueryException("serviceName is required")
        if not span_name:
            # Distinguish absent from the explicit "all" wildcard —
            # getSpanDurations has no all-spans form, so the wildcard
            # gets an accurate rejection, not "required".
            if params.get("spanName") == "all":
                raise QueryException(
                    "spanName must name a specific span "
                    "(getSpanDurations has no 'all' form)")
            raise QueryException("spanName is required")
        return 200, {
            "durations": self.query.get_span_durations(
                time_stamp, service, span_name)
        }

    def _service_names_to_trace_ids(self, params):
        """getServiceNamesToTraceIds (zipkinQuery.thrift) over HTTP:
        participating service name -> unsigned-hex trace ids."""
        time_stamp, service, span_name = self._slice_params(params)
        if not service:
            raise QueryException("serviceName is required")
        mapping = self.query.get_service_names_to_trace_ids(
            time_stamp, service, span_name)
        return 200, {
            "serviceNames": {
                svc: [_hex_id(t) for t in tids]
                for svc, tids in sorted(mapping.items())
            }
        }

    def _traces_exist(self, params):
        """tracesExist (zipkinQuery.thrift:154): which of the queried
        ids have ANY stored span — the cheap batched membership probe
        the thrift surface offers before a full trace fetch. Ids are
        comma-separated unsigned hex (the /api/trace/<id> URL
        convention; legacy signed decimal accepted). The TPU store
        answers through the trace-membership gid buckets when their
        exactness gate holds, the O(ring) scan otherwise."""
        raw = _require(params, "traceIds")
        tids = [_parse_trace_id(t.strip())
                for t in raw.split(",") if t.strip()]
        exist = self.query.traces_exist(tids)
        return 200, {"exist": sorted(_hex_id(t) for t in exist)}

    def _is_pinned(self, trace_id: int):
        try:
            ttl = self.query.get_trace_time_to_live(trace_id)
        except KeyError:
            raise
        return 200, {"pinned": ttl >= self.pin_ttl_s}

    def _pin(self, trace_id: int, state: bool):
        self.query.set_trace_time_to_live(
            trace_id, self.pin_ttl_s if state else DEFAULT_TTL_S
        )
        return 200, {"pinned": state}

    def _ingest_json(self, body: bytes):
        if self.json_ingest is None:
            return 501, {"error": "no collector attached"}
        code = self.json_ingest.post(body)
        if code is ResultCode.TRY_LATER:
            return 503, {"error": "try later"}
        return 202, {"accepted": True}

    def _ingest_scribe(self, body: bytes):
        if self.scribe is None:
            return 501, {"error": "no collector attached"}
        entries = [
            (e["category"], e["message"]) for e in json.loads(body)
        ]
        code = self.scribe.log(entries)
        return 200, {"result": code.name}

    def _profile(self, params):
        """POST /debug/profile?seconds=N — capture a jax.profiler trace
        for N seconds (this request's thread blocks for the window;
        ThreadingHTTPServer keeps serving others). Returns the trace
        directory, viewable with TensorBoard/Perfetto."""
        from zipkin_tpu.obs import profile as obs_profile

        try:
            seconds = float(params.get("seconds", "1.0"))
        except ValueError:
            return 400, {"error": "seconds must be a number"}
        try:
            out_dir, effective = obs_profile.capture(seconds)
        except obs_profile.ProfilerBusy as e:
            return 409, {"error": str(e)}
        except Exception as e:  # backend can't trace → service-level 503
            return 503, {"error": f"profiler unavailable: {e}"}
        return 200, {"profileDir": out_dir, "seconds": effective}

    def _metrics(self):
        out = {}
        if self.collector is not None:
            out.update({
                "collector.queue_size": self.collector.queue.size,
                "collector.active_workers": self.collector.queue.active_workers,
                "collector.processed": self.collector.queue.processed,
                "collector.errors": self.collector.queue.errors,
                "collector.spans_stored": self.collector.spans_stored,
                "collector.spans_dropped": self.collector.spans_dropped,
                "sampler.rate": self.collector.sampler.rate,
            })
        counters = getattr(self.query.store, "counters", None)
        if callable(counters):
            out.update({f"store.{k}": v for k, v in counters().items()})
        coal = getattr(self.query, "coalescer", None)
        if coal is not None:
            # The read-path dispatch-floor observable: how many device
            # launches cross-request micro-batching removed.
            out.update({
                "query.coalesce_batches": coal.batches,
                "query.coalesce_queries": coal.queries,
                "query.coalesce_launches_saved": coal.launches_saved,
                "query.coalesce_max_batch": coal.max_batch,
            })
        disp = getattr(self.query.store, "dispatcher", None)
        if disp is not None:
            # Store-level twin of the coalescer block: collective
            # launches the cross-shard dispatcher fused away
            # (docs/SHARDING.md).
            out.update({
                "shard.dispatch_batches": disp.batches,
                "shard.dispatch_requests": disp.requests,
                "shard.dispatch_launches_saved": disp.launches_saved,
                "shard.dispatch_max_batch": disp.max_batch,
            })
        eng = getattr(self.query, "engine", None)
        if eng is not None:
            # Resident-engine tier accounting (docs/QUERY_ENGINE.md).
            out.update({
                "query.cache_hits": eng.c_hits.value,
                "query.cache_misses": eng.c_misses.value,
                "query.cache_entries": len(eng.cache),
                "query.sketch_answers": eng.c_sketch.value,
            })
        return out


# Dynamic path segments collapse to {id} so the per-route latency
# family stays bounded-cardinality; anything unrecognized buckets into
# "other" (a hostile scanner must not mint one series per probe).
_ROUTE_ID_RE = re.compile(
    r"^(/api/(?:trace|get|timeline|combo|is_pinned))/[^/]+$")
_ROUTE_PIN_RE = re.compile(r"^/api/pin/[^/]+/(?:true|false)$")
_KNOWN_ROUTES = frozenset((
    "/", "/index.html", "/traces", "/aggregate", "/health", "/metrics",
    "/debug/profile", "/api/query", "/api/services", "/api/spans",
    "/api/v1/spans", "/api/top_annotations", "/api/top_kv_annotations",
    "/api/quantiles", "/api/dependencies", "/api/traces_exist",
    "/api/span_durations", "/api/service_names_to_trace_ids",
    "/api/data_ttl", "/api/windowed_quantiles", "/api/slo_burn",
    "/api/latency_heatmap", "/api/replication", "/api/health",
    "/api/fleet", "/debug/events", "/scribe",
))


def _route_label(path: str) -> str:
    m = _ROUTE_ID_RE.match(path)
    if m:
        return m.group(1) + "/{id}"
    if _ROUTE_PIN_RE.match(path):
        return "/api/pin/{id}"
    if path in _KNOWN_ROUTES:
        return path
    if path.startswith("/api/dependencies/"):
        return "/api/dependencies/{window}"
    if path.startswith("/vars/"):
        return "/vars/{name}"
    return "other"


def _parse_trace_id(raw: str) -> int:
    """Unsigned hex (the wire form) or signed decimal (legacy),
    canonicalized to signed int64 — span_from_json does the same, and
    stores that compare ids exactly (the in-memory reference) must see
    the id the span was stored under, not its unsigned twin."""
    if raw.startswith("-"):
        return int(raw)
    u = int(raw, 16)
    return u - (1 << 64) if u >= (1 << 63) else u


def _require(params, key):
    v = params.get(key)
    if not v:
        raise QueryException(f"{key} is required")
    return v


def make_server(api: ApiServer, host: str = "0.0.0.0", port: int = 9411
                ) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def _respond(self):
            parsed = urlparse(self.path)
            params = dict(parse_qsl(parsed.query))
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            extra_headers: list = []
            status, payload = api.handle(
                self.command, parsed.path, params, body,
                headers=dict(self.headers),
                response_headers=extra_headers,
            )
            if isinstance(payload, RawResponse):
                ctype, data = payload.content_type, payload.body
            else:
                ctype = "application/json"
                data = json.dumps(payload).encode("utf-8")
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for name, value in extra_headers:
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(data)

        do_GET = _respond
        do_POST = _respond

        def log_message(self, *args):  # quiet by default
            pass

    return ThreadingHTTPServer((host, port), Handler)


def serve_forever_in_thread(server: ThreadingHTTPServer) -> threading.Thread:
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return t
