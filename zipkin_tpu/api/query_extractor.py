"""GET params → QueryRequest (QueryExtractor.scala:26-92 semantics).

Notably the ``annotationQuery`` mini-language: terms joined by " and ";
``key=value`` terms become binary-annotation (string) queries, bare
``key`` terms become annotation queries. ``spanName`` values "all"/""
mean no span filter. Default limit mirrors the web constant (100).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from zipkin_tpu.query.request import (
    BinaryAnnotationQuery,
    Order,
    QueryRequest,
)

DEFAULT_LIMIT = 100

_ORDERS = {
    "timestamp-desc": Order.TIMESTAMP_DESC,
    "timestamp-asc": Order.TIMESTAMP_ASC,
    "duration-desc": Order.DURATION_DESC,
    "duration-asc": Order.DURATION_ASC,
    "none": Order.NONE,
}


def extract_query(params: Dict[str, str]) -> Optional[QueryRequest]:
    service = params.get("serviceName")
    if not service:
        return None
    span_name = params.get("spanName")
    if span_name in ("all", "", None):
        span_name = None
    annotations = []
    binary = []
    for term in params.get("annotationQuery", "").split(" and "):
        if not term:
            continue
        if "=" in term:
            key, _, value = term.partition("=")
            if key:
                binary.append(
                    BinaryAnnotationQuery(key, value.encode("utf-8"))
                )
        else:
            annotations.append(term)
    end_ts = int(params.get("timestamp") or params.get("endTs")
                 or int(time.time() * 1_000_000))
    limit = int(params.get("limit") or DEFAULT_LIMIT)
    order = _ORDERS.get(params.get("order", "none"), Order.NONE)
    return QueryRequest(
        service_name=service,
        span_name=span_name,
        annotations=tuple(annotations),
        binary_annotations=tuple(binary),
        end_ts=end_ts,
        limit=limit,
        order=order,
    )
