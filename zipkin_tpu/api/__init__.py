"""HTTP JSON API mirroring zipkin-web's route surface.

Reference routes (web/Main.scala:77-89): /api/query, /api/services,
/api/spans, /api/top_annotations, /api/top_kv_annotations,
/api/dependencies, /api/trace/:id (alias /api/get/:id),
/api/is_pinned/:id, /api/pin/:id/:state — plus ingest doors
(POST /api/spans JSON, POST /scribe) and /health and /metrics.
"""

from zipkin_tpu.api.server import ApiServer, make_server  # noqa: F401
from zipkin_tpu.api.query_extractor import extract_query  # noqa: F401
