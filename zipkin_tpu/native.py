"""ctypes bindings for the native span parser (native/span_codec.cc).

The C++ parser turns a raw thrift Span sequence into columnar numpy
arrays in one pass — the native fast path for the collector's hot
decode (reference role: scrooge's binary deserializer on
ScribeSpanReceiver.scala:96-107). String fields come back as
(offset, length) slices into the input buffer; the host interns them
through the shared DictionarySet so device ids stay consistent.

The library is built on demand with g++ (cached next to the source);
callers must handle ``NativeUnavailable`` and fall back to the pure
python codec (zipkin_tpu.wire.thrift) — see ``parse_spans_columnar``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from zipkin_tpu.columnar.dictionary import DictionarySet
from zipkin_tpu.columnar.schema import (
    FLAG_DEBUG,
    FLAG_HAS_PARENT,
    NO_ENDPOINT,
    NO_SERVICE,
    NO_TS,
    SpanBatch,
)
from zipkin_tpu.models.constants import (
    CLIENT_RECV,
    CLIENT_SEND,
    SERVER_RECV,
    SERVER_SEND,
)

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "span_codec.cc")
_SO = os.path.join(os.path.dirname(_SRC), "libzipkin_native.so")

_lock = threading.Lock()
_lib = None


class NativeUnavailable(RuntimeError):
    pass


class _SpanColumns(ctypes.Structure):
    _fields_ = [(name, ctypes.c_void_p) for name in (
        "trace_id", "span_id", "parent_id", "has_parent", "debug",
        "name_off", "name_len",
        "ann_span_idx", "ann_ts", "ann_value_off", "ann_value_len",
        "ann_ipv4", "ann_port", "ann_svc_off", "ann_svc_len",
        "bann_span_idx", "bann_key_off", "bann_key_len",
        "bann_value_off", "bann_value_len", "bann_type",
        "bann_ipv4", "bann_port", "bann_svc_off", "bann_svc_len",
    )]


def _build(force: bool = False) -> str:
    if not force and os.path.exists(_SO) and (
        os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
    ):
        return _SO
    try:
        subprocess.run(
            ["g++", "-O3", "-Wall", "-shared", "-fPIC", "-std=c++17",
             "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120,
        )
    except (OSError, subprocess.SubprocessError) as e:
        raise NativeUnavailable(f"could not build native codec: {e}") from e
    return _SO


def _load() -> ctypes.CDLL:
    """Build (if stale) and dlopen, rebuilding once on a load failure —
    a stale or wrong-arch .so from a previous checkout must fall through
    to a fresh build, and a still-failing load must surface as
    NativeUnavailable so callers engage the pure-python fallback."""
    path = _build()
    try:
        return ctypes.CDLL(path)
    except OSError:
        path = _build(force=True)
        try:
            return ctypes.CDLL(path)
        except OSError as e:
            raise NativeUnavailable(
                f"could not load native codec: {e}"
            ) from e


def get_lib():
    global _lib
    with _lock:
        if _lib is None:
            lib = _load()
            lib.zk_parse_spans.restype = ctypes.c_int
            lib.zk_parse_spans.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(_SpanColumns),
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
            ]
            lib.zk_base64_decode.restype = ctypes.c_int64
            lib.zk_base64_decode.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p,
            ]
            _lib = lib
    return _lib


def available() -> bool:
    try:
        get_lib()
        return True
    except NativeUnavailable:
        return False


def base64_decode(data: bytes) -> bytes:
    lib = get_lib()
    out = ctypes.create_string_buffer((len(data) * 3) // 4 + 4)
    n = lib.zk_base64_decode(data, len(data), out)
    if n < 0:
        raise ValueError("bad base64 payload")
    return out.raw[:n]


_CORE_TS = {CLIENT_SEND: "ts_cs", CLIENT_RECV: "ts_cr",
            SERVER_RECV: "ts_sr", SERVER_SEND: "ts_ss"}


def indexable_from_batch(batch: SpanBatch, dicts: DictionarySet) -> np.ndarray:
    """Columnar should_index (store/base.py:51): exclude spans that are
    client-side and carry the literal service name "client"."""
    ns = batch.n_spans
    out = np.ones(ns, bool)
    client_svc = dicts.services.get("client")
    if client_svc is None or ns == 0:
        return out
    cs_id, cr_id = 0, 1  # CORE_ANNOTATION_IDS cs/cr
    is_core_client = np.isin(batch.ann_value_id, (cs_id, cr_id))
    has_client_side = np.zeros(ns, bool)
    np.logical_or.at(has_client_side, batch.ann_span_idx[is_core_client], True)
    svc_is_client = batch.ann_service_id == client_svc
    has_client_svc = np.zeros(ns, bool)
    np.logical_or.at(has_client_svc, batch.ann_span_idx[svc_is_client], True)
    out &= ~(has_client_side & has_client_svc)
    return out


class ParseCapacityError(ValueError):
    """Valid payload larger than the parse buffers — chunk and retry
    (distinct from malformed input so callers don't drop good data)."""


def parse_spans_columnar(
    payload: bytes, dicts: DictionarySet,
    max_spans: int = 1 << 16,
) -> Tuple[SpanBatch, np.ndarray]:
    """Thrift Span sequence → (SpanBatch, name_lc_id column).

    The numeric work happens in C++; this wrapper interns strings and
    assembles the SpanBatch. Raises NativeUnavailable when the shared
    object can't be built; ValueError on malformed input;
    ParseCapacityError when the payload exceeds the parse buffers.
    """
    batch, name_lc, _, _ = parse_spans_columnar_sampled(
        payload, dicts, 0, max_spans
    )
    return batch, name_lc


def parse_spans_columnar_sampled(
    payload: bytes, dicts: DictionarySet,
    sample_threshold: int, max_spans: int = 1 << 16,
) -> Tuple[SpanBatch, np.ndarray, int, int]:
    """parse_spans_columnar with the sampler's trace-id threshold test
    applied on the numeric columns BEFORE any string interning, so
    sampled-out traffic never pollutes the dictionaries (or pays intern
    cost). Debug-flagged spans always pass (SpanSamplerFilter.scala:40).

    Returns (batch, name_lc, n_dropped, n_kept_debug) where
    n_kept_debug counts kept spans carrying the debug flag (the slow
    path never runs those through the sampler's counters).
    """
    lib = get_lib()
    max_anns = max_spans * 8
    max_banns = max_spans * 8

    cols = {}

    def arr(name, n, dtype):
        a = np.zeros(n, dtype)
        cols[name] = a
        return a.ctypes.data_as(ctypes.c_void_p)

    sc = _SpanColumns(
        trace_id=arr("trace_id", max_spans, np.int64),
        span_id=arr("span_id", max_spans, np.int64),
        parent_id=arr("parent_id", max_spans, np.int64),
        has_parent=arr("has_parent", max_spans, np.uint8),
        debug=arr("debug", max_spans, np.uint8),
        name_off=arr("name_off", max_spans, np.int64),
        name_len=arr("name_len", max_spans, np.int32),
        ann_span_idx=arr("ann_span_idx", max_anns, np.int32),
        ann_ts=arr("ann_ts", max_anns, np.int64),
        ann_value_off=arr("ann_value_off", max_anns, np.int64),
        ann_value_len=arr("ann_value_len", max_anns, np.int32),
        ann_ipv4=arr("ann_ipv4", max_anns, np.int32),
        ann_port=arr("ann_port", max_anns, np.int32),
        ann_svc_off=arr("ann_svc_off", max_anns, np.int64),
        ann_svc_len=arr("ann_svc_len", max_anns, np.int32),
        bann_span_idx=arr("bann_span_idx", max_banns, np.int32),
        bann_key_off=arr("bann_key_off", max_banns, np.int64),
        bann_key_len=arr("bann_key_len", max_banns, np.int32),
        bann_value_off=arr("bann_value_off", max_banns, np.int64),
        bann_value_len=arr("bann_value_len", max_banns, np.int32),
        bann_type=arr("bann_type", max_banns, np.int32),
        bann_ipv4=arr("bann_ipv4", max_banns, np.int32),
        bann_port=arr("bann_port", max_banns, np.int32),
        bann_svc_off=arr("bann_svc_off", max_banns, np.int64),
        bann_svc_len=arr("bann_svc_len", max_banns, np.int32),
    )
    n_spans = ctypes.c_int32(0)
    n_anns = ctypes.c_int32(0)
    n_banns = ctypes.c_int32(0)
    rc = lib.zk_parse_spans(
        payload, len(payload), ctypes.byref(sc),
        max_spans, max_anns, max_banns,
        ctypes.byref(n_spans), ctypes.byref(n_anns), ctypes.byref(n_banns),
    )
    if rc == -1:
        raise ValueError("malformed thrift span payload")
    if rc in (-2, -3, -4):
        raise ParseCapacityError(
            "payload exceeds parse capacity; chunk the input"
        )
    ns, na, nb = n_spans.value, n_anns.value, n_banns.value

    # Sampler threshold test on the numeric columns, pre-intern.
    debug_col = cols["debug"][:ns] != 0
    if sample_threshold > 0 and ns:
        tids = cols["trace_id"][:ns]
        t = np.where(tids == np.int64(-(2**63)), np.int64(2**63 - 1),
                     np.abs(tids))
        keep = debug_col | (t > np.int64(sample_threshold))
    else:
        keep = np.ones(ns, bool)
    kept_idx = np.flatnonzero(keep)
    dropped = int(ns - kept_idx.size)
    kept_debug = int(np.count_nonzero(debug_col & keep))
    new_of_old = np.cumsum(keep) - 1  # old span index → new
    ka = (keep[cols["ann_span_idx"][:na]] if na
          else np.zeros(0, bool))
    kb = (keep[cols["bann_span_idx"][:nb]] if nb
          else np.zeros(0, bool))
    kns = kept_idx.size

    b = SpanBatch.empty(kns, int(np.count_nonzero(ka)),
                        int(np.count_nonzero(kb)))
    b.trace_id[:] = cols["trace_id"][:ns][keep]
    b.span_id[:] = cols["span_id"][:ns][keep]
    b.parent_id[:] = cols["parent_id"][:ns][keep]
    b.flags[:] = (
        cols["has_parent"][:ns][keep] * np.uint8(FLAG_HAS_PARENT)
        + cols["debug"][:ns][keep] * np.uint8(FLAG_DEBUG)
    )

    mem = payload  # bytes: slicing is cheap

    name_lc = np.empty(kns, np.int32)
    for out_i, i in enumerate(kept_idx):
        raw = mem[int(cols["name_off"][i]):
                  int(cols["name_off"][i]) + int(cols["name_len"][i])]
        name = raw.decode("utf-8", "replace")
        b.name_id[out_i] = dicts.span_names.encode(name)
        name_lc[out_i] = (
            -1 if name == "" else dicts.span_names.encode(name.lower())
        )

    # Annotation table + per-span core-ts columns and owning service.
    server_svc = np.full(kns, NO_SERVICE, np.int64)
    client_svc = np.full(kns, NO_SERVICE, np.int64)
    aj = 0
    for j in np.flatnonzero(ka):
        si = int(new_of_old[cols["ann_span_idx"][j]])
        ts = int(cols["ann_ts"][j])
        voff, vlen = int(cols["ann_value_off"][j]), int(cols["ann_value_len"][j])
        value = mem[voff:voff + vlen].decode("utf-8", "replace")
        b.ann_span_idx[aj] = si
        b.ann_ts[aj] = ts
        b.ann_value_id[aj] = dicts.annotations.encode(value)
        slen = int(cols["ann_svc_len"][j])
        if slen >= 0 or slen == -2:
            if slen == -2:
                # Endpoint present but service_name absent: same default
                # as the python codec (wire/thrift.py _r_endpoint).
                svc_name = "unknown"
            else:
                soff = int(cols["ann_svc_off"][j])
                svc_name = mem[soff:soff + slen].decode("utf-8", "replace")
            svc_id = dicts.services.encode(svc_name.lower())
            b.ann_service_id[aj] = svc_id
            b.ann_endpoint_id[aj] = dicts.endpoints.encode(
                (int(cols["ann_ipv4"][j]), int(cols["ann_port"][j]), svc_name)
            )
            if value in (SERVER_RECV, SERVER_SEND) and server_svc[si] < 0:
                server_svc[si] = svc_id
            elif value in (CLIENT_SEND, CLIENT_RECV) and client_svc[si] < 0:
                client_svc[si] = svc_id
        core_col = _CORE_TS.get(value)
        if core_col is not None:
            getattr(b, core_col)[si] = ts
        if b.ts_first[si] == NO_TS or ts < b.ts_first[si]:
            b.ts_first[si] = ts
        if b.ts_last[si] == NO_TS or ts > b.ts_last[si]:
            b.ts_last[si] = ts
        aj += 1

    has_ts = b.ts_first != NO_TS
    b.duration[has_ts] = b.ts_last[has_ts] - b.ts_first[has_ts]
    b.service_id[:] = np.where(
        server_svc >= 0, server_svc,
        np.where(client_svc >= 0, client_svc, NO_SERVICE),
    ).astype(np.int32)

    from zipkin_tpu.models.span import AnnotationType
    from zipkin_tpu.wire.thrift import _decode_binary_value

    bj = 0
    for j in np.flatnonzero(kb):
        b.bann_span_idx[bj] = int(new_of_old[cols["bann_span_idx"][j]])
        koff, klen = int(cols["bann_key_off"][j]), int(cols["bann_key_len"][j])
        b.bann_key_id[bj] = dicts.binary_keys.encode(
            mem[koff:koff + klen].decode("utf-8", "replace")
        )
        voff, vlen = int(cols["bann_value_off"][j]), int(cols["bann_value_len"][j])
        btype = int(cols["bann_type"][j])
        b.bann_type[bj] = btype if 0 <= btype <= 6 else 1

        value = _decode_binary_value(
            mem[voff:voff + vlen], AnnotationType(int(b.bann_type[bj]))
        )
        if isinstance(value, bytearray):
            value = bytes(value)
        b.bann_value_id[bj] = dicts.binary_values.encode(value)
        slen = int(cols["bann_svc_len"][j])
        if slen >= 0 or slen == -2:
            if slen == -2:
                svc_name = "unknown"
            else:
                soff = int(cols["bann_svc_off"][j])
                svc_name = mem[soff:soff + slen].decode("utf-8", "replace")
            b.bann_service_id[bj] = dicts.services.encode(svc_name.lower())
            b.bann_endpoint_id[bj] = dicts.endpoints.encode(
                (int(cols["bann_ipv4"][j]), int(cols["bann_port"][j]), svc_name)
            )
        bj += 1
    return b, name_lc, dropped, kept_debug
