"""ctypes bindings for the native span parser (native/span_codec.cc).

The C++ parser turns a raw thrift Span sequence into columnar numpy
arrays in one pass — the native fast path for the collector's hot
decode (reference role: scrooge's binary deserializer on
ScribeSpanReceiver.scala:96-107). String fields come back as
(offset, length) slices into the input buffer; the host interns them
through the shared DictionarySet so device ids stay consistent.

The library is built on demand with g++ (cached next to the source);
callers must handle ``NativeUnavailable`` and fall back to the pure
python codec (zipkin_tpu.wire.thrift) — see ``parse_spans_columnar``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from zipkin_tpu.columnar.dictionary import DictionarySet
from zipkin_tpu.columnar.schema import (
    FLAG_DEBUG,
    FLAG_HAS_PARENT,
    NO_ENDPOINT,
    NO_SERVICE,
    NO_TS,
    SpanBatch,
)
from zipkin_tpu.models.constants import (
    CLIENT_RECV,
    CLIENT_SEND,
    SERVER_RECV,
    SERVER_SEND,
)

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "span_codec.cc")
_SO = os.path.join(os.path.dirname(_SRC), "libzipkin_native.so")

_lock = threading.Lock()  # lock-order: 86 native-build
_lib = None


class NativeUnavailable(RuntimeError):
    pass


class _SpanColumns(ctypes.Structure):
    _fields_ = [(name, ctypes.c_void_p) for name in (
        "trace_id", "span_id", "parent_id", "has_parent", "debug",
        "name_off", "name_len",
        "ann_span_idx", "ann_ts", "ann_value_off", "ann_value_len",
        "ann_ipv4", "ann_port", "ann_svc_off", "ann_svc_len",
        "bann_span_idx", "bann_key_off", "bann_key_len",
        "bann_value_off", "bann_value_len", "bann_type",
        "bann_ipv4", "bann_port", "bann_svc_off", "bann_svc_len",
    )]


def _build(force: bool = False) -> str:
    if not force and os.path.exists(_SO) and (
        os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
    ):
        return _SO
    try:
        subprocess.run(
            ["g++", "-O3", "-Wall", "-shared", "-fPIC", "-std=c++17",
             "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120,
        )
    except (OSError, subprocess.SubprocessError) as e:
        raise NativeUnavailable(f"could not build native codec: {e}") from e
    return _SO


def _load() -> ctypes.CDLL:
    """Build (if stale) and dlopen, rebuilding once on a load failure —
    a stale or wrong-arch .so from a previous checkout must fall through
    to a fresh build, and a still-failing load must surface as
    NativeUnavailable so callers engage the pure-python fallback."""
    path = _build()
    try:
        return ctypes.CDLL(path)
    except OSError:
        path = _build(force=True)
        try:
            return ctypes.CDLL(path)
        except OSError as e:
            raise NativeUnavailable(
                f"could not load native codec: {e}"
            ) from e


def get_lib():
    global _lib
    with _lock:
        if _lib is None:
            lib = _load()
            lib.zk_parse_spans.restype = ctypes.c_int
            lib.zk_parse_spans.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(_SpanColumns),
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
            ]
            lib.zk_base64_decode.restype = ctypes.c_int64
            lib.zk_base64_decode.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p,
            ]
            lib.zk_group_strings.restype = ctypes.c_int32
            lib.zk_group_strings.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32,
            ]
            _lib = lib
    return _lib


def _group_strings(lib, payload: bytes, offs: np.ndarray, lens: np.ndarray):
    """Content-dedup of (off, len) slices via the C++ hash table.

    Returns (group_of [n] int32 with -1 for len<0 rows, reps: list of
    the unique byte strings in group order)."""
    n = len(offs)
    if n == 0:
        return np.zeros(0, np.int32), []
    offs = np.ascontiguousarray(offs, np.int64)
    lens = np.ascontiguousarray(lens, np.int32)
    group_of = np.empty(n, np.int32)
    rep_off = np.empty(n, np.int64)
    rep_len = np.empty(n, np.int32)
    ng = lib.zk_group_strings(
        payload,
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n,
        group_of.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        rep_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        rep_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n,
    )
    reps = [
        payload[int(rep_off[g]):int(rep_off[g]) + int(rep_len[g])]
        for g in range(ng)
    ]
    return group_of, reps


def available() -> bool:
    try:
        get_lib()
        return True
    except NativeUnavailable:
        return False


def base64_decode(data: bytes) -> bytes:
    lib = get_lib()
    out = ctypes.create_string_buffer((len(data) * 3) // 4 + 4)
    n = lib.zk_base64_decode(data, len(data), out)
    if n < 0:
        raise ValueError("bad base64 payload")
    return out.raw[:n]


_CORE_TS = {CLIENT_SEND: "ts_cs", CLIENT_RECV: "ts_cr",
            SERVER_RECV: "ts_sr", SERVER_SEND: "ts_ss"}


def indexable_from_batch(batch: SpanBatch, dicts: DictionarySet) -> np.ndarray:
    """Columnar should_index (store/base.py:51): exclude spans that are
    client-side and carry the literal service name "client"."""
    ns = batch.n_spans
    out = np.ones(ns, bool)
    client_svc = dicts.services.get("client")
    if client_svc is None or ns == 0:
        return out
    cs_id, cr_id = 0, 1  # CORE_ANNOTATION_IDS cs/cr
    is_core_client = np.isin(batch.ann_value_id, (cs_id, cr_id))
    has_client_side = np.zeros(ns, bool)
    np.logical_or.at(has_client_side, batch.ann_span_idx[is_core_client], True)
    svc_is_client = batch.ann_service_id == client_svc
    has_client_svc = np.zeros(ns, bool)
    np.logical_or.at(has_client_svc, batch.ann_span_idx[svc_is_client], True)
    out &= ~(has_client_side & has_client_svc)
    return out


class ParseCapacityError(ValueError):
    """Valid payload larger than the parse buffers — chunk and retry
    (distinct from malformed input so callers don't drop good data)."""


def parse_spans_columnar(
    payload: bytes, dicts: DictionarySet,
    max_spans: int = 1 << 16,
) -> Tuple[SpanBatch, np.ndarray]:
    """Thrift Span sequence → (SpanBatch, name_lc_id column).

    The numeric work happens in C++; this wrapper interns strings and
    assembles the SpanBatch. Raises NativeUnavailable when the shared
    object can't be built; ValueError on malformed input;
    ParseCapacityError when the payload exceeds the parse buffers.
    """
    batch, name_lc, _, _ = parse_spans_columnar_sampled(
        payload, dicts, 0, max_spans
    )
    return batch, name_lc


def parse_spans_columnar_sampled(
    payload: bytes, dicts: DictionarySet,
    sample_threshold: int, max_spans: int = 1 << 16,
) -> Tuple[SpanBatch, np.ndarray, int, int]:
    """parse_spans_columnar with the sampler's trace-id threshold test
    applied on the numeric columns BEFORE any string interning, so
    sampled-out traffic never pollutes the dictionaries (or pays intern
    cost). Debug-flagged spans always pass (SpanSamplerFilter.scala:40).

    Returns (batch, name_lc, n_dropped, n_kept_debug) where
    n_kept_debug counts kept spans carrying the debug flag (the slow
    path never runs those through the sampler's counters).
    """
    lib = get_lib()
    max_anns = max_spans * 8
    max_banns = max_spans * 8

    cols = {}

    def arr(name, n, dtype):
        a = np.zeros(n, dtype)
        cols[name] = a
        return a.ctypes.data_as(ctypes.c_void_p)

    sc = _SpanColumns(
        trace_id=arr("trace_id", max_spans, np.int64),
        span_id=arr("span_id", max_spans, np.int64),
        parent_id=arr("parent_id", max_spans, np.int64),
        has_parent=arr("has_parent", max_spans, np.uint8),
        debug=arr("debug", max_spans, np.uint8),
        name_off=arr("name_off", max_spans, np.int64),
        name_len=arr("name_len", max_spans, np.int32),
        ann_span_idx=arr("ann_span_idx", max_anns, np.int32),
        ann_ts=arr("ann_ts", max_anns, np.int64),
        ann_value_off=arr("ann_value_off", max_anns, np.int64),
        ann_value_len=arr("ann_value_len", max_anns, np.int32),
        ann_ipv4=arr("ann_ipv4", max_anns, np.int32),
        ann_port=arr("ann_port", max_anns, np.int32),
        ann_svc_off=arr("ann_svc_off", max_anns, np.int64),
        ann_svc_len=arr("ann_svc_len", max_anns, np.int32),
        bann_span_idx=arr("bann_span_idx", max_banns, np.int32),
        bann_key_off=arr("bann_key_off", max_banns, np.int64),
        bann_key_len=arr("bann_key_len", max_banns, np.int32),
        bann_value_off=arr("bann_value_off", max_banns, np.int64),
        bann_value_len=arr("bann_value_len", max_banns, np.int32),
        bann_type=arr("bann_type", max_banns, np.int32),
        bann_ipv4=arr("bann_ipv4", max_banns, np.int32),
        bann_port=arr("bann_port", max_banns, np.int32),
        bann_svc_off=arr("bann_svc_off", max_banns, np.int64),
        bann_svc_len=arr("bann_svc_len", max_banns, np.int32),
    )
    n_spans = ctypes.c_int32(0)
    n_anns = ctypes.c_int32(0)
    n_banns = ctypes.c_int32(0)
    rc = lib.zk_parse_spans(
        payload, len(payload), ctypes.byref(sc),
        max_spans, max_anns, max_banns,
        ctypes.byref(n_spans), ctypes.byref(n_anns), ctypes.byref(n_banns),
    )
    if rc == -1:
        raise ValueError("malformed thrift span payload")
    if rc in (-2, -3, -4):
        raise ParseCapacityError(
            "payload exceeds parse capacity; chunk the input"
        )
    ns, na, nb = n_spans.value, n_anns.value, n_banns.value

    # Sampler threshold test on the numeric columns, pre-intern.
    debug_col = cols["debug"][:ns] != 0
    if sample_threshold > 0 and ns:
        tids = cols["trace_id"][:ns]
        t = np.where(tids == np.int64(-(2**63)), np.int64(2**63 - 1),
                     np.abs(tids))
        keep = debug_col | (t > np.int64(sample_threshold))
    else:
        keep = np.ones(ns, bool)
    kept_idx = np.flatnonzero(keep)
    dropped = int(ns - kept_idx.size)
    kept_debug = int(np.count_nonzero(debug_col & keep))
    new_of_old = np.cumsum(keep) - 1  # old span index → new
    ka = (keep[cols["ann_span_idx"][:na]] if na
          else np.zeros(0, bool))
    kb = (keep[cols["bann_span_idx"][:nb]] if nb
          else np.zeros(0, bool))
    kns = kept_idx.size

    b = SpanBatch.empty(kns, int(np.count_nonzero(ka)),
                        int(np.count_nonzero(kb)))
    b.trace_id[:] = cols["trace_id"][:ns][keep]
    b.span_id[:] = cols["span_id"][:ns][keep]
    b.parent_id[:] = cols["parent_id"][:ns][keep]
    b.flags[:] = (
        cols["has_parent"][:ns][keep] * np.uint8(FLAG_HAS_PARENT)
        + cols["debug"][:ns][keep] * np.uint8(FLAG_DEBUG)
    )

    # From here on, work is per UNIQUE string (C++ content-dedup +
    # vectorized id lookup), not per row — annotation-heavy traffic
    # repeats the same few names/values millions of times, and the
    # per-row intern loop this replaces dominated the decode profile.
    I64_MAX = np.int64(2**63 - 1)
    I64_MIN = np.int64(-(2**63) + 1)

    # Span names: unique → intern once (original + lowercase).
    n_g, n_reps = _group_strings(
        lib, payload, cols["name_off"][:ns][keep],
        cols["name_len"][:ns][keep],
    )
    name_strs = [r.decode("utf-8", "replace") for r in n_reps]
    name_ids = np.array(
        [dicts.span_names.encode(s) for s in name_strs], np.int32
    ).reshape(-1)
    name_lc_ids_u = np.array(
        [-1 if s == "" else dicts.span_names.encode(s.lower())
         for s in name_strs], np.int32,
    ).reshape(-1)
    if kns:
        b.name_id[:] = name_ids[n_g]
        name_lc = name_lc_ids_u[n_g].copy()
    else:
        name_lc = np.empty(0, np.int32)

    def svc_and_endpoints(sel, off_col, len_col, ipv4_col, port_col, nrows):
        """Per-row (service_id, endpoint_id) columns for one annotation
        table. len == -2 means endpoint present but service_name absent
        (decodes as "unknown", wire/thrift.py _r_endpoint); len == -1
        means no endpoint."""
        offs = off_col[sel]
        lens = len_col[sel]
        s_g, s_reps = _group_strings(lib, payload, offs, lens)
        s_strs = [r.decode("utf-8", "replace") for r in s_reps]
        s_ids = np.array(
            [dicts.services.encode(s.lower()) for s in s_strs], np.int64
        ).reshape(-1)
        svc_col = np.full(nrows, NO_SERVICE, np.int64)
        named = s_g >= 0
        if named.any():
            svc_col[named] = s_ids[s_g[named]]
        unknown = lens == -2
        if unknown.any():
            svc_col[unknown] = dicts.services.encode("unknown")
        # Endpoint ids: unique (ipv4, port, service token) triples.
        ep_col = np.full(nrows, NO_ENDPOINT, np.int64)
        token = s_g.astype(np.int64)
        token[unknown] = -2
        present = (lens >= 0) | unknown

        def signed32(v: int) -> int:
            # Endpoint tuples key the dictionary with the SIGNED ipv4
            # (thrift i32), matching the python codec bit-for-bit.
            return v - (1 << 32) if v >= (1 << 31) else v

        def signed16(v: int) -> int:
            return v - (1 << 16) if v >= (1 << 15) else v

        if present.any():
            # One packed int64 key per row — np.unique(axis=0) sorts
            # void-dtype rows and dominates the profile; the 1-D unique
            # is an order of magnitude cheaper. token+2 >= 0 (< 2^15
            # unique services per payload by construction: group count
            # <= rows, and packed overflow falls back to the row path).
            tok = token[present] + 2
            ipv4 = ipv4_col[sel][present].astype(np.int64) & 0xFFFFFFFF
            port = port_col[sel][present].astype(np.int64) & 0xFFFF
            if int(tok.max(initial=0)) < (1 << 15):
                packed = (tok << 48) | (ipv4 << 16) | port
                uniq, inv = np.unique(packed, return_inverse=True)
                ep_ids = np.array([
                    dicts.endpoints.encode((
                        signed32(int((u >> 16) & 0xFFFFFFFF)),
                        signed16(int(u & 0xFFFF)),
                        "unknown" if (u >> 48) == 0
                        else s_strs[int(u >> 48) - 2],
                    ))
                    for u in uniq
                ], np.int64).reshape(-1)
            else:
                key = np.stack([ipv4, port, tok], axis=1)
                uniq, inv = np.unique(key, axis=0, return_inverse=True)
                ep_ids = np.array([
                    dicts.endpoints.encode((
                        signed32(int(u[0])), signed16(int(u[1])),
                        "unknown" if u[2] == 0 else s_strs[int(u[2]) - 2],
                    ))
                    for u in uniq
                ], np.int64).reshape(-1)
            ep_col[present] = ep_ids[inv]
        return svc_col, ep_col, present

    # Annotations.
    a_span = new_of_old[cols["ann_span_idx"][:na]][ka].astype(np.int32)
    a_ts = cols["ann_ts"][:na][ka]
    kna = a_span.size
    v_g, v_reps = _group_strings(
        lib, payload, cols["ann_value_off"][:na][ka],
        cols["ann_value_len"][:na][ka],
    )
    v_strs = [r.decode("utf-8", "replace") for r in v_reps]
    v_ids = np.array(
        [dicts.annotations.encode(s) for s in v_strs], np.int32
    ).reshape(-1)
    group_of_value = {s: g for g, s in enumerate(v_strs)}
    if kna:
        b.ann_span_idx[:] = a_span
        b.ann_ts[:] = a_ts
        b.ann_value_id[:] = v_ids[v_g]
        svc_col, ep_col, ep_present = svc_and_endpoints(
            ka, cols["ann_svc_off"][:na], cols["ann_svc_len"][:na],
            cols["ann_ipv4"][:na], cols["ann_port"][:na], kna,
        )
        b.ann_service_id[:] = svc_col.astype(np.int32)
        b.ann_endpoint_id[:] = ep_col.astype(np.int32)

        # Core-ts columns: duplicate indices in fancy assignment keep
        # the LAST occurrence — same as the sequential loop's overwrite.
        for value_str, core_col in _CORE_TS.items():
            g = group_of_value.get(value_str)
            if g is not None:
                m = v_g == g
                getattr(b, core_col)[a_span[m]] = a_ts[m]
        firsts = np.full(kns, I64_MAX, np.int64)
        lasts = np.full(kns, I64_MIN, np.int64)
        np.minimum.at(firsts, a_span, a_ts)
        np.maximum.at(lasts, a_span, a_ts)
        touched = firsts != I64_MAX
        b.ts_first[touched] = firsts[touched]
        b.ts_last[touched] = lasts[touched]

        # Owning service (server-preferred, first occurrence wins —
        # assign in reverse so the first write lands last).
        def first_wins(kind_groups):
            out = np.full(kns, NO_SERVICE, np.int64)
            m = np.isin(v_g, kind_groups) & ep_present
            out[a_span[m][::-1]] = svc_col[m][::-1]
            return out

        server_svc = first_wins([
            g for s, g in group_of_value.items()
            if s in (SERVER_RECV, SERVER_SEND)
        ])
        client_svc = first_wins([
            g for s, g in group_of_value.items()
            if s in (CLIENT_SEND, CLIENT_RECV)
        ])
    else:
        server_svc = np.full(kns, NO_SERVICE, np.int64)
        client_svc = np.full(kns, NO_SERVICE, np.int64)

    has_ts = b.ts_first != NO_TS
    b.duration[has_ts] = b.ts_last[has_ts] - b.ts_first[has_ts]
    b.service_id[:] = np.where(
        server_svc >= 0, server_svc,
        np.where(client_svc >= 0, client_svc, NO_SERVICE),
    ).astype(np.int32)

    # Binary annotations.
    from zipkin_tpu.models.span import AnnotationType
    from zipkin_tpu.wire.thrift import _decode_binary_value

    knb = int(np.count_nonzero(kb))
    if knb:
        b.bann_span_idx[:] = (
            new_of_old[cols["bann_span_idx"][:nb]][kb].astype(np.int32)
        )
        k_g, k_reps = _group_strings(
            lib, payload, cols["bann_key_off"][:nb][kb],
            cols["bann_key_len"][:nb][kb],
        )
        k_ids = np.array(
            [dicts.binary_keys.encode(r.decode("utf-8", "replace"))
             for r in k_reps], np.int32,
        ).reshape(-1)
        b.bann_key_id[:] = k_ids[k_g]
        btype = cols["bann_type"][:nb][kb]
        btype = np.where((btype >= 0) & (btype <= 6), btype, 1)
        b.bann_type[:] = btype.astype(np.uint8)
        # Values decode per unique (bytes, type) pair.
        bv_g, bv_reps = _group_strings(
            lib, payload, cols["bann_value_off"][:nb][kb],
            cols["bann_value_len"][:nb][kb],
        )
        packed = bv_g.astype(np.int64) * 8 + btype.astype(np.int64)
        uniq, inv = np.unique(packed, return_inverse=True)
        pair_ids = np.empty(len(uniq), np.int64)
        for u_i, u in enumerate(uniq):
            value = _decode_binary_value(
                bv_reps[int(u) // 8], AnnotationType(int(u) % 8)
            )
            if isinstance(value, bytearray):
                value = bytes(value)
            pair_ids[u_i] = dicts.binary_values.encode(value)
        b.bann_value_id[:] = pair_ids[inv]
        svc_col, ep_col, _ = svc_and_endpoints(
            kb, cols["bann_svc_off"][:nb], cols["bann_svc_len"][:nb],
            cols["bann_ipv4"][:nb], cols["bann_port"][:nb], knb,
        )
        b.bann_service_id[:] = svc_col.astype(np.int32)
        b.bann_endpoint_id[:] = ep_col.astype(np.int32)
    return b, name_lc, dropped, kept_debug
