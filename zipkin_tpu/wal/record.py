"""WAL unit records: the stage-1 encoded launch group ↔ bytes.

What gets journaled is the ``TpuSpanStore._plan_units`` output — one
launch unit's chunker parts, each a (SpanBatch, name_lc, indexable)
triple — BEFORE the donating commit. Journaling at this point (post
encode, pre pad) is what makes replay deterministic: the columns
already carry final dictionary ids, and replay re-pads through the
same ``_pad_unit`` body, so a replayed drive cuts bitwise-identical
launches (the PR-4 serial==pipelined property extended across a
restart).

Because the columns are dictionary ids, each record also carries the
DICTIONARY DELTA its encode step appended — the entries between the
previous record's high-water sizes and this one's. Dictionaries are
append-only and encode order equals journal order (both happen under
the store's encode lock), so replaying deltas in sequence rebuilds the
exact id assignment; a record whose "before" sizes don't match the
replay-time dictionaries is a checkpoint/log mismatch and fails fast
(``WalReplayError``) instead of decoding garbage.

Payload layout (inside the log's CRC frame):

    u32 meta_len | meta json | column blobs back-to-back

where meta lists, per part, each column's (name, dtype, length) in a
fixed order (SpanBatch columns + name_lc + indexable) and the blobs
follow in exactly that order — no per-column framing needed.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Sequence, Tuple

import numpy as np

from zipkin_tpu.columnar.schema import SpanBatch

_LEN = struct.Struct(">I")

# Fixed column order per part; the two host-side sidecars ride last.
_PART_COLS: Tuple[str, ...] = (
    SpanBatch.SPAN_COLUMNS + SpanBatch.ANN_COLUMNS
    + SpanBatch.BANN_COLUMNS
)
_EXTRA_COLS: Tuple[str, ...] = ("name_lc", "indexable")

# Dictionary order is part of the record format (sizes/deltas are
# positional) — it matches checkpoint.save's meta["dicts"] order.
DICT_NAMES: Tuple[str, ...] = (
    "services", "span_names", "annotations", "binary_keys",
    "binary_values", "endpoints",
)


class WalReplayError(RuntimeError):
    """A WAL record is inconsistent with the state being replayed into
    (dictionary high-water mismatch, unknown record version): the log
    and the checkpoint are not from the same lineage. Recovery fails
    fast rather than committing misencoded batches."""


def dict_sizes(dicts) -> List[int]:
    return [len(getattr(dicts, name)) for name in DICT_NAMES]


def dump_value(v) -> dict:
    """Tagged JSON form of one dictionary entry — the ONE codec shared
    by WAL records and checkpoint manifests (checkpoint._dict_dump
    delegates here). apply_dict_deltas equality-verifies restored
    checkpoint entries against WAL-delta values, so the two surfaces
    must stay byte-compatible forever; sharing the codec makes drift
    impossible."""
    if isinstance(v, bytes):
        return {"b": v.hex()}
    if isinstance(v, tuple):
        return {"t": list(v)}
    if v is None:
        return {"n": None}
    return {"s": v}


def load_value(item: dict):
    """Inverse of dump_value."""
    if "b" in item:
        return bytes.fromhex(item["b"])
    if "t" in item:
        return tuple(item["t"])
    if "n" in item:
        return None
    return item["s"]


def dump_dict_deltas(dicts, before: Sequence[int]
                     ) -> Tuple[List[int], Dict[str, list]]:
    """(current sizes, per-dictionary entry dumps for [before, now)).
    Caller holds the store's encode lock, so the sizes are stable."""
    sizes = dict_sizes(dicts)
    deltas: Dict[str, list] = {}
    for i, name in enumerate(DICT_NAMES):
        if sizes[i] > before[i]:
            d = getattr(dicts, name)
            values = d.values()
            deltas[name] = [
                dump_value(v) for v in values[before[i]:sizes[i]]
            ]
    return sizes, deltas


def apply_dict_deltas(dicts, before: Sequence[int],
                      deltas: Dict[str, list]) -> None:
    """Replay one record's dictionary delta. Entries already present
    (the checkpoint's dictionary snapshot can run ahead of its applied
    sequence — it is cut later, under the host lock) are VERIFIED
    rather than re-encoded; a mismatch is a lineage error."""
    for i, name in enumerate(DICT_NAMES):
        d = getattr(dicts, name)
        have = len(d)
        if have < before[i]:
            raise WalReplayError(
                f"dictionary '{name}' has {have} entries but the WAL "
                f"record was encoded against {before[i]} — the log "
                f"does not belong to this checkpoint lineage")
        for j, item in enumerate(deltas.get(name, ())):
            pos = before[i] + j
            value = load_value(item)
            if pos < have:
                existing = d.decode(pos + d._first_id)
                if existing != value:
                    raise WalReplayError(
                        f"dictionary '{name}' entry {pos} is "
                        f"{existing!r} but the WAL record appended "
                        f"{value!r} — checkpoint/log lineage mismatch")
                continue
            got = d.encode(value)
            if got != pos + d._first_id:
                raise WalReplayError(
                    f"dictionary '{name}' assigned id {got} replaying "
                    f"entry {pos} — out-of-order replay or lineage "
                    f"mismatch")


def encode_unit(group, before: Sequence[int],
                deltas: Dict[str, list],
                extra: Dict[str, object] = None) -> bytes:
    """One launch group (list of (SpanBatch, name_lc, indexable)) plus
    its dictionary delta → record payload bytes.

    ``extra`` merges additional meta keys into the record header —
    lineage keys the fleet-observability layer stamps (``ts``: commit
    timestamp µs, ``b3``: [trace_id, span_id] of the sampled launch
    unit's self-trace). ``decode_unit`` ignores unknown keys, so
    stamped and unstamped records replay identically; the keys ride
    the shipped payload to followers, who read them via
    ``unit_meta``."""
    parts_meta = []
    blobs: List[bytes] = []
    for batch, name_lc, indexable in group:
        cols = []
        for col in _PART_COLS:
            arr = np.ascontiguousarray(getattr(batch, col))
            cols.append([col, arr.dtype.str, int(arr.shape[0])])
            blobs.append(arr.tobytes())
        for col, arr in zip(_EXTRA_COLS, (name_lc, indexable)):
            arr = np.ascontiguousarray(arr)
            cols.append([col, arr.dtype.str, int(arr.shape[0])])
            blobs.append(arr.tobytes())
        parts_meta.append(cols)
    head = {"v": 1, "before": list(map(int, before)), "deltas": deltas,
            "parts": parts_meta}
    if extra:
        for k in ("v", "before", "deltas", "parts"):
            if k in extra:
                raise ValueError(f"extra meta key {k!r} shadows the "
                                 f"record header")
        head.update(extra)
    meta = json.dumps(head, separators=(",", ":")).encode("utf-8")
    return _LEN.pack(len(meta)) + meta + b"".join(blobs)


def unit_meta(payload: bytes) -> Dict[str, object]:
    """Record payload → its json meta header alone (no column blobs
    decoded). Followers use this to read the lineage keys (``ts``,
    ``b3``) off a shipped record without paying a second columnar
    decode."""
    (mlen,) = _LEN.unpack_from(payload, 0)
    return json.loads(payload[_LEN.size:_LEN.size + mlen]
                      .decode("utf-8"))


def decode_unit(payload: bytes):
    """Record payload → (group, before_sizes, deltas); the inverse of
    ``encode_unit``. Raises WalReplayError on an unknown version (the
    frame CRC already vouches for the bytes themselves)."""
    (mlen,) = _LEN.unpack_from(payload, 0)
    meta = json.loads(payload[_LEN.size:_LEN.size + mlen]
                      .decode("utf-8"))
    if meta.get("v") != 1:
        raise WalReplayError(
            f"unknown WAL record version {meta.get('v')!r}")
    off = _LEN.size + mlen
    group = []
    for cols in meta["parts"]:
        arrays = {}
        for col, dtype, length in cols:
            dt = np.dtype(dtype)
            nbytes = dt.itemsize * length
            arrays[col] = np.frombuffer(
                payload, dtype=dt, count=length, offset=off
            ).copy()
            off += nbytes
        name_lc = arrays.pop("name_lc")
        indexable = arrays.pop("indexable")
        group.append((SpanBatch(**arrays), name_lc, indexable))
    return group, meta["before"], meta["deltas"]
