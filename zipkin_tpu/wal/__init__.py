"""Durable write-ahead log + crash recovery (docs/DURABILITY.md).

The commit-log role the reference delegates to Cassandra, made
explicit for a store whose truth lives in volatile HBM:

- ``WriteAheadLog`` (wal/log.py) — segmented, CRC-framed, optionally
  deflated append log with per-batch / group-commit / off fsync
  policies and checkpoint-coordinated truncation;
- ``wal/record.py`` — the unit record codec: stage-1 encoded launch
  groups plus their dictionary deltas, so replay re-cuts bitwise
  identical launches;
- ``wal/recovery.py`` — checkpoint restore + deterministic tail
  replay through the store's normal commit body.

Ack contract: with a WAL attached, ``TpuSpanStore.apply`` /
``write_thrift`` return only after the batch's launch units are
APPENDED; receivers that promise durability (scribe OK, kafka offset
commits) additionally wait on the durable frontier
(``Collector.ingest_durable`` / ``WriteAheadLog.wait_durable``).
"""

from zipkin_tpu.wal.log import (
    FsyncPolicy,
    WalDurabilityError,
    WriteAheadLog,
)
from zipkin_tpu.wal.record import WalReplayError
from zipkin_tpu.wal.recovery import (
    apply_record_into,
    recover,
    replay_into,
    replay_sharded_into,
)
from zipkin_tpu.wal.sharded import ShardedWal

__all__ = [
    "FsyncPolicy",
    "WalDurabilityError",
    "WriteAheadLog",
    "WalReplayError",
    "ShardedWal",
    "apply_record_into",
    "recover",
    "replay_into",
    "replay_sharded_into",
]
