"""Sharded write-ahead log: per-shard segment logs + a group-commit
epoch log (the durability tier under parallel/shard.ShardedSpanStore).

One launch unit on an n-shard mesh carries one encoded part PER SHARD
(every shard's rings advance in the same fused launch), so its journal
entry must cover all n parts atomically — replaying some shards' parts
without the others would desynchronize the fleet. Layout:

    <dir>/shard-000/wal-*.seg   part 0 of every unit (record codec,
    <dir>/shard-001/wal-*.seg   empty dictionary deltas)
    ...
    <dir>/epoch/wal-*.seg       the GROUP-COMMIT record: a part-less
                                unit record carrying the dictionary
                                delta the unit's encode step appended

Every member log shares one sequence numbering: epoch N's record in
the epoch log and part record N in each shard log describe the same
launch unit. ``append_unit`` appends the n shard records FIRST, the
epoch record LAST — under the 'batch' fsync policy that makes the
epoch record a true group commit (it cannot be durable before the
parts it spans); under 'interval'/'off' the member logs drift within
their fsync windows and open-time ALIGNMENT restores lockstep: every
log is physically cut (``WriteAheadLog.cut_tail``) back to the
shortest member's frontier, i.e. the longest prefix of COMPLETE
epochs. A unit is committed iff its epoch survives alignment; partial
groups are cut in full, never partially applied — the same
prefix-or-nothing shape the single log's torn-tail scan guarantees.

Replay (``replay_units``) zips the epoch log with the shard logs:
apply the epoch's dictionary delta, rebuild the n-part group, drive it
through ``ShardedSpanStore._build_unit``/``_commit_unit`` — the exact
stage-1/stage-3 bodies live ingest uses — so an 8-shard recovery lands
a bitwise-identical fleet state (wal/recovery.replay_sharded_into).

Shard logs register their metrics on a PRIVATE registry (n twins of
every zipkin_wal_* family would collide on the default registry); the
epoch log's metrics land on the real registry and read as the fleet's
group-commit observables.
"""

from __future__ import annotations

import os
import threading
from typing import Iterator, List, Optional, Tuple

from zipkin_tpu.wal.log import FsyncPolicy, WriteAheadLog
from zipkin_tpu.wal.record import decode_unit, encode_unit


class ShardedWal:
    """See the module docstring. Thread-safe; one instance owns one
    directory tree. The surface mirrors WriteAheadLog where the
    checkpoint/recovery layers touch it (truncate/sync/close/stats,
    torn_records_cut, c_replayed) and adds the unit-level
    append_unit/replay_units pair the sharded store journals through."""

    def __init__(self, directory: str, n_shards: int,
                 fsync: str = FsyncPolicy.INTERVAL,
                 interval_s: float = 0.05,
                 segment_bytes: int = 64 << 20,
                 compress: bool = True,
                 registry=None):
        from zipkin_tpu import obs

        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1; got {n_shards}")
        self.directory = os.path.abspath(directory)
        self.n_shards = int(n_shards)
        # Keeps member appends lockstep (one unit's n+1 records carry
        # one sequence number) across concurrent append/truncate.
        # Held ABOVE the member logs' own conditions (rank 60).
        self._lock = threading.Lock()  # lock-order: 58 wal-group
        # Shard logs meter on a private registry: n copies of every
        # zipkin_wal_* family would fight over one name on the default
        # registry (replace-on-reregister would leave only the last
        # shard visible). The epoch log IS the fleet's group-commit
        # observable, so it meters for real.
        self._shard_registry = obs.Registry()
        self.shards: List[WriteAheadLog] = [
            WriteAheadLog(
                os.path.join(self.directory, f"shard-{i:03d}"),
                fsync=fsync, interval_s=interval_s,
                segment_bytes=segment_bytes, compress=compress,
                registry=self._shard_registry,
            )
            for i in range(self.n_shards)
        ]
        self.epoch = WriteAheadLog(
            os.path.join(self.directory, "epoch"),
            fsync=fsync, interval_s=interval_s,
            segment_bytes=segment_bytes, compress=compress,
            registry=registry)
        # Open-time alignment: cut every member back to the shortest
        # frontier — the longest prefix of COMPLETE epochs (a crash
        # between member appends/fsyncs leaves the logs ragged).
        logs = self.shards + [self.epoch]
        upto = min(log.last_seq for log in logs)
        self.aligned_records_cut = sum(
            log.cut_tail(upto) for log in logs)
        # c_replayed rides the epoch log (recovery bumps it per unit).
        self.c_replayed = self.epoch.c_replayed

    # -- frontier / loss accounting ---------------------------------------

    @property
    def last_seq(self) -> int:
        return self.epoch.last_seq

    @property
    def durable_seq(self) -> int:
        """Highest epoch durable across EVERY member — the group-commit
        ack frontier (an epoch whose parts are not all durable is not
        a durable unit)."""
        return min(log.durable_seq
                   for log in self.shards + [self.epoch])

    @property
    def torn_records_cut(self) -> int:
        """Units lost to torn tails or group alignment, fleet-wide
        (the recovery stats' data-loss signal)."""
        return sum(log.torn_records_cut
                   for log in self.shards + [self.epoch])

    # -- append path ------------------------------------------------------

    def append_unit(self, parts, before, deltas) -> int:
        """Journal one launch unit: ``parts`` is one
        (SpanBatch, name_lc, indexable) triple per shard in shard
        order; ``before``/``deltas`` are the unit's dictionary marks
        (wal/record.dump_dict_deltas). Returns the epoch sequence.
        Shard records append before the epoch record — the group's
        commit point."""
        if len(parts) != self.n_shards:
            raise ValueError(
                f"unit has {len(parts)} parts for a {self.n_shards}"
                f"-shard log")
        with self._lock:
            seqs = [
                log.append(encode_unit([part], before, {}))
                for log, part in zip(self.shards, parts)
            ]
            seq = self.epoch.append(encode_unit([], before, deltas))
            if any(s != seq for s in seqs):
                raise RuntimeError(
                    f"sharded WAL lost lockstep: shard seqs {seqs} vs "
                    f"epoch seq {seq}")
            return seq

    def wait_durable(self, seq: int,
                     timeout: Optional[float] = 30.0) -> bool:
        """Group-commit ack barrier: epoch ``seq`` and all its parts
        durable on every member."""
        return all(log.wait_durable(seq, timeout)
                   for log in self.shards + [self.epoch])

    def sync(self) -> None:
        """Force everything appended durable — parts first, then the
        epochs that span them."""
        for log in self.shards:
            log.sync()
        self.epoch.sync()

    # -- replay -----------------------------------------------------------

    def replay_units(self, from_seq: int = 0
                     ) -> Iterator[Tuple[int, list, list, dict]]:
        """Yield (seq, parts, before_sizes, deltas) for every COMPLETE
        epoch past ``from_seq``. Open-time alignment already cut the
        members to a common frontier, so a shard iterator running out
        mid-replay means post-open rot — stop at the last complete
        prefix (the single log's prefix semantics, fleet-wide)."""
        shard_iters = [log.replay(from_seq) for log in self.shards]
        for seq, payload in self.epoch.replay(from_seq):
            parts = []
            for it in shard_iters:
                got = next(it, None)
                if got is None or got[0] != seq:
                    return
                group, _before, _deltas = decode_unit(got[1])
                parts.append(group[0])
            _group, before, deltas = decode_unit(payload)
            yield seq, parts, before, deltas

    # -- truncation / lifecycle -------------------------------------------

    def truncate(self, upto_seq: int) -> int:
        """Checkpoint-covered truncation on every member; returns
        segment files deleted fleet-wide (the checkpoint.save stat)."""
        with self._lock:
            return sum(log.truncate(upto_seq)
                       for log in self.shards + [self.epoch])

    def close(self) -> None:
        for log in self.shards:
            log.close()
        self.epoch.close()

    def stats(self) -> dict:
        out = {f"shard{i}_{k}": v
               for i, log in enumerate(self.shards)
               for k, v in log.stats().items()}
        out.update(self.epoch.stats())
        out["wal_shards"] = self.n_shards
        out["wal_aligned_records_cut"] = self.aligned_records_cut
        return out
