"""Crash recovery: checkpoint restore + deterministic WAL tail replay.

Recovery is re-execution: restore the newest checkpoint (manifest
revision 13 carries the last-applied WAL sequence plus the host pacing
clocks), then drive every WAL record past that sequence through the
store's NORMAL commit body — ``_commit_unit``, the same one the serial
writer and the ingest pipeline's commit thread run — so eviction
capture, cold-tier sealing, the sweep cadence, and the
dependency-bucket rotation all re-fire exactly as they did before the
crash. Because records are the pre-pad launch groups (wal/record.py)
and the pacing clocks restore exactly, a recovered store is bitwise
identical to one that never crashed, for every durably appended batch;
batches whose append never reached the log (or sat past a torn tail)
are absent in full — never partially applied.

The DrJAX restartable-stage discipline (arXiv:2403.07128) is the same
move: stages that cut identical launch units from identical inputs can
be re-executed from a journal instead of having their outputs
persisted.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple

import numpy as np

from zipkin_tpu.columnar.encode import to_signed64
from zipkin_tpu.wal.record import (
    WalReplayError,
    apply_dict_deltas,
    decode_unit,
    dict_sizes,
)


def pin_tids_of(hot) -> Optional[np.ndarray]:
    """Pinned trace ids as an int64 array (None when the bank is
    empty) — snapshot once per replay/ship session, like live ingest's
    write_thrift pin path."""
    return (np.fromiter(hot.pins.tids(), np.int64,
                        len(hot.pins.tids()))
            if hot.pins else None)


def apply_record_into(hot, seq: int, payload: bytes,
                      pin_tids: Optional[np.ndarray] = None) -> int:
    """Drive ONE journaled record through the store's normal commit
    body (``_commit_unit``) — the single replay step shared by crash
    recovery and the warm-standby follower (replicate/follow), so a
    standby replays bit-for-bit the way a recovering primary does.
    Returns the unit's span count."""
    group, before, deltas = decode_unit(payload)
    apply_dict_deltas(hot.dicts, before, deltas)
    # wal_seq threads into the pad so a paged store's planner can
    # serve RECORDED page claims for sequences the checkpoint already
    # planned (pipelined-save window) instead of re-planning them —
    # the replay-equals-original bitwise contract for page layouts.
    unit = hot._pad_unit(group, wal_seq=seq)._replace(wal_seq=seq)
    with hot._lock:
        for batch, _lc, _ix in group:
            for tid in np.unique(batch.trace_id):
                hot.ttls.setdefault(int(tid), 1.0)
            if pin_tids is not None and len(pin_tids):
                keep = np.isin(batch.trace_id, pin_tids)
                if keep.any():
                    pinned = hot._select_batch(batch, keep)
                    hot._bump_read_epoch()
                    hot.pins.note_write(
                        to_signed64, hot.codec.decode(pinned))
        hot._prune_ttls()
        hot._commit_unit(unit)
    return unit.n_spans


def replay_sharded_into(store, wal,
                        from_seq: Optional[int] = None) -> dict:
    """Sharded twin of ``replay_into``: drive every COMPLETE epoch of
    a ShardedWal past ``from_seq`` through the sharded store's normal
    stage-1/stage-3 bodies (``_build_unit`` → ``stage_unit`` →
    ``_commit_unit``), so an n-shard recovery re-cuts bitwise-identical
    fused launches — every shard's rings, sketch-mirror twin, and the
    fleet frontier land exactly where an uncrashed fleet's would."""
    from zipkin_tpu.store.tpu import TpuSpanStore

    if from_seq is None:
        from_seq = int(getattr(store, "_wal_applied", 0))
    t0 = time.perf_counter()
    n_records = 0
    n_spans = 0
    pin_tids = pin_tids_of(store)
    for seq, parts, before, deltas in wal.replay_units(from_seq):
        apply_dict_deltas(store.dicts, before, deltas)
        with store._lock:
            unit = store._build_unit(parts)._replace(wal_seq=seq)
            for batch, _lc, _ix in parts:
                for tid in np.unique(batch.trace_id):
                    store.ttls.setdefault(int(tid), 1.0)
                if pin_tids is not None and len(pin_tids):
                    keep = np.isin(batch.trace_id, pin_tids)
                    if keep.any():
                        pinned = TpuSpanStore._select_batch(batch, keep)
                        store._bump_read_epoch()
                        store.pins.note_write(
                            to_signed64, store.codec.decode(pinned))
            from zipkin_tpu.store.base import prune_ttls

            prune_ttls(store.ttls, TpuSpanStore.MAX_TTL_ENTRIES)
            unit = unit._replace(db=store.stage_unit(unit.db))
            store._commit_unit(unit)
        n_spans += unit.n_spans
        wal.c_replayed.inc()
        n_records += 1
    with store._lock:
        store._wal_marks = dict_sizes(store.dicts)
    return {
        "replayed_records": n_records,
        "replayed_spans": n_spans,
        "replay_s": round(time.perf_counter() - t0, 3),
        "applied_seq": int(store._wal_applied),
        "torn_records_cut": int(wal.torn_records_cut),
    }


def replay_into(store, wal, from_seq: Optional[int] = None) -> dict:
    """Replay every WAL record with seq > ``from_seq`` (default: the
    store's restored applied frontier) through the normal ingest path.
    Accepts a TpuSpanStore or a TieredSpanStore (replay routes through
    the hot store; an attached eviction sink captures and seals
    exactly as live ingest would), or a ShardedSpanStore paired with a
    ShardedWal (dispatched to ``replay_sharded_into``). Returns replay
    stats."""
    if hasattr(wal, "replay_units"):
        return replay_sharded_into(store, wal, from_seq)
    hot = getattr(store, "hot", store)
    if from_seq is None:
        from_seq = int(getattr(hot, "_wal_applied", 0))
    t0 = time.perf_counter()
    n_records = 0
    n_spans = 0
    # Pinned traces restored from the checkpoint keep banking their
    # post-checkpoint arrivals during replay, exactly as live ingest
    # would (write_thrift's columnar pin path) — otherwise replayed
    # spans of a pinned trace would live only in the volatile ring and
    # vanish once it laps.
    pin_tids = pin_tids_of(hot)
    for seq, payload in wal.replay(from_seq):
        n_spans += apply_record_into(hot, seq, payload, pin_tids)
        wal.c_replayed.inc()
        n_records += 1
    # Future appends journal deltas from the replayed high-water marks.
    with hot._lock:
        hot._wal_marks = dict_sizes(hot.dicts)
    return {
        "replayed_records": n_records,
        "replayed_spans": n_spans,
        "replay_s": round(time.perf_counter() - t0, 3),
        "applied_seq": int(hot._wal_applied),
        "torn_records_cut": int(wal.torn_records_cut),
    }


def recover(checkpoint_dir: Optional[str], wal,
            fresh_store: Optional[Callable[[], object]] = None,
            mesh=None) -> Tuple[object, dict]:
    """Full recovery: restore the newest checkpoint under
    ``checkpoint_dir`` (falling back to ``.old``, exactly like
    checkpoint.load), or build a fresh store via ``fresh_store`` when
    no checkpoint exists yet, then attach ``wal`` and replay its tail.
    Returns (store, stats). The returned store is ready for live
    ingest: appends continue after the last replayed sequence and
    journal dictionary deltas from the replayed high-water marks."""
    from zipkin_tpu import checkpoint

    store = None
    if checkpoint.exists(checkpoint_dir):
        store = checkpoint.load(checkpoint_dir, mesh=mesh)
    elif fresh_store is not None:
        store = fresh_store()
    else:
        raise WalReplayError(
            f"no checkpoint at {checkpoint_dir!r} and no fresh_store "
            f"factory to build an empty store for WAL replay")
    hot = getattr(store, "hot", store)
    if not hasattr(hot, "attach_wal"):
        raise WalReplayError(
            "recovered store does not support a write-ahead log "
            "(TpuSpanStore/TieredSpanStore, or ShardedSpanStore with "
            "a ShardedWal)")
    hot.attach_wal(wal)
    stats = replay_into(store, wal)
    return store, stats
