"""Segmented, CRC-framed write-ahead log (host side).

The reference Zipkin inherits durability from Cassandra's commit log
(every SnappyCodec'd write lands in the commit log before the memtable
acks); this store's production state is volatile device HBM, so the
commit-log role must be explicit. ``WriteAheadLog`` is that role: an
append-only sequence of CRC32-framed records across size-bounded
segment files, with a configurable fsync policy and a durable-sequence
frontier receivers ack against (docs/DURABILITY.md).

Format. A segment file is

    b"ZWAL1" | u32 header_len | header json {"version", "base_seq"}
    record*  where record = u32 payload_len | u8 flags | u32 crc32
                            | payload

``flags & FLAG_DEFLATE`` marks a raw-zlib-compressed payload (level 1,
the checkpoint's tradeoff); the CRC covers the stored (possibly
compressed) bytes, so a scan never pays decompression to validate.
Sequence numbers are implicit — ``base_seq`` plus the record's index —
which keeps the frame 9 bytes and makes "the log is a prefix" the only
shape a valid log can have. Sequence 0 is reserved for "nothing
applied"; the first record is seq 1.

Torn tails. A crash mid-append leaves a short or CRC-bad final record;
``open`` scans every segment and CUTS the log at the last valid prefix
(physically truncating the torn segment and deleting anything after
it), so replay and subsequent appends always see a clean prefix. A
CRC-corrupt record in the MIDDLE of the log gets the same treatment —
prefix semantics, never skip-and-continue (a skipped record would
desynchronize the dictionary deltas every later record builds on).

Fsync policy (``fsync=``):

- ``"batch"``    — fsync inside every append; ``append`` returning
  means durable (lowest loss window, highest per-batch latency).
- ``"interval"`` — group commit: appends buffer in the OS, a
  background thread fsyncs every ``interval_s``; ackers block in
  ``wait_durable`` until the group commit covering their record lands
  (the default: amortizes one fsync over every record in the window).
- ``"off"``      — never fsync; the durable frontier tracks the append
  frontier (OS-crash loss window, process-crash safe — the bytes are
  in the page cache). Measurably reproduces no-WAL throughput.

Truncation. ``truncate(upto_seq)`` deletes whole segments whose
records are all covered by a checkpoint (checkpoint.save calls it with
the manifest's applied sequence once the snapshot is durably in
place); the active segment is rolled first when fully covered, so
steady-state disk is one checkpoint plus the post-checkpoint tail.

Shipping-aware retention (docs/REPLICATION.md). A log being shipped to
followers must not truncate records a registered follower has not yet
fetched: ``register_cursor(name, seq)`` pins the truncation frontier
at the minimum registered cursor (``advance_cursor`` moves it as the
follower acks, ``drop_cursor`` releases it), and ``retain_bytes``
keeps at least that many newest bytes of checkpoint-covered tail on
disk regardless — so a follower that reconnects shortly after a
checkpoint can still catch up from the log instead of needing an
anchor bootstrap. An un-pinned log with retain_bytes=0 truncates
exactly as before.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from typing import Iterator, List, Optional, Tuple

_MAGIC = b"ZWAL1"
_HDR = struct.Struct(">I")
_REC = struct.Struct(">IBI")  # payload_len, flags, crc32
FLAG_DEFLATE = 0x01
# Payloads below this don't deflate (header overhead dominates).
_COMPRESS_MIN = 512
# Frame sanity bound: a length word past this is torn garbage, not a
# record (also bounds a corrupt length from allocating the read).
_MAX_RECORD = 1 << 31


class FsyncPolicy:
    BATCH = "batch"
    INTERVAL = "interval"
    OFF = "off"
    ALL = (BATCH, INTERVAL, OFF)


class WalDurabilityError(RuntimeError):
    """The durable-append barrier cannot be satisfied right now: the
    group-commit fsync is failing, the durability wait timed out, or a
    failed append could not be rolled back to a clean prefix. Callers
    on the ack path MUST NOT ack — receivers map this to scribe
    TRY_LATER (backpressure, the client retries)."""


class _Segment:
    """Host bookkeeping for one segment file."""

    __slots__ = ("path", "base_seq", "n_records", "nbytes")

    def __init__(self, path: str, base_seq: int, n_records: int,
                 nbytes: int):
        self.path = path
        self.base_seq = base_seq
        self.n_records = n_records
        self.nbytes = nbytes

    @property
    def last_seq(self) -> int:
        return self.base_seq + self.n_records - 1


def _segment_path(directory: str, base_seq: int) -> str:
    return os.path.join(directory, f"wal-{base_seq:016d}.seg")


def _fsync_dir(directory: str) -> None:
    """Fsync the directory entry itself: file-data fsync does not
    cover the dirent, so a power/OS crash after a segment create (or
    delete) could otherwise resurface a pre-roll directory — a created
    segment vanishing loses acked records, a deleted one resurrecting
    breaks the base_seq chain and cuts the valid tail at open. Best
    effort on filesystems that reject directory fsync (EINVAL)."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_header(f, base_seq: int) -> int:
    header = json.dumps({"version": 1, "base_seq": base_seq},
                        separators=(",", ":")).encode("utf-8")
    f.write(_MAGIC + _HDR.pack(len(header)) + header)
    return len(_MAGIC) + _HDR.size + len(header)


def _read_header(f) -> Optional[Tuple[int, int]]:
    """(base_seq, header_end_offset) or None for an unreadable header
    (treated as an empty/garbage segment)."""
    head = f.read(len(_MAGIC) + _HDR.size)
    if len(head) < len(_MAGIC) + _HDR.size or head[:len(_MAGIC)] != _MAGIC:
        return None
    (hlen,) = _HDR.unpack(head[len(_MAGIC):])
    if hlen > 1 << 20:
        return None
    raw = f.read(hlen)
    if len(raw) < hlen:
        return None
    try:
        header = json.loads(raw.decode("utf-8"))
        base_seq = int(header["base_seq"])
    except (ValueError, KeyError, UnicodeDecodeError):
        return None
    return base_seq, len(_MAGIC) + _HDR.size + hlen


def _iter_records(path: str):
    """Yield (index, payload_bytes, end_offset) for every CRC-valid
    record from the segment's prefix; stops (without raising) at the
    first torn or corrupt frame. Payloads are decompressed."""
    with open(path, "rb") as f:
        got = _read_header(f)
        if got is None:
            return
        _, off = got
        i = 0
        while True:
            head = f.read(_REC.size)
            if len(head) < _REC.size:
                return
            length, flags, crc = _REC.unpack(head)
            if length > _MAX_RECORD:
                return
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                return
            off += _REC.size + length
            if flags & FLAG_DEFLATE:
                try:
                    payload = zlib.decompress(payload)
                except zlib.error:
                    return
            yield i, payload, off
            i += 1


def _scan_segment(path: str) -> Tuple[Optional[int], int, int]:
    """(base_seq, n_valid_records, valid_prefix_bytes); base_seq None
    when even the header is unreadable. Validates CRCs only — never
    decompresses (see _iter_records for the replay-time read)."""
    with open(path, "rb") as f:
        got = _read_header(f)
        if got is None:
            return None, 0, 0
        base_seq, off = got
        n = 0
        while True:
            head = f.read(_REC.size)
            if len(head) < _REC.size:
                return base_seq, n, off
            length, _flags, crc = _REC.unpack(head)
            if length > _MAX_RECORD:
                return base_seq, n, off
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                return base_seq, n, off
            off += _REC.size + length
            n += 1


class WriteAheadLog:
    """See the module docstring. Thread-safe; one instance owns one
    directory. ``append`` takes opaque payload bytes (the store's unit
    record codec lives in zipkin_tpu.wal.record) and returns the
    record's sequence number."""

    def __init__(self, directory: str, fsync: str = FsyncPolicy.INTERVAL,
                 interval_s: float = 0.05,
                 segment_bytes: int = 64 << 20,
                 compress: bool = True,
                 retain_bytes: int = 0,
                 registry=None):
        from zipkin_tpu import obs

        if fsync not in FsyncPolicy.ALL:
            raise ValueError(
                f"fsync policy must be one of {FsyncPolicy.ALL}; "
                f"got {fsync!r}")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.fsync = fsync
        self.interval_s = max(1e-3, float(interval_s))
        self.segment_bytes = max(1 << 12, int(segment_bytes))
        self.compress = compress
        # Shipping retention floor: keep at least this many newest
        # bytes of checkpoint-covered tail (0 = truncate everything
        # covered, the pre-replication behavior).
        self.retain_bytes = max(0, int(retain_bytes))
        self._cond = threading.Condition()  # lock-order: 60 wal
        # Registered follower cursors: name -> highest fetched seq.
        # truncate() never deletes a segment holding records past the
        # minimum cursor (the shipping retention pin).
        self._cursors: dict = {}  # guarded-by: _cond
        self._segments: List[_Segment] = []  # guarded-by: _cond
        self._file = None  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        # Set when a failed append leaves bytes we could not truncate
        # away (every later append would sit past a torn frame and be
        # silently cut at recovery — refuse instead).
        self._poisoned: Optional[BaseException] = None  # guarded-by: _cond
        # Last group-commit fsync failure (cleared by the next success);
        # wait_durable surfaces it instead of timing out silently.
        # _sync_fails counts failures monotonically, so waiters can
        # distinguish "still failing" (a FRESH failure landed while
        # they waited) from "stale error, retry thread merely starved".
        self._sync_error: Optional[BaseException] = None  # guarded-by: _cond
        self._sync_fails = 0  # guarded-by: _cond
        # Durable-frontier observer (obs.fleet lineage): called with
        # the new durable seq AFTER _cond is released at every site
        # that advances the frontier. Must never be invoked under
        # _cond — the callback flushes self-trace spans through
        # store.apply, whose lock ranks BELOW the WAL's (10 < 60).
        self._on_durable = None  # guarded-by: _cond (the slot, not the call)
        self.torn_records_cut = 0  # records dropped by the open() scan
        self._next_seq = 1  # guarded-by: _cond
        self._durable = 0  # guarded-by: _cond
        self._open_scan()
        reg = registry or obs.default_registry()
        self._registry = reg
        self.h_append = reg.register(obs.LatencySketch(
            "zipkin_wal_append_seconds",
            "WAL record append latency (frame + OS write; excludes "
            "group-commit fsync waits)"))
        self.h_fsync = reg.register(obs.LatencySketch(
            "zipkin_wal_fsync_seconds",
            "WAL fsync latency (per batch, per group commit, or "
            "explicit sync())"))
        self.g_bytes = reg.register(obs.Gauge(
            "zipkin_wal_segment_bytes",
            "Live WAL bytes on disk across all segments",
            fn=self._live_bytes))
        self.g_backlog = reg.register(obs.Gauge(
            "zipkin_wal_truncation_backlog_segments",
            "Segment files not yet covered by a checkpoint truncation",
            fn=self._live_segments))
        self.c_records = reg.register(obs.Counter(
            "zipkin_wal_records_total", "Records appended to the WAL"))
        self.c_replayed = reg.register(obs.Counter(
            "zipkin_wal_replayed_records_total",
            "Records replayed through the ingest path at recovery"))
        self.c_corrupt = reg.register(obs.Counter(
            "zipkin_wal_corrupt_records_total",
            "Torn/CRC-corrupt records cut from the log tail"))
        self.c_truncated = reg.register(obs.Counter(
            "zipkin_wal_truncated_segments_total",
            "Segment files deleted by checkpoint-covered truncation"))
        if self.torn_records_cut:
            self.c_corrupt.inc(self.torn_records_cut)
        self._syncer: Optional[threading.Thread] = None
        if self.fsync == FsyncPolicy.INTERVAL:
            self._syncer = threading.Thread(
                target=self._sync_loop, name="zipkin-wal-sync",
                daemon=True)
            self._syncer.start()

    def _live_bytes(self) -> float:
        """Gauge callback (exposition thread): the _segments list is
        _cond-guarded, so snapshot under it — the old lock-free lambda
        raced truncate()'s list swap (graftlint guarded-by)."""
        with self._cond:
            return float(sum(s.nbytes for s in self._segments))

    def _live_segments(self) -> float:
        with self._cond:
            return float(len(self._segments))

    # -- open-time scan -------------------------------------------------

    # graftlint: disable=guarded-by — __init__-time, pre-thread
    def _open_scan(self) -> None:
        """Adopt the valid prefix of an existing directory: scan every
        segment in base_seq order, truncate the first torn/corrupt one
        at its last valid record, and delete everything after it."""
        names = sorted(n for n in os.listdir(self.directory)
                       if n.startswith("wal-") and n.endswith(".seg"))
        cut = False
        expect = None
        for name in names:
            path = os.path.join(self.directory, name)
            if cut:
                # Count the RECORDS this discarded file held, not the
                # file: the corrupt counter is an operator's data-loss
                # signal (docs/DURABILITY.md runbook), and a later
                # segment can carry hundreds of acked records.
                _, n_lost, _ = _scan_segment(path)
                self.torn_records_cut += max(1, n_lost)
                os.remove(path)
                continue
            base_seq, n_valid, valid_bytes = _scan_segment(path)
            total = os.path.getsize(path)
            if base_seq is None or (expect is not None
                                    and base_seq != expect):
                # Unreadable header or a sequence hole: nothing after
                # this point is a sound prefix.
                cut = True
                self.torn_records_cut += max(1, n_valid)
                os.remove(path)
                continue
            if valid_bytes < total:
                # Torn tail: cut at the last valid record. Anything in
                # LATER segments would sit past the cut — drop it too.
                self.torn_records_cut += 1
                with open(path, "r+b") as f:
                    f.truncate(valid_bytes)
                cut = True
            self._segments.append(
                _Segment(path, base_seq, n_valid, valid_bytes))
            expect = base_seq + n_valid
        self._next_seq = (self._segments[-1].last_seq + 1
                          if self._segments else 1)
        self._durable = self._next_seq - 1

    # -- frontier properties --------------------------------------------

    @property
    def last_seq(self) -> int:
        """Sequence of the most recently appended record (0 = none)."""
        with self._cond:
            return self._next_seq - 1

    @property
    def durable_seq(self) -> int:
        """Highest sequence known fsynced (== last_seq under the
        'batch' and 'off' policies)."""
        with self._cond:
            return self._durable

    def first_available_seq(self) -> int:
        """Lowest sequence the log can still replay (truncation may
        have deleted earlier records). ``last_seq + 1`` when the log
        holds no records — a follower whose cursor is at or past
        ``first_available_seq() - 1`` can catch up from the log alone;
        anything older needs an anchor bootstrap (replicate/ship)."""
        with self._cond:
            for seg in self._segments:
                if seg.n_records:
                    return seg.base_seq
            return self._next_seq

    # -- follower cursors (shipping retention pins) ---------------------

    def register_cursor(self, name: str, seq: int = 0) -> None:
        """Pin truncation at ``seq``: segments holding records past the
        minimum registered cursor survive truncate() until the cursor
        advances. Re-registering moves the pin (monotonically — a
        follower can never un-fetch)."""
        with self._cond:
            have = self._cursors.get(name, -1)
            self._cursors[name] = max(have, int(seq))

    def advance_cursor(self, name: str, seq: int) -> None:
        self.register_cursor(name, seq)

    def drop_cursor(self, name: str) -> None:
        with self._cond:
            self._cursors.pop(name, None)

    def cursors(self) -> dict:
        with self._cond:
            return dict(self._cursors)

    # -- fleet observability hooks --------------------------------------

    def set_on_durable(self, fn) -> None:
        """Register ``fn(durable_seq)`` to run after every durable-
        frontier advance, OUTSIDE ``_cond``. With ``fsync='off'`` or
        ``'batch'`` the call happens synchronously inside ``append``
        (the caller may hold its own locks — obs.fleet's tracker
        defers its flush via ``suppressed()`` for exactly this case);
        under ``'interval'`` it runs on the group-commit thread."""
        with self._cond:
            self._on_durable = fn

    def _notify_durable(self, prev: int) -> None:
        """Fire the durable observer if the frontier moved past
        ``prev``. Called WITHOUT _cond held."""
        with self._cond:
            fn, now = self._on_durable, self._durable
        if fn is not None and now > prev:
            try:
                fn(now)
            except Exception:  # graftlint: disable=swallowed-exception
                pass  # the observer must not poison the append/fsync path

    def sync_error(self) -> Optional[BaseException]:
        """The parked group-commit fsync failure, or None when the
        last fsync succeeded — the stall watchdog's fsync probe."""
        with self._cond:
            return self._sync_error

    # -- append path ----------------------------------------------------

    def _ensure_file_locked(self):  # called-under: _cond
        if self._file is None:
            if not self._segments:
                self._roll_locked()
            else:
                self._file = open(self._segments[-1].path, "ab")
        if self._segments[-1].nbytes >= self.segment_bytes:
            self._roll_locked()
        return self._file

    def _roll_locked(self) -> None:  # called-under: _cond
        if self._file is not None:
            self._file.flush()
            os.fsync(self._file.fileno())
            self._file.close()
        path = _segment_path(self.directory, self._next_seq)
        self._file = open(path, "wb")
        nbytes = _write_header(self._file, self._next_seq)
        self._file.flush()
        # The new segment's DIRENT must be durable before any record
        # in it is claimed durable — fsyncing file bytes alone leaves
        # the file itself able to vanish in a power crash.
        _fsync_dir(self.directory)
        self._segments.append(_Segment(path, self._next_seq, 0, nbytes))

    def append(self, payload: bytes) -> int:
        """Append one record; returns its sequence number. Durability
        on return follows the fsync policy (module docstring) — use
        ``wait_durable``/``sync`` for an explicit barrier."""
        flags = 0
        data = payload
        if self.compress and len(payload) >= _COMPRESS_MIN:
            packed = zlib.compress(payload, 1)
            if len(packed) < len(payload):
                data, flags = packed, FLAG_DEFLATE
        frame = _REC.pack(len(data), flags, zlib.crc32(data)) + data
        t0 = time.perf_counter()
        with self._cond:
            if self._closed:
                raise RuntimeError("write-ahead log is closed")
            if self._poisoned is not None:
                raise WalDurabilityError(
                    "write-ahead log is poisoned by an earlier "
                    "unrecoverable append failure"
                ) from self._poisoned
            f = self._ensure_file_locked()
            seg = self._segments[-1]
            try:
                f.write(frame)
                f.flush()
            except BaseException as e:
                # A partial frame may be on disk. Left there, every
                # LATER append would sit past a torn frame and be
                # silently cut at recovery — so restore the segment's
                # valid prefix now (truncate + reposition), or refuse
                # all further appends if even that fails.
                try:
                    f.truncate(seg.nbytes)
                    f.seek(seg.nbytes)
                except OSError as e2:
                    self._poisoned = e2
                raise WalDurabilityError(
                    "WAL append failed; the torn frame was "
                    + ("rolled back" if self._poisoned is None
                       else "NOT rolled back — log poisoned")
                ) from e
            seg.n_records += 1
            seg.nbytes += len(frame)
            seq = self._next_seq
            self._next_seq += 1
            prev_durable = self._durable
            if self.fsync == FsyncPolicy.BATCH:
                self._fsync_locked()
            elif self.fsync == FsyncPolicy.OFF:
                self._durable = seq
                self._cond.notify_all()
            # INTERVAL: the group-commit thread advances the frontier.
        self._notify_durable(prev_durable)
        self.h_append.observe(time.perf_counter() - t0)
        self.c_records.inc()
        return seq

    def _fsync_locked(self) -> None:  # called-under: _cond
        if self._file is not None:
            t0 = time.perf_counter()
            os.fsync(self._file.fileno())
            self.h_fsync.observe(time.perf_counter() - t0)
        self._sync_error = None
        self._durable = self._next_seq - 1
        self._cond.notify_all()

    def sync(self) -> None:
        """Force every appended record durable now — fsyncs under ANY
        policy, including ``off`` (the graceful-shutdown barrier must
        not depend on the steady-state policy)."""
        with self._cond:
            self._fsync_locked()
        # Always notify (prev=-1): under fsync='off' the frontier was
        # already at the append frontier, but lineage seqs registered
        # AFTER their append's own notification (note_append runs once
        # append returns) still need a durable callback — sync() is
        # the explicit barrier that drains them.
        self._notify_durable(-1)

    def wait_durable(self, seq: int, timeout: Optional[float] = 30.0
                     ) -> bool:
        """Block until the durable frontier covers ``seq`` (the
        group-commit ack barrier). True when covered; False on
        timeout."""
        deadline = None if timeout is None else (
            time.monotonic() + timeout)
        # A parked group-commit error gets a grace period to clear (a
        # transient EIO the sync loop recovers from on its next tick);
        # past that, it surfaces here — the acker must fail fast, not
        # time out against a broken fsync and (worse) ack. The raise
        # additionally requires a FRESH failure since this wait began
        # (the monotonic failure count moved): a stale parked error
        # whose retry thread is merely starved for the CPU keeps
        # waiting instead of spuriously failing the ack.
        err_grace = max(2.0 * self.interval_s, 0.05)
        err_since = None
        fails0 = None
        with self._cond:
            while self._durable < seq:
                if self._sync_error is not None:
                    now = time.monotonic()
                    if err_since is None:
                        err_since = now
                        fails0 = self._sync_fails
                    elif (now - err_since > err_grace
                            and self._sync_fails > fails0):
                        raise WalDurabilityError(
                            "group-commit fsync is failing; record "
                            "not durable"
                        ) from self._sync_error
                else:
                    err_since = None
                if self._closed:
                    return self._durable >= seq
                rest = None if deadline is None else (
                    deadline - time.monotonic())
                if rest is not None and rest <= 0:
                    return False
                wait = 0.5 if rest is None else rest
                if self._sync_error is not None:
                    wait = min(wait, err_grace / 2)
                self._cond.wait(timeout=wait)
            return True

    def _sync_loop(self) -> None:
        while True:
            fd = None
            target = 0
            with self._cond:
                if self._closed:
                    return
                if (self._durable < self._next_seq - 1
                        and self._file is not None):
                    # Snapshot the frontier and dup the fd, then fsync
                    # OUTSIDE the lock: appends (which only need the OS
                    # buffer) must not stall behind the group commit's
                    # disk wait, or the WAL's append overhead grows a
                    # synchronous fsync every interval. Every record
                    # <= target is already flushed to the OS (append
                    # flushes under the lock; rolled segments fsync at
                    # roll), so advancing to the pre-snapshot target
                    # after the fsync is sound even while new appends
                    # land — or the segment rolls — mid-fsync.
                    target = self._next_seq - 1
                    try:
                        fd = os.dup(self._file.fileno())
                    except OSError as e:
                        self._sync_error = e
                        self._sync_fails += 1
                        self._cond.notify_all()
            if fd is not None:
                try:
                    t0 = time.perf_counter()
                    os.fsync(fd)
                except Exception as e:  # noqa: BLE001
                    # The thread must SURVIVE a transient EIO/ENOSPC:
                    # park the error for wait_durable to surface
                    # (ackers fail instead of timing out against a
                    # silently dead group commit) and retry next tick.
                    with self._cond:
                        self._sync_error = e
                        self._sync_fails += 1
                        self._cond.notify_all()
                else:
                    self.h_fsync.observe(time.perf_counter() - t0)
                    with self._cond:
                        prev = self._durable
                        self._sync_error = None
                        if target > self._durable:
                            self._durable = target
                        self._cond.notify_all()
                    self._notify_durable(prev)
                finally:
                    os.close(fd)
            time.sleep(self.interval_s)

    # -- replay ---------------------------------------------------------

    def replay(self, from_seq: int = 0) -> Iterator[Tuple[int, bytes]]:
        """Yield (seq, payload) for every record with seq > from_seq,
        in order. The open()-time scan already cut any torn tail, so
        this sees only CRC-valid frames; a record that rots BETWEEN
        open and replay still stops the iteration at the last valid
        prefix (counted corrupt) rather than raising."""
        with self._cond:
            segments = list(self._segments)
        for seg in segments:
            if seg.last_seq <= from_seq:
                continue
            n_seen = 0
            try:
                for i, payload, _off in _iter_records(seg.path):
                    n_seen = i + 1
                    seq = seg.base_seq + i
                    if seq > from_seq:
                        yield seq, payload
            except FileNotFoundError:
                # A concurrent truncate() deleted the file after the
                # snapshot (possible only for already-covered,
                # un-pinned segments — shipping readers pin theirs):
                # stop at the prefix served so far; the caller's next
                # replay(from_seq) resumes past the hole.
                return
            if n_seen < seg.n_records:
                self.c_corrupt.inc(seg.n_records - n_seen)
                return

    # -- truncation -----------------------------------------------------

    def _delete_segment(self, path: str) -> None:
        from zipkin_tpu.testing.crash import kill_point

        kill_point("mid-truncate")
        os.remove(path)

    def truncate(self, upto_seq: int) -> int:
        """Delete whole segments fully covered by ``upto_seq`` (a
        checkpoint's applied frontier). The active segment rolls first
        when fully covered so its file can go too. Registered follower
        cursors clamp the frontier (a shipped log never deletes what a
        follower still has to fetch) and ``retain_bytes`` keeps the
        newest covered tail on disk. Returns the number of segment
        files deleted."""
        removed = 0
        with self._cond:
            # Follower pin: records past the minimum cursor are not
            # yet fetched — truncation must stop below them no matter
            # what the checkpoint covers.
            if self._cursors:
                upto_seq = min(upto_seq, min(self._cursors.values()))
            # Roll BEFORE deleting whenever the newest record-bearing
            # segment is covered — even on a reopened log that has not
            # appended yet (file not open). Deleting every segment
            # would leave an empty directory with no record of
            # _next_seq: the next open would restart sequences at 1
            # below the checkpoint's applied frontier, and recovery
            # would silently skip that many durably-acked records. The
            # fresh empty segment persists base_seq across the wipe.
            if (self._segments
                    and self._segments[-1].n_records > 0
                    and self._segments[-1].last_seq <= upto_seq):
                self._roll_locked()
            # Byte floor: walking from the newest segment, everything
            # inside the retain_bytes window survives even when
            # checkpoint-covered (reconnecting followers catch up from
            # the log instead of re-anchoring).
            protected: set = set()
            if self.retain_bytes > 0:
                tail = 0
                for seg in reversed(self._segments):
                    if tail >= self.retain_bytes:
                        break
                    protected.add(seg.base_seq)
                    tail += seg.nbytes
            keep: List[_Segment] = []
            for seg in self._segments:
                is_active = (self._file is not None
                             and seg is self._segments[-1])
                if (not is_active and seg.n_records > 0
                        and seg.last_seq <= upto_seq
                        and seg.base_seq not in protected):
                    self._delete_segment(seg.path)
                    removed += 1
                else:
                    keep.append(seg)
            self._segments = keep
            if removed:
                # Make the deletes durable: a deleted segment
                # resurrecting after a power crash would break the
                # base_seq chain and cut the surviving valid tail.
                _fsync_dir(self.directory)
        if removed:
            self.c_truncated.inc(removed)
        return removed

    def cut_tail(self, upto_seq: int) -> int:
        """Physically cut the log back so ``upto_seq`` is its last
        record — the sharded group-commit alignment (wal/sharded): a
        crash between one member log's append and another's leaves the
        fleet's logs at different frontiers, and every log must rewind
        to the shortest so the epoch chain stays lockstep. Segments
        wholly past the cut are deleted; the segment containing the
        cut is truncated at the record boundary. Returns the number of
        records cut (counted corrupt — they were never part of a
        complete group and are data loss in the same operator sense as
        a torn tail)."""
        removed = 0
        cut = 0
        with self._cond:
            if upto_seq >= self._next_seq - 1:
                return 0
            if self._file is not None:
                self._file.flush()
                self._file.close()
                self._file = None
            keep: List[_Segment] = []
            for seg in self._segments:
                if seg.base_seq > upto_seq:
                    cut += seg.n_records
                    self._delete_segment(seg.path)
                    removed += 1
                    continue
                if seg.n_records and seg.last_seq > upto_seq:
                    n_keep = upto_seq - seg.base_seq + 1
                    end = None
                    for i, _payload, off in _iter_records(seg.path):
                        if i + 1 == n_keep:
                            end = off
                            break
                    cut += seg.n_records - n_keep
                    with open(seg.path, "r+b") as f:
                        f.truncate(end)
                    seg.n_records = n_keep
                    seg.nbytes = end
                keep.append(seg)
            self._segments = keep
            self._next_seq = upto_seq + 1
            self._durable = min(self._durable, upto_seq)
            if removed or cut:
                _fsync_dir(self.directory)
        if cut:
            self.torn_records_cut += cut
            self.c_corrupt.inc(cut)
        return cut

    # -- lifecycle ------------------------------------------------------

    def close(self) -> None:
        """Fsync, stop the group-commit thread, release the file, and
        unregister this log's metrics."""
        with self._cond:
            if self._closed:
                return
            self._fsync_locked()
            self._closed = True
            self._cond.notify_all()
            if self._file is not None:
                self._file.close()
                self._file = None
        if self._syncer is not None:
            self._syncer.join(timeout=5.0)
        for m in (self.h_append, self.h_fsync, self.g_bytes,
                  self.g_backlog, self.c_records, self.c_replayed,
                  self.c_corrupt, self.c_truncated):
            if self._registry.get(m.name) is m:
                self._registry.unregister(m.name)

    def stats(self) -> dict:
        with self._cond:
            return {
                "wal_segments": len(self._segments),
                "wal_bytes": sum(s.nbytes for s in self._segments),
                "wal_last_seq": self._next_seq - 1,
                "wal_durable_seq": self._durable,
            }
