// Native thrift-binary span parser → columnar arrays.
//
// Plays the role of scrooge's generated BinaryThriftStructSerializer on
// the reference's hot decode path (ScribeSpanReceiver.scala:96-107) —
// but emits structure-of-arrays output directly, so the host python
// layer only interns strings and uploads numpy arrays.
//
// Layout parsed: zipkinCore.thrift Span/Annotation/BinaryAnnotation/
// Endpoint (see zipkin_tpu/wire/thrift.py for the field table). Unknown
// fields are skipped. All output numeric columns are caller-allocated
// numpy arrays passed as raw pointers; strings come back as (offset,
// length) pairs into the input buffer.
//
// Build: g++ -O3 -shared -fPIC -o libzipkin_native.so span_codec.cc
// Entry points are exported with C linkage for ctypes.

#include <cstdint>
#include <cstring>

namespace {

constexpr int T_STOP = 0;
constexpr int T_BOOL = 2;
constexpr int T_BYTE = 3;
constexpr int T_DOUBLE = 4;
constexpr int T_I16 = 6;
constexpr int T_I32 = 8;
constexpr int T_I64 = 10;
constexpr int T_STRING = 11;
constexpr int T_STRUCT = 12;
constexpr int T_MAP = 13;
constexpr int T_SET = 14;
constexpr int T_LIST = 15;

struct Reader {
  const uint8_t* data;
  size_t len;
  size_t pos;
  bool ok;

  bool need(size_t n) {
    if (pos + n > len) { ok = false; return false; }
    return true;
  }
  uint8_t u8() { if (!need(1)) return 0; return data[pos++]; }
  int16_t i16() {
    if (!need(2)) return 0;
    int16_t v = (int16_t)((data[pos] << 8) | data[pos + 1]);
    pos += 2; return v;
  }
  int32_t i32() {
    if (!need(4)) return 0;
    uint32_t v = ((uint32_t)data[pos] << 24) | ((uint32_t)data[pos+1] << 16) |
                 ((uint32_t)data[pos+2] << 8) | (uint32_t)data[pos+3];
    pos += 4; return (int32_t)v;
  }
  int64_t i64() {
    if (!need(8)) return 0;
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v = (v << 8) | data[pos + i];
    pos += 8; return (int64_t)v;
  }
  // Returns offset of string payload; fills n.
  int64_t str(int32_t* n) {
    int32_t sz = i32();
    if (sz < 0 || !need((size_t)sz)) { ok = false; *n = 0; return 0; }
    int64_t off = (int64_t)pos;
    pos += (size_t)sz;
    *n = sz;
    return off;
  }
  // Depth-bounded: a crafted payload of deeply nested containers on the
  // network-facing ingest path must fail the parse, not blow the C stack.
  static constexpr int kMaxSkipDepth = 64;

  void skip(int t, int depth = 0) {
    if (depth > kMaxSkipDepth) { ok = false; return; }
    switch (t) {
      case T_BOOL: case T_BYTE: need(1); pos += 1; break;
      case T_I16: need(2); pos += 2; break;
      case T_I32: need(4); pos += 4; break;
      case T_I64: case T_DOUBLE: need(8); pos += 8; break;
      case T_STRING: { int32_t n; str(&n); break; }
      case T_STRUCT: {
        while (ok) {
          uint8_t ft = u8();
          if (ft == T_STOP) break;
          i16();
          skip(ft, depth + 1);
        }
        break;
      }
      case T_LIST: case T_SET: {
        uint8_t et = u8();
        int32_t n = i32();
        for (int32_t i = 0; i < n && ok; i++) skip(et, depth + 1);
        break;
      }
      case T_MAP: {
        uint8_t kt = u8(), vt = u8();
        int32_t n = i32();
        for (int32_t i = 0; i < n && ok; i++) {
          skip(kt, depth + 1); skip(vt, depth + 1);
        }
        break;
      }
      default: ok = false;
    }
  }
};

struct Endpoint {
  int32_t ipv4 = 0;
  int32_t port = 0;
  int64_t svc_off = 0;
  int32_t svc_len = -1;  // -1: no endpoint; -2: endpoint w/o service_name
};

Endpoint read_endpoint(Reader& r) {
  Endpoint ep;
  ep.svc_len = -2;
  while (r.ok) {
    uint8_t ft = r.u8();
    if (ft == T_STOP) break;
    int16_t fid = r.i16();
    if (fid == 1 && ft == T_I32) ep.ipv4 = r.i32();
    else if (fid == 2 && ft == T_I16) ep.port = (int32_t)(uint16_t)r.i16();
    else if (fid == 3 && ft == T_STRING) ep.svc_off = r.str(&ep.svc_len);
    else r.skip(ft);
  }
  return ep;
}

}  // namespace

// Output bundle: parallel arrays, caller-allocated. String columns are
// (off, len) into the input buffer; len -1 means absent.
extern "C" {

struct SpanColumns {
  // span table
  int64_t* trace_id;
  int64_t* span_id;
  int64_t* parent_id;
  uint8_t* has_parent;
  uint8_t* debug;
  int64_t* name_off;
  int32_t* name_len;
  // annotation table
  int32_t* ann_span_idx;
  int64_t* ann_ts;
  int64_t* ann_value_off;
  int32_t* ann_value_len;
  int32_t* ann_ipv4;
  int32_t* ann_port;
  int64_t* ann_svc_off;
  int32_t* ann_svc_len;  // -1: no host; -2: host w/o service_name
  // binary annotation table
  int32_t* bann_span_idx;
  int64_t* bann_key_off;
  int32_t* bann_key_len;
  int64_t* bann_value_off;
  int32_t* bann_value_len;
  int32_t* bann_type;
  int32_t* bann_ipv4;
  int32_t* bann_port;
  int64_t* bann_svc_off;
  int32_t* bann_svc_len;  // -1: no host; -2: host w/o service_name
};

// Parse a back-to-back sequence of thrift Span structs.
// Returns 0 on success, negative on error:
//   -1 malformed thrift   -2 span capacity   -3 ann capacity
//   -4 binary capacity
// Fills n_spans/n_anns/n_banns with the counts consumed.
int zk_parse_spans(
    const uint8_t* data, int64_t len,
    SpanColumns* out,
    int32_t max_spans, int32_t max_anns, int32_t max_banns,
    int32_t* n_spans, int32_t* n_anns, int32_t* n_banns) {
  Reader r{data, (size_t)len, 0, true};
  int32_t si = 0, ai = 0, bi = 0;
  while (r.pos < r.len) {
    if (si >= max_spans) return -2;
    int64_t trace_id = 0, span_id = 0, parent_id = 0;
    uint8_t has_parent = 0, debug = 0;
    int64_t name_off = 0;
    int32_t name_len = 0;
    while (r.ok) {
      uint8_t ft = r.u8();
      if (ft == T_STOP) break;
      int16_t fid = r.i16();
      if (fid == 1 && ft == T_I64) trace_id = r.i64();
      else if (fid == 3 && ft == T_STRING) name_off = r.str(&name_len);
      else if (fid == 4 && ft == T_I64) span_id = r.i64();
      else if (fid == 5 && ft == T_I64) { parent_id = r.i64(); has_parent = 1; }
      else if (fid == 9 && ft == T_BOOL) debug = r.u8() != 0;
      else if (fid == 6 && ft == T_LIST) {
        uint8_t et = r.u8();
        int32_t n = r.i32();
        if (et != T_STRUCT) return -1;
        for (int32_t i = 0; i < n && r.ok; i++) {
          if (ai >= max_anns) return -3;
          int64_t ts = 0, voff = 0;
          int32_t vlen = 0;
          Endpoint ep; ep.svc_len = -1;
          while (r.ok) {
            uint8_t aft = r.u8();
            if (aft == T_STOP) break;
            int16_t afid = r.i16();
            if (afid == 1 && aft == T_I64) ts = r.i64();
            else if (afid == 2 && aft == T_STRING) voff = r.str(&vlen);
            else if (afid == 3 && aft == T_STRUCT) ep = read_endpoint(r);
            else r.skip(aft);
          }
          out->ann_span_idx[ai] = si;
          out->ann_ts[ai] = ts;
          out->ann_value_off[ai] = voff;
          out->ann_value_len[ai] = vlen;
          out->ann_ipv4[ai] = ep.ipv4;
          out->ann_port[ai] = ep.port;
          out->ann_svc_off[ai] = ep.svc_off;
          out->ann_svc_len[ai] = ep.svc_len;
          ai++;
        }
      } else if (fid == 8 && ft == T_LIST) {
        uint8_t et = r.u8();
        int32_t n = r.i32();
        if (et != T_STRUCT) return -1;
        for (int32_t i = 0; i < n && r.ok; i++) {
          if (bi >= max_banns) return -4;
          int64_t koff = 0, voff = 0;
          int32_t klen = 0, vlen = 0, btype = 1;  // default BYTES
          Endpoint ep; ep.svc_len = -1;
          while (r.ok) {
            uint8_t bft = r.u8();
            if (bft == T_STOP) break;
            int16_t bfid = r.i16();
            if (bfid == 1 && bft == T_STRING) koff = r.str(&klen);
            else if (bfid == 2 && bft == T_STRING) voff = r.str(&vlen);
            else if (bfid == 3 && bft == T_I32) btype = r.i32();
            else if (bfid == 4 && bft == T_STRUCT) ep = read_endpoint(r);
            else r.skip(bft);
          }
          out->bann_span_idx[bi] = si;
          out->bann_key_off[bi] = koff;
          out->bann_key_len[bi] = klen;
          out->bann_value_off[bi] = voff;
          out->bann_value_len[bi] = vlen;
          out->bann_type[bi] = btype;
          out->bann_ipv4[bi] = ep.ipv4;
          out->bann_port[bi] = ep.port;
          out->bann_svc_off[bi] = ep.svc_off;
          out->bann_svc_len[bi] = ep.svc_len;
          bi++;
        }
      } else {
        r.skip(ft);
      }
    }
    if (!r.ok) return -1;
    out->trace_id[si] = trace_id;
    out->span_id[si] = span_id;
    out->parent_id[si] = parent_id;
    out->has_parent[si] = has_parent;
    out->debug[si] = debug;
    out->name_off[si] = name_off;
    out->name_len[si] = name_len;
    si++;
  }
  *n_spans = si;
  *n_anns = ai;
  *n_banns = bi;
  return 0;
}

// Content-dedup of string slices: assign each (offset, length) slice of
// ``buf`` a group id such that byte-identical slices share a group, and
// record one representative slice per group. The python layer then
// interns each UNIQUE string once and builds dictionary-id columns by
// vectorized lookup — removing the per-row intern loop from the hot
// decode (scrooge decodes each struct once; our dictionary encoding
// makes per-unique work the natural unit).
//
// Rows with len < 0 (absent field sentinels) get group -1.
// Open-addressing FNV-1a table sized to the next power of two >= 2n;
// returns the number of groups, or -1 if max_groups is exceeded.
int32_t zk_group_strings(
    const uint8_t* buf,
    const int64_t* offs, const int32_t* lens, int32_t n,
    int32_t* group_of,            // [n] out
    int64_t* rep_off, int32_t* rep_len,  // [max_groups] out
    int32_t max_groups) {
  if (n <= 0) return 0;
  uint32_t cap = 16;
  while (cap < (uint32_t)n * 2u) cap <<= 1;
  // slots hold group index + 1 (0 = empty).
  int32_t* slots = new int32_t[cap]();
  int32_t n_groups = 0;
  for (int32_t i = 0; i < n; i++) {
    int32_t len = lens[i];
    if (len < 0) { group_of[i] = -1; continue; }
    const uint8_t* s = buf + offs[i];
    uint64_t h = 1469598103934665603ull;  // FNV-1a 64
    for (int32_t k = 0; k < len; k++) h = (h ^ s[k]) * 1099511628211ull;
    uint32_t slot = (uint32_t)h & (cap - 1);
    for (;;) {
      int32_t g = slots[slot];
      if (g == 0) {
        if (n_groups >= max_groups) { delete[] slots; return -1; }
        rep_off[n_groups] = offs[i];
        rep_len[n_groups] = len;
        slots[slot] = n_groups + 1;
        group_of[i] = n_groups++;
        break;
      }
      int32_t gi = g - 1;
      if (rep_len[gi] == len &&
          memcmp(buf + rep_off[gi], s, (size_t)len) == 0) {
        group_of[i] = gi;
        break;
      }
      slot = (slot + 1) & (cap - 1);
    }
  }
  delete[] slots;
  return n_groups;
}

// Standard base64 decode (for scribe LogEntry payloads); returns output
// length or -1 on bad input. Skips whitespace; handles padding.
int64_t zk_base64_decode(const uint8_t* in, int64_t in_len, uint8_t* out) {
  static int8_t lut[256];
  static bool init = false;
  if (!init) {
    for (int i = 0; i < 256; i++) lut[i] = -1;
    const char* tbl =
        "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
    for (int i = 0; i < 64; i++) lut[(uint8_t)tbl[i]] = (int8_t)i;
    init = true;
  }
  uint32_t acc = 0;
  int bits = 0;
  int64_t o = 0;
  for (int64_t i = 0; i < in_len; i++) {
    uint8_t c = in[i];
    if (c == '=' || c == '\n' || c == '\r' || c == ' ') continue;
    int8_t v = lut[c];
    if (v < 0) return -1;
    acc = (acc << 6) | (uint32_t)v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out[o++] = (uint8_t)((acc >> bits) & 0xFF);
    }
  }
  return o;
}

}  // extern "C"
