"""SURVEY §5 determinism test — the race-detector analogue.

The reference leans on JVM memory-safety primitives (ArrayBlockingQueue,
synchronized); the TPU build's equivalent guarantee is *replayability*:
the same device batches, ingested in the same order into a fresh state,
must produce bitwise-identical arrays — every sketch register, ring
slot, and counter. This locks in the scatter-order assumptions the
capacity guards in store/tpu.py depend on (colliding slot writes within
one launch would be implementation-defined and would fail this test).
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from zipkin_tpu.store import device as dev
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.tracegen import ColumnarTraceGen

CONFIG = dev.StoreConfig(
    capacity=256, ann_capacity=1024, bann_capacity=512,
    max_services=16, max_span_names=32, max_annotation_values=64,
    max_binary_keys=16, cms_width=256, hll_p=6, quantile_buckets=128,
)


def _device_batches(n_batches=4, n_traces=8):
    store = TpuSpanStore(CONFIG)
    gen = ColumnarTraceGen(store.dicts, n_services=8, n_span_names=16,
                           spans_per_trace=7)
    out = []
    for _ in range(n_batches):
        batch, name_lc, indexable = gen.next_batch(n_traces)
        out.append(dev.make_device_batch(
            batch, name_lc, indexable,
            pad_spans=64, pad_anns=128, pad_banns=64,
        ))
    return out


def _run(batches):
    state = dev.init_state(CONFIG)
    for db in batches:
        state = dev.ingest_step(state, db)
    # Include the archive step: its full-ring join must be as
    # deterministic as the ingest scatters it depends on.
    state = dev.dep_archive_auto(state, batches[-1].trace_id.shape[0])
    return state


def _leaves(state):
    flat, _ = jax.tree_util.tree_flatten(state)
    return [np.asarray(x) for x in flat]


def test_same_batches_bitwise_same_state():
    batches = _device_batches()
    a = _leaves(_run(batches))
    b = _leaves(_run(batches))
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        assert x.dtype == y.dtype
        np.testing.assert_array_equal(
            x, y, err_msg=f"leaf {i} diverged between identical replays"
        )


def test_query_results_deterministic():
    """Same state → same query winners (the device kernels sort with
    stable composite keys; ties must not flap between calls)."""
    batches = _device_batches()
    state = _run(batches)
    r1 = dev.query_trace_ids_by_service(state, 0, -1, 2**62, 8)
    r2 = dev.query_trace_ids_by_service(state, 0, -1, 2**62, 8)
    for x, y in zip(r1, r2):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_control_loop_reads_device_counters():
    """The adaptive controller's flow source is the store's device
    counter, not host accounting (AdaptiveSampler.scala:204-237's group
    sum, re-expressed as the psum-able spans_seen scalar)."""
    from zipkin_tpu.ingest.collector import Collector
    from zipkin_tpu.sampler.adaptive import AdaptiveConfig
    from zipkin_tpu.tracegen import generate_traces

    store = TpuSpanStore(CONFIG)
    cfg = AdaptiveConfig(
        target_store_rate=60.0,  # spans/minute target
        update_freq_s=1.0, window_s=4.0, sufficient_window_s=2.0,
        outlier_window_s=1.0,
    )
    collector = Collector(store, adaptive=cfg, concurrency=1)
    spans = [s for t in generate_traces(n_traces=20, max_depth=3) for s in t]
    t0 = 1000.0
    collector.control_tick(now_s=t0)
    # Poison the host counter: if control_tick read it, the flow would be
    # absurd and the rate would not follow the device counter's story.
    # (spans_stored is a registry-backed property now; poke the counter.)
    collector._c_stored.inc(10**9)
    n_ticks = 6
    per_tick = max(1, len(spans) // n_ticks)
    rate_before = collector.sampler.rate
    for i in range(n_ticks):
        collector.accept(spans[i * per_tick:(i + 1) * per_tick])
        collector.flush()
        collector.control_tick(now_s=t0 + (i + 1) * cfg.update_freq_s)
    # Device counter says ~200 spans/min >> 60 target → rate must drop.
    assert collector.sampler.rate < rate_before
    assert store.stored_span_count() == float(
        store.state.counters["spans_seen"]
    )


def test_extreme_trace_id_queryable():
    """trace_id == 2^63-1 is a valid id and must survive the dedup's
    sort keys (regression: an I64_MAX sentinel on the trace-id key made
    such traces unqueryable)."""
    from zipkin_tpu.models.span import Annotation, Endpoint, Span

    ep = Endpoint(1, 80, "edge")
    tid = 2**63 - 1
    span = Span(tid, "op", 7, None,
                (Annotation(10, "sr", ep), Annotation(11, "custom", ep)), ())
    store = TpuSpanStore(CONFIG)
    store.apply([span])
    res = store.get_trace_ids_by_name("edge", None, 100, 3)
    assert [i.trace_id for i in res] == [tid]
    res2 = store.get_trace_ids_by_annotation("edge", "custom", None, 100, 3)
    assert [i.trace_id for i in res2] == [tid]


def test_stored_span_count_sources():
    from zipkin_tpu.store.memory import InMemorySpanStore
    from zipkin_tpu.store.sql import SqliteSpanStore
    from zipkin_tpu.tracegen import generate_traces

    spans = [s for t in generate_traces(n_traces=3, max_depth=3) for s in t]
    mem = InMemorySpanStore()
    mem.apply(spans)
    assert mem.stored_span_count() == float(len(spans))
    sql = SqliteSpanStore()
    sql.apply(spans)
    assert sql.stored_span_count() == float(len(spans))
    sql.close()


def test_client_server_halves_order_independent_links():
    """The client and server halves of an RPC share (trace_id, span_id)
    in the span table; parent attribution for their children must not
    depend on which half arrived first (_tab_insert's scatter-min keeps
    the lowest service id deterministically — COVERAGE.md row 3)."""
    from zipkin_tpu.models.span import Annotation, Endpoint, Span

    cl = Endpoint(1, 1, "alpha-client")
    sv = Endpoint(2, 2, "beta-server")
    child_ep = Endpoint(3, 3, "gamma-child")
    client_half = Span(99, "rpc", 5, None,
                       (Annotation(10, "cs", cl), Annotation(40, "cr", cl)),
                       ())
    server_half = Span(99, "rpc", 5, None,
                       (Annotation(20, "sr", sv), Annotation(30, "ss", sv)),
                       ())
    child = Span(99, "leaf", 6, 5,
                 (Annotation(22, "sr", child_ep),
                  Annotation(28, "ss", child_ep)), ())

    def links(order):
        store = TpuSpanStore(CONFIG)
        # Intern every service first so dictionary ids don't depend on
        # the arrival order under test.
        for name in ("alpha-client", "beta-server", "gamma-child"):
            store.dicts.services.encode(name)
        for s in order:
            store.apply([s])
        deps = store.get_dependencies()
        return sorted((l.parent, l.child, l.duration_moments.count)
                      for l in deps.links)

    a = links([client_half, server_half, child])
    b = links([server_half, client_half, child])
    c = links([client_half, server_half, child][::-1])
    assert a == b == c
    assert any(child_name == "gamma-child" for _, child_name, _ in a)


def test_chained_ingest_steps_bitwise_matches_sequential():
    """dev.ingest_steps (k batches per launch via lax.scan) must land
    bitwise-identical state to k sequential ingest_step launches."""
    batches = _device_batches(n_batches=4)
    seq = dev.init_state(CONFIG)
    for db in batches:
        seq = dev.ingest_step(seq, jax.device_put(db))
    stacked = dev.stack_device_batches(batches)
    chained = dev.ingest_steps(dev.init_state(CONFIG), stacked)
    for a, b in zip(_leaves(seq), _leaves(chained)):
        np.testing.assert_array_equal(a, b)


def test_store_chained_writes_bitwise_match_single(monkeypatch):
    """TpuSpanStore._write_parts grouping (multi-chunk launches) must
    not change the stored state vs one-launch-per-chunk."""
    from zipkin_tpu.tracegen import generate_traces

    spans = [s for t in generate_traces(n_traces=120, max_depth=3,
                                        n_services=6) for s in t]
    cfg = dev.StoreConfig(
        capacity=256, ann_capacity=1024, bann_capacity=512,
        max_services=16, max_span_names=64, max_annotation_values=128,
        max_binary_keys=32, cms_width=256, hll_p=6, quantile_buckets=128,
    )
    chained = TpuSpanStore(cfg)
    single = TpuSpanStore(cfg)
    monkeypatch.setattr(TpuSpanStore, "CHAIN_SIZES", (),
                        raising=True)
    single.apply(spans)
    monkeypatch.undo()
    assert chained.CHAIN_SIZES == (16, 8, 4)
    chained.apply(spans)
    assert len(spans) > 2 * chained._max_chunk_spans()  # really chained
    for a, b in zip(_leaves(chained.state), _leaves(single.state)):
        np.testing.assert_array_equal(a, b)
