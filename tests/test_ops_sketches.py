"""Sketch-kernel tests: hashing, moments, count-min, HLL, quantile, top-k.

Runs on the CPU backend (conftest) — the same jitted code paths the TPU
executes. Accuracy bounds asserted are the sketches' theoretical
guarantees, not tuned-to-pass tolerances.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zipkin_tpu.models.dependencies import Moments
from zipkin_tpu.ops import cms, hll
from zipkin_tpu.ops import moments as M
from zipkin_tpu.ops import quantile as Q
from zipkin_tpu.ops import topk
from zipkin_tpu.ops.hashing import clz32, fmix32, hash2_32, join64, split64


class TestHashing:
    def test_split_join_roundtrip(self):
        xs = np.array([0, 1, -1, 2**63 - 1, -(2**63), 123456789012345], np.int64)
        hi, lo = split64(xs)
        assert hi.dtype == np.uint32 and lo.dtype == np.uint32
        np.testing.assert_array_equal(join64(hi, lo), xs)

    def test_fmix32_avalanche(self):
        xs = jnp.arange(1, 10000, dtype=jnp.uint32)
        hs = np.asarray(fmix32(xs))
        assert len(np.unique(hs)) == len(hs)  # bijective on a small range
        # roughly half the bits set on average
        bits = np.unpackbits(hs.view(np.uint8)).mean()
        assert 0.45 < bits < 0.55

    def test_hash2_seed_independence(self):
        hi = jnp.zeros(1000, jnp.uint32)
        lo = jnp.arange(1000, dtype=jnp.uint32)
        h0 = np.asarray(hash2_32(hi, lo, 0))
        h1 = np.asarray(hash2_32(hi, lo, 1))
        assert (h0 != h1).mean() > 0.99
        # low bits well distributed: with 1000 draws over 256 buckets,
        # E[missing] = 256*(255/256)^1000 ~ 5
        assert len(np.unique(h0 & 255)) > 235

    def test_hash_uses_both_words(self):
        lo = jnp.arange(1000, dtype=jnp.uint32)
        a = np.asarray(hash2_32(jnp.zeros(1000, jnp.uint32), lo, 7))
        b = np.asarray(hash2_32(jnp.ones(1000, jnp.uint32), lo, 7))
        assert (a != b).mean() > 0.99

    def test_clz32(self):
        xs = jnp.array([0, 1, 2, 3, 255, 256, 2**31, 2**32 - 1], jnp.uint32)
        np.testing.assert_array_equal(
            np.asarray(clz32(xs)), [32, 31, 30, 30, 24, 23, 0, 0]
        )


class TestMoments:
    def test_combine_matches_host_moments(self):
        rng = np.random.default_rng(0)
        xs = rng.lognormal(8.0, 1.0, size=256).astype(np.float32)
        host = Moments.of_many(float(x) for x in xs)
        dev = jax.jit(lambda v: M.reduce_moments(M.of(v)))(jnp.asarray(xs))
        got = np.asarray(dev, np.float64)
        assert got[0] == pytest.approx(host.n)
        assert got[1] == pytest.approx(host.mean, rel=1e-5)
        assert got[2] == pytest.approx(host.m2, rel=1e-3)
        assert got[3] == pytest.approx(host.m3, rel=1e-2, abs=1e-2 * abs(host.m4))
        assert got[4] == pytest.approx(host.m4, rel=1e-2)

    def test_combine_zero_identity(self):
        m = M.of(jnp.asarray(5.0))
        np.testing.assert_allclose(np.asarray(M.combine(m, M.zero())), np.asarray(m))
        np.testing.assert_allclose(np.asarray(M.combine(M.zero(), m)), np.asarray(m))

    def test_segment_moments_exact(self):
        values = jnp.asarray([10.0, 20.0, 30.0, 100.0, 5.0])
        seg = jnp.asarray([0, 0, 0, 1, 2])
        out = np.asarray(M.segment_moments(values, seg, 4), np.float64)
        ref0 = Moments.of_many([10.0, 20.0, 30.0])
        assert out[0][0] == 3 and out[0][1] == pytest.approx(ref0.mean)
        assert out[0][2] == pytest.approx(ref0.m2, rel=1e-5)
        assert out[1][0] == 1 and out[1][1] == 100.0
        assert out[3][0] == 0  # untouched segment

    def test_segment_moments_mask(self):
        values = jnp.asarray([10.0, 999.0, 20.0])
        seg = jnp.asarray([0, 0, 0])
        valid = jnp.asarray([True, False, True])
        out = np.asarray(M.segment_moments(values, seg, 1, valid=valid))
        assert out[0][0] == 2
        assert out[0][1] == pytest.approx(15.0)


class TestCountMin:
    def test_exact_when_sparse(self):
        keys = np.arange(100, dtype=np.int64) * 7919
        hi, lo = split64(keys)
        sk = cms.init(depth=4, width=1 << 12)
        sk = jax.jit(cms.update)(sk, hi, lo)
        est = np.asarray(cms.query(sk, jnp.asarray(hi), jnp.asarray(lo)))
        np.testing.assert_array_equal(est, np.ones(100))

    def test_never_underestimates(self):
        rng = np.random.default_rng(1)
        keys = rng.integers(-(2**62), 2**62, size=5000, dtype=np.int64)
        true = {}
        for k in keys:
            true[k] = true.get(k, 0) + 1
        hi, lo = split64(keys)
        sk = cms.update(cms.init(depth=4, width=1 << 10), hi, lo)
        uniq = np.array(list(true), np.int64)
        uh, ul = split64(uniq)
        est = np.asarray(cms.query(sk, uh, ul))
        want = np.array([true[k] for k in uniq])
        assert (est >= want).all()
        # CMS guarantee: err <= e*N/width with prob 1-e^-depth; check mean err
        assert (est - want).mean() < np.e * len(keys) / (1 << 10)

    def test_weights_and_merge(self):
        keys = np.array([42, 43], np.int64)
        hi, lo = split64(keys)
        a = cms.update(cms.init(), hi, lo, weights=jnp.asarray([5, 3]))
        b = cms.update(cms.init(), hi, lo, weights=jnp.asarray([1, 2]))
        m = cms.merge(a, b)
        np.testing.assert_array_equal(np.asarray(cms.query(m, hi, lo)), [6, 5])
        assert int(cms.total(m)) == 11

    def test_duplicate_keys_in_batch(self):
        keys = np.array([7, 7, 7, 9], np.int64)
        hi, lo = split64(keys)
        sk = cms.update(cms.init(), hi, lo)
        est = np.asarray(cms.query(sk, *split64(np.array([7, 9], np.int64))))
        np.testing.assert_array_equal(est, [3, 1])


class TestHLL:
    @pytest.mark.parametrize("n", [100, 10_000, 200_000])
    def test_cardinality_within_error(self, n):
        keys = np.arange(n, dtype=np.int64) * 2654435761 + 17
        hi, lo = split64(keys)
        sk = jax.jit(hll.update)(hll.init(), hi, lo)
        est = float(hll.estimate(sk))
        # 1.04/sqrt(2^14) ~ 0.8%; allow 4 sigma
        assert abs(est - n) / n < 0.033

    def test_duplicates_do_not_inflate(self):
        keys = np.tile(np.arange(1000, dtype=np.int64), 50)
        hi, lo = split64(keys)
        sk = hll.update(hll.init(), hi, lo)
        assert abs(float(hll.estimate(sk)) - 1000) / 1000 < 0.05

    def test_merge_is_union(self):
        a_keys = np.arange(0, 30_000, dtype=np.int64)
        b_keys = np.arange(15_000, 45_000, dtype=np.int64)  # 50% overlap
        a = hll.update(hll.init(), *split64(a_keys))
        b = hll.update(hll.init(), *split64(b_keys))
        est = float(hll.estimate(hll.merge(a, b)))
        assert abs(est - 45_000) / 45_000 < 0.033

    def test_empty(self):
        assert float(hll.estimate(hll.init())) == 0.0


class TestLogHistogram:
    def test_relative_error_guarantee(self):
        rng = np.random.default_rng(2)
        xs = rng.lognormal(mean=9.0, sigma=1.5, size=50_000).astype(np.float32)
        sk = jax.jit(Q.update)(Q.init(alpha=0.01), jnp.asarray(xs))
        for q in (0.5, 0.95, 0.99):
            got = float(Q.quantile(sk, q))
            want = float(np.quantile(xs, q))
            assert abs(got - want) / want < 0.021  # 2*alpha margin

    def test_grouped_update(self):
        sk = Q.init(shape=(3,))
        values = jnp.asarray([100.0, 200.0, 100.0, 1e6])
        groups = jnp.asarray([0, 0, 1, 2])
        sk = jax.jit(Q.update_grouped)(sk, groups, values)
        counts = np.asarray(Q.count(sk))
        np.testing.assert_array_equal(counts, [2, 1, 1])
        assert float(Q.quantile(sk, 0.5)[2]) == pytest.approx(1e6, rel=0.02)

    def test_merge(self):
        a = Q.update(Q.init(), jnp.asarray([10.0] * 100))
        b = Q.update(Q.init(), jnp.asarray([1000.0] * 100))
        m = Q.merge(a, b)
        assert float(Q.count(m)) == 200
        assert float(Q.quantile(m, 0.99)) == pytest.approx(1000.0, rel=0.02)

    def test_empty_is_nan(self):
        assert np.isnan(float(Q.quantile(Q.init(), 0.5)))

    def test_valid_mask(self):
        sk = Q.update(
            Q.init(), jnp.asarray([10.0, 1e9]), valid=jnp.asarray([True, False])
        )
        assert float(Q.count(sk)) == 1


class TestTopK:
    def test_exact_topk(self):
        state = topk.init(100)
        ids = jnp.asarray([5, 5, 5, 9, 9, 3])
        state = jax.jit(topk.update)(state, ids)
        vals, got = topk.top_k(state, 2)
        np.testing.assert_array_equal(np.asarray(got), [5, 9])
        np.testing.assert_array_equal(np.asarray(vals), [3, 2])

    def test_out_of_range_and_invalid_dropped(self):
        state = topk.init(4)
        state = topk.update(
            state,
            jnp.asarray([0, 7, -1, 2, 2]),
            valid=jnp.asarray([True, True, True, True, False]),
        )
        np.testing.assert_array_equal(np.asarray(state.counts), [1, 0, 1, 0])

    def test_weighted_merge(self):
        a = topk.update(topk.init(8), jnp.asarray([1]), weights=jnp.asarray([10.0]))
        b = topk.update(topk.init(8), jnp.asarray([1, 2]), weights=jnp.asarray([5.0, 99.0]))
        m = topk.merge(a, b)
        vals, ids = topk.top_k(m, 2)
        np.testing.assert_array_equal(np.asarray(ids), [2, 1])
        np.testing.assert_array_equal(np.asarray(vals), [99.0, 15.0])

    def test_topk_from_cms(self):
        keys = np.array([11, 22, 33], np.int64)
        hi, lo = split64(keys)
        sk = cms.update(cms.init(), hi, lo, weights=jnp.asarray([5, 50, 2]))
        vals, pos = topk.topk_from_cms(sk, jnp.asarray(hi), jnp.asarray(lo), 2)
        assert int(pos[0]) == 1 and int(vals[0]) == 50
