"""Windowed Moments-sketch arena (aggregate/windows.py + the r13
device/mirror/query vertical): cell-sum exactness vs a memory oracle,
solver rank tolerance, bucket-boundary and ragged windows,
epoch-stamped ring wrap, adopt_state resync, the pre-rev-14 checkpoint
compat path, and the API JSON surface."""

import json
import os

import numpy as np
import pytest

from zipkin_tpu.aggregate import windows as win
from zipkin_tpu.models.span import (
    Annotation,
    BinaryAnnotation,
    Endpoint,
    Span,
)
from zipkin_tpu.store.device import StoreConfig
from zipkin_tpu.store.tpu import TpuSpanStore

BASE_US = 1_700_000_000_000_000
BUCKET_S = 60
BUCKET_US = BUCKET_S * 1_000_000

EPS = [Endpoint(0x0A000001 + i, 80, f"svc{i}") for i in range(4)]


def _cfg(**kw):
    base = dict(
        capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
        max_services=32, max_span_names=64, max_annotation_values=128,
        max_binary_keys=32, cms_width=1 << 10, hll_p=8,
        quantile_buckets=512, window_seconds=BUCKET_S,
        window_buckets=8,
    )
    base.update(kw)
    return StoreConfig(**base)


def _span(i, ep, ts, dur, error=False, error_key=False):
    anns = [Annotation(ts, "sr", ep), Annotation(ts + dur, "ss", ep)]
    banns = []
    if error:
        anns.append(Annotation(ts + 1, "error", ep))
    if error_key:
        banns.append(BinaryAnnotation("error", b"true", 6, ep))
    return Span(i // 3 + 1, f"op{i % 5}", i + 1, None, tuple(anns),
                tuple(banns))


def _gen_spans(n=400, seed=0, buckets=6, services=4):
    rng = np.random.default_rng(seed)
    spans = []
    for i in range(n):
        ep = EPS[i % services]
        ts = BASE_US + int(rng.integers(0, buckets * BUCKET_US))
        dur = int(rng.lognormal(7.0, 1.4)) + 1
        spans.append(_span(i, ep, ts, dur, error=(i % 10 == 0),
                           error_key=(i % 17 == 3)))
    return spans


def _oracle_cells(store, spans):
    """Independent per-(service, final-live-bucket) cell sums from the
    raw span objects, using the same quantization (the final arena
    state is order-independent: a slot holds exactly the rows of its
    max-ever bucket)."""
    c = store.config
    gamma = store.sketch_mirror.gamma
    W = c.win_slots
    rows = []
    for s in spans:
        svc_name = s.service_name
        ts = s.first_timestamp
        if svc_name is None or ts is None:
            continue
        svc = store.dicts.services.get(svc_name.lower())
        if svc is None or svc >= c.max_services:
            continue
        err = (any(a.value == "error" for a in s.annotations)
               or any(b.key == "error" for b in s.binary_annotations))
        rows.append((svc, ts // (c.window_us), s.duration, err))
    final_epoch = {}
    for svc, b, dur, err in rows:
        w = b % W
        final_epoch[w] = max(final_epoch.get(w, -1), b)
    cells = {}
    for svc, b, dur, err in rows:
        if final_epoch[b % W] != b:
            continue  # overwritten by a newer bucket on the same slot
        cell = cells.setdefault(
            (svc, b), {"total": 0, "err": 0, "n": 0,
                       "s": [0, 0, 0, 0], "xs": []})
        cell["total"] += 1
        cell["err"] += int(err)
        if dur is not None and dur >= 0:
            x = int(win.duration_x(
                np.array([dur]), c.quantile_buckets, gamma)[0])
            cell["n"] += 1
            for k in range(4):
                cell["s"][k] += x ** (k + 1)
            cell["xs"].append(x)
    return cells, final_epoch


class TestCellExactness:
    def test_cells_match_memory_oracle_bitwise(self):
        store = TpuSpanStore(_cfg())
        spans = _gen_spans()
        store.apply(spans)
        m = store.sketch_mirror
        cells, final_epoch = _oracle_cells(store, spans)
        W = store.config.win_slots
        # Epoch stamps.
        for w in range(W):
            assert int(m.win_epoch[w]) == final_epoch.get(w, -1)
        # Every oracle cell matches the mirror cell EXACTLY (integer
        # sums — the Moments-sketch merge invariant), and occupied
        # mirror cells are exactly the oracle's.
        occupied = {
            (svc, int(m.win_epoch[w]))
            for svc in range(store.config.max_services)
            for w in range(W)
            if m.win_counts[svc, w, 0] > 0
        }
        assert occupied == set(cells)
        for (svc, b), want in cells.items():
            w = b % W
            assert list(m.win_counts[svc, w]) == [
                want["total"], want["err"], want["n"]]
            assert list(m.win_sums[svc, w]) == want["s"]
            if want["n"]:
                assert -int(m.win_mm[svc, w, 0]) == min(want["xs"])
                assert int(m.win_mm[svc, w, 1]) == max(want["xs"])

    def test_mirror_matches_device_bitwise(self):
        import jax

        store = TpuSpanStore(_cfg())
        store.apply(_gen_spans(seed=3))
        m = store.sketch_mirror
        st = store.state
        dev_arrays = jax.device_get(
            (st.win_epoch, st.win_counts, st.win_sums, st.win_mm))
        for got, want in zip(
                (m.win_epoch, m.win_counts, m.win_sums, m.win_mm),
                dev_arrays):
            np.testing.assert_array_equal(got, want)

    def test_error_flags_both_conventions(self):
        # One pad-512 apply (the file's shared launch shape): spans
        # 0..39 carry the "error" ANNOTATION VALUE, 40..69 the "error"
        # BINARY KEY, the rest are clean — both zipkin conventions
        # count, nothing else does.
        store = TpuSpanStore(_cfg())
        spans = [
            _span(3 * i, EPS[0], BASE_US + i, 100,
                  error=(i < 40), error_key=(40 <= i < 70))
            for i in range(400)
        ]
        store.apply(spans)
        burn = store.slo_burn("svc0", windows_s=[3600],
                              now_us=BASE_US + BUCKET_US)
        assert burn["windows"][0]["total"] == 400
        assert burn["windows"][0]["errors"] == 70


class TestSolver:
    def test_windowed_quantile_rank_tolerance(self):
        """The documented solver gate: the maxent estimate's rank in
        the TRUE duration distribution is within SOLVER_RANK_TOL of
        the requested q (the Moments-sketch paper's metric)."""
        # n=400 shares the pad-512 launch shape every other test in
        # this file compiles — tier-1 pays ONE ingest compile here.
        store = TpuSpanStore(_cfg())
        spans = _gen_spans(n=400, seed=7)
        store.apply(spans)
        durs = np.sort([
            s.duration for s in spans
            if (s.service_name or "").lower() == "svc1"
            and s.duration is not None
        ])
        for q in (0.5, 0.9, 0.99):
            est = store.windowed_quantiles("svc1", [q])
            assert est is not None
            rank = np.searchsorted(durs, est[0]) / max(len(durs) - 1, 1)
            assert abs(rank - q) <= win.SOLVER_RANK_TOL, (q, est, rank)

    def test_point_mass_and_empty_cells(self):
        store = TpuSpanStore(_cfg())
        assert store.windowed_quantiles("svc0", [0.5]) is None
        store.apply([_span(i, EPS[0], BASE_US + i, 5000)
                     for i in range(10)])
        est = store.windowed_quantiles("svc0", [0.5, 0.99])
        gamma = store.sketch_mirror.gamma
        # All durations in one coarse bucket → both quantiles at its
        # midpoint, within the bucket's relative width.
        assert est[0] == est[1]
        assert abs(np.log(est[0] / 5000.0)) <= 2 * np.log(gamma) * (
            1 << store.config.win_x_shift)


class TestWindows:
    def test_ragged_and_boundary_windows_match_oracle_counts(self):
        """Bucket-boundary spans (ts exactly at k·bucket and k·bucket-1)
        and ragged [start, end) extents: windowed totals equal the
        oracle's whole-bucket expansion."""
        store = TpuSpanStore(_cfg())
        # Bucket-ALIGNED base so off = BUCKET_US - 1 stays in bucket b.
        # 10 spans per (bucket, boundary offset) × (b+1) weights = 300
        # spans → the file's shared pad-512 launch shape.
        base = (BASE_US // BUCKET_US) * BUCKET_US
        spans = []
        i = 0
        for b in range(4):
            for off in (0, 1, BUCKET_US - 1):
                for _ in range(10 * (b + 1)):
                    spans.append(_span(
                        i, EPS[0], base + b * BUCKET_US + off, 100))
                    i += 1
        store.apply(spans)
        b0 = base // BUCKET_US
        m = store.sketch_mirror
        epoch, counts, sums, mm = m.window_row(
            store.dicts.services.get("svc0"))
        for lo_b, hi_b in ((0, 0), (0, 3), (1, 2), (2, 3), (3, 3)):
            ws = win.merge_cells(epoch, counts, sums, mm,
                                 b0 + lo_b, b0 + hi_b)
            want = sum(30 * (b + 1) for b in range(lo_b, hi_b + 1))
            assert ws.total == want, (lo_b, hi_b)
            # Ragged µs extents snap to whole buckets: any sub-bucket
            # offset inside the same bucket span answers identically.
            est = store.windowed_quantiles(
                "svc0", [0.5],
                start_us=base + lo_b * BUCKET_US + 123,
                end_us=base + hi_b * BUCKET_US + BUCKET_US - 7)
            est2 = store.windowed_quantiles(
                "svc0", [0.5],
                start_us=base + lo_b * BUCKET_US,
                end_us=base + (hi_b + 1) * BUCKET_US)
            assert est == est2

    def test_epoch_ring_wrap_reuses_stale_cells(self):
        """Writing W + k distinct buckets wraps the ring: wrapped slots
        self-clear (epoch advances, old cell content gone), totals
        reflect only live buckets, and a late span for an overwritten
        bucket is dropped — mirror and device agreeing bitwise."""
        import jax

        store = TpuSpanStore(_cfg(window_buckets=4))
        W = 4
        for b in range(W + 3):  # buckets 0..6; slots 0..2 wrapped
            store.apply([
                _span(10 * b + j, EPS[0],
                      BASE_US + b * BUCKET_US + j, 1000 * (b + 1))
                for j in range(b + 1)
            ])
        m = store.sketch_mirror
        svc = store.dicts.services.get("svc0")
        base_b = BASE_US // BUCKET_US
        live = {int(e) - base_b for e in m.win_epoch if e >= 0}
        assert live == {3, 4, 5, 6}
        epoch, counts, sums, mm = m.window_row(svc)
        for b in (3, 4, 5, 6):
            ws = win.merge_cells(epoch, counts, sums, mm,
                                 base_b + b, base_b + b)
            assert ws.total == b + 1
        # A late write for overwritten bucket 0 must be dropped.
        before = counts.copy()
        store.apply([_span(999, EPS[0], BASE_US + 5, 777)])
        epoch2, counts2, _, _ = m.window_row(svc)
        np.testing.assert_array_equal(counts2, before)
        np.testing.assert_array_equal(epoch2, epoch)
        st = store.state
        got = jax.device_get(
            (st.win_epoch, st.win_counts, st.win_sums, st.win_mm))
        np.testing.assert_array_equal(got[0], m.win_epoch)
        np.testing.assert_array_equal(got[1], m.win_counts)
        np.testing.assert_array_equal(got[2], m.win_sums)
        np.testing.assert_array_equal(got[3], m.win_mm)

    def test_window_ring_wrap_deep_sweep(self):
        """Slow lane: many laps over a small ring with varying batch
        sizes and cross-bucket batches, re-gating bitwise mirror
        identity and the live-set invariant each lap."""
        import jax

        store = TpuSpanStore(_cfg(window_buckets=4))
        rng = np.random.default_rng(11)
        i = 0
        for lap in range(12):
            spans = []
            for _ in range(int(rng.integers(5, 40))):
                b = lap * 2 + int(rng.integers(0, 3))
                spans.append(_span(
                    i, EPS[i % 4],
                    BASE_US + b * BUCKET_US + int(rng.integers(
                        0, BUCKET_US)),
                    int(rng.lognormal(6, 1)) + 1,
                    error=bool(rng.integers(0, 2))))
                i += 1
            store.apply(spans)
            m = store.sketch_mirror
            st = store.state
            got = jax.device_get(
                (st.win_epoch, st.win_counts, st.win_sums, st.win_mm))
            np.testing.assert_array_equal(got[0], m.win_epoch)
            np.testing.assert_array_equal(got[1], m.win_counts)
            np.testing.assert_array_equal(got[2], m.win_sums)
            np.testing.assert_array_equal(got[3], m.win_mm)


class TestBurnAndHeatmap:
    def test_slo_burn_matches_memory_oracle(self):
        from zipkin_tpu.store.memory import InMemorySpanStore

        store = TpuSpanStore(_cfg())
        oracle = InMemorySpanStore()
        spans = _gen_spans(n=300, seed=5, buckets=4)
        store.apply(spans)
        oracle.apply(spans)
        # Bucket-aligned now: the sketch's whole-bucket windows then
        # cover exactly the oracle's span-level [now - w, now).
        now = (max(s.first_timestamp for s in spans) // BUCKET_US + 1
               ) * BUCKET_US
        for svc in ("svc0", "svc2"):
            got = store.slo_burn(svc, objective=0.99,
                                 windows_s=[60, 180, 3600], now_us=now)
            want = oracle.slo_burn(svc, objective=0.99,
                                   windows_s=[60, 180, 3600],
                                   now_us=now)
            assert got["windows"] == want["windows"], svc

    def test_heatmap_grid_shape_and_mass(self):
        store = TpuSpanStore(_cfg())
        spans = _gen_spans(n=300, seed=9, buckets=5)
        store.apply(spans)
        hm = store.latency_heatmap("svc1", bands=8)
        n_cols = len(hm["bucketStartsTs"])
        assert n_cols == len(hm["cells"]) == len(hm["totals"])
        assert len(hm["bandEdgesMicros"]) == len(hm["cells"][0]) + 1
        assert hm["bucketStartsTs"] == sorted(hm["bucketStartsTs"])
        edges = hm["bandEdgesMicros"]
        assert edges == sorted(edges)
        # Per-column solver mass re-normalizes to the cell's duration
        # count (within float rounding of the pmf).
        m = store.sketch_mirror
        svc = store.dicts.services.get("svc1")
        epoch, counts, _, _ = m.window_row(svc)
        for col, ts0 in zip(hm["cells"], hm["bucketStartsTs"]):
            b = ts0 // BUCKET_US
            w = int(np.flatnonzero(epoch == b)[0])
            assert abs(sum(col) - counts[w, 2]) <= 0.51


class TestLifecycle:
    def test_mirror_resync_after_adopt_state(self):
        src = TpuSpanStore(_cfg())
        spans = _gen_spans(n=200, seed=13)
        src.apply(spans)
        # The adopting store shares the codec: adoption moves device
        # state, not dictionaries (the bench streaming pattern).
        dst = TpuSpanStore(_cfg(), codec=src.codec)
        dst.adopt_state(src.state, spans_written=len(spans))
        assert not dst.sketch_mirror.warm
        # First windowed read resyncs the window twins with the other
        # aggregates, exactly equal to the source mirror's cells.
        got = dst.windowed_quantiles("svc1", [0.5, 0.99])
        want = src.windowed_quantiles("svc1", [0.5, 0.99])
        assert got == want
        for a, b in zip(dst.sketch_mirror.window_arrays(),
                        src.sketch_mirror.window_arrays()):
            np.testing.assert_array_equal(a, b)

    def test_window_disabled_store_still_serves(self):
        store = TpuSpanStore(_cfg(window_seconds=0))
        store.apply(_gen_spans(n=60))
        assert store.windowed_quantiles("svc0", [0.5]) is None
        assert store.slo_burn("svc0") is None
        assert store.latency_heatmap("svc0") is None
        # Lifetime quantiles still serve.
        assert store.service_duration_quantiles("svc0", [0.5])

    def test_rev14_checkpoint_and_wal_replay_carry_cells(
            self, tmp_path):
        """The ISSUE acceptance ride: window cells survive a rev-14
        checkpoint + WAL tail replay BITWISE — the recovered arena
        (device leaves AND resynced mirror twins) equals an uncrashed
        oracle's, and windowed answers match."""
        from zipkin_tpu import checkpoint
        from zipkin_tpu.testing.crash import states_bitwise_equal
        from zipkin_tpu.wal import WriteAheadLog, recover

        spans = _gen_spans(n=400, seed=29)
        oracle = TpuSpanStore(_cfg())
        oracle.apply(spans[:200])
        oracle.apply(spans[200:])

        store = TpuSpanStore(_cfg())
        wal = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
        store.attach_wal(wal)
        store.apply(spans[:200])
        checkpoint.save(store, str(tmp_path / "ckpt"))  # rev 14 leaves
        store.apply(spans[200:])  # the replayed tail
        wal.sync()
        del store  # crash: HBM gone, snapshot + log survive

        wal2 = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
        rec, _ = recover(str(tmp_path / "ckpt"), wal2)
        try:
            assert states_bitwise_equal(oracle.state, rec.state)
            m = rec.ensure_sketch_mirror()
            for a, b in zip(m.window_arrays(),
                            oracle.sketch_mirror.window_arrays()):
                np.testing.assert_array_equal(a, b)
            assert (rec.windowed_quantiles("svc1", [0.5, 0.99])
                    == oracle.windowed_quantiles("svc1", [0.5, 0.99]))
            burn_r = rec.slo_burn("svc1", objective=0.99)
            burn_o = oracle.slo_burn("svc1", objective=0.99)
            assert burn_r == burn_o
        finally:
            wal2.close()

    def test_pre_rev14_checkpoint_restores_empty_arena(self, tmp_path):
        """Compat: a snapshot written before revision 14 (no win_*
        leaves, no window config keys) restores with an EMPTY arena at
        the daemon's flag geometry (checkpoint.load config_defaults —
        meta keys always win, absent keys fill from the flags), and
        post-restore ingest populates it."""
        from zipkin_tpu import checkpoint

        store = TpuSpanStore(_cfg())
        store.apply(_gen_spans(n=120, seed=21))
        path = os.path.join(str(tmp_path), "ckpt")
        checkpoint.save(store, path)
        # Doctor the snapshot into pre-14 shape.
        state_file = os.path.join(path, "state.npz")
        data = dict(np.load(state_file))
        for k in list(data):
            if k.startswith("win_"):
                del data[k]
        np.savez(state_file, **data)
        meta_file = os.path.join(path, "meta.json")
        with open(meta_file) as f:
            meta = json.load(f)
        meta["revision"] = 13
        for k in ("window_seconds", "window_buckets"):
            meta["config"].pop(k, None)
        meta["slab_crc32"] = {
            k: v for k, v in (meta.get("slab_crc32") or {}).items()
            if not k.startswith("win_")
        }
        with open(meta_file, "w") as f:
            json.dump(meta, f)
        # The daemon restore path: flag geometry fills the missing
        # window keys; without defaults the arena stays disabled (the
        # snapshot's config governs).
        plain = checkpoint.load(path)
        try:
            assert not plain.config.window_enabled
            assert plain.windowed_quantiles("svc0", [0.5]) is None
        finally:
            plain.close()
        restored = checkpoint.load(path, config_defaults={
            "window_seconds": BUCKET_S, "window_buckets": 8})
        try:
            assert restored.config.window_enabled
            m = restored.ensure_sketch_mirror()
            assert (m.win_epoch == -1).all()
            assert not m.win_counts.any()
            # Lifetime aggregates survived; the arena only covers
            # post-restore ingest.
            assert restored.windowed_quantiles("svc0", [0.5]) is None
            assert restored.service_duration_quantiles("svc0", [0.5])
            restored.apply(_gen_spans(n=30, seed=22))
            assert restored.windowed_quantiles("svc0", [0.5])
        finally:
            restored.close()


class TestQuerySurface:
    def test_engine_and_api_routes(self):
        from zipkin_tpu.api.server import ApiServer
        from zipkin_tpu.query.service import QueryService

        store = TpuSpanStore(_cfg())
        store.apply(_gen_spans(n=200, seed=17))
        q = QueryService(store)
        try:
            api = ApiServer(q, collector=None)
            code, body = api.handle("GET", "/api/windowed_quantiles", {
                "serviceName": "svc1", "q": "0.5,0.99"})
            assert code == 200 and body["durationsMicro"] is not None
            json.dumps(body)
            code, body = api.handle("GET", "/api/slo_burn", {
                "serviceName": "svc1", "objective": "0.99",
                "windows": "60,3600"})
            assert code == 200
            assert [w["windowSeconds"] for w in body["windows"]] == [
                60, 3600]
            json.dumps(body)
            code, body = api.handle("GET", "/api/latency_heatmap", {
                "serviceName": "svc1", "bands": "6"})
            assert code == 200 and body["cells"]
            json.dumps(body)
            # Geometry echoed at /vars, read-only.
            code, body = api.handle("GET", "/vars/windowSeconds", {})
            assert (code, body) == (200, {"windowSeconds": BUCKET_S})
            code, body = api.handle("GET", "/vars/windowBuckets", {})
            assert (code, body) == (200, {"windowBuckets": 8})
            code, _ = api.handle("POST", "/vars/windowSeconds", {},
                                 b"30")
            assert code == 400
            # Unknown service answers null, not 500.
            code, body = api.handle("GET", "/api/windowed_quantiles", {
                "serviceName": "nosuch"})
            assert (code, body["durationsMicro"]) == (200, None)
        finally:
            q.close()

    def test_memory_store_exact_scan_parity(self):
        from zipkin_tpu.api.server import ApiServer
        from zipkin_tpu.query.service import QueryService
        from zipkin_tpu.store.memory import InMemorySpanStore

        store = InMemorySpanStore()
        spans = _gen_spans(n=100, seed=19)
        store.apply(spans)
        q = QueryService(store)
        try:
            api = ApiServer(q, collector=None)
            code, body = api.handle("GET", "/api/windowed_quantiles", {
                "serviceName": "svc0"})
            assert code == 200 and body["durationsMicro"] is not None
            code, body = api.handle("GET", "/api/slo_burn", {
                "serviceName": "svc0"})
            assert code == 200 and body["windows"]
            code, body = api.handle("GET", "/api/latency_heatmap", {
                "serviceName": "svc0"})
            assert code == 200 and body["cells"]
        finally:
            q.close()

    def test_sketch_tier_counts_and_window_sketch(self):
        """Windowed reads are sketch-tier: they bump the sketch-answer
        counter and the zipkin_window_query_seconds family, never the
        dispatch sketch."""
        from zipkin_tpu import obs
        from zipkin_tpu.query.engine import QueryEngine

        store = TpuSpanStore(_cfg())
        store.apply(_gen_spans(n=200, seed=23))
        reg = obs.Registry()
        eng = QueryEngine(store, registry=reg)
        try:
            before = eng.c_sketch.value
            eng.windowed_quantiles("svc0", [0.5])
            eng.slo_burn("svc0")
            eng.latency_heatmap("svc0")
            assert eng.c_sketch.value == before + 3
            fam = reg.get("zipkin_window_query_seconds")
            text = reg.render_text()
            assert fam is not None
            assert 'endpoint="windowed_quantiles"' in text
            assert 'endpoint="slo_burn"' in text
            assert 'endpoint="latency_heatmap"' in text
        finally:
            eng.close()
