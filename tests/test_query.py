"""Query service + TimeSkewAdjuster tests.

Golden skew scenarios follow the reference's TimeSkewAdjusterSpec
pattern: multi-service traces with known clock offsets must come back
causally ordered. Runs against both the in-memory store and the TPU
store (the query layer is store-agnostic).
"""

import pytest

from zipkin_tpu.models.span import Annotation, BinaryAnnotation, Endpoint, Span
from zipkin_tpu.query import (
    BinaryAnnotationQuery,
    Order,
    QueryException,
    QueryRequest,
    QueryResponse,
    QueryService,
    TimeSkewAdjuster,
)
from zipkin_tpu.models.trace import Trace
from zipkin_tpu.store.device import StoreConfig
from zipkin_tpu.store.memory import InMemorySpanStore
from zipkin_tpu.store.tpu import TpuSpanStore

WEB = Endpoint(0x01010101, 80, "web")
API = Endpoint(0x02020202, 80, "api")
DB = Endpoint(0x03030303, 80, "db")

SMALL = StoreConfig(
    capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
    max_services=32, max_span_names=128, max_annotation_values=256,
    max_binary_keys=64, cms_width=1 << 10, hll_p=8, quantile_buckets=512,
)


def rpc(tid, sid, parent, client_ep, server_ep, cs, sr, ss, cr, name="call",
        extra_ann=None, bann=None):
    anns = [
        Annotation(cs, "cs", client_ep),
        Annotation(sr, "sr", server_ep),
        Annotation(ss, "ss", server_ep),
        Annotation(cr, "cr", client_ep),
    ]
    if extra_ann:
        anns.append(extra_ann)
    return Span(tid, name, sid, parent, tuple(anns), tuple(bann or ()))


STORES = [
    ("memory", InMemorySpanStore),
    ("tpu", lambda: TpuSpanStore(SMALL)),
]


@pytest.mark.parametrize("label,factory", STORES)
class TestGetTraceIds:
    def load(self, factory):
        store = factory()
        # trace 1: web->api with annotation "boom" + binary {k: v1}
        store.apply([
            rpc(1, 10, None, WEB, API, 100, 110, 190, 200, name="index",
                extra_ann=Annotation(150, "boom", API),
                bann=[BinaryAnnotation("k", b"v1", host=API)]),
        ])
        # trace 2: web->api, later, no custom annotation
        store.apply([
            rpc(2, 10, None, WEB, API, 1100, 1110, 1190, 1200, name="index"),
        ])
        # trace 3: different span name
        store.apply([
            rpc(3, 10, None, WEB, API, 2100, 2110, 2190, 2200, name="other"),
        ])
        return QueryService(store)

    def test_no_slices_by_service(self, label, factory):
        svc = self.load(factory)
        resp = svc.get_trace_ids(QueryRequest("api", limit=10))
        assert set(resp.trace_ids) == {1, 2, 3}

    def test_span_name_slice(self, label, factory):
        svc = self.load(factory)
        resp = svc.get_trace_ids(QueryRequest("api", span_name="index"))
        assert set(resp.trace_ids) == {1, 2}

    def test_annotation_slice(self, label, factory):
        svc = self.load(factory)
        resp = svc.get_trace_ids(QueryRequest("api", annotations=("boom",)))
        assert resp.trace_ids == (1,)

    def test_binary_annotation_slice(self, label, factory):
        svc = self.load(factory)
        resp = svc.get_trace_ids(QueryRequest(
            "api", binary_annotations=(BinaryAnnotationQuery("k", b"v1"),)
        ))
        assert resp.trace_ids == (1,)

    def test_multi_slice_intersection(self, label, factory):
        svc = self.load(factory)
        # span name "index" AND annotation "boom" → only trace 1.
        resp = svc.get_trace_ids(QueryRequest(
            "api", span_name="index", annotations=("boom",)
        ))
        assert resp.trace_ids == (1,)

    def test_multi_slice_no_common(self, label, factory):
        svc = self.load(factory)
        resp = svc.get_trace_ids(QueryRequest(
            "api", span_name="other", annotations=("boom",)
        ))
        assert resp.trace_ids == ()

    def test_order_timestamp_desc(self, label, factory):
        svc = self.load(factory)
        resp = svc.get_trace_ids(QueryRequest(
            "api", order=Order.TIMESTAMP_DESC
        ))
        assert resp.trace_ids == (3, 2, 1)

    def test_order_duration_desc(self, label, factory):
        store = factory()
        store.apply([rpc(1, 10, None, WEB, API, 100, 110, 120, 400)])  # 300
        store.apply([rpc(2, 10, None, WEB, API, 100, 110, 120, 200)])  # 100
        store.apply([rpc(3, 10, None, WEB, API, 100, 110, 120, 900)])  # 800
        svc = QueryService(store)
        resp = svc.get_trace_ids(QueryRequest("api", order=Order.DURATION_DESC))
        assert resp.trace_ids == (3, 1, 2)

    def test_limit(self, label, factory):
        svc = self.load(factory)
        resp = svc.get_trace_ids(QueryRequest(
            "api", limit=2, order=Order.TIMESTAMP_DESC
        ))
        assert resp.trace_ids == (3, 2)

    def test_end_ts_pagination(self, label, factory):
        svc = self.load(factory)
        resp = svc.get_trace_ids(QueryRequest(
            "api", end_ts=1500, order=Order.TIMESTAMP_DESC
        ))
        assert resp.trace_ids == (2, 1)

    def test_missing_service_raises(self, label, factory):
        svc = self.load(factory)
        with pytest.raises(QueryException):
            svc.get_trace_ids(QueryRequest(""))

    def test_trace_fetch_and_summaries(self, label, factory):
        svc = self.load(factory)
        traces = svc.get_traces_by_ids([1])
        assert len(traces) == 1
        summaries = svc.get_trace_summaries_by_ids([1])
        assert summaries and summaries[0].trace_id == 1
        combos = svc.get_trace_combos_by_ids([1])
        assert combos[0].summary is not None


class TestTimeSkewAdjuster:
    def test_skewed_server_comes_back_inside_client_interval(self):
        # Server clock 1000 ahead: sr/ss stamped +1000.
        span = rpc(1, 1, None, WEB, API,
                   cs=100, sr=1150 , ss=1180, cr=200)
        t = TimeSkewAdjuster().adjust(Trace([span]))
        ann = t.spans[0].annotations_as_map()
        assert 100 <= ann["sr"].timestamp <= ann["ss"].timestamp <= 200
        # Client annotations untouched.
        assert ann["cs"].timestamp == 100 and ann["cr"].timestamp == 200

    def test_well_ordered_trace_untouched(self):
        span = rpc(1, 1, None, WEB, API, cs=100, sr=110, ss=180, cr=200)
        t = TimeSkewAdjuster().adjust(Trace([span]))
        assert t.spans[0] == span

    def test_skew_propagates_to_children(self):
        # api's clock is +10000 vs web. Both the api server half of the
        # root and api's client half of the child carry the offset.
        root = rpc(1, 1, None, WEB, API, cs=100, sr=10150, ss=10180, cr=300)
        child = rpc(1, 2, 1, API, DB, cs=10160, sr=10165, ss=10170, cr=10175)
        t = TimeSkewAdjuster().adjust(Trace([root, child]))
        spans = {s.id: s for s in t.spans}
        root_ann = spans[1].annotations_as_map()
        child_ann = spans[2].annotations_as_map()
        # Causality restored: child runs inside the root's server window.
        assert root_ann["sr"].timestamp >= root_ann["cs"].timestamp
        assert child_ann["cs"].timestamp >= root_ann["sr"].timestamp
        assert child_ann["cr"].timestamp <= root_ann["ss"].timestamp + 1

    def test_server_longer_than_client_not_adjusted(self):
        span = rpc(1, 1, None, WEB, API, cs=100, sr=90, ss=250, cr=200)
        t = TimeSkewAdjuster().adjust(Trace([span]))
        assert t.spans[0] == span

    def test_client_only_span_gets_synthetic_server_half(self):
        parent = Span(1, "p", 1, None, (
            Annotation(100, "cs", WEB), Annotation(200, "cr", WEB),
        ))
        child = rpc(1, 2, 1, API, DB, cs=120, sr=130, ss=150, cr=160)
        adj = TimeSkewAdjuster()
        t = adj.adjust(Trace([parent, child]))
        spans = {s.id: s for s in t.spans}
        ann = spans[1].annotations_as_map()
        assert "sr" in ann and "ss" in ann
        assert ann["sr"].timestamp == 100 and ann["ss"].timestamp == 200
        assert "TIME_SKEW_ADD_SERVER_RECV" in adj.warnings

    def test_malformed_trace_without_root_passes_through(self):
        orphan = Span(1, "x", 5, parent_id=99,
                      annotations=(Annotation(1, "cs", WEB),))
        t = TimeSkewAdjuster().adjust(Trace([orphan]))
        assert list(t.spans) == [orphan]


class TestQueryServiceAggregates:
    def test_dependencies_null_for_memory_store(self):
        svc = QueryService(InMemorySpanStore())
        deps = svc.get_dependencies()
        assert deps.links == ()

    def test_dependencies_from_tpu_store(self):
        store = TpuSpanStore(SMALL)
        store.apply([
            rpc(1, 1, None, WEB, API, 100, 110, 190, 200),
            rpc(1, 2, 1, API, DB, 120, 125, 170, 180),
        ])
        svc = QueryService(store)
        deps = svc.get_dependencies()
        assert {(l.parent, l.child) for l in deps.links} == {("api", "db")}

    def test_top_annotations_passthrough(self):
        store = TpuSpanStore(SMALL)
        store.apply([
            rpc(1, 1, None, WEB, API, 100, 110, 190, 200,
                extra_ann=Annotation(150, "hot-path", API)),
        ])
        svc = QueryService(store)
        assert svc.get_top_annotations("api") == ["hot-path"]
        assert QueryService(InMemorySpanStore()).get_top_annotations("api") == []
