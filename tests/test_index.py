"""Index column families: the fast path must answer exactly what the
scan kernels answer.

Two regimes matter (store.base.index_first_topk):
- complete buckets (never wrapped): the index IS the full entry set for
  the key, results must equal the scan's bit for bit;
- wrapped buckets: the store falls back to the scan, so results must
  again equal a scan-only store.

Tracegen spans are cross-host (cs/cr on the client endpoint, sr/ss and
the custom annotation on the server endpoint — tracegen/gen.py:59-67),
so these tests exercise the host-set (min, max) entry pairs that make
annotation queries exact for two-host spans.
"""

import pytest

from zipkin_tpu.store.device import StoreConfig
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.tracegen import generate_traces


def _cfg(use_index: bool, **kw) -> StoreConfig:
    base = dict(
        capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
        max_services=32, max_span_names=64, max_annotation_values=256,
        max_binary_keys=64, cms_width=1 << 10, hll_p=8,
        quantile_buckets=256, use_index=use_index,
    )
    base.update(kw)
    return StoreConfig(**base)


def _pair(spans, **kw):
    fast, scan = TpuSpanStore(_cfg(True, **kw)), TpuSpanStore(_cfg(False))
    for st in (fast, scan):
        st.apply(spans)
    return fast, scan


def _ids(res):
    return [(i.trace_id, i.timestamp) for i in res]


SPANS = [s for t in generate_traces(n_traces=25, max_depth=4,
                                    n_services=6) for s in t]
END_TS = max(s.last_timestamp for s in SPANS if s.last_timestamp) + 1


@pytest.mark.parametrize("limit", [3, 10])
def test_index_matches_scan_by_service(limit):
    fast, scan = _pair(SPANS)
    for svc in sorted(scan.get_all_service_names()):
        assert _ids(fast.get_trace_ids_by_name(svc, None, END_TS, limit)) \
            == _ids(scan.get_trace_ids_by_name(svc, None, END_TS, limit)), svc


def test_index_matches_scan_by_span_name():
    fast, scan = _pair(SPANS)
    for svc in sorted(scan.get_all_service_names()):
        for name in sorted(scan.get_span_names(svc)):
            assert _ids(
                fast.get_trace_ids_by_name(svc, name, END_TS, 10)
            ) == _ids(
                scan.get_trace_ids_by_name(svc, name, END_TS, 10)
            ), (svc, name)


def test_index_matches_scan_by_annotation_cross_host():
    """The custom annotation is hosted by the SERVER endpoint; querying
    it under the CLIENT service still matches (per-slot semantics), so
    the host-set entry pairs must cover both."""
    fast, scan = _pair(SPANS)
    for svc in sorted(scan.get_all_service_names()):
        assert _ids(fast.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, END_TS, 10
        )) == _ids(scan.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, END_TS, 10
        )), svc


def test_index_matches_scan_by_binary_value():
    fast, scan = _pair(SPANS)
    for svc in sorted(scan.get_all_service_names()):
        for value in (b"/api/widgets", None):
            assert _ids(fast.get_trace_ids_by_annotation(
                svc, "http.uri", value, END_TS, 10
            )) == _ids(scan.get_trace_ids_by_annotation(
                svc, "http.uri", value, END_TS, 10
            )), (svc, value)


def test_end_ts_filter_through_index():
    fast, scan = _pair(SPANS)
    svc = sorted(scan.get_all_service_names())[0]
    mid = (min(s.first_timestamp for s in SPANS if s.first_timestamp)
           + END_TS) // 2
    assert _ids(fast.get_trace_ids_by_name(svc, None, mid, 10)) == \
        _ids(scan.get_trace_ids_by_name(svc, None, mid, 10))


def test_wrapped_bucket_falls_back_to_scan():
    """Force tiny bucket depths so every bucket wraps: results must
    still equal the scan-only store (index_first_topk fallback)."""
    fast, scan = _pair(
        SPANS,
        idx_service_depth=64, idx_name_buckets=256, idx_name_depth=64,
        idx_ann_buckets=256, idx_ann_depth=64, idx_bann_buckets=256,
        idx_bann_depth=32,
    )
    # With 25*~N spans over 6 services, 64-deep service buckets wrap.
    for svc in sorted(scan.get_all_service_names()):
        assert _ids(fast.get_trace_ids_by_name(svc, None, END_TS, 10)) \
            == _ids(scan.get_trace_ids_by_name(svc, None, END_TS, 10))
        assert _ids(fast.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, END_TS, 10
        )) == _ids(scan.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, END_TS, 10
        ))


def test_eviction_through_index():
    """Evicted spans must vanish from index results (gid round-trip
    liveness), exactly as they vanish from the scan."""
    fast, scan = _pair([], )
    small_fast = TpuSpanStore(_cfg(True, capacity=128, ann_capacity=512,
                                   bann_capacity=256))
    small_scan = TpuSpanStore(_cfg(False, capacity=128, ann_capacity=512,
                                   bann_capacity=256))
    spans = [s for t in generate_traces(n_traces=60, max_depth=3,
                                        n_services=4) for s in t]
    for st in (small_fast, small_scan):
        st.apply(spans)  # > 2x capacity: the ring wraps
    end_ts = max(s.last_timestamp for s in spans if s.last_timestamp) + 1
    for svc in sorted(small_scan.get_all_service_names()):
        assert _ids(
            small_fast.get_trace_ids_by_name(svc, None, end_ts, 10)
        ) == _ids(
            small_scan.get_trace_ids_by_name(svc, None, end_ts, 10)
        ), svc
