"""Index column families: the fast path must answer exactly what the
scan kernels answer.

Two regimes matter (store.base.index_first_topk):
- complete buckets (never wrapped): the index IS the full entry set for
  the key, results must equal the scan's bit for bit;
- wrapped buckets: the store falls back to the scan, so results must
  again equal a scan-only store.

Tracegen spans are cross-host (cs/cr on the client endpoint, sr/ss and
the custom annotation on the server endpoint — tracegen/gen.py:59-67),
so these tests exercise the host-set (min, max) entry pairs that make
annotation queries exact for two-host spans.
"""

import pytest

from zipkin_tpu.store.device import StoreConfig
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.tracegen import generate_traces


def _cfg(use_index: bool, **kw) -> StoreConfig:
    base = dict(
        capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
        max_services=32, max_span_names=64, max_annotation_values=256,
        max_binary_keys=64, cms_width=1 << 10, hll_p=8,
        quantile_buckets=256, use_index=use_index,
    )
    base.update(kw)
    return StoreConfig(**base)


def _pair(spans, **kw):
    fast, scan = TpuSpanStore(_cfg(True, **kw)), TpuSpanStore(_cfg(False))
    for st in (fast, scan):
        st.apply(spans)
    return fast, scan


def _ids(res):
    return [(i.trace_id, i.timestamp) for i in res]


SPANS = [s for t in generate_traces(n_traces=25, max_depth=4,
                                    n_services=6) for s in t]
END_TS = max(s.last_timestamp for s in SPANS if s.last_timestamp) + 1


@pytest.mark.parametrize("limit", [3, 10])
def test_index_matches_scan_by_service(limit):
    fast, scan = _pair(SPANS)
    for svc in sorted(scan.get_all_service_names()):
        assert _ids(fast.get_trace_ids_by_name(svc, None, END_TS, limit)) \
            == _ids(scan.get_trace_ids_by_name(svc, None, END_TS, limit)), svc


def test_index_matches_scan_by_span_name():
    fast, scan = _pair(SPANS)
    for svc in sorted(scan.get_all_service_names()):
        for name in sorted(scan.get_span_names(svc)):
            assert _ids(
                fast.get_trace_ids_by_name(svc, name, END_TS, 10)
            ) == _ids(
                scan.get_trace_ids_by_name(svc, name, END_TS, 10)
            ), (svc, name)


def test_index_matches_scan_by_annotation_cross_host():
    """The custom annotation is hosted by the SERVER endpoint; querying
    it under the CLIENT service still matches (per-slot semantics), so
    the host-set entry pairs must cover both."""
    fast, scan = _pair(SPANS)
    for svc in sorted(scan.get_all_service_names()):
        assert _ids(fast.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, END_TS, 10
        )) == _ids(scan.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, END_TS, 10
        )), svc


def test_index_matches_scan_by_binary_value():
    fast, scan = _pair(SPANS)
    for svc in sorted(scan.get_all_service_names()):
        for value in (b"/api/widgets", None):
            assert _ids(fast.get_trace_ids_by_annotation(
                svc, "http.uri", value, END_TS, 10
            )) == _ids(scan.get_trace_ids_by_annotation(
                svc, "http.uri", value, END_TS, 10
            )), (svc, value)


def test_end_ts_filter_through_index():
    fast, scan = _pair(SPANS)
    svc = sorted(scan.get_all_service_names())[0]
    mid = (min(s.first_timestamp for s in SPANS if s.first_timestamp)
           + END_TS) // 2
    assert _ids(fast.get_trace_ids_by_name(svc, None, mid, 10)) == \
        _ids(scan.get_trace_ids_by_name(svc, None, mid, 10))


def test_wrapped_bucket_falls_back_to_scan():
    """Force tiny bucket depths so every bucket wraps: results must
    still equal the scan-only store (index_first_topk fallback)."""
    fast, scan = _pair(
        SPANS,
        idx_service_depth=64, idx_name_buckets=256, idx_name_depth=64,
        idx_ann_buckets=256, idx_ann_depth=64, idx_bann_buckets=256,
        idx_bann_depth=32,
    )
    # With 25*~N spans over 6 services, 64-deep service buckets wrap.
    for svc in sorted(scan.get_all_service_names()):
        assert _ids(fast.get_trace_ids_by_name(svc, None, END_TS, 10)) \
            == _ids(scan.get_trace_ids_by_name(svc, None, END_TS, 10))
        assert _ids(fast.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, END_TS, 10
        )) == _ids(scan.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, END_TS, 10
        ))


def test_trace_membership_fast_path_matches_scan():
    """Whole-trace fetch and durations through the gid buckets must
    equal the full-ring scan results exactly."""
    fast, scan = _pair(SPANS)
    tids = sorted({s.trace_id for s in SPANS})[:20]
    got = fast.get_spans_by_trace_ids(tids)
    want = scan.get_spans_by_trace_ids(tids)
    assert [sorted(s.id for s in t) for t in got] == \
        [sorted(s.id for s in t) for t in want]
    assert got == want  # full span equality incl. annotations
    assert fast.get_traces_duration(tids) == scan.get_traces_duration(tids)
    assert fast.traces_exist(tids + [424242]) == \
        scan.traces_exist(tids + [424242])


def test_hot_trace_beyond_bucket_depth_falls_back():
    """A trace with more spans than TRACE_SPAN_DEPTH keeps its bucket
    gate false (its own entries displace each other while resident), so
    reads must fall back to the scan and stay exact."""
    from zipkin_tpu.models.span import Annotation, Endpoint, Span
    from zipkin_tpu.store.device import StoreConfig

    cfg = _cfg(True)
    n_hot = StoreConfig.TRACE_SPAN_DEPTH + 18
    ep = Endpoint(5, 80, "hotsvc")
    hot = [
        Span(555, "op", i + 1, None,
             (Annotation(100 + i, "sr", ep), Annotation(200 + i, "ss", ep)),
             ())
        for i in range(n_hot)  # > TRACE_SPAN_DEPTH
    ]
    fast, scan = TpuSpanStore(cfg), TpuSpanStore(_cfg(False))
    for st in (fast, scan):
        st.apply(hot)
    got = fast.get_spans_by_trace_ids([555])
    want = scan.get_spans_by_trace_ids([555])
    assert got and len(got[0]) == n_hot
    assert got == want
    assert fast.get_traces_duration([555]) == scan.get_traces_duration([555])


def test_trace_membership_after_eviction():
    """Ring-lap survivors read identically through fast path and scan."""
    fast = TpuSpanStore(_cfg(True, capacity=128, ann_capacity=512,
                             bann_capacity=256))
    scan = TpuSpanStore(_cfg(False, capacity=128, ann_capacity=512,
                             bann_capacity=256))
    spans = [s for t in generate_traces(n_traces=60, max_depth=3,
                                        n_services=4) for s in t]
    for st in (fast, scan):
        st.apply(spans)
    tids = sorted({s.trace_id for s in spans})
    assert fast.traces_exist(tids) == scan.traces_exist(tids)
    survivors = sorted(scan.traces_exist(tids))[:10]
    assert fast.get_spans_by_trace_ids(survivors) == \
        scan.get_spans_by_trace_ids(survivors)
    assert fast.get_traces_duration(survivors) == \
        scan.get_traces_duration(survivors)


def test_index_first_topk_gating():
    """The trust policy itself, as a pure function: complete buckets
    answer unless the top-k window truncated an underfull result;
    wrapped buckets answer only above their watermark."""
    from zipkin_tpu.store.base import index_first_topk

    scan_calls = []

    def scan(k):
        scan_calls.append(k)
        return [(1, 100), (2, 90)], False

    def run(cands, complete, wm, limit=2, window=None):
        scan_calls.clear()
        return index_first_topk(
            limit, 1 << 20,
            lambda k: (cands, complete, wm,
                       k if window is None else window),
            scan,
        ), bool(scan_calls)

    # Complete + enough distinct traces: index answers.
    ids, scanned = run([(1, 100), (2, 90)], True, -1)
    assert [i.trace_id for i in ids] == [1, 2] and not scanned
    # Complete + underfull + window NOT saturated: the true full answer.
    ids, scanned = run([(1, 100)], True, -1)
    assert [i.trace_id for i in ids] == [1] and not scanned
    # Complete + underfull + saturated window (k = limit*8 = 16
    # candidates, all one trace): retried at full depth — the fake
    # fetch's unclamped window (k) then exceeds the candidate count, so
    # the retry PROVES the underfull answer without a scan.
    ids, scanned = run([(1, 100 - i) for i in range(16)], True, -1)
    assert [i.trace_id for i in ids] == [1] and not scanned
    # Wrapped + full + last candidate above the watermark: trusted.
    ids, scanned = run([(1, 100), (2, 90)], False, 50)
    assert [i.trace_id for i in ids] == [1, 2] and not scanned
    # Wrapped + full + last candidate below the watermark: must scan.
    ids, scanned = run([(1, 100), (2, 90)], False, 95)
    assert scanned
    # Wrapped + underfull: must scan.
    ids, scanned = run([(1, 100)], False, -1)
    assert scanned
    # Complete + kernel-clamped window that FILLED: the candidates may
    # have been truncated by the clamp, so 'underfull' must be judged
    # against the kernel's real window, not the requested k — must
    # scan. (Regression: the two-bucket binary-value probe trusted a
    # silently cut window; caught by the 3-store oracle parity drive.)
    ids, scanned = run([(1, 100 - i) for i in range(12)], True, -1,
                       window=12)
    assert scanned


def _three_host_span(tid=777, marker="middle marker"):
    """A span whose annotations carry THREE distinct host services: the
    (min, max) host-pair index entries skip the middle host entirely."""
    from zipkin_tpu.models.span import Annotation, Endpoint, Span

    a = Endpoint(1, 1, "svc-lo")
    b = Endpoint(2, 2, "svc-mid")
    c = Endpoint(3, 3, "svc-hi")
    return Span(tid, "op", 1, None, (
        Annotation(100, "cs", a),
        Annotation(110, marker, b),
        Annotation(120, "sr", c),
    ), ())


def test_middle_host_annotation_query_stays_exact():
    """A 3+-distinct-host span is indexed under its (min, max) hosts
    only; a query under the MIDDLE host must not trust the (incomplete
    yet never-wrapped) fast-path bucket — ann_poison forces the scan,
    which finds the span (per-slot semantics)."""
    span = _three_host_span()
    spans = [span] + SPANS  # interleave real traffic
    fast, scan = _pair(spans)
    for svc in ("svc-lo", "svc-mid", "svc-hi"):
        got = _ids(fast.get_trace_ids_by_annotation(
            svc, "middle marker", None, END_TS, 10))
        want = _ids(scan.get_trace_ids_by_annotation(
            svc, "middle marker", None, END_TS, 10))
        assert got == want, svc
        # Per-slot semantics: the span carries the marker AND has an
        # annotation hosted by each of the three services.
        assert any(t == 777 for t, _ in want), svc
    # The middle-host query really does return the span via the scan.
    assert any(
        t == 777 for t, _ in _ids(fast.get_trace_ids_by_annotation(
            "svc-mid", "middle marker", None, END_TS, 10))
    )
    # Binary-annotation queries under the middle host share the gate.
    for svc in ("svc-lo", "svc-mid", "svc-hi"):
        assert _ids(fast.get_trace_ids_by_annotation(
            svc, "http.uri", b"/api/widgets", END_TS, 10
        )) == _ids(scan.get_trace_ids_by_annotation(
            svc, "http.uri", b"/api/widgets", END_TS, 10
        )), svc


def test_middle_host_poison_self_heals_after_eviction():
    """The poison is a displaced-gid gate, not a permanent flag: once
    the 3-host span is evicted (a full ring turnover later), the
    middle-host service's fast path is trusted again."""
    import numpy as np

    kw = dict(capacity=64, ann_capacity=512, bann_capacity=256)
    fast = TpuSpanStore(_cfg(True, **kw))
    # Same ring geometry for the oracle: eviction must be identical or
    # a parity comparison is meaningless.
    scan = TpuSpanStore(_cfg(False, **kw))
    span = _three_host_span()
    filler = [s for t in generate_traces(n_traces=40, max_depth=3,
                                         n_services=4) for s in t]
    assert len(filler) >= 64, "generator must fill the ring for this test"
    for st in (fast, scan):
        st.apply([span])
        st.apply(filler)
    svc_mid = fast.dicts.services.get("svc-mid")
    assert svc_mid is not None
    poison = int(np.asarray(fast.state.ann_poison)[svc_mid])
    wp = int(fast.state.write_pos)
    # Ring turned over: the gate must have expired.
    assert poison < wp - fast.config.capacity
    # And fast-path results stay exact — on a query the data really
    # matches (both stores non-empty), not a vacuous [] == [].
    end2 = max(s.last_timestamp for s in filler if s.last_timestamp) + 1
    nonempty = 0
    for svc in sorted(scan.get_all_service_names()):
        got = fast.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, end2, 10)
        want = scan.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, end2, 10)
        assert _ids(got) == _ids(want), svc
        nonempty += bool(want)
    assert nonempty > 0


def test_duplicate_trace_ids_in_request():
    """Duplicated request ids must not duplicate spans or wedge the
    index fast path's cap escalation (qids are uniqued; reconstruction
    is per request id)."""
    fast, scan = _pair(SPANS)
    tid = SPANS[0].trace_id
    got = fast.get_spans_by_trace_ids([tid] * 10)
    want = scan.get_spans_by_trace_ids([tid] * 10)
    assert len(got) == len(want) == 10
    assert got == want
    assert len({len(t) for t in got}) == 1  # all copies identical
    assert fast.get_traces_duration([tid] * 10) == \
        scan.get_traces_duration([tid] * 10)


def test_pre_index_snapshot_poisons_trust(tmp_path):
    """Restoring a snapshot that predates the index families must not
    let empty zero-cursor buckets claim completeness: reads fall back
    to the scans and every restored span stays visible."""
    import json
    import os

    import numpy as np

    from zipkin_tpu import checkpoint

    store = TpuSpanStore(_cfg(True))
    spans = [s for t in generate_traces(n_traces=6, max_depth=3,
                                        n_services=4) for s in t]
    store.apply(spans)
    path = str(tmp_path / "preindex")
    checkpoint.save(store, path)

    state_file = os.path.join(path, "state.npz")
    data = dict(np.load(state_file))
    for k in list(data):
        if k.startswith(("cand_", "tr_")):
            del data[k]
    np.savez_compressed(state_file, **data)
    meta_file = os.path.join(path, "meta.json")
    with open(meta_file) as f:
        meta = json.load(f)
    meta["revision"] = 4
    for k in list(meta["config"]):
        if k.startswith("idx_"):
            meta["config"].pop(k)
    with open(meta_file, "w") as f:
        json.dump(meta, f)

    restored = checkpoint.load(path)
    tids = sorted({s.trace_id for s in spans})
    assert restored.traces_exist(tids) == store.traces_exist(tids)
    assert restored.get_spans_by_trace_ids(tids[:3]) == \
        store.get_spans_by_trace_ids(tids[:3])
    end_ts = max(s.last_timestamp for s in spans if s.last_timestamp) + 1
    svc = sorted(store.get_all_service_names())[0]
    assert _ids(restored.get_trace_ids_by_name(svc, None, end_ts, 10)) \
        == _ids(store.get_trace_ids_by_name(svc, None, end_ts, 10))


def test_pre_rev7_snapshot_disables_key_table(tmp_path):
    """A revision-6 snapshot predates the per-key cursor table: its
    displacement history is unrecoverable, so post-restore key claims
    must NEVER certify completeness (the claim-is-first-record
    invariant doesn't cross the restore boundary). load() tombstones
    the table; post-restore ingest and queries stay exact via the
    bucket gates."""
    import json
    import os

    import numpy as np

    from zipkin_tpu import checkpoint
    from zipkin_tpu.store.device import _FP_TOMB as tomb

    store = TpuSpanStore(_cfg(True))
    spans = [s for t in generate_traces(n_traces=6, max_depth=3,
                                        n_services=4) for s in t]
    store.apply(spans)
    path = str(tmp_path / "rev6")
    checkpoint.save(store, path)
    state_file = os.path.join(path, "state.npz")
    data = dict(np.load(state_file))
    for k in ("key_tab", "key_wm", "ann_poison"):
        del data[k]
    np.savez_compressed(state_file, **data)
    meta_file = os.path.join(path, "meta.json")
    with open(meta_file) as f:
        meta = json.load(f)
    meta["revision"] = 6
    meta["config"].pop("idx_key_slots", None)
    with open(meta_file, "w") as f:
        json.dump(meta, f)

    restored = checkpoint.load(path)
    # Table tombstoned: every word is the un-claimable sentinel.
    assert (np.asarray(restored.state.key_tab) == tomb).all()
    # New ingest can't resurrect key trust...
    more = [s for t in generate_traces(n_traces=4, max_depth=3,
                                       n_services=4) for s in t]
    restored.apply(more)
    assert (np.asarray(restored.state.key_tab) == tomb).all()
    # ...and reads stay exact vs a never-snapshotted oracle.
    oracle = TpuSpanStore(_cfg(False))
    oracle.apply(spans)
    oracle.apply(more)
    end_ts = max(
        s.last_timestamp for s in spans + more if s.last_timestamp
    ) + 1
    for svc in sorted(oracle.get_all_service_names()):
        assert _ids(restored.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, end_ts, 10
        )) == _ids(oracle.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, end_ts, 10
        )), svc


def test_eviction_through_index():
    """Evicted spans must vanish from index results (gid round-trip
    liveness), exactly as they vanish from the scan."""
    fast, scan = _pair([], )
    small_fast = TpuSpanStore(_cfg(True, capacity=128, ann_capacity=512,
                                   bann_capacity=256))
    small_scan = TpuSpanStore(_cfg(False, capacity=128, ann_capacity=512,
                                   bann_capacity=256))
    spans = [s for t in generate_traces(n_traces=60, max_depth=3,
                                        n_services=4) for s in t]
    for st in (small_fast, small_scan):
        st.apply(spans)  # > 2x capacity: the ring wraps
    end_ts = max(s.last_timestamp for s in spans if s.last_timestamp) + 1
    for svc in sorted(small_scan.get_all_service_names()):
        assert _ids(
            small_fast.get_trace_ids_by_name(svc, None, end_ts, 10)
        ) == _ids(
            small_scan.get_trace_ids_by_name(svc, None, end_ts, 10)
        ), svc


def test_get_trace_ids_multi_matches_singular():
    """The one-launch batched read must answer every query exactly as
    the singular paths (and the scan-only oracle) do."""
    fast, scan = _pair(SPANS)
    queries = []
    for svc in sorted(scan.get_all_service_names()):
        queries.append(("name", svc, None, END_TS, 10))
        names = sorted(scan.get_span_names(svc))
        if names:
            queries.append(("name", svc, names[0], END_TS, 5))
        queries.append(
            ("annotation", svc, "some custom annotation", None, END_TS, 10))
        queries.append(
            ("annotation", svc, "http.uri", b"/api/widgets", END_TS, 10))
        queries.append(("annotation", svc, "http.uri", None, END_TS, 10))
    queries.append(("name", "no-such-service", None, END_TS, 10))
    queries.append(("annotation", "no-such-service", "x", None, END_TS, 10))
    queries.append(("name", queries[0][1], None, END_TS, 0))  # limit 0
    got = fast.get_trace_ids_multi(queries)
    assert len(got) == len(queries)
    for q, ids in zip(queries, got):
        if q[0] == "name":
            want = scan.get_trace_ids_by_name(*q[1:])
        else:
            want = scan.get_trace_ids_by_annotation(*q[1:])
        assert _ids(ids) == _ids(want), q


def test_get_trace_ids_multi_wrapped_buckets_fall_back():
    """Distrusted buckets inside a batched read drop to the singular
    scan path per query — results must still match the oracle."""
    fast, scan = _pair(
        SPANS,
        idx_service_depth=64, idx_name_buckets=256, idx_name_depth=64,
        idx_ann_buckets=256, idx_ann_depth=64, idx_bann_buckets=256,
        idx_bann_depth=32,
    )
    queries = []
    for svc in sorted(scan.get_all_service_names()):
        queries.append(("name", svc, None, END_TS, 10))
        queries.append(
            ("annotation", svc, "some custom annotation", None, END_TS, 10))
    got = fast.get_trace_ids_multi(queries)
    for q, ids in zip(queries, got):
        if q[0] == "name":
            want = scan.get_trace_ids_by_name(*q[1:])
        else:
            want = scan.get_trace_ids_by_annotation(*q[1:])
        assert _ids(ids) == _ids(want), q


def test_get_trace_ids_multi_middle_host_poison():
    """Batched reads honor the ann_poison middle-host gate too."""
    span = _three_host_span()
    fast, scan = _pair([span] + SPANS)
    queries = [
        ("annotation", svc, "middle marker", None, END_TS, 10)
        for svc in ("svc-lo", "svc-mid", "svc-hi")
    ]
    got = fast.get_trace_ids_multi(queries)
    for q, ids in zip(queries, got):
        want = scan.get_trace_ids_by_annotation(*q[1:])
        assert _ids(ids) == _ids(want), q
        assert any(t == 777 for t, _ in _ids(ids)), q


def test_sparse_key_under_hot_bucket_stays_on_fast_path():
    """The per-key cursor table (StoreState.key_tab): a sparse
    (service, annotation-value) pair whose hashed bucket is wrapped by a
    hot bucket-mate must still answer from the index — its own entries
    were never displaced, so its key record proves the window complete
    (NOTES_r03 §4's 'known fallback', closed by VERDICT r3 item 5)."""
    from zipkin_tpu.models.span import Annotation, Endpoint, Span

    # One annotation bucket: every (service, value) pair is bucket-mates
    # with every other — the aliasing worst case, deterministically.
    cfg = _cfg(True, idx_ann_buckets=1, idx_ann_depth=64)
    fast, scan = TpuSpanStore(cfg), TpuSpanStore(_cfg(False))
    ep = Endpoint(1, 80, "websvc")
    ts = [1000]

    def span(i, value):
        ts[0] += 10
        return Span(10_000 + i, "op", 1, None,
                    (Annotation(ts[0], "sr", ep),
                     Annotation(ts[0] + 1, value, ep)), ())

    spans = [span(i, "hot marker") for i in range(150)]
    spans += [span(200 + j, "rare marker") for j in range(2)]
    spans += [span(300 + i, "hot marker") for i in range(40)]
    for st in (fast, scan):
        st.apply(spans)
    end_ts = ts[0] + 10
    assert fast.index_fallbacks == 0
    got = fast.get_trace_ids_by_annotation(
        "websvc", "rare marker", None, end_ts, 10)
    want = scan.get_trace_ids_by_annotation(
        "websvc", "rare marker", None, end_ts, 10)
    assert _ids(got) == _ids(want)
    assert sorted(t for t, _ in _ids(got)) == [10200, 10201]
    # The rare pair answered from the index: no scan fallback despite
    # its bucket having wrapped 3x on the hot pair's traffic.
    assert fast.index_fallbacks == 0 and fast.index_hits == 1
    # The batched path honors the same gate.
    multi = fast.get_trace_ids_multi(
        [("annotation", "websvc", "rare marker", None, end_ts, 10)])
    assert _ids(multi[0]) == _ids(want)
    assert fast.index_fallbacks == 0


def test_negative_lookup_stays_on_fast_path():
    """A query for a key that was NEVER indexed must answer [] from the
    index even when its hashed bucket wrapped on other keys' traffic:
    zero claim drops + absent key record prove emptiness (the
    reference's instant empty-row read)."""
    from zipkin_tpu.models.span import Annotation, Endpoint, Span

    cfg = _cfg(True, idx_ann_buckets=1, idx_ann_depth=64)
    fast, scan = TpuSpanStore(cfg), TpuSpanStore(_cfg(False))
    ep = Endpoint(1, 80, "websvc")
    other = Endpoint(2, 80, "othersvc")
    spans = [
        Span(20_000 + i, "op", 1, None,
             (Annotation(1000 + 10 * i, "sr", ep),
              Annotation(1001 + 10 * i, "hot marker", ep)), ())
        for i in range(150)  # wraps the single 64-deep ann bucket 2x+
    ]
    # Interns "ghost marker", but ONLY under othersvc.
    spans.append(Span(30_000, "op", 1, None,
                      (Annotation(5000, "sr", other),
                       Annotation(5001, "ghost marker", other)), ()))
    for st in (fast, scan):
        st.apply(spans)
    end_ts = 10_000
    assert fast.index_fallbacks == 0
    got = fast.get_trace_ids_by_annotation(
        "websvc", "ghost marker", None, end_ts, 10)
    want = scan.get_trace_ids_by_annotation(
        "websvc", "ghost marker", None, end_ts, 10)
    assert got == want == []
    # Answered by the negative gate, not the O(ring) scan.
    assert fast.index_fallbacks == 0 and fast.index_hits == 1
    # Same through the batched path.
    multi = fast.get_trace_ids_multi(
        [("annotation", "websvc", "ghost marker", None, end_ts, 10)])
    assert multi[0] == []
    assert fast.index_fallbacks == 0


def test_pre_rev8_snapshot_disables_negative_gate(tmp_path):
    """A revision-7 snapshot kept its key table but never counted claim
    drops, so an absent record proves nothing: restores must force the
    drop counter >= 1 (negative gate off) for the store's lifetime."""
    import json
    import os

    import numpy as np

    from zipkin_tpu import checkpoint

    store = TpuSpanStore(_cfg(True))
    store.apply(SPANS)
    path = str(tmp_path / "rev7")
    checkpoint.save(store, path)
    state_file = os.path.join(path, "state.npz")
    data = dict(np.load(state_file))
    del data["counters.key_claim_drops"]
    np.savez_compressed(state_file, **data)
    meta_file = os.path.join(path, "meta.json")
    with open(meta_file) as f:
        meta = json.load(f)
    meta["revision"] = 7
    with open(meta_file, "w") as f:
        json.dump(meta, f)
    restored = checkpoint.load(path)
    assert int(np.asarray(
        restored.state.counters["key_claim_drops"]
    )) >= 1
    # Current-revision snapshots round-trip the counter untouched.
    path2 = str(tmp_path / "rev8")
    checkpoint.save(store, path2)
    again = checkpoint.load(path2)
    assert int(np.asarray(
        again.state.counters["key_claim_drops"]
    )) == int(np.asarray(store.state.counters["key_claim_drops"]))


def test_dictionary_overflow_service_routes_to_scan():
    """More distinct services than max_services: overflow services live
    only in the raw ring columns (no index family can represent them),
    so their queries must take the scan path and still answer exactly —
    never the index's trusted-empty (round-4 parity-drive finding)."""
    spans = [s for t in generate_traces(n_traces=30, max_depth=3,
                                        n_services=12) for s in t]
    fast = TpuSpanStore(_cfg(True, max_services=4))
    scan = TpuSpanStore(_cfg(False, max_services=4))
    mem_names = set()
    for st in (fast, scan):
        st.apply(spans)
    end_ts = max(s.last_timestamp for s in spans if s.last_timestamp) + 1
    # Query EVERY service that appears in the raw spans, including ones
    # whose dictionary id exceeds max_services.
    for s in spans:
        for a in s.annotations:
            if a.host and a.host.service_name:
                mem_names.add(a.host.service_name)
    assert len(mem_names) > 4  # the overflow case is actually exercised
    for svc in sorted(mem_names):
        got = _ids(fast.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, end_ts, 10))
        want = _ids(scan.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, end_ts, 10))
        assert got == want, svc
        got_n = _ids(fast.get_trace_ids_by_name(svc, None, end_ts, 10))
        want_n = _ids(scan.get_trace_ids_by_name(svc, None, end_ts, 10))
        assert got_n == want_n, svc
    # The batched multi path must agree as well.
    queries = [("name", svc, None, end_ts, 10) for svc in sorted(mem_names)]
    multi = fast.get_trace_ids_multi(queries)
    for svc, res in zip(sorted(mem_names), multi):
        assert _ids(res) == _ids(
            scan.get_trace_ids_by_name(svc, None, end_ts, 10)), svc
    # Catalog endpoints must not clamp an overflow id into the last
    # indexed row (advisor r4: a clamped gather silently serves service
    # max_services-1's data): compare every endpoint against a store
    # whose service capacity covers the whole vocabulary. Counts match
    # exactly because the ring never wraps in this test (the scan path
    # counts ring-resident rows; the indexed path counts lifetime).
    big = TpuSpanStore(_cfg(True))  # max_services=32 covers all
    big.apply(spans)

    def canon(pairs):  # top-k tie ORDER is not a product guarantee
        return sorted(pairs, key=lambda p: (-p[1], p[0]))

    for svc in sorted(mem_names):
        assert fast.get_span_names(svc) == big.get_span_names(svc), svc
        # k past the vocabulary so tie-breaks at the cutoff can't
        # change set membership.
        assert canon(fast.top_annotations(svc, 999)) == \
            canon(big.top_annotations(svc, 999)), svc
        assert canon(fast.top_binary_keys(svc, 999)) == \
            canon(big.top_binary_keys(svc, 999)), svc
        qs = [0.5, 0.95]
        assert fast.service_duration_quantiles(svc, qs) == \
            big.service_duration_quantiles(svc, qs), svc
    assert fast.get_all_service_names() == big.get_all_service_names()


def test_far_future_timestamps_stay_exact():
    """Timestamps past the coarse ts-watermark domain (>= 2^51 µs,
    ~year 2041) must take _index_write's EXACT overflow-fallback war
    instead of saturating the coarse i32 domain: results match the
    scan-only oracle both for wrapped (watermark-gated) and unwrapped
    buckets, and the stored watermark stays an upper bound (never a
    silently-wrapped underestimate that would certify a stale
    window)."""
    from zipkin_tpu.models.span import Annotation, Endpoint, Span

    # One annotation bucket, tiny depth: traffic wraps it, so answers
    # ride the watermark trust gate — exactly where a broken overflow
    # war would certify wrong windows.
    cfg = _cfg(True, idx_ann_buckets=1, idx_ann_depth=64)
    fast, scan = TpuSpanStore(cfg), TpuSpanStore(_cfg(False))
    ep = Endpoint(1, 80, "futuresvc")
    base = 1 << 52  # past the 2^(31+20) coarse ceiling
    spans = [
        Span(40_000 + i, "op", 1, None,
             (Annotation(base + 10 * i, "sr", ep),
              Annotation(base + 10 * i + 1, "future marker", ep)), ())
        for i in range(150)  # wraps the single 64-deep bucket twice
    ]
    for st in (fast, scan):
        st.apply(spans)
    end_ts = base + 10_000
    got = _ids(fast.get_trace_ids_by_annotation(
        "futuresvc", "future marker", None, end_ts, 10))
    want = _ids(scan.get_trace_ids_by_annotation(
        "futuresvc", "future marker", None, end_ts, 10))
    assert got == want
    assert len(got) == 10  # real data answered, not a vacuous []
    # The watermark must be a true upper bound on displaced ts (exact
    # war), not an i32-saturated or wrapped value.
    import numpy as np

    lay, _, _ = fast.config.cand_layout
    b_base, _, n_b, _ = lay[2]  # CAND_ANN family row
    wm = np.asarray(fast.state.cand_wm)[b_base:b_base + n_b]
    live_wm = wm[wm > -(2 << 60)]
    assert live_wm.size and (live_wm >= base).all()
    assert (live_wm <= base + 10 * 150 + (1 << 20)).all()


def test_ts_watermark_coarse_boundary_window_stays_exact():
    """Regression: a displaced ts in the LAST coarse unit below
    2^(31+shift) µs used to ceil to exactly 2^31, wrap negative in the
    i32 scatter, and silently UNDERSTATE the watermark (a wrapped
    bucket could then certify a window missing displaced entries).
    Such timestamps must route through the exact overflow war: results
    match the scan oracle and the stored watermark stays >= the true
    displaced maximum."""
    import numpy as np

    from zipkin_tpu.models.span import Annotation, Endpoint, Span
    from zipkin_tpu.store.device import _WM_TS_SHIFT

    cfg = _cfg(True, idx_ann_buckets=1, idx_ann_depth=64)
    fast, scan = TpuSpanStore(cfg), TpuSpanStore(_cfg(False))
    ep = Endpoint(1, 80, "edgesvc")
    # All 150 ts sit inside [(2^31 - 1) << shift, 2^(31+shift)) — the
    # former wrap window (2^20 µs wide).
    base = ((1 << 31) - 1) << _WM_TS_SHIFT
    spans = [
        Span(50_000 + i, "op", 1, None,
             (Annotation(base + 5 * i, "sr", ep),
              Annotation(base + 5 * i + 1, "edge marker", ep)), ())
        for i in range(150)  # wraps the single 64-deep bucket twice
    ]
    for st in (fast, scan):
        st.apply(spans)
    end_ts = base + (1 << 19)
    got = _ids(fast.get_trace_ids_by_annotation(
        "edgesvc", "edge marker", None, end_ts, 10))
    want = _ids(scan.get_trace_ids_by_annotation(
        "edgesvc", "edge marker", None, end_ts, 10))
    assert got == want
    assert len(got) == 10
    lay, _, _ = fast.config.cand_layout
    b_base, _, n_b, _ = lay[2]  # CAND_ANN family row
    wm = np.asarray(fast.state.cand_wm)[b_base:b_base + n_b]
    live_wm = wm[wm > -(2 << 60)]
    # 86 entries were displaced (150 - 64); the true max displaced ts
    # is base + 5*85 + 1. The watermark must bound it from ABOVE.
    assert live_wm.size and (live_wm >= base).all()
