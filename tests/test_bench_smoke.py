"""Tier-1 perf-structure gate (scripts/bench_smoke.py): the compiled
ingest step's scatter/sort op counts must not regress.

Per-kernel overhead dominates the target device class (NOTES_r03 §3);
the r6 unified index arena exists to cut scatter/sort launches per
batch, and the r12 counting-sort rank path deleted the last hot-path
sort. The ceilings live in ONE place — ``zipkin_tpu.store.census`` —
consumed here and by the smoke script, so a path change updates
exactly one number (raise one only with a NOTES entry explaining what
bought the extra launches). r5 split-design baseline: 101 scatters /
6 sorts / 80 gathers; r6: 95/5/79; r12: 95/4/79.
"""

import json
import subprocess
import sys

from zipkin_tpu.store.census import (
    ARGSORT_STEP_SORTS,
    BASE_STEP_GATHERS,
    BASE_STEP_SCATTERS,
    BASE_STEP_SORTS,
    MAX_MIRROR_DELTA_RATIO,
    MAX_STEP_SORTS,
    expected_census,
)


def test_bench_smoke_json_and_op_ceilings():
    proc = subprocess.run(
        [sys.executable, "scripts/bench_smoke.py", "--spans", "2000",
         "--k", "4"],
        capture_output=True, text=True, timeout=780,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    rec = json.loads(line)  # exactly one JSON line
    assert rec["metric"] == "bench_smoke"
    assert rec["spans"] > 0 and rec["ingest_spans_per_s"] > 0
    # The index-family step-count gate — measured WITH telemetry wired
    # (the store registers its obs metrics and the counter block is
    # fetched), so a device counter fetch that grew the step would
    # trip here. Default config = window arena off = BASE lowering.
    assert rec["step_scatters"] <= BASE_STEP_SCATTERS, rec
    assert rec["step_sorts"] <= BASE_STEP_SORTS, rec
    assert rec["step_gathers"] <= BASE_STEP_GATHERS, rec
    # The telemetry counter block itself must lower as a pure read.
    tel = rec["telemetry"]
    assert tel["counter_block_scatters"] == 0
    assert tel["counter_block_sorts"] == 0
    # spans_seen counts the warm-up step too, so >= the timed spans.
    assert tel["counter_block"]["spans_seen"] >= rec["spans"]
    assert tel["counter_block"]["ring_occupancy"] > 0
    # Per-stage sketch summary rode along (p50/p99 in ms).
    assert tel["ingest_step_ms"]["count"] > 0
    assert tel["ingest_step_ms"]["p50"] > 0
    # Batched-query phase ran and agreed with serial execution.
    mq = rec["multi_query"]
    assert mq["k"] == 4 and mq["identical"] is True
    assert mq["serial_ms"] > 0 and mq["batched_ms"] > 0
    # Archive phase: capture -> compact -> cold query identity vs the
    # memory-store oracle, with eviction capture leaving the fused
    # ingest step's op census UNTOUCHED (the tier-1 gate the cold tier
    # must hold: capture is a separate read-only launch).
    ar = rec["archive"]
    assert ar["identical"] is True
    assert ar["segments_written"] >= 1
    assert ar["compactions"] >= 1
    assert ar["segments_pruned"] >= 1
    assert ar["cold_compression_ratio"] > 1.5
    # The capture claim: a store with an eviction sink lowers the
    # fused step IDENTICALLY to a sink-less one. (The archive phase's
    # tiny ring takes the exact small-store watermark path, so its
    # absolute counts differ from the canonical-shape ceilings above —
    # equality is the invariant here.)
    assert (ar["step_census_with_capture"]
            == ar["step_census_plain"]), ar
    # Pipelined-ingest phase (r9 tentpole): the three-stage pipeline
    # must land a bitwise-identical device state AND an identical cold
    # tier, a warmed steady state must perform ZERO jit recompiles
    # (pow2 staging buckets only hit cached entries), H2D staging must
    # add zero ops to the fused step's lowering (its census with
    # device-resident args equals the host-array census — the
    # step_scatters/sorts/gathers ceilings above were already measured
    # with the obs layer wired), and ingest must never have stalled on
    # capture sealing at the phase's generous backlog (deliberate
    # backpressure is exercised in tests/test_pipeline.py).
    pp = rec["pipeline"]
    assert pp["identical"] is True, pp
    assert pp["recompiles_after_warmup"] == 0, pp
    assert pp["staging_census_equal"] is True, pp
    assert pp["capture_stall_s"] == 0, pp
    assert pp["windows_sealed"] >= 1, pp
    assert pp["pipelined_ingest_s"] > 0 and pp["serial_ingest_s"] > 0
    # Durability phase (r10 tentpole): a full-log replay into a fresh
    # store must land a BITWISE identical state (the half of the
    # ack-after-append contract a live process can prove without
    # dying — SIGKILL coverage is tests/test_crash.py), journaling
    # must add zero jit recompiles in steady state and replay zero
    # more, and the append overhead must hold the acceptance budget:
    # <= 10% at the group-commit default, with fsync=off reproducing
    # the no-WAL throughput (paired per-round ratios over interleaved
    # drives keep these ratios honest on a noisy CI host).
    w = rec["wal"]
    assert w["replay_identical"] is True, w
    assert w["steady_state_recompiles"] == 0, w
    assert w["replay_recompiles"] == 0, w
    assert w["replayed_records"] >= 1, w
    assert w["append_overhead_interval"] <= 0.10, w
    assert w["append_overhead_off"] <= 0.10, w
    assert w["wal_bytes_per_span"] > 0, w
    assert w["recovery_s"] > 0 and w["replay_spans_per_s"] > 0, w
    # Resident-query-engine phase (r11 tentpole): sketch-tier answers
    # must be IDENTICAL to the device read path's and come off the
    # host mirror well under the 10 ms p50 target (they are pure
    # numpy — single-digit-ms is generous headroom even on a loaded
    # CI host); the steady-state query loop must perform ZERO jit
    # recompiles (the resident programs stay resident); cache hits
    # must be bitwise-equal to cold answers and an ingest commit must
    # invalidate precisely (the frontier-keyed re-answer equals a
    # fresh store read). Index-tier p99 is structural headroom on CPU
    # (the ~110 ms dispatch floor is a device-class property — the
    # TPU bench gates the real <50 ms target); here it just must not
    # regress past the old per-request floor's order of magnitude.
    q = rec["query"]
    assert q["sketch_identical"] is True, q
    assert q["sketch_p50_ms"] < 10.0, q
    assert q["steady_recompiles"] == 0, q
    assert q["cache_hit_identical"] is True, q
    assert q["cache_invalidation_exact"] is True, q
    assert q["cache_hits"] >= 1 and q["sketch_answers"] >= 1, q
    assert 0 < q["index_p99_ms"] < 250.0, q
    # Ingest-structure phase (r12 tentpole): the counting-sort rank
    # path must lower with strictly fewer sorts than the argsort path
    # (the deleted O(N log N) entry cost, structurally — store-level
    # bitwise identity between the paths is fuzz-gated in
    # tests/test_rank_paths.py); a batch-escalated geometry must
    # perform ZERO steady-state recompiles through the pipeline once
    # warmed; and the stage-1 sketch-mirror COO delta must stay
    # inside its encode-stage budget (it rides the hot path since r11
    # and nothing watched it until now).
    ing = rec["ingest_structure"]
    assert ing["rank_path_counting_cfg"] == ["counting"], ing
    assert ing["rank_path_argsort_cfg"] == ["argsort"], ing
    assert ing["census_counting"]["sort"] < MAX_STEP_SORTS + 1, ing
    assert ing["census_counting"]["sort"] < ARGSORT_STEP_SORTS, ing
    assert ing["census_argsort"]["sort"] <= ARGSORT_STEP_SORTS, ing
    assert (ing["census_counting"]["scatter"]
            <= ing["census_argsort"]["scatter"]), ing
    assert (ing["census_counting"]["gather"]
            <= ing["census_argsort"]["gather"]), ing
    assert ing["rank_path_counting"] == 1.0, ing
    assert ing["recompiles_after_batch_escalation"] == 0, ing
    assert ing["escalated_batch_spans_limit"] == 512.0, ing
    assert ing["mirror_delta_ratio"] <= MAX_MIRROR_DELTA_RATIO, ing
    # The ceilings the smoke JSON carries must be the census module's
    # (one definition site — this test would catch a re-hard-coding).
    # The main stream runs the library default (window arena OFF), so
    # it carries the BASE ceilings.
    assert rec["census_ceilings"] == {
        "scatter": BASE_STEP_SCATTERS, "sort": BASE_STEP_SORTS,
        "gather": BASE_STEP_GATHERS,
    }
    # Windowed-analytics phase (r13 tentpole): the arena's fused-step
    # cost is exactly the gated census bump (the window-off lowering
    # stays at the BASE counts), mirror and device window cells are
    # BITWISE identical through serial and pipelined drives, the
    # window update adds zero steady-state recompiles, and the
    # sketch-tier windowed quantile answers inside the documented
    # solver rank tolerance with sub-10ms host-only latency.
    w = rec["windows"]
    ws, wo, wg = expected_census("+WINDOW")
    assert w["census_window_on"] == {
        "scatter": ws, "sort": wo, "gather": wg,
    }, w
    assert w["census_window_off"] == {
        "scatter": BASE_STEP_SCATTERS, "sort": BASE_STEP_SORTS,
        "gather": BASE_STEP_GATHERS,
    }, w
    assert w["mirror_bitwise"] is True, w
    assert w["pipelined_bitwise"] is True, w
    assert w["recompiles_steady_state"] == 0, w
    assert w["quantile_rank_err"] <= w["solver_rank_tol"], w
    assert w["windowed_quantile_ms"] < 10.0, w
    assert w["burn_errors"] >= 1, w
    assert w["heatmap_columns"] >= 1, w
    assert w["window_spans_folded"] > 0, w
    # Paged-layout phase (r19 tentpole): the paged fused-step lowering
    # must cost EXACTLY the gated census bump (the ring lowering stays
    # at BASE), queries through the paged layout must answer BITWISE
    # identical to a ring store fed the same skewed stream (whole-trace
    # reads and id lookups), and re-driving warmed shapes through the
    # ingest pipeline must perform ZERO recompiles (page claims are
    # host-side planner work; pad buckets alone pick compiled
    # variants). The ≥2x retention-per-byte acceptance arm lives in
    # bench.py's bench_paged phase (needs the full eviction sweep).
    ps, po, pg = expected_census("+PAGED")
    bs2, bo2, bg2 = expected_census()
    pg_rec = rec["paged"]
    assert pg_rec["census_paged_on"] == {
        "scatter": ps, "sort": po, "gather": pg,
    }, pg_rec
    assert pg_rec["census_paged_off"] == {
        "scatter": bs2, "sort": bo2, "gather": bg2,
    }, pg_rec
    assert pg_rec["query_parity_bitwise"] is True, pg_rec
    assert pg_rec["ids_parity_bitwise"] is True, pg_rec
    assert pg_rec["recompiles_steady_state"] == 0, pg_rec
    assert pg_rec["skewed_spans_per_s"] > 0, pg_rec
    assert pg_rec["pages_active"] >= 1, pg_rec
    # Replication phase (r15 tentpole): a device-free ReplicaSpanStore
    # fed only shipped WAL records over the real framed-TCP ship path
    # must answer the sketch tier and row reads BITWISE identical to
    # the primary at the same applied frontier (mirror arrays equal
    # element-for-element), the whole replication stream must add
    # ZERO jit compiles (the replica is device-free; the warm standby
    # replays into already-compiled shapes), the standby must land a
    # bitwise-equal device state with a measured failover RTO, the
    # follower must catch up to lag 0 under full ingest load, and its
    # cursor must be pinned in the WAL's retention registry.
    rep = rec["replication"]
    assert rep["replica_mirror_bitwise"] is True, rep
    assert rep["replica_answers_identical"] is True, rep
    assert rep["replication_recompiles"] == 0, rep
    assert rep["standby_bitwise"] is True, rep
    assert 0 < rep["failover_rto_s"] < 60.0, rep
    assert rep["caught_up"] is True, rep
    assert rep["records_shipped"] >= 1, rep
    assert rep["shipped_bytes"] > 0, rep
    assert rep["replica_sketch_p50_ms"] < 10.0, rep
    assert rep["follower_cursor_pinned"] is True, rep
    # Sharded-serving phase (r16 tentpole): a 2-shard fleet on the
    # virtual mesh must fuse a barrier-released burst of 8 concurrent
    # reads through the cross-shard dispatcher into AT MOST the two
    # collective launches the design budgets (one fused catalog
    # bundle + one multi-probe kernel), answer them BITWISE identical
    # to serialized re-execution, add ZERO jit recompiles in steady
    # state (the mapped kernels stay resident; batching only changes
    # who launches them), and answer the fleet sketch tier bitwise
    # against a single-device oracle fed the same spans (name-aligned
    # histogram rows + identical HLL registers).
    sh = rec["sharded"]
    assert "skipped" not in sh, sh
    assert sh["shards"] == 2, sh
    assert sh["identical"] is True, sh
    assert sh["errors"] == [], sh
    assert sh["burst_launches"] <= 2, sh
    assert sh["steady_state_recompiles"] == 0, sh
    assert sh["dispatcher_launches_saved"] >= 6, sh
    assert sh["fleet_hist_rows_bitwise"] is True, sh
    assert sh["fleet_hll_bitwise"] is True, sh
    assert sh["service_names_identical"] is True, sh
    # Fleet-observability phase (r17 tentpole): a live primary+
    # follower ship pair under ingest must land ONE causally-linked
    # self-trace spanning encode → WAL append → fsync → ship →
    # follower apply in the primary's own store with verified parent
    # ids; the federated scrape must carry both processes label-
    # distinguished with values bitwise identical to each process's
    # own scrape; the watchdog must fire on an injected parked-fsync
    # error and clear with it; and self-tracing at the production
    # sampling cadence must cost ≤5% ingest wall time while adding
    # ZERO new device launches (compile delta 0, step census equal).
    fo = rec["fleet_obs"]
    assert fo["trace_roundtrip"] is True, fo
    assert fo["parent_ids_ok"] is True, fo
    assert fo["federation_labels_ok"] is True, fo
    assert fo["federation_bitwise"] is True, fo
    assert fo["visible_lag_recorded"] is True, fo
    assert fo["watchdog_fired"] is True, fo
    assert fo["watchdog_cleared"] is True, fo
    assert fo["overhead_ratio"] <= 1.05, fo
    assert fo["lineage_steady_state_compiles"] == 0, fo
    assert fo["census_equal"] is True, fo
    assert fo["fleet_processes"] == 2, fo
    # graftlint phase (this PR's tentpole): the concurrency/JAX-hazard
    # analyzer must cover the whole package, find ZERO findings not in
    # the checked-in baseline, and stay inside its 30s budget (the
    # fixture-corpus sensitivity pins live in tests/test_analysis.py;
    # this gates the smoke wiring end-to-end).
    lint = rec["lint"]
    assert lint["findings_new"] == 0, lint
    assert lint["files"] >= 80, lint
    assert lint["locks"] >= 25, lint
    assert lint["elapsed_s"] < 30.0, lint
