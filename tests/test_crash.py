"""Crash-injection matrix: SIGKILL the ingest process at every named
point, restart, and prove the durability contract (docs/DURABILITY.md):

- every durably-ACKED batch survives recovery,
- the recovered state is BITWISE identical to an uncrashed oracle
  drive of exactly the recovered prefix (hot rings, index arena,
  counters — and for tiered drives the cold segment frontier and
  federated reads),
- un-acked tail batches are provably absent, never partially applied.

The child (zipkin_tpu.testing.crash) is a REAL process that dies by
SIGKILL mid-write — no mocked fsync, no in-process simulation. One
smoke scenario runs in the tier-1 lane; the full kill-point matrix
(checkpoint swaps, WAL truncation, cold-tier sealing) is slow-lane.
"""

import signal

import pytest

from zipkin_tpu.testing.crash import (
    acked_batches,
    run_crash_child,
    verify_recovery,
)

SIGKILLED = -signal.SIGKILL


def _crash_and_verify(tmp_path, point, hit, batches, ckpt_at=(),
                      tiered=False, segment_bytes=64 << 20):
    wd = str(tmp_path)
    proc = run_crash_child(wd, point=point, hit=hit, batches=batches,
                           ckpt_at=ckpt_at, tiered=tiered,
                           segment_bytes=segment_bytes)
    assert proc.returncode == SIGKILLED, (
        f"child survived {point}:{hit} (rc {proc.returncode})\n"
        f"{proc.stderr[-2000:]}")
    return verify_recovery(wd, total_batches=batches, tiered=tiered)


# -- tier-1 smoke --------------------------------------------------------


def test_crash_smoke_kill_after_append_recovers_exactly(tmp_path):
    """After-append/before-commit is the canonical hole journaling
    closes: the record is durable, the device commit never ran. The
    kill lands mid-drive with a checkpoint already covering part of
    the log, so recovery exercises restore + truncated-prefix replay
    in one pass."""
    info = _crash_and_verify(tmp_path, "after-append", hit=4,
                             batches=6, ckpt_at=(2,))
    # the killed batch was appended but never acked: replay applied it
    # anyway (append is one-way durable) and acked stayed behind
    assert info["applied"] == 4
    assert info["acked"] == 3


# -- full kill-point matrix (slow lane) ----------------------------------


def test_crash_before_append_loses_only_the_unacked_batch(tmp_path):
    info = _crash_and_verify(tmp_path, "before-append", hit=5,
                             batches=8, ckpt_at=(3,))
    # batch 5 never reached the log: exactly the acked prefix survives
    assert info["applied"] == info["acked"] == 4


def test_crash_after_commit_before_ack(tmp_path):
    info = _crash_and_verify(tmp_path, "after-commit", hit=5,
                             batches=8, ckpt_at=(3,))
    # committed AND journaled, but the ack never went out — recovery
    # keeps it (durability is one-way: acked => present)
    assert info["applied"] == 5
    assert info["acked"] == 4


def test_crash_mid_first_checkpoint_recovers_from_wal_alone(tmp_path):
    # the kill lands between checkpoint.save's two renames on the
    # FIRST save: no snapshot exists at all; recovery must rebuild a
    # fresh store and replay the full log
    info = _crash_and_verify(tmp_path, "mid-checkpoint", hit=1,
                             batches=8, ckpt_at=(5,))
    assert info["applied"] == info["acked"] == 5


def test_crash_mid_second_checkpoint_falls_back_to_old(tmp_path):
    # the second save dies mid-swap: the first snapshot survives only
    # as ``ckpt.old``; load's fallback + tail replay must cover it —
    # and the WAL was not yet truncated by the dead save, so the tail
    # is still there
    info = _crash_and_verify(tmp_path, "mid-checkpoint", hit=2,
                             batches=10, ckpt_at=(4, 8))
    assert info["applied"] == info["acked"] == 8

def test_crash_mid_truncate_leaves_recoverable_suffix(tmp_path):
    # tiny segments so the checkpoint's truncation deletes several
    # files; the kill lands between per-segment deletes — the
    # surviving suffix plus the snapshot must still cover everything
    info = _crash_and_verify(tmp_path, "mid-truncate", hit=2,
                             batches=8, ckpt_at=(6,),
                             segment_bytes=1 << 12)
    assert info["applied"] == info["acked"] == 6


def test_crash_mid_seal_replays_capture_and_cold_tier(tmp_path):
    # tiered drive over a 2^8 ring: the kill lands between an eviction
    # capture pull and the cold segment append; replay must re-capture
    # and re-seal to an identical cold tier
    info = _crash_and_verify(tmp_path, "mid-seal", hit=2,
                             batches=30, tiered=True)
    assert info["applied"] >= info["acked"]
    assert info["replayed_records"] > 0


def test_crash_mid_seal_with_checkpoint(tmp_path):
    info = _crash_and_verify(tmp_path, "mid-seal", hit=3,
                             batches=30, ckpt_at=(10,), tiered=True)
    assert info["applied"] >= info["acked"]


def test_clean_child_exits_zero(tmp_path):
    # harness sanity: with no kill point the drive completes
    proc = run_crash_child(str(tmp_path), point=None, batches=4,
                           ckpt_at=(2,))
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert acked_batches(str(tmp_path)) == 4
