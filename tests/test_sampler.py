"""Sampler tests: threshold semantics + each adaptive stage as a pure fn
(reference pattern: AdaptiveSamplerTest tests stages without ZK)."""

import jax.numpy as jnp
import numpy as np
import pytest

from zipkin_tpu.sampler import (
    AdaptiveConfig,
    AdaptiveSampleRateController,
    Sampler,
    calculate_sample_rate,
    cooldown_check,
    discounted_average,
    outlier_check,
    rate_to_threshold,
    request_rate_check,
    sample_mask,
    sufficient_data_check,
    valid_data_check,
)
from zipkin_tpu.sampler.core import LONG_MAX, LONG_MIN


class TestSamplerCore:
    def test_rate_one_keeps_everything(self):
        tids = jnp.asarray([0, 1, -1, LONG_MAX, LONG_MIN], jnp.int64)
        mask = sample_mask(tids, jnp.zeros(5, bool), rate_to_threshold(1.0))
        assert bool(mask.all())

    def test_rate_zero_drops_everything_except_debug(self):
        tids = jnp.asarray([5, LONG_MAX, -7], jnp.int64)
        debug = jnp.asarray([False, False, True])
        mask = sample_mask(tids, debug, rate_to_threshold(0.0))
        np.testing.assert_array_equal(np.asarray(mask), [False, False, True])

    def test_statistical_rate(self):
        rng = np.random.default_rng(3)
        tids = rng.integers(LONG_MIN, LONG_MAX, size=200_000, dtype=np.int64)
        mask = sample_mask(jnp.asarray(tids), jnp.zeros(len(tids), bool),
                           rate_to_threshold(0.2))
        frac = float(np.asarray(mask).mean())
        assert abs(frac - 0.2) < 0.01

    def test_consistent_with_host_sampler(self):
        rng = np.random.default_rng(4)
        tids = rng.integers(LONG_MIN, LONG_MAX, size=500, dtype=np.int64)
        s = Sampler(0.35)
        host = np.array([s(int(t)) for t in tids])
        dev = np.asarray(
            sample_mask(jnp.asarray(tids), jnp.zeros(500, bool), s.threshold)
        )
        np.testing.assert_array_equal(host, dev)

    def test_min_value_maps_to_max(self):
        # Long.MinValue is treated as MaxValue → kept at any rate > 0.
        mask = sample_mask(jnp.asarray([LONG_MIN], jnp.int64),
                           jnp.zeros(1, bool), rate_to_threshold(0.01))
        assert bool(mask[0])


class TestStages:
    def test_request_rate_check(self):
        assert request_rate_check([1.0], 0) is None
        assert request_rate_check([1.0], 10) == [1.0]
        assert request_rate_check(None, 10) is None

    def test_sufficient_data_check(self):
        assert sufficient_data_check([1, 2], 3) is None
        assert sufficient_data_check([1, 2, 3], 3) == [1, 2, 3]

    def test_valid_data_check(self):
        assert valid_data_check([1, 0, 2]) == [1, 0, 2]
        assert valid_data_check([1, -1]) is None

    def test_outlier_check_requires_persistent_deviation(self):
        target = 100.0
        # all within 15% → no move
        assert outlier_check([100, 105, 110], target, 3) is None
        # persistently above
        assert outlier_check([200, 210, 190], target, 3) is not None
        # one in-range sample in the tail cancels it
        assert outlier_check([200, 100, 190], target, 3) is None

    def test_discounted_average_weights_recent(self):
        # newest sample (last) dominates
        avg_rising = discounted_average([0, 0, 100])
        avg_falling = discounted_average([100, 0, 0])
        assert avg_rising > avg_falling
        assert discounted_average([50, 50, 50]) == pytest.approx(50)

    def test_calculate_sample_rate_linear_controller(self):
        # storing 200/min, target 100/min, rate 1.0 → halve
        got = calculate_sample_rate([200.0] * 5, 1.0, 100.0)
        assert got == pytest.approx(0.5, rel=0.01)

    def test_calculate_sample_rate_change_threshold(self):
        # 3% change is under the 5% threshold → no update
        assert calculate_sample_rate([103.0] * 5, 1.0, 100.0) is None

    def test_calculate_sample_rate_clamped(self):
        got = calculate_sample_rate([10.0] * 5, 0.5, 100.0)
        assert got == 1.0  # would be 5.0, clamped

    def test_cooldown(self):
        assert cooldown_check(0.5, 10.0, None, 30.0) == 0.5
        assert cooldown_check(0.5, 10.0, 0.0, 30.0) is None
        assert cooldown_check(0.5, 40.0, 0.0, 30.0) == 0.5


class TestController:
    def make(self, target=100.0):
        cfg = AdaptiveConfig(
            target_store_rate=target, update_freq_s=30.0,
            window_s=300.0, sufficient_window_s=90.0, outlier_window_s=60.0,
        )
        return AdaptiveSampleRateController(cfg)

    def test_converges_toward_target(self):
        ctl = self.make(target=100.0)
        # Closed loop: the raw flow is 400 spans/min; the store sees
        # flow * rate. The controller should settle near rate 0.25.
        now = 0.0
        for _ in range(20):
            ctl.observe(400.0 * ctl.rate, now)
            now += 30
        assert ctl.rate == pytest.approx(0.25, rel=0.15)

    def test_no_move_when_on_target(self):
        ctl = self.make(target=100.0)
        now = 0.0
        moved = [ctl.observe(100.0, now + 30 * i) for i in range(10)]
        assert all(m is None for m in moved)
        assert ctl.rate == 1.0

    def test_disabled_when_target_zero(self):
        ctl = self.make(target=0.0)
        assert all(ctl.observe(500.0, 30.0 * i) is None for i in range(10))
