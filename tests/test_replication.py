"""WAL-shipped replication (zipkin_tpu.replicate + store/replica):
device-free replica bitwise agreement at a fixed frontier, the
durable-only ship bound (un-acked tail absent in full), gap/idempotent
apply semantics, the TCP ship path incl. anchor bootstrap, warm-standby
follow + promote, replica retention, the pre-rev-14 cold-resync compat
path, and (slow lane) crash-during-ship reconnect/recovery/truncation
races."""

import os
import json
import tempfile
import time

import numpy as np
import pytest

from zipkin_tpu.replicate import (
    Follower,
    ReplicaTarget,
    ShipClient,
    ShipServer,
    StandbyTarget,
    WalShipper,
)
from zipkin_tpu.replicate.protocol import config_from_dict
from zipkin_tpu.store import device as dev
from zipkin_tpu.store.archive import TieredSpanStore
from zipkin_tpu.store.replica import ReplicaSpanStore, ReplicaReadOnlyError
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.testing.crash import states_bitwise_equal
from zipkin_tpu.tracegen import generate_traces
from zipkin_tpu.wal import WalReplayError, WriteAheadLog, recover

CFG = dev.StoreConfig(
    capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
    max_services=32, max_span_names=256, max_annotation_values=256,
    max_binary_keys=64, cms_width=1 << 10, hll_p=8,
    quantile_buckets=512,
)


def _spans(n=2400, n_traces=500, seed_services=12):
    traces = generate_traces(n_traces=n_traces, max_depth=3,
                             n_services=seed_services)
    return [s for t in traces for s in t][:n]


def _feed(store, spans, chunk=128):
    for i in range(0, len(spans), chunk):
        store.apply(spans[i:i + chunk])


def _mirror_equal(a, b) -> bool:
    return all(np.array_equal(x, y) for x, y in zip(a, b))


@pytest.fixture()
def wal_dir(tmp_path):
    return str(tmp_path / "wal")


def _replay_into_replica(wal, replica, from_seq=0):
    for seq, payload in wal.replay(from_seq):
        replica.apply_record(seq, payload)


class TestReplicaAgreement:
    def test_replica_bitwise_agreement_at_fixed_frontier(self, wal_dir):
        """The acceptance gate: a device-free replica fed only WAL
        records answers the sketch tier AND row/index reads identical
        to the tiered primary at the same applied frontier — mirror
        arrays bitwise equal to the primary's device aggregates."""
        import jax

        primary = TieredSpanStore(TpuSpanStore(CFG))
        wal = WriteAheadLog(wal_dir, fsync="off")
        primary.attach_wal(wal)
        spans = _spans()
        _feed(primary, spans)
        replica = ReplicaSpanStore(CFG, background_compaction=False)
        try:
            _replay_into_replica(wal, replica)
            hot = primary.hot
            st = hot.state
            device_arrays = [np.asarray(a) for a in jax.device_get((
                st.svc_hist, st.ann_svc_counts, st.name_presence,
                st.ann_value_counts, st.bann_key_counts,
                st.hll_traces, st.win_epoch, st.win_counts,
                st.win_sums, st.win_mm))]
            assert _mirror_equal(device_arrays,
                                 replica.sketch_mirror.arrays())
            # Catalogs + aggregates.
            assert (replica.get_all_service_names()
                    == primary.get_all_service_names())
            svcs = sorted(primary.get_all_service_names())
            for svc in svcs[:4]:
                assert (replica.get_span_names(svc)
                        == primary.get_span_names(svc)), svc
                assert (replica.service_duration_quantiles(
                    svc, [0.5, 0.95, 0.99])
                    == primary.service_duration_quantiles(
                        svc, [0.5, 0.95, 0.99])), svc
                assert (replica.top_annotations(svc)
                        == primary.top_annotations(svc)), svc
                assert (replica.top_binary_keys(svc)
                        == primary.top_binary_keys(svc)), svc
            assert (replica.estimated_unique_traces()
                    == primary.estimated_unique_traces())
            # Row + index reads (cold segments vs hot+cold federation).
            tids = sorted({s.trace_id for s in spans[::37]})[:20]
            assert (replica.get_spans_by_trace_ids(tids)
                    == primary.get_spans_by_trace_ids(tids))
            assert (replica.traces_exist(tids)
                    == primary.traces_exist(tids))
            assert (replica.get_traces_duration(tids)
                    == primary.get_traces_duration(tids))
            end_ts = 1 << 62
            for svc in svcs[:4]:
                assert (replica.get_trace_ids_by_name(
                    svc, None, end_ts, 10)
                    == primary.get_trace_ids_by_name(
                        svc, None, end_ts, 10)), svc
            # Staleness is explicit.
            assert replica.applied_seq() == wal.last_seq
            f0 = replica.write_frontier()
            assert replica.write_frontier() == f0
        finally:
            replica.close()
            wal.close()

    def test_unacked_tail_absent_in_full(self, wal_dir):
        """The ship feed is bounded by the DURABLE frontier: records
        the primary has not fsynced are never handed to a follower, so
        a primary crash can never leave a replica ahead of recovery."""
        primary = TpuSpanStore(CFG)
        # Huge group-commit interval: appends stay un-durable until an
        # explicit sync — the durable frontier visibly lags.
        wal = WriteAheadLog(wal_dir, fsync="interval", interval_s=3600)
        primary.attach_wal(wal)
        shipper = WalShipper(primary, wal)
        _feed(primary, _spans(n=600, n_traces=120))
        assert wal.durable_seq < wal.last_seq
        got = shipper.fetch("f1", 0, 1 << 30)
        assert got is not None
        records, last, durable = got
        assert last == wal.last_seq and durable == wal.durable_seq
        assert all(seq <= durable for seq, _ in records)
        assert len(records) == durable
        wal.sync()
        records2, _, durable2 = shipper.fetch("f1", durable, 1 << 30)
        assert durable2 == wal.last_seq
        assert [s for s, _ in records2] == list(
            range(durable + 1, wal.last_seq + 1))
        shipper.close()
        wal.close()

    def test_replica_gap_rejected_duplicate_skipped(self, wal_dir):
        primary = TpuSpanStore(CFG)
        wal = WriteAheadLog(wal_dir, fsync="off")
        primary.attach_wal(wal)
        _feed(primary, _spans(n=600, n_traces=120))
        records = list(wal.replay(0))
        assert len(records) >= 3
        replica = ReplicaSpanStore(CFG, background_compaction=False)
        try:
            replica.apply_record(*records[0])
            # Duplicate: idempotent no-op.
            assert replica.apply_record(*records[0]) == 0
            # Gap: lineage error, nothing applied.
            with pytest.raises(WalReplayError):
                replica.apply_record(*records[2])
            assert replica.applied_seq() == records[0][0]
            # In-order continues fine.
            replica.apply_record(*records[1])
            assert replica.applied_seq() == records[1][0]
            # Writes are refused.
            with pytest.raises(ReplicaReadOnlyError):
                replica.apply([])
            with pytest.raises(ReplicaReadOnlyError):
                replica.set_time_to_live(1, 60.0)
        finally:
            replica.close()
            wal.close()

    def test_replica_retention_drops_old_segments(self, wal_dir):
        primary = TpuSpanStore(CFG)
        wal = WriteAheadLog(wal_dir, fsync="off")
        primary.attach_wal(wal)
        spans = _spans(n=2000, n_traces=400)
        _feed(primary, spans)
        replica = ReplicaSpanStore(CFG, retain_spans=512,
                                   background_compaction=False)
        try:
            _replay_into_replica(wal, replica)
            segs = replica.archive.snapshot()
            assert segs, "retention dropped everything"
            lo = min(s.gid_lo for s in segs)
            wp = replica.counters()["replica_wp"]
            assert lo >= wp - 512 - CFG.capacity  # whole segments only
            # Recent traces still read; the sketch tier still covers
            # the WHOLE history (mirror is lifetime state).
            recent = [spans[-1].trace_id]
            assert replica.get_spans_by_trace_ids(recent)
            assert (replica.estimated_unique_traces()
                    == primary.estimated_unique_traces())
        finally:
            replica.close()
            wal.close()


class TestShipWire:
    def _serve(self, primary):
        shipper = WalShipper(primary)
        server = ShipServer(shipper, host="127.0.0.1", port=0)
        server.serve_in_thread()
        return shipper, server, server.server_address[1]

    def test_tcp_follow_and_anchor_bootstrap(self, wal_dir):
        primary = TieredSpanStore(TpuSpanStore(CFG))
        wal = WriteAheadLog(wal_dir, fsync="off")
        primary.attach_wal(wal)
        shipper, server, port = self._serve(primary)
        spans = _spans(n=1600, n_traces=320)
        half = 768
        _feed(primary, spans[:half])
        client = ShipClient("127.0.0.1", port, "t1", mode="replica")
        hello = client.connect()
        assert config_from_dict(hello["config"]) == CFG
        replica = ReplicaSpanStore(CFG, background_compaction=False)
        follower = Follower(ReplicaTarget(replica), client,
                            poll_interval_s=0.002).start()
        _feed(primary, spans[half:])
        wal.sync()
        try:
            assert follower.drain(60.0), follower.status()
            assert _mirror_equal(
                primary.hot.ensure_sketch_mirror().arrays(),
                replica.sketch_mirror.arrays())
            status = follower.status()
            assert status["lagRecords"] == 0
            assert status["role"] == "replica"
            assert shipper.status()["followers"]["t1"]["cursor"] >= 1
            # Anchor bootstrap: release the pin, truncate the whole
            # log, and bring up a SECOND replica from nothing — it
            # must adopt the anchor (sketch tier exact from genesis)
            # and resume at the primary's frontier.
            wal.drop_cursor("t1")
            assert wal.truncate(wal.last_seq) >= 1
            c2 = ShipClient("127.0.0.1", port, "t2", mode="replica")
            c2.connect()
            rep2 = ReplicaSpanStore(CFG, background_compaction=False)
            f2 = Follower(ReplicaTarget(rep2), c2,
                          poll_interval_s=0.002)
            try:
                assert f2.step() is True  # NEED_ANCHOR -> adopt
                assert rep2.applied_seq() == wal.last_seq
                assert _mirror_equal(
                    replica.sketch_mirror.arrays(),
                    rep2.sketch_mirror.arrays())
                assert (rep2.estimated_unique_traces()
                        == primary.estimated_unique_traces())
                # Row coverage starts at the anchor (documented):
                # no segments yet, sketch tier fully live.
                assert len(rep2.archive) == 0
            finally:
                f2.close()
                rep2.close()
        finally:
            follower.close()
            replica.close()
            server.shutdown()
            wal.close()

    def test_standby_follow_promote_bitwise(self, wal_dir):
        primary = TpuSpanStore(CFG)
        wal = WriteAheadLog(wal_dir, fsync="off")
        primary.attach_wal(wal)
        _shipper, server, port = self._serve(primary)
        spans = _spans(n=1600, n_traces=320)
        client = ShipClient("127.0.0.1", port, "sby", mode="standby")
        client.connect()
        standby = TpuSpanStore(CFG)
        follower = Follower(StandbyTarget(standby), client,
                            poll_interval_s=0.002).start()
        try:
            _feed(primary, spans)
            wal.sync()
            assert follower.drain(60.0), follower.status()
            promoted = follower.promote()
            assert promoted is standby
            assert states_bitwise_equal(primary.state, promoted.state)
            # The promoted store owns writes now.
            promoted.apply(spans[:32])
        finally:
            server.shutdown()
            wal.close()


class TestStandbyAck:
    def test_standby_acks_checkpoint_frontier_not_applied(
            self, wal_dir):
        """The retention pin must track what the standby can recover
        to on its OWN (its checkpointed frontier), never its volatile
        applied frontier — otherwise the primary may truncate records
        a crashed standby still needs, and a standby cannot
        anchor-bootstrap out of that hole."""
        primary = TpuSpanStore(CFG)
        wal = WriteAheadLog(wal_dir, fsync="off")
        primary.attach_wal(wal)
        _feed(primary, _spans(n=600, n_traces=120))
        shipper = WalShipper(primary, wal)
        standby = TpuSpanStore(CFG)
        target = StandbyTarget(standby)
        # Hand-drive one fetch round the way Follower.step does.
        got = shipper.fetch("sby", target.applied_seq(), 1 << 30,
                            ack=target.ack_seq())
        for seq, payload in got[0]:
            target.apply(seq, payload)
        assert target.applied_seq() == wal.last_seq
        # Applied is ahead, but NOTHING is locally durable yet: the
        # pin (ack) must still be 0 and truncation must delete nothing.
        assert target.ack_seq() == 0
        shipper.fetch("sby", target.applied_seq(), 1 << 30,
                      ack=target.ack_seq())
        assert wal.truncate(wal.last_seq) == 0
        assert [s for s, _ in wal.replay(0)][0] == 1
        # A successful local checkpoint advances the ack; only then
        # may the covered prefix go.
        target.note_checkpointed(target.applied_seq())
        assert target.ack_seq() == wal.last_seq
        shipper.fetch("sby", target.applied_seq(), 1 << 30,
                      ack=target.ack_seq())
        assert wal.truncate(wal.last_seq) >= 1
        shipper.close()
        wal.close()


class TestColdResync:
    def test_pre_rev14_checkpoint_plus_replicated_tail_resync(
            self, tmp_path):
        """The satellite: a standby restored from a PRE-rev-14
        checkpoint (no window leaves — empty arena) fed the replicated
        WAL tail must lazily resync its sketch mirror (the adopt_state
        path: restore marks it cold, ensure_sketch_mirror refetches)
        BITWISE to its own device aggregates, window twins included —
        and its lifetime sketches must match the uncrashed oracle."""
        import jax

        from zipkin_tpu import checkpoint

        cfg = CFG._replace(window_seconds=60, window_buckets=8)
        primary = TpuSpanStore(cfg)
        wal = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
        primary.attach_wal(wal)
        spans = _spans(n=1200, n_traces=240)
        _feed(primary, spans[:600])
        path = str(tmp_path / "ckpt")
        checkpoint.save(primary, path)
        _feed(primary, spans[600:])  # the replicated tail
        wal.sync()

        # Doctor the snapshot into pre-rev-14 shape (the r13 compat
        # idiom: drop win_* leaves + window config keys).
        state_file = os.path.join(path, "state.npz")
        data = dict(np.load(state_file))
        for k in list(data):
            if k.startswith("win_"):
                del data[k]
        np.savez(state_file, **data)
        meta_file = os.path.join(path, "meta.json")
        with open(meta_file) as f:
            meta = json.load(f)
        meta["revision"] = 13
        for k in ("window_seconds", "window_buckets"):
            meta["config"].pop(k, None)
        meta["slab_crc32"] = {
            k: v for k, v in (meta.get("slab_crc32") or {}).items()
            if not k.startswith("win_")
        }
        with open(meta_file, "w") as f:
            json.dump(meta, f)

        standby = checkpoint.load(path, config_defaults={
            "window_seconds": 60, "window_buckets": 8,
        })
        assert standby.config.window_enabled
        assert not standby.sketch_mirror.warm  # restore marked cold
        target = StandbyTarget(standby)
        for seq, payload in wal.replay(int(standby._wal_applied)):
            target.apply(seq, payload)
        assert int(standby._wal_applied) == wal.last_seq
        # Lazy resync == the device truth, window twins included.
        m = standby.ensure_sketch_mirror()
        st = standby.state
        device_arrays = [np.asarray(a) for a in jax.device_get((
            st.svc_hist, st.ann_svc_counts, st.name_presence,
            st.ann_value_counts, st.bann_key_counts, st.hll_traces,
            st.win_epoch, st.win_counts, st.win_sums, st.win_mm))]
        assert _mirror_equal(device_arrays, m.arrays())
        # Lifetime sketches survive the rev-13 snapshot: they match
        # the uncrashed oracle exactly. (The window arena holds only
        # the post-checkpoint tail BY DESIGN — pre-14 snapshots carry
        # no arena; its twins are gated against the device above.)
        oracle_m = primary.ensure_sketch_mirror().arrays()
        assert _mirror_equal(oracle_m[:6], m.arrays()[:6])
        wal.close()


@pytest.mark.slow
class TestCrashDuringShip:
    def test_follower_reconnects_across_server_restart(self, wal_dir):
        """Crash-during-ship: the ship endpoint dies mid-stream; the
        follower backs off, reconnects when the endpoint returns
        (same port), and converges bitwise with nothing skipped."""
        primary = TpuSpanStore(CFG)
        wal = WriteAheadLog(wal_dir, fsync="off")
        primary.attach_wal(wal)
        shipper = WalShipper(primary)
        server = ShipServer(shipper, host="127.0.0.1", port=0)
        port = server.server_address[1]
        server.serve_in_thread()
        spans = _spans(n=2000, n_traces=400)
        client = ShipClient("127.0.0.1", port, "rc", mode="replica")
        client.connect()
        replica = ReplicaSpanStore(CFG, background_compaction=False)
        follower = Follower(ReplicaTarget(replica), client,
                            poll_interval_s=0.002).start()
        try:
            _feed(primary, spans[:768])
            deadline = time.monotonic() + 30
            while (replica.applied_seq() == 0
                    and time.monotonic() < deadline):
                time.sleep(0.005)
            assert replica.applied_seq() > 0
            # Kill the endpoint mid-stream, keep feeding.
            server.shutdown()
            server.server_close()
            _feed(primary, spans[768:1408])
            # Resurrect on the SAME port; follower reconnects itself.
            server = ShipServer(shipper, host="127.0.0.1", port=port)
            server.serve_in_thread()
            _feed(primary, spans[1408:])
            wal.sync()
            assert follower.drain(60.0), follower.status()
            assert _mirror_equal(
                primary.ensure_sketch_mirror().arrays(),
                replica.sketch_mirror.arrays())
        finally:
            follower.close()
            replica.close()
            server.shutdown()
            wal.close()

    def test_primary_crash_recovery_resumes_ship(self, wal_dir):
        """The primary process dies and recovers from its own WAL; the
        follower's cursor stays valid (prefix semantics) and the
        replica converges with the RECOVERED primary bitwise."""
        primary = TpuSpanStore(CFG)
        wal = WriteAheadLog(wal_dir, fsync="off")
        primary.attach_wal(wal)
        spans = _spans(n=1600, n_traces=320)
        _feed(primary, spans[:768])
        replica = ReplicaSpanStore(CFG, background_compaction=False)
        _replay_into_replica(wal, replica)
        cursor = replica.applied_seq()
        # "Crash": drop the store + log objects on the floor; recover
        # from disk exactly like the daemon boot path.
        wal.close()
        del primary
        wal2 = WriteAheadLog(wal_dir, fsync="off")
        recovered, stats = recover(
            None, wal2, fresh_store=lambda: TpuSpanStore(CFG))
        assert stats["replayed_records"] >= 1
        _feed(recovered, spans[768:])
        wal2.sync()
        _replay_into_replica(wal2, replica, from_seq=cursor)
        try:
            assert _mirror_equal(
                recovered.ensure_sketch_mirror().arrays(),
                replica.sketch_mirror.arrays())
            assert replica.applied_seq() == wal2.last_seq
        finally:
            replica.close()
            wal2.close()

    def test_truncation_never_outruns_pinned_follower(self, wal_dir):
        """Aggressive checkpoint-style truncation after every batch
        races the follower's fetches: the cursor pin means no record
        is ever skipped and the replica still converges bitwise."""
        primary = TpuSpanStore(CFG)
        wal = WriteAheadLog(wal_dir, fsync="off")
        primary.attach_wal(wal)
        shipper = WalShipper(primary)
        server = ShipServer(shipper, host="127.0.0.1", port=0)
        port = server.server_address[1]
        server.serve_in_thread()
        client = ShipClient("127.0.0.1", port, "pin", mode="replica")
        client.connect()
        replica = ReplicaSpanStore(CFG, background_compaction=False)
        follower = Follower(ReplicaTarget(replica), client,
                            poll_interval_s=0.001).start()
        spans = _spans(n=2000, n_traces=400)
        try:
            for i in range(0, len(spans), 128):
                primary.apply(spans[i:i + 128])
                # The checkpoint contract: everything applied is
                # covered — without the pin this deletes fetchable
                # history out from under the follower.
                wal.truncate(int(primary._wal_applied))
            wal.sync()
            assert follower.drain(60.0), follower.status()
            assert replica.applied_seq() == wal.last_seq
            assert _mirror_equal(
                primary.ensure_sketch_mirror().arrays(),
                replica.sketch_mirror.arrays())
        finally:
            follower.close()
            replica.close()
            server.shutdown()
            wal.close()
