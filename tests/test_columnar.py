"""Columnar codec tests: dictionary encoding + Span↔SpanBatch roundtrip."""

import numpy as np
import pytest

from zipkin_tpu.columnar import (
    FLAG_DEBUG,
    FLAG_HAS_PARENT,
    NO_SERVICE,
    NO_TS,
    SpanBatch,
    SpanCodec,
)
from zipkin_tpu.columnar.dictionary import Dictionary, DictionarySet
from zipkin_tpu.columnar.encode import to_signed64
from zipkin_tpu.models.constants import CORE_ANNOTATION_IDS, FIRST_USER_ANNOTATION_ID
from zipkin_tpu.models.span import (
    Annotation,
    AnnotationType,
    BinaryAnnotation,
    Endpoint,
    Span,
)

EP_WEB = Endpoint(ipv4=0x7F000001, port=80, service_name="web")
EP_DB = Endpoint(ipv4=0x7F000002, port=5432, service_name="db")


def make_span(trace_id=1, span_id=100, parent=None, name="get", debug=False):
    return Span(
        trace_id=trace_id,
        name=name,
        id=span_id,
        parent_id=parent,
        annotations=(
            Annotation(1000, "cs", EP_WEB),
            Annotation(1500, "custom-event", EP_WEB),
            Annotation(2000, "cr", EP_WEB),
        ),
        binary_annotations=(
            BinaryAnnotation("http.uri", "/widgets", AnnotationType.STRING, EP_WEB),
            BinaryAnnotation("payload", b"\x00\x01", AnnotationType.BYTES, None),
        ),
        debug=debug,
    )


class TestDictionary:
    def test_dense_ids_first_seen_order(self):
        d = Dictionary()
        assert d.encode("a") == 0
        assert d.encode("b") == 1
        assert d.encode("a") == 0
        assert d.decode(1) == "b"
        assert len(d) == 2

    def test_reserved_ids(self):
        d = Dictionary(reserved={"cs": 0, "sa": 5})
        assert d.encode("cs") == 0
        assert d.encode("sa") == 5
        assert d.encode("new") == 6

    def test_get_without_assign(self):
        d = Dictionary()
        assert d.get("missing") is None
        assert len(d) == 0

    def test_core_annotation_ids_reserved(self):
        ds = DictionarySet()
        for value, vid in CORE_ANNOTATION_IDS.items():
            assert ds.annotations.encode(value) == vid
        assert ds.annotations.encode("userann") >= FIRST_USER_ANNOTATION_ID


class TestSigned64:
    def test_roundtrip_boundaries(self):
        for x in (0, 1, -1, 2**63 - 1, -(2**63)):
            assert to_signed64(x) == x
        assert to_signed64(2**63) == -(2**63)
        assert to_signed64(2**64 - 1) == -1


class TestCodecRoundtrip:
    def test_roundtrip_lossless(self):
        spans = [
            make_span(trace_id=1, span_id=100, parent=None, debug=True),
            make_span(trace_id=1, span_id=101, parent=100, name="child"),
            make_span(trace_id=-5, span_id=-7, parent=-9),
            Span(trace_id=2, name="bare", id=3),  # no annotations at all
        ]
        codec = SpanCodec()
        batch = codec.encode(spans)
        assert batch.n_spans == 4
        decoded = codec.decode(batch)
        assert decoded == spans

    def test_core_ts_columns(self):
        codec = SpanCodec()
        b = codec.encode(
            [
                Span(
                    trace_id=1,
                    name="rpc",
                    id=2,
                    annotations=(
                        Annotation(10, "cs", EP_WEB),
                        Annotation(12, "sr", EP_DB),
                        Annotation(18, "ss", EP_DB),
                        Annotation(20, "cr", EP_WEB),
                    ),
                )
            ]
        )
        assert b.ts_cs[0] == 10 and b.ts_sr[0] == 12
        assert b.ts_ss[0] == 18 and b.ts_cr[0] == 20
        assert b.ts_first[0] == 10 and b.ts_last[0] == 20
        assert b.duration[0] == 10

    def test_missing_fields_sentinels(self):
        codec = SpanCodec()
        b = codec.encode([Span(trace_id=1, name="bare", id=3)])
        assert b.ts_cs[0] == NO_TS and b.duration[0] == NO_TS
        assert b.service_id[0] == NO_SERVICE
        assert not (b.flags[0] & FLAG_HAS_PARENT)

    def test_flags(self):
        codec = SpanCodec()
        b = codec.encode([make_span(debug=True, parent=99)])
        assert b.flags[0] & FLAG_DEBUG
        assert b.flags[0] & FLAG_HAS_PARENT
        assert b.parent_id[0] == 99

    def test_service_id_is_owning_service_lowercased(self):
        ep = Endpoint(service_name="WEB")
        codec = SpanCodec()
        b = codec.encode(
            [Span(trace_id=1, name="x", id=1, annotations=(Annotation(1, "sr", ep),))]
        )
        assert codec.dicts.services.decode(int(b.service_id[0])) == "web"

    def test_shared_dictionaries_across_batches(self):
        codec = SpanCodec()
        b1 = codec.encode([make_span(trace_id=1)])
        b2 = codec.encode([make_span(trace_id=2)])
        assert b1.name_id[0] == b2.name_id[0]
        assert b1.service_id[0] == b2.service_id[0]


class TestBatchOps:
    def test_concat_rebases_span_idx(self):
        codec = SpanCodec()
        b1 = codec.encode([make_span(trace_id=1, span_id=1)])
        b2 = codec.encode([make_span(trace_id=2, span_id=2)])
        cat = b1.concat(b2)
        assert cat.n_spans == 2
        assert cat.n_annotations == b1.n_annotations + b2.n_annotations
        assert set(cat.ann_span_idx[-3:]) == {1}
        assert codec.decode(cat) == codec.decode(b1) + codec.decode(b2)

    def test_select_mask_and_indices(self):
        codec = SpanCodec()
        spans = [make_span(trace_id=t, span_id=t * 10) for t in (1, 2, 3)]
        batch = codec.encode(spans)
        sub = batch.select(np.array([True, False, True]))
        assert codec.decode(sub) == [spans[0], spans[2]]
        sub2 = batch.select(np.array([2, 0]))
        assert codec.decode(sub2) == [spans[2], spans[0]]

    def test_empty(self):
        b = SpanBatch.empty()
        assert b.n_spans == 0 and b.n_annotations == 0 and b.n_binary == 0
        assert SpanCodec().decode(b) == []
