"""Robustness fixes from the round-1 advisory: depth-bounded thrift
skip, native/python codec parity on unnamed endpoints, TTL key
canonicalization, and store concurrency (RWLock)."""

import struct
import threading
import time

import pytest

from zipkin_tpu.concurrency import RWLock
from zipkin_tpu.models.span import Annotation, Endpoint, Span
from zipkin_tpu.store import device as dev
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.wire.thrift import ThriftError, span_to_bytes, spans_from_bytes

T_STOP, T_I64, T_STRING, T_STRUCT = 0, 10, 11, 12


def nested_struct_payload(depth: int) -> bytes:
    """A Span struct whose unknown field nests structs ``depth`` deep."""
    out = [struct.pack(">bh", T_I64, 1), struct.pack(">q", 1)]  # trace_id
    for _ in range(depth):
        out.append(struct.pack(">bh", T_STRUCT, 99))
    out.append(b"\x00" * (depth + 1))  # close every struct + the span
    return b"".join(out)


def unnamed_endpoint_payload() -> bytes:
    """A Span with one annotation whose endpoint has no service_name."""
    ep = struct.pack(">bh", 8, 1) + struct.pack(">i", 0x0A000001)
    ep += struct.pack(">bh", 6, 2) + struct.pack(">h", 80)
    ep += b"\x00"
    ann = struct.pack(">bh", T_I64, 1) + struct.pack(">q", 123)
    ann += struct.pack(">bh", T_STRING, 2) + struct.pack(">i", 2) + b"sr"
    ann += struct.pack(">bh", T_STRUCT, 3) + ep
    ann += b"\x00"
    span = struct.pack(">bh", T_I64, 1) + struct.pack(">q", 9)
    span += struct.pack(">bh", T_STRING, 3) + struct.pack(">i", 1) + b"x"
    span += struct.pack(">bh", T_I64, 4) + struct.pack(">q", 10)
    span += struct.pack(">bh", 15, 6) + struct.pack(">bi", T_STRUCT, 1) + ann
    span += b"\x00"
    return span


class TestSkipDepthBound:
    def test_python_parser_rejects_deep_nesting(self):
        # Deep enough that unbounded recursion would raise RecursionError.
        payload = nested_struct_payload(5000)
        with pytest.raises(ThriftError):
            spans_from_bytes(payload)

    def test_python_parser_accepts_shallow_unknown_structs(self):
        spans = spans_from_bytes(nested_struct_payload(10))
        assert len(spans) == 1 and spans[0].trace_id == 1

    def test_native_parser_rejects_deep_nesting(self):
        native = pytest.importorskip("zipkin_tpu.native")
        if not native.available():
            pytest.skip("native codec unavailable")
        from zipkin_tpu.columnar.dictionary import DictionarySet

        with pytest.raises(ValueError):
            native.parse_spans_columnar(
                nested_struct_payload(10_000), DictionarySet()
            )


class TestUnnamedEndpointParity:
    def test_python_defaults_to_unknown(self):
        spans = spans_from_bytes(unnamed_endpoint_payload())
        assert spans[0].annotations[0].host.service_name == "unknown"

    def test_native_matches_python_default(self):
        native = pytest.importorskip("zipkin_tpu.native")
        if not native.available():
            pytest.skip("native codec unavailable")
        from zipkin_tpu.columnar.dictionary import DictionarySet

        dicts = DictionarySet()
        batch, _ = native.parse_spans_columnar(
            unnamed_endpoint_payload(), dicts
        )
        assert batch.n_annotations == 1
        svc_id = int(batch.ann_service_id[0])
        assert dicts.services.decode(svc_id) == "unknown"


def small_store():
    return TpuSpanStore(dev.StoreConfig(
        capacity=256, ann_capacity=1024, bann_capacity=512,
        max_services=16, max_span_names=32, max_annotation_values=64,
        max_binary_keys=16, cms_width=256, hll_p=6, quantile_buckets=128,
    ))


def make_span(tid: int, sid: int) -> Span:
    ep = Endpoint(1, 80, "svc")
    ts = (tid % 1000) * 10
    return Span(trace_id=tid, name="op", id=sid,
                annotations=(Annotation(ts + 1, "sr", ep),
                             Annotation(ts + 5, "ss", ep)))


class TestTtlKeyCanonicalization:
    def test_unsigned_trace_id_ttl_roundtrip(self):
        store = small_store()
        big = 2**63 + 17  # arrives unsigned on the wire
        store.apply([make_span(big, 1)])
        assert store.get_time_to_live(big) == 1.0
        store.set_time_to_live(big, 3600.0)
        assert store.get_time_to_live(big) == 3600.0
        # The signed alias of the same id resolves to the same entry.
        assert store.get_time_to_live(big - 2**64) == 3600.0

    def test_rewrite_does_not_reset_pin(self):
        store = small_store()
        store.apply([make_span(5, 1)])
        store.set_time_to_live(5, 7200.0)
        store.apply([make_span(5, 2)])  # more spans of the pinned trace
        assert store.get_time_to_live(5) == 7200.0


class TestRWLock:
    def test_writer_excludes_readers(self):
        lock = RWLock()
        order = []
        with lock.read():
            order.append("r1")
        t_done = threading.Event()

        def writer():
            with lock.write():
                order.append("w")
            t_done.set()

        with lock.read():
            t = threading.Thread(target=writer)
            t.start()
            time.sleep(0.05)
            assert "w" not in order  # writer blocked behind reader
        assert t_done.wait(2)
        assert order == ["r1", "w"]

    def test_concurrent_ingest_and_query(self):
        """Queries interleaved with donating ingest steps must neither
        deadlock nor crash (ADVICE r1 high)."""
        store = small_store()
        store.apply([make_span(1, 1)])
        errors = []
        stop = threading.Event()

        def query_loop():
            try:
                while not stop.is_set():
                    store.get_spans_by_trace_ids([1, 2, 3])
                    store.get_trace_ids_by_name("svc", None, 10**15, 5)
                    store.counters()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=query_loop) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            for i in range(2, 30):
                store.apply([make_span(i, j) for j in range(1, 4)])
        finally:
            stop.set()
            for t in threads:
                t.join(10)
        assert not errors
        got = store.get_spans_by_trace_ids([29])
        assert got and len(got[0]) == 3


class TestShardedConcurrency:
    def test_concurrent_sharded_ingest_and_query(self):
        """Donating sharded ingest under the write lock must never let a
        concurrent reader see freed buffers; counters stay exact."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from zipkin_tpu.parallel.shard import ShardedSpanStore
        from zipkin_tpu.store.device import StoreConfig
        from zipkin_tpu.tracegen import generate_traces

        n = min(4, len(jax.devices()))
        mesh = Mesh(np.array(jax.devices()[:n]), axis_names=("shard",))
        cfg = StoreConfig(
            capacity=512, ann_capacity=2048, bann_capacity=1024,
            max_services=16, max_span_names=32, max_annotation_values=64,
            max_binary_keys=16, cms_width=256, hll_p=6,
            quantile_buckets=128,
        )
        store = ShardedSpanStore(mesh, cfg)
        batches = [
            [s for t in generate_traces(
                n_traces=6, max_depth=3, n_services=4,
                rng=np.random.default_rng(seed)) for s in t]
            for seed in range(8)
        ]
        store.apply(batches[0])
        svc = sorted(store.get_all_service_names())[0]
        errors = []

        def writer():
            try:
                for b in batches[1:]:
                    store.apply(b)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def reader():
            try:
                for _ in range(12):
                    ids = store.get_trace_ids_by_name(svc, None, 2**62, 5)
                    if ids:
                        store.get_spans_by_trace_ids(
                            [ids[0].trace_id])
                    store.stored_span_count()
                    store.get_dependencies()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=writer)] + [
            threading.Thread(target=reader) for _ in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        total = sum(len(b) for b in batches)
        assert store.stored_span_count() == float(total)


class TestDecoderFuzz:
    """Random and truncated byte soup into both decoders: corrupt input
    is a VALID input class (kafka/scribe deliver it freely) — the
    decoders must reject or truncate, never crash or hang."""

    def _payloads(self, n=200):
        import numpy as np

        rng = np.random.default_rng(42)
        from zipkin_tpu.tracegen import generate_traces

        good = b"".join(
            span_to_bytes(s)
            for t in generate_traces(n_traces=3, max_depth=3)
            for s in t
        )
        out = []
        for i in range(n):
            kind = i % 4
            if kind == 0:  # pure noise
                out.append(rng.bytes(int(rng.integers(1, 400))))
            elif kind == 1:  # truncated valid payload
                out.append(good[: int(rng.integers(1, len(good)))])
            elif kind == 2:  # valid payload with flipped bytes
                b = bytearray(good)
                for _ in range(int(rng.integers(1, 12))):
                    b[int(rng.integers(0, len(b)))] = int(
                        rng.integers(0, 256))
                out.append(bytes(b))
            else:  # noise appended to valid
                out.append(good + rng.bytes(int(rng.integers(1, 64))))
        return out

    def test_python_decoder_survives_fuzz(self):
        decoded = rejected = 0
        for payload in self._payloads():
            try:
                spans = spans_from_bytes(payload)
                decoded += 1
                for s in spans:  # decoded objects must be well-formed
                    s.service_name
                    hash(s)
            except ThriftError:
                rejected += 1
        assert decoded + rejected == 200
        assert rejected > 0  # the fuzz really produced garbage

    def test_native_decoder_survives_fuzz(self):
        from zipkin_tpu import native
        from zipkin_tpu.columnar.dictionary import DictionarySet

        if not native.available():
            pytest.skip("native lib unavailable")
        ok = bad = 0
        for payload in self._payloads():
            dicts = DictionarySet()
            try:
                batch, _, _, _ = native.parse_spans_columnar_sampled(
                    payload, dicts, 0, max_spans=4096
                )
            except (ValueError, native.NativeUnavailable):
                # ValueError covers ParseCapacityError; anything else
                # (segfault-adjacent ctypes errors, assertion blowups)
                # must FAIL the test, not count as a clean rejection.
                bad += 1
                continue
            ok += 1
            assert batch.n_spans >= 0
        assert ok + bad == 200
