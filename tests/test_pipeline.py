"""Pipelined ingest (store/pipeline) — the r9 tentpole's guarantees.

The three-stage pipeline (encode ∥ H2D staging ∥ device commit) and
the async eviction sealer must change WHEN work happens, never WHAT
state results:

- a pipelined drive lands a device state bitwise identical to the
  serial path's (same chunk boundaries, same CHAIN_SIZES grouping,
  same pow2 pads — the determinism suite's replayability claim
  extended across the threading seam);
- async capture sealing produces the identical cold tier, and a slow
  sealer BOUNDS memory (the in-flight queue is the only buffer) by
  stalling ingest instead of growing;
- checkpoint saves taken mid-flight quiesce the pipeline and cut the
  archive manifest at the sealed frontier, so a restore never claims
  a window that was pulled but not yet sealed.
"""

from __future__ import annotations

import threading
import time

import jax
import numpy as np
import pytest

from zipkin_tpu.store import device as dev
from zipkin_tpu.store.archive import ArchiveParams, TieredSpanStore
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.tracegen import generate_traces

# Same geometry as tests/test_determinism.py — shares its jit cache.
CONFIG = dev.StoreConfig(
    capacity=256, ann_capacity=1024, bann_capacity=512,
    max_services=16, max_span_names=32, max_annotation_values=64,
    max_binary_keys=16, cms_width=256, hll_p=6, quantile_buckets=128,
)


def _spans(n_traces=120, n_services=6):
    return [s for t in generate_traces(n_traces=n_traces, max_depth=3,
                                       n_services=n_services) for s in t]


def _leaves(state):
    flat, _ = jax.tree_util.tree_flatten(state)
    return [np.asarray(x) for x in flat]


def _assert_bitwise_equal(a_state, b_state):
    a, b = _leaves(a_state), _leaves(b_state)
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        assert x.dtype == y.dtype
        np.testing.assert_array_equal(
            x, y, err_msg=f"leaf {i} diverged pipelined vs serial"
        )


def _params():
    return ArchiveParams.for_config(
        CONFIG, compact_fanin=2, small_span_limit=CONFIG.capacity,
        bloom_bits=1 << 12, cms_width=1 << 9, hll_p=6,
    )


def test_pipelined_bitwise_matches_serial():
    spans = _spans()
    serial = TpuSpanStore(CONFIG)
    for i in range(0, len(spans), 40):
        serial.apply(spans[i:i + 40])
    piped = TpuSpanStore(CONFIG)
    with piped.pipelined(depth=3):
        for i in range(0, len(spans), 40):
            piped.apply(spans[i:i + 40])
        piped.drain_pipeline()
        # Reads during/after drain see everything accepted.
        assert (piped.counter_block()["spans_seen"]
                == serial.counter_block()["spans_seen"])
    _assert_bitwise_equal(serial.state, piped.state)
    serial.close()
    piped.close()


def test_pipelined_capture_matches_inline_sealing():
    """Pipelined ingest + ASYNC sealer == serial ingest + inline
    sealer: same device state, same capture windows, same segments —
    the sealer changes where the D2H+deflate runs, never what is
    captured (the pull still happens before any overwrite)."""
    spans = _spans(n_traces=260)[:4 * CONFIG.capacity]

    def drive(backlog, pipeline):
        hot = TpuSpanStore(CONFIG)
        hot.capture_backlog = backlog
        tiered = TieredSpanStore(hot, params=_params())
        if pipeline:
            hot.start_pipeline(3)
        for i in range(0, len(spans), 64):
            tiered.apply(spans[i:i + 64])
        hot.drain_pipeline()
        hot.seal_barrier()
        hot.stop_pipeline()
        return hot, tiered

    sh, st = drive(0, False)
    ph, pt = drive(2, True)
    _assert_bitwise_equal(sh.state, ph.state)
    cs, cp = st.counters(), pt.counters()
    assert cs["archive_cold_spans"] == cp["archive_cold_spans"] > 0
    assert (cs["archive_segments_written"]
            == cp["archive_segments_written"] >= 1)
    # Sealed frontier caught up with the pull clock after the barrier.
    assert ph._sealed_upto == ph._cap_upto > 0
    segs = pt.archive.snapshot()
    assert segs[0].gid_lo == 0
    for a, b in zip(segs, segs[1:]):
        assert a.gid_hi == b.gid_lo
    # Reads agree across the two sealing modes, gid dedup included.
    tids = sorted({s.trace_id for s in spans})
    sample = [tids[0], tids[len(tids) // 2], tids[-1]]
    assert (pt.get_spans_by_trace_ids(sample)
            == st.get_spans_by_trace_ids(sample))
    st.close()
    pt.close()


def test_capture_backpressure_bounds_memory():
    """A slow sealer must BOUND in-flight capture memory at the
    backlog (ingest stalls — the stall counter proves it fired) and
    still seal every window in order with no loss."""
    hot = TpuSpanStore(CONFIG)
    hot.capture_backlog = 1
    windows = []
    max_backlog = [0]

    def slow_sink(batch, gids, lo, hi, pull_s):
        max_backlog[0] = max(max_backlog[0], hot._sealer.queued())
        time.sleep(0.15)
        windows.append((lo, hi, batch.n_spans))

    hot.eviction_sink = slow_sink
    # Fat spans lap the annotation ring every ~33 spans, forcing a
    # capture window on nearly every chunk — far faster than the
    # sealer's 0.15s, so the 1-deep backlog must fill and stall.
    from zipkin_tpu.models.span import Annotation, Endpoint, Span

    ep = Endpoint(1, 80, "fat")
    spans = [
        Span(tid, "op", tid, None, tuple(
            [Annotation(1000 + 100 * tid, "sr", ep)]
            + [Annotation(1000 + 100 * tid + i, "custom", ep)
               for i in range(31)]
        ), ())
        for tid in range(1, 2 * CONFIG.capacity + 1)
    ]
    for i in range(0, len(spans), 64):
        hot.apply(spans[i:i + 64])
    hot.seal_barrier()
    assert len(windows) >= 4, "the drive must have captured repeatedly"
    # Bounded: the queue never grew past the backlog...
    assert max_backlog[0] <= 1
    # ...because ingest stalled on it (deliberate backpressure).
    assert float(hot._sealer.c_stall.value) > 0
    # No loss, no reorder: windows tile [0, cap_upto) contiguously.
    assert windows[0][0] == 0
    for (_, hi_a, _), (lo_b, _, _) in zip(windows, windows[1:]):
        assert hi_a == lo_b
    assert windows[-1][1] == hot._cap_upto == hot._sealed_upto
    hot.close()


def test_checkpoint_during_pipelined_ingest(tmp_path):
    """Threaded stress: concurrent queries + a mid-flight checkpoint
    save while the pipeline ingests with async capture enabled
    (SuspectGuard + RWLock interplay). The save must quiesce the
    pipeline + capture backlog, and the restored tiered store must
    have contiguous cold coverage — a pulled-but-unsealed window may
    never be claimed by the manifest."""
    from zipkin_tpu import checkpoint

    spans = _spans(n_traces=300)[:6 * CONFIG.capacity // 2]
    hot = TpuSpanStore(CONFIG)
    hot.capture_backlog = 2
    tiered = TieredSpanStore(hot, params=_params())
    hot.start_pipeline(3)
    errors = []
    stop_reads = threading.Event()

    def writer():
        try:
            for i in range(0, len(spans), 64):
                tiered.apply(spans[i:i + 64])
        except Exception as e:  # noqa: BLE001 — surfaced by the assert
            errors.append(e)

    def reader():
        end_ts = 1 << 60
        try:
            while not stop_reads.is_set():
                tiered.get_trace_ids_by_name("svc-0", None, end_ts, 5)
                tiered.traces_exist([spans[0].trace_id])
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    w = threading.Thread(target=writer)
    r = threading.Thread(target=reader)
    w.start()
    r.start()
    time.sleep(0.3)  # land the save mid-stream
    ckpt = tmp_path / "ckpt"
    checkpoint.save(tiered, str(ckpt))
    w.join()
    stop_reads.set()
    r.join()
    hot.drain_pipeline()
    hot.stop_pipeline()
    assert not errors, errors
    restored = checkpoint.load(str(ckpt))
    try:
        assert restored.get_all_service_names()
        # Cold coverage is contiguous from gid 0 to the restored
        # capture clock (capture_now at load flushed the tail).
        segs = restored.archive.snapshot()
        if segs:
            assert segs[0].gid_lo == 0
            for a, b in zip(segs, segs[1:]):
                assert a.gid_hi == b.gid_lo
            assert segs[-1].gid_hi == restored.hot._cap_upto
    finally:
        restored.close()
        tiered.close()


def test_zero_recompiles_in_pipelined_steady_state():
    """After a pipelined warm drive, a second pipelined drive over the
    same chunk shapes must hit only cached jit entries — the pow2
    staging buckets exist exactly so steady state never recompiles."""
    spans = _spans(n_traces=120)

    def drive():
        store = TpuSpanStore(CONFIG)
        with store.pipelined(depth=3):
            for i in range(0, len(spans), 40):
                store.apply(spans[i:i + 40])
            store.drain_pipeline()
        store.close()

    drive()  # warm (staged args key their own jit cache rows)
    before = dev.compile_count()
    drive()
    assert dev.compile_count() == before


def test_pipeline_lifecycle_and_error_surfacing():
    spans = _spans(n_traces=20)
    store = TpuSpanStore(CONFIG)
    pipe = store.start_pipeline(2)
    with pytest.raises(RuntimeError):
        store.start_pipeline(2)  # one pipeline per store
    store.apply(spans)
    store.drain_pipeline()
    store.stop_pipeline()
    # Feeding a stopped pipeline object raises; the store itself fell
    # back to the serial path and still works.
    with pytest.raises(RuntimeError):
        from zipkin_tpu.store.pipeline import IngestUnit

        pipe.feed(IngestUnit(None, 0, 0, 0, 1, False))
    store.apply(spans[:5])
    assert store.counter_block()["spans_seen"] == len(spans) + 5
    # A commit-side failure parks, re-raises ONCE on drain (the failed
    # units' spans are dropped, like a serial per-batch failure), and
    # the pipeline then keeps working — a transient fault must not
    # wedge the store permanently.
    store2 = TpuSpanStore(CONFIG)
    store2.start_pipeline(2)
    boom = RuntimeError("commit exploded")

    def bad_commit(unit):
        raise boom

    store2._commit_unit = bad_commit
    store2.apply(spans)
    with pytest.raises(RuntimeError, match="commit exploded"):
        store2.drain_pipeline()
    del store2._commit_unit  # fault clears; class method resumes
    store2.apply(spans[:5])
    store2.drain_pipeline()  # does not re-raise the surfaced error
    assert store2.counter_block()["spans_seen"] == 5
    store2.stop_pipeline()
    store.close()
    store2.close()


def test_ingest_latency_metrics_split():
    """The r9 _observe_ingest fix: dispatch time is always observed,
    TRUE step latency (device completion) is sampled — the first
    launch always observes so even one write reports."""
    from zipkin_tpu import obs

    reg = obs.Registry()
    store = TpuSpanStore(CONFIG, registry=reg)
    spans = _spans(n_traces=10)
    store.apply(spans)
    d = reg.as_dict()
    launches = d["zipkin_store_ingest_launches_total"]
    assert launches >= 1
    assert d["zipkin_store_ingest_dispatch_seconds_count"] == launches
    assert d["zipkin_store_ingest_step_seconds_count"] >= 1
    # The sampled true latency includes device compute, so its mean
    # cannot undercut dispatch-only timing on the same launch count.
    assert d["zipkin_store_ingest_step_seconds_sum"] > 0
    assert store.counters()["jit_compiles"] == dev.compile_count() > 0
    store.close()
