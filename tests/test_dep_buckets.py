"""Time-bucketed dependency banks: get_dependencies(start, end) honesty.

Reference: Aggregates.getDependencies(startDate, endDate)
(Aggregates.scala:26-31); the hourly Dependencies rows the anormdb/
cassandra aggregators persist (Dependencies.scala:59-67). Here each
archive pass lands in a time-tagged device bank; a window query folds
only overlapping banks (+ the live unarchived window).
"""

import jax
import numpy as np
import pytest

from zipkin_tpu.models.span import Annotation, Endpoint, Span
from zipkin_tpu.store.device import StoreConfig
from zipkin_tpu.store.tpu import TpuSpanStore

HOUR = 3_600_000_000  # µs

CFG = StoreConfig(
    capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
    max_services=32, max_span_names=64, max_annotation_values=128,
    max_binary_keys=32, cms_width=512, hll_p=6, quantile_buckets=128,
    dep_buckets=4,
)


def _pair(parent_svc, child_svc, tid, base_ts):
    pa = Endpoint(1, 80, parent_svc)
    ca = Endpoint(2, 80, child_svc)
    parent = Span(tid, "op", 1, None,
                  (Annotation(base_ts, "sr", pa),
                   Annotation(base_ts + 100, "ss", pa)), ())
    child = Span(tid, "op2", 2, 1,
                 (Annotation(base_ts + 10, "sr", ca),
                  Annotation(base_ts + 60, "ss", ca)), ())
    return [parent, child]


def _links(deps):
    return {(l.parent, l.child) for l in deps.links}


def test_dependencies_honor_time_window():
    store = TpuSpanStore(CFG)
    store.apply(_pair("alpha", "beta", 100, 1 * HOUR))
    store.archive_now()
    store.apply(_pair("gamma", "delta", 200, 2 * HOUR))
    store.archive_now()

    assert _links(store.get_dependencies()) == {
        ("alpha", "beta"), ("gamma", "delta")
    }
    h1 = store.get_dependencies(1 * HOUR, 2 * HOUR - 1)
    assert _links(h1) == {("alpha", "beta")}
    h2 = store.get_dependencies(2 * HOUR, 3 * HOUR)
    assert _links(h2) == {("gamma", "delta")}
    assert _links(store.get_dependencies(5 * HOUR, 6 * HOUR)) == set()
    # Dependencies ts range reflects the window clip.
    assert h1.end_time <= 2 * HOUR - 1
    assert h2.start_time >= 2 * HOUR


def test_live_unarchived_window_included():
    store = TpuSpanStore(CFG)
    store.apply(_pair("alpha", "beta", 100, 1 * HOUR))
    store.archive_now()
    # Hour-3 traffic stays live (no archive pass yet).
    store.apply(_pair("eps", "zeta", 300, 3 * HOUR))
    h3 = store.get_dependencies(3 * HOUR, 4 * HOUR)
    assert _links(h3) == {("eps", "zeta")}
    assert _links(store.get_dependencies()) == {
        ("alpha", "beta"), ("eps", "zeta")
    }


def test_bucket_ring_overflow_preserves_totals():
    """More archive passes than dep_buckets: displaced banks fold into
    the all-time tail — totals never regress, only window precision for
    the oldest data degrades (tail covers every window)."""
    store = TpuSpanStore(CFG)
    expected = set()
    for i in range(CFG.dep_buckets + 3):
        p, c = f"svc{i}p", f"svc{i}c"
        store.apply(_pair(p, c, 1000 + i, (i + 1) * HOUR))
        store.archive_now()
        expected.add((p, c))
    assert _links(store.get_dependencies()) == expected
    # A recent bucket still answers precisely.
    last = CFG.dep_buckets + 2
    recent = store.get_dependencies((last + 1) * HOUR,
                                    (last + 2) * HOUR - 1)
    assert (f"svc{last}p", f"svc{last}c") in _links(recent)


def test_sharded_dependencies_window():
    from jax.sharding import Mesh

    from zipkin_tpu.parallel.shard import ShardedSpanStore

    n = min(8, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n]), axis_names=("shard",))
    store = ShardedSpanStore(mesh, CFG)
    store.apply(_pair("alpha", "beta", 100, 1 * HOUR))
    assert _links(store.get_dependencies(1 * HOUR, 2 * HOUR)) == {
        ("alpha", "beta")
    }
    assert _links(store.get_dependencies(5 * HOUR, 6 * HOUR)) == set()


def test_api_dependencies_window_route():
    from zipkin_tpu.api.server import ApiServer
    from zipkin_tpu.query.service import QueryService

    store = TpuSpanStore(CFG)
    store.apply(_pair("alpha", "beta", 100, 1 * HOUR))
    store.archive_now()
    store.apply(_pair("gamma", "delta", 200, 2 * HOUR))
    store.archive_now()
    api = ApiServer(QueryService(store))
    status, body = api.handle(
        "GET", f"/api/dependencies/{1 * HOUR}/{2 * HOUR - 1}", {}
    )
    assert status == 200
    assert {(l["parent"], l["child"]) for l in body["links"]} == {
        ("alpha", "beta")
    }
    status, body = api.handle(
        "GET", "/api/dependencies",
        {"startTime": str(2 * HOUR), "endTime": str(3 * HOUR)},
    )
    assert status == 200
    assert {(l["parent"], l["child"]) for l in body["links"]} == {
        ("gamma", "delta")
    }


def test_sql_dependencies_window():
    from zipkin_tpu.store.sql import SqliteSpanStore
    from zipkin_tpu.tracegen import generate_traces

    store = SqliteSpanStore()
    store.apply(_pair("alpha", "beta", 100, 1 * HOUR))
    store.aggregate_dependencies()
    store.apply(_pair("gamma", "delta", 200, 2 * HOUR))
    store.aggregate_dependencies()
    assert _links(store.get_dependencies()) == {
        ("alpha", "beta"), ("gamma", "delta")
    }
    assert _links(store.get_dependencies(1 * HOUR, 2 * HOUR - 1)) == {
        ("alpha", "beta")
    }
    assert _links(
        store.get_dependencies(start_ts=2 * HOUR)
    ) == {("gamma", "delta")}
    store.close()
