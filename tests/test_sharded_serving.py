"""Multi-chip sharded serving (docs/SHARDING.md): the cross-shard
dispatcher's launch-fusion accounting and bitwise-vs-serialized
identity, dispatcher-routed reads against the in-memory oracle under
concurrent ingest, group-commit WAL crash recovery, sharded
checkpoint + WAL tail recovery, and pipelined sharded ingest."""

import threading

import jax
import jax.tree_util as jtu
import numpy as np
import pytest
from jax.sharding import Mesh

from zipkin_tpu import checkpoint
from zipkin_tpu.parallel.shard import ShardedSpanStore
from zipkin_tpu.store import device as dev
from zipkin_tpu.store.memory import InMemorySpanStore
from zipkin_tpu.tracegen import generate_traces
from zipkin_tpu.wal import ShardedWal, recover

CFG = dev.StoreConfig(
    capacity=256, ann_capacity=1024, bann_capacity=512,
    max_services=16, max_span_names=64, max_annotation_values=64,
    max_binary_keys=16, cms_width=256, hll_p=8, quantile_buckets=128,
    # Window arena ON: the conformance mix includes windowed reads,
    # and the bitwise tests then cover the window leaves + the fleet
    # mirror's window-cell merge too.
    window_seconds=3600, window_buckets=4,
)


@pytest.fixture(scope="module")
def mesh2():
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    return Mesh(np.array(jax.devices()[:2]), axis_names=("shard",))


def _spans(n_traces=12, n_services=6, seed=7):
    return [s for t in generate_traces(
        n_traces=n_traces, max_depth=3, n_services=n_services,
        rng=np.random.default_rng(seed)) for s in t]


def _disjoint_spans(n, seed):
    """Hand-built spans on 'xtra-*' services the oracle never queries:
    concurrent-ingest noise that cannot collide with the generated
    service/span-name universe."""
    from zipkin_tpu.models.span import Annotation, Endpoint, Span

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        tid = int(rng.integers(1, 2**62))
        ep = Endpoint(1, 80, f"xtra-{int(rng.integers(0, 4))}")
        out.append(Span(tid, "xtra-op", tid, None, (
            Annotation(1_000_000_000_000 + tid % 10_000, "sr", ep),
            Annotation(1_000_000_000_100 + tid % 10_000, "ss", ep),
        )))
    return out


def _ids_key(ids):
    return sorted((int(i.trace_id), int(i.timestamp)) for i in ids)


def _states_equal(a, b) -> bool:
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jtu.tree_leaves(a), jtu.tree_leaves(b)))


def test_dispatcher_fuses_concurrent_reads(mesh2):
    """THE acceptance criterion: 8 concurrent reads (4 catalog + 4
    index) land in one dispatcher micro-window and cost <= 2 collective
    launches — one fused catalog bundle, one multi-probe kernel —
    counter-proven via collective_launches() deltas, with results
    identical to serialized execution."""
    store = ShardedSpanStore(mesh2, CFG, dispatch_window_s=1.0)
    try:
        store.apply(_spans())
        svcs = sorted(store.get_all_service_names())[:4]
        # Warm-up compiles every kernel the workers hit (the counter
        # counts launches, not compiles, but cold compiles could
        # stretch a worker past the micro-window).
        for svc in svcs:
            store.service_duration_quantiles(svc, [0.5, 0.99])
            store.get_trace_ids_by_name(svc, None, 2**62, 10)
        store.get_trace_ids_multi(
            [("name", svc, None, 2**62, 10) for svc in svcs])
        store.dispatcher.drain()

        barrier = threading.Barrier(9)
        results = {}
        errors = []

        def cat_worker(i, svc):
            try:
                barrier.wait()
                results[i] = store.service_duration_quantiles(
                    svc, [0.5, 0.99])
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        def ids_worker(i, svc):
            try:
                barrier.wait()
                results[i] = _ids_key(store.get_trace_ids_by_name(
                    svc, None, 2**62, 10))
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = (
            [threading.Thread(target=cat_worker, args=(i, svcs[i]),
                              daemon=True) for i in range(4)]
            + [threading.Thread(target=ids_worker, args=(4 + i, svcs[i]),
                                daemon=True) for i in range(4)]
        )
        for t in threads:
            t.start()
        before = store.collective_launches()
        barrier.wait()
        for t in threads:
            t.join(timeout=120.0)
        assert not [t for t in threads if t.is_alive()], "reader hung"
        assert not errors, errors
        delta = store.collective_launches() - before
        assert delta <= 2, (
            f"8 concurrent reads cost {delta} collective launches; "
            "the dispatcher must fuse them into <= 2 (one catalog "
            "bundle + one multi-probe kernel)")
        assert store.dispatcher.stats()["launches_saved"] >= 6

        # Bitwise identity with serialized execution: re-issue every
        # query alone (a batch of one rides the singular kernels).
        for i in range(4):
            assert results[i] == store.service_duration_quantiles(
                svcs[i], [0.5, 0.99])
        for i in range(4):
            assert results[4 + i] == _ids_key(
                store.get_trace_ids_by_name(svcs[i], None, 2**62, 10))
    finally:
        store.close()


def test_dispatcher_reads_match_memory_oracle_under_ingest(mesh2):
    """Sharded conformance through the dispatcher: N threads issue
    mixed queries (trace-id index, span-name catalog, fleet-mirror
    windowed quantiles, cross-shard trace fetch) while a writer keeps
    full ingest running on disjoint services — every answer must
    equal the reference (memory-store oracle for device reads; the
    pre-ingest fleet answer for windowed reads, which the disjoint
    writer must not perturb). This is the workload that deadlocked
    the collective rendezvous before the r15 _coll_lock fix."""
    store = ShardedSpanStore(mesh2, CFG, dispatch_window_s=0.02)
    oracle = InMemorySpanStore()
    try:
        base = _spans(n_traces=12, n_services=4, seed=3)
        store.apply(base)
        oracle.apply(base)
        svcs = sorted(oracle.get_all_service_names())
        expect_ids = {
            svc: _ids_key(oracle.get_trace_ids_by_name(
                svc, None, 2**62, 50)) for svc in svcs
        }
        expect_names = {
            svc: set(oracle.get_span_names(svc)) for svc in svcs}
        # Windowed reads come off the fleet mirror; the writer's spans
        # land on disjoint service rows, so these answers must hold
        # steady under its ingest.
        expect_wq = {
            svc: store.windowed_quantiles(svc, [0.5, 0.99])
            for svc in svcs}
        by_trace = {}
        for s in base:
            by_trace.setdefault(s.trace_id, 0)
            by_trace[s.trace_id] += 1

        stop = threading.Event()
        errors = []

        def writer():
            # Disjoint 'xtra-*' services, and little enough volume
            # that the base spans never evict (ring capacity 256 per
            # shard vs ~base/2 + 36 rows).
            try:
                for i in range(3):
                    if stop.is_set():
                        return
                    store.apply(_disjoint_spans(12, seed=100 + i))
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        def reader():
            try:
                for _ in range(3):
                    for svc in svcs:
                        got = _ids_key(store.get_trace_ids_by_name(
                            svc, None, 2**62, 50))
                        assert got == expect_ids[svc], svc
                        assert set(store.get_span_names(svc)) == \
                            expect_names[svc], svc
                        assert store.windowed_quantiles(
                            svc, [0.5, 0.99]) == expect_wq[svc], svc
                    tids = [t for t, _ in list(by_trace.items())[:4]]
                    for tr in store.get_spans_by_trace_ids(tids):
                        assert len(tr) == by_trace[tr[0].trace_id]
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        w = threading.Thread(target=writer, daemon=True)
        readers = [threading.Thread(target=reader, daemon=True)
                   for _ in range(5)]
        w.start()
        for t in readers:
            t.start()
        for t in [w] + readers:
            t.join(timeout=180.0)
        stop.set()
        assert not [t for t in [w] + readers if t.is_alive()], "hung"
        assert not errors, errors
        assert store.dispatcher.stats()["requests"] > 0
    finally:
        store.close()


def test_sharded_crash_recovery_bitwise_matches_uncrashed(mesh2,
                                                          tmp_path):
    """Group-commit WAL recovery: a fleet that crashed after its
    appends replays to BITWISE the uncrashed fleet's device state —
    every shard's rings, dictionaries, and the applied frontier."""
    wal_dir = str(tmp_path / "wal")
    primary = ShardedSpanStore(mesh2, CFG)
    wal = ShardedWal(wal_dir, 2, fsync="off")
    primary.attach_wal(wal)
    chunks = [_spans(n_traces=6, seed=11), _spans(n_traces=5, seed=12)]
    for chunk in chunks:
        primary.apply(chunk)
    primary.wal_sync()
    prim_state = jax.device_get(primary.inner.states)
    prim_frontier = primary.write_frontier()
    svc = sorted(primary.get_all_service_names())[0]
    prim_ids = _ids_key(primary.get_trace_ids_by_name(
        svc, None, 2**62, 20))
    primary.close()
    wal.close()  # crash: no checkpoint was ever taken

    wal2 = ShardedWal(wal_dir, 2, fsync="off")
    recovered, stats = recover(
        None, wal2, fresh_store=lambda: ShardedSpanStore(mesh2, CFG))
    try:
        assert stats["replayed_records"] == len(chunks)
        assert stats["replayed_spans"] == sum(len(c) for c in chunks)
        assert stats["torn_records_cut"] == 0
        assert _states_equal(prim_state,
                             jax.device_get(recovered.inner.states))
        assert recovered.write_frontier() == prim_frontier
        assert recovered._wal_applied == len(chunks)
        assert _ids_key(recovered.get_trace_ids_by_name(
            svc, None, 2**62, 20)) == prim_ids
    finally:
        recovered.close()
        wal2.close()


def test_sharded_checkpoint_wal_tail_recovery(mesh2, tmp_path):
    """The full durability loop: checkpoint (sharded clocks + WAL
    truncation), post-checkpoint tail in the WAL, crash, recover —
    replaying ONLY the tail on top of the snapshot lands bitwise on
    the uncrashed fleet, and the resynced mirrors come back warm."""
    wal_dir = str(tmp_path / "wal")
    ckpt_dir = str(tmp_path / "ckpt")
    primary = ShardedSpanStore(mesh2, CFG)
    wal = ShardedWal(wal_dir, 2, fsync="off")
    primary.attach_wal(wal)
    primary.apply(_spans(n_traces=6, seed=21))
    stats = checkpoint.save(primary, ckpt_dir)
    assert stats["wal_truncated_segments"] >= 0
    primary.apply(_spans(n_traces=5, seed=22))  # the tail
    primary.wal_sync()
    prim_state = jax.device_get(primary.inner.states)
    prim_frontier = primary.write_frontier()
    primary.close()
    wal.close()

    wal2 = ShardedWal(wal_dir, 2, fsync="off")
    recovered, rstats = recover(ckpt_dir, wal2, mesh=mesh2)
    try:
        assert rstats["replayed_records"] == 1  # tail only
        assert _states_equal(prim_state,
                             jax.device_get(recovered.inner.states))
        assert recovered.write_frontier() == prim_frontier
        assert recovered.ensure_sketch_mirror().warm
    finally:
        recovered.close()
        wal2.close()


def test_sharded_pipelined_ingest_bitwise_matches_serial(mesh2):
    """The three-stage pipeline driving every shard's commit must land
    the identical fleet state as the serial write path — same batches,
    same launches, different threads."""
    serial = ShardedSpanStore(mesh2, CFG)
    piped = ShardedSpanStore(mesh2, CFG)
    try:
        chunks = [_spans(n_traces=4, seed=s) for s in (31, 32, 33)]
        for c in chunks:
            serial.apply(c)
        with piped.pipelined(depth=4):
            for c in chunks:
                piped.apply(c)
        assert _states_equal(jax.device_get(serial.inner.states),
                             jax.device_get(piped.inner.states))
        assert serial.write_frontier() == piped.write_frontier()
        assert serial.counters() == piped.counters()
        assert serial.shard_counters() == piped.shard_counters()
    finally:
        serial.close()
        piped.close()


def test_shard_occupancy_gauges_track_per_shard_state(mesh2):
    """Satellite (b): per-shard occupancy/lap gauges read off the
    memoized counter blocks and key by shard index."""
    from zipkin_tpu import obs

    reg = obs.Registry()
    store = ShardedSpanStore(mesh2, CFG, registry=reg)
    try:
        store.apply(_spans(n_traces=8, seed=41))
        occ = store._occupancy_by_shard()
        laps = store._laps_by_shard()
        assert set(occ) == {"0", "1"}
        assert sum(occ.values()) == store.counters()["ring_occupancy"]
        assert all(v >= 0 for v in laps.values())
        fam = reg.get("zipkin_shard_occupancy")
        assert fam is not None
        per_shard = store.shard_counters()
        assert len(per_shard) == 2
        assert sum(b["ring_occupancy"] for b in per_shard) == \
            store.counters()["ring_occupancy"]
    finally:
        store.close()
