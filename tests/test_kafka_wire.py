"""Kafka receiver/sink against BYTES ON A SOCKET.

VERDICT r4 missing #3: three rounds of injected-callable shims never
met a broker's framing. These tests run the real KafkaSpanSink and
KafkaSpanReceiver through the v0 wire-protocol FakeKafkaBroker
(testing/kafka_fake.py — the FakeCassandra simulate-don't-mock
pattern), covering produce/fetch framing, CRC verification, sink
batching, collector pushback with retry, corrupt payloads on the
topic, and at-least-once redelivery."""

import threading
import time

import pytest

from zipkin_tpu.ingest.kafka import KafkaSpanReceiver, KafkaSpanSink
from zipkin_tpu.ingest.queue import QueueFullException
from zipkin_tpu.store.memory import InMemorySpanStore
from zipkin_tpu.testing.kafka_fake import (
    FakeKafkaBroker,
    MinimalKafkaConsumer,
    MinimalKafkaProducer,
)
from zipkin_tpu.tracegen import generate_traces
from zipkin_tpu.wire.thrift import span_to_bytes

SPANS = [s for t in generate_traces(n_traces=6, max_depth=3,
                                    n_services=4) for s in t]


@pytest.fixture()
def broker():
    with FakeKafkaBroker() as b:
        yield b


def test_produce_fetch_roundtrip(broker):
    prod = MinimalKafkaProducer(broker.host, broker.port)
    for i in range(5):
        prod.send("raw", b"value-%d" % i)
    cons = MinimalKafkaConsumer(broker.host, broker.port, "raw")
    got = list(cons)
    assert got == [b"value-%d" % i for i in range(5)]
    # Offsets advance; a fresh consumer at offset 3 sees the tail only.
    tail = list(MinimalKafkaConsumer(broker.host, broker.port, "raw",
                                     offset=3))
    assert tail == [b"value-3", b"value-4"]
    prod.close()


def test_broker_rejects_corrupt_crc(broker):
    prod = MinimalKafkaProducer(broker.host, broker.port)
    prod.send("t", b"fine")
    with pytest.raises(IOError):
        prod.send("t", b"mangled", corrupt_crc=True)
    assert broker.stats["corrupt_rejected"] == 1
    assert list(MinimalKafkaConsumer(broker.host, broker.port, "t")) == \
        [b"fine"]


def test_truncated_produce_set_rejected_whole(broker):
    """A produce message set missing its tail is a framing bug: the
    broker must reject the WHOLE set (ERR_CORRUPT), never silently
    append the complete prefix and ack success."""
    import socket
    import struct

    from zipkin_tpu.testing.kafka_fake import (_bytes, _i16, _i32,
                                               _string,
                                               encode_message_set)

    mset = encode_message_set([b"a", b"b"])[:-1]  # drop final byte
    body = (_i16(1) + _i32(1000) + _i32(1) + _string("t")
            + _i32(1) + _i32(0) + _bytes(mset))
    frame = _i16(0) + _i16(0) + _i32(1) + _string("raw") + body
    with socket.create_connection((broker.host, broker.port)) as s:
        s.sendall(struct.pack(">i", len(frame)) + frame)
        head = s.recv(4)
        (size,) = struct.unpack(">i", head)
        resp = b""
        while len(resp) < size:
            resp += s.recv(size - len(resp))
    err = struct.unpack(">h", resp[-10:-8])[0]
    assert err != 0
    assert broker.stats["corrupt_rejected"] == 1
    assert broker.log("t").values == []  # nothing appended


def test_message_keys_round_trip(broker):
    """Keys survive produce -> log -> fetch (the broker re-encodes
    key+value, not value alone)."""
    import socket
    import struct

    from zipkin_tpu.testing.kafka_fake import (_bytes, _i16, _i32, _i64,
                                               _string, decode_message_set,
                                               encode_message)

    msg = encode_message(b"the-value", key=b"the-key")
    mset = _i64(0) + _i32(len(msg)) + msg
    body = (_i16(1) + _i32(1000) + _i32(1) + _string("keyed")
            + _i32(1) + _i32(0) + _bytes(mset))
    frame = _i16(0) + _i16(0) + _i32(1) + _string("raw") + body
    with socket.create_connection((broker.host, broker.port)) as s:
        s.sendall(struct.pack(">i", len(frame)) + frame)
        head = s.recv(4)
        (size,) = struct.unpack(">i", head)
        while size > 0:
            size -= len(s.recv(size))
    stored = decode_message_set(
        _i64(0) + _i32(len(broker.log("keyed").values[0]))
        + broker.log("keyed").values[0])
    assert stored == [(0, b"the-key", b"the-value")]


def test_sink_to_receiver_end_to_end(broker):
    """KafkaSpanSink publishes thrift spans through the socket; the
    receiver consumes them off the same topic into a store, and the
    store answers queries — the full reference pipeline
    (collector/Kafka.scala producer -> KafkaProcessor.scala consumer)."""
    sink = KafkaSpanSink(MinimalKafkaProducer(broker.host, broker.port),
                         topic="zipkin")
    sink.apply(SPANS)
    sink.close()
    assert sink.stats["published"] == len(SPANS)

    store = InMemorySpanStore()
    receiver = KafkaSpanReceiver(
        process=store.apply,
        streams=[MinimalKafkaConsumer(broker.host, broker.port, "zipkin")],
    )
    receiver.run()
    assert receiver.stats["messages"] == len(SPANS)
    assert receiver.stats["bad"] == 0
    tid = SPANS[0].trace_id
    assert store.get_spans_by_trace_id(tid)
    assert store.get_all_service_names()


def test_sink_batching_one_message_many_spans(broker):
    """batch=True publishes ONE message of concatenated Span structs;
    the receiver must decode all of them from that single fetch."""
    sink = KafkaSpanSink(MinimalKafkaProducer(broker.host, broker.port),
                         topic="batched", batch=True)
    sink.apply(SPANS)
    sink.close()
    assert len(broker.log("batched").values) == 1

    store = InMemorySpanStore()
    receiver = KafkaSpanReceiver(
        process=store.apply,
        streams=[MinimalKafkaConsumer(broker.host, broker.port,
                                      "batched")],
    )
    receiver.run()
    assert receiver.stats["messages"] == 1
    assert float(store.stored_span_count()) == len(SPANS)


def test_receiver_retries_on_pushback(broker):
    """Collector pushback (QueueFullException) retries the SAME message
    with backoff — kafka's at-least-once stance — and delivers once the
    queue drains."""
    sink = KafkaSpanSink(MinimalKafkaProducer(broker.host, broker.port))
    sink.apply(SPANS[:4])
    sink.close()

    store = InMemorySpanStore()
    fails = {"left": 3}

    def congested(spans):
        if fails["left"] > 0:
            fails["left"] -= 1
            raise QueueFullException("full")
        store.apply(spans)

    receiver = KafkaSpanReceiver(
        process=congested,
        streams=[MinimalKafkaConsumer(broker.host, broker.port,
                                      "zipkin")],
        retry_backoff_s=0.001,
    )
    receiver.run()
    assert receiver.stats["retries"] == 3
    assert receiver.stats["dropped"] == 0
    assert float(store.stored_span_count()) == 4


def test_receiver_drops_after_max_retries(broker):
    sink = KafkaSpanSink(MinimalKafkaProducer(broker.host, broker.port))
    sink.apply(SPANS[:2])
    sink.close()

    def always_full(spans):
        raise QueueFullException("full")

    receiver = KafkaSpanReceiver(
        process=always_full,
        streams=[MinimalKafkaConsumer(broker.host, broker.port,
                                      "zipkin")],
        retry_backoff_s=0.0, max_retries=2,
    )
    receiver.run()
    assert receiver.stats["dropped"] == 2
    assert receiver.stats["retries"] == 4  # 2 messages x 2 retries


def test_corrupt_payload_on_topic_is_counted_not_fatal(broker):
    """Garbage VALUES (valid kafka framing, broken thrift) are counted
    bad and the stream continues — per-message corruption isolation."""
    prod = MinimalKafkaProducer(broker.host, broker.port)
    prod.send("zipkin", span_to_bytes(SPANS[0]))
    prod.send("zipkin", b"\x0c\x00\x01garbage-not-thrift")
    prod.send("zipkin", span_to_bytes(SPANS[1]))
    store = InMemorySpanStore()
    receiver = KafkaSpanReceiver(
        process=store.apply,
        streams=[MinimalKafkaConsumer(broker.host, broker.port,
                                      "zipkin")],
    )
    receiver.run()
    assert receiver.stats["messages"] == 3
    assert receiver.stats["bad"] == 1
    assert float(store.stored_span_count()) == 2


def test_at_least_once_redelivery_is_tolerated(broker):
    """Re-consuming from offset 0 (a rebalance/crash replay) delivers
    duplicates; the store's same-id merge keeps answers stable."""
    sink = KafkaSpanSink(MinimalKafkaProducer(broker.host, broker.port))
    sink.apply(SPANS[:7])
    sink.close()
    store = InMemorySpanStore()
    for _ in range(2):  # two full passes over the topic
        KafkaSpanReceiver(
            process=store.apply,
            streams=[MinimalKafkaConsumer(broker.host, broker.port,
                                          "zipkin")],
        ).run()
    from zipkin_tpu.models.trace import Trace

    tid = SPANS[0].trace_id
    spans = store.get_spans_by_trace_id(tid)
    once = [s for s in SPANS[:7] if s.trace_id == tid]
    # The store keeps both deliveries; the query layer's merge-by-id
    # (Trace.scala:38-44 semantics) collapses replays to one span per
    # id with the same timing — annotation lists concatenate under
    # merge (reference Span merge semantics), so only the span set and
    # duration are asserted identical to a single delivery's.
    assert len(spans) == 2 * len(once)
    t_dup, t_once = Trace(spans), Trace(once)
    assert [s.id for s in t_dup.spans] == [s.id for s in t_once.spans]
    assert t_dup.duration == t_once.duration


def _first_stored_value(broker, topic):
    """Decode the first stored message's VALUE (the log keeps raw
    crc..value message bytes)."""
    from zipkin_tpu.testing.kafka_fake import (_i32, _i64,
                                               decode_message_set)

    v = broker.log(topic).values[0]
    return decode_message_set(_i64(0) + _i32(len(v)) + v)[0][2]


def test_compressed_sink_round_trips_through_broker(broker):
    """compress=True frames each value with the negotiation byte and
    deflates past the size floor; the receiver unframes transparently
    and the store answers identically to the uncompressed path."""
    sink = KafkaSpanSink(MinimalKafkaProducer(broker.host, broker.port),
                         topic="deflated", batch=True, compress=True)
    sink.apply(SPANS)
    sink.close()
    assert sink.stats["published"] == len(SPANS)
    # The batched payload crossed the compression floor: wire bytes
    # shrank, and the stored value leads with the deflate marker.
    assert sink.stats["bytes_wire"] < sink.stats["bytes_raw"]
    from zipkin_tpu.ingest.kafka import FRAME_DEFLATE

    assert _first_stored_value(broker, "deflated")[0] == FRAME_DEFLATE

    store = InMemorySpanStore()
    receiver = KafkaSpanReceiver(
        process=store.apply,
        streams=[MinimalKafkaConsumer(broker.host, broker.port,
                                      "deflated")],
    )
    receiver.run()
    assert receiver.stats["bad"] == 0
    assert float(store.stored_span_count()) == len(SPANS)
    tid = SPANS[0].trace_id
    assert store.get_spans_by_trace_id(tid) == [
        s for s in SPANS if s.trace_id == tid
    ]


def test_small_payload_framed_raw_not_inflated(broker):
    """Below the size floor the sink ships the framed-raw form (tiny
    deflate streams inflate); the receiver strips the marker."""
    from zipkin_tpu.ingest.kafka import FRAME_RAW

    sink = KafkaSpanSink(MinimalKafkaProducer(broker.host, broker.port),
                         topic="tiny", compress=True,
                         compress_min_bytes=1 << 20)
    sink.apply(SPANS[:1])
    sink.close()
    assert _first_stored_value(broker, "tiny")[0] == FRAME_RAW
    store = InMemorySpanStore()
    KafkaSpanReceiver(
        process=store.apply,
        streams=[MinimalKafkaConsumer(broker.host, broker.port,
                                      "tiny")],
    ).run()
    assert float(store.stored_span_count()) == 1


def test_mixed_legacy_and_framed_messages_interoperate(broker):
    """One topic carrying legacy unframed, framed-raw, and deflate
    messages decodes them all — the negotiation byte can't collide
    with a thrift Span's first field byte."""
    prod = MinimalKafkaProducer(broker.host, broker.port)
    legacy = KafkaSpanSink(prod, topic="mixed")
    legacy.apply(SPANS[:2])
    framed = KafkaSpanSink(prod, topic="mixed", compress=True,
                           compress_min_bytes=0)
    framed.apply(SPANS[2:4])
    tiny = KafkaSpanSink(prod, topic="mixed", compress=True,
                         compress_min_bytes=1 << 20)
    tiny.apply(SPANS[4:5])
    store = InMemorySpanStore()
    receiver = KafkaSpanReceiver(
        process=store.apply,
        streams=[MinimalKafkaConsumer(broker.host, broker.port,
                                      "mixed")],
    )
    receiver.run()
    assert receiver.stats["bad"] == 0
    assert float(store.stored_span_count()) == 5


def test_corrupt_deflate_frame_counted_not_fatal(broker):
    """A deflate-marked message whose stream is garbage counts bad and
    the stream continues (per-message corruption isolation, same
    stance as corrupt thrift)."""
    prod = MinimalKafkaProducer(broker.host, broker.port)
    prod.send("zx", b"\x01this-is-not-a-zlib-stream")
    prod.send("zx", span_to_bytes(SPANS[0]))
    store = InMemorySpanStore()
    receiver = KafkaSpanReceiver(
        process=store.apply,
        streams=[MinimalKafkaConsumer(broker.host, broker.port, "zx")],
    )
    receiver.run()
    assert receiver.stats["bad"] == 1
    assert float(store.stored_span_count()) == 1


def test_live_polling_consumer_sees_later_produces(broker):
    """poll_forever consumers block on an empty partition and pick up
    messages produced AFTER the receiver started — the long-running
    deployment shape (a real stream never exhausts)."""
    store = InMemorySpanStore()
    consumer = MinimalKafkaConsumer(broker.host, broker.port, "zipkin",
                                    poll_forever=True)
    receiver = KafkaSpanReceiver(process=store.apply, streams=[consumer])
    t = threading.Thread(target=receiver.run, daemon=True)
    t.start()
    sink = KafkaSpanSink(MinimalKafkaProducer(broker.host, broker.port))
    sink.apply(SPANS[:3])
    sink.close()
    deadline = time.time() + 5
    while time.time() < deadline and store.stored_span_count() < 3:
        time.sleep(0.01)
    assert float(store.stored_span_count()) == 3
    consumer.stop()
    t.join(timeout=5)
    assert not t.is_alive()
