"""Checkpoint save/restore + composed mains smoke tests."""

import numpy as np
import pytest

from zipkin_tpu import checkpoint
from zipkin_tpu.models.span import Annotation, BinaryAnnotation, Endpoint, Span
from zipkin_tpu.store.device import StoreConfig
from zipkin_tpu.store.tpu import TpuSpanStore

CFG = StoreConfig(
    capacity=1 << 9, ann_capacity=1 << 11, bann_capacity=1 << 10,
    max_services=16, max_span_names=64, max_annotation_values=64,
    max_binary_keys=16, cms_width=1 << 9, hll_p=6, quantile_buckets=128,
)

WEB = Endpoint(1, 80, "web")
API = Endpoint(2, 80, "api")


def rpc(tid, sid, parent, t0, t1):
    return Span(tid, "op", sid, parent, (
        Annotation(t0, "cs", WEB),
        Annotation(t0 + 1, "sr", API),
        Annotation(t1 - 1, "ss", API),
        Annotation(t1, "cr", WEB),
    ), (BinaryAnnotation("k", b"v", host=API),))


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        store = TpuSpanStore(CFG)
        store.apply([rpc(1, 1, None, 100, 200), rpc(1, 2, 1, 110, 150)])
        store.set_time_to_live(1, 777.0)
        path = str(tmp_path / "ckpt")
        checkpoint.save(store, path)

        restored = checkpoint.load(path)
        # Queries behave identically on the restored store.
        assert restored.get_spans_by_trace_ids([1]) == \
            store.get_spans_by_trace_ids([1])
        assert restored.get_all_service_names() == {"web", "api"}
        assert restored.get_time_to_live(1) == 777.0
        assert restored.counters() == store.counters()
        got = {(l.parent, l.child) for l in restored.get_dependencies().links}
        assert got == {(l.parent, l.child) for l in store.get_dependencies().links}

    def test_restored_store_accepts_writes(self, tmp_path):
        store = TpuSpanStore(CFG)
        store.apply([rpc(1, 1, None, 100, 200)])
        path = str(tmp_path / "ckpt")
        checkpoint.save(store, path)
        restored = checkpoint.load(path)
        restored.apply([rpc(2, 1, None, 300, 400)])
        assert restored.traces_exist([1, 2]) == {1, 2}
        # Dictionary ids survived: the same service maps to the same id.
        assert restored.dicts.services.get("api") == store.dicts.services.get("api")

    def test_legacy_snapshot_migrates_live_links(self, tmp_path):
        """A pre-revision-4 snapshot carried unarchived links only
        implicitly: resident ring rows past the dep_archived_gid
        watermark, joined on demand by the retired ring join. load()
        must reconstruct exactly those links into the streaming-join
        window (no loss, no double count)."""
        import json
        import os

        store = TpuSpanStore(CFG)
        # Trace 3's child arrives WITHOUT its parent: under the legacy
        # schema it sat in the ring awaiting the on-demand join; the
        # migration must queue it in the pending ring so the parent
        # arriving post-upgrade still links.
        store.apply([rpc(1, 1, None, 100, 200), rpc(1, 2, 1, 110, 150),
                     rpc(2, 7, None, 300, 400), rpc(2, 8, 7, 310, 330),
                     rpc(3, 21, 20, 500, 550)])
        expected = [(l.parent, l.child, l.duration_moments.count)
                    for l in store.get_dependencies().links]
        assert expected  # the fixture must actually produce links

        path = str(tmp_path / "ckpt")
        checkpoint.save(store, path)

        # Rewrite the snapshot into the revision-3 layout: links exist
        # only in the ring + a zero watermark; the streaming-join leaves
        # don't exist yet.
        state_file = os.path.join(path, "state.npz")
        data = dict(np.load(state_file))
        for gone in ("span_tab", "pend_key", "pend_dur", "pend_tsf",
                     "pend_tsl", "pend_pos", "dep_window",
                     "dep_window_ts"):
            del data[gone]
        data["dep_moments"] = np.zeros_like(data["dep_moments"])
        data["dep_banks"] = np.zeros_like(data["dep_banks"])
        data["dep_archived_gid"] = np.int64(0)
        np.savez_compressed(state_file, **data)
        meta_file = os.path.join(path, "meta.json")
        with open(meta_file) as f:
            meta = json.load(f)
        meta["revision"] = 3
        cfg = dict(meta["config"])
        cfg.pop("span_tab_slots", None)
        cfg.pop("pend_slots", None)
        meta["config"] = cfg
        with open(meta_file, "w") as f:
            json.dump(meta, f)

        restored = checkpoint.load(path)
        got = [(l.parent, l.child, l.duration_moments.count)
               for l in restored.get_dependencies().links]
        assert got == expected
        # The orphan child queued by the migration links once its
        # parent arrives post-restore (dep_sweep resolves the pending
        # entry against the newly inserted parent).
        before = sum(l.duration_moments.count
                     for l in restored.get_dependencies().links)
        restored.apply([rpc(3, 20, None, 490, 560)])
        after = sum(l.duration_moments.count
                    for l in restored.get_dependencies().links)
        assert after >= before + 1  # the orphan child linked

    def test_chunked_save_resumes_after_wedged_transfer(self, tmp_path,
                                                        monkeypatch):
        """A transfer that wedges mid-save (r4: one 544MB device_get
        hung >70 min) must cost the failed leaves only: the staged
        leaves survive on disk, and a retry with an unchanged state
        generation skips them and completes a CONSISTENT snapshot."""
        store = TpuSpanStore(CFG)
        store.apply([rpc(1, 1, None, 100, 200), rpc(1, 2, 1, 110, 150)])
        path = str(tmp_path / "ckpt")

        real_get = checkpoint._bounded_get
        fail = {"after": 5}  # wedge every transfer past the 5th

        def flaky(x, deadline_s):
            if deadline_s is not None and fail["after"] <= 0:
                raise TimeoutError("simulated wedge")
            fail["after"] -= 1
            return real_get(x, None)

        monkeypatch.setattr(checkpoint, "_bounded_get", flaky)
        with pytest.raises(TimeoutError):
            checkpoint.save(store, path, chunk_deadline_s=5.0,
                            slab_retries=0)
        staging = path + ".staging"
        assert __import__("os").path.isdir(staging)
        assert not __import__("os").path.isdir(path)  # nothing partial

        # Retry with a healthy tunnel: staged leaves are reused. The
        # simulated wedge carried no orphan thread, so the suspect
        # stamp needs the operator override (a real timeout's orphan
        # finishes and ensure_writable clears the flag itself).
        store.clear_suspect()
        monkeypatch.setattr(checkpoint, "_bounded_get", real_get)
        stats = checkpoint.save(store, path, chunk_deadline_s=5.0)
        assert stats["resumed_leaves"] > 0
        assert not __import__("os").path.isdir(staging)  # cleaned up
        restored = checkpoint.load(path)
        assert restored.get_spans_by_trace_ids([1]) == \
            store.get_spans_by_trace_ids([1])
        assert restored.counters() == store.counters()

    def test_stale_staging_discarded_after_writes(self, tmp_path,
                                                  monkeypatch):
        """Writes between save attempts change the state generation:
        the stale staged leaves must be DISCARDED, never mixed into the
        new cut (a mixed snapshot would be silently inconsistent)."""
        store = TpuSpanStore(CFG)
        store.apply([rpc(1, 1, None, 100, 200)])
        path = str(tmp_path / "ckpt")

        real_get = checkpoint._bounded_get
        fail = {"after": 5}

        def flaky(x, deadline_s):
            if deadline_s is not None and fail["after"] <= 0:
                raise TimeoutError("simulated wedge")
            fail["after"] -= 1
            return real_get(x, None)

        monkeypatch.setattr(checkpoint, "_bounded_get", flaky)
        with pytest.raises(TimeoutError):
            checkpoint.save(store, path, chunk_deadline_s=5.0,
                            slab_retries=0)
        store.clear_suspect()  # simulated wedge: no orphan to join
        monkeypatch.setattr(checkpoint, "_bounded_get", real_get)
        store.apply([rpc(2, 3, None, 300, 400)])  # generation changes
        stats = checkpoint.save(store, path, chunk_deadline_s=5.0)
        assert stats["resumed_leaves"] == 0  # stale stage discarded
        restored = checkpoint.load(path)
        assert restored.get_spans_by_trace_ids([2]) == \
            store.get_spans_by_trace_ids([2])

    def test_sweep_between_attempts_discards_staging(self, tmp_path,
                                                     monkeypatch):
        """dep_sweep mutates dep_window/pend_key while moving NO write
        cursor — the one mutation a cursor-only fingerprint would miss
        (review r5). The device-side sweeps counter must change the
        generation so stale staged leaves are discarded, not mixed."""
        store = TpuSpanStore(CFG)
        # A child whose parent arrives later leaves pending-ring state
        # for the sweep to fold.
        store.apply([rpc(1, 2, 7, 110, 150)])
        store.apply([rpc(1, 7, None, 100, 200)])
        path = str(tmp_path / "ckpt")
        real_get = checkpoint._bounded_get
        fail = {"after": 5}

        def flaky(x, deadline_s):
            if deadline_s is not None and fail["after"] <= 0:
                raise TimeoutError("simulated wedge")
            fail["after"] -= 1
            return real_get(x, None)

        monkeypatch.setattr(checkpoint, "_bounded_get", flaky)
        with pytest.raises(TimeoutError):
            checkpoint.save(store, path, chunk_deadline_s=5.0,
                            slab_retries=0)
        store.clear_suspect()  # simulated wedge: no orphan to join
        monkeypatch.setattr(checkpoint, "_bounded_get", real_get)
        before = int(store.counters()["sweeps"])
        store.get_dependencies()  # triggers the pending sweep
        assert int(store.counters()["sweeps"]) > before
        stats = checkpoint.save(store, path, chunk_deadline_s=5.0)
        assert stats["resumed_leaves"] == 0  # sweep changed generation
        restored = checkpoint.load(path)
        got = {(l.parent, l.child)
               for l in restored.get_dependencies().links}
        assert got == {(l.parent, l.child)
                       for l in store.get_dependencies().links}

    def test_wedged_slab_fails_fast_with_bounded_lock_hold(
            self, tmp_path, monkeypatch):
        """ADVICE r5 #2 regression: the FIRST slab timeout must fail
        the save immediately — no retry/backoff while the
        writer-blocking read lock is held (the retry enqueues behind
        the wedged transfer and can never succeed until it clears, so
        it only ever extended the ingest stall). A slow fake device
        wedges every transfer after the first few; the save must
        return within ~one deadline (no backoff sleeps, no second
        attempt), stamp the store suspect, and leave the staged leaves
        for the resume path."""
        import os
        import time

        store = TpuSpanStore(CFG)
        store.apply([rpc(1, 1, None, 100, 200)])
        path = str(tmp_path / "ckpt")

        deadline = 0.3
        real_get = checkpoint._bounded_get
        calls = {"n": 0, "wedged": 0}

        def slow_device(x, deadline_s):
            calls["n"] += 1
            if deadline_s is not None and calls["n"] > 3:
                # Slow fake device: block for the full deadline the
                # way a wedged tunnel does, then surface the timeout.
                calls["wedged"] += 1
                time.sleep(deadline_s)
                err = TimeoutError("simulated slow device")
                raise err
            return real_get(x, None)

        monkeypatch.setattr(checkpoint, "_bounded_get", slow_device)
        t0 = time.perf_counter()
        with pytest.raises(TimeoutError):
            # slab_retries is deliberately > 0: fail-fast must ignore
            # it (the parameter is kept for call-site compatibility).
            checkpoint.save(store, path, chunk_deadline_s=deadline,
                            slab_retries=5)
        held = time.perf_counter() - t0
        # Exactly ONE wedged transfer was attempted — no retries — so
        # the lock hold is bounded by one deadline plus the healthy
        # leaves' transfer time, far below even a single retry cycle
        # (deadline + backoff + deadline).
        assert calls["wedged"] == 1
        assert held < 2 * deadline + 5.0
        # The store is stamped suspect (orphan bookkeeping) and the
        # staged leaves survived for the resume.
        assert store.suspect
        assert os.path.isdir(path + ".staging")
        # Resume with a healthy device completes and clears nothing
        # it shouldn't: the snapshot restores.
        monkeypatch.setattr(checkpoint, "_bounded_get", real_get)
        store.clear_suspect()
        stats = checkpoint.save(store, path, chunk_deadline_s=5.0)
        assert stats["resumed_leaves"] > 0
        restored = checkpoint.load(path)
        assert restored.get_spans_by_trace_ids([1]) == \
            store.get_spans_by_trace_ids([1])

    def test_chunked_save_slabs_large_leaves(self, tmp_path,
                                             monkeypatch):
        """Leaves larger than the slab budget transfer in pieces and
        reassemble bit-exactly."""
        monkeypatch.setattr(checkpoint, "_SLAB_BYTES", 1 << 12)
        store = TpuSpanStore(CFG)
        store.apply([rpc(1, 1, None, 100, 200), rpc(1, 2, 1, 110, 150)])
        path = str(tmp_path / "ckpt")
        stats = checkpoint.save(store, path, chunk_deadline_s=30.0)
        # 4KB slabs over >=several-hundred-KB state: many slabs.
        assert stats["slabs"] > 50
        assert stats["mb_per_s_avg"] > 0
        restored = checkpoint.load(path)
        assert restored.get_spans_by_trace_ids([1]) == \
            store.get_spans_by_trace_ids([1])
        assert restored.counters() == store.counters()

    def test_atomic_overwrite(self, tmp_path):
        store = TpuSpanStore(CFG)
        store.apply([rpc(1, 1, None, 100, 200)])
        path = str(tmp_path / "ckpt")
        checkpoint.save(store, path)
        store.apply([rpc(2, 1, None, 300, 400)])
        checkpoint.save(store, path)  # overwrite in place
        restored = checkpoint.load(path)
        assert restored.traces_exist([1, 2]) == {1, 2}


class TestMains:
    def test_tracegen_main_tpu_roundtrip(self):
        from zipkin_tpu.main.tracegen import run

        assert run(n_traces=3, max_depth=4, use_tpu=True, verbose=False)

    def test_tracegen_main_memory_roundtrip(self):
        from zipkin_tpu.main.tracegen import run

        assert run(n_traces=3, max_depth=4, use_tpu=False, verbose=False)

    def test_example_build_app_and_seed(self):
        from zipkin_tpu.main.example import build_app, build_parser, seed

        args = build_parser().parse_args(
            ["--memory-store", "--seed-traces", "2"]
        )
        store, collector, api, _shipper = build_app(args)
        seed(collector, 2)
        status, services = api.handle("GET", "/api/services", {})
        assert status == 200 and services
        # Runtime-adjustable sample rate (HttpVar parity).
        status, body = api.handle("POST", "/vars/sampleRate", {}, b"0.25")
        assert status == 200 and body["sampleRate"] == 0.25
        assert collector.sampler.rate == 0.25
        collector.close()


def test_pinned_traces_survive_checkpoint_restart(tmp_path):
    """Pin → save → load → flood: the eviction-exempt bank restores
    with the TTL, so the retention contract holds across restarts."""
    from zipkin_tpu.models.span import Annotation, Endpoint, Span
    from zipkin_tpu.store.device import StoreConfig
    from zipkin_tpu.store.tpu import TpuSpanStore
    from zipkin_tpu import checkpoint

    cfg = StoreConfig(
        capacity=256, ann_capacity=1024, bann_capacity=512,
        max_services=16, max_span_names=32, max_annotation_values=64,
        max_binary_keys=16, cms_width=256, hll_p=6, quantile_buckets=128,
    )
    store = TpuSpanStore(cfg)
    ep = Endpoint(1, 80, "pinned-svc")
    tid = 777
    store.apply([Span(tid, "op", 1, None,
                      (Annotation(10, "sr", ep), Annotation(20, "ss", ep)),
                      ())])
    store.set_time_to_live(tid, 30 * 24 * 3600.0)
    path = str(tmp_path / "ckpt")
    checkpoint.save(store, path)

    restored = checkpoint.load(path)
    assert restored.get_time_to_live(tid) == 30 * 24 * 3600.0
    noise_ep = Endpoint(2, 80, "noise")
    for i in range(0, 2 * cfg.capacity, 128):
        restored.apply([
            Span(10_000 + i + j, "n", 50_000 + i + j, None,
                 (Annotation(30 + j, "sr", noise_ep),), ())
            for j in range(128)
        ])
    got = restored.get_spans_by_trace_id(tid)
    assert len(got) == 1 and got[0].id == 1
    assert tid in restored.traces_exist([tid])


def test_pin_bank_dedups_redelivered_spans():
    from zipkin_tpu.models.span import Annotation, Endpoint, Span
    from zipkin_tpu.store.device import StoreConfig
    from zipkin_tpu.store.tpu import TpuSpanStore

    cfg = StoreConfig(
        capacity=256, ann_capacity=1024, bann_capacity=512,
        max_services=16, max_span_names=32, max_annotation_values=64,
        max_binary_keys=16, cms_width=256, hll_p=6, quantile_buckets=128,
    )
    store = TpuSpanStore(cfg)
    ep = Endpoint(1, 80, "svc")
    tid = 888
    span = Span(tid, "op", 1, None, (Annotation(10, "sr", ep),), ())
    store.apply([span])
    store.set_time_to_live(tid, 30 * 24 * 3600.0)
    # Transport retry re-delivers the identical span 5 times.
    for _ in range(5):
        store.apply([span])
    bank = store.pins.get(store.pins.tids().pop())
    assert len(bank) == 1


def test_sharded_checkpoint_roundtrip(tmp_path):
    """ShardedSpanStore snapshot -> restore over a fresh mesh: queries,
    sketches, and pinned banks all survive (the sharded analogue of the
    single-store durability contract)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from zipkin_tpu import checkpoint
    from zipkin_tpu.models.span import Annotation, Endpoint, Span
    from zipkin_tpu.parallel.shard import ShardedSpanStore
    from zipkin_tpu.store.device import StoreConfig
    from zipkin_tpu.tracegen import generate_traces

    n = min(4, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n]), axis_names=("shard",))
    cfg = StoreConfig(
        capacity=256, ann_capacity=1024, bann_capacity=512,
        max_services=16, max_span_names=32, max_annotation_values=64,
        max_binary_keys=16, cms_width=256, hll_p=6, quantile_buckets=128,
    )
    store = ShardedSpanStore(mesh, cfg)
    spans = [s for t in generate_traces(n_traces=12, max_depth=3,
                                        n_services=6) for s in t]
    store.apply(spans)
    ep = Endpoint(1, 80, "pinsvc")
    store.apply([Span(4242, "p", 1, None, (Annotation(7, "sr", ep),), ())])
    store.set_time_to_live(4242, 30 * 24 * 3600.0)
    path = str(tmp_path / "sharded-ckpt")
    checkpoint.save(store, path)

    restored = checkpoint.load(path)
    assert restored.n == n
    assert restored.stored_span_count() == store.stored_span_count()
    svc = sorted(store.get_all_service_names())[0]
    want = store.get_trace_ids_by_name(svc, None, 2**62, 10)
    got = restored.get_trace_ids_by_name(svc, None, 2**62, 10)
    assert [(i.trace_id, i.timestamp) for i in want] == \
           [(i.trace_id, i.timestamp) for i in got]
    tid = want[0].trace_id
    assert [s.id for t in restored.get_spans_by_trace_ids([tid]) for s in t] \
        == [s.id for t in store.get_spans_by_trace_ids([tid]) for s in t]
    assert restored.get_time_to_live(4242) == 30 * 24 * 3600.0
    assert restored.get_spans_by_trace_id(4242)
    d1 = {(l.parent, l.child) for l in store.get_dependencies().links}
    d2 = {(l.parent, l.child) for l in restored.get_dependencies().links}
    assert d1 == d2


def test_sharded_legacy_snapshot_migrates(tmp_path):
    """Pre-revision-4 SHARDED snapshot: per-shard live-link migration,
    the [n_shards] write_pos fallback slicing, and the shard_map span-
    table rebuild must all restore links and cross-batch joins."""
    import json
    import os

    import jax
    import numpy as np
    from jax.sharding import Mesh

    from zipkin_tpu import checkpoint
    from zipkin_tpu.parallel.shard import ShardedSpanStore
    from zipkin_tpu.store.device import StoreConfig
    from zipkin_tpu.tracegen import generate_traces

    n = min(4, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n]), axis_names=("shard",))
    cfg = StoreConfig(
        capacity=256, ann_capacity=1024, bann_capacity=512,
        max_services=16, max_span_names=32, max_annotation_values=64,
        max_binary_keys=16, cms_width=256, hll_p=6, quantile_buckets=128,
    )
    store = ShardedSpanStore(mesh, cfg)
    traces = generate_traces(n_traces=10, max_depth=3, n_services=6)
    parents = [t[0] for t in traces]
    children = [s for t in traces for s in t[1:]]
    store.apply(parents + children)
    expected = {(l.parent, l.child, l.duration_moments.count)
                for l in store.get_dependencies().links}
    assert expected

    path = str(tmp_path / "sharded-legacy")
    checkpoint.save(store, path)

    # Rewrite into the revision-3 layout: links only implicit in the
    # per-shard rings + zero watermarks; no streaming-join leaves.
    state_file = os.path.join(path, "state.npz")
    data = dict(np.load(state_file))
    for gone in ("span_tab", "pend_key", "pend_dur", "pend_tsf",
                 "pend_tsl", "pend_pos", "dep_window", "dep_window_ts"):
        del data[gone]
    data["dep_moments"] = np.zeros_like(data["dep_moments"])
    data["dep_banks"] = np.zeros_like(data["dep_banks"])
    data["dep_archived_gid"] = np.zeros(n, np.int64)
    np.savez_compressed(state_file, **data)
    meta_file = os.path.join(path, "meta.json")
    with open(meta_file) as f:
        meta = json.load(f)
    meta["revision"] = 3
    for k in ("span_tab_slots", "pend_slots"):
        meta["config"].pop(k, None)
    with open(meta_file, "w") as f:
        json.dump(meta, f)

    restored = checkpoint.load(path, mesh=mesh)
    got = {(l.parent, l.child, l.duration_moments.count)
           for l in restored.get_dependencies().links}
    assert got == expected
    # The rebuilt span table must resolve a child arriving post-restore
    # whose parent only exists in the checkpointed ring.
    late = [t[1] for t in generate_traces(n_traces=1, max_depth=2,
                                          n_services=6) if len(t) > 1]
    from zipkin_tpu.models.span import Annotation, Endpoint, Span

    parent = parents[0]
    ep = Endpoint(9, 80, sorted(restored.get_all_service_names())[0])
    child = Span(parent.trace_id, "late", 987654, parent.id,
                 (Annotation(50, "sr", ep), Annotation(60, "ss", ep)), ())
    restored.apply([child])
    after = {(l.parent, l.child) for l in restored.get_dependencies().links}
    assert len(after) >= len({(p, c) for p, c, _ in expected})
