"""FP twin: strictly increasing ranks, incl. through a callee."""
import threading


class Store:
    def __init__(self):
        self.a = threading.Lock()  # lock-order: 10 outer
        self.b = threading.Lock()  # lock-order: 20 inner

    def good(self):
        with self.a:
            self._inner()

    def _inner(self):
        with self.b:
            pass
