"""FP twin: the canonical rebind loop, and rebinding before reuse."""
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def step(state, batch):
    return state + batch


def drive(state, batches):
    for batch in batches:
        state = step(state, batch)
    return state


def rebound(state, batch):
    state = step(state, batch)
    return state.sum()


def nested_scope(state, batch):
    out = step(state, batch)

    def later(state):
        # Different scope + own param: not a read of the donated
        # outer buffer.
        return state + 1

    return later(out)
