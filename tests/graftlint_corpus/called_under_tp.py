"""TP: a called-under method invoked without the declared lock."""
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()  # lock-order: 10 store
        self.n = 0

    def _bump_locked(self):  # called-under: _lock
        self.n += 1

    def bad(self):
        self._bump_locked()  # lock not held
