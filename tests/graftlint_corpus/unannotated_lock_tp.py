"""TP: a lock with no lock-order annotation."""
import threading


class Store:
    def __init__(self):
        self.naked = threading.Lock()
