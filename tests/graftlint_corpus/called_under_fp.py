"""FP twin: call sites hold the lock (directly or transitively)."""
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()  # lock-order: 10 store
        self.n = 0

    def _bump_locked(self):  # called-under: _lock
        self.n += 1

    def good(self):
        with self._lock:
            self._bump_locked()

    def _chain_locked(self):  # called-under: _lock
        self._bump_locked()
