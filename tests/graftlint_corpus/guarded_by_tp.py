"""TP: guarded attribute read+written outside its lock; also a
foreign private access without the owner lock."""
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()  # lock-order: 10 store
        self._frontier = 0  # guarded-by: _lock

    def bump(self):
        self._frontier += 1  # no lock held

    def peek(self):
        return self._frontier  # no lock held


def foreign(store):
    return store._frontier  # not inside 'with store._lock'
