"""TP: a <-> b acquisition cycle across two call paths, plus a
non-reentrant self re-entry."""
import threading


class Store:
    def __init__(self):
        self.a = threading.Lock()  # lock-order: 10 a
        self.b = threading.Lock()  # lock-order: 20 b

    def path_one(self):
        with self.a:
            with self.b:
                pass

    def path_two(self):
        with self.b:
            with self.a:
                pass

    def reenter(self):
        with self.a:
            with self.a:
                pass
