"""TP: donated buffer read after the donating call."""
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def step(state, batch):
    return state + batch


def drive(state, batch):
    out = step(state, batch)
    return out + state.sum()  # state's buffer was donated
