"""FP twin: every access holds the lock (directly, via called-under,
or in __init__); rwlock mode semantics; sanctioned foreign access."""
import threading


class RWLock:
    pass


class Store:
    def __init__(self):
        self._lock = threading.Lock()  # lock-order: 10 store
        self._rw = RWLock()  # lock-order: 40 commit
        self._frontier = 0  # guarded-by: _lock
        self.state = object()  # guarded-by: _rw.write
        self._frontier = 1  # __init__ is exempt

    def bump(self):
        with self._lock:
            self._frontier += 1
        self._locked_peek()

    def _locked_peek(self):  # called-under: _lock
        return self._frontier

    def swap(self, new):
        with self._rw.write():
            self.state = new

    def read(self):
        with self._rw.read():
            return self.state

    def suppressed(self):
        return self._frontier  # graftlint: disable=guarded-by


def foreign(store):
    with store._lock:
        return store._frontier
