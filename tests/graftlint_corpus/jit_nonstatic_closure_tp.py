"""TP: jitted fn closes over a lowercase module scalar and a
reassigned module global."""
import jax

scale = 3
MODE = 1
MODE = 2


@jax.jit
def step(x):
    return x * scale + MODE
