"""FP twin: the sanctioned launch shapes stay silent — a read-mode
hold WITH the collective-launch leaf lock, a write-mode hold (writers
exclude each other, no concurrent dispatch), and unlocked launches."""
import threading

import jax
from jax.experimental.shard_map import shard_map


def _step(states):
    return states


class RWLock:
    pass


class Fleet:
    def __init__(self):
        self._rw = RWLock()  # lock-order: 40 commit
        self._coll_lock = threading.Lock()  # lock-order: 45 collective-launch
        self._sum_kernel = jax.jit(shard_map(_step, mesh=None,
                                             in_specs=None,
                                             out_specs=None))
        self.states = None

    def good_serialized_read(self):
        with self._rw.read():
            with self._coll_lock:
                return self._sum_kernel(self.states)

    def good_write_hold(self):
        with self._rw.write():
            return self._sum_kernel(self.states)

    def good_unlocked(self):
        return self._sum_kernel(self.states)

    def good_host_work_under_read(self):
        with self._rw.read():
            return len(self.states)
