"""TP: Python branch on a traced parameter inside a jitted fn."""
from functools import partial

import jax


@partial(jax.jit, static_argnums=(1,))
def step(x, k):
    if x > 0:
        return x + k
    return x - k
