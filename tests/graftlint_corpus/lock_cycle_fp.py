"""FP twin: a -> b only (a DAG), and RLock re-entry is legal."""
import threading


class Store:
    def __init__(self):
        self.a = threading.Lock()  # lock-order: 10 a
        self.b = threading.Lock()  # lock-order: 20 b
        self.r = threading.RLock()  # lock-order: 30 r

    def path_one(self):
        with self.a:
            with self.b:
                pass

    def path_two(self):
        with self.a:
            pass

    def reenter_rlock(self):
        with self.r:
            with self.r:
                pass
