"""TP: device syncs inside a write-lock region — lexically and
through a resolvable callee."""
import jax
import numpy as np


class RWLock:
    pass


class Store:
    def __init__(self):
        self._rw = RWLock()  # lock-order: 40 commit
        self.state = None

    def bad_direct(self, x):
        with self._rw.write():
            return jax.device_get(x)

    def bad_via_call(self, x):
        with self._rw.write():
            return self._pull(x)

    def _pull(self, x):
        return np.asarray(x)
