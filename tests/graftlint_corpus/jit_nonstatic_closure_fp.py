"""FP twin: UPPERCASE constants and locals are fine."""
import jax

SCALE = 3


@jax.jit
def step(x):
    local = 2
    return x * SCALE + local
