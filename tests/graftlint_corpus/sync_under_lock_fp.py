"""FP twin: syncs under the READ lock (the sanctioned capture-pull
pattern) or outside locks entirely."""
import jax
import numpy as np


class RWLock:
    pass


class Store:
    def __init__(self):
        self._rw = RWLock()  # lock-order: 40 commit
        self.state = None

    def good_read(self, x):
        with self._rw.read():
            return jax.device_get(x)

    def good_unlocked(self, x):
        got = np.asarray(x)
        with self._rw.write():
            self.state = got
