"""TP: broad except that drops the error on the floor."""


def risky():
    raise ValueError("boom")


def bad():
    try:
        risky()
    except Exception:
        pass


def bad_bare():
    try:
        risky()
    except:  # noqa: E722
        return None
