"""FP twin: re-raise, use the bound error, or count it."""
import logging

log = logging.getLogger(__name__)


class C:
    def inc(self):
        pass


errors = C()


def risky():
    raise ValueError("boom")


def reraises():
    try:
        risky()
    except Exception:
        raise


def parks():
    parked = None
    try:
        risky()
    except Exception as e:
        parked = e
    return parked


def logs():
    try:
        risky()
    except Exception:
        log.exception("risky failed")


def counts():
    try:
        risky()
    except Exception:
        errors.inc()
