"""TP: shard_map/pjit collective launches under a shared READ lock
with no collective-launch leaf held — concurrent readers would
dispatch overlapping collectives into the cross-device rendezvous."""
import threading

import jax
from jax.experimental.shard_map import shard_map
from jax.experimental.pjit import pjit


def _step(states):
    return states


_FLEET_SUM = jax.jit(shard_map(_step, mesh=None,
                               in_specs=None, out_specs=None))


class RWLock:
    pass


class Fleet:
    def __init__(self):
        self._rw = RWLock()  # lock-order: 40 commit
        self._sum_kernel = shard_map(_step, mesh=None,
                                     in_specs=None, out_specs=None)
        self.states = None

    def bad_self_attr(self):
        with self._rw.read():
            return self._sum_kernel(self.states)

    def bad_module_kernel(self):
        with self._rw.read():
            return _FLEET_SUM(self.states)

    def bad_local_alias(self):
        kern = pjit(_step)
        with self._rw.read():
            return kern(self.states)

    def bad_inline(self):
        with self._rw.read():
            return shard_map(_step, mesh=None, in_specs=None,
                             out_specs=None)(self.states)
