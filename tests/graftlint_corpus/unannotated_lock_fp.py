"""FP twin: annotated lock (module-level too)."""
import threading

_MOD_LOCK = threading.Lock()  # lock-order: 20 module


class Store:
    def __init__(self):
        self.dressed = threading.Lock()  # lock-order: 10 dressed
