"""TP: acquires the outer-ranked lock while holding the inner one."""
import threading


class Store:
    def __init__(self):
        self.a = threading.Lock()  # lock-order: 10 outer
        self.b = threading.Lock()  # lock-order: 20 inner

    def bad(self):
        with self.b:
            with self.a:  # rank 10 acquired under rank 20
                pass
