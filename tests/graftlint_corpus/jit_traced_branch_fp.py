"""FP twin: branches on static params and on Noneness only."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnums=(1,))
def step(x, k, mask=None):
    if k > 2:
        x = x * 2
    if mask is not None:
        x = jnp.where(mask, x, 0)

    def body(x, n):
        # Nested def: its own (shadowing) params run in a different
        # trace scope — branching here must not read as a branch on
        # the OUTER traced x.
        if n > 0:
            return x
        return -x

    return body(x, 3)
