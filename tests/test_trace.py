"""Trace assembly tests (reference: zipkin-common TraceTest)."""

from zipkin_tpu.models.span import Annotation, Endpoint, Span
from zipkin_tpu.models.trace import Trace, TraceCombo, TraceSummary

EP = Endpoint(1, 80, "svc")


def ann(ts, value, ep=EP):
    return Annotation(ts, value, ep)


def make_trace():
    root = Span(1, "root", 100, None, (ann(100, "sr"), ann(500, "ss")))
    child1 = Span(1, "c1", 200, 100, (ann(150, "sr"), ann(200, "ss")))
    child2 = Span(1, "c2", 300, 100, (ann(250, "sr"), ann(300, "ss")))
    grandchild = Span(1, "g", 400, 300, (ann(260, "sr"), ann(280, "ss")))
    # shuffled input order; Trace must sort by first timestamp
    return Trace([child2, grandchild, root, child1])


def test_spans_sorted_by_first_timestamp():
    t = make_trace()
    assert [s.name for s in t.spans] == ["root", "c1", "c2", "g"]


def test_trace_id_and_root():
    t = make_trace()
    assert t.id == 1
    assert t.get_root_span().name == "root"
    assert t.get_root_most_span().name == "root"


def test_root_most_span_with_missing_root():
    orphan = Span(1, "orphan", 200, 999, (ann(150, "sr"),))
    child = Span(1, "child", 300, 200, (ann(160, "sr"),))
    t = Trace([orphan, child])
    assert t.get_root_most_span().name == "orphan"


def test_duration_and_timespan():
    t = make_trace()
    assert t.start_and_end_timestamp() == (100, 500)
    assert t.duration == 400


def test_span_depths():
    depths = make_trace().to_span_depths()
    assert depths == {100: 1, 200: 2, 300: 2, 400: 3}


def test_services_and_counts():
    t = make_trace()
    assert t.services == {"svc"}
    assert t.service_counts() == {"svc": 4}


def test_merges_split_spans():
    client = Span(1, "rpc", 7, None, (ann(10, "cs"), ann(40, "cr")))
    server = Span(1, "rpc", 7, None, (ann(20, "sr"), ann(30, "ss")))
    t = Trace([client, server])
    assert len(t.spans) == 1
    assert len(t.spans[0].annotations) == 4


def test_summary_and_combo():
    t = make_trace()
    s = TraceSummary.from_trace(t)
    assert s.trace_id == 1
    assert s.duration_micro == 400
    combo = TraceCombo.from_trace(t)
    assert combo.summary == s
    assert combo.timeline.root_span_id == 100
    assert combo.timeline.annotations[0].timestamp == 100
    assert combo.span_depths[400] == 3


def test_empty_trace():
    t = Trace([])
    assert t.id is None
    assert t.get_root_span() is None
    assert t.duration == 0
    assert TraceSummary.from_trace(t) is None


def test_parent_id_cycle_does_not_recurse_forever():
    # Malformed input: two spans that are each other's parent.
    a = Span(9, "a", 1, 2, (ann(1, "sr"),))
    b = Span(9, "b", 2, 1, (ann(2, "sr"),))
    t = Trace([a, b])
    assert t.get_root_most_span().name == "a"
    assert t.to_span_depths() == {1: 1, 2: 2}
