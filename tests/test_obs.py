"""Telemetry layer tests: registry semantics, Prometheus exposition,
latency sketches (moments + log-histogram agreement), the device
counter block, per-route API latency, the profiler endpoint, and
collector ingest-step self-tracing."""

import json
import math
import threading

import numpy as np
import pytest

from zipkin_tpu import obs
from zipkin_tpu.api import ApiServer
from zipkin_tpu.ingest.collector import Collector
from zipkin_tpu.models.span import Annotation, Endpoint, Span
from zipkin_tpu.query.service import QueryService
from zipkin_tpu.store.memory import InMemorySpanStore

EP = Endpoint(0x01010101, 80, "svc")


def span(tid, sid=1, ts=100):
    return Span(tid, "op", sid, None, (
        Annotation(ts, "sr", EP), Annotation(ts + 10, "ss", EP),
    ), ())


class TestRegistry:
    def test_counter_monotonic_and_locked(self):
        r = obs.Registry()
        c = r.register(obs.Counter("t_total", "h"))
        threads = [
            threading.Thread(target=lambda: [c.inc() for _ in range(1000)])
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 8000
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_fn_and_set(self):
        g = obs.Gauge("g", "h", fn=lambda: 41)
        assert g.value == 41
        g.set(5)
        assert g.value == 5

    def test_reregister_replaces(self):
        r = obs.Registry()
        r.register(obs.Counter("x", "h")).inc(3)
        c2 = r.register(obs.Counter("x", "h"))
        assert r.get("x") is c2 and c2.value == 0

    def test_labels_children(self):
        c = obs.Counter("req_total", "h", labelnames=("route",))
        c.labels(route="/a").inc(2)
        c.labels(route="/b").inc()
        assert c.labels(route="/a").value == 2
        with pytest.raises(ValueError):
            c.labels(nope="x")

    def test_sketch_quantiles_and_moments(self):
        h = obs.LatencySketch("lat_seconds", "h")
        vals = np.random.default_rng(7).uniform(1e-4, 1.0, 5000)
        for v in vals:
            h.observe(float(v))
        p50, p99 = h.quantile_values((0.5, 0.99))
        # DDSketch relative-accuracy guarantee (alpha=1%, small slack
        # for the discrete rank step).
        assert abs(p50 - np.quantile(vals, 0.5)) / p50 < 0.05
        assert abs(p99 - np.quantile(vals, 0.99)) / p99 < 0.05
        snap = h.snapshot()
        assert snap["count"] == 5000
        assert abs(snap["mean"] - vals.mean()) < 1e-6
        assert abs(snap["stddev"] - vals.std()) < 1e-6

    def test_sketch_merge(self):
        a = obs.LatencySketch("m", "h")
        b = obs.LatencySketch("m", "h")
        for v in (0.1, 0.2):
            a.observe(v)
        for v in (0.3, 0.4):
            b.observe(v)
        a.merge(b)
        assert a.count == 4
        assert abs(a.snapshot()["mean"] - 0.25) < 1e-9


class TestPrometheusText:
    def _registry(self):
        r = obs.Registry()
        r.register(obs.Counter("z_total", "a counter")).inc(2)
        r.register(obs.Gauge("z_gauge", "a gauge", fn=lambda: 1.5))
        h = r.register(obs.LatencySketch("z_seconds", "a summary"))
        h.observe(0.25)
        return r

    def test_type_and_help_lines(self):
        text = self._registry().render_text()
        assert "# TYPE z_total counter\n" in text
        assert "# TYPE z_gauge gauge\n" in text
        assert "# TYPE z_seconds summary\n" in text
        assert "# HELP z_total a counter\n" in text
        assert "\nz_total 2\n" in text
        assert "\nz_gauge 1.5\n" in text
        assert 'z_seconds{quantile="0.5"}' in text
        assert 'z_seconds{quantile="0.99"}' in text
        assert "\nz_seconds_count 1\n" in text

    def test_label_escaping(self):
        r = obs.Registry()
        c = r.register(obs.Counter("esc_total", "h",
                                   labelnames=("route",)))
        c.labels(route='we"ird\\path\nx').inc()
        text = r.render_text()
        assert 'esc_total{route="we\\"ird\\\\path\\nx"} 1' in text

    def test_empty_sketch_renders_nan(self):
        r = obs.Registry()
        r.register(obs.LatencySketch("never_seconds", "h"))
        text = r.render_text()
        assert 'never_seconds{quantile="0.5"} NaN' in text
        assert "never_seconds_count 0" in text


class TestFleetExposition:
    """Federated-scrape exposition (obs/fleet.render_federated):
    format validity of the merged ``/metrics?fleet=1`` view — one
    HELP/TYPE per family, follower-name label escaping, and
    counter monotonicity across successive federated scrapes."""

    def _sources(self, follower="r1", inc=3):
        from zipkin_tpu.obs.fleet import registry_snapshot

        a = obs.Registry()
        a.register(obs.Counter("fx_total", "fleet requests")).inc(inc)
        sk = a.register(obs.LatencySketch("fx_seconds", "fleet lat"))
        sk.observe(0.01)
        b = obs.Registry()
        b.register(obs.Counter("fx_total", "fleet requests")).inc(inc)
        return a, b, [
            ((("role", "primary"),), registry_snapshot(a)),
            ((("role", "follower"), ("follower", follower)),
             registry_snapshot(b)),
        ]

    def test_merged_scrape_type_help_unique(self):
        from zipkin_tpu.obs.fleet import render_federated

        _a, _b, sources = self._sources()
        text = render_federated(sources)
        for fam in ("fx_total", "fx_seconds"):
            assert text.count(f"# TYPE {fam} ") == 1, fam
            assert text.count(f"# HELP {fam} ") == 1, fam
        # Both processes' samples survive under the one family header.
        assert text.count("fx_total{") == 2

    def test_follower_name_label_escaping(self):
        from zipkin_tpu.obs.fleet import render_federated

        _a, _b, sources = self._sources(follower='we"ird\\host\nx')
        text = render_federated(sources)
        assert 'follower="we\\"ird\\\\host\\nx"' in text
        # No raw newline may leak into a sample line.
        for line in text.splitlines():
            if line.startswith("fx_total{"):
                assert line.count("}") == 1

    def test_counters_monotonic_across_federated_scrapes(self):
        from zipkin_tpu.obs.fleet import (
            registry_snapshot,
            render_federated,
        )

        a, b, sources = self._sources()

        def scrape():
            srcs = [
                ((("role", "primary"),), registry_snapshot(a)),
                ((("role", "follower"), ("follower", "r1")),
                 registry_snapshot(b)),
            ]
            out = {}
            for line in render_federated(srcs).splitlines():
                if line.startswith("fx_total{"):
                    key, v = line.rsplit(" ", 1)
                    out[key] = float(v)
            return out

        s1 = scrape()
        a.get("fx_total").inc(2)
        b.get("fx_total").inc(5)
        s2 = scrape()
        assert set(s1) == set(s2) and len(s1) == 2
        for key in s1:
            assert s2[key] >= s1[key], key
        assert sum(s2.values()) == sum(s1.values()) + 7

    def test_federated_values_bitwise_match_own_scrape(self):
        """Every sample value in the merged view formats EXACTLY as
        the owning process's own /metrics scrape does (same _fmt
        path) — federation may relabel, never re-round."""
        from zipkin_tpu.obs.fleet import (
            registry_snapshot,
            render_federated,
        )

        r = obs.Registry()
        sk = r.register(obs.LatencySketch("bw_seconds", "h"))
        for v in (0.000123, 0.37, 1.5e-5):
            sk.observe(v)
        def keyed(text):
            out = set()
            for line in text.splitlines():
                if not line or line.startswith("#"):
                    continue
                name = line.split("{")[0].split(" ")[0]
                out.add(name + "|" + line.rsplit(" ", 1)[1])
            return out

        own = keyed(r.render_text())
        fed = keyed(render_federated(
            [((("role", "primary"),), registry_snapshot(r))]))
        assert own == fed


class TestWalTelemetry:
    """The write-ahead log's metric surface (zipkin_tpu.wal): append/
    fsync sketches, segment-bytes and truncation-backlog gauges, and
    the record/replay/corrupt/truncation counters, all rendered in
    Prometheus exposition form."""

    def test_wal_metric_families_exposed(self, tmp_path):
        from zipkin_tpu.wal import WriteAheadLog

        r = obs.Registry()
        wal = WriteAheadLog(str(tmp_path / "w"), fsync="batch",
                            registry=r, compress=False)
        wal.append(b"x" * 200)
        wal.append(b"y" * 200)
        text = r.render_text()
        assert "# TYPE zipkin_wal_append_seconds summary" in text
        assert "# TYPE zipkin_wal_fsync_seconds summary" in text
        assert "# TYPE zipkin_wal_segment_bytes gauge" in text
        assert ("# TYPE zipkin_wal_truncation_backlog_segments gauge"
                in text)
        assert "# TYPE zipkin_wal_records_total counter" in text
        assert "# TYPE zipkin_wal_replayed_records_total counter" in text
        assert "# TYPE zipkin_wal_corrupt_records_total counter" in text
        assert "# TYPE zipkin_wal_truncated_segments_total counter" in text
        assert "\nzipkin_wal_records_total 2\n" in text
        assert "zipkin_wal_append_seconds_count 2" in text
        # fsync=batch observes one fsync per append
        assert "zipkin_wal_fsync_seconds_count 2" in text
        vals = r.as_dict()
        assert vals["zipkin_wal_segment_bytes"] > 0
        assert vals["zipkin_wal_truncation_backlog_segments"] == 1.0
        wal.close()
        # close() unregisters this log's metrics
        assert r.get("zipkin_wal_records_total") is None

    def test_corrupt_and_truncated_counters(self, tmp_path):
        from zipkin_tpu.wal import WriteAheadLog

        d = str(tmp_path / "w")
        wal = WriteAheadLog(d, fsync="batch", compress=False,
                            segment_bytes=1 << 12)
        import os

        for i in range(12):
            wal.append(bytes([i]) * 1500)
        removed = wal.truncate(upto_seq=8)
        assert removed >= 1
        assert int(wal.c_truncated.value) == removed
        wal.close()
        # tear the tail, reopen with a fresh registry: the open-time
        # scan counts the cut record
        seg = sorted(n for n in os.listdir(d) if n.endswith(".seg"))[-1]
        with open(os.path.join(d, seg), "r+b") as f:
            f.truncate(os.path.getsize(os.path.join(d, seg)) - 10)
        r2 = obs.Registry()
        wal2 = WriteAheadLog(d, fsync="batch", registry=r2)
        text = r2.render_text()
        assert "\nzipkin_wal_corrupt_records_total 1\n" in text
        wal2.close()


class TestWindowTelemetry:
    """The windowed Moments-sketch arena's metric surface
    (zipkin_window_*): fold counters (monotonic across scrapes and
    ring self-clears), the cell-occupancy/retention gauges, and the
    per-endpoint serve-latency sketch family, all in Prometheus
    exposition form with TYPE/HELP lines and escaped labels."""

    BASE_US = 1_700_000_000_000_000

    def _store(self, reg):
        from zipkin_tpu.store.device import StoreConfig
        from zipkin_tpu.store.tpu import TpuSpanStore

        return TpuSpanStore(StoreConfig(
            capacity=1 << 10, ann_capacity=1 << 12,
            bann_capacity=1 << 11, max_services=16, max_span_names=32,
            max_annotation_values=64, max_binary_keys=16,
            cms_width=1 << 10, hll_p=8, quantile_buckets=512,
            window_seconds=60, window_buckets=4,
        ), registry=reg)

    def _spans(self, n, errors=0, base_off=0):
        out = []
        for i in range(n):
            ts = self.BASE_US + base_off + i
            anns = [Annotation(ts, "sr", EP),
                    Annotation(ts + 500, "ss", EP)]
            if i < errors:
                anns.append(Annotation(ts + 1, "error", EP))
            out.append(Span(i + 1, "op", i + 1, None, tuple(anns), ()))
        return out

    def test_window_families_exposed_and_monotonic(self):
        reg = obs.Registry()
        store = self._store(reg)
        store.apply(self._spans(10, errors=3))
        text = reg.render_text()
        assert "# TYPE zipkin_window_spans_total counter" in text
        assert "# HELP zipkin_window_spans_total" in text
        assert "# TYPE zipkin_window_errors_total counter" in text
        assert "# TYPE zipkin_window_cells_active gauge" in text
        assert "# TYPE zipkin_window_retention_seconds gauge" in text
        assert "\nzipkin_window_spans_total 10\n" in text
        assert "\nzipkin_window_errors_total 3\n" in text
        assert "\nzipkin_window_cells_active 1\n" in text
        assert "\nzipkin_window_retention_seconds 240\n" in text
        # Monotonic across scrapes even when the ring SELF-CLEARS a
        # slot (bucket 0 overwritten 4 ring-lengths later): the cell
        # gauge may move, the fold counters only climb.
        v1 = reg.as_dict()
        store.apply(self._spans(
            5, errors=1, base_off=4 * 60_000_000))
        v2 = reg.as_dict()
        assert v2["zipkin_window_spans_total"] == 15
        assert v2["zipkin_window_errors_total"] == 4
        assert (v2["zipkin_window_spans_total"]
                >= v1["zipkin_window_spans_total"])
        assert (v2["zipkin_window_errors_total"]
                >= v1["zipkin_window_errors_total"])
        # counters() surfaces the same accounting for /metrics JSON.
        c = store.counters()
        assert c["window_spans"] == 15.0
        assert c["window_errors"] == 4.0

    def test_window_query_sketch_family_and_escaping(self):
        from zipkin_tpu.query.engine import QueryEngine

        reg = obs.Registry()
        store = self._store(reg)
        store.apply(self._spans(8))
        eng = QueryEngine(store, registry=reg)
        try:
            eng.windowed_quantiles("svc", [0.5])
            eng.slo_burn("svc")
            text = reg.render_text()
            assert ("# TYPE zipkin_window_query_seconds summary"
                    in text)
            assert ('zipkin_window_query_seconds{'
                    'endpoint="windowed_quantiles",quantile="0.5"}'
                    in text)
            assert ('zipkin_window_query_seconds{endpoint="slo_burn"'
                    in text)
            assert "zipkin_window_query_seconds_count" in text
        finally:
            eng.close()
        # Label escaping holds for the family machinery the window
        # sketch uses (hostile endpoint names can't corrupt the feed).
        s = obs.LatencySketch("w_seconds", "h",
                              labelnames=("endpoint",))
        s.labels(endpoint='a"b\\c\nd').observe(0.1)
        r2 = obs.Registry()
        r2.register(s)
        assert 'endpoint="a\\"b\\\\c\\nd"' in r2.render_text()


class TestApiMetricsSurface:
    """Acceptance shape: /metrics serves valid Prometheus text covering
    every pipeline stage with latency quantiles, and stays monotonic
    across scrapes."""

    def _app(self):
        reg = obs.Registry()
        store = InMemorySpanStore()
        collector = Collector(store, concurrency=2, registry=reg)
        api = ApiServer(QueryService(store), collector, registry=reg)
        return store, collector, api, reg

    def test_all_five_stages_present(self):
        store, collector, api, reg = self._app()
        collector.accept([span(1)])
        collector.flush()
        api.handle("GET", "/api/services", {})
        status, payload = api.handle("GET", "/metrics", {})
        assert status == 200
        text = payload.body.decode()
        stage_markers = {
            "queue": "zipkin_queue_depth",
            "collector": "zipkin_collector_spans_stored_total",
            "store": 'zipkin_store_counter{name="spans_stored"}',
            "query": 'zipkin_api_request_seconds{route="/api/services"'
                     ',quantile="0.99"}',
            "sampler": "zipkin_sampler_rate",
        }
        for stage, marker in stage_markers.items():
            assert marker in text, (stage, text)
        # >= 12 distinct metric families exposed.
        families = {
            line.split()[2] for line in text.splitlines()
            if line.startswith("# TYPE")
        }
        assert len(families) >= 12, sorted(families)
        # p50 AND p99 lines exist for the latency summaries.
        assert 'quantile="0.5"' in text and 'quantile="0.99"' in text

    def test_counters_monotonic_across_requests(self):
        store, collector, api, reg = self._app()

        def scrape():
            _, payload = api.handle("GET", "/metrics", {})
            out = {}
            for line in payload.body.decode().splitlines():
                if line.startswith("#"):
                    continue
                k, _, v = line.rpartition(" ")
                if v not in ("NaN", "+Inf", "-Inf"):
                    out[k] = float(v)
            return out

        first = scrape()
        for i in range(3):
            collector.accept([span(10 + i)])
        collector.flush()
        api.handle("GET", "/api/services", {})
        second = scrape()
        counters = [
            k for k in first
            if k.endswith("_total") or k.endswith("_count")
        ]
        assert counters
        for k in counters:
            assert second.get(k, 0) >= first[k], k
        assert (second["zipkin_collector_spans_stored_total"]
                >= first["zipkin_collector_spans_stored_total"] + 3)

    def test_json_form_unchanged(self):
        store, collector, api, reg = self._app()
        status, body = api.handle("GET", "/metrics", {"format": "json"})
        assert status == 200
        assert "collector.queue_size" in body
        json.dumps(body)  # still a plain JSON dict

    def test_route_label_normalization(self):
        from zipkin_tpu.api.server import _route_label

        assert _route_label("/api/trace/deadbeef") == "/api/trace/{id}"
        assert _route_label("/api/pin/1f/true") == "/api/pin/{id}"
        assert _route_label("/api/query") == "/api/query"
        assert _route_label("/some/scanner/path") == "other"

    def test_profile_endpoint(self):
        store, collector, api, reg = self._app()
        status, body = api.handle("POST", "/debug/profile",
                                  {"seconds": "0.05"})
        # 200 with a trace dir when the backend can trace, 503 when the
        # profiler is unavailable in this environment — never a crash.
        assert status in (200, 503), body
        if status == 200:
            import os

            assert os.path.isdir(body["profileDir"])
        status2, body2 = api.handle("POST", "/debug/profile",
                                    {"seconds": "nope"})
        assert status2 == 400


class TestCollectorTelemetry:
    def test_threaded_failure_counters_exact(self):
        """Failure-path counters must not lose increments under
        concurrent submitters (the old dict read-modify-write hazard)."""
        reg = obs.Registry()
        store = InMemorySpanStore()
        collector = Collector(store, concurrency=4, registry=reg)
        n_threads, n_each = 8, 50

        def slam():
            for _ in range(n_each):
                collector._decode_segments_slow([b"\x00garbage"])

        threads = [threading.Thread(target=slam) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert collector.bad_payloads == n_threads * n_each

    def test_batch_and_latency_sketches_fill(self):
        reg = obs.Registry()
        store = InMemorySpanStore()
        collector = Collector(store, concurrency=1, registry=reg)
        for i in range(4):
            collector.accept([span(i + 1), span(i + 1, sid=2)])
        collector.flush()
        d = reg.as_dict()
        assert d["zipkin_collector_batch_spans_count"] == 4
        assert d['zipkin_collector_batch_spans{quantile="0.5"}'] == \
            pytest.approx(2.0, rel=0.05)
        assert d["zipkin_collector_write_seconds_count"] == 4

    def test_ingest_self_trace_spans_reach_store(self):
        """self_trace=True records one zipkin-tpu span per ingest step,
        written straight to the store — and never recursively."""
        reg = obs.Registry()
        store = InMemorySpanStore()
        collector = Collector(store, concurrency=1, registry=reg,
                              self_trace=True)
        collector.accept([span(42)])
        collector.flush()
        assert "zipkin-tpu" in store.get_all_service_names()
        assert "collector ingest" in store.get_span_names("zipkin-tpu")
        # Exactly one self span for one processed batch (no feedback).
        self_spans = [
            s for s in store.spans
            if "zipkin-tpu" in s.service_names
        ]
        assert len(self_spans) == 1


class TestSelfTraceRoundTrip:
    def test_api_request_trace_queryable_by_id(self):
        """Acceptance: the self-trace span for an API round trip is
        fetchable through /api/trace/{id} using the echoed trace id."""
        reg = obs.Registry()
        store = InMemorySpanStore()
        collector = Collector(store, concurrency=1, registry=reg)
        api = ApiServer(QueryService(store), collector, registry=reg)
        resp_headers = []
        api.handle("GET", "/api/services", {},
                   response_headers=resp_headers)
        tid_hex = dict(resp_headers)["X-B3-TraceId"]
        collector.flush()
        status, body = api.handle("GET", f"/api/trace/{tid_hex}", {})
        assert status == 200
        assert body[0]["annotations"][0]["endpoint"]["serviceName"] == \
            "zipkin-tpu"


class TestDeviceCounterBlock:
    def _store(self):
        from zipkin_tpu.store.device import StoreConfig
        from zipkin_tpu.store.tpu import TpuSpanStore

        return TpuSpanStore(StoreConfig(
            capacity=1 << 10, ann_capacity=1 << 12,
            bann_capacity=1 << 11, max_services=32, max_span_names=128,
            max_annotation_values=256, max_binary_keys=64,
            cms_width=1 << 10, hll_p=8, quantile_buckets=256,
        ), registry=obs.Registry())

    def test_block_fields_and_memo(self):
        from zipkin_tpu.store import device as dev

        store = self._store()
        store.apply([span(1), span(2)])
        blk = store.counter_block()
        assert set(blk) == set(dev.COUNTER_BLOCK_FIELDS)
        assert blk["spans_seen"] == 2
        assert blk["ring_occupancy"] == 2 and blk["ring_laps"] == 0
        assert blk["batches"] == 1
        # Memoized between ingest steps: same dict object back.
        assert store.counter_block() is blk
        store.apply([span(3)])
        blk2 = store.counter_block()
        assert blk2 is not blk and blk2["spans_seen"] == 3
        # counters() keeps every legacy key + the host guards.
        c = store.counters()
        for key in ("spans_seen", "anns_seen", "banns_seen", "batches",
                    "key_claim_drops", "sweeps", "index_hits",
                    "index_scan_fallbacks", "anns_truncated",
                    "banns_truncated", "ring_occupancy"):
            assert key in c, key

    def test_step_census_memoized(self):
        store = self._store()
        census = store.step_census(n_spans=64, n_anns=128, n_banns=64)
        assert census["scatter"] > 0 and census["sort"] > 0
        assert store.step_census(n_spans=64, n_anns=128,
                                 n_banns=64) is census

    def test_counter_block_lowering_has_no_scatters(self):
        """The telemetry fetch is a pure read: no scatter/sort ops may
        ever lower from it (the zero-extra-passes design claim)."""
        import re

        from zipkin_tpu.store import device as dev

        store = self._store()
        text = dev.counter_block.lower(store.state).as_text()
        for op in ("scatter", "sort"):
            assert not re.findall(rf'"stablehlo\.{op}"', text), op

    def test_ingest_latency_sketch_fills(self):
        reg = obs.Registry()
        from zipkin_tpu.store.device import StoreConfig
        from zipkin_tpu.store.tpu import TpuSpanStore

        store = TpuSpanStore(StoreConfig(
            capacity=1 << 10, ann_capacity=1 << 12,
            bann_capacity=1 << 11, max_services=32, max_span_names=128,
            max_annotation_values=256, max_binary_keys=64,
            cms_width=1 << 10, hll_p=8, quantile_buckets=256,
        ), registry=reg)
        store.apply([span(9)])
        d = reg.as_dict()
        assert d["zipkin_store_ingest_launches_total"] == 1
        assert d["zipkin_store_ingest_step_seconds_count"] == 1


class TestSuspectStore:
    def test_slab_timeout_marks_store_suspect(self, tmp_path,
                                              monkeypatch):
        """ADVICE r5 regression: a slab-save timeout (slow fake device)
        must flag the store so donating ingest and the next save refuse
        to race the orphaned reader; joining the orphan clears it."""
        import jax

        from zipkin_tpu import checkpoint
        from zipkin_tpu.store.base import StoreSuspectError

        store = TestDeviceCounterBlock()._store()
        store.apply([span(1)])
        real_get = jax.device_get
        release = threading.Event()

        def slow_get(x):
            # Only the checkpoint's abandonable fetch threads are
            # daemons here; the main thread's gets pass through.
            if threading.current_thread().daemon and not release.is_set():
                release.wait(30)
            return real_get(x)

        with monkeypatch.context() as m:
            m.setattr(jax, "device_get", slow_get)
            with pytest.raises(TimeoutError):
                checkpoint.save(store, str(tmp_path / "ckpt"),
                                chunk_deadline_s=0.3, slab_retries=0)
        assert store.suspect
        # Donating writes refuse while the orphan may still read state.
        with pytest.raises(StoreSuspectError):
            store.apply([span(2)])
        # The next save refuses too (it would cut a new snapshot over
        # buffers the orphan still reads).
        with pytest.raises(StoreSuspectError):
            checkpoint.save(store, str(tmp_path / "ckpt2"))
        # Un-wedge the fake device; joining the orphan clears the flag.
        release.set()
        store.ensure_writable(wait_s=10.0)
        assert not store.suspect
        store.apply([span(2)])
        assert store.counter_block()["spans_seen"] == 2
        checkpoint.save(store, str(tmp_path / "ckpt3"))
        restored = checkpoint.load(str(tmp_path / "ckpt3"))
        assert restored.counter_block()["spans_seen"] == 2


class TestQueryEngineMetricSplit:
    """The PR 4 ingest observation split applied to reads
    (query/engine.py): zipkin_query_serve_seconds{tier=...} is
    end-to-end including sketch/cache hits, zipkin_query_dispatch_
    seconds isolates actual device launch + D2H — sketch and cache
    answers must never appear in the dispatch sketch."""

    def _engine_app(self):
        from zipkin_tpu.store.device import StoreConfig
        from zipkin_tpu.store.tpu import TpuSpanStore
        from zipkin_tpu.tracegen import generate_traces

        reg = obs.Registry()
        store = TpuSpanStore(StoreConfig(
            capacity=1 << 10, ann_capacity=1 << 12,
            bann_capacity=1 << 11, max_services=32, max_span_names=64,
            max_annotation_values=256, max_binary_keys=64,
            cms_width=1 << 10, hll_p=8, quantile_buckets=256,
        ), registry=reg)
        spans = [s for t in generate_traces(n_traces=12, max_depth=3,
                                            n_services=4) for s in t]
        store.apply(spans)
        service = QueryService(store, coalesce_window_s=0.0,
                               registry=reg)
        api = ApiServer(service, collector=None, registry=reg)
        return store, service, api, reg

    def test_serve_dispatch_split_exposed(self):
        store, service, api, reg = self._engine_app()
        end_ts = 1 << 61
        svc0 = sorted(store.get_all_service_names())[0]
        service.get_service_names()           # sketch tier
        service.get_span_names(svc0)          # sketch tier
        q = [("name", svc0, None, end_ts, 5)]
        service.engine.get_trace_ids_multi(q)  # index tier (dispatch)
        service.engine.get_trace_ids_multi(q)  # cache tier
        status, payload = api.handle("GET", "/metrics", {})
        assert status == 200
        text = payload.body.decode()
        assert "# TYPE zipkin_query_serve_seconds summary" in text
        assert "# TYPE zipkin_query_dispatch_seconds summary" in text
        for tier in ("sketch", "index", "cache"):
            assert (f'zipkin_query_serve_seconds{{tier="{tier}"'
                    in text), (tier, text)
            assert (f'zipkin_query_serve_seconds_count'
                    f'{{tier="{tier}"}}' in text), tier
        assert "zipkin_query_cache_hits_total 1" in text
        assert "zipkin_query_cache_entries 1" in text
        # Coalesce amortization sketches (batch size satellite).
        assert ("# TYPE zipkin_query_coalesce_batch_size summary"
                in text)
        assert "zipkin_query_coalesce_batch_queries_count 1" in text

    def test_sketch_and_cache_hits_never_count_as_dispatch(self):
        store, service, api, reg = self._engine_app()
        eng = service.engine
        svc0 = sorted(store.get_all_service_names())[0]
        q = [("name", svc0, None, 1 << 61, 5)]
        eng.get_trace_ids_multi(q)  # one real dispatch
        d0 = eng.h_dispatch.count
        assert d0 >= 1
        for _ in range(5):
            service.get_service_names()                # sketch
            eng.service_duration_quantiles(svc0, [0.5])  # sketch
            eng.get_trace_ids_multi(q)                 # cache hit
        assert eng.h_dispatch.count == d0  # no new device launches
        serve_sketch = eng.h_serve.labels(tier="sketch").count
        serve_cache = eng.h_serve.labels(tier="cache").count
        assert serve_sketch >= 10 and serve_cache >= 5
        # End-to-end sketch serves stay microsecond-scale (the whole
        # point): p99 well under the device dispatch floor.
        p99 = eng.h_serve.labels(tier="sketch").quantile_values([0.99])
        assert p99[0] < 0.01, p99
