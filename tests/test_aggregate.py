"""Aggregation parity: python oracle vs streaming bank vs device recompute."""

import numpy as np
import pytest

from zipkin_tpu.aggregate import (
    IncrementalAggregator,
    aggregate_spans,
    recompute_dependencies,
)
from zipkin_tpu.models.span import Annotation, Endpoint, Span
from zipkin_tpu.store.device import StoreConfig
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.tracegen import generate_traces

WEB = Endpoint(1, 80, "web")
API = Endpoint(2, 80, "api")
DB = Endpoint(3, 80, "db")

CFG = StoreConfig(
    capacity=1 << 11, ann_capacity=1 << 13, bann_capacity=1 << 12,
    max_services=32, max_span_names=128, max_annotation_values=128,
    max_binary_keys=32, cms_width=1 << 10, hll_p=8, quantile_buckets=256,
)


def rpc(tid, sid, parent, client, server, t0, t1):
    return Span(tid, "op", sid, parent, (
        Annotation(t0, "cs", client),
        Annotation(t0 + 1, "sr", server),
        Annotation(t1 - 1, "ss", server),
        Annotation(t1, "cr", client),
    ))


def split_halves(tid, sid, parent, client, server, t0, t1):
    """The same RPC as two half spans (client-reported + server-reported),
    exercising the merge-before-join step."""
    c = Span(tid, "op", sid, parent,
             (Annotation(t0, "cs", client), Annotation(t1, "cr", client)))
    s = Span(tid, "op", sid, parent,
             (Annotation(t0 + 1, "sr", server), Annotation(t1 - 1, "ss", server)))
    return [c, s]


class TestOracle:
    def test_basic_join(self):
        spans = [
            rpc(1, 1, None, WEB, API, 0, 1000),
            rpc(1, 2, 1, API, DB, 100, 400),
            rpc(1, 3, 1, API, DB, 500, 600),
        ]
        deps = aggregate_spans(spans)
        links = {(l.parent, l.child): l for l in deps.links}
        assert set(links) == {("api", "db")}
        m = links[("api", "db")].duration_moments
        assert m.count == 2
        assert m.mean == pytest.approx((300 + 100) / 2)

    def test_merges_split_halves_before_join(self):
        spans = (
            split_halves(1, 1, None, WEB, API, 0, 1000)
            + split_halves(1, 2, 1, API, DB, 100, 400)
        )
        deps = aggregate_spans(spans)
        links = {(l.parent, l.child) for l in deps.links}
        # Parent's merged service name is server-preferred: "api".
        assert links == {("api", "db")}

    def test_orphan_children_ignored(self):
        deps = aggregate_spans([rpc(1, 2, 99, API, DB, 0, 100)])
        assert deps.links == ()

    def test_time_range(self):
        deps = aggregate_spans([
            rpc(1, 1, None, WEB, API, 1000, 2000),
            rpc(1, 2, 1, API, DB, 1100, 1200),
        ])
        assert deps.start_time == 1100 and deps.end_time == 1200


class TestStreamingVsOracleParity:
    def test_tracegen_parity(self):
        store = TpuSpanStore(CFG)
        all_spans = []
        for spans in generate_traces(n_traces=12, max_depth=5, n_services=5):
            store.apply(spans)
            all_spans.extend(spans)
        want = {
            (l.parent, l.child): l.duration_moments
            for l in aggregate_spans(all_spans).links
        }
        got = {
            (l.parent, l.child): l.duration_moments
            for l in store.get_dependencies().links
        }
        assert set(got) == set(want)
        for k in want:
            assert got[k].count == want[k].count, k
            assert got[k].mean == pytest.approx(want[k].mean, rel=1e-4), k

    def test_device_recompute_matches_streaming_when_in_retention(self):
        store = TpuSpanStore(CFG)
        for spans in generate_traces(n_traces=8, max_depth=4, n_services=4):
            store.apply(spans)
        streaming = {
            (l.parent, l.child): l.duration_moments
            for l in store.get_dependencies().links
        }
        recomputed = {
            (l.parent, l.child): l.duration_moments
            for l in recompute_dependencies(store).links
        }
        assert set(streaming) == set(recomputed)
        for k in streaming:
            assert streaming[k].count == recomputed[k].count


class TestIncrementalAggregator:
    def test_batched_fold_matches_one_shot(self):
        spans = [
            rpc(t, 1, None, WEB, API, t * 1000, t * 1000 + 500)
            for t in range(1, 9)
        ] + [
            rpc(t, 2, 1, API, DB, t * 1000 + 10, t * 1000 + 100)
            for t in range(1, 9)
        ]
        inc = IncrementalAggregator(batch_size=3)
        inc.offer(spans)
        one = aggregate_spans(spans)
        got = {(l.parent, l.child): l.duration_moments for l in inc.result().links}
        want = {(l.parent, l.child): l.duration_moments for l in one.links}
        assert set(got) == set(want)
        for k in want:
            assert got[k].count == want[k].count
            assert got[k].mean == pytest.approx(want[k].mean)

    def test_resume_skips_already_aggregated(self):
        inc = IncrementalAggregator(resume_ts=5000)
        inc.offer([
            rpc(1, 1, None, WEB, API, 1000, 2000),  # before watermark
            rpc(1, 2, 1, API, DB, 1100, 1200),
        ])
        assert inc.result().links == ()

    def test_resume_from_watermark(self):
        inc = IncrementalAggregator()
        assert inc.resume_from() is None
        inc.offer([
            rpc(1, 1, None, WEB, API, 1000, 2000),
            rpc(1, 2, 1, API, DB, 1100, 1200),
        ])
        assert inc.resume_from() == 1200
