"""SqliteSpanStore: the same conformance suite as every other backend
(SpanStoreValidator reuse pattern) + the SQL dependency aggregator."""

import pytest

from zipkin_tpu.models.span import Annotation, Endpoint, Span
from zipkin_tpu.store.sql import SqliteSpanStore
from zipkin_tpu.testing.conformance import (
    conformance_test_names,
    run_conformance_test,
)

WEB = Endpoint(1, 80, "web")
API = Endpoint(2, 80, "api")
DB = Endpoint(3, 80, "db")


@pytest.mark.parametrize("name", conformance_test_names())
def test_sqlite_store_conformance(name):
    run_conformance_test(name, SqliteSpanStore)


def rpc(tid, sid, parent, client, server, t0, t1):
    return Span(tid, "op", sid, parent, (
        Annotation(t0, "cs", client),
        Annotation(t0 + 1, "sr", server),
        Annotation(t1 - 1, "ss", server),
        Annotation(t1, "cr", client),
    ))


class TestSqlAggregator:
    def test_join_and_moments(self):
        store = SqliteSpanStore()
        store.apply([
            rpc(1, 1, None, WEB, API, 0, 1000),
            rpc(1, 2, 1, API, DB, 100, 400),
            rpc(2, 1, None, WEB, API, 5000, 6000),
            rpc(2, 2, 1, API, DB, 5100, 5200),
        ])
        deps = store.aggregate_dependencies()
        links = {(l.parent, l.child): l for l in deps.links}
        assert set(links) == {("api", "db")}
        m = links[("api", "db")].duration_moments
        assert m.count == 2
        assert m.mean == pytest.approx((300 + 100) / 2)

    def test_incremental_resume(self):
        store = SqliteSpanStore()
        store.apply([
            rpc(1, 1, None, WEB, API, 0, 1000),
            rpc(1, 2, 1, API, DB, 100, 400),
        ])
        first = store.aggregate_dependencies()
        assert sum(l.duration_moments.count for l in first.links) == 1
        # Re-running without new data must not double-count.
        again = store.aggregate_dependencies()
        assert sum(l.duration_moments.count for l in again.links) == 1
        # New spans after the watermark are picked up.
        store.apply([
            rpc(9, 1, None, WEB, API, 10_000, 11_000),
            rpc(9, 2, 1, API, DB, 10_100, 10_500),
        ])
        third = store.aggregate_dependencies()
        assert sum(l.duration_moments.count for l in third.links) == 2

    def test_empty(self):
        store = SqliteSpanStore()
        assert store.get_dependencies().links == ()

    def test_file_backed(self, tmp_path):
        path = str(tmp_path / "spans.db")
        store = SqliteSpanStore(path)
        store.apply([rpc(1, 1, None, WEB, API, 0, 100)])
        store.close()
        reopened = SqliteSpanStore(path)
        assert reopened.traces_exist([1]) == {1}
        assert reopened.get_all_service_names() == {"web", "api"}
