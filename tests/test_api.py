"""HTTP API tests: route parity, query extraction, ingest doors, pinning.

ApiServer.handle is driven directly (no sockets) except one end-to-end
socket test over the real ThreadingHTTPServer.
"""

import json

import pytest

from zipkin_tpu.api import ApiServer, extract_query, make_server
from zipkin_tpu.api.server import serve_forever_in_thread
from zipkin_tpu.ingest.collector import Collector
from zipkin_tpu.ingest.receiver import span_to_json
from zipkin_tpu.models.span import Annotation, BinaryAnnotation, Endpoint, Span
from zipkin_tpu.query.request import Order
from zipkin_tpu.query.service import QueryService
from zipkin_tpu.store.memory import InMemorySpanStore
from zipkin_tpu.wire.thrift import span_to_scribe_message

WEB = Endpoint(0x01010101, 80, "web")
API = Endpoint(0x02020202, 80, "api")


def rpc(tid, sid, parent, cs, cr, name="call"):
    return Span(tid, name, sid, parent, (
        Annotation(cs, "cs", WEB),
        Annotation(cs + 1, "sr", API),
        Annotation(cr - 1, "ss", API),
        Annotation(cr, "cr", WEB),
        Annotation(cs + 5, "hot", API),
    ), (BinaryAnnotation("k", b"v", host=API),))


@pytest.fixture
def app():
    store = InMemorySpanStore()
    collector = Collector(store)
    api = ApiServer(QueryService(store), collector)
    store.apply([rpc(1, 10, None, 100, 200)])
    store.apply([rpc(2, 10, None, 1100, 1300, name="other")])
    return api


class TestQueryExtractor:
    def test_basic(self):
        qr = extract_query({"serviceName": "api", "limit": "5"})
        assert qr.service_name == "api" and qr.limit == 5
        assert qr.span_name is None and qr.order is Order.NONE

    def test_span_name_all_is_none(self):
        assert extract_query({"serviceName": "a", "spanName": "all"}).span_name is None
        assert extract_query({"serviceName": "a", "spanName": "x"}).span_name == "x"

    def test_annotation_query_language(self):
        qr = extract_query({
            "serviceName": "a",
            "annotationQuery": "error and http.code=500 and retry",
        })
        assert set(qr.annotations) == {"error", "retry"}
        assert [(b.key, b.value) for b in qr.binary_annotations] == [
            ("http.code", b"500")
        ]

    def test_no_service_is_none(self):
        assert extract_query({}) is None


class TestRoutes:
    def test_services(self, app):
        status, body = app.handle("GET", "/api/services", {})
        assert status == 200 and body == ["api", "web"]

    def test_spans(self, app):
        status, body = app.handle("GET", "/api/spans", {"serviceName": "api"})
        assert status == 200 and body == ["call", "other"]

    def test_spans_requires_service(self, app):
        status, _ = app.handle("GET", "/api/spans", {})
        assert status == 400

    def test_query(self, app):
        status, body = app.handle(
            "GET", "/api/query",
            {"serviceName": "api", "timestamp": str(10**18)},
        )
        assert status == 200
        assert set(body["traceIds"]) == {"1", "2"}
        assert len(body["summaries"]) == 2

    def test_trace_fetch(self, app):
        status, body = app.handle("GET", "/api/trace/1", {})
        assert status == 200
        assert body[0]["traceId"] == "1"
        status2, body2 = app.handle("GET", "/api/get/1", {})
        assert status2 == 200 and body2 == body

    def test_trace_missing_404(self, app):
        status, _ = app.handle("GET", "/api/trace/999", {})
        assert status == 404

    def test_quantiles_route(self, app):
        # params arrive as SCALAR strings (dict(parse_qsl(...)) in the
        # HTTP layer), not lists.
        status, body = app.handle(
            "GET", "/api/quantiles",
            {"serviceName": "api", "q": "0.5,0.99"},
        )
        assert status == 200
        assert body["quantiles"] == [0.5, 0.99]
        # The fixture store may or may not expose the histogram; the
        # contract is the shape: None or one duration per quantile.
        vals = body["durationsMicro"]
        assert vals is None or (
            len(vals) == 2 and all(v >= 0 for v in vals))

    def test_quantiles_requires_service(self, app):
        status, _ = app.handle("GET", "/api/quantiles", {})
        assert status == 400

    def test_dependencies_shape(self, app):
        status, body = app.handle("GET", "/api/dependencies", {})
        assert status == 200 and "links" in body

    def test_pin_cycle(self, app):
        status, body = app.handle("POST", "/api/pin/1/true", {})
        assert status == 200 and body["pinned"] is True
        _, q = app.handle("GET", "/api/is_pinned/1", {})
        assert q["pinned"] is True
        app.handle("POST", "/api/pin/1/false", {})
        _, q2 = app.handle("GET", "/api/is_pinned/1", {})
        assert q2["pinned"] is False

    def test_health_and_metrics(self, app):
        assert app.handle("GET", "/health", {})[0] == 200
        # Default form is Prometheus text exposition ...
        status, payload = app.handle("GET", "/metrics", {})
        from zipkin_tpu.api.server import RawResponse

        assert status == 200 and isinstance(payload, RawResponse)
        assert payload.content_type.startswith("text/plain")
        assert b"zipkin_queue_depth" in payload.body
        # ... the legacy JSON dict stayed at ?format=json.
        status, metrics = app.handle("GET", "/metrics",
                                     {"format": "json"})
        assert status == 200 and "collector.queue_size" in metrics
        assert "store.spans_stored" in metrics

    def test_unknown_404(self, app):
        assert app.handle("GET", "/api/nope", {})[0] == 404


class TestIngestDoors:
    def test_json_ingest(self, app):
        span = rpc(77, 1, None, 50, 60)
        body = json.dumps([span_to_json(span)]).encode()
        status, resp = app.handle("POST", "/api/spans", {}, body)
        assert status == 202
        app.collector.flush()
        status, got = app.handle("GET", "/api/trace/4d", {})
        assert status == 200 and got[0]["traceId"] == "4d"

    def test_scribe_ingest(self, app):
        span = rpc(88, 1, None, 50, 60)
        body = json.dumps([
            {"category": "zipkin", "message": span_to_scribe_message(span)}
        ]).encode()
        status, resp = app.handle("POST", "/scribe", {}, body)
        assert status == 200 and resp["result"] == "OK"
        app.collector.flush()
        assert app.handle("GET", "/api/trace/58", {})[0] == 200


class TestSocketEndToEnd:
    def test_real_http_roundtrip(self, app):
        import urllib.request

        server = make_server(app, host="127.0.0.1", port=0)
        port = server.server_address[1]
        serve_forever_in_thread(server)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/services", timeout=5
            ) as r:
                assert json.loads(r.read()) == ["api", "web"]
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/spans",
                data=json.dumps([span_to_json(rpc(5, 1, None, 1, 2))]).encode(),
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=5) as r:
                assert r.status == 202
        finally:
            server.shutdown()


class TestStaticUi:
    def test_index_served_at_page_routes(self, app):
        from zipkin_tpu.api.server import RawResponse

        for path in ("/", "/index.html", "/traces", "/aggregate"):
            status, payload = app.handle("GET", path, {})
            assert status == 200
            assert isinstance(payload, RawResponse)
            assert payload.content_type.startswith("text/html")
            body = payload.body.decode("utf-8")
            # The SPA drives the real API routes.
            for needle in ("/api/query", "/api/trace/", "/api/dependencies",
                           "renderTrace", "renderDeps"):
                assert needle in body


class TestSelfTracing:
    def _app(self):
        from zipkin_tpu.ingest.collector import Collector
        from zipkin_tpu.store.memory import InMemorySpanStore

        store = InMemorySpanStore()
        collector = Collector(store, concurrency=1)
        api = ApiServer(QueryService(store), collector)
        return store, collector, api

    def test_query_requests_produce_self_traces(self):
        store, collector, api = self._app()
        status, _ = api.handle("GET", "/api/services", {})
        assert status == 200
        collector.flush()
        assert "zipkin-tpu" in store.get_all_service_names()
        names = store.get_span_names("zipkin-tpu")
        assert "get /api/services" in names
        # The self-trace is queryable through the API itself.
        status, body = api.handle(
            "GET", "/api/query", {"serviceName": "zipkin-tpu"})
        collector.flush()
        assert status == 200 and body["traceIds"]

    def test_b3_continuation(self):
        """An inbound B3 context is joined as a CHILD (r17): the
        server span lives in the caller's trace, parented under the
        caller's span id, with a FRESH span id of its own (the probe's
        request and the API's server span stay distinct spans —
        client.Tracer.resolve(child=True))."""
        store, collector, api = self._app()
        api.handle("GET", "/api/services", {},
                   headers={"X-B3-TraceId": "abcd1234",
                            "X-B3-SpanId": "1111",
                            "X-B3-ParentSpanId": "2222"})
        collector.flush()
        spans = store.get_spans_by_trace_id(0xABCD1234)
        assert spans
        assert spans[0].parent_id == 0x1111
        assert spans[0].id not in (0x1111, 0x2222)

    def test_response_echoes_trace_id(self):
        """Self-traced API responses echo X-B3-TraceId/-SpanId with
        exactly the ids the recorded span carries — the devtools
        extension's contract (web/extension/). Under child-join the
        echoed span id is the server span's OWN (fresh) id, not the
        caller's."""
        store, collector, api = self._app()
        resp_headers: list = []
        api.handle("GET", "/api/services", {},
                   headers={"X-B3-TraceId": "beef", "X-B3-SpanId": "77"},
                   response_headers=resp_headers)
        hdr = dict(resp_headers)
        assert hdr["X-B3-TraceId"] == "beef"
        assert hdr["X-B3-SpanId"] != "77"
        collector.flush()
        spans = store.get_spans_by_trace_id(0xBEEF)
        assert spans and spans[0].id == int(hdr["X-B3-SpanId"], 16)
        assert spans[0].parent_id == 0x77
        # Fresh trace: the echoed id is queryable afterwards.
        resp_headers = []
        api.handle("GET", "/api/services", {},
                   response_headers=resp_headers)
        tid = int(dict(resp_headers)["X-B3-TraceId"], 16)
        collector.flush()
        assert store.get_spans_by_trace_id(tid)
        # Ingest doors stay untraced AND unheadered.
        resp_headers = []
        api.handle("POST", "/api/spans", {}, b"[]",
                   response_headers=resp_headers)
        assert not dict(resp_headers).get("X-B3-TraceId")

    def test_ingest_doors_not_traced(self):
        store, collector, api = self._app()
        api.handle("POST", "/api/spans", {}, b"[]")
        api.handle("GET", "/health", {})
        collector.flush()
        assert "zipkin-tpu" not in store.get_all_service_names()


def test_negative_trace_id_roundtrip_through_hex_api():
    """A trace id with the top bit set must survive query -> hex id ->
    trace fetch / pin on an exact-compare store (regression: unsigned
    parse left pins writing a ghost key)."""
    from zipkin_tpu.ingest.collector import Collector
    from zipkin_tpu.store.memory import InMemorySpanStore
    from zipkin_tpu.models.span import Annotation, Endpoint, Span

    store = InMemorySpanStore()
    api = ApiServer(QueryService(store), Collector(store, concurrency=1),
                    self_trace=False)
    ep = Endpoint(1, 80, "neg")
    store.apply([Span(-123, "op", 1, None,
                      (Annotation(5, "sr", ep), Annotation(9, "ss", ep)), ())])
    status, body = api.handle("GET", "/api/query", {"serviceName": "neg"})
    assert status == 200 and body["traceIds"] == ["ffffffffffffff85"]
    status, spans = api.handle("GET", "/api/trace/ffffffffffffff85", {})
    assert status == 200 and spans[0]["traceId"] == "ffffffffffffff85"
    status, _ = api.handle("POST", "/api/pin/ffffffffffffff85/true", {})
    assert status == 200
    assert store.get_time_to_live(-123) > 1.0


class TestTimelineComboRoutes:
    def test_timeline_route(self, app):
        status, body = app.handle("GET", "/api/timeline/1", {})
        assert status == 200
        assert body["traceId"] == "1"
        assert body["annotations"]
        assert body["annotations"] == sorted(
            body["annotations"], key=lambda a: a["timestamp"])
        assert {"serviceName", "spanName", "spanId"} <= set(
            body["annotations"][0])

    def test_combo_route(self, app):
        status, body = app.handle("GET", "/api/combo/1", {})
        assert status == 200
        assert body["trace"] and body["summary"]["traceId"] == "1"
        assert body["timeline"]["annotations"]
        assert body["spanDepths"]

    def test_missing_trace_404(self, app):
        assert app.handle("GET", "/api/timeline/dead", {})[0] == 404
        assert app.handle("GET", "/api/combo/dead", {})[0] == 404

    def test_timeline_includes_binary_annotations(self, app):
        status, body = app.handle("GET", "/api/timeline/1", {})
        assert status == 200
        assert body["binaryAnnotations"]
        assert body["binaryAnnotations"][0]["key"]


def _strict_json_roundtrip(payload):
    """Round-trip a handler payload through a STRICT JSON parser:
    json.dumps happily emits the bare tokens Infinity/-Infinity/NaN
    (python floats), which json.loads ALSO accepts by default — but no
    browser's JSON.parse does. parse_constant firing means the route
    shipped invalid JSON (the /api/dependencies Infinity bug)."""

    def boom(name):
        raise AssertionError(f"route emitted non-JSON constant {name!r}")

    return json.loads(json.dumps(payload), parse_constant=boom)


class TestStrictJsonEveryRoute:
    """Every API route's body must parse under a strict JSON reader —
    on an EMPTY store (monoid zeros: the Dependencies Time.Top/Bottom
    infinities, NaN quantiles) and on a seeded one."""

    # (method, path, params, body) — every JSON route the server maps.
    ROUTES = [
        ("GET", "/health", {}, b""),
        ("GET", "/metrics", {"format": "json"}, b""),
        ("GET", "/api/services", {}, b""),
        ("GET", "/api/spans", {"serviceName": "api"}, b""),
        ("GET", "/api/top_annotations", {"serviceName": "api"}, b""),
        ("GET", "/api/top_kv_annotations", {"serviceName": "api"}, b""),
        ("GET", "/api/quantiles", {"serviceName": "api"}, b""),
        ("GET", "/api/dependencies", {}, b""),
        ("GET", "/api/dependencies/0/100", {}, b""),
        ("GET", "/api/traces_exist", {"traceIds": "1,2,deadbeef"}, b""),
        ("GET", "/api/query", {"serviceName": "api"}, b""),
        ("GET", "/api/trace/1", {}, b""),
        ("GET", "/api/timeline/1", {}, b""),
        ("GET", "/api/combo/1", {}, b""),
        ("GET", "/api/is_pinned/1", {}, b""),
        ("GET", "/vars/sampleRate", {}, b""),
        ("POST", "/vars/sampleRate", {}, b"1.0"),
        ("POST", "/api/pin/1/true", {}, b""),
        ("POST", "/api/pin/1/false", {}, b""),
        ("POST", "/api/spans", {}, b"[]"),
        ("POST", "/scribe", {}, b"[]"),
    ]

    def _drive(self, api):
        from zipkin_tpu.api.server import RawResponse

        for method, path, params, body in self.ROUTES:
            status, payload = api.handle(method, path, params, body)
            assert not isinstance(payload, RawResponse), path
            _strict_json_roundtrip(payload)  # raises on Infinity/NaN

    def test_empty_store_strict_json(self):
        store = InMemorySpanStore()
        api = ApiServer(QueryService(store), Collector(store),
                        self_trace=False)
        self._drive(api)

    def test_seeded_store_strict_json(self, app):
        self._drive(app)

    def test_empty_dependencies_infinity_regression(self):
        """The Dependencies monoid zero is (+inf, -inf); the route must
        serialize that as null, never the invalid bare Infinity."""
        store = InMemorySpanStore()
        api = ApiServer(QueryService(store), self_trace=False)
        status, body = api.handle("GET", "/api/dependencies", {})
        assert status == 200
        assert body["startTime"] is None and body["endTime"] is None
        _strict_json_roundtrip(body)


class TestThriftSliceRoutes:
    """The three remaining ZipkinQuery thrift methods over HTTP —
    getSpanDurations, getServiceNamesToTraceIds, getDataTimeToLive
    (zipkinQuery.thrift) — per backend (memory / sql / tpu): the query
    layer is store-agnostic, so every backend must answer identically
    for the same data."""

    def _seed(self, store):
        store.apply([rpc(1, 10, None, 100, 200)])
        store.apply([rpc(2, 11, None, 1100, 1300)])
        store.apply([rpc(3, 12, None, 2100, 2500, name="other")])
        return ApiServer(QueryService(store), self_trace=False)

    def _check(self, api):
        status, body = api.handle(
            "GET", "/api/span_durations",
            {"serviceName": "web", "spanName": "call"})
        assert status == 200
        # rpc() spans are owned by the server side ("api"); traces 1
        # and 2 carry name "call" with durations 100 and 200 µs. The
        # index ranks traces by timestamp, so compare unordered.
        assert set(body["durations"]) == {"api"}
        assert sorted(body["durations"]["api"]) == [100, 200]

        status, body = api.handle(
            "GET", "/api/service_names_to_trace_ids",
            {"serviceName": "web", "spanName": "call"})
        assert status == 200
        got = {k: sorted(v) for k, v in body["serviceNames"].items()}
        assert got == {"api": ["1", "2"], "web": ["1", "2"]}

        # timeStamp restricts the slice like any end_ts.
        status, body = api.handle(
            "GET", "/api/span_durations",
            {"serviceName": "web", "spanName": "call",
             "timeStamp": "500"})
        assert status == 200 and body == {"durations":
                                          {"api": [100]}}

        status, body = api.handle("GET", "/api/data_ttl", {})
        assert status == 200
        from zipkin_tpu.store.base import DEFAULT_SPAN_TTL_S

        assert body == {"dataTimeToLive": DEFAULT_SPAN_TTL_S}

        # Missing params are 400s, not stack traces.
        assert api.handle("GET", "/api/span_durations", {})[0] == 400
        assert api.handle(
            "GET", "/api/span_durations", {"serviceName": "web"}
        )[0] == 400
        assert api.handle(
            "GET", "/api/service_names_to_trace_ids", {})[0] == 400

    def test_memory_store(self):
        self._check(self._seed(InMemorySpanStore()))

    def test_sql_store(self):
        from zipkin_tpu.store.sql import SqliteSpanStore

        store = SqliteSpanStore()
        self._check(self._seed(store))
        store.close()

    def test_tpu_store(self):
        from zipkin_tpu.store.device import StoreConfig
        from zipkin_tpu.store.tpu import TpuSpanStore

        store = TpuSpanStore(StoreConfig(
            capacity=256, ann_capacity=1024, bann_capacity=512,
            max_services=16, max_span_names=32,
            max_annotation_values=64, max_binary_keys=16,
            cms_width=256, hll_p=6, quantile_buckets=128,
        ))
        self._check(self._seed(store))

    def test_query_client_methods(self, app):
        """QueryClient wrappers against the real HTTP server."""
        from zipkin_tpu.client import QueryClient

        server = make_server(app, host="127.0.0.1", port=0)
        serve_forever_in_thread(server)
        try:
            base = f"http://127.0.0.1:{server.server_address[1]}"
            qc = QueryClient(base)
            durs = qc.span_durations("web", "call")
            assert durs == {"api": [100]}
            names = qc.service_names_to_trace_ids("web", "call")
            assert names == {"api": ["1"], "web": ["1"]}
            from zipkin_tpu.store.base import DEFAULT_SPAN_TTL_S

            assert qc.data_ttl() == DEFAULT_SPAN_TTL_S
        finally:
            server.shutdown()


class TestTracesExistRoute:
    """tracesExist (zipkinQuery.thrift:154) over HTTP, per backend."""

    def test_memory_store(self, app):
        status, body = app.handle(
            "GET", "/api/traces_exist", {"traceIds": "1,2,deadbeef"})
        assert status == 200
        assert body == {"exist": ["1", "2"]}

    def test_requires_ids(self, app):
        assert app.handle("GET", "/api/traces_exist", {})[0] == 400

    def test_negative_id_hex_form(self):
        store = InMemorySpanStore()
        api = ApiServer(QueryService(store), self_trace=False)
        ep = Endpoint(1, 80, "neg")
        store.apply([Span(-123, "op", 1, None,
                          (Annotation(5, "sr", ep),), ())])
        status, body = api.handle(
            "GET", "/api/traces_exist",
            {"traceIds": "ffffffffffffff85,42"})
        assert status == 200 and body == {"exist": ["ffffffffffffff85"]}

    def test_sql_store(self, tmp_path):
        from zipkin_tpu.store.sql import SqliteSpanStore

        store = SqliteSpanStore()
        api = ApiServer(QueryService(store), self_trace=False)
        store.apply([rpc(7, 10, None, 100, 200)])
        status, body = api.handle(
            "GET", "/api/traces_exist", {"traceIds": "7,8"})
        assert status == 200 and body == {"exist": ["7"]}
        store.close()

    def test_tpu_store(self):
        from zipkin_tpu.store.device import StoreConfig
        from zipkin_tpu.store.tpu import TpuSpanStore

        store = TpuSpanStore(StoreConfig(
            capacity=256, ann_capacity=1024, bann_capacity=512,
            max_services=16, max_span_names=32,
            max_annotation_values=64, max_binary_keys=16,
            cms_width=256, hll_p=6, quantile_buckets=128,
        ))
        api = ApiServer(QueryService(store), self_trace=False)
        store.apply([rpc(9, 10, None, 100, 200)])
        status, body = api.handle(
            "GET", "/api/traces_exist", {"traceIds": "9,a"})
        assert status == 200 and body == {"exist": ["9"]}
