"""Tracegen: object generator parity shape + columnar generator integrity
+ the end-to-end write/query-back smoke (tracegen/Main.scala:48-117)."""

import numpy as np
import pytest

from zipkin_tpu.columnar.dictionary import DictionarySet
from zipkin_tpu.models.constants import CORE_ANNOTATIONS
from zipkin_tpu.models.trace import Trace
from zipkin_tpu.store.memory import InMemorySpanStore
from zipkin_tpu.tracegen import ColumnarTraceGen, generate_traces


class TestObjectGenerator:
    def test_shape(self):
        traces = generate_traces(n_traces=5, max_depth=7)
        assert len(traces) == 5
        for spans in traces:
            assert len(spans) >= 1
            tids = {s.trace_id for s in spans}
            assert len(tids) == 1
            root = [s for s in spans if s.parent_id is None]
            assert len(root) == 1
            for s in spans:
                values = [a.value for a in s.annotations]
                assert set(values) & CORE_ANNOTATIONS == {"cs", "sr", "ss", "cr"}
                assert s.binary_annotations
                assert s.duration is not None and s.duration > 0

    def test_tree_depth_bounded(self):
        traces = generate_traces(n_traces=10, max_depth=3)
        for spans in traces:
            t = Trace(spans)
            tree = t.get_span_tree(t.get_root_span())
            assert max(tree.depths(1).values()) <= 3

    def test_deterministic_by_seed(self):
        a = generate_traces(3, rng=np.random.default_rng(7))
        b = generate_traces(3, rng=np.random.default_rng(7))
        assert a == b

    def test_end_to_end_smoke_queryback(self):
        """Write through a store and read back via every SPI query."""
        store = InMemorySpanStore()
        traces = generate_traces(n_traces=5)
        for spans in traces:
            store.apply(spans)
        services = store.get_all_service_names()
        assert services
        svc = sorted(services)[0]
        assert store.get_span_names(svc)
        end_ts = 10**18
        ids = store.get_trace_ids_by_name(svc, None, end_ts, 10)
        assert ids
        got = store.get_spans_by_trace_ids([i.trace_id for i in ids])
        assert got
        durations = store.get_traces_duration([i.trace_id for i in ids])
        assert all(d.duration >= 0 for d in durations)


class TestColumnarGenerator:
    def make(self, spt=7):
        return ColumnarTraceGen(DictionarySet(), n_services=16,
                                n_span_names=32, spans_per_trace=spt)

    def test_batch_shape_and_tree(self):
        gen = self.make()
        batch, name_lc, indexable = gen.next_batch(10)
        assert batch.n_spans == 70
        assert batch.n_annotations == 140
        assert batch.n_binary == 70
        # Heap tree: every non-root's parent is in the same trace.
        for t in range(10):
            rows = slice(t * 7, (t + 1) * 7)
            tid = set(batch.trace_id[rows].tolist())
            assert len(tid) == 1
            sids = set(batch.span_id[rows].tolist())
            parents = batch.parent_id[rows][1:]  # non-roots
            assert set(parents.tolist()) <= sids

    def test_unique_trace_ids_across_batches(self):
        gen = self.make()
        b1, _, _ = gen.next_batch(50)
        b2, _, _ = gen.next_batch(50)
        ids = np.concatenate([b1.trace_id, b2.trace_id])
        assert len(np.unique(ids)) == 100

    def test_timestamps_consistent(self):
        gen = self.make()
        batch, _, _ = gen.next_batch(20)
        assert (batch.ts_first <= batch.ts_last).all()
        assert (batch.duration == batch.ts_last - batch.ts_first).all()
        assert (batch.ts_cs == batch.ts_first).all()
        assert (batch.ts_cr == batch.ts_last).all()

    def test_feeds_tpu_store(self):
        from zipkin_tpu.columnar.encode import SpanCodec
        from zipkin_tpu.store.device import StoreConfig
        from zipkin_tpu.store.tpu import TpuSpanStore

        cfg = StoreConfig(
            capacity=1 << 10, ann_capacity=1 << 11, bann_capacity=1 << 10,
            max_services=32, max_span_names=64, max_annotation_values=64,
            max_binary_keys=16, cms_width=1 << 10, hll_p=8,
            quantile_buckets=256,
        )
        store = TpuSpanStore(cfg)
        gen = ColumnarTraceGen(store.dicts, n_services=8, n_span_names=16)
        batch, name_lc, indexable = gen.next_batch(32)
        store.write_batch(batch, indexable)
        assert store.counters()["spans_seen"] == 32 * 7
        # Dep links exist (heap tree has parent-child pairs).
        deps = store.get_dependencies()
        total = sum(l.duration_moments.count for l in deps.links)
        assert total == 32 * 6  # every non-root joins its parent
        # Service catalog populated via annotation rows.
        assert store.get_all_service_names() <= {f"svc-{i:04d}" for i in range(8)}
        assert store.get_all_service_names()
