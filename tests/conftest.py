"""Test env: force an 8-device virtual CPU mesh before jax is used.

Mirrors the reference's approach of testing multi-node behavior without a
cluster (FakeCassandra / minicluster, SURVEY.md §4): we test multi-chip
sharding on a host-simulated device mesh.

The environment may pre-register an accelerator backend (and pre-set
JAX_PLATFORMS) via sitecustomize, so setting env vars is not enough —
we also flip the config explicitly before any backend initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
