"""Test env: force an 8-device virtual CPU mesh before jax is imported.

Mirrors the reference's approach of testing multi-node behavior without a
cluster (FakeCassandra / minicluster, SURVEY.md §4): we test multi-chip
sharding on a host-simulated device mesh.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
