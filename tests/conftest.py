"""Test env: force an 8-device virtual CPU mesh before jax is used.

Mirrors the reference's approach of testing multi-node behavior without a
cluster (FakeCassandra / minicluster, SURVEY.md §4): we test multi-chip
sharding on a host-simulated device mesh.

The environment may pre-register an accelerator backend (and pre-set
JAX_PLATFORMS) via sitecustomize, so setting env vars is not enough —
we also flip the config explicitly before any backend initializes.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _force_cpu_mesh_env  # noqa: E402

_force_cpu_mesh_env(8, os.environ)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# Fast lane / slow lane (VERDICT r4 weak #6: the full suite reached
# 33 min on CPU and slow suites rot). Tests measured >= ~8 s (soaks,
# eviction laps, sharded conformance, checkpoint round-trips) are
# marked ``slow`` here by FUNCTION name — one maintainable list instead
# of decorators scattered over ten files. The default lane excludes
# them (pyproject addopts) and runs in ~2.5 min; the full lane is
#     python -m pytest tests/ -m ""
# and stays the bar for index/trust/parallel changes (README).
# ---------------------------------------------------------------------------

_SLOW_TESTS = {
    "test_tracegen_parity",
    "test_save_restore_roundtrip",
    "test_tracegen_main_tpu_roundtrip",
    "test_pinned_traces_survive_checkpoint_restart",
    "test_sharded_checkpoint_roundtrip",
    "test_sharded_checkpoint_wal_tail_recovery",
    "test_sharded_pipelined_ingest_bitwise_matches_serial",
    "test_sharded_legacy_snapshot_migrates",
    "test_dependencies_honor_time_window",
    "test_sharded_dependencies_window",
    "test_moments_numerically_stable_for_large_means",
    "test_chained_ingest_steps_bitwise_matches_sequential",
    "test_same_batches_bitwise_same_state",
    "test_store_chained_writes_bitwise_match_single",
    "test_dictionary_overflow_service_routes_to_scan",
    "test_hot_trace_beyond_bucket_depth_falls_back",
    "test_index_matches_scan_by_service",
    "test_middle_host_poison_self_heals_after_eviction",
    "test_pre_index_snapshot_poisons_trust",
    "test_pre_rev7_snapshot_disables_key_table",
    "test_sparse_key_under_hot_bucket_stays_on_fast_path",
    "test_trace_membership_after_eviction",
    "test_trace_membership_fast_path_matches_scan",
    "test_wrapped_bucket_falls_back_to_scan",
    "test_far_future_timestamps_stay_exact",
    "test_ts_watermark_coarse_boundary_window_stays_exact",
    "test_sharded_dep_links_survive_eviction",
    "test_sharded_dep_moments_match_single_store",
    "test_sharded_dictionary_overflow_service_routes_to_scan",
    "test_sharded_hll_is_union",
    "test_sharded_ingest_totals",
    "test_sharded_multi_query_matches_singular",
    "test_sharded_query_roundtrip",
    "test_sharded_store_conformance",
    "test_summary_dep_compaction_parity",
    "test_no_slices_by_service",
    "test_concurrent_sharded_ingest_and_query",
    "test_cross_batch_links_survive_archive",
    "test_dependency_links_from_streaming_join",
    "test_oversized_batch_rejected_but_apply_chunks",
    "test_single_span_annotation_overflow_truncated",
    "test_sketches_survive_eviction",
    "test_hot_trace_candidate_escalation",
    "test_pinned_trace_survives_ring_eviction",
    "test_sharded_pinned_trace_survives_eviction",
    "test_feeds_tpu_store",
    "test_chunked_save_resumes_after_wedged_transfer",
    "test_stale_staging_discarded_after_writes",
    "test_sweep_between_attempts_discards_staging",
    "test_chunked_save_slabs_large_leaves",
    "test_wedged_slab_fails_fast_with_bounded_lock_hold",
    "test_tiered_checkpoint_roundtrip",
    "test_two_process_distributed_routing",
    # Cold-tier deep coverage beyond the fast-lane acceptance drive
    # (TestTieredConformance stays fast; these re-build tiered stores).
    "test_bytes_roundtrip_bit_exact",
    "test_compression_actually_compresses",
    "test_merge_zone_is_monoidal",
    "test_contiguous_coverage_no_gaps",
    "test_captured_spans_are_complete",
    "test_multi_matches_singular",
    "test_service_and_span_name_catalogs",
    "test_pin_through_tiers_banks_cold_rows",
    "test_capture_now_flushes_resident_window",
    "test_tiered_store_conformance",
    "test_annotation_heavy_chained_writes_stay_complete",
    "test_transient_pull_failure_is_retried_not_skipped",
    "test_query_client_methods",
    # Pipelined-ingest stress lane (tests/test_pipeline.py): the fast
    # lane keeps the bitwise pipelined==serial gate, the zero-recompile
    # gate, lifecycle/error surfacing, and the metric split; these
    # three re-drive tiered stores / sleep on a slow sealer / run a
    # threaded save, which the fast-lane wall budget can't afford.
    "test_pipelined_capture_matches_inline_sealing",
    "test_capture_backpressure_bounds_memory",
    "test_checkpoint_during_pipelined_ingest",
    # Crash-injection matrix (tests/test_crash.py): each case SIGKILLs
    # a real child drive, then recovers + re-drives an oracle. The
    # after-append smoke stays in tier-1; the rest of the kill-point
    # matrix (checkpoint swaps, truncation, cold-tier sealing) is here.
    "test_crash_before_append_loses_only_the_unacked_batch",
    "test_crash_after_commit_before_ack",
    "test_crash_mid_first_checkpoint_recovers_from_wal_alone",
    "test_crash_mid_second_checkpoint_falls_back_to_old",
    "test_crash_mid_truncate_leaves_recoverable_suffix",
    "test_crash_mid_seal_replays_capture_and_cold_tier",
    "test_crash_mid_seal_with_checkpoint",
    "test_clean_child_exits_zero",
    # Windowed-analytics deep sweeps (tests/test_windows.py): tier-1
    # keeps cell exactness, ring-wrap, boundary, solver, resync and
    # API gates; the multi-lap fuzz sweep and the checkpoint
    # round-trip ride the slow lane (bench_smoke's windows phase
    # already smoke-gates mirror bitwise identity every tier-1 run).
    "test_window_ring_wrap_deep_sweep",
    "test_pre_rev14_checkpoint_restores_empty_arena",
    # Replication deep coverage (tests/test_replication.py): tier-1
    # keeps the durable-only ship bound, gap/idempotency, standby
    # promote, and the pre-rev-14 cold-resync compat path, and
    # bench_smoke's replication phase smoke-gates replica bitwise
    # agreement + RTO every tier-1 run; the full agreement sweep, the
    # TCP anchor-bootstrap drive, and the retention soak re-drive
    # multi-thousand-span stores the fast-lane wall budget can't
    # afford (the crash-during-ship matrix is marked slow directly).
    "test_replica_bitwise_agreement_at_fixed_frontier",
    "test_tcp_follow_and_anchor_bootstrap",
    "test_replica_retention_drops_old_segments",
    "test_standby_follow_promote_bitwise",
    # Paged-layout deep coverage (tests/test_paged.py): tier-1 keeps
    # the SPI conformance sweep, the Pallas/XLA bitwise gate, planner
    # geometry guards, the reclaim fuzz, rev-18 + pre-18 checkpoint
    # compat, and WAL-replay bitwise; bench_smoke's paged phase gates
    # census arithmetic, ring-vs-paged bitwise parity and the
    # zero-recompile bound every tier-1 run, so the long skewed-stream
    # parity drive, the tiered eviction/capture drive, the mirror
    # sweep, and the counting-rank census build ride here.
    "test_query_parity_vs_ring_skewed_stream",
    "test_tiered_parity_through_eviction_and_capture",
    "test_mirror_is_layout_independent",
    "test_paged_counters_and_census_budget",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.originalname in _SLOW_TESTS or item.name in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
