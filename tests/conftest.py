"""Test env: force an 8-device virtual CPU mesh before jax is used.

Mirrors the reference's approach of testing multi-node behavior without a
cluster (FakeCassandra / minicluster, SURVEY.md §4): we test multi-chip
sharding on a host-simulated device mesh.

The environment may pre-register an accelerator backend (and pre-set
JAX_PLATFORMS) via sitecustomize, so setting env vars is not enough —
we also flip the config explicitly before any backend initializes.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _force_cpu_mesh_env  # noqa: E402

_force_cpu_mesh_env(8, os.environ)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
