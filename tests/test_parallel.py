"""Sharded ingest on the 8-device virtual CPU mesh: per-shard isolation +
collective global summary correctness (psum/pmax/all_gather-combine)."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zipkin_tpu.models.dependencies import Moments
from zipkin_tpu.ops import hll
from zipkin_tpu.parallel.shard import (
    ShardedSpanStore,
    ShardedStore,
    global_summary,
    stack_batches,
    stacked_incoming,
)
from zipkin_tpu.store import device as dev
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.testing.conformance import (
    conformance_test_names,
    run_conformance_test,
)
from zipkin_tpu.tracegen import ColumnarTraceGen, generate_traces

CFG = dev.StoreConfig(
    capacity=256, ann_capacity=1024, bann_capacity=512,
    max_services=16, max_span_names=32, max_annotation_values=64,
    max_binary_keys=16, cms_width=256, hll_p=8, quantile_buckets=128,
)


@pytest.fixture(scope="module")
def mesh():
    n = min(8, len(jax.devices()))
    return Mesh(np.array(jax.devices()[:n]), axis_names=("shard",))


def _shard_batches(mesh, gen, traces_per_shard=4):
    n = mesh.shape["shard"]
    pad = traces_per_shard * gen.spans_per_trace
    out = []
    for _ in range(n):
        batch, name_lc, indexable = gen.next_batch(traces_per_shard)
        out.append(dev.make_device_batch(
            batch, name_lc, indexable,
            pad_spans=pad, pad_anns=2 * pad, pad_banns=pad,
        ))
    stacked = stack_batches(out)
    return jax.device_put(stacked, NamedSharding(mesh, P("shard")))


def test_sharded_ingest_totals(mesh):
    n = mesh.shape["shard"]
    store = ShardedStore(mesh, CFG)
    helper = TpuSpanStore(CFG)
    gen = ColumnarTraceGen(helper.dicts, n_services=8, n_span_names=16)
    stacked = _shard_batches(mesh, gen)
    summary = store.ingest(stacked, incoming=stacked_incoming(stacked))
    assert float(summary["spans_seen"]) == n * 4 * 7
    # Additive sketches: total span count per service sums across shards.
    assert float(np.asarray(summary["svc_span_counts"]).sum()) == n * 4 * 7


def test_sharded_hll_is_union(mesh):
    n = mesh.shape["shard"]
    store = ShardedStore(mesh, CFG)
    helper = TpuSpanStore(CFG)
    gen = ColumnarTraceGen(helper.dicts, n_services=8, n_span_names=16)
    stacked = _shard_batches(mesh, gen, traces_per_shard=8)
    summary = store.ingest(stacked, incoming=stacked_incoming(stacked))
    est = float(hll.estimate(hll.HyperLogLog(summary["hll_traces"])))
    true = n * 8  # all trace ids distinct across shards
    assert abs(est - true) / true < 0.25

def test_sharded_dep_moments_match_single_store(mesh):
    """Collective-combined moments == one store ingesting everything."""
    n = mesh.shape["shard"]
    sharded = ShardedStore(mesh, CFG)
    single = TpuSpanStore(CFG)
    gen = ColumnarTraceGen(single.dicts, n_services=8, n_span_names=16)

    batches = []
    for _ in range(n):
        batch, name_lc, indexable = gen.next_batch(4)
        batches.append((batch, name_lc, indexable))
    # Single store sees all batches sequentially.
    for batch, name_lc, indexable in batches:
        single.write_batch(batch, indexable)
    # Shards see one each.
    dbs = [
        dev.make_device_batch(b, nl, ix, pad_spans=32, pad_anns=64,
                              pad_banns=32)
        for b, nl, ix in batches
    ]
    stacked = jax.device_put(stack_batches(dbs),
                             NamedSharding(mesh, P("shard")))
    summary = sharded.ingest(stacked, incoming=stacked_incoming(stacked))

    got = np.asarray(summary["dep_moments"], np.float64)
    want = np.asarray(dev.total_dep_moments(single.state), np.float64)
    nz = np.flatnonzero(want[:, 0] > 0)
    assert nz.size > 0
    np.testing.assert_allclose(got[nz, 0], want[nz, 0])  # counts exact
    np.testing.assert_allclose(got[nz, 1], want[nz, 1], rtol=1e-5)  # means
    np.testing.assert_allclose(got[nz, 2], want[nz, 2], rtol=1e-3)


@pytest.mark.parametrize("name", conformance_test_names())
def test_sharded_store_conformance(mesh, name):
    """The 8-shard store passes the same behavioral suite as the
    in-memory reference and the single-device store — the sharded READ
    path (top-k merge, collective durations, cross-shard gather) is
    semantically invisible (SpanStoreValidator.scala:27 reused across
    backends)."""
    run_conformance_test(name, lambda: ShardedSpanStore(mesh, CFG))


def test_sharded_query_roundtrip(mesh):
    """Tracegen traffic in, every read API answers across shards."""
    store = ShardedSpanStore(mesh, CFG)
    traces = generate_traces(n_traces=12, max_depth=3, n_services=6)
    spans = [s for t in traces for s in t]
    store.apply(spans)
    assert store.stored_span_count() == float(len(spans))
    svc = sorted(store.get_all_service_names())[0]
    ids = store.get_trace_ids_by_name(svc, None, 2**62, 10)
    assert ids
    assert len({i.trace_id for i in ids}) == len(ids)
    found = store.get_spans_by_trace_ids([i.trace_id for i in ids[:4]])
    assert found and all(found)
    # Spans of one trace live on exactly one shard (trace-affine routing),
    # and the cross-shard fetch returns them all.
    whole = {s.trace_id: len(t) for t in found for s in t[:1]}
    for tid, n_spans in whole.items():
        assert n_spans == sum(1 for s in spans if s.trace_id == tid)
    deps = store.get_dependencies()
    assert deps.links
    qs = store.service_duration_quantiles(svc, [0.5, 0.99])
    assert qs is not None
    assert store.estimated_unique_traces() > 0


def test_sharded_dep_links_survive_eviction(mesh):
    """Ring wraparound on shards must not lose dependency links: the
    per-shard archive step (make_sharded_archive) folds links of
    soon-to-be-evicted children, so summaries never regress."""
    n = mesh.shape["shard"]
    store = ShardedStore(mesh, CFG)
    helper = TpuSpanStore(CFG)
    gen = ColumnarTraceGen(helper.dicts, n_services=8, n_span_names=16)
    rounds = 25  # 28 spans/shard/round vs capacity 256: wraps ~3x
    last_total = 0.0
    for _ in range(rounds):
        stacked = _shard_batches(mesh, gen)
        summary = store.ingest(stacked,
                               incoming=stacked_incoming(stacked))
        total = float(np.asarray(summary["dep_moments"])[:, 0].sum())
        assert total >= last_total  # link counts never regress
        last_total = total
    expected = n * rounds * 4 * (gen.spans_per_trace - 1)
    assert last_total == expected


def test_summary_dep_compaction_parity(mesh):
    """The per-step dependency summary ships only the top-k live cells
    across the mesh (psum counts → top_k → all_gather k rows) instead
    of the full [S*S, 5] bank; the result must equal the full gather
    bit-for-bit, and the overflow fallback (live cells > k) must stay
    lossless (VERDICT r4 weak #7)."""
    store = ShardedStore(mesh, CFG)
    helper = TpuSpanStore(CFG)
    gen = ColumnarTraceGen(helper.dicts, n_services=8, n_span_names=16)
    stacked = _shard_batches(mesh, gen)
    store.ingest(stacked, incoming=stacked_incoming(stacked))
    full = global_summary(store.states, mesh, dep_k=None)
    want = np.asarray(full["dep_moments"])
    # Branch preconditions, asserted so geometry drift can't silently
    # turn this into full-vs-full: dep_k must sit strictly between the
    # live-cell count (compact branch taken) and the cell count (the
    # Python dep_k >= cells shortcut not taken); the overflow probe
    # needs nz > 1 to take the lax.cond fallback.
    nz = int((want[:, 0] > 0).sum())
    cells = want.shape[0]
    dep_k = 128
    assert 1 < nz <= dep_k < cells, (nz, dep_k, cells)
    compact = global_summary(store.states, mesh, dep_k=dep_k)
    overflow = global_summary(store.states, mesh, dep_k=1)  # nz > k
    assert want[:, 0].sum() > 0
    np.testing.assert_array_equal(
        np.asarray(compact["dep_moments"]), want)
    np.testing.assert_array_equal(
        np.asarray(overflow["dep_moments"]), want)


def test_sharded_multi_query_matches_singular(mesh):
    """ShardedSpanStore.get_trace_ids_multi (one mesh launch for all
    probes) must answer exactly what the singular sharded paths — and a
    same-geometry single-device oracle — answer."""
    store = ShardedSpanStore(mesh, CFG)
    oracle = TpuSpanStore(CFG)
    spans = [s for t in generate_traces(n_traces=24, max_depth=3,
                                        n_services=5) for s in t]
    store.apply(spans)
    oracle.apply(spans)
    end_ts = max(s.last_timestamp for s in spans if s.last_timestamp) + 1
    queries = []
    for svc in sorted(oracle.get_all_service_names()):
        queries.append(("name", svc, None, end_ts, 10))
        queries.append(("annotation", svc, "some custom annotation",
                        None, end_ts, 10))
        queries.append(("annotation", svc, "http.uri", b"/api/widgets",
                        end_ts, 10))
        queries.append(("annotation", svc, "http.uri", None, end_ts, 10))
    queries.append(("name", "no-such-svc", None, end_ts, 10))
    got = store.get_trace_ids_multi(queries)
    assert len(got) == len(queries)

    def ids(r):
        return sorted((i.trace_id, i.timestamp) for i in r)

    nonempty = 0
    for q, res in zip(queries, got):
        if q[0] == "name":
            single = store.get_trace_ids_by_name(*q[1:])
            want = oracle.get_trace_ids_by_name(*q[1:])
        else:
            single = store.get_trace_ids_by_annotation(*q[1:])
            want = oracle.get_trace_ids_by_annotation(*q[1:])
        assert ids(res) == ids(single) == ids(want), q
        nonempty += bool(want)
    assert nonempty > 0


def test_multihost_routing_math(mesh):
    """parallel/multihost: the producer-side partitioner, the store's
    placement hash, and the per-process consume set must agree — the
    invariant that makes every consumed span local-by-construction."""
    from zipkin_tpu.parallel import multihost as mh

    store = ShardedSpanStore(mesh, CFG)
    n = store.n
    spans = [s for t in generate_traces(n_traces=20, max_depth=3,
                                        n_services=4) for s in t]
    # Partitioner == store placement, span-for-span.
    for s in spans:
        assert mh.partition_for_trace(s.trace_id, n) == \
            store._shard_of(s.trace_id)
    # Single-host: this process owns EVERY shard of the global mesh.
    gmesh = mh.global_mesh()
    local = mh.local_shard_ids(gmesh)
    assert local == list(range(len(jax.devices())))
    assert mh.partitions_for_process(gmesh) == local
    # Routing groups: complete partition, trace-affine, and filterable
    # to an owned subset.
    groups = mh.route_spans(spans, n)
    assert sum(len(g) for g in groups.values()) == len(spans)
    for sid, group in groups.items():
        assert all(mh.shard_of(s.trace_id, n) == sid for s in group)
    owned = [0, 1]
    sub = mh.route_spans(spans, n, keep=owned)
    assert set(sub) <= set(owned)
    assert sum(len(g) for g in sub.values()) == \
        sum(len(g) for sid, g in groups.items() if sid in owned)
    # A locally-routed group ingests cleanly and reads back.
    if 0 in groups and groups[0]:
        store.apply(groups[0])
        tid = groups[0][0].trace_id
        assert store.get_spans_by_trace_ids([tid])


def test_sharded_dictionary_overflow_service_routes_to_scan(mesh):
    """Overflow services (dictionary id >= max_services) must scan on
    the sharded store too — the index path would trusted-empty them
    (round-4 review finding: the fix originally landed single-device
    only, while get_trace_ids_multi's fallback funnels overflow queries
    into exactly these sharded singular paths)."""
    from zipkin_tpu.store.device import StoreConfig
    from zipkin_tpu.tracegen import generate_traces

    cfg = StoreConfig(capacity=1 << 10, ann_capacity=1 << 12,
                      bann_capacity=1 << 11, max_services=4,
                      use_index=True)
    scan_cfg = cfg._replace(use_index=False)
    sharded = ShardedSpanStore(mesh, cfg)
    oracle = ShardedSpanStore(mesh, scan_cfg)
    spans = [s for t in generate_traces(n_traces=24, max_depth=3,
                                        n_services=12) for s in t]
    names = set()
    for s in spans:
        for a in s.annotations:
            if a.host and a.host.service_name:
                names.add(a.host.service_name)
    assert len(names) > 4
    for st in (sharded, oracle):
        st.apply(spans)
    end_ts = max(s.last_timestamp for s in spans if s.last_timestamp) + 1

    def ids(res):
        return sorted((i.trace_id, i.timestamp) for i in res)

    for svc in sorted(names):
        assert ids(sharded.get_trace_ids_by_name(svc, None, end_ts, 10)) \
            == ids(oracle.get_trace_ids_by_name(svc, None, end_ts, 10)), svc
        assert ids(sharded.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, end_ts, 10
        )) == ids(oracle.get_trace_ids_by_annotation(
            svc, "some custom annotation", None, end_ts, 10
        )), svc
    # Catalog endpoints (span names, quantiles, top-k) must not clamp
    # overflow ids into the last indexed row (advisor r4) — compare
    # against a sharded store whose capacity covers the vocabulary.
    big = ShardedSpanStore(mesh, cfg._replace(max_services=32))
    big.apply(spans)

    def canon(pairs):  # top-k tie ORDER is not a product guarantee
        return sorted(pairs, key=lambda p: (-p[1], p[0]))

    for svc in sorted(names):
        assert sharded.get_span_names(svc) == big.get_span_names(svc), svc
        assert canon(sharded.top_annotations(svc, 999)) == \
            canon(big.top_annotations(svc, 999)), svc
        assert canon(sharded.top_binary_keys(svc, 999)) == \
            canon(big.top_binary_keys(svc, 999)), svc
        assert sharded.service_duration_quantiles(svc, [0.5, 0.99]) == \
            big.service_duration_quantiles(svc, [0.5, 0.99]), svc
    assert sharded.get_all_service_names() == big.get_all_service_names()


def test_concurrent_catalog_reads_do_not_deadlock(mesh):
    """The r14-noted hazard: N API threads each launching a shard_map
    collective (psum catalogs, HLL pmax) under the SHARED read lock
    interleave their per-device rendezvous on the XLA CPU backend and
    hang forever. ShardedSpanStore serializes collective launches
    behind the dedicated _coll_lock leaf — this drives the exact
    pattern (concurrent catalog + quantile + cardinality reads) and
    gates completion with a hard timeout."""
    import threading

    store = ShardedSpanStore(mesh, CFG)
    spans = [
        s for t in generate_traces(n_traces=10, max_depth=3,
                                   n_services=6) for s in t
    ]
    store.apply(spans)
    # Single-threaded warm-up compiles every kernel the workers hit,
    # so the timeout below bounds rendezvous stalls, not compiles.
    svc = sorted(store.get_all_service_names())[0]
    store.service_duration_quantiles(svc, [0.5, 0.99])
    store.estimated_unique_traces()
    store.get_span_names(svc)
    errors = []

    def worker():
        try:
            for _ in range(3):
                assert store.get_all_service_names()
                assert store.service_duration_quantiles(
                    svc, [0.5, 0.99]) is not None
                assert store.estimated_unique_traces() > 0
                store.get_span_names(svc)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120.0)
    hung = [t for t in threads if t.is_alive()]
    assert not hung, (
        f"{len(hung)} catalog reader(s) deadlocked at the collective "
        f"rendezvous — the _coll_lock serialization regressed")
    assert not errors, errors
