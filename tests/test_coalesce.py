"""Cross-request query coalescing (query/coalesce.py): concurrent API
queries share one device launch, with results identical to serial
execution — correctness on the 8-device CPU mesh (conftest) and a
bitwise batched-vs-unbatched determinism check.
"""

import threading

from zipkin_tpu.query.coalesce import QueryCoalescer
from zipkin_tpu.query.request import QueryRequest
from zipkin_tpu.query.service import QueryService
from zipkin_tpu.store.device import StoreConfig
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.tracegen import generate_traces

SPANS = [s for t in generate_traces(n_traces=30, max_depth=4,
                                    n_services=6) for s in t]
END_TS = max(s.last_timestamp for s in SPANS if s.last_timestamp) + 1


def _store():
    st = TpuSpanStore(StoreConfig(
        capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
        max_services=32, max_span_names=64, max_annotation_values=256,
        max_binary_keys=64, cms_width=1 << 10, hll_p=8,
        quantile_buckets=256,
    ))
    st.apply(SPANS)
    return st


def _ids(res):
    return [(i.trace_id, i.timestamp) for i in res]


def test_concurrent_requests_share_launch_and_match_serial():
    """N threads fire getTraceIds simultaneously; the coalescer must
    batch at least some of them into one get_trace_ids_multi launch,
    and every caller must receive exactly its serial answer."""
    store = _store()
    svc = QueryService(store, coalesce_window_s=0.2)
    svcs = sorted(store.get_all_service_names())
    reqs = [
        QueryRequest(service_name=svcs[i % len(svcs)], end_ts=END_TS,
                     limit=10)
        for i in range(12)
    ]
    want = [
        _ids(store.get_trace_ids_by_name(r.service_name, None, r.end_ts,
                                         r.limit))
        for r in reqs
    ]
    results = [None] * len(reqs)
    errors = []
    barrier = threading.Barrier(len(reqs))

    def call(i):
        try:
            barrier.wait()
            resp = svc.get_trace_ids(reqs[i])
            results[i] = list(resp.trace_ids)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=call, args=(i,))
               for i in range(len(reqs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    for i, r in enumerate(reqs):
        assert results[i] == [tid for tid, _ in want[i]], r.service_name
    # The dispatch-floor claim: fewer launches than callers.
    assert svc.coalescer.queries == len(reqs)
    assert svc.coalescer.launches_saved >= 1
    assert svc.coalescer.batches + svc.coalescer.launches_saved == len(reqs)


def test_batched_and_unbatched_paths_bitwise_identical():
    """The determinism contract: the same query list through ONE
    get_trace_ids_multi launch and through the singular per-query paths
    must produce bitwise-identical (trace id, timestamp) sets."""
    store = _store()
    queries = []
    for s in sorted(store.get_all_service_names()):
        queries.append(("name", s, None, END_TS, 10))
        queries.append(
            ("annotation", s, "some custom annotation", None, END_TS, 10))
        queries.append(
            ("annotation", s, "http.uri", b"/api/widgets", END_TS, 10))
    batched = store.get_trace_ids_multi(queries)
    for q, got in zip(queries, batched):
        if q[0] == "name":
            want = store.get_trace_ids_by_name(*q[1:])
        else:
            want = store.get_trace_ids_by_annotation(*q[1:])
        assert _ids(got) == _ids(want), q
    # And through the coalescer itself (single caller, window 0).
    coal = QueryCoalescer(store, window_s=0.0)
    again = coal.run(queries)
    assert [_ids(r) for r in again] == [_ids(r) for r in batched]


def test_coalescer_propagates_errors_to_every_caller():
    class Boom:
        def get_trace_ids_multi(self, queries):
            raise RuntimeError("device gone")

    coal = QueryCoalescer(Boom(), window_s=0.05)
    errs = []
    barrier = threading.Barrier(3)

    def call():
        try:
            barrier.wait()
            coal.run([("name", "svc", None, 10, 10)])
        except RuntimeError as e:
            errs.append(str(e))

    threads = [threading.Thread(target=call) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert errs == ["device gone"] * 3


def test_multi_slice_request_rides_one_launch_per_round():
    """A multi-slice getTraceIds (span name + annotation) resolves both
    probe and aligned rounds through the batched path, and matches the
    slice-by-slice singular results intersected by hand."""
    store = _store()
    svc = QueryService(store, coalesce_window_s=0.0)
    service = sorted(store.get_all_service_names())[0]
    names = sorted(store.get_span_names(service))
    assert names
    qr = QueryRequest(service_name=service, span_name=names[0],
                      annotations=["some custom annotation"],
                      end_ts=END_TS, limit=10)
    resp = svc.get_trace_ids(qr)
    by_name = {
        i.trace_id
        for i in store.get_trace_ids_by_name(service, names[0], END_TS, 10)
    }
    by_ann = {
        i.trace_id for i in store.get_trace_ids_by_annotation(
            service, "some custom annotation", None, END_TS, 10)
    }
    assert set(resp.trace_ids) <= (by_name & by_ann) or not resp.trace_ids
