"""Paged span layout (r19): conformance, bitwise ring parity, page
reclaim under wrap, Pallas-vs-XLA gather identity, rev-18 checkpoint
and WAL replay determinism.

The layout contract under test (docs/STORAGE_TIERS.md): spans land in
fixed ``page_rows`` device pages claimed from a free list during the
fused ingest step, chained per trace through the host page table
(store/paged.PagePlanner), with gids epoch-encoded so every ring-scan
kernel keeps working unchanged. Everything observable — query answers,
checkpoint state, WAL recovery — must be bitwise indistinguishable
from what the stream's content dictates, never from page placement.
"""

import json
import os

import jax
import numpy as np
import pytest

from zipkin_tpu import checkpoint
from zipkin_tpu.models.span import Annotation, BinaryAnnotation, Endpoint, Span
from zipkin_tpu.store import device as dev
from zipkin_tpu.store.census import expected_census
from zipkin_tpu.store.device import StoreConfig
from zipkin_tpu.store.paged import PagePlanner
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.testing.conformance import (
    conformance_test_names,
    run_conformance_test,
)
from zipkin_tpu.testing.crash import states_bitwise_equal
from zipkin_tpu.wal import WriteAheadLog, recover, replay_into

CFG_RING = StoreConfig(
    capacity=1 << 10, ann_capacity=1 << 12, bann_capacity=1 << 11,
    max_services=32, max_span_names=128, max_annotation_values=256,
    max_binary_keys=64, cms_width=1 << 10, hll_p=8,
    quantile_buckets=512,
)
# 1024 / 128 = 8 pages — the planner's minimum pool, and 128 rows is
# lane-aligned so the Pallas gather path is eligible on TPU.
CFG_PAGED = CFG_RING._replace(layout="paged", page_rows=128)

BASE_TS = 1_700_000_000_000_000


def _spans_for(tid: int, n: int, svc: str = "psvc") -> list:
    """n spans of one trace, unique span ids, two annotations each."""
    ep = Endpoint(10, 80, svc)
    out = []
    for j in range(n):
        t0 = BASE_TS + tid * 1000 + j
        out.append(Span(
            tid, f"op{j % 4}", tid * 100_000 + j + 1, None,
            (Annotation(t0, "sr", ep), Annotation(t0 + 7, "ss", ep)),
            (BinaryAnnotation("k", b"v", host=ep),),
        ))
    return out


def _skewed_stream(seed: int, total: int, max_size: int = 64):
    """Zipf-sized traces (1-span polls to page-filling batch traces)
    interleaved — the shape the paged layout exists for. Returns
    (spans, {tid: n_spans})."""
    rng = np.random.default_rng(seed)
    traces, sizes = [], {}
    tid, count = 1, 0
    while count < total:
        n = min(int(rng.zipf(1.6)), max_size)
        traces.append(_spans_for(tid, n, svc=f"psvc{tid % 3}"))
        sizes[tid] = n
        count += n
        tid += 1
    flat = [s for tr in traces for s in tr]
    return flat, sizes


def _drive(store, spans, batch=200):
    for i in range(0, len(spans), batch):
        store.apply(spans[i:i + batch])


# ---------------------------------------------------------------------------
# Conformance: the paged layout is a SpanStore like any other
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", conformance_test_names())
def test_paged_conformance(name):
    run_conformance_test(name, lambda: TpuSpanStore(CFG_PAGED))


# ---------------------------------------------------------------------------
# Bitwise query parity vs the ring layout
# ---------------------------------------------------------------------------


def test_query_parity_vs_ring_skewed_stream():
    """Whole-trace reads and id lookups answer IDENTICALLY through
    both layouts on a skewed stream (no wrap, so both retain all) —
    page placement must never leak into query results."""
    spans, sizes = _skewed_stream(seed=11, total=600)
    ring = TpuSpanStore(CFG_RING)
    paged = TpuSpanStore(CFG_PAGED)
    _drive(ring, spans)
    _drive(paged, spans)
    # Precondition: neither layout dropped anything. The paged pool
    # fragments (a 64-span trace pins a half-filled exclusive page),
    # so "fits the ring" does not imply "fits the pages" — the stream
    # above is sized to fit BOTH, and this guards the sizing.
    assert paged._planner.stats()["page_reclaims"] == 0

    for tid in sizes:
        assert (ring.get_spans_by_trace_ids([tid])
                == paged.get_spans_by_trace_ids([tid])), tid
    # Batched multi-trace reads through the shared page list too
    # (small traces share pages; the gather must filter co-tenants).
    some = sorted(sizes)[:48]
    assert (ring.get_spans_by_trace_ids(some)
            == paged.get_spans_by_trace_ids(some))

    end_ts = BASE_TS + (len(sizes) + 2) * 1000 + 10_000
    key = lambda x: (x.trace_id, x.timestamp)  # noqa: E731
    for i in range(3):
        assert (sorted(ring.get_trace_ids_by_name(
                    f"psvc{i}", None, end_ts, 200), key=key)
                == sorted(paged.get_trace_ids_by_name(
                    f"psvc{i}", None, end_ts, 200), key=key)), i


def test_tiered_parity_through_eviction_and_capture():
    """Past wrap, reclaimed pages are captured into the cold tier
    BEFORE their rows are overwritten — so a tiered paged store reads
    back every trace COMPLETE, exactly like the tiered ring does, even
    though the two layouts evict in a different order."""
    from zipkin_tpu.store.archive import ArchiveParams, TieredSpanStore

    def tiered(cfg):
        hot = TpuSpanStore(cfg)
        return TieredSpanStore(hot, params=ArchiveParams.for_config(
            hot.config, compact_fanin=2,
            small_span_limit=hot.config.capacity,
            bloom_bits=1 << 12, cms_width=1 << 10, hll_p=6))

    spans, sizes = _skewed_stream(seed=23, total=3 * CFG_RING.capacity)
    tr = tiered(CFG_RING)
    tp = tiered(CFG_PAGED)
    _drive(tr, spans)
    _drive(tp, spans)

    sample = sorted(sizes)[::7]
    got_r = tr.get_spans_by_trace_ids(sample)
    got_p = tp.get_spans_by_trace_ids(sample)
    for tid, spans_r, spans_p in zip(sample, got_r, got_p):
        want = sorted(s.id for s in _spans_for(tid, sizes[tid]))
        assert sorted(s.id for s in spans_r) == want, tid
        assert sorted(s.id for s in spans_p) == want, tid


def test_mirror_is_layout_independent():
    """The sketch mirror folds batch CONTENT only (store/mirror.py's
    delta_of contract) — ring and paged drives of the same stream must
    leave every mirrored array element-equal, wrap included."""
    spans, _ = _skewed_stream(seed=31, total=2 * CFG_RING.capacity)
    ring = TpuSpanStore(CFG_RING)
    paged = TpuSpanStore(CFG_PAGED)
    _drive(ring, spans)
    _drive(paged, spans)
    for i, (a, b) in enumerate(zip(ring.sketch_mirror.arrays(),
                                   paged.sketch_mirror.arrays())):
        np.testing.assert_array_equal(a, b, err_msg=f"mirror array {i}")


# ---------------------------------------------------------------------------
# Pallas page gather == XLA take fallback, bitwise
# ---------------------------------------------------------------------------


def test_pallas_and_xla_page_gather_bitwise_identical():
    """Both lowering paths of _paged_gather_impl feed the same per-row
    (slot, epoch) validity mask and mask dead rows to -1, so their four
    output arrays must be bit-for-bit equal (the kernel runs in
    interpreter mode on CPU)."""
    spans, sizes = _skewed_stream(seed=5, total=700)
    store = TpuSpanStore(CFG_PAGED)
    _drive(store, spans)

    qids = np.asarray(sorted(sizes)[:24], np.int64)
    chains = store._planner.chains_for(qids)
    assert chains is not None
    pages, epochs = chains
    assert len(pages) >= 2  # stream is big enough to span pages
    k = max(2, 1 << (len(pages) - 1).bit_length())
    pages = np.concatenate([pages, np.full(k - len(pages), -1, np.int32)])
    epochs = np.concatenate([epochs, np.zeros(k - len(epochs), np.int64)])

    state = store.state
    c = state.config

    def gather(pallas: bool):
        return dev._paged_gather_impl(
            tuple(getattr(state, col) for col in dev.SPAN_MAT_COLS),
            tuple(getattr(state, col) for col in dev.ANN_MAT_COLS),
            tuple(getattr(state, col) for col in dev.BANN_MAT_COLS),
            jax.numpy.asarray(qids),
            jax.numpy.asarray(pages), jax.numpy.asarray(epochs),
            state.ann_write_pos, state.bann_write_pos,
            c.capacity, c.page_rows, c.ann_capacity, c.bann_capacity,
            256, 512, 256, pallas,
        )

    out_p = jax.device_get(gather(True))
    out_x = jax.device_get(gather(False))
    names = ("counts", "span_mat", "ann_mat", "bann_mat")
    for name, a, b in zip(names, out_p, out_x):
        np.testing.assert_array_equal(a, b, err_msg=name)
    assert int(out_p[0][0]) == sum(sizes[int(t)] for t in qids)


# ---------------------------------------------------------------------------
# Page reclaim under wrap (chain splice fuzz)
# ---------------------------------------------------------------------------


def test_page_reclaim_fuzz_invariants_and_liveness():
    """~4x-capacity skewed stream: free list + page table invariants
    hold after every batch, chains are spliced (never dangling), and
    live-trace queries return exactly the rows the device still holds
    — a subset of what was fed, never an invented or stale row."""
    spans, sizes = _skewed_stream(seed=97, total=4 * CFG_PAGED.capacity)
    fed_ids = {}
    for s in spans:
        fed_ids.setdefault(s.trace_id, set()).add(s.id)

    store = TpuSpanStore(CFG_PAGED)
    pl = store._planner
    for i in range(0, len(spans), 250):
        store.apply(spans[i:i + 250])
        st = pl.stats()
        assert st["pages_active"] + st["pages_free"] == pl.n_pages
        with pl._lock:
            # every chain entry points at a page still in its epoch
            # (reclaim must splice entries out, never leave them)
            for tid, ent in pl.traces.items():
                for (p, e) in ent.chain:
                    assert pl.page_epoch[p] == e, (tid, p, e)
                if not ent.overflowed:
                    assert ent.live == len(ent.chain), tid
            fills = [pl.page_fill[p] for p in range(pl.n_pages)
                     if pl.page_epoch[p] >= 0]
            assert all(0 <= f <= pl.R for f in fills)
    assert pl.stats()["page_reclaims"] > 0

    # Device/planner agreement: live rows on device == filled slots of
    # active pages (reclaim kills a page's rows in the claiming step).
    row_gid, trace_col = jax.device_get(
        (store.state.row_gid, store.state.trace_id))
    live = row_gid >= 0
    with pl._lock:
        planned = sum(pl.page_fill[p] for p in range(pl.n_pages)
                      if pl.page_epoch[p] >= 0)
    assert int(live.sum()) == planned

    # Query spot-check on surviving traces: what comes back is exactly
    # the device's live rows for that trace, drawn from the fed spans.
    with pl._lock:
        alive = [t for t, ent in pl.traces.items()
                 if not ent.overflowed][::5][:24]
    for tid in alive:
        got = store.get_spans_by_trace_ids([tid])[0]
        n_dev = int((live & (trace_col == tid)).sum())
        assert len(got) == n_dev, tid
        assert {s.id for s in got} <= fed_ids[tid], tid


def test_planner_rejects_bad_geometry():
    with pytest.raises(ValueError, match="power of two"):
        PagePlanner(CFG_RING._replace(layout="paged", page_rows=96))
    with pytest.raises(ValueError, match="multiple of page_rows"):
        PagePlanner(CFG_RING._replace(
            capacity=(1 << 10) + 8, layout="paged", page_rows=16))
    with pytest.raises(ValueError, match=">= 8 pages"):
        PagePlanner(CFG_RING._replace(layout="paged", page_rows=512))
    with pytest.raises(ValueError, match="layout"):
        PagePlanner(CFG_RING)


def test_sharded_store_rejects_paged_layout():
    from jax.sharding import Mesh

    from zipkin_tpu.parallel.shard import ShardedStore

    mesh = Mesh(np.array(jax.devices()[:1]), ("shard",))
    with pytest.raises(ValueError, match="single-device only"):
        ShardedStore(mesh, CFG_PAGED)


def test_paged_counters_and_census_budget():
    """counters() carries the allocator gauges only on the paged
    layout, and the fused-step lowering costs exactly the census
    table's +PAGED bump (zero silent growth)."""
    cfg_ring = CFG_RING._replace(rank_path="counting")
    cfg_paged = cfg_ring._replace(layout="paged", page_rows=128)
    spans, _ = _skewed_stream(seed=3, total=400)
    ring = TpuSpanStore(cfg_ring)
    paged = TpuSpanStore(cfg_paged)
    _drive(ring, spans)
    _drive(paged, spans)

    pc = paged.counters()
    assert pc["pages_active"] >= 1
    assert pc["pages_active"] + pc["pages_free"] == float(
        cfg_paged.n_pages)
    assert "page_reclaims_total" in pc
    assert "pages_active" not in ring.counters()

    ps, po, pg = expected_census("+PAGED")
    bs, bo, bg = expected_census()
    assert paged.step_census(256, 1024, 512) == {
        "scatter": ps, "sort": po, "gather": pg}
    assert ring.step_census(256, 1024, 512) == {
        "scatter": bs, "sort": bo, "gather": bg}


# ---------------------------------------------------------------------------
# Checkpoint: rev 18 roundtrip + pre-18 compat
# ---------------------------------------------------------------------------


def test_checkpoint_rev18_roundtrip_paged(tmp_path):
    """Save/load a WRAPPED paged store: device state bitwise, planner
    snapshot identical, queries answer the same, and post-restore
    ingest stays bitwise in lockstep with the uncheckpointed store
    (the planner must resume mid-epoch, not re-derive from zero)."""
    spans, sizes = _skewed_stream(seed=41, total=2 * CFG_PAGED.capacity)
    store = TpuSpanStore(CFG_PAGED)
    _drive(store, spans)

    path = str(tmp_path / "ckpt")
    checkpoint.save(store, path)
    rec = checkpoint.load(path)

    assert rec.config.layout == "paged"
    assert rec.config.page_rows == CFG_PAGED.page_rows
    assert states_bitwise_equal(store.state, rec.state)
    assert rec._planner.snapshot() == store._planner.snapshot()

    sample = sorted(sizes)[::9][:16]
    assert (store.get_spans_by_trace_ids(sample)
            == rec.get_spans_by_trace_ids(sample))

    # Post-restore writes: same tail stream → same claims → same bits.
    tail, _ = _skewed_stream(seed=43, total=300)
    _drive(store, tail)
    _drive(rec, tail)
    assert states_bitwise_equal(store.state, rec.state)
    assert rec._planner.stats() == store._planner.stats()


def test_pre18_snapshot_without_planner_meta_rebuilds(tmp_path):
    """A paged config pointed at a snapshot saved WITHOUT planner meta
    (the pre-18 shape) rebuilds the page table from the resident
    device columns — queries must answer exactly like the original."""
    spans, sizes = _skewed_stream(seed=53, total=2 * CFG_PAGED.capacity)
    store = TpuSpanStore(CFG_PAGED)
    _drive(store, spans)

    path = str(tmp_path / "ckpt")
    checkpoint.save(store, path)
    meta_file = os.path.join(path, "meta.json")
    with open(meta_file) as f:
        meta = json.load(f)
    assert meta["revision"] >= 18 and "paged" in meta
    del meta["paged"]
    meta["revision"] = 17
    with open(meta_file, "w") as f:
        json.dump(meta, f)

    rec = checkpoint.load(path)
    assert states_bitwise_equal(store.state, rec.state)
    st, rt = store._planner.stats(), rec._planner.stats()
    assert (st["pages_active"], st["pages_free"]) == (
        rt["pages_active"], rt["pages_free"])
    sample = sorted(sizes)[::11][:16]
    assert (store.get_spans_by_trace_ids(sample)
            == rec.get_spans_by_trace_ids(sample))


def test_pre18_ring_snapshot_still_loads(tmp_path):
    """Backward compat: a ring snapshot rewritten to the pre-18 meta
    shape (no layout knobs in config at all) restores through the
    revision-tolerant config checks as a ring store, bitwise."""
    spans, _ = _skewed_stream(seed=61, total=600)
    store = TpuSpanStore(CFG_RING)
    _drive(store, spans)

    path = str(tmp_path / "ckpt")
    checkpoint.save(store, path)
    meta_file = os.path.join(path, "meta.json")
    with open(meta_file) as f:
        meta = json.load(f)
    meta["revision"] = 17
    meta.pop("paged", None)
    for gone in ("layout", "page_rows", "page_max_chain"):
        meta["config"].pop(gone, None)
    with open(meta_file, "w") as f:
        json.dump(meta, f)

    rec = checkpoint.load(path)
    assert rec.config.layout == "ring"
    assert rec._planner is None
    assert states_bitwise_equal(store.state, rec.state)


# ---------------------------------------------------------------------------
# WAL: deterministic, bitwise replay of the paged plan stream
# ---------------------------------------------------------------------------


def test_wal_replay_paged_is_bitwise(tmp_path):
    """Replaying the journal into a FRESH paged store re-derives the
    exact claim sequence: device state AND planner page table (free
    list, epochs, chains, touch stamps) come back bit-identical."""
    spans, _ = _skewed_stream(seed=71, total=2 * CFG_PAGED.capacity)
    store = TpuSpanStore(CFG_PAGED)
    wal = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
    store.attach_wal(wal)
    _drive(store, spans)
    wal.sync()
    assert store._planner.stats()["page_reclaims"] > 0

    fresh = TpuSpanStore(CFG_PAGED)
    stats = replay_into(fresh, wal, from_seq=0)
    assert stats["replayed_records"] == wal.last_seq
    assert states_bitwise_equal(store.state, fresh.state)
    assert fresh._planner.snapshot() == store._planner.snapshot()
    wal.close()


def test_recover_checkpoint_plus_tail_replays_recorded_plans(tmp_path):
    """Mid-stream checkpoint + tail replay (the crash shape): plans at
    seq <= the snapshot's frontier replay from the recorded memo, the
    tail re-derives — recovery lands bitwise on the uncrashed oracle,
    wrap and reclaims included, and keeps ingesting identically."""
    spans, _ = _skewed_stream(seed=83, total=2 * CFG_PAGED.capacity)
    # Cut on a _drive batch boundary: the claim plan is a function of
    # the CHUNK stream, so oracle and crashed store must batch alike.
    half = (len(spans) // 2 // 200) * 200

    oracle = TpuSpanStore(CFG_PAGED)
    _drive(oracle, spans)

    store = TpuSpanStore(CFG_PAGED)
    wal = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
    store.attach_wal(wal)
    _drive(store, spans[:half])
    checkpoint.save(store, str(tmp_path / "ckpt"))
    _drive(store, spans[half:])
    wal.sync()
    del store  # crash: HBM gone, snapshot + log survive

    wal2 = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
    rec, rstats = recover(str(tmp_path / "ckpt"), wal2)
    assert rstats["replayed_records"] > 0
    assert states_bitwise_equal(oracle.state, rec.state)
    assert rec._planner.stats() == oracle._planner.stats()

    tail, _ = _skewed_stream(seed=89, total=250)
    _drive(oracle, tail)
    _drive(rec, tail)
    assert states_bitwise_equal(oracle.state, rec.state)
    wal2.close()
