"""Moments / DependencyLink / Dependencies monoid tests
(reference: zipkin-common DependenciesTest)."""

import math
import random

from zipkin_tpu.models.dependencies import (
    Dependencies,
    DependencyLink,
    Moments,
    merge_dependency_links,
)


def test_moments_basic_stats():
    xs = [2.0, 4.0, 4.0, 4.0, 5.0, 5.0, 7.0, 9.0]
    m = Moments.of_many(xs)
    assert m.count == 8
    assert math.isclose(m.mean, 5.0)
    assert math.isclose(m.variance, 4.0)
    assert math.isclose(m.stddev, 2.0)


def test_moments_monoid_associative_and_commutative():
    rng = random.Random(42)
    xs = [rng.uniform(0, 1000) for _ in range(100)]
    a = Moments.of_many(xs[:30])
    b = Moments.of_many(xs[30:70])
    c = Moments.of_many(xs[70:])
    whole = Moments.of_many(xs)
    for combo in [(a + b) + c, a + (b + c), c + b + a]:
        assert math.isclose(combo.mean, whole.mean, rel_tol=1e-9)
        assert math.isclose(combo.variance, whole.variance, rel_tol=1e-9)
        assert combo.count == whole.count


def test_moments_central_roundtrip():
    xs = [1.0, 5.0, 9.0, 14.0, 2.0]
    m = Moments.of_many(xs)
    m2 = Moments.from_central(*m.to_central())
    assert m2 == m
    n, mean, c2, _, _ = m.to_central()
    assert n == 5
    assert math.isclose(mean, m.mean)
    assert math.isclose(c2 / n, m.variance, rel_tol=1e-9)


def test_moments_numerically_stable_for_large_means():
    # Realistic zipkin durations: mean ~60s (6e7 µs), σ ~1ms (1e3 µs).
    # Raw power sums would destroy variance/kurtosis here.
    rng = random.Random(7)
    xs = [6.0e7 + rng.gauss(0, 1.0e3) for _ in range(20_000)]
    half = len(xs) // 2
    m = Moments.of_many(xs[:half]) + Moments.of_many(xs[half:])
    assert math.isclose(m.mean, sum(xs) / len(xs), rel_tol=1e-12)
    exact_var = sum((x - sum(xs) / len(xs)) ** 2 for x in xs) / len(xs)
    assert math.isclose(m.variance, exact_var, rel_tol=1e-6)
    assert abs(m.skewness) < 0.1
    assert abs(m.kurtosis) < 0.2


def test_moments_skewness_kurtosis_sane():
    sym = Moments.of_many([1.0, 2.0, 3.0, 4.0, 5.0])
    assert abs(sym.skewness) < 1e-9
    skewed = Moments.of_many([1.0, 1.0, 1.0, 10.0])
    assert skewed.skewness > 0


def test_dependency_link_merge():
    a = DependencyLink("web", "db", Moments.of(10.0))
    b = DependencyLink("web", "db", Moments.of(20.0))
    merged = a + b
    assert merged.duration_moments.count == 2
    assert math.isclose(merged.duration_moments.mean, 15.0)


def test_merge_dependency_links_groups():
    links = [
        DependencyLink("web", "db", Moments.of(10.0)),
        DependencyLink("web", "cache", Moments.of(1.0)),
        DependencyLink("web", "db", Moments.of(30.0)),
    ]
    merged = {(l.parent, l.child): l for l in merge_dependency_links(links)}
    assert len(merged) == 2
    assert merged[("web", "db")].duration_moments.count == 2


def test_dependencies_monoid():
    d1 = Dependencies(100, 200, (DependencyLink("a", "b", Moments.of(5.0)),))
    d2 = Dependencies(150, 400, (DependencyLink("a", "b", Moments.of(7.0)),))
    total = d1 + d2
    assert total.start_time == 100
    assert total.end_time == 400
    assert len(total.links) == 1
    assert total.links[0].duration_moments.count == 2

    # zero is the identity
    z = Dependencies.zero()
    assert (d1 + z).start_time == d1.start_time
    assert (z + d1).end_time == d1.end_time
    assert (d1 + z).links == d1.links
