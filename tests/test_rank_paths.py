"""r12 fuzz equivalence suite: the counting-sort and argsort FIFO-rank
paths must be BITWISE interchangeable.

The unified index write (_index_write) derives every arena mutation —
slot assignment, in-batch overflow drops, displacement bookkeeping,
key claims, watermark wars — from the within-bucket arrival rank, so
the two rank implementations being bitwise-identical is what makes
``StoreConfig.rank_path`` pure perf policy (mixable across launches,
checkpoints, and replay). This suite fuzzes the rank vectors directly
across the adversarial bucket shapes (duplicate-heavy, empty-bucket,
all-one-bucket, ragged tails) and proves whole-store arena-state
identity on real ingest workloads, plus the wm_shift == 0 small-store
regime's static argsort fallback.

Tier-1 discipline: the rank-VECTOR fuzz (cheap, eager, covers every
adversarial bucket class) and ONE whole-store drive pair run in the
fast lane; the remaining whole-store twins and the large-batch
escalated sweep ride the slow lane (one tiny config pair shared
across every state case, so the jit cache is paid once).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from zipkin_tpu.store import device as dev
from zipkin_tpu.store.tpu import TpuSpanStore
from zipkin_tpu.testing.crash import states_bitwise_equal
from zipkin_tpu.tracegen import generate_traces

BASE = dict(
    capacity=1 << 10, ann_capacity=1 << 11, bann_capacity=1 << 10,
    max_services=16, max_span_names=32, max_annotation_values=64,
    max_binary_keys=32, cms_width=1 << 8, hll_p=6, quantile_buckets=64,
)
CFG_ARG = dev.StoreConfig(**BASE, rank_path="argsort")
CFG_CNT = dev.StoreConfig(**BASE, rank_path="counting")


def _assert_rank_pair(bucket, valid, n_buckets, blocks=(8, 16, 64)):
    want = np.asarray(dev._fifo_ranks(bucket, valid, n_buckets))
    for blk in blocks:
        got = np.asarray(
            dev._fifo_ranks_counting(bucket, valid, n_buckets, blk))
        np.testing.assert_array_equal(want, got, err_msg=f"block {blk}")


class TestRankVectorEquivalence:
    def test_fuzz_random_shapes(self):
        rng = np.random.default_rng(7)
        # (rows, buckets): duplicate-heavy (few buckets), sparse (more
        # buckets than rows => most empty), ragged non-pow2 tails,
        # single-row.
        for n, nb in [(513, 3), (256, 2), (1000, 4096), (97, 13),
                      (1, 5), (64, 64), (301, 1)]:
            bucket = jnp.asarray(rng.integers(0, nb, n), jnp.int32)
            valid = jnp.asarray(rng.random(n) < 0.7)
            _assert_rank_pair(bucket, valid, nb)

    def test_all_one_bucket(self):
        n = 300
        bucket = jnp.zeros(n, jnp.int32)
        _assert_rank_pair(bucket, jnp.ones(n, bool), 7)
        # Ranks must be exactly arrival order in the single bucket.
        got = dev._fifo_ranks_counting(bucket, jnp.ones(n, bool), 7, 8)
        np.testing.assert_array_equal(np.asarray(got), np.arange(n))

    def test_all_invalid_and_mixed(self):
        rng = np.random.default_rng(3)
        n = 200
        bucket = jnp.asarray(rng.integers(0, 9, n), jnp.int32)
        _assert_rank_pair(bucket, jnp.zeros(n, bool), 9)
        # Alternating validity: ~valid rows rank among themselves via
        # the sentinel bucket, exactly like the argsort sentinel key.
        valid = jnp.asarray(np.arange(n) % 2 == 0)
        _assert_rank_pair(bucket, valid, 9)

    def test_block_larger_than_rows(self):
        bucket = jnp.asarray([0, 1, 0, 1, 0], jnp.int32)
        _assert_rank_pair(bucket, jnp.ones(5, bool), 2,
                          blocks=(8, 16, 32, 64))


class TestRankModePolicy:
    def test_wm_shift_zero_static_fallback(self):
        # The small-store regime (capacity <= 2^9 => wm_shift == 0)
        # keeps argsort for EVERY policy, counting included.
        for policy in ("auto", "argsort", "counting"):
            assert dev.rank_mode(policy, 4096, 512, 0) == ("argsort", 0)

    def test_scratch_infeasible_degrades(self):
        # Bench-ring scale: no block fits => argsort, even forced.
        assert dev.rank_mode("counting", 2_000_000, 800_000,
                             13) == ("argsort", 0)
        assert dev.rank_block_for(2_000_000, 800_000) == 0

    def test_counting_engages_when_feasible(self):
        # Forced counting engages on any backend (what the CI gates
        # pin the path with); "auto" is backend-aware — on this CPU
        # suite it keeps argsort (the faster implementation here),
        # on TPU it picks counting at the same shape.
        kind, blk = dev.rank_mode("counting", 8192, 1600, 3)
        assert kind == "counting" and blk in dev._RANK_BLOCKS
        import jax

        want = "counting" if jax.default_backend() == "tpu" else "argsort"
        assert dev.rank_mode("auto", 8192, 1600, 3)[0] == want

    def test_unknown_policy_raises(self):
        with pytest.raises(ValueError):
            dev.rank_mode("bogus", 10, 10, 3)

    def test_small_store_wm_shift_is_zero(self):
        # The archive-phase geometry (capacity 2^8) computes
        # wm_shift == 0 in ingest_step, so its rank_mode is argsort
        # for every policy — the same derivation ingest_step uses.
        cap = 1 << 8
        wm_shift = max(0, cap.bit_length() - 1 - dev._WM_COARSE_FRAC_BITS)
        assert wm_shift == 0
        assert dev.rank_mode("counting", 2048, 512,
                             wm_shift) == ("argsort", 0)

    @pytest.mark.slow
    def test_small_store_config_uses_argsort(self):
        # A capacity-2^8 store (the archive-phase geometry) computes
        # wm_shift == 0 in ingest_step — drive one batch and check the
        # recorded active path.
        cfg = dev.StoreConfig(
            capacity=1 << 8, ann_capacity=1 << 9, bann_capacity=1 << 8,
            max_services=8, max_span_names=16,
            max_annotation_values=32, max_binary_keys=16,
            cms_width=1 << 8, hll_p=6, quantile_buckets=64,
            rank_path="counting",
        )
        store = TpuSpanStore(cfg)
        traces = generate_traces(n_traces=8, max_depth=2, n_services=4)
        store.apply([s for t in traces for s in t][:48])
        paths = dev.active_paths(cfg)
        assert paths["rank"] == ("argsort",)
        assert store.counters()["rank_path_counting"] == 0.0


def _drive_pair(spans, chunk=64):
    """Same spans through an argsort store and a counting store (one
    shared config-pair geometry => the jit cache is paid once for the
    whole module)."""
    stores = []
    for cfg in (CFG_ARG, CFG_CNT):
        st = TpuSpanStore(cfg)
        for i in range(0, len(spans), chunk):
            st.apply(spans[i:i + chunk])
        stores.append(st)
    return stores


class TestArenaStateEquivalence:
    def test_duplicate_heavy_workload(self):
        # One service, one span name: every candidate row of a batch
        # piles into a handful of buckets (heavy in-batch overflow,
        # the displacement machinery's worst case).
        traces = generate_traces(n_traces=45, max_depth=3,
                                 n_services=1)
        spans = [s for t in traces for s in t][:280]
        a, c = _drive_pair(spans)
        assert states_bitwise_equal(a.state, c.state)
        assert dev.active_paths(CFG_CNT)["rank"] == ("counting",)
        assert dev.active_paths(CFG_ARG)["rank"] == ("argsort",)

    @pytest.mark.slow
    def test_all_one_trace_bucket(self):
        # A single trace: every trace-membership row of every batch
        # lands in ONE bucket (the all-one-bucket regime), wrapping
        # its FIFO several times over. (The rank-VECTOR all-one-bucket
        # case stays in tier-1 above; this is the whole-store twin.)
        traces = generate_traces(n_traces=1, max_depth=6,
                                 n_services=4)
        spans = [s for t in traces for s in t]
        spans = (spans * (200 // max(1, len(spans)) + 1))[:200]
        a, c = _drive_pair(spans)
        assert states_bitwise_equal(a.state, c.state)

    @pytest.mark.slow
    def test_sparse_empty_buckets(self):
        # Many services/names over few spans: most buckets stay empty
        # and writes never wrap (the trivially-complete regime).
        traces = generate_traces(n_traces=20, max_depth=2,
                                 n_services=16)
        spans = [s for t in traces for s in t][:100]
        a, c = _drive_pair(spans)
        assert states_bitwise_equal(a.state, c.state)


@pytest.mark.slow
class TestEscalatedBatchSweep:
    def test_large_batch_geometries_bitwise(self):
        # The batch-escalation sweep: the SAME spans at several
        # batch_spans geometries, argsort vs counting at each — launch
        # shapes change (bigger pads), bitwise identity must not.
        traces = generate_traces(n_traces=700, max_depth=3,
                                 n_services=8)
        spans = [s for t in traces for s in t][:4000]
        for bs in (128, 256, 512):
            pair = []
            for rank_path in ("argsort", "counting"):
                cfg = dev.StoreConfig(**BASE, rank_path=rank_path,
                                      batch_spans=bs)
                st = TpuSpanStore(cfg)
                for i in range(0, len(spans), 1024):
                    st.apply(spans[i:i + 1024])
                pair.append(st)
            a, c = pair
            assert states_bitwise_equal(a.state, c.state), bs
            assert a.counters()["batch_spans_limit"] == float(bs)
